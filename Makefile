# Developer/CI entry points for tpu-sartsolver.
#
#   make lint        - sartsolve lint --self (AST rules + compile audit)
#   make test        - tier-1 test suite (CPU backend, ROADMAP.md contract)
#   make faults      - fault-injection matrix: per-site recover/degrade
#                      proofs (docs/RESILIENCE.md; subset of tier-1)
#   make drills      - availability drill matrix: SIGKILL + graceful-stop
#                      (SIGTERM, exit 4) + hang-watchdog + OOM-degradation
#                      end-to-end drills (docs/RESILIENCE.md §5-§7;
#                      subset of tier-1)
#   make verify      - lint, then tier-1 tests (the fail-fast CI path)
#   make native-asan - rebuild the native helper with ASan+UBSan and run
#                      its tests against it (skips cleanly with no g++)
#   make goldens     - regenerate the compile-audit golden signatures for
#                      this backend (commit the result)

PYTHON ?= python
BUILD_DIR ?= .build
ASAN_SO := $(BUILD_DIR)/libsartrt_asan.so

.PHONY: lint test faults drills verify native-asan goldens

lint:
	JAX_PLATFORMS=cpu $(PYTHON) -m sartsolver_tpu.cli lint --self

test:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider

# The fault-injection matrix (docs/RESILIENCE.md): for every named site a
# recover leg (transient fault retried, clean output, exit 0) and a
# degrade leg (budget exhausted -> FAILED/DIVERGED row + exit 2, or
# resumable infrastructure abort + exit 3). Runs inside the tier-1 time
# budget (~25 s on the CI box); `make test` includes it.
faults:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_resilience.py -q \
		-p no:cacheprovider

# The availability drill matrix (docs/RESILIENCE.md §5-§7): real-process
# SIGKILL + SIGTERM kill/stop/resume drills at deterministic flush-window
# markers, plus the watchdog hang-escalation and OOM batch-halving drills.
# Runs inside the tier-1 time budget; `make test` includes it.
drills:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_killdrill.py \
		tests/test_availability.py -q -p no:cacheprovider

# New static-analysis violations fail before the (much slower) test run.
verify: lint test

goldens:
	JAX_PLATFORMS=cpu $(PYTHON) -m sartsolver_tpu.cli lint --audit-only \
		--update-goldens

# Sanitizer build of the native ingest helper (sartrt.cpp). The library is
# a -shared object loaded via ctypes, so the sanitizer runtimes must be
# preloaded into the python process; leak checking is disabled (the Python
# interpreter's own allocations drown it in noise). Skips cleanly when no
# compiler or sanitizer runtime is available.
native-asan:
	@command -v g++ >/dev/null 2>&1 || \
		{ echo "native-asan: skipped (no g++)"; exit 0; }
	@asan_rt=$$(g++ -print-file-name=libasan.so); \
	ubsan_rt=$$(g++ -print-file-name=libubsan.so); \
	if [ ! -e "$$asan_rt" ]; then \
		echo "native-asan: skipped (no libasan runtime)"; exit 0; \
	fi; \
	mkdir -p $(BUILD_DIR); \
	g++ -O1 -g -fno-omit-frame-pointer -fsanitize=address,undefined \
		-shared -fPIC -std=c++17 \
		sartsolver_tpu/native/sartrt.cpp -o $(ASAN_SO) || exit 1; \
	echo "native-asan: built $(ASAN_SO); running tests/test_native.py"; \
	preload="$$asan_rt"; \
	[ -e "$$ubsan_rt" ] && preload="$$preload $$ubsan_rt"; \
	env LD_PRELOAD="$$preload" \
		ASAN_OPTIONS=detect_leaks=0:abort_on_error=1 \
		UBSAN_OPTIONS=halt_on_error=1:print_stacktrace=1 \
		SART_NATIVE_LIB=$(ASAN_SO) \
		JAX_PLATFORMS=cpu \
		$(PYTHON) -m pytest tests/test_native.py -q -p no:cacheprovider
