"""Fused Pallas sweep (ops/fused_sweep.py) vs the two-matmul reference path.

The fused kernel must be a pure re-scheduling: identical masking, update
rules and convergence behavior as the unfused solver (which itself is
oracle-tested against NumPy fp64 in test_sart_core.py). These tests run the
kernel in Pallas interpreter mode on CPU and assert near-bitwise agreement.
"""

import dataclasses

import numpy as np
import pytest

from sartsolver_tpu.config import SolverOptions
from sartsolver_tpu.ops.fused_sweep import fused_available, pick_block_voxels
from sartsolver_tpu.ops.laplacian import make_laplacian


P, V = 24, 256  # tile-aligned: P % 8 == 0, V % 128 == 0


def _case(seed=0, saturated=True):
    rng = np.random.default_rng(seed)
    H = rng.uniform(0.1, 1.0, (P, V)).astype(np.float32)
    H[:, :3] = 0.0  # masked voxels (zero ray density)
    H[3, :] = 0.0  # masked pixel (zero ray length)
    f_true = rng.uniform(0.5, 2.0, V)
    g = H.astype(np.float64) @ f_true
    if saturated:
        g[5] = -1.0  # saturated detector
    return H, g


def _laplacian(seed=1):
    rng = np.random.default_rng(seed)
    rows = np.arange(V)
    cols = (rows + 1) % V
    vals = rng.uniform(-0.2, 0.2, V)
    rows = np.concatenate([rows, np.arange(V)])
    cols = np.concatenate([cols, np.arange(V)])
    vals = np.concatenate([vals, np.full(V, 0.3)])
    return make_laplacian(rows, cols, vals, dtype="float32")


def _solve(H, g, opts, lap=None, batch=None):
    import jax.numpy as jnp

    from sartsolver_tpu.models.sart import (
        make_problem, solve, solve_normalized_batch, prepare_measurement,
    )

    problem = make_problem(H, lap, opts=opts)
    if batch is None:
        return solve(problem, g, opts=opts)
    G = np.stack([g] * batch) * np.linspace(1.0, 1.5, batch)[:, None]
    gs, msqs, norms = [], [], []
    for b in range(batch):
        g64, msq, norm = prepare_measurement(G[b], opts)
        gs.append(g64)
        msqs.append(msq)
        norms.append(norm)
    res = solve_normalized_batch(
        problem,
        jnp.asarray(np.stack(gs), jnp.float32),
        jnp.asarray(msqs, jnp.float32),
        jnp.zeros((batch, H.shape[1]), jnp.float32),
        opts=opts, axis_name=None, voxel_axis=None, use_guess=True,
    )
    return res._replace(
        solution=np.asarray(res.solution) * np.asarray(norms)[:, None]
    )


@pytest.mark.parametrize("logarithmic", [False, True])
@pytest.mark.parametrize("with_lap", [False, True])
def test_fused_matches_unfused(logarithmic, with_lap):
    H, g = _case()
    lap = _laplacian() if with_lap else None
    base = SolverOptions(
        max_iterations=30, conv_tolerance=1e-12, logarithmic=logarithmic,
        beta_laplace=1e-3 if with_lap else 0.0, relaxation=0.7,
    )
    ref = _solve(H, g, dataclasses.replace(base, fused_sweep="off"), lap)
    fus = _solve(H, g, dataclasses.replace(base, fused_sweep="interpret"), lap)
    assert int(ref.iterations) == int(fus.iterations)
    assert int(ref.status) == int(fus.status)
    np.testing.assert_allclose(
        np.asarray(fus.solution), np.asarray(ref.solution), rtol=2e-5, atol=2e-6
    )


@pytest.mark.parametrize("logarithmic", [False, True])
def test_fused_matches_unfused_batched(logarithmic):
    H, g = _case(seed=3)
    base = SolverOptions(
        max_iterations=25, conv_tolerance=1e-4, logarithmic=logarithmic,
    )
    ref = _solve(H, g, dataclasses.replace(base, fused_sweep="off"), batch=3)
    fus = _solve(H, g, dataclasses.replace(base, fused_sweep="interpret"), batch=3)
    np.testing.assert_array_equal(np.asarray(ref.iterations), np.asarray(fus.iterations))
    np.testing.assert_array_equal(np.asarray(ref.status), np.asarray(fus.status))
    np.testing.assert_allclose(fus.solution, ref.solution, rtol=2e-5, atol=2e-6)


def test_fused_convergence_freeze_parity():
    """Early-converging frames freeze identically under the fused path."""
    H, g = _case(seed=4, saturated=False)
    base = SolverOptions(max_iterations=60, conv_tolerance=1e-3)
    ref = _solve(H, g, dataclasses.replace(base, fused_sweep="off"))
    fus = _solve(H, g, dataclasses.replace(base, fused_sweep="interpret"))
    assert int(ref.status) == 0
    assert int(ref.iterations) == int(fus.iterations)
    np.testing.assert_allclose(
        float(fus.convergence), float(ref.convergence), rtol=1e-4, atol=1e-7
    )


def test_unaligned_shapes_fall_back():
    assert not fused_available(23, 256, 4)  # pixels not sublane-aligned
    assert not fused_available(24, 200, 4)  # voxels not lane-aligned
    assert fused_available(24, 256, 4)
    H, g = _case()
    Hu, gu = H[:23], g[:23]
    opts = SolverOptions(max_iterations=5, conv_tolerance=1e-12, fused_sweep="auto")
    res = _solve(Hu, gu, opts)  # auto on CPU backend -> unfused; must just run
    assert np.isfinite(np.asarray(res.solution)).all()
    with pytest.raises(ValueError, match="tile-aligned"):
        _solve(Hu, gu, dataclasses.replace(opts, fused_sweep="interpret"))


def test_block_picker():
    assert pick_block_voxels(8192, 65536, 4) % 128 == 0
    assert 65536 % pick_block_voxels(8192, 65536, 4) == 0
    # bf16 halves the panel bytes -> at least as wide a block
    assert pick_block_voxels(8192, 65536, 2) >= pick_block_voxels(8192, 65536, 4)
    assert pick_block_voxels(8, 128, 4) == 128


def test_selftest_returns_cached_bool():
    import jax

    from sartsolver_tpu.ops import fused_sweep as fs

    first = fs.fused_selftest()
    assert isinstance(first, bool)
    # cached per backend (bool identity alone would hold vacuously)
    assert jax.default_backend() in fs._selftest_result
    assert fs.fused_selftest() == first


def test_scoped_vmem_model():
    """Pin the scoped-VMEM accounting to what TPU v5e measurements showed
    (2026-07-29): the benchmark shapes must be eligible, with the raised
    compiler limit requested exactly when XLA's 16 MiB default would OOM."""
    from sartsolver_tpu.ops.fused_sweep import (
        _SCOPED_VMEM_RAISED_KIB, fused_compile_options,
    )

    P, V = 8192, 65536
    # bf16 B=32 OOMed at the default limit in round 2; it must stay eligible
    # and request the raised limit rather than being declined or crashing.
    assert fused_available(P, V, 2, batch=32)
    opt = fused_compile_options(P, V, 2, batch=32)
    assert opt == {"xla_tpu_scoped_vmem_limit_kib": str(_SCOPED_VMEM_RAISED_KIB)}
    # the B=1 headline configs also clear the raise cap
    assert fused_available(P, V, 4, batch=1)
    assert fused_available(P, V, 2, batch=1)
    # a tiny problem fits the default budget: no options needed
    assert fused_compile_options(8, 256, 4, batch=1) is None
    # absurd batch blows past the raise cap -> ineligible (two-matmul path)
    assert not fused_available(P, V, 4, batch=4096)


def test_block_picker_steps_down_to_fit_vmem_cap():
    """The panel width must narrow when the byte-target width would push the
    whole-kernel scoped-VMEM estimate past the raise cap — int8 has NO
    two-matmul fallback, so large batches must keep fusing at a narrower
    panel instead of erroring (the 12 MiB int8 target picks bs=1024 at the
    headline shape, which at B>=40 estimates past the 48 MiB cap where
    bs=512 still fits)."""
    from sartsolver_tpu.ops.fused_sweep import (
        _SCOPED_VMEM_EST_CAP_BYTES, _scoped_vmem_estimate,
    )

    P, V = 8192, 65536
    assert pick_block_voxels(P, V, 1, batch=1) == 1024
    for batch in (32, 40, 48, 64):
        bs = pick_block_voxels(P, V, 1, batch=batch)
        assert bs > 0, f"int8 batch={batch} lost the fused sweep"
        assert _scoped_vmem_estimate(P, V, bs, 1, batch) <= _SCOPED_VMEM_EST_CAP_BYTES
        assert fused_available(P, V, 1, batch=batch)
    assert pick_block_voxels(P, V, 1, batch=40) < 1024


def test_minimum_panel_solve_matches_unfused(monkeypatch):
    """Numerics of the minimum-panel fallback path: shrink the panel-bytes
    target so the target-derived width is 0 and the picker's 128-voxel
    clamp engages, then assert the fused (interpret) solve still matches
    the unfused reference bit-for-tolerance — the same path a tall RTM
    (or a tall per-chip shard of a voxel-major mesh) takes on hardware."""
    from sartsolver_tpu.ops import fused_sweep as fs

    monkeypatch.setattr(fs, "_PANEL_BYTES_TARGET", 16 << 10)
    assert (16 << 10) // (P * 4 + fs._VOXEL_PANEL_OPERANDS * 4) // 128 == 0
    assert pick_block_voxels(P, V, 4) == 128

    H, g = _case(seed=7)
    base = SolverOptions(max_iterations=25, conv_tolerance=1e-12)
    ref = _solve(H, g, dataclasses.replace(base, fused_sweep="off"))
    fus = _solve(H, g, dataclasses.replace(base, fused_sweep="interpret"))
    assert int(ref.iterations) == int(fus.iterations)
    np.testing.assert_allclose(
        np.asarray(fus.solution), np.asarray(ref.solution), rtol=2e-5, atol=2e-6
    )


def test_block_picker_tall_matrices_keep_minimum_panel():
    """A tall matrix (large pixel count — the per-chip shard shape of a
    voxel-major mesh) must fall back to the minimum 128-wide panel when
    even that exceeds the panel-bytes target, as long as the scoped-VMEM
    estimate cap still fits: losing fusion entirely would drop such shards
    to the ~8x-slower two-matmul gemv path."""
    # bf16 at 49152 pixels: a 128-panel is 12.6 MiB (> the 8 MiB target)
    # but the kernel estimate is ~26 MiB, well under the 48 MiB cap
    assert pick_block_voxels(49152, 131072, 2) == 128
    assert fused_available(49152, 131072, 2)
    # fp32 at the same height: the 128-panel estimate alone is ~50 MiB,
    # past the cap -> genuinely ineligible
    assert pick_block_voxels(49152, 131072, 4) == 0
    assert not fused_available(49152, 131072, 4)


def test_compiler_options_dispatch_cpu_safe():
    """The dispatch wrapper must never attach the TPU-only flag off-TPU
    (auto resolves unfused on CPU) and must stay callable under an outer
    trace (sharded path inlines the core)."""
    import jax

    from sartsolver_tpu.models import sart

    H, g = _case()
    opts = SolverOptions(max_iterations=3, conv_tolerance=1e-12, fused_sweep="auto")
    res = _solve(H, g, opts)
    assert np.isfinite(np.asarray(res.solution)).all()
    # the CPU path must have dispatched through the option-less jit core
    assert sart._jitted_solver.cache_info().currsize >= 1
    assert sart._jitted_solver(None) is sart._jitted_solver(None)
    # and the tracer branch (sharded path) inlines without a fresh jit
    @jax.jit
    def traced(rtm, gv):
        from sartsolver_tpu.models.sart import (
            SARTProblem, compute_ray_stats, solve_normalized_batch,
        )

        dens, length = compute_ray_stats(rtm, dtype=np.float32)
        problem = SARTProblem(rtm, dens, length, None)
        import jax.numpy as jnp

        return solve_normalized_batch(
            problem, gv[None, :], jnp.ones((1,), np.float32),
            jnp.zeros((1, rtm.shape[1]), np.float32),
            opts=opts, axis_name=None, voxel_axis=None, use_guess=True,
        )

    res2 = traced(np.asarray(H, np.float32), np.asarray(g, np.float32))
    assert np.isfinite(np.asarray(res2.solution)).all()


@pytest.mark.parametrize("logarithmic", [False, True])
def test_fused_matches_unfused_bf16(logarithmic):
    """The fused kernel feeds a bf16 panel to the dot directly (mixed
    f32xbf16 contraction, no conversion scratch); interpreter mode must
    agree with the unfused two-matmul path on the same bf16 RTM, pinning
    the mixed-dtype semantics off-TPU."""
    H, g = _case()
    lap = _laplacian()
    base = SolverOptions(
        max_iterations=30, conv_tolerance=1e-12, beta_laplace=1e-3,
        rtm_dtype="bfloat16", logarithmic=logarithmic,
    )
    res_f = _solve(H, g, dataclasses.replace(base, fused_sweep="interpret"), lap)
    res_u = _solve(H, g, dataclasses.replace(base, fused_sweep="off"), lap)
    np.testing.assert_allclose(
        np.asarray(res_f.solution), np.asarray(res_u.solution),
        rtol=2e-5, atol=1e-6,
    )
    assert int(res_f.iterations) == int(res_u.iterations)


def test_auto_declines_raise_needing_shapes_without_options():
    """VERDICT-r2 contract: auto-fusion must degrade, not break. A shape
    that only compiles at the raised scoped-VMEM limit resolves fused only
    when the caller claims it attached the limit (vmem_raised); under a
    user's outer jit (no options attachable) it falls back to two-matmul."""
    import jax
    import jax.numpy as jnp

    from sartsolver_tpu.models.sart import _resolve_fused

    opts = SolverOptions(fused_sweep="auto", rtm_dtype="bfloat16")
    big = jax.ShapeDtypeStruct((8192, 65536), jnp.bfloat16)
    small = jax.ShapeDtypeStruct((24, 256), jnp.float32)
    orig = jax.default_backend
    jax.default_backend = lambda: "tpu"
    try:
        assert _resolve_fused(opts, None, big, 32, vmem_raised=True) == "compiled"
        assert _resolve_fused(opts, None, big, 32, vmem_raised=False) is None
        # shapes inside the default budget fuse either way
        assert _resolve_fused(opts, None, small, 1, vmem_raised=False) == "compiled"
    finally:
        jax.default_backend = orig


@pytest.mark.parametrize("P_,V_,B_,logarithmic,rtm_dtype,with_lap", [
    # explicit corners: every dtype x variant x laplacian combination is
    # exercised at least once, at shapes away from the fixture's 24x256 —
    # notably int8 with logarithmic/laplacian pins the aux-panel ordering
    # of the int8 update closures (scale, [vm, obs,] penalty)
    (8, 128, 1, False, "float32", False),
    (40, 384, 3, True, "float32", True),
    (16, 256, 2, False, "bfloat16", True),
    (32, 128, 1, True, "bfloat16", False),
    (24, 384, 2, False, "int8", True),
    (40, 256, 3, True, "int8", True),
    (8, 128, 2, True, "int8", False),
])
def test_fused_matches_unfused_config_sweep(
    P_, V_, B_, logarithmic, rtm_dtype, with_lap
):
    """Interpreter-mode fused must track the unfused path across shapes,
    variants, storage dtypes and regularization — not just the fixture
    shape. int8 has no unfused loop; it is compared loosely against the
    fp32 unfused solve (quantized-system perturbation only)."""
    rng = np.random.default_rng(P_ * 1000 + V_)
    H = rng.uniform(0.05, 1.0, (P_, V_)).astype(np.float32)
    H[:, 0] = 0.0  # one dead voxel
    g = H.astype(np.float64) @ rng.uniform(0.5, 2.0, V_)
    lap = None
    if with_lap:
        li = np.arange(V_)
        lap = make_laplacian(
            np.r_[li, li[1:]], np.r_[li, li[:-1]],
            np.r_[np.full(V_, 1.0), np.full(V_ - 1, -0.5)].astype(np.float32),
        )
    base = SolverOptions(
        max_iterations=12, conv_tolerance=0.0, logarithmic=logarithmic,
        beta_laplace=1e-3 if with_lap else 0.0, rtm_dtype=rtm_dtype,
    )
    fus = _solve(H, g, dataclasses.replace(base, fused_sweep="interpret"),
                 lap, batch=B_)
    if rtm_dtype == "int8":
        ref = _solve(H, g, dataclasses.replace(
            base, fused_sweep="off", rtm_dtype="float32"), lap, batch=B_)
        a, b = np.asarray(fus.solution), np.asarray(ref.solution)
        assert np.linalg.norm(a - b) / max(np.linalg.norm(b), 1e-30) < 0.08
    else:
        ref = _solve(H, g, dataclasses.replace(base, fused_sweep="off"),
                     lap, batch=B_)
        np.testing.assert_allclose(
            np.asarray(fus.solution), np.asarray(ref.solution),
            rtol=3e-5, atol=3e-6,
        )
