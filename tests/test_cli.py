"""End-to-end CLI integration: synthetic multi-camera fixtures -> sartsolve
-> solution file contents (SURVEY §4.4)."""

import numpy as np
import h5py
import pytest

from sartsolver_tpu.cli import main

import fixtures as fx


@pytest.fixture
def world(tmp_path):
    return fx.write_world(tmp_path, with_laplacian=True)


def run_cli(paths, *extra):
    args = [
        "-o", paths["output"],
        paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
        paths["img_a"], paths["img_b"],
        "--use_cpu",  # fp64 parity profile on the CPU backend
        "-m", "300", "-c", "1e-6",
        *extra,
    ]
    return main(args)


def test_end_to_end_reconstruction(world, capsys):
    paths, H, f_true, times, scales = world
    assert run_cli(paths) == 0

    out = capsys.readouterr().out
    assert out.count("Processed in:") == len(times)

    with h5py.File(paths["output"], "r") as f:
        value = f["solution/value"][:]
        status = f["solution/status"][:]
        t = f["solution/time"][:]
        assert value.shape == (len(times), fx.NVOXEL)
        assert set(f["solution"]) >= {"value", "time", "status",
                                      f"time_{fx.CAM_A}", f"time_{fx.CAM_B}"}
        # voxel map round-trip (main.cpp:143)
        assert "voxel_map" in f
        assert f["voxel_map/value"].shape[0] == fx.NVOXEL

    # reconstructions reproduce the measurements
    for i, s in enumerate(scales):
        fitted = H @ value[i]
        np.testing.assert_allclose(fitted, H @ (f_true * s), rtol=0.05)
    np.testing.assert_allclose(t, times, atol=0.05)
    assert (status == 0).all()


def test_no_guess_flag(world):
    paths, *_ = world
    assert run_cli(paths, "--no_guess") == 0
    with h5py.File(paths["output"], "r") as f:
        assert f["solution/value"].shape[0] > 0


def test_relaxation_decay_flag(world, capsys):
    paths, H, f_true, times, scales = world
    assert run_cli(paths, "-R", "0.9", "--relaxation_decay", "0.9") == 0
    with h5py.File(paths["output"], "r") as f:
        value = f["solution/value"][:]
    # heavily damped but still reconstructing (geometric schedule shrinks
    # late steps; quality bound is looser than the fixed-alpha test's)
    for i, s in enumerate(scales):
        np.testing.assert_allclose(H @ value[i], H @ (f_true * s), rtol=0.25)
    capsys.readouterr()
    # out-of-range decay takes the polite validation exit
    with pytest.raises(SystemExit):
        run_cli(paths, "--relaxation_decay", "0")
    assert "relaxation_decay" in capsys.readouterr().err


def test_logarithmic_mode(world):
    paths, H, f_true, times, scales = world
    assert run_cli(paths, "-L") == 0
    with h5py.File(paths["output"], "r") as f:
        value = f["solution/value"][:]
    fitted = H @ value[0]
    np.testing.assert_allclose(fitted, H @ (f_true * scales[0]), rtol=0.05)


def test_laplacian_flag(world):
    paths, *_ = world
    assert run_cli(paths, "-l", paths["laplacian"], "-b", "0.001") == 0


def test_time_range_flag(world):
    paths, H, f_true, times, scales = world
    assert run_cli(paths, "-t", "0.15:0.35") == 0
    with h5py.File(paths["output"], "r") as f:
        assert f["solution/value"].shape[0] == 2


def test_pixel_shards_flag(world):
    """Sharded run (4 virtual CPU devices) produces the same solutions."""
    paths, H, f_true, times, scales = world
    assert run_cli(paths) == 0
    with h5py.File(paths["output"], "r") as f:
        ref = f["solution/value"][:]
    assert run_cli(paths, "--pixel_shards", "4") == 0
    with h5py.File(paths["output"], "r") as f:
        sharded = f["solution/value"][:]
    np.testing.assert_allclose(sharded, ref, rtol=1e-8, atol=1e-10)


def test_batch_frames_flag(world):
    """Batched no-guess run matches the serial no-guess run exactly."""
    paths, H, f_true, times, scales = world
    assert run_cli(paths, "--no_guess") == 0
    with h5py.File(paths["output"], "r") as f:
        serial = f["solution/value"][:]
        serial_status = f["solution/status"][:]
    assert run_cli(paths, "--no_guess", "--batch_frames", "3") == 0
    with h5py.File(paths["output"], "r") as f:
        batched = f["solution/value"][:]
        batched_status = f["solution/status"][:]
        t = f["solution/time"][:]
    np.testing.assert_allclose(batched, serial, rtol=1e-9, atol=1e-12)
    np.testing.assert_array_equal(batched_status, serial_status)
    assert t.shape[0] == len(times)  # partial final batch flushed too


def test_chain_frames_matches_serial(world, capsys):
    """--chain_frames K (device-chained warm-start loop, the default) must
    write byte-identical results to serial dispatch (--chain_frames 1):
    same statuses, same iteration counts, same solutions. K=3 over 4
    frames also exercises the padded tail (one duplicated frame whose
    output is discarded)."""
    paths, *_ = world
    assert run_cli(paths, "--chain_frames", "1") == 0
    with h5py.File(paths["output"], "r") as f:
        val_serial = f["solution/value"][:]
        st_serial = f["solution/status"][:]
        it_serial = f["solution/iterations"][:]

    assert run_cli(paths, "--chain_frames", "3") == 0
    out = capsys.readouterr().out
    assert "average over chain" in out
    with h5py.File(paths["output"], "r") as f:
        val_chain = f["solution/value"][:]
        st_chain = f["solution/status"][:]
        it_chain = f["solution/iterations"][:]

    np.testing.assert_array_equal(st_chain, st_serial)
    np.testing.assert_array_equal(it_chain, it_serial)
    np.testing.assert_allclose(val_chain, val_serial, rtol=1e-12, atol=1e-14)


def test_chain_frames_validation(world, capsys):
    paths, *_ = world
    with pytest.raises(SystemExit):
        main(["-o", paths["output"], paths["rtm_a1"], paths["img_a"],
              "--chain_frames", "0"])
    assert "chain_frames" in capsys.readouterr().err


def test_batch_frames_requires_no_guess(world):
    paths, *_ = world
    with pytest.raises(SystemExit):
        run_cli(paths, "--batch_frames", "2")


def test_invalid_args_exit_1(world, capsys):
    paths, *_ = world
    with pytest.raises(SystemExit):
        main(["-R", "2.0", paths["rtm_b"], paths["img_b"]])
    with pytest.raises(SystemExit):
        main(["-m", "0", paths["rtm_b"], paths["img_b"]])
    with pytest.raises(SystemExit):
        main([paths["rtm_b"]])  # fewer than two inputs


def test_bad_input_file_returns_1(world, tmp_path, capsys):
    paths, *_ = world
    bad = str(tmp_path / "bad.h5")
    with h5py.File(bad, "w") as f:
        f.create_group("mystery")
    assert main([bad, paths["img_a"]]) == 1
    assert "neither an RTM" in capsys.readouterr().err


def test_resume_appends_remaining_frames(world, capsys):
    """--resume skips already-written frames, warm-starts from the last
    solution and appends — the final file matches a single full run."""
    paths, H, f_true, times, scales = world

    # reference: one uninterrupted run
    ref_out = paths["output"] + ".ref.h5"
    assert run_cli({**paths, "output": ref_out}) == 0
    with h5py.File(ref_out, "r") as f:
        ref_value = f["solution/value"][:]
        ref_times = f["solution/time"][:]

    # "interrupted" run: only the first half of the time range...
    assert run_cli(paths, "-t", "0.05:0.25") == 0
    with h5py.File(paths["output"], "r") as f:
        assert f["solution/value"].shape[0] == 2
    # ...then resume over the full range
    capsys.readouterr()
    assert run_cli(paths, "--resume") == 0
    assert capsys.readouterr().out.count("Processed in:") == len(times) - 2

    with h5py.File(paths["output"], "r") as f:
        value = f["solution/value"][:]
        t = f["solution/time"][:]
        assert "voxel_map" in f
    np.testing.assert_allclose(t, ref_times)
    np.testing.assert_allclose(value, ref_value, rtol=1e-10, atol=1e-13)

    # resuming a complete file is a no-op, not an error or a duplicate
    capsys.readouterr()
    assert run_cli(paths, "--resume") == 0
    assert capsys.readouterr().out.count("Processed in:") == 0
    with h5py.File(paths["output"], "r") as f:
        assert f["solution/value"].shape[0] == len(times)


def test_resume_rejects_incompatible_file(world, capsys):
    paths, *_ = world
    with h5py.File(paths["output"], "w") as f:
        f.create_dataset("solution/value", data=np.zeros((1, 3)),
                         maxshape=(None, 3), chunks=(1, 3))
        f.create_dataset("solution/time", data=np.asarray([0.1]),
                         maxshape=(None,), chunks=(1,))
        f.create_dataset("solution/status", data=np.asarray([0], np.int32),
                         maxshape=(None,), chunks=(1,))
    assert run_cli(paths, "--resume") == 1
    assert "Cannot resume" in capsys.readouterr().err


def test_resume_truncates_torn_flush(world, capsys):
    """A crash mid-flush leaves per-frame datasets at different lengths;
    resume must trust only fully-written frames and redo the torn one."""
    paths, H, f_true, times, scales = world
    assert run_cli(paths) == 0
    with h5py.File(paths["output"], "r+") as f:
        # simulate _update killed after extending time/status but before
        # writing the value rows for a 5th frame
        f["solution/time"].resize((5,))
        f["solution/time"][4] = 0.9
        f["solution/status"].resize((5,))
    capsys.readouterr()
    assert run_cli(paths, "--resume") == 0
    assert capsys.readouterr().out.count("Processed in:") == 0  # 4 complete
    with h5py.File(paths["output"], "r") as f:
        assert f["solution/time"].shape == (4,)  # torn tail truncated
        assert f["solution/status"].shape == (4,)
        assert f["solution/value"].shape[0] == 4


def test_resume_recreates_torn_first_flush(world):
    """status is created last; a file without it is a torn first flush and
    must be rebuilt from scratch rather than resumed or rejected."""
    paths, H, f_true, times, scales = world
    with h5py.File(paths["output"], "w") as f:
        f.create_dataset("solution/value", data=np.zeros((1, fx.NVOXEL)),
                         maxshape=(None, fx.NVOXEL), chunks=(1, fx.NVOXEL))
    assert run_cli(paths, "--resume") == 0
    with h5py.File(paths["output"], "r") as f:
        assert f["solution/value"].shape[0] == len(times)
        assert f["solution/status"].shape[0] == len(times)


def test_timing_flag_prints_summary(world, capsys):
    paths, *_ = world
    assert run_cli(paths, "--timing") == 0
    out = capsys.readouterr().out
    assert "timing summary" in out
    for phase in ("validate + index inputs", "ingest RTM + upload",
                  "solve chain",  # the default device-chained frame loop
                  "write voxel map"):
        assert phase in out
    # sweep-path provenance in the artifact (VERDICT r3 next #4); on the
    # CPU test backend 'auto' resolves to the two-matmul path
    assert "fused sweep: requested=auto" in out
    assert "engaged=off" in out


def test_provenance_line_printed(world, capsys):
    """Every run prints one startup provenance line with the chosen
    mesh/layout/dtype/fused decision (VERDICT r4 next #6) — no --timing
    needed."""
    paths, *_ = world
    assert run_cli(paths) == 0
    out = capsys.readouterr().out
    line = next(l for l in out.splitlines() if l.startswith("solver: "))
    # the conftest forces 8 virtual CPU devices; the fp64 profile's auto
    # mesh is the reference-style pixel-major row-block layout over all 8
    assert "mesh=8x1" in line
    assert "pixel-major" in line
    assert "compute=float64" in line  # --use_cpu parity profile
    assert "fused_sweep=auto->" in line
    assert "processes=1" in line


def test_internal_error_propagates(world, monkeypatch):
    """VERDICT r1 #7: the polite exit-1 funnel is for input errors only —
    an internal bug (e.g. a shape error in the solver) must traceback."""
    paths, *_ = world
    from sartsolver_tpu.parallel import sharded

    def boom(self, *a, **kw):
        raise ValueError("internal solver bug")

    monkeypatch.setattr(sharded.DistributedSARTSolver, "solve_batch", boom)
    monkeypatch.setattr(sharded.DistributedSARTSolver, "solve_chain", boom)
    with pytest.raises(ValueError, match="internal solver bug"):
        run_cli(paths)


def test_multihost_resume_appends(world, capsys):
    """--multihost --resume single-process: process-0 read + broadcast path."""
    paths, *_ = world
    assert run_cli(paths, "-t", "0:0.25", "-m", "50") == 0
    n_first = capsys.readouterr().out.count("Processed in:")
    assert run_cli(paths, "--resume", "--multihost", "-m", "50") == 0
    n_second = capsys.readouterr().out.count("Processed in:")
    assert n_first >= 1 and n_second >= 1
    import h5py
    with h5py.File(paths["output"], "r") as f:
        assert f["solution/value"].shape[0] == n_first + n_second


def test_mesh_flag_error_is_polite(world, capsys):
    """--pixel_shards beyond the device count is a flag mistake: message +
    exit(1), not a traceback (SartInputError funnel)."""
    paths, *_ = world
    assert run_cli(paths, "--pixel_shards", "4096") == 1
    assert "devices" in capsys.readouterr().err


def test_multihost_resume_error_raises_everywhere(world):
    """A broken resume file in --multihost must fail the job cleanly (the
    error is broadcast before any process can hang in the collective)."""
    paths, *_ = world
    from sartsolver_tpu.config import SartInputError
    from sartsolver_tpu.parallel import multihost as mh

    with pytest.raises(SartInputError, match="corrupt"):
        mh.broadcast_resume_state(None, 16, error="resume file corrupt")


def test_int8_flag_combinations(world, tmp_path, capsys):
    """--rtm_dtype int8 combos: polite exit 1 where it cannot run (CPU
    'auto' backend, --use_cpu), end-to-end solve with
    --fused_sweep interpret — including under --multihost, which is now
    allowed (voxel-major meshes stripe ingest by column, round 3)."""
    paths, H, f_true, times, scales = world
    out = str(tmp_path / "i8.h5")
    inputs = [paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
              paths["img_a"], paths["img_b"]]
    with pytest.raises(SystemExit):
        main(["-o", out, "--rtm_dtype", "int8", "--use_cpu", *inputs])
    capsys.readouterr()
    # auto on the CPU backend cannot engage the fused sweep -> polite error
    assert main(["-o", out, "--rtm_dtype", "int8", *inputs]) == 1
    assert "fused sweep" in capsys.readouterr().err
    # interpret mode runs anywhere, multihost flag included
    assert main(["-o", out, "--rtm_dtype", "int8", "--multihost",
                 "--fused_sweep", "interpret", "-m", "100", *inputs]) == 0
    with h5py.File(out) as f:
        v = f["solution/value"][...]
    for i in range(v.shape[0]):
        fit = H @ v[i]
        ref = H @ (f_true * scales[i])
        assert np.abs(fit - ref).max() / np.abs(ref).max() < 0.05


def test_pipelined_chain_drains_inflight_group_on_error(world, monkeypatch):
    """A frame-read failure mid-run must not discard the already-solved
    in-flight group: the pipelined loop (round 4) defers group k's write
    until group k+1 dispatches, so the error path has to drain it. Here
    the prefetcher yields the first 2 frames (= one full chain of 2) and
    then dies; the run exits 1, but those 2 frames are in the file."""
    import sartsolver_tpu.cli as cli_mod
    from sartsolver_tpu.utils.prefetch import FramePrefetcher

    paths, H, f_true, times, scales = world
    orig_iter = FramePrefetcher.__iter__

    def broken_iter(self):
        it = orig_iter(self)
        count = 0
        for item in it:
            if count >= 2:
                raise OSError("simulated frame-read failure")
            count += 1
            yield item

    monkeypatch.setattr(FramePrefetcher, "__iter__", broken_iter)
    rc = run_cli(paths, "--chain_frames", "2")
    assert rc == 1  # OSError -> polite input-error exit
    with h5py.File(paths["output"], "r") as f:
        assert f["solution/value"].shape[0] == 2
        assert (f["solution/status"][:] == 0).all()


def test_minimal_cache_sizes_match_default(world):
    """--max_cached_frames 1 --max_cached_solutions 1 forces the image
    block cache to evict every frame and the writer to flush per frame
    (solution.cpp:55 cadence at its minimum) — outputs must be identical
    to the default cache sizes."""
    paths, H, f_true, times, scales = world
    assert run_cli(paths) == 0
    with h5py.File(paths["output"], "r") as f:
        ref_value = f["solution/value"][:]
        ref_status = f["solution/status"][:]

    assert run_cli(paths, "--max_cached_frames", "1",
                   "--max_cached_solutions", "1") == 0
    with h5py.File(paths["output"], "r") as f:
        np.testing.assert_array_equal(f["solution/value"][:], ref_value)
        np.testing.assert_array_equal(f["solution/status"][:], ref_status)


def test_debug_nans_clean_run(world):
    """--debug_nans on a healthy solve completes normally (the flag turns
    on jax_debug_nans; a clean pipeline must not trip it)."""
    import jax

    paths, *_ = world
    try:
        assert run_cli(paths, "--debug_nans", "-m", "20") == 0
    finally:
        jax.config.update("jax_debug_nans", False)  # don't leak to other tests


def test_profile_dir_writes_trace(world, tmp_path):
    """--profile_dir wraps the frame loop in jax.profiler.trace and leaves
    a trace artifact behind."""
    import os

    paths, *_ = world
    prof = str(tmp_path / "prof")
    assert run_cli(paths, "--profile_dir", prof, "-m", "10") == 0
    found = []
    for root, _dirs, files in os.walk(prof):
        found += files
    assert found, "profiler trace directory is empty"


def test_timing_summary_printed(world, capsys):
    paths, *_ = world
    assert run_cli(paths, "--timing", "-m", "10") == 0
    out = capsys.readouterr().out
    assert "timing summary" in out
    assert "ingest RTM + upload" in out
    # fused-path provenance line (VERDICT r3 next #4); the fp64 CPU
    # profile never fuses, so 'off'/'not traced' variants are acceptable
    assert "fused sweep: requested=" in out
