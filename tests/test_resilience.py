"""Resilience layer: fault matrix, retry/backoff, divergence recovery,
per-frame failure isolation (docs/RESILIENCE.md).

For every named injection site the matrix proves both legs:

- **recover** — a transient fault (one tripped attempt) is retried and the
  run completes with the same output as a clean run, exit 0;
- **degrade** — a persistent fault exhausts its budget and the run takes
  its documented degradation path: a FAILED/DIVERGED status row + run
  continues + exit 2 for per-frame hazards, a resumable abort + exit 3
  for infrastructure hazards.

The killdrill (tests/test_killdrill.py) separately proves the resumed
output stays byte-identical with the resilience layer active (it is
always active — the retry wrappers and isolation are the default path).

``make faults`` runs exactly this module.
"""

import os
import threading
import time

import h5py
import numpy as np
import pytest

import fixtures as fx
from sartsolver_tpu.cli import main
from sartsolver_tpu.config import DIVERGED, SolverOptions
from sartsolver_tpu.models.sart import make_problem, solve
from sartsolver_tpu.resilience import faults
from sartsolver_tpu.resilience.failures import (
    EXIT_INFRASTRUCTURE,
    EXIT_PARTIAL,
    FRAME_FAILED,
    FrameFailure,
)
from sartsolver_tpu.resilience.retry import (
    RetriesExhausted,
    RetryPolicy,
    reset_retry_stats,
    retry_call,
    retry_stats,
)


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    """Every test starts and ends with no armed faults, fresh retry stats
    and fast backoff (the real defaults would add ~0.1 s per retry)."""
    monkeypatch.setenv("SART_RETRY_BASE_DELAY", "0.001")
    monkeypatch.setenv("SART_RETRY_MAX_DELAY", "0.002")
    faults.clear_faults()
    reset_retry_stats()
    yield
    faults.clear_faults()
    reset_retry_stats()


# ---------------------------------------------------------------------------
# fault-injection registry
# ---------------------------------------------------------------------------

def test_fault_spec_parsing_and_validation():
    armed = faults.parse_fault_spec(
        "hdf5.frame_read:io:1, solve.dispatch:error:0.5:3"
    )
    assert armed["hdf5.frame_read"].kind == "io"
    assert armed["solve.dispatch"].count == 3
    for bad in ("nosuch.site:io:1", "hdf5.frame_read:meteor:1",
                "hdf5.frame_read:io:0", "hdf5.frame_read:io:2",
                "hdf5.frame_read:io", "hdf5.frame_read:io:1:0"):
        with pytest.raises(ValueError):
            faults.parse_fault_spec(bad)


def test_fault_env_round_trip(monkeypatch):
    monkeypatch.setenv("SART_FAULT", "io.flush:io:1:2")
    faults.reset()
    with pytest.raises(faults.InjectedIOError):
        faults.fire(faults.SITE_FLUSH)
    with pytest.raises(faults.InjectedIOError):
        faults.fire(faults.SITE_FLUSH)
    faults.fire(faults.SITE_FLUSH)  # count=2 exhausted: no more trips
    assert faults.fault_trips()["io.flush"] == 2
    monkeypatch.delenv("SART_FAULT")
    faults.reset()


def test_fault_count_and_kinds():
    faults.inject(faults.SITE_SOLVE, "error", count=1)
    with pytest.raises(faults.InjectedFault):
        faults.fire(faults.SITE_SOLVE)
    faults.fire(faults.SITE_SOLVE)  # capped

    faults.inject(faults.SITE_FRAME_READ, "nan", count=1)
    faults.fire(faults.SITE_FRAME_READ)  # nan kind never raises
    arr = np.ones((2, 3))
    poisoned = faults.corrupt(faults.SITE_FRAME_READ, arr)
    assert np.isnan(poisoned).any() and not np.isnan(arr).any()
    # capped: second corrupt is the identity, no copy
    assert faults.corrupt(faults.SITE_FRAME_READ, arr) is arr


def test_fault_probability_is_seeded_deterministic():
    faults.inject(faults.SITE_PREFETCH, "io", prob=0.5)
    pattern1 = []
    for _ in range(32):
        try:
            faults.fire(faults.SITE_PREFETCH)
            pattern1.append(False)
        except faults.InjectedIOError:
            pattern1.append(True)
    faults.clear_faults()
    faults.inject(faults.SITE_PREFETCH, "io", prob=0.5)
    pattern2 = []
    for _ in range(32):
        try:
            faults.fire(faults.SITE_PREFETCH)
            pattern2.append(False)
        except faults.InjectedIOError:
            pattern2.append(True)
    assert pattern1 == pattern2
    assert any(pattern1) and not all(pattern1)


# ---------------------------------------------------------------------------
# retry/backoff
# ---------------------------------------------------------------------------

def test_retry_recovers_after_transient_failure():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    assert retry_call(flaky, site="hdf5.rtm_ingest",
                      policy=RetryPolicy(attempts=4, base_delay=0),
                      sleep=lambda s: None) == "ok"
    stats = retry_stats()["hdf5.rtm_ingest"]
    assert stats["attempts"] == 3 and stats["recoveries"] == 1


def test_retry_exhaustion_raises_with_cause():
    def dead():
        raise OSError("permanent")

    with pytest.raises(RetriesExhausted) as exc:
        retry_call(dead, site="hdf5.rtm_ingest",
                   policy=RetryPolicy(attempts=3, base_delay=0),
                   sleep=lambda s: None)
    assert isinstance(exc.value.__cause__, OSError)
    assert exc.value.attempts == 3
    assert retry_stats()["hdf5.rtm_ingest"]["exhausted"] == 1


def test_retry_does_not_swallow_internal_errors():
    def bug():
        raise ValueError("internal bug")

    with pytest.raises(ValueError, match="internal bug"):
        retry_call(bug, site="hdf5.rtm_ingest",
                   policy=RetryPolicy(attempts=5, base_delay=0),
                   sleep=lambda s: None)
    assert retry_stats()["hdf5.rtm_ingest"]["attempts"] == 1


def test_retry_backoff_is_exponential_capped_jittered():
    delays = []

    def dead():
        raise OSError("x")

    with pytest.raises(RetriesExhausted):
        retry_call(dead, site="prefetch.next",
                   policy=RetryPolicy(attempts=5, base_delay=0.1,
                                      max_delay=0.3, jitter=0.1),
                   sleep=delays.append)
    assert len(delays) == 4  # no sleep after the final attempt
    # exponential under the cap, +-10% jitter
    assert 0.09 <= delays[0] <= 0.11
    assert 0.18 <= delays[1] <= 0.22
    assert all(d <= 0.3 * 1.1 for d in delays)
    assert delays[3] <= 0.33  # capped


def test_retry_deadline_gives_up_early(monkeypatch):
    t = {"now": 0.0}
    monkeypatch.setattr(time, "monotonic", lambda: t["now"])

    def dead():
        t["now"] += 40.0
        raise OSError("slow device")

    with pytest.raises(RetriesExhausted) as exc:
        retry_call(dead, site="multihost.init",
                   policy=RetryPolicy(attempts=10, base_delay=0,
                                      deadline=60.0),
                   sleep=lambda s: None)
    assert exc.value.attempts == 2  # 80s elapsed > 60s deadline


# ---------------------------------------------------------------------------
# FramePrefetcher error paths (ADVICE: satellite coverage)
# ---------------------------------------------------------------------------

def _make_composite(tmp_path, **kw):
    from sartsolver_tpu.io import hdf5files as hf
    from sartsolver_tpu.io.image import CompositeImage

    paths, *_ = fx.write_world(tmp_path, **kw)
    m, i = hf.categorize_input_files(
        [paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
         paths["img_a"], paths["img_b"]])
    sm, si = hf.sort_rtm_files(m), hf.sort_image_files(i)
    masks = hf.read_rtm_frame_masks(sm)
    return CompositeImage(si, masks, [(0.0, 10.0, 0.0, 0.0)], fx.NPIXEL)


def test_prefetcher_surfaces_worker_exception(tmp_path, monkeypatch):
    """A non-retryable worker error (an internal bug, not I/O) ends the
    stream and re-raises on the consumer side — never silently truncates."""
    from sartsolver_tpu.io.image import CompositeImage
    from sartsolver_tpu.utils.prefetch import FramePrefetcher

    composite = _make_composite(tmp_path)
    orig = CompositeImage.frame

    def broken(self, i=None):
        if i == 2:
            raise ValueError("internal decode bug")
        return orig(self, i)

    monkeypatch.setattr(CompositeImage, "frame", broken)
    got = []
    with FramePrefetcher(composite) as frames:
        with pytest.raises(ValueError, match="internal decode bug"):
            for item in frames:
                got.append(item)
    assert len(got) == 2  # frames 0 and 1 arrived before the error


def test_prefetcher_close_during_blocked_put(tmp_path):
    """close() while the worker is blocked on a full queue must release
    the thread, not deadlock."""
    from sartsolver_tpu.utils.prefetch import FramePrefetcher

    composite = _make_composite(tmp_path, n_frames=8)
    pf = FramePrefetcher(composite, depth=1)
    deadline = time.monotonic() + 5
    while pf._queue.qsize() < 1 and time.monotonic() < deadline:
        time.sleep(0.01)  # worker fills the depth-1 queue, then blocks
    assert pf._queue.qsize() >= 1
    pf.close()
    assert not pf._thread.is_alive()


def test_prefetcher_retries_transient_read(tmp_path):
    """One tripped read attempt is retried transparently: every frame
    arrives, in order, and the retry stats show the recovery."""
    from sartsolver_tpu.utils.prefetch import FramePrefetcher

    composite = _make_composite(tmp_path)
    faults.inject(faults.SITE_PREFETCH, "io", count=1)
    with FramePrefetcher(composite) as frames:
        got = list(frames)
    assert len(got) == 4
    assert not any(isinstance(item, FrameFailure) for item in got)
    assert retry_stats()["prefetch.next"]["recoveries"] == 1


def test_prefetcher_isolates_exhausted_frame(tmp_path):
    """Retries exhausted on one frame: with isolation the stream yields a
    FrameFailure for it and CONTINUES; without isolation (the library
    default) the stream aborts with RetriesExhausted."""
    from sartsolver_tpu.utils.prefetch import FramePrefetcher

    policy = RetryPolicy(attempts=2, base_delay=0)
    composite = _make_composite(tmp_path)
    faults.inject(faults.SITE_PREFETCH, "io", count=2)  # = frame 0's budget
    with FramePrefetcher(composite, isolate_failures=True,
                         retry_policy=policy) as frames:
        got = list(frames)
    assert len(got) == 4
    assert isinstance(got[0], FrameFailure)
    assert got[0].time == composite.frame_time(0)
    assert isinstance(got[0].error, RetriesExhausted)
    assert all(not isinstance(item, FrameFailure) for item in got[1:])

    # same fault, no isolation: the stream dies with the exhaustion error
    faults.inject(faults.SITE_PREFETCH, "io", count=2)
    composite2 = _make_composite(tmp_path)
    with FramePrefetcher(composite2, retry_policy=policy) as frames:
        with pytest.raises(RetriesExhausted):
            list(frames)


# ---------------------------------------------------------------------------
# in-solve divergence recovery (models/sart.py)
# ---------------------------------------------------------------------------

def _small_case(seed=0, P=16, V=12):
    rng = np.random.default_rng(seed)
    H = rng.uniform(0.1, 1.0, (P, V)).astype(np.float32)
    f_true = rng.uniform(0.5, 2.0, V)
    return H, H @ f_true


def test_guard_off_by_default_and_identical_when_healthy():
    H, g = _small_case()
    o_off = SolverOptions(max_iterations=200, conv_tolerance=1e-6)
    o_on = SolverOptions(max_iterations=200, conv_tolerance=1e-6,
                         divergence_recovery=3)
    assert o_off.divergence_recovery == 0
    r_off = solve(make_problem(H, opts=o_off), g, opts=o_off)
    r_on = solve(make_problem(H, opts=o_on), g, opts=o_on)
    np.testing.assert_array_equal(
        np.asarray(r_off.solution), np.asarray(r_on.solution))
    assert int(r_off.iterations) == int(r_on.iterations)
    assert int(r_on.status) == 0


def test_nan_poisoned_frame_fails_cleanly():
    """Non-finite measurement: no good iterate can exist (the Eq. 4 guess
    is computed from the poisoned data), so the input guard pre-fails the
    frame — status DIVERGED, zero solution, zero iterations burned."""
    H, g = _small_case(1)
    g = g.copy()
    g[0] = np.nan
    opts = SolverOptions(max_iterations=200, conv_tolerance=1e-6,
                         divergence_recovery=3)
    res = solve(make_problem(H, opts=opts), g, opts=opts)
    assert int(res.status) == DIVERGED
    assert int(res.iterations) == 0
    np.testing.assert_array_equal(np.asarray(res.solution), 0.0)


def test_corrupted_seed_fails_cleanly():
    H, g = _small_case(2)
    f0 = np.full(H.shape[1], np.inf)
    opts = SolverOptions(max_iterations=50, divergence_recovery=2)
    res = solve(make_problem(H, opts=opts), g, f0=f0, opts=opts)
    assert int(res.status) == DIVERGED and int(res.iterations) == 0


def test_batch_isolates_poisoned_frame():
    """One poisoned frame in a batch diverges alone; its neighbours solve
    to exactly what they solve in a clean batch."""
    from sartsolver_tpu.models.sart import (
        prepare_measurement, solve_normalized_batch,
    )
    import jax.numpy as jnp

    H, g = _small_case(3)
    opts = SolverOptions(max_iterations=200, conv_tolerance=1e-6,
                         divergence_recovery=3)
    problem = make_problem(H, opts=opts)
    g_bad = g.copy()
    g_bad[0] = np.nan

    def stage(frames):
        gs, msqs = [], []
        for fr in frames:
            g64, msq, _ = prepare_measurement(fr, opts)
            gs.append(np.asarray(g64, np.float32))
            msqs.append(msq)
        return (jnp.asarray(np.stack(gs)),
                jnp.asarray(np.asarray(msqs, np.float32)),
                jnp.zeros((len(frames), H.shape[1]), jnp.float32))

    res = solve_normalized_batch(
        problem, *stage([g, g_bad, g * 1.1]), opts=opts, use_guess=True)
    ref = solve_normalized_batch(
        problem, *stage([g, g * 1.1]), opts=opts, use_guess=True)
    assert list(np.asarray(res.status)) == [0, DIVERGED, 0]
    sol = np.asarray(res.solution)
    np.testing.assert_array_equal(sol[0], np.asarray(ref.solution)[0])
    np.testing.assert_array_equal(sol[2], np.asarray(ref.solution)[1])
    np.testing.assert_array_equal(sol[1], 0.0)


def test_escalation_ladder_rolls_back_and_exhausts():
    """Genuine numeric divergence (an explicit-Euler-unstable Laplacian
    weight): the guard trips, rolls back, halves, and iterates again
    between trips — ending in a clean DIVERGED frame holding a finite
    iterate, where the unguarded solver runs to the cap with the iterate
    grown ~1e9."""
    from sartsolver_tpu.ops.laplacian import make_laplacian

    H, g = _small_case(3)
    V = H.shape[1]
    rows, cols, vals = [], [], []
    for i in range(V):
        rows.append(i); cols.append(i); vals.append(2.0)
        if i > 0:
            rows.append(i); cols.append(i - 1); vals.append(-1.0)
        if i < V - 1:
            rows.append(i); cols.append(i + 1); vals.append(-1.0)
    lap = make_laplacian(np.asarray(rows), np.asarray(cols),
                         np.asarray(vals, np.float32), dtype="float32")
    kw = dict(max_iterations=500, conv_tolerance=1e-6, beta_laplace=0.8)
    o_on = SolverOptions(divergence_recovery=6, divergence_threshold=1e3, **kw)
    o_off = SolverOptions(**kw)
    r_on = solve(make_problem(H, lap, opts=o_on), g, opts=o_on)
    r_off = solve(make_problem(H, lap, opts=o_off), g, opts=o_off)

    assert int(r_on.status) == DIVERGED
    # the solve RESUMED after each rollback: more iterations than trips
    assert 6 < int(r_on.iterations) < 500
    sol_on = np.asarray(r_on.solution)
    assert np.isfinite(sol_on).all()
    # unguarded: the oscillation grows unbounded for the whole cap
    assert np.asarray(r_off.solution).max() > 1e6 * sol_on.max()


def test_dark_frame_with_guard_stays_benign():
    """An all-zero (shutter-closed) frame must NOT be reported DIVERGED:
    prepare_measurement remaps msq <= 0 to 1.0 before the solver sees it,
    so the guard-on outcome matches guard-off (a finite solve that
    terminates on the stall test, reference-parity status)."""
    H, _ = _small_case(5)
    g = np.zeros(H.shape[0])
    o_on = SolverOptions(max_iterations=500, conv_tolerance=1e-6,
                         divergence_recovery=3)
    o_off = SolverOptions(max_iterations=500, conv_tolerance=1e-6)
    r_on = solve(make_problem(H, opts=o_on), g, opts=o_on)
    r_off = solve(make_problem(H, opts=o_off), g, opts=o_off)
    assert int(r_on.status) == int(r_off.status) != DIVERGED
    assert int(r_on.iterations) == int(r_off.iterations) < 500
    np.testing.assert_array_equal(
        np.asarray(r_on.solution), np.asarray(r_off.solution))


def test_fault_seed_is_process_stable():
    """Trip patterns must reproduce across processes: hash(str) is salted
    per interpreter, so the site seed uses a stable digest."""
    assert faults.site_seed("prefetch.next") == int(
        __import__("zlib").crc32(b"prefetch.next"))


def test_real_device_errors_are_recoverable():
    """The real counterpart of the injected device faults (jaxlib's
    XlaRuntimeError — device OOM, halted runtime) must be in the
    isolation set, or production runs die on exactly the hazard the
    sites model; trace-time bug types must NOT be."""
    from jax.errors import JaxRuntimeError

    from sartsolver_tpu.resilience.failures import RECOVERABLE_FRAME_ERRORS

    assert issubclass(JaxRuntimeError, RECOVERABLE_FRAME_ERRORS)
    assert not issubclass(ValueError, RECOVERABLE_FRAME_ERRORS)
    assert not issubclass(TypeError, RECOVERABLE_FRAME_ERRORS)


def test_log_solver_guard_and_fused_refusal():
    H, g = _small_case(4)
    g = g.copy()
    g[1] = np.nan
    opts = SolverOptions(max_iterations=50, logarithmic=True,
                         divergence_recovery=2)
    res = solve(make_problem(H, opts=opts), g, opts=opts)
    assert int(res.status) == DIVERGED
    with pytest.raises(ValueError, match="divergence_recovery"):
        bad = SolverOptions(max_iterations=50, logarithmic=True,
                            divergence_recovery=2, fused_sweep="interpret")
        solve(make_problem(H, opts=bad), g, opts=bad)


# ---------------------------------------------------------------------------
# CLI fault matrix: recover AND degrade per injection site
# ---------------------------------------------------------------------------

@pytest.fixture
def world(tmp_path):
    return fx.write_world(tmp_path, with_laplacian=True)


def run_cli(paths, *extra):
    return main([
        "-o", paths["output"],
        paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
        paths["img_a"], paths["img_b"],
        "--use_cpu", "-m", "300", "-c", "1e-6",
        *extra,
    ])


def _read_out(paths):
    with h5py.File(paths["output"], "r") as f:
        return (f["solution/value"][:], f["solution/status"][:],
                f["solution/iterations"][:])


def test_cli_frame_read_transient_recovers(world, monkeypatch):
    """SITE hdf5.frame_read, recover leg: one torn read is retried; the
    output equals a clean run's, exit 0."""
    paths, *_ = world
    assert run_cli(paths, "--max_cached_frames", "1") == 0
    clean = _read_out(paths)
    faults.inject(faults.SITE_FRAME_READ, "io", count=1)
    assert run_cli(paths, "--max_cached_frames", "1") == 0
    got = _read_out(paths)
    np.testing.assert_array_equal(got[0], clean[0])
    np.testing.assert_array_equal(got[1], clean[1])
    assert retry_stats()["prefetch.next"]["recoveries"] == 1


def test_cli_frame_read_persistent_isolated(world, capsys):
    """SITE hdf5.frame_read, degrade leg: retries exhausted on one frame →
    FAILED status row, zeros, iterations -1; the other frames solve; exit
    2; summary printed."""
    paths, *_ = world
    faults.inject(faults.SITE_FRAME_READ, "io", count=3)  # = retry budget
    rc = run_cli(paths, "--max_cached_frames", "1")
    assert rc == EXIT_PARTIAL
    value, status, iters = _read_out(paths)
    assert status.shape[0] == 4
    assert list(status) == [FRAME_FAILED, 0, 0, 0]
    assert iters[0] == -1
    np.testing.assert_array_equal(value[0], 0.0)
    assert (value[1:] > 0).any()
    err = capsys.readouterr()
    assert "FAILED" in err.err
    assert "resilience summary" in err.out
    assert "1 failed" in err.out


def test_cli_frame_read_nan_diverges(world):
    """SITE hdf5.frame_read, corruption leg: a NaN-poisoned frame becomes
    a DIVERGED row under --divergence_recovery; the run continues, exit
    2."""
    paths, *_ = world
    faults.inject(faults.SITE_FRAME_READ, "nan", count=1)
    rc = run_cli(paths, "--max_cached_frames", "1",
                 "--divergence_recovery", "2")
    assert rc == EXIT_PARTIAL
    value, status, iters = _read_out(paths)
    assert list(status) == [DIVERGED, 0, 0, 0]
    np.testing.assert_array_equal(value[0], 0.0)


def test_cli_solve_fault_fails_group_and_continues(world):
    """SITE solve.dispatch: a dispatch fault fails exactly its chain group
    (FAILED rows, order preserved), later groups solve, exit 2."""
    paths, *_ = world
    faults.inject(faults.SITE_SOLVE, "error", count=1)
    rc = run_cli(paths, "--chain_frames", "2")
    assert rc == EXIT_PARTIAL
    value, status, iters = _read_out(paths)
    assert list(status) == [FRAME_FAILED, FRAME_FAILED, 0, 0]
    assert (value[2:] > 0).any()


def test_cli_solve_fault_serial_single_frame(world):
    """SITE solve.dispatch, serial loop: exactly one frame fails; the
    next frame's warm start falls back to the last good one."""
    paths, *_ = world
    faults.inject(faults.SITE_SOLVE, "error", count=1)
    rc = run_cli(paths, "--chain_frames", "1")
    assert rc == EXIT_PARTIAL
    _, status, _ = _read_out(paths)
    assert list(status) == [FRAME_FAILED, 0, 0, 0]


def test_cli_device_put_fault_isolated(world):
    """SITE device.put: a staging fault is absorbed like a solve fault."""
    paths, *_ = world
    faults.inject(faults.SITE_DEVICE_PUT, "io", count=1)
    rc = run_cli(paths, "--chain_frames", "2")
    assert rc == EXIT_PARTIAL
    _, status, _ = _read_out(paths)
    assert sorted(status)[:2] == [FRAME_FAILED, FRAME_FAILED]
    assert (status == 0).sum() == 2


def test_cli_fail_fast_disables_isolation(world):
    """--fail_fast: the first exhausted frame aborts the run with the
    infrastructure exit code (the reference's die-on-fault behavior,
    minus the retries)."""
    paths, *_ = world
    faults.inject(faults.SITE_FRAME_READ, "io", count=3)
    rc = run_cli(paths, "--max_cached_frames", "1", "--fail_fast")
    assert rc == EXIT_INFRASTRUCTURE


def test_cli_flush_fault_aborts_resumable(world, capsys):
    """SITE io.flush, degrade leg: a flush failure aborts with the
    infrastructure exit code and the file resumes to a clean run's
    output."""
    paths, *_ = world
    ref = paths["output"] + ".ref.h5"
    assert run_cli({**paths, "output": ref}) == 0
    with h5py.File(ref, "r") as f:
        want = f["solution/value"][:]

    faults.inject(faults.SITE_FLUSH, "io", count=1)
    rc = run_cli(paths, "--max_cached_solutions", "1")
    assert rc == EXIT_INFRASTRUCTURE
    assert "resumable" in capsys.readouterr().err
    assert run_cli(paths, "--resume") == 0
    value, status, _ = _read_out(paths)
    assert status.shape[0] == 4 and (status == 0).all()
    np.testing.assert_allclose(value, want, rtol=1e-10, atol=1e-13)


def test_cli_rtm_ingest_transient_recovers(world):
    """SITE hdf5.rtm_ingest, recover leg: a torn stripe read is retried;
    byte-identical output, exit 0."""
    paths, *_ = world
    assert run_cli(paths) == 0
    clean = _read_out(paths)
    faults.inject(faults.SITE_RTM_INGEST, "io", count=1)
    assert run_cli(paths) == 0
    got = _read_out(paths)
    np.testing.assert_array_equal(got[0], clean[0])
    assert retry_stats()["hdf5.rtm_ingest"]["recoveries"] == 1


def test_cli_rtm_ingest_exhausted_aborts(world, capsys):
    """SITE hdf5.rtm_ingest, degrade leg: no matrix, no run —
    infrastructure exit after the retry budget."""
    paths, *_ = world
    faults.inject(faults.SITE_RTM_INGEST, "io", count=100)
    rc = run_cli(paths)
    assert rc == EXIT_INFRASTRUCTURE
    assert "Unrecoverable after retries" in capsys.readouterr().err


def test_cli_multihost_init_transient_recovers(world):
    """SITE multihost.init, recover leg: the coordinator answers on the
    second attempt (single-process degenerate multihost run)."""
    paths, *_ = world
    faults.inject(faults.SITE_MULTIHOST_INIT, "error", count=1)
    assert run_cli(paths, "--multihost") == 0
    assert retry_stats()["multihost.init"]["recoveries"] == 1


def test_cli_multihost_init_exhausted_aborts(world, capsys):
    """SITE multihost.init, degrade leg: the coordinator never comes up."""
    paths, *_ = world
    faults.inject(faults.SITE_MULTIHOST_INIT, "error", count=100)
    rc = run_cli(paths, "--multihost")
    assert rc == EXIT_INFRASTRUCTURE
    assert "Unrecoverable after retries" in capsys.readouterr().err


def test_cli_divergence_recovery_flag_validation(world, capsys):
    paths, *_ = world
    with pytest.raises(SystemExit):
        run_cli(paths, "--divergence_recovery", "-1")
    capsys.readouterr()
    with pytest.raises(SystemExit):
        run_cli(paths, "--divergence_recovery", "2", "-L",
                "--fused_sweep", "on")
    assert "divergence_recovery" in capsys.readouterr().err


def test_cli_divergence_recovery_healthy_run_identical(world):
    """The guard threaded through the CLI changes nothing on a healthy
    run (the per-frame where-selects are exact identities)."""
    paths, *_ = world
    assert run_cli(paths) == 0
    clean = _read_out(paths)
    assert run_cli(paths, "--divergence_recovery", "3") == 0
    got = _read_out(paths)
    np.testing.assert_array_equal(got[0], clean[0])
    np.testing.assert_array_equal(got[1], clean[1])
    np.testing.assert_array_equal(got[2], clean[2])


def test_cli_flag_parse_error_exits_1_not_2(world, capsys):
    """argparse's native exit code for bad flags is 2, which would collide
    with EXIT_PARTIAL in the documented contract; the CLI remaps it."""
    paths, *_ = world
    with pytest.raises(SystemExit) as exc:
        run_cli(paths, "--no_such_flag")
    assert exc.value.code == 1
    capsys.readouterr()
    with pytest.raises(SystemExit) as exc:
        run_cli(paths, "--batch_frames", "notanumber")
    assert exc.value.code == 1
    capsys.readouterr()
    with pytest.raises(SystemExit) as exc:
        main(["--help"])
    assert exc.value.code == 0  # --help stays 0
    capsys.readouterr()


def test_cli_failed_frames_not_retried_on_resume(world):
    """Documented FAILED-row semantics: --resume treats a FAILED row as
    written (rows are append-only; rerun without --resume to retry)."""
    paths, *_ = world
    faults.inject(faults.SITE_FRAME_READ, "io", count=3)
    assert run_cli(paths, "--max_cached_frames", "1") == EXIT_PARTIAL
    assert run_cli(paths, "--resume") == 0  # nothing left to do
    _, status, _ = _read_out(paths)
    assert list(status) == [FRAME_FAILED, 0, 0, 0]
