"""Pixel-sharded fused panel sweep (ISSUE 5) on the virtual 8-device mesh.

The voxel-panel scan with a per-panel back-projection psum
(ops/fused_sweep.py:sharded_panel_sweep) brings the one-HBM-read loop to
the row-sharded layout the reference distributes over MPI ranks. These
tests mirror the voxel-shard fused parity suite: fused-vs-unfused
numerical parity for the linear, logarithmic and int8 variants, warm-chain
reuse, panel-width invariance, and the divergence-recovery R=0 trace
identity — all under ``XLA_FLAGS=--xla_force_host_platform_device_count=8``
(conftest.py).
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sartsolver_tpu.config import SolverOptions
from sartsolver_tpu.models.sart import (
    FUSED_ENGAGEMENT,
    _resolve_fused,
    make_problem,
    solve,
)
from sartsolver_tpu.parallel.mesh import make_mesh
from sartsolver_tpu.parallel.sharded import DistributedSARTSolver

from test_sart_core import laplacian_1d_chain, make_case


def _aligned_case(seed=20, P=48, V=256):
    rng = np.random.default_rng(seed)
    H = rng.uniform(0.1, 1.0, (P, V)).astype(np.float32)
    f_true = rng.uniform(0.5, 2.0, V)
    g = H.astype(np.float64) @ f_true
    return H, g


def test_panel_sweep_direct_matches_reference_math():
    """sharded_panel_sweep under shard_map == bp-psum + update + forward
    projection computed densely, including the int8 fwd_scale contract."""
    from jax.sharding import PartitionSpec as P_

    from sartsolver_tpu.ops.fused_sweep import sharded_panel_sweep
    from sartsolver_tpu.parallel import shard_map

    rng = np.random.default_rng(3)
    P, V, B = 64, 256, 2  # 8 pixel rows per shard (sublane-aligned)
    H = rng.uniform(0.1, 1.0, (P, V)).astype(np.float32)
    w = rng.standard_normal((B, P)).astype(np.float32)
    f = rng.uniform(0.1, 1.0, (B, V)).astype(np.float32)
    aux = rng.uniform(0.5, 1.5, (1, V)).astype(np.float32)

    def update_fn(f_p, bp_p, a_p):
        return jnp.maximum(f_p + a_p * bp_p, 0)

    mesh = make_mesh(8, 1)
    fn = jax.jit(shard_map(
        lambda r, w_, f_, a_: sharded_panel_sweep(
            r, w_, f_, [a_], update_fn, axis_name="pixels",
            panel_voxels=128,
        ),
        mesh=mesh,
        in_specs=(P_("pixels", None), P_(None, "pixels"), P_(None, None),
                  P_(None, None)),
        out_specs=(P_(None, None), P_(None, "pixels")),
        check_vma=False,
    ))
    f_new, fitted = fn(H, w, f, aux)

    bp_ref = w.astype(np.float64) @ H.astype(np.float64)
    f_new_ref = np.maximum(f.astype(np.float64) + aux * bp_ref, 0)
    fitted_ref = f_new_ref @ H.astype(np.float64).T
    np.testing.assert_allclose(np.asarray(f_new), f_new_ref, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(fitted), fitted_ref, rtol=1e-4,
                               atol=1e-3)


def test_panel_sweep_rejects_misaligned_shapes():
    from sartsolver_tpu.ops.fused_sweep import (
        panel_available,
        pick_panel_voxels,
        sharded_panel_sweep,
    )

    assert not panel_available(8, 200, 4)  # voxels % 128 != 0
    assert not panel_available(9, 256, 4)  # pixels % 8 != 0
    assert panel_available(8, 256, 4)
    assert pick_panel_voxels(8, 200, 4) == 0
    # every returned width divides the voxel extent and is lane-aligned
    for v in (256, 384, 1024, 8192):
        bs = pick_panel_voxels(64, v, 4)
        assert bs > 0 and v % bs == 0 and bs % 128 == 0
    with pytest.raises(ValueError, match="panel_available"):
        sharded_panel_sweep(
            jnp.ones((8, 200)), jnp.ones((1, 8)), jnp.ones((1, 200)), [],
            lambda f, bp: f + bp, axis_name="pixels",
        )


def test_resolve_fused_pixel_sharded_modes():
    """Sweep selection no longer gates on ``pixel_axis is None``: explicit
    modes engage the panel scan under pixel sharding, 'auto' declines off-
    TPU (CPU test backend), misaligned per-shard blocks raise."""
    aligned = jnp.zeros((8, 256), jnp.float32)
    misaligned = jnp.zeros((8, 200), jnp.float32)
    for mode in ("on", "interpret"):
        opts = SolverOptions(fused_sweep=mode)
        assert _resolve_fused(opts, "pixels", aligned, 1) == "panel"
        with pytest.raises(ValueError, match="not tile-aligned"):
            _resolve_fused(opts, "pixels", misaligned, 1)
    assert _resolve_fused(
        SolverOptions(fused_sweep="auto"), "pixels", aligned, 1) is None
    assert _resolve_fused(
        SolverOptions(fused_sweep="off"), "pixels", aligned, 1) is None


@pytest.mark.parametrize("logarithmic", [False, True])
@pytest.mark.parametrize("with_lap", [False, True])
def test_pixel_sharded_fused_equals_unfused(logarithmic, with_lap):
    """Fused panel scan on the row-sharded (8, 1) mesh == the unfused
    two-matmul path: same statuses, same iteration counts, solutions to
    fp32 tolerance (the per-panel psum only regroups the reduction)."""
    from sartsolver_tpu.ops.laplacian import make_laplacian

    H, g = _aligned_case()
    lap = (make_laplacian(*laplacian_1d_chain(H.shape[1], 0.1),
                          dtype="float32") if with_lap else None)
    mk = lambda mode: SolverOptions(
        logarithmic=logarithmic, max_iterations=15, conv_tolerance=1e-12,
        fused_sweep=mode, fused_panel_voxels=128 if mode == "on" else None,
    )
    s_off = DistributedSARTSolver(H, lap, opts=mk("off"), mesh=make_mesh(8, 1))
    res_off = s_off.solve(g)
    s_on = DistributedSARTSolver(H, lap, opts=mk("on"), mesh=make_mesh(8, 1))
    res_on = s_on.solve(g)
    assert FUSED_ENGAGEMENT["last"] == "panel"
    np.testing.assert_allclose(
        res_on.solution, res_off.solution, rtol=2e-4, atol=1e-5)
    assert res_on.status == res_off.status
    assert res_on.iterations == res_off.iterations


@pytest.mark.parametrize("logarithmic", [False, True])
def test_2d_mesh_panel_fused_equals_single_device(logarithmic):
    """Pixel AND voxel sharded (2, 4): the panel scan's per-panel pixel
    psum composes with the voxel-axis forward-projection psum; result
    matches the unfused single-device solve."""
    H, g = _aligned_case(seed=21)
    opts_ref = SolverOptions(
        logarithmic=logarithmic, max_iterations=15, conv_tolerance=1e-12,
        fused_sweep="off",
    )
    res_ref = solve(make_problem(H, opts=opts_ref), g, opts=opts_ref)
    opts_on = dataclasses.replace(
        opts_ref, fused_sweep="on", fused_panel_voxels=128)
    solver = DistributedSARTSolver(H, opts=opts_on, mesh=make_mesh(2, 4))
    res = solver.solve(g)
    assert FUSED_ENGAGEMENT["last"] == "panel"
    np.testing.assert_allclose(
        res.solution, np.asarray(res_ref.solution), rtol=2e-4, atol=1e-5)
    assert res.status == int(res_ref.status)
    assert res.iterations == int(res_ref.iterations)


def test_panel_width_choice_does_not_change_results():
    """The panel width only re-chunks the voxel axis; every voxel's psum
    reduces the same per-shard partials, so solutions agree to fp32
    reassociation noise across widths (XLA blocks the contraction
    differently per slice width) and the derived default."""
    H, g = _aligned_case(seed=22)
    base = None
    for pv in (128, 256, None):
        opts = SolverOptions(max_iterations=12, conv_tolerance=1e-12,
                             fused_sweep="on", fused_panel_voxels=pv)
        solver = DistributedSARTSolver(H, opts=opts, mesh=make_mesh(8, 1))
        sol = solver.solve(g).solution
        if base is None:
            base = sol
        else:
            np.testing.assert_allclose(sol, base, rtol=1e-4, atol=1e-6)


def test_int8_pixel_sharded_loop_matches_single_device():
    """int8 storage now runs on the row-sharded mesh. With a shared f0
    seed (no out-of-loop guess projection, whose per-shard vector
    quantization is a documented approximation), the panel loop's exact
    in-flight dequantization must track the single-device fused solve to
    fp32 tolerance — for the 1-D and 2-D pixel-sharded meshes."""
    H, g = _aligned_case(seed=23)
    opts = SolverOptions(max_iterations=40, conv_tolerance=0.0,
                         rtm_dtype="int8", fused_sweep="interpret")
    f0 = np.full(H.shape[1], 0.5)
    single = solve(make_problem(H, None, opts=opts), g, f0=f0, opts=opts)
    for mesh_shape in ((8, 1), (2, 4)):
        solver = DistributedSARTSolver(
            H, None, opts=opts, mesh=make_mesh(*mesh_shape))
        res = solver.solve(g, f0=f0)
        assert FUSED_ENGAGEMENT["last"] == "panel"
        assert int(res.status) == int(single.status)
        np.testing.assert_allclose(
            res.solution, np.asarray(single.solution), rtol=1e-5, atol=1e-7,
            err_msg=f"mesh {mesh_shape}")


def test_int8_pixel_sharded_guess_mode_runs():
    """Eq. 4 guess mode on the pixel-sharded int8 path: solves cleanly and
    stays near the fp32 pixel-sharded solve (the int8 storage rounding +
    per-shard guess quantization bound the drift; on this underdetermined
    fixture the guess difference persists in the null space, so the bar
    is the documented int8-vs-fp32 tracking tolerance, not fp32 ulp)."""
    H, g = _aligned_case(seed=24)
    opts_i8 = SolverOptions(max_iterations=40, conv_tolerance=0.0,
                            rtm_dtype="int8", fused_sweep="interpret")
    opts_fp = dataclasses.replace(opts_i8, rtm_dtype=None, fused_sweep="on")
    mesh = make_mesh(8, 1)
    res_i8 = DistributedSARTSolver(H, None, opts=opts_i8, mesh=mesh).solve(g)
    res_fp = DistributedSARTSolver(H, None, opts=opts_fp, mesh=mesh).solve(g)
    assert int(res_i8.status) == int(res_fp.status)
    assert np.isfinite(res_i8.solution).all()
    scale = np.abs(res_fp.solution).max()
    assert np.abs(res_i8.solution - res_fp.solution).max() < 0.05 * scale


def test_int8_fused_off_rejected_any_mesh():
    """The driver's int8 refusal is now a MODE refusal (fused_sweep='off'),
    not a mesh refusal: construction succeeds on a pixel-sharded mesh with
    a fused mode, and fails with the updated message when fused is off."""
    from sartsolver_tpu.config import SartInputError

    H, _ = _aligned_case(seed=25)
    with pytest.raises(SartInputError, match="on any mesh"):
        DistributedSARTSolver(
            H, None,
            opts=SolverOptions(rtm_dtype="int8", fused_sweep="off"),
            mesh=make_mesh(8, 1),
        )
    # pixel-sharded int8 with a fused mode constructs (and stages int8)
    solver = DistributedSARTSolver(
        H, None,
        opts=SolverOptions(rtm_dtype="int8", fused_sweep="interpret"),
        mesh=make_mesh(8, 1),
    )
    assert solver.problem.rtm.dtype == jnp.int8


def test_warm_chain_fused_matches_unfused():
    """solve_chain + chain-to-chain warm handoff on the pixel-sharded
    fused path: statuses and solutions match the unfused chain, and the
    carried fitted (the panel scan's locally-complete forward projection)
    seeds the next chain without a recompute."""
    H, g = _aligned_case(seed=26)
    frames = np.stack([g, g * 1.2, g * 0.7])
    mk = lambda mode: SolverOptions(
        max_iterations=12, conv_tolerance=1e-10, fused_sweep=mode,
        fused_panel_voxels=128 if mode == "on" else None,
    )
    s_on = DistributedSARTSolver(H, opts=mk("on"), mesh=make_mesh(8, 1))
    s_off = DistributedSARTSolver(H, opts=mk("off"), mesh=make_mesh(8, 1))
    c_on, c_off = s_on.solve_chain(frames), s_off.solve_chain(frames)
    np.testing.assert_array_equal(np.asarray(c_on.status),
                                  np.asarray(c_off.status))
    np.testing.assert_allclose(
        c_on.fetch_solutions(), c_off.fetch_solutions(),
        rtol=2e-4, atol=1e-5)
    assert c_on.fitted_norm is not None
    w_on = s_on.solve_chain(frames[:1] * 1.05, warm=c_on)
    w_off = s_off.solve_chain(frames[:1] * 1.05, warm=c_off)
    np.testing.assert_allclose(
        w_on.fetch_solutions(), w_off.fetch_solutions(),
        rtol=2e-4, atol=1e-5)


def test_divergence_recovery_r0_trace_identity_and_guarded_run():
    """R=0 keeps the panel-fused program byte-identical to the default
    trace (the guard is a Python-level gate, pinned so enabling the knob
    at 0 can never perturb the pod path's compiled loop), R>0 traces a
    genuinely different program, and a guarded linear panel-fused solve
    matches the unguarded one on healthy data."""
    H, g = _aligned_case(seed=27)

    def lowered_text(recovery):
        opts = SolverOptions(
            max_iterations=8, conv_tolerance=1e-10, fused_sweep="on",
            fused_panel_voxels=128, divergence_recovery=recovery,
        )
        solver = DistributedSARTSolver(H, opts=opts, mesh=make_mesh(8, 1))
        g_dev, norms, msqs = solver._stage_frames(
            solver._check_frames(g[None], False), False)
        f0 = jnp.zeros((1, solver.padded_nvoxel), jnp.float32)
        return solver._batch_fn(True).lower(
            solver.problem, g_dev, jnp.asarray(msqs, jnp.float32), f0
        ).as_text()

    t_default = lowered_text(0)
    assert t_default == lowered_text(0)  # deterministic baseline
    t_guarded = lowered_text(2)
    assert t_guarded != t_default

    # healthy data: guarded == unguarded results (panel path, linear)
    opts0 = SolverOptions(max_iterations=12, conv_tolerance=1e-10,
                          fused_sweep="on", fused_panel_voxels=128)
    opts2 = dataclasses.replace(opts0, divergence_recovery=2)
    r0 = DistributedSARTSolver(H, opts=opts0, mesh=make_mesh(8, 1)).solve(g)
    r2 = DistributedSARTSolver(H, opts=opts2, mesh=make_mesh(8, 1)).solve(g)
    np.testing.assert_array_equal(np.asarray(r0.solution),
                                  np.asarray(r2.solution))
    assert r0.iterations == r2.iterations


def test_unaligned_voxels_fall_back_under_auto_semantics():
    """Padding makes every driver mesh tile-aligned, so the panel path is
    always eligible there; this pins the raw-core contract instead — an
    unaligned hand-built block declines 'auto' (off-TPU) and raises for
    explicit modes (test_resolve_fused_pixel_sharded_modes) — plus the
    driver end-to-end on a deliberately awkward logical shape (52 pixels,
    40 voxels: padding on both axes)."""
    H, g, _ = make_case(seed=28, P=52, V=40)
    opts = SolverOptions(max_iterations=10, conv_tolerance=1e-12,
                         fused_sweep="on")
    solver = DistributedSARTSolver(H, opts=opts, mesh=make_mesh(8, 1))
    res = solver.solve(g)
    assert FUSED_ENGAGEMENT["last"] == "panel"
    opts_off = dataclasses.replace(opts, fused_sweep="off")
    ref = DistributedSARTSolver(
        H, opts=opts_off, mesh=make_mesh(8, 1)).solve(g)
    np.testing.assert_allclose(res.solution, ref.solution,
                               rtol=2e-4, atol=1e-5)
    assert res.iterations == ref.iterations


def test_panel_plan_metrics_recorded():
    """The obs layer's collective plan: tracing the panel path records the
    panel count / psum plan in the metrics registry, so --metrics_out
    artifacts show the per-iteration collective granularity."""
    from sartsolver_tpu.obs import metrics as obs_metrics

    H, g = _aligned_case(seed=29)
    reg = obs_metrics.reset_registry()
    opts = SolverOptions(max_iterations=4, conv_tolerance=1e-10,
                         fused_sweep="on", fused_panel_voxels=128)
    DistributedSARTSolver(H, opts=opts, mesh=make_mesh(8, 1)).solve(g)
    got = {s["name"]: s["value"] for s in reg.snapshot()
           if s["name"].startswith(("fused_panel", "collectives_planned"))}
    # the aligned case: V=256 per-shard voxels, panel 128 -> 2 panels
    assert got.get("fused_panel_count") == 2.0
    assert got.get("fused_panel_voxels") == 128.0
    assert got.get("collectives_planned_total", 0) >= 2.0
