"""Native runtime (libsartrt) vs NumPy fallback equivalence + prefetcher."""

import numpy as np
import pytest

from sartsolver_tpu import native
from sartsolver_tpu.utils.prefetch import FramePrefetcher

import fixtures as fx


@pytest.mark.skipif(__import__("shutil").which("g++") is None,
                    reason="no C++ toolchain; NumPy fallback is the contract")
def test_native_lib_builds():
    lib = native.get_lib()
    assert lib is not None, "g++ toolchain present but native build failed"
    assert lib.sart_native_abi_version() == 2


def test_scatter_coo_matches_numpy():
    rng = np.random.default_rng(1)
    mat_native = np.zeros((40, 30), np.float32)
    mat_np = np.zeros((40, 30), np.float32)
    nnz = 200
    rows = rng.integers(0, 40, nnz)
    cols = rng.integers(0, 30, nnz)
    vals = rng.uniform(size=nnz).astype(np.float32)
    native.scatter_coo(mat_native, rows, cols, vals)
    mat_np[rows, cols] = vals
    np.testing.assert_array_equal(mat_native, mat_np)


def test_scatter_coo_noncontiguous_falls_back():
    mat = np.zeros((40, 60), np.float32)[:, ::2]  # non-contiguous view
    rows = np.array([1, 2])
    cols = np.array([3, 4])
    vals = np.array([1.5, 2.5], np.float32)
    native.scatter_coo(mat, rows, cols, vals)
    assert mat[1, 3] == 1.5 and mat[2, 4] == 2.5


def test_prefetcher_yields_all_frames_in_order(tmp_path):
    paths, H, f_true, times, scales = fx.write_world(tmp_path)
    from sartsolver_tpu.io import hdf5files as hf
    from sartsolver_tpu.io.image import CompositeImage
    m, i = hf.categorize_input_files(
        [paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
         paths["img_a"], paths["img_b"]])
    sm, si = hf.sort_rtm_files(m), hf.sort_image_files(i)
    masks = hf.read_rtm_frame_masks(sm)

    ci = CompositeImage(si, masks, [(0.0, np.inf, 0.0, 0.0)], fx.NPIXEL, 0)
    direct = []
    while (fr := ci.next_frame()) is not None:
        direct.append((fr, ci.frame_time()))

    ci2 = CompositeImage(si, masks, [(0.0, np.inf, 0.0, 0.0)], fx.NPIXEL, 0)
    fetched = list(FramePrefetcher(ci2, depth=2))
    assert len(fetched) == len(direct)
    for (f_direct, t_direct), (f_pre, t_pre, cam_t) in zip(direct, fetched):
        np.testing.assert_array_equal(f_pre, f_direct)
        assert t_pre == t_direct
        assert len(cam_t) == 2


def test_prefetcher_propagates_errors(tmp_path):
    class Exploding:
        # the prefetcher's indexed-streaming surface (frame/len + the
        # time accessors it reads for failure isolation)
        def __len__(self):
            return 3

        def frame(self, i=None):
            raise RuntimeError("boom")

        def frame_time(self, i=None):
            return 0.0

        def camera_frame_time(self, i=None):
            return []

    with pytest.raises(RuntimeError, match="boom"):
        list(FramePrefetcher(Exploding()))


def test_prefetcher_depth_validation(tmp_path):
    with pytest.raises(ValueError):
        FramePrefetcher(None, depth=0)


def test_prefetcher_early_close_releases_worker(tmp_path):
    """Abandoning the iterator mid-stream must not leave the worker blocked."""
    paths, *_ = fx.write_world(tmp_path, n_frames=4)
    from sartsolver_tpu.io import hdf5files as hf
    from sartsolver_tpu.io.image import CompositeImage
    m, i = hf.categorize_input_files(
        [paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
         paths["img_a"], paths["img_b"]])
    sm, si = hf.sort_rtm_files(m), hf.sort_image_files(i)
    masks = hf.read_rtm_frame_masks(sm)
    ci = CompositeImage(si, masks, [(0.0, np.inf, 0.0, 0.0)], fx.NPIXEL, 0)
    pf = FramePrefetcher(ci, depth=1)
    next(iter(pf))  # consume one frame, leave the rest queued
    pf.close()
    assert not pf._thread.is_alive()


def test_sparse_rtm_out_of_range_voxel_rejected(tmp_path):
    """Malformed sparse voxel_index must fail cleanly, not corrupt memory."""
    import h5py
    from sartsolver_tpu.io import hdf5files as hf
    from sartsolver_tpu.io.raytransfer import read_rtm_block
    paths, *_ = fx.write_world(tmp_path)
    with h5py.File(paths["rtm_a2"], "r+") as f:
        vi = f["rtm/with_reflections/voxel_index"]
        data = vi[:]
        data[0] = 10_000  # far outside the global nvoxel
        vi[...] = data
    m, _ = hf.categorize_input_files(
        [paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"]])
    sm = hf.sort_rtm_files(m)
    with pytest.raises(ValueError, match="voxel"):
        read_rtm_block(sm, "with_reflections", fx.NPIXEL, fx.NVOXEL, 0)
