"""Observability layer (sartsolver_tpu/obs, docs/OBSERVABILITY.md):
schema round-trip, sink outputs (JSONL / Prometheus / Chrome trace),
fault-path counters, disabled-path identity, multihost aggregation via
the fake-collectives path, heartbeat content, PhaseTimer-over-registry.

``make obs`` runs exactly this module plus a generated-artifact
``sartsolve metrics --check`` drill.
"""

import json
import os
import re

import h5py
import numpy as np
import pytest

import fixtures as fx
from sartsolver_tpu.cli import main
from sartsolver_tpu.obs import metrics, schema, sinks, trace
from sartsolver_tpu.obs.cli import metrics_main
from sartsolver_tpu.obs.run import RunTelemetry, aggregate_snapshots
from sartsolver_tpu.resilience import faults, watchdog
from sartsolver_tpu.resilience.retry import reset_retry_stats
from sartsolver_tpu.utils.timing import PhaseTimer


@pytest.fixture
def world(tmp_path):
    return fx.write_world(tmp_path, with_laplacian=True)


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """No armed faults, fast retries, no env sinks leaking between tests."""
    monkeypatch.setenv("SART_RETRY_BASE_DELAY", "0.001")
    monkeypatch.setenv("SART_RETRY_MAX_DELAY", "0.002")
    for var in ("SART_METRICS_PROM", "SART_TRACE_EVENTS",
                "SART_HEARTBEAT_FILE", "SART_FAULT"):
        monkeypatch.delenv(var, raising=False)
    faults.clear_faults()
    reset_retry_stats()
    yield
    faults.clear_faults()
    reset_retry_stats()
    trace.uninstall()


def run_cli(paths, *extra):
    return main([
        "-o", paths["output"],
        paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
        paths["img_a"], paths["img_b"],
        "--use_cpu", "-m", "300", "-c", "1e-6",
        *extra,
    ])


def _records(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_registry_instruments_and_labels():
    r = metrics.MetricsRegistry()
    r.counter("c", site="a").inc()
    r.counter("c", site="a").inc(2)
    r.counter("c", site="b").inc(5)
    r.gauge("g").set(3)
    r.gauge("g").set(1)
    r.histogram("h").observe(2.0)
    r.histogram("h").observe(4.0)
    snap = {(s["name"], tuple(sorted(s["labels"].items()))): s
            for s in r.snapshot()}
    assert snap[("c", (("site", "a"),))]["value"] == 3
    assert snap[("c", (("site", "b"),))]["value"] == 5
    assert snap[("g", ())]["value"] == 1
    h = snap[("h", ())]
    assert (h["count"], h["sum"], h["min"], h["max"]) == (2, 6.0, 2.0, 4.0)


def test_registry_counter_rejects_negative():
    with pytest.raises(ValueError):
        metrics.MetricsRegistry().counter("c").inc(-1)


def test_gauge_set_max_is_high_water():
    g = metrics.MetricsRegistry().gauge("depth")
    g.set_max(3)
    g.set_max(1)  # never lowers
    assert g.value == 3


def test_prometheus_families_are_contiguous():
    """All samples of one metric family must form one block under its
    single # HELP/# TYPE pair, whatever order label-sets registered in
    (strict scrapers reject interleaved families)."""
    r = metrics.MetricsRegistry()
    r.counter("frames_total", status="converged").inc(3)
    r.gauge("depth").set(1)
    r.counter("frames_total", status="failed").inc(1)  # late label-set
    text = sinks.render_prometheus(r.snapshot())
    lines = text.splitlines()
    fam = [i for i, ln in enumerate(lines) if "sart_frames_total" in ln]
    assert fam == list(range(fam[0], fam[0] + 4))  # HELP + TYPE + 2 samples
    assert lines.count("# TYPE sart_frames_total counter") == 1


def test_prometheus_every_family_has_help():
    """Exposition-format satellite: strict scrapers warn on HELP-less
    families, so every # TYPE line is immediately preceded by a # HELP
    line for the same family — curated text for the known metrics, a
    docs pointer for anything new."""
    r = metrics.MetricsRegistry()
    r.counter("frames_total", status="converged").inc(3)
    r.gauge("prefetch_queue_depth").set(2)
    r.histogram("frame_solve_ms").observe(12.5)
    r.counter("retry_success_total", site="hdf5.frame_read").inc()
    r.counter("somebody_elses_metric").inc()  # fallback text path
    lines = sinks.render_prometheus(r.snapshot()).splitlines()
    for i, line in enumerate(lines):
        if line.startswith("# TYPE "):
            family = line.split()[2]
            assert i > 0 and lines[i - 1].startswith(f"# HELP {family} "), \
                f"family {family} has no HELP line"
            # HELP carries text, not just the name
            assert len(lines[i - 1].split(" ", 3)[3]) > 4
    # curated text survives the suffixing of histogram sub-series
    assert any(ln.startswith("# HELP sart_frame_solve_ms_count ")
               and "sample count" in ln for ln in lines)


def test_registry_merge_semantics():
    a = metrics.MetricsRegistry()
    a.counter("frames").inc(3)
    a.gauge("depth").set(2)
    a.histogram("ms").observe(10.0)
    b = metrics.MetricsRegistry()
    b.counter("frames").inc(4)
    b.gauge("depth").set(5)
    b.histogram("ms").observe(30.0)
    b.counter("only_b").inc(1)
    a.merge_snapshot(b.snapshot())
    snap = {s["name"]: s for s in a.snapshot()}
    assert snap["frames"]["value"] == 7  # counters sum
    assert snap["depth"]["value"] == 5  # gauges max
    assert snap["ms"]["count"] == 2 and snap["ms"]["max"] == 30.0
    assert snap["only_b"]["value"] == 1  # remote-only appended


def test_reset_registry_swaps_default():
    metrics.get_registry().counter("stale").inc()
    fresh = metrics.reset_registry()
    assert fresh is metrics.get_registry()
    assert not [s for s in fresh.snapshot() if s["name"] == "stale"]


# ---------------------------------------------------------------------------
# PhaseTimer as a registry view
# ---------------------------------------------------------------------------

def test_phase_timer_total_and_order():
    t = PhaseTimer()
    t.add("zulu", 0.2)  # insertion order must win over name order
    t.add("alpha", 0.1)
    t.add("zulu", 0.2)
    out = t.summary()
    lines = out.splitlines()
    assert lines[0] == "timing summary (wall clock):"
    assert lines[1].strip().startswith("zulu")
    assert "avg over 2" in lines[1]
    assert lines[2].strip().startswith("alpha")
    assert lines[-1].strip().startswith("total")
    assert "500.0 ms" in lines[-1]


def test_phase_timer_is_registry_view():
    r = metrics.MetricsRegistry()
    t = PhaseTimer(registry=r)
    t.add("ingest", 1.5)
    snap = [s for s in r.snapshot() if s["name"] == "phase_seconds"]
    assert snap and snap[0]["labels"]["phase"] == "ingest"
    assert snap[0]["sum"] == pytest.approx(1.5)


def test_phase_timer_empty():
    assert "no phases" in PhaseTimer().summary()


def test_phase_timer_detail_rows_excluded_from_total():
    """Per-frame solve rows lie INSIDE the frame-loop phase; summing
    them into the total would fabricate wall clock (review finding)."""
    t = PhaseTimer()
    t.add("frame loop", 10.0)
    t.add("solve frame", 8.0, detail=True)
    out = t.summary()
    assert "solve frame" in out  # still printed as a row
    assert out.splitlines()[-1].strip().startswith("total")
    assert "10000.0 ms" in out.splitlines()[-1]  # not 18000


# ---------------------------------------------------------------------------
# schema round-trip
# ---------------------------------------------------------------------------

def test_schema_valid_records_roundtrip(tmp_path):
    records = [
        schema.make_meta_record(backend="cpu"),
        schema.make_frame_record(1.5, 0, "converged", 10, 3.2, 1e-6,
                                 "chain"),
        schema.make_frame_record(2.5, -3, "failed", -1, None, None,
                                 "failed", error="InjectedIOError"),
        schema.make_event_record("watchdog: fired", 1.0),
        {"type": "metric", "kind": "counter", "name": "frames_total",
         "labels": {"status": "converged"}, "value": 1.0},
        {"type": "metric", "kind": "histogram", "name": "ms",
         "labels": {}, "count": 1, "sum": 2.0, "min": 2.0, "max": 2.0},
        schema.make_summary_record(2, {"converged": 1, "failed": 1}),
    ]
    for rec in records:
        assert schema.validate_record(rec) == [], rec
    path = tmp_path / "run.jsonl"
    path.write_text("".join(json.dumps(r) + "\n" for r in records))
    n, errors = schema.validate_jsonl(str(path), require_run=True)
    assert n == len(records) and errors == []


@pytest.mark.parametrize("rec,needle", [
    ({"type": "nope"}, "unknown record type"),
    ({"type": "frame", "time": 1.0}, "missing required key"),
    ({"type": "frame", "time": "x", "status": 0, "status_name": "s",
      "iterations": 1, "solve_ms": 1.0, "convergence": 1.0,
      "group": "g"}, "has type str"),
    ({"type": "metric", "kind": "counter", "name": "n",
      "labels": {"a": 1}, "value": 1.0}, "strings"),
    ({"type": "metric", "kind": "exotic", "name": "n", "labels": {}},
     "unknown metric kind"),
    ({"type": "meta", "schema": schema.SCHEMA_VERSION + 1, "tool": "t"},
     "newer than"),
    ({"type": "frame", "time": 1.0, "status": 0, "status_name": "s",
      "iterations": 1, "solve_ms": True, "convergence": 1.0,
      "group": "g"}, "solve_ms"),
])
def test_schema_rejects_malformed(rec, needle):
    errors = schema.validate_record(rec)
    assert errors and any(needle in e for e in errors), errors


def test_validate_jsonl_flags_bad_lines(tmp_path):
    path = tmp_path / "bad.jsonl"
    path.write_text('{"type": "event", "message": "m", "t": 1.0}\n'
                    "not json at all\n")
    _, errors = schema.validate_jsonl(str(path))
    assert len(errors) == 1 and "line 2" in errors[0]


def test_run_contract_checks(tmp_path):
    # meta-first, metric presence, summary/frames consistency
    path = tmp_path / "run.jsonl"
    recs = [
        schema.make_meta_record(),
        schema.make_frame_record(1.0, 0, "converged", 5, 1.0, 1e-6, "g"),
        schema.make_summary_record(2, {"converged": 2}),  # wrong count
    ]
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    _, errors = schema.validate_jsonl(str(path), require_run=True)
    assert any("no metric records" in e for e in errors)
    assert any("summary counts 2" in e for e in errors)


# ---------------------------------------------------------------------------
# CLI end to end: artifact + sinks
# ---------------------------------------------------------------------------

def test_cli_metrics_out_artifact(world, tmp_path, capsys):
    paths, H, f_true, times, scales = world
    artifact = str(tmp_path / "run.jsonl")
    prom = str(tmp_path / "run.prom")
    trace_out = str(tmp_path / "run.trace.json")
    os.environ["SART_METRICS_PROM"] = prom
    os.environ["SART_TRACE_EVENTS"] = trace_out
    try:
        assert run_cli(paths, "--metrics_out", artifact) == 0
    finally:
        os.environ.pop("SART_METRICS_PROM", None)
        os.environ.pop("SART_TRACE_EVENTS", None)
    err = capsys.readouterr().err
    assert artifact in err  # the note goes to stderr, never stdout

    # the acceptance contract: --check validates, and every frame record
    # carries solve wall-ms, iterations, convergence and status
    assert metrics_main(["--check", artifact]) == 0
    records = _records(artifact)
    assert records[0]["type"] == "meta"
    assert records[0]["mesh"] == "8x1"
    frames = [r for r in records if r["type"] == "frame"]
    assert len(frames) == len(times)
    for fr in frames:
        assert fr["solve_ms"] > 0
        assert fr["iterations"] > 0
        assert fr["convergence"] is not None
        assert fr["status"] == 0
    names = {(r["name"], tuple(sorted((r.get("labels") or {}).items())))
             for r in records if r["type"] == "metric"}
    assert ("frames_total", (("status", "converged"),)) in names
    assert ("frame_solve_ms", ()) in names
    assert ("writer_queue_depth", ()) in names
    assert ("prefetch_queue_depth", ()) in names
    assert any(n == "bytes_ingested_total" for n, _ in names)
    summary = [r for r in records if r["type"] == "summary"]
    assert len(summary) == 1 and summary[0]["frames"] == len(times)

    # Prometheus textfile
    prom_text = open(prom).read()
    assert '# TYPE sart_frames_total counter' in prom_text
    assert 'sart_frames_total{status="converged"} 4' in prom_text

    # Chrome trace: beacon-fed phase spans + explicit spans, valid JSON
    tr = json.load(open(trace_out))
    names = {e["name"] for e in tr["traceEvents"]}
    assert "ingest.rtm" in names  # explicit span
    assert watchdog.PHASE_DISPATCH in names  # beacon-fed span
    assert all("ts" in e and "pid" in e for e in tr["traceEvents"])


def test_cli_metrics_summary_and_diff(world, tmp_path, capsys):
    paths, *_ = world
    a = str(tmp_path / "a.jsonl")
    b = str(tmp_path / "b.jsonl")
    assert run_cli(paths, "--metrics_out", a) == 0
    assert run_cli(paths, "--metrics_out", b) == 0
    capsys.readouterr()
    assert metrics_main([a]) == 0
    out = capsys.readouterr().out
    assert "4 frame(s)" in out and "converged" in out and "solve ms" in out
    assert metrics_main(["--diff", a, b]) == 0
    out = capsys.readouterr().out
    assert "frames: 4 -> 4" in out
    # an impossible regression threshold trips exit 2
    rigged = _records(a)
    for rec in rigged:
        if rec["type"] == "frame" and rec["solve_ms"]:
            rec["solve_ms"] *= 100
    c = str(tmp_path / "c.jsonl")
    with open(c, "w") as f:
        f.writelines(json.dumps(r) + "\n" for r in rigged)
    assert metrics_main(["--diff", "--threshold", "50", a, c]) == 2


def test_metrics_check_rejects_corrupt(world, tmp_path, capsys):
    paths, *_ = world
    artifact = str(tmp_path / "run.jsonl")
    assert run_cli(paths, "--metrics_out", artifact) == 0
    lines = open(artifact).read().splitlines()
    frame_idx = next(i for i, ln in enumerate(lines) if '"frame"' in ln)
    broken = json.loads(lines[frame_idx])
    del broken["iterations"]
    lines[frame_idx] = json.dumps(broken)
    with open(artifact, "w") as f:
        f.write("\n".join(lines) + "\n")
    assert metrics_main(["--check", artifact]) == 1
    assert "iterations" in capsys.readouterr().err


def test_abort_artifact_is_partial_and_validates(world, tmp_path, capsys):
    """A run that dies before any metric exists still writes a --check-
    clean artifact: finalize_local marks it partial, and the validator
    exempts partial artifacts from the metric-presence requirement."""
    paths, *_ = world
    artifact = str(tmp_path / "abort.jsonl")
    missing = str(tmp_path / "missing.h5")
    assert main(["-o", str(tmp_path / "out.h5"), missing, paths["img_a"],
                 "--metrics_out", artifact]) == 1
    capsys.readouterr()
    assert metrics_main(["--check", artifact]) == 0
    records = _records(artifact)
    assert records[0]["type"] == "meta" and records[0]["partial"] is True


def test_diff_bench_artifacts_threshold(tmp_path, capsys):
    """BENCH artifacts diff on the headline value — a rate, so a DROP
    past the threshold is the regression (review finding: the advertised
    BENCH hook previously compared nothing)."""
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps(schema.make_bench_record(
        "sart_iter_s", 100.0, "iter/s", 1.0, {})) + "\n")
    new.write_text(json.dumps(schema.make_bench_record(
        "sart_iter_s", 50.0, "iter/s", 0.5, {})) + "\n")
    assert metrics_main(["--diff", "--threshold", "5",
                         str(old), str(new)]) == 2
    out = capsys.readouterr()
    assert "bench sart_iter_s: 100 -> 50" in out.out
    assert "regression" in out.err
    # improvement direction never trips
    assert metrics_main(["--diff", "--threshold", "5",
                         str(new), str(old)]) == 0


def _write_artifact(path, records):
    path.write_text("".join(json.dumps(r) + "\n" for r in records))


def test_diff_missing_bench_section_is_loud_skip(tmp_path, capsys):
    """Edge case: the baseline has a bench section, the new artifact does
    not (or vice versa). The gate cannot run — and must say so on stderr
    instead of silently passing as 'no regression'."""
    with_bench = tmp_path / "with.json"
    without = tmp_path / "without.json"
    _write_artifact(with_bench, [schema.make_bench_record(
        "sart_iter_s", 100.0, "iter/s", 1.0, {})])
    # a bare summary-less artifact: individually valid records, no bench
    _write_artifact(without, [{"type": "metric", "kind": "counter",
                               "name": "frames_total", "labels": {},
                               "value": 4}])
    assert metrics_main(["--diff", "--threshold", "5",
                         str(with_bench), str(without)]) == 0
    err = capsys.readouterr().err
    assert "bench section missing from the new artifact" in err
    assert "gate skipped" in err
    capsys.readouterr()
    assert metrics_main(["--diff", "--threshold", "5",
                         str(without), str(with_bench)]) == 0
    assert ("bench section missing from the baseline artifact"
            in capsys.readouterr().err)


def test_diff_zero_baseline_rate_is_loud_skip(tmp_path, capsys):
    """Edge case: a zero-valued baseline rate. No ZeroDivisionError, no
    silent pass — the ratio gate skips with a note."""
    zero = tmp_path / "zero.json"
    live = tmp_path / "live.json"
    _write_artifact(zero, [schema.make_bench_record(
        "sart_iter_s", 0.0, "iter/s", 0.0, {})])
    _write_artifact(live, [schema.make_bench_record(
        "sart_iter_s", 50.0, "iter/s", 0.5, {})])
    assert metrics_main(["--diff", "--threshold", "5",
                         str(zero), str(live)]) == 0
    assert ("baseline bench headline value is zero"
            in capsys.readouterr().err)


def test_diff_one_sided_histogram_is_loud_skip(world, tmp_path, capsys):
    """Edge case: a histogram family present in only one artifact (e.g.
    iterations_to_converge absent because every frame failed) is noted,
    not compared and not a crash."""
    paths, *_ = world
    a = str(tmp_path / "a.jsonl")
    assert run_cli(paths, "--metrics_out", a) == 0
    stripped = [r for r in _records(a)
                if r.get("name") != "iterations_to_converge"]
    b = tmp_path / "b.jsonl"
    _write_artifact(b, stripped)
    capsys.readouterr()
    assert metrics_main(["--diff", "--threshold", "5", a, str(b)]) == 0
    err = capsys.readouterr().err
    assert ("histogram iterations_to_converge missing from the new "
            "artifact" in err)


def test_diff_roofline_gate_trips_on_utilization_drop(tmp_path, capsys):
    """The tentpole's BENCH gate: detail.roofline mxu/hbm utilization
    are rates — a drop past the threshold exits 2 even when the raw
    headline is unchanged (a faster chip can hide an efficiency loss in
    iter/s; the utilization fraction cannot)."""
    def bench(mxu, hbm):
        return [schema.make_bench_record(
            "sart_iter_s", 100.0, "iter/s", 1.0,
            {"roofline": {"mxu_util": mxu, "hbm_util": hbm,
                          "bound": "hbm"}})]
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    _write_artifact(old, bench(0.40, 0.60))
    _write_artifact(new, bench(0.40, 0.30))  # hbm utilization halved
    assert metrics_main(["--diff", "--threshold", "10",
                         str(old), str(new)]) == 2
    out = capsys.readouterr()
    assert "roofline hbm_util" in out.out
    assert "utilization regression" in out.err
    # same direction but inside the band: passes
    ok = tmp_path / "ok.json"
    _write_artifact(ok, bench(0.40, 0.58))
    assert metrics_main(["--diff", "--threshold", "10",
                         str(old), str(ok)]) == 0
    # improvement never trips
    assert metrics_main(["--diff", "--threshold", "10",
                         str(new), str(old)]) == 0


def test_record_buffers_skipped_when_disabled():
    """With no sink configured the typed record lists must not grow
    (unbounded host memory on long runs); the registry aggregates the
    --timing/summary paths read stay live."""
    telem = RunTelemetry(metrics.MetricsRegistry())
    for i in range(10):
        telem.record_frame(float(i), 0, 5, 1e-6, 2.0, "frame")
        telem.record_event(f"event {i}")
    assert telem._frames == [] and telem._events == []
    snap = {s["name"]: s for s in telem.registry.snapshot()}
    assert snap["frames_total"]["value"] == 10
    assert snap["availability_events_total"]["value"] == 10


def test_metrics_subcommand_usage_errors(capsys):
    assert metrics_main([]) == 1
    assert metrics_main(["--diff", "one.jsonl"]) == 1
    assert metrics_main(["/does/not/exist.jsonl"]) == 1


# ---------------------------------------------------------------------------
# faults increment the matching failure counters; exit codes unchanged
# ---------------------------------------------------------------------------

def test_artifact_under_injected_faults(world, tmp_path, monkeypatch):
    paths, H, f_true, times, scales = world
    artifact = str(tmp_path / "run.jsonl")
    # every frame read fails permanently -> all frames FAILED, exit 2
    monkeypatch.setenv("SART_FAULT", "hdf5.frame_read:io:1")
    faults.reset()
    try:
        assert run_cli(paths, "--metrics_out", artifact) == 2
    finally:
        monkeypatch.delenv("SART_FAULT")
        faults.reset()
    assert metrics_main(["--check", artifact]) == 0
    records = _records(artifact)
    frames = [r for r in records if r["type"] == "frame"]
    assert frames and all(f["status"] == -3 for f in frames)
    assert all(f["solve_ms"] is None for f in frames)
    counters = {(r["name"], tuple(sorted(r["labels"].items()))): r["value"]
                for r in records if r["type"] == "metric"
                and r["kind"] == "counter"}
    assert counters[("frames_total", (("status", "failed"),))] == len(frames)
    # the isolation path absorbs RetriesExhausted — that class is the
    # failure counter's key, and the armed site shows in fault_trips
    assert counters[("frame_failures_total",
                     (("error", "RetriesExhausted"),))] == len(frames)
    assert counters[("fault_trips_total",
                     (("site", "hdf5.frame_read"),))] > 0
    assert counters[("retry_exhausted_total",
                     (("site", "prefetch.next"),))] == len(frames)


# ---------------------------------------------------------------------------
# disabled-path identity
# ---------------------------------------------------------------------------

def _normalized_stdout(raw: str) -> str:
    return re.sub(r"\d+\.\d+ ms", "X ms", raw)


def _solution_state(path):
    with h5py.File(path, "r") as f:
        return (f["solution/value"][:], f["solution/status"][:],
                f["solution/iterations"][:], f["solution/time"][:])


def test_disabled_path_identity(world, tmp_path, capsys):
    """Enabling the sinks changes NOTHING user-visible: stdout is
    line-identical (modulo wall-clock digits) and the solution file's
    datasets are byte-identical — the artifact note rides stderr."""
    paths, *_ = world
    assert run_cli(paths) == 0
    plain_out = capsys.readouterr().out
    plain = _solution_state(paths["output"])
    artifact = str(tmp_path / "run.jsonl")
    assert run_cli(paths, "--metrics_out", artifact) == 0
    captured = capsys.readouterr()
    assert _normalized_stdout(captured.out) == _normalized_stdout(plain_out)
    assert artifact not in captured.out
    got = _solution_state(paths["output"])
    for a, b in zip(plain, got):
        np.testing.assert_array_equal(a, b)


def test_span_disabled_is_noop():
    assert trace.active_buffer() is None
    with trace.span("anything", key="value"):
        pass  # no buffer installed: shared null context, records nothing
    assert trace.active_buffer() is None


# ---------------------------------------------------------------------------
# multihost aggregation (fake-collectives path)
# ---------------------------------------------------------------------------

def _fake_allgather_for(snapshots, max_bytes):
    """Build an allgather stub presenting ``snapshots`` as the pod."""
    from sartsolver_tpu.obs.run import _encode_snapshot

    rows = [_encode_snapshot(s, max_bytes)[0] for s in snapshots]

    def allgather(local):
        assert any(bytes(local.tobytes()) == r.tobytes() for r in rows)
        return np.stack(rows)

    return allgather


def test_multihost_aggregation_merges_counters():
    host0 = metrics.MetricsRegistry()
    host0.counter("frames_total", status="converged").inc(3)
    host0.gauge("prefetch_queue_depth").set(1)
    host0.histogram("frame_solve_ms").observe(10.0)
    host1 = metrics.MetricsRegistry()
    host1.counter("frames_total", status="converged").inc(5)
    host1.counter("retry_exhausted_total", site="hdf5.rtm_ingest").inc(1)
    host1.gauge("prefetch_queue_depth").set(4)
    host1.histogram("frame_solve_ms").observe(30.0)
    snaps = [host0.snapshot(), host1.snapshot()]
    merged = aggregate_snapshots(
        snaps[0], allgather=_fake_allgather_for(snaps, 4096),
        max_bytes=4096,
    )
    by = {(s["name"], tuple(sorted(s["labels"].items()))): s
          for s in merged}
    assert by[("frames_total", (("status", "converged"),))]["value"] == 8
    assert by[("prefetch_queue_depth", ())]["value"] == 4
    h = by[("frame_solve_ms", ())]
    assert h["count"] == 2 and h["min"] == 10.0 and h["max"] == 30.0
    assert by[("retry_exhausted_total",
               (("site", "hdf5.rtm_ingest"),))]["value"] == 1


def test_aggregation_truncation_keeps_counters():
    r = metrics.MetricsRegistry()
    r.counter("important_total").inc(7)
    for i in range(50):
        r.histogram("bulk", idx=str(i)).observe(1.0)
    snap = r.snapshot()
    merged = aggregate_snapshots(
        snap, allgather=_fake_allgather_for([snap], 512), max_bytes=512,
    )
    by = {s["name"]: s for s in merged}
    assert by["important_total"]["value"] == 7
    assert by["aggregation_truncated"]["value"] == 1


def test_telemetry_finalize_multihost_fake(tmp_path):
    """RunTelemetry.finalize drives the aggregation through the same
    injectable allgather and only the primary writes."""
    reg = metrics.MetricsRegistry()
    telem = RunTelemetry(reg, jsonl_path=str(tmp_path / "agg.jsonl"))
    telem.record_frame(1.0, 0, 5, 1e-6, 2.0, "frame")
    peer = metrics.MetricsRegistry()
    peer.counter("frames_total", status="converged").inc(9)

    def allgather(local):
        from sartsolver_tpu.obs.run import _encode_snapshot

        peer_buf, _ = _encode_snapshot(peer.snapshot(),
                                       len(local) - 8)
        return np.stack([np.asarray(local), peer_buf])

    telem.finalize(multihost=True, primary=True, allgather=allgather)
    records = _records(str(tmp_path / "agg.jsonl"))
    counters = {(r["name"], tuple(sorted(r["labels"].items()))): r["value"]
                for r in records
                if r["type"] == "metric" and r["kind"] == "counter"}
    assert counters[("frames_total", (("status", "converged"),))] == 10


def test_finalize_without_sinks_runs_no_collective():
    """The disabled path must stay collective-free: a --multihost run
    with no sink configured never pays the end-of-run allgather (the
    gate is part of the pod's collective schedule)."""
    telem = RunTelemetry(metrics.MetricsRegistry())
    assert not telem.enabled

    def explode(_buf):
        raise AssertionError("allgather must not run with no sinks")

    telem.finalize(multihost=True, primary=True, allgather=explode)


def test_encode_snapshot_truncation_is_valid_json():
    """Over-cap snapshots shrink by re-encoding (counters prefix +
    in-payload flag), never by byte-slicing — a sliced payload would
    decode to nothing on every peer."""
    import json as _json

    from sartsolver_tpu.obs.run import _encode_snapshot

    r = metrics.MetricsRegistry()
    for i in range(200):
        r.counter("c", idx=str(i)).inc(1)
    buf, truncated = _encode_snapshot(r.snapshot(), 2048)
    assert truncated
    raw = buf.tobytes()
    length = int.from_bytes(raw[:8], "little")
    decoded = _json.loads(raw[8:8 + length].decode())  # must not raise
    assert any(s["name"] == "aggregation_truncated" for s in decoded)
    assert any(s["name"] == "c" for s in decoded)  # a counter prefix kept


def test_telemetry_secondary_writes_nothing(tmp_path):
    path = tmp_path / "secondary.jsonl"
    telem = RunTelemetry(metrics.MetricsRegistry(), jsonl_path=str(path))
    telem.record_frame(1.0, 0, 5, 1e-6, 2.0, "frame")
    telem.finalize(primary=False)
    assert not path.exists()


# ---------------------------------------------------------------------------
# heartbeat content (satellite): phase + frame counter, not just mtime
# ---------------------------------------------------------------------------

def test_heartbeat_carries_phase_and_frame_counter(tmp_path, monkeypatch):
    hb = str(tmp_path / "hb")
    monkeypatch.setenv("SART_HEARTBEAT_FILE", hb)
    base = watchdog.frames_done()
    watchdog.beacon(watchdog.PHASE_DISPATCH)
    watchdog.beacon(watchdog.PHASE_FRAME_DONE)
    content = open(hb).read()
    assert f"phase={watchdog.PHASE_DISPATCH}" in content
    assert f"frames={base + 1}" in content
    assert "unix=" in content
    watchdog.beacon(watchdog.PHASE_FLUSH)
    watchdog.beacon(watchdog.PHASE_FRAME_DONE)
    content = open(hb).read()
    assert f"phase={watchdog.PHASE_FLUSH}" in content
    assert f"frames={base + 2}" in content


def test_cli_heartbeat_content(world, tmp_path, monkeypatch):
    paths, H, f_true, times, scales = world
    hb = str(tmp_path / "hb")
    monkeypatch.setenv("SART_HEARTBEAT_FILE", hb)
    base = watchdog.frames_done()
    assert run_cli(paths) == 0
    content = open(hb).read()
    assert f"frames={base + len(times)}" in content
    assert content.startswith("phase=")
    # serial path: no scheduler, so no occupancy key leaks in
    assert "occupancy=" not in content


def test_heartbeat_occupancy_when_scheduler_drives(tmp_path, monkeypatch):
    """Satellite: while the continuous batcher drives, the heartbeat
    line gains occupancy= and the in-flight lane serials — a supervisor
    reading it sees lane health, not just a frame counter."""
    hb = str(tmp_path / "hb")
    monkeypatch.setenv("SART_HEARTBEAT_FILE", hb)
    watchdog.set_sched_status_provider(
        lambda: {"occupancy": 0.75, "lanes": [3, 7], "strides": 12}
    )
    try:
        watchdog.beacon(watchdog.PHASE_DISPATCH)
        watchdog.beacon(watchdog.PHASE_FRAME_DONE)
    finally:
        watchdog.set_sched_status_provider(None)
    content = open(hb).read()
    assert "occupancy=0.750" in content
    assert "lanes=3,7" in content
    # still one parseable key=value line
    assert all("=" in tok for tok in content.split())


def test_cli_heartbeat_occupancy_on_sched_path(world, tmp_path,
                                               monkeypatch):
    """Through the real CLI: the default batched path is the scheduler,
    and its heartbeat lines carry the lane view (the last write happens
    at the final frame's retirement, while the provider is installed)."""
    paths, *_ = world
    hb = str(tmp_path / "hb")
    monkeypatch.setenv("SART_HEARTBEAT_FILE", hb)
    assert run_cli(paths, "--no_guess", "--batch_frames", "2") == 0
    content = open(hb).read()
    assert "occupancy=" in content
    assert "lanes=" in content


# ---------------------------------------------------------------------------
# bench schema sharing
# ---------------------------------------------------------------------------

def test_bench_payload_validates_and_keeps_driver_keys():
    import importlib.util
    import sys as _sys

    bench_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")
    spec = importlib.util.spec_from_file_location("_bench_mod", bench_path)
    bench = importlib.util.module_from_spec(spec)
    # bench installs no hooks at import; safe to exec in-process
    spec.loader.exec_module(bench)
    payload = bench._bench_payload(12.5, "iter/s (unit test)", 1.25,
                                   {"sweep": []})
    assert schema.validate_record(payload) == []
    # the historical driver contract: these exact keys, top-level
    for key in ("metric", "value", "unit", "vs_baseline", "detail"):
        assert key in payload
    assert payload["type"] == "bench"
    assert payload["value"] == 12.5 and payload["vs_baseline"] == 1.25
    _sys.modules.pop("_bench_mod", None)


def test_bench_watchdog_payload_validates():
    import importlib.util

    bench_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")
    spec = importlib.util.spec_from_file_location("_bench_mod2", bench_path)
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    payload = bench._watchdog_payload(600.0)
    assert schema.validate_record(payload) == []
    assert payload["value"] == 0.0  # no partial results in this process


# ---------------------------------------------------------------------------
# trace buffer details
# ---------------------------------------------------------------------------

def test_trace_span_and_beacon_tap():
    buf = trace.TraceBuffer()
    trace.install(buf)
    try:
        with trace.span("unit.work", cat="test", frame=3):
            pass
        watchdog.beacon("unit.phase_a")
        watchdog.beacon("unit.phase_b")  # closes phase_a's span
    finally:
        trace.uninstall()
    chrome = buf.to_chrome()
    events = chrome["traceEvents"]
    spans = [e for e in events if e["name"] == "unit.work"]
    assert spans and spans[0]["ph"] == "X" and spans[0]["args"]["frame"] == 3
    assert any(e["name"] == "unit.phase_a" and e["ph"] == "X"
               for e in events)
    buf.close_open_spans()
    assert any(e["name"] == "unit.phase_b"
               for e in buf.to_chrome()["traceEvents"])
    # after uninstall the watchdog tap is cleared
    watchdog.beacon("unit.phase_c")
    assert not any(e["name"] == "unit.phase_c"
                   for e in buf.to_chrome()["traceEvents"])


def test_trace_buffer_is_bounded():
    buf = trace.TraceBuffer(max_events=3)
    for i in range(10):
        buf.add_instant(f"e{i}", "test", 1)
    chrome = buf.to_chrome()
    assert len(chrome["traceEvents"]) == 3
    assert chrome["otherData"]["dropped_events"] == 7
    # the head survives (the part that attributes a slow run)
    assert chrome["traceEvents"][0]["name"] == "e0"


def test_heartbeat_write_is_atomic(tmp_path, monkeypatch):
    """Published via rename — a supervisor reading at an arbitrary
    instant must never see a truncated file; no temp litter remains."""
    hb = tmp_path / "hb"
    monkeypatch.setenv("SART_HEARTBEAT_FILE", str(hb))
    watchdog.beacon(watchdog.PHASE_FRAME_DONE)
    assert hb.read_text().startswith("phase=")
    assert list(tmp_path.glob("hb.*")) == []  # no .tmp left behind
