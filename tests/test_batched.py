"""Batched multi-frame solve: per-frame results must equal serial solves."""

import numpy as np
import pytest

from sartsolver_tpu.config import SolverOptions
from sartsolver_tpu.ops.laplacian import make_laplacian
from sartsolver_tpu.parallel.mesh import make_mesh
from sartsolver_tpu.parallel.sharded import DistributedSARTSolver

from test_sart_core import laplacian_1d_chain, make_case


def make_frames(H, n_frames=3, seed=30):
    rng = np.random.default_rng(seed)
    f_true = rng.uniform(0.5, 2.0, H.shape[1])
    G = np.stack([
        np.abs(H @ (f_true * s) + 0.01 * rng.standard_normal(H.shape[0]))
        for s in (1.0, 1.3, 0.8)[:n_frames]
    ])
    G[0, 3] = -1.0  # one saturated pixel in frame 0 only
    return G


@pytest.mark.parametrize("logarithmic", [False, True])
@pytest.mark.parametrize("mesh_shape", [(8, 1), (2, 4)])
def test_batch_equals_serial(logarithmic, mesh_shape):
    H, _, _ = make_case(seed=31, P=52, V=40)
    lap = make_laplacian(*laplacian_1d_chain(H.shape[1], 0.1), dtype="float64")
    G = make_frames(H)
    opts = SolverOptions.cpu_parity(
        logarithmic=logarithmic, max_iterations=25, conv_tolerance=1e-12
    )
    solver = DistributedSARTSolver(H, lap, opts=opts, mesh=make_mesh(*mesh_shape))

    batch = solver.solve_batch(G)
    for b in range(G.shape[0]):
        serial = solver.solve(G[b])
        np.testing.assert_allclose(
            batch.solution[b], serial.solution, rtol=1e-9, atol=1e-12,
            err_msg=f"frame {b}",
        )
        assert batch.status[b] == serial.status
        assert batch.iterations[b] == serial.iterations


def test_batch_per_frame_convergence_freezing():
    """Frames converging at different iterations keep their own counts."""
    H, _, _ = make_case(seed=32, P=48, V=32, noise=0.0, neg_pixels=0)
    rng = np.random.default_rng(0)
    f_true = rng.uniform(0.5, 2.0, H.shape[1])
    # frame 1 starts much closer to convergence than frame 0
    G = np.stack([np.abs(H @ f_true) * 3.0, np.abs(H @ f_true)])
    opts = SolverOptions.cpu_parity(max_iterations=500, conv_tolerance=1e-6)
    solver = DistributedSARTSolver(H, opts=opts, mesh=make_mesh(8, 1))
    batch = solver.solve_batch(G)
    serial_iters = [solver.solve(G[b]).iterations for b in range(2)]
    assert list(batch.iterations) == serial_iters


def test_batch_warm_start():
    H, _, _ = make_case(seed=33, P=48, V=32)
    G = make_frames(H)
    f0 = np.full((G.shape[0], H.shape[1]), 0.7)
    opts = SolverOptions.cpu_parity(max_iterations=15, conv_tolerance=1e-12)
    solver = DistributedSARTSolver(H, opts=opts, mesh=make_mesh(4, 2))
    batch = solver.solve_batch(G, f0=f0)
    for b in range(G.shape[0]):
        serial = solver.solve(G[b], f0=f0[b])
        np.testing.assert_allclose(batch.solution[b], serial.solution, rtol=1e-9)


def test_batch_shape_validation():
    H, _, _ = make_case(seed=34)
    opts = SolverOptions.cpu_parity(max_iterations=5, conv_tolerance=1e-6)
    solver = DistributedSARTSolver(H, opts=opts, mesh=make_mesh(8, 1))
    with pytest.raises(ValueError, match="Measurements must be"):
        solver.solve_batch(np.zeros((2, H.shape[0] + 1)))


def test_bfloat16_rtm_storage():
    """bf16 RTM with fp32 accumulation stays close to the fp32 result."""
    H, g, _ = make_case(seed=35, P=64, V=48)
    opts32 = SolverOptions(max_iterations=10, conv_tolerance=1e-12)
    optsbf = SolverOptions(max_iterations=10, conv_tolerance=1e-12,
                           rtm_dtype="bfloat16")
    s32 = DistributedSARTSolver(H, opts=opts32, mesh=make_mesh(4, 2))
    sbf = DistributedSARTSolver(H, opts=optsbf, mesh=make_mesh(4, 2))
    r32 = s32.solve(g)
    rbf = sbf.solve(g)
    assert np.isfinite(rbf.solution).all()
    # bf16 has ~3 decimal digits; solutions should agree to ~1%
    np.testing.assert_allclose(rbf.solution, r32.solution, rtol=0.05, atol=0.01)
