"""Durability-discipline lint (SL201–SL205) fixtures and pins.

Each rule gets a true-positive fixture AND a near-miss the rule must
stay silent on — the near-misses encode the precision contract
(docs/STATIC_ANALYSIS.md): reads of durable paths, fsync'd publishes,
completed-before-publish ordering, sorted listings, and checkpoint-
covered mutations are all fine. Plus the family-alone package self-lint
pin (a regression in SL2xx cannot hide behind the other catalogues) and
the catalogue/CLI integration.
"""

import os

from sartsolver_tpu.analysis.durability import DURABILITY_RULES
from sartsolver_tpu.analysis.rules import lint_paths, lint_source


def _lint(src):
    return lint_source("fixture.py", src, rules=DURABILITY_RULES)


def _ids(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# SL201 — raw durable write
# ---------------------------------------------------------------------------


def test_sl201_raw_append_to_durable_path():
    findings = _lint(
        "class J:\n"
        "    def __init__(self, path):\n"
        "        self.path = path  # durable: journal\n"
        "    def append(self, line):\n"
        "        with open(self.path, 'a') as f:\n"
        "            f.write(line)\n"
    )
    assert _ids(findings) == ["SL201"]
    assert findings[0].line == 5


def test_sl201_derived_local_path_is_still_durable():
    findings = _lint(
        "import os\n"
        "class S:\n"
        "    def __init__(self, d):\n"
        "        self.responses_dir = d  # durable: response\n"
        "    def publish(self, rid, data):\n"
        "        p = os.path.join(self.responses_dir, rid + '.json')\n"
        "        with open(p, 'w') as f:\n"
        "            f.write(data)\n"
    )
    assert _ids(findings) == ["SL201"]


def test_sl201_silent_on_reads_and_unmarked_paths():
    findings = _lint(
        "class J:\n"
        "    def __init__(self, path, scratch):\n"
        "        self.path = path  # durable: journal\n"
        "        self.scratch = scratch\n"
        "    def replay(self):\n"
        "        with open(self.path) as f:\n"
        "            return f.read()\n"
        "    def note(self, line):\n"
        "        with open(self.scratch, 'a') as f:\n"
        "            f.write(line)\n"
    )
    assert not [f for f in findings if f.rule == "SL201"]


def test_sl201_suppressible_with_line_comment():
    findings = _lint(
        "class J:\n"
        "    def __init__(self, path):\n"
        "        self.path = path  # durable: journal\n"
        "    def append(self, line):\n"
        "        # legacy escape hatch kept for the migration window\n"
        "        with open(self.path, 'a') as f:  "
        "# sart-lint: disable=SL201\n"
        "            f.write(line)\n"
    )
    assert not findings


# ---------------------------------------------------------------------------
# SL202 — os.replace without fsync
# ---------------------------------------------------------------------------


def test_sl202_replace_without_fsync():
    findings = _lint(
        "import os\n"
        "def publish(path, data):\n"
        "    tmp = path + '.tmp'\n"
        "    with open(tmp, 'w') as f:\n"
        "        f.write(data)\n"
        "    os.replace(tmp, path)\n"
    )
    assert _ids(findings) == ["SL202"]
    assert findings[0].line == 6


def test_sl202_silent_when_tmp_handle_is_fsynced():
    findings = _lint(
        "import os\n"
        "def publish(path, data):\n"
        "    tmp = path + '.tmp'\n"
        "    with open(tmp, 'w') as f:\n"
        "        f.write(data)\n"
        "        f.flush()\n"
        "        os.fsync(f.fileno())\n"
        "    os.replace(tmp, path)\n"
    )
    assert not findings


# ---------------------------------------------------------------------------
# SL203 — commit-order violation
# ---------------------------------------------------------------------------

_SL203_BASE = (
    "import os\n"
    "class S:\n"
    "    def __init__(self, d, journal):\n"
    "        self.responses_dir = d  # durable: response\n"
    "        self.journal = journal\n"
    "    def _respond(self, rid, body):\n"
    "        p = os.path.join(self.responses_dir, rid + '.json')\n"
    "        write_json_atomic(p, body)\n"
)


def test_sl203_publish_before_completed_append():
    findings = _lint(
        _SL203_BASE
        + "    def _finish(self, req, outcome):\n"
          "        self._respond(req.id, {'state': 'done'})\n"
          "        self.journal.completed(req, outcome)\n"
    )
    assert _ids(findings) == ["SL203"]


def test_sl203_silent_when_completed_commits_first():
    findings = _lint(
        _SL203_BASE
        + "    def _finish(self, req, outcome):\n"
          "        self.journal.completed(req, outcome)\n"
          "        self._respond(req.id, {'state': 'done'})\n"
    )
    assert not findings


def test_sl203_only_anchors_the_direct_completed_handler():
    # the serve loop publishes OTHER requests' responses (replay
    # republish, acceptance verdicts) before calling into the handler;
    # only the function appending the completed marker itself is held
    # to the ordering
    findings = _lint(
        _SL203_BASE
        + "    def _finish(self, req, outcome):\n"
          "        self.journal.completed(req, outcome)\n"
          "        self._respond(req.id, {'state': 'done'})\n"
          "    def run(self, reqs):\n"
          "        self._respond('other', {'state': 'pending'})\n"
          "        for req in reqs:\n"
          "            self._finish(req, {})\n"
    )
    assert not findings


# ---------------------------------------------------------------------------
# SL204 — replay nondeterminism
# ---------------------------------------------------------------------------


def test_sl204_wall_clock_reachable_from_replay():
    findings = _lint(
        "import time\n"
        "class S:\n"
        "    def _replay(self):\n"
        "        self._note()\n"
        "    def _note(self):\n"
        "        return time.time()\n"
    )
    assert _ids(findings) == ["SL204"]
    assert findings[0].line == 6


def test_sl204_unsorted_listdir_in_restore():
    findings = _lint(
        "import os\n"
        "class S:\n"
        "    def restore_state(self):\n"
        "        for name in os.listdir(self.d):\n"
        "            pass\n"
    )
    assert _ids(findings) == ["SL204"]


def test_sl204_silent_on_sorted_listdir_and_foreign_functions():
    findings = _lint(
        "import os, time\n"
        "class S:\n"
        "    def restore_state(self):\n"
        "        for name in sorted(os.listdir(self.d)):\n"
        "            pass\n"
        "    def heartbeat(self):\n"
        "        return time.time()\n"
    )
    assert not findings


# ---------------------------------------------------------------------------
# SL205 — uncheckpointed mutation
# ---------------------------------------------------------------------------

_SL205_BASE = (
    "class S:\n"
    "    def __init__(self):\n"
    "        # checkpointed by: _save_state\n"
    "        self.counters = {}\n"
    "    def _save_state(self):\n"
    "        pass\n"
)


def test_sl205_mutation_with_no_boundary():
    findings = _lint(
        _SL205_BASE
        + "    def handle(self):\n"
          "        self.counters['x'] = 1\n"
    )
    assert _ids(findings) == ["SL205"]


def test_sl205_silent_with_local_boundary():
    findings = _lint(
        _SL205_BASE
        + "    def handle(self):\n"
          "        self.counters['x'] = 1\n"
          "        self._save_state()\n"
    )
    assert not findings


def test_sl205_caller_boundary_covers_the_callee():
    findings = _lint(
        _SL205_BASE
        + "    def _bump(self):\n"
          "        self.counters['x'] = 1\n"
          "    def handle(self):\n"
          "        self._bump()\n"
          "        self._save_state()\n"
    )
    assert not findings


def test_sl205_one_uncovered_caller_is_enough():
    findings = _lint(
        _SL205_BASE
        + "    def _bump(self):\n"
          "        self.counters['x'] = 1\n"
          "    def handle(self):\n"
          "        self._bump()\n"
          "        self._save_state()\n"
          "    def hotpath(self):\n"
          "        self._bump()\n"
    )
    assert _ids(findings) == ["SL205"]


def test_sl205_mutator_verb_call_counts_as_mutation():
    findings = _lint(
        "class S:\n"
        "    def __init__(self, admission):\n"
        "        # checkpointed by: _save_state\n"
        "        self.admission = admission\n"
        "    def _save_state(self):\n"
        "        pass\n"
        "    def reject(self, req):\n"
        "        self.admission.shed(req, 'overload')\n"
        "    def view(self):\n"
        "        return self.admission.export_state()\n"
    )
    assert _ids(findings) == ["SL205"]
    assert findings[0].line == 8


# ---------------------------------------------------------------------------
# catalogue + package integration
# ---------------------------------------------------------------------------


def test_sl2xx_registered_in_full_catalogue():
    from sartsolver_tpu.analysis.rules import ALL_RULES

    ids = {rule.id for rule in ALL_RULES}
    assert {"SL201", "SL202", "SL203", "SL204", "SL205"} <= ids


def test_package_self_lint_clean_with_only_sl2xx():
    """Acceptance: the package self-lint passes with the durability
    family alone — a regression in SL2xx cannot hide behind the other
    catalogues. The only suppressions in tree carry why-comments."""
    import sartsolver_tpu

    pkg = os.path.dirname(os.path.abspath(sartsolver_tpu.__file__))
    findings = lint_paths([pkg], rules=DURABILITY_RULES)
    assert not findings, "\n".join(f.format() for f in findings)


def test_list_rules_covers_sl2xx(capsys):
    from sartsolver_tpu.analysis.cli import lint_main

    assert lint_main(["--list-rules", "--select", "SL2"]) == 0
    out = capsys.readouterr().out
    for rule_id in ("SL201", "SL202", "SL203", "SL204", "SL205"):
        assert rule_id in out


def test_select_family_runs_alone(tmp_path, capsys):
    """--select SL2 on a file with both a JAX hazard and a durability
    hazard reports only the durability one."""
    from sartsolver_tpu.analysis.cli import lint_main

    src = (
        "class J:\n"
        "    def __init__(self, path):\n"
        "        self.path = path  # durable: journal\n"
        "    def append(self, line):\n"
        "        with open(self.path, 'a') as f:\n"
        "            f.write(line)\n"
    )
    p = tmp_path / "fixture.py"
    p.write_text(src)
    assert lint_main([str(p), "--select", "SL2", "--no-audit"]) == 1
    out = capsys.readouterr().out
    assert "SL201" in out
