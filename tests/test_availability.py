"""Availability layer: graceful shutdown, hang watchdog, OOM degradation
(docs/RESILIENCE.md §5-§7).

Deterministic CPU drills for the three pressures that dominate fleet
operation — preemption (SIGTERM → stop flag → exit 4; the full
subprocess drills live in tests/test_killdrill.py), silent hangs
(injected ``hang`` faults interrupted by the watchdog's staged
escalation) and device OOM on dispatch (injected ``oom`` faults driving
the batch-halving ladder) — plus the unit semantics of each building
block and the trace-identity proof (the ``guarded_dispatch`` compile
golden equals ``sharded_batch``'s).

``make drills`` runs this module together with the killdrill.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import h5py
import numpy as np
import pytest

import fixtures as fx
from sartsolver_tpu.cli import main
from sartsolver_tpu.resilience import degrade, faults, shutdown, watchdog
from sartsolver_tpu.resilience.failures import (
    EXIT_INFRASTRUCTURE,
    EXIT_INTERRUPTED,
    EXIT_PARTIAL,
    FRAME_FAILED,
    RECOVERABLE_FRAME_ERRORS,
    WatchdogTimeout,
)

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """Fresh faults/flags, fast retries, and a bounded hang release so a
    drill whose watchdog misfires fails loudly instead of wedging the
    suite."""
    monkeypatch.setenv("SART_RETRY_BASE_DELAY", "0.001")
    monkeypatch.setenv("SART_RETRY_MAX_DELAY", "0.002")
    monkeypatch.setenv("SART_HANG_RELEASE", "60")
    monkeypatch.delenv("SART_WATCHDOG_TIMEOUT", raising=False)
    monkeypatch.delenv("SART_HEARTBEAT_FILE", raising=False)
    faults.clear_faults()
    shutdown.reset()
    yield
    faults.clear_faults()
    shutdown.reset()


# ---------------------------------------------------------------------------
# fault-registry extensions: oom + hang kinds
# ---------------------------------------------------------------------------

def test_oom_fault_kind_raises_resource_exhausted():
    faults.inject(faults.SITE_SOLVE, "oom", count=1)
    with pytest.raises(faults.InjectedOOM) as exc:
        faults.fire(faults.SITE_SOLVE)
    assert "RESOURCE_EXHAUSTED" in str(exc.value)
    assert isinstance(exc.value, faults.InjectedFault)  # isolation-absorbable
    assert isinstance(exc.value, RECOVERABLE_FRAME_ERRORS)
    faults.fire(faults.SITE_SOLVE)  # capped


def test_hang_fault_release_valve(monkeypatch):
    """An unwatched hang must not deadlock forever: after
    SART_HANG_RELEASE seconds it raises InjectedFault."""
    monkeypatch.setenv("SART_HANG_RELEASE", "0.12")
    faults.inject(faults.SITE_DEVICE_PUT, "hang", count=1)
    t0 = time.monotonic()
    with pytest.raises(faults.InjectedFault, match="hang.*released"):
        faults.fire(faults.SITE_DEVICE_PUT)
    assert 0.1 <= time.monotonic() - t0 < 5.0


def test_is_resource_exhausted_matcher():
    assert degrade.is_resource_exhausted(faults.InjectedOOM("boom"))
    assert degrade.is_resource_exhausted(
        RuntimeError("RESOURCE_EXHAUSTED: Out of memory while trying to "
                     "allocate 123 bytes"))
    assert degrade.is_resource_exhausted(RuntimeError("xla: out of memory"))
    assert not degrade.is_resource_exhausted(RuntimeError("divide by zero"))
    assert not degrade.is_resource_exhausted(OSError("disk full"))


# ---------------------------------------------------------------------------
# degradation ladder
# ---------------------------------------------------------------------------

def test_ladder_halves_and_sticks():
    events = []
    ladder = degrade.GroupSizeLadder(8, on_event=events.append)
    assert not ladder.degraded and ladder.summary() is None
    assert ladder.note_oom(RuntimeError("oom"))
    assert ladder.size == 4 and ladder.degraded
    assert ladder.note_oom(RuntimeError("oom"))
    assert ladder.note_oom(RuntimeError("oom"))
    assert ladder.size == 1
    # exhausted: the caller falls through to per-frame isolation
    assert not ladder.note_oom(RuntimeError("oom"))
    assert ladder.size == 1
    assert len(events) == 3
    assert "8 -> 4 -> 2 -> 1" in ladder.summary()


def test_ladder_rejects_bad_size():
    with pytest.raises(ValueError):
        degrade.GroupSizeLadder(0)


# ---------------------------------------------------------------------------
# shutdown flag semantics (subprocess SIGTERM drills: test_killdrill.py)
# ---------------------------------------------------------------------------

def test_shutdown_flag_set_by_real_signal():
    assert not shutdown.stop_requested()
    with shutdown.installed():
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 5
        while not shutdown.stop_requested() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert shutdown.stop_requested()
        assert shutdown.stop_signal() == "SIGTERM"
    # uninstalled: the flag survives until reset, the handler does not
    assert shutdown.stop_requested()
    shutdown.reset()
    assert not shutdown.stop_requested()


def test_shutdown_install_resets_stale_flag():
    shutdown._state["stop"] = True
    with shutdown.installed():
        assert not shutdown.stop_requested()


# ---------------------------------------------------------------------------
# watchdog: beacons, heartbeat, staged escalation
# ---------------------------------------------------------------------------

def test_beacon_records_phase_and_thread():
    watchdog.beacon("unit.phase")
    phase, serial, t, ident = watchdog.last_beacon()
    assert phase == "unit.phase" and ident == threading.get_ident()
    watchdog.beacon("unit.phase2")
    assert watchdog.last_beacon()[1] == serial + 1


def test_frame_done_beacon_touches_heartbeat(tmp_path, monkeypatch):
    hb = str(tmp_path / "heartbeat")
    monkeypatch.setenv("SART_HEARTBEAT_FILE", hb)
    watchdog.beacon(watchdog.PHASE_FRAME_DONE)
    assert os.path.exists(hb)
    first = os.stat(hb).st_mtime_ns
    time.sleep(0.05)
    watchdog.beacon(watchdog.PHASE_FRAME_DONE)
    assert os.stat(hb).st_mtime_ns >= first
    # non-frame phases never touch it
    os.unlink(hb)
    watchdog.beacon(watchdog.PHASE_DISPATCH)
    assert not os.path.exists(hb)


def test_watchdog_stays_quiet_under_progress():
    wd = watchdog.Watchdog(timeout=0.3, poll=0.05, hard_exit=False)
    with wd:
        for _ in range(12):
            watchdog.beacon("steady")
            time.sleep(0.05)
    assert wd.fired == 0


def test_watchdog_interrupts_cooperative_stall():
    """Stage 1: a Python-level stall on the main thread is interrupted
    with WatchdogTimeout within timeout + poll."""
    wd = watchdog.Watchdog(timeout=0.3, grace=30, poll=0.05,
                           hard_exit=False)
    watchdog.beacon("stall.start")
    t0 = time.monotonic()
    with wd:
        with pytest.raises(WatchdogTimeout):
            while time.monotonic() - t0 < 10:
                time.sleep(0.01)  # cooperative: async exc lands here
    assert wd.fired == 1
    assert time.monotonic() - t0 < 5


def test_watchdog_revokes_pending_interrupt_after_progress():
    """A stage-1 interrupt aimed at a thread inside a C call stays
    PENDING until the call returns. If the stall resolves on its own
    (progress beacons resume — a slow-but-healthy compile/write), the
    watchdog must revoke the pending exception: otherwise it would
    detonate at an arbitrary later bytecode of a healthy run."""
    wd = watchdog.Watchdog(timeout=0.3, grace=30, poll=0.05,
                           hard_exit=False)
    watchdog.beacon("pre.stall")
    stop_ticker = threading.Event()

    def ticker():
        time.sleep(0.9)  # let stage 1 fire into the sleeping main first
        while not stop_ticker.is_set():
            watchdog.beacon("tick")  # progress resumes -> revocation
            time.sleep(0.05)

    t = threading.Thread(target=ticker, daemon=True)
    try:
        with wd:
            t.start()
            # one long C-level sleep: the interrupt cannot be delivered
            # inside it, only queued as pending
            time.sleep(2.0)
            # back at bytecode level: a revoked interrupt must NOT fire
            for _ in range(50):
                time.sleep(0.01)
    finally:
        stop_ticker.set()
        t.join(timeout=5)
    assert wd.fired >= 1  # stage 1 really did interrupt the stall


def test_watchdog_from_env(monkeypatch):
    monkeypatch.delenv("SART_WATCHDOG_TIMEOUT", raising=False)
    assert watchdog.Watchdog.from_env() is None
    monkeypatch.setenv("SART_WATCHDOG_TIMEOUT", "0")
    assert watchdog.Watchdog.from_env() is None
    monkeypatch.setenv("SART_WATCHDOG_TIMEOUT", "7.5")
    monkeypatch.setenv("SART_WATCHDOG_GRACE", "2.5")
    wd = watchdog.Watchdog.from_env()
    assert wd.timeout == 7.5 and wd.grace == 2.5


def test_watchdog_hard_abort_in_subprocess():
    """Stage 3: a non-cooperative stall (one long C-level sleep — the
    pending async exception can never fire) must end in os._exit(3),
    never a deadlocked process."""
    code = (
        "import time\n"
        "from sartsolver_tpu.resilience import watchdog\n"
        "wd = watchdog.Watchdog(timeout=0.3, grace=0.3, poll=0.05)\n"
        "wd.start()\n"
        "time.sleep(60)\n"  # C-level: only the hard abort can end this
        "print('unreachable')\n"
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True,
        text=True, timeout=120,
    )
    assert proc.returncode == EXIT_INFRASTRUCTURE
    assert "aborting with exit 3" in proc.stderr
    assert "thread stacks" in proc.stderr
    assert "unreachable" not in proc.stdout


# ---------------------------------------------------------------------------
# CLI drills: hang + oom through the real frame loop
# ---------------------------------------------------------------------------

@pytest.fixture
def world(tmp_path):
    return fx.write_world(tmp_path, with_laplacian=True)


def run_cli(paths, *extra):
    return main([
        "-o", paths["output"],
        paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
        paths["img_a"], paths["img_b"],
        "--use_cpu", "-m", "300", "-c", "1e-6",
        *extra,
    ])


def _read_out(paths):
    with h5py.File(paths["output"], "r") as f:
        return (f["solution/value"][:], f["solution/status"][:],
                f["solution/iterations"][:])


def _arm_watchdog(monkeypatch, timeout="3", grace="60"):
    """In-process drills must never reach the hard abort (it would take
    pytest with it): a generous grace keeps stage 3 unreachable while
    stage 1/2 still fire fast."""
    monkeypatch.setenv("SART_WATCHDOG_TIMEOUT", timeout)
    monkeypatch.setenv("SART_WATCHDOG_GRACE", grace)


def test_cli_hang_at_solve_dispatch_escalates_to_failed_row(
        world, monkeypatch, capsys):
    """Injected hang at solve.dispatch: stack dump + WatchdogTimeout →
    the frame becomes a FAILED row within the watchdog timeout and the
    run continues (exit 2) — never a deadlocked process."""
    paths, *_ = world
    assert run_cli(paths, "--chain_frames", "1") == 0  # warm the compiles
    capsys.readouterr()
    _arm_watchdog(monkeypatch)
    faults.inject(faults.SITE_SOLVE, "hang", count=1)
    t0 = time.monotonic()
    rc = run_cli(paths, "--chain_frames", "1")
    elapsed = time.monotonic() - t0
    assert rc == EXIT_PARTIAL
    assert elapsed < 30  # interrupted, not released (release is 60s)
    _, status, iters = _read_out(paths)
    assert list(status) == [FRAME_FAILED, 0, 0, 0]
    assert iters[0] == -1
    err = capsys.readouterr()
    assert "dumping thread stacks" in err.err
    assert "WatchdogTimeout" in err.err
    assert "watchdog" in err.out  # summary records the event


def test_cli_hang_at_device_put_escalates_to_failed_row(
        world, monkeypatch, capsys):
    """Injected hang at the host->device staging site: same escalation."""
    paths, *_ = world
    assert run_cli(paths, "--chain_frames", "2") == 0
    capsys.readouterr()
    _arm_watchdog(monkeypatch)
    faults.inject(faults.SITE_DEVICE_PUT, "hang", count=1)
    rc = run_cli(paths, "--chain_frames", "2")
    assert rc == EXIT_PARTIAL
    _, status, _ = _read_out(paths)
    # the hang fails its whole chain group, later groups solve
    assert list(status) == [FRAME_FAILED, FRAME_FAILED, 0, 0]
    assert "dumping thread stacks" in capsys.readouterr().err


def test_cli_hang_during_solver_construction_aborts(world, monkeypatch,
                                                    capsys):
    """A hang BEFORE the frame loop exists (here: the Laplacian staging
    device.put inside DistributedSARTSolver.__init__) has no frame to
    fail — the watchdog covers the whole expensive body (ingest chunk
    beacons + staging beacons) and the interrupt aborts with
    EXIT_INFRASTRUCTURE instead of wedging until the hang release."""
    paths, *_ = world
    _arm_watchdog(monkeypatch, timeout="2")
    faults.inject(faults.SITE_DEVICE_PUT, "hang", count=1)
    t0 = time.monotonic()
    rc = run_cli(paths, "-l", paths["laplacian"], "-b", "0.001")
    assert rc == EXIT_INFRASTRUCTURE
    assert time.monotonic() - t0 < 30  # interrupted, not released (60s)
    err = capsys.readouterr().err
    assert "dumping thread stacks" in err
    assert "Aborted by the hang watchdog" in err


def test_cli_hang_at_prefetch_aborts_resumably(world, monkeypatch, capsys):
    """Injected hang in the prefetch worker: the main thread is blocked
    on the frame queue (its stage-1 interrupt stays pending), stage 2
    interrupts the worker, the pending interrupt then fires — a clean
    EXIT_INFRASTRUCTURE abort, not a deadlock."""
    paths, *_ = world
    assert run_cli(paths) == 0
    capsys.readouterr()
    _arm_watchdog(monkeypatch, timeout="1.5", grace="1.5")
    faults.inject(faults.SITE_PREFETCH, "hang", count=1)
    t0 = time.monotonic()
    rc = run_cli(paths)
    assert rc == EXIT_INFRASTRUCTURE
    assert time.monotonic() - t0 < 30
    assert "dumping thread stacks" in capsys.readouterr().err


def test_cli_oom_degrades_group_size_and_completes(world, capsys):
    """Injected RESOURCE_EXHAUSTED at dispatch: the chain group halves
    (4 → 2), the same frames re-solve, every frame is written with
    results identical to the undegraded run, and the summary reports the
    sticky reduction."""
    paths, *_ = world
    assert run_cli(paths, "--chain_frames", "4") == 0
    clean = _read_out(paths)
    capsys.readouterr()
    faults.inject(faults.SITE_SOLVE, "oom", count=1)
    rc = run_cli(paths, "--chain_frames", "4")
    assert rc == 0  # every frame solved — degraded, not failed
    got = _read_out(paths)
    np.testing.assert_array_equal(got[0], clean[0])
    np.testing.assert_array_equal(got[1], clean[1])
    np.testing.assert_array_equal(got[2], clean[2])
    out = capsys.readouterr()
    assert "re-solving the same frames at 2" in out.err
    assert "oom degradation: frame-group size 4 -> 2" in out.out


def test_cli_oom_ladder_reaches_one_then_isolates(world, capsys):
    """Persistent OOM: 4 → 2 → 1, then per-frame isolation takes over
    (FAILED rows), the run completes with exit 2."""
    paths, *_ = world
    faults.inject(faults.SITE_SOLVE, "oom", count=100)
    rc = run_cli(paths, "--chain_frames", "4")
    assert rc == EXIT_PARTIAL
    _, status, _ = _read_out(paths)
    assert list(status) == [FRAME_FAILED] * 4
    out = capsys.readouterr()
    assert "frame-group size 4 -> 2 -> 1" in out.out


def test_cli_oom_recovery_after_two_halvings(world):
    """OOM twice: 4 → 2 → 1; the remaining dispatches succeed at size 1
    and every frame is still written successfully."""
    paths, *_ = world
    assert run_cli(paths, "--chain_frames", "4") == 0
    clean = _read_out(paths)
    faults.inject(faults.SITE_SOLVE, "oom", count=2)
    rc = run_cli(paths, "--chain_frames", "4")
    assert rc == 0
    got = _read_out(paths)
    np.testing.assert_array_equal(got[0], clean[0])
    np.testing.assert_array_equal(got[1], clean[1])


def test_cli_multihost_oom_never_halves(world):
    """The ladder is a per-process decision: a multihost OOM must abort
    fail-fast (one process re-dispatching a half-sized collective
    program while its peers run the full size would deadlock the pod),
    never halve-and-retry. Degenerate single-process multihost run pins
    the gate."""
    paths, *_ = world
    faults.inject(faults.SITE_SOLVE, "oom", count=1)
    with pytest.raises(faults.InjectedOOM):
        run_cli(paths, "--multihost", "--chain_frames", "4")
    # the aborted run wrote nothing: no half-sized re-dispatch ever
    # produced rows, so the lazily-created output file never appeared
    # (or, had earlier groups flushed, holds no row past the fault)
    if os.path.exists(paths["output"]):
        with h5py.File(paths["output"], "r") as f:
            assert "solution" not in f or f["solution/value"].shape[0] == 0


def test_cli_heartbeat_file_touched(world, tmp_path, monkeypatch):
    paths, *_ = world
    hb = str(tmp_path / "hb")
    monkeypatch.setenv("SART_HEARTBEAT_FILE", hb)
    assert run_cli(paths) == 0
    assert os.path.exists(hb)


def test_cli_watchdog_off_path_identical(world, monkeypatch):
    """With the watchdog armed but never firing, outputs are identical
    to an unwatched run (the layer is pure observation until a stall)."""
    paths, *_ = world
    assert run_cli(paths) == 0
    clean = _read_out(paths)
    _arm_watchdog(monkeypatch, timeout="300")
    assert run_cli(paths) == 0
    got = _read_out(paths)
    np.testing.assert_array_equal(got[0], clean[0])
    np.testing.assert_array_equal(got[1], clean[1])
    np.testing.assert_array_equal(got[2], clean[2])


# ---------------------------------------------------------------------------
# trace identity: the availability layer is off-path by construction
# ---------------------------------------------------------------------------

def test_guarded_dispatch_registered():
    from sartsolver_tpu.analysis.registry import load_registered_entries

    entries = load_registered_entries()
    assert "guarded_dispatch" in entries
    assert entries["guarded_dispatch"].min_devices == 2


def test_guarded_dispatch_golden_equals_sharded_batch():
    """The checked-in golden of the availability-wrapped dispatch must be
    byte-equal to the unwrapped sharded_batch golden: the machine-checked
    form of 'with the layer disabled the traced programs are
    identical'."""
    import jax

    from sartsolver_tpu.analysis.audit import GOLDENS_DIR

    if jax.default_backend() != "cpu":
        pytest.skip("goldens are checked in for the cpu backend")
    with open(os.path.join(GOLDENS_DIR, "guarded_dispatch.cpu.json")) as fh:
        guarded = json.load(fh)
    with open(os.path.join(GOLDENS_DIR, "sharded_batch.cpu.json")) as fh:
        plain = json.load(fh)
    assert guarded == plain
