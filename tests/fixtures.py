"""Synthetic HDF5 fixtures matching the reference file schemas.

Builds a small multi-camera, multi-segment world:

- 4x4x1 voxel grid, 16 voxels.
- camera A: 3x4 image, 8 masked pixels, RTM split into TWO segment files
  (voxels 0-7 dense, voxels 8-15 sparse) — exercises segment sorting,
  voxel-offset stitching and both storage layouts.
- camera B: 2x3 image, all 6 pixels masked, single dense RTM file.
- asynchronous timelines: camera B's clock is offset by a small jitter.
- a 1-D chain Laplacian over the 16 voxels.
"""

from __future__ import annotations

import os

import h5py
import numpy as np

NX, NY, NZ = 4, 4, 1
NVOXEL = NX * NY * NZ
WAVELENGTH = 500.0

CAM_A = "camA"  # 3x4 image, mask keeps 8 pixels
CAM_B = "camB"  # 2x3 image, all 6 pixels

MASK_A = np.array(
    [[1, 0, 1, 1],
     [0, 1, 1, 0],
     [1, 1, 0, 1]], dtype=np.int64)
MASK_B = np.ones((2, 3), dtype=np.int64)

NPIX_A = int(MASK_A.sum())  # 8
NPIX_B = int(MASK_B.sum())  # 6
NPIXEL = NPIX_A + NPIX_B  # 14 (camA rows first: "camA" < "camB")


def make_rtm_matrices(seed=0):
    rng = np.random.default_rng(seed)
    H_a = rng.uniform(0.1, 1.0, (NPIX_A, NVOXEL)).astype(np.float32)
    H_b = rng.uniform(0.1, 1.0, (NPIX_B, NVOXEL)).astype(np.float32)
    # make the sparse segment actually sparse
    H_a[:, 8:][rng.uniform(size=H_a[:, 8:].shape) < 0.4] = 0.0
    return H_a, H_b


def _write_voxel_map(group, cells, values, coordinate_system=None):
    vm = group.create_group("voxel_map")
    vm.attrs.create("nx", NX, dtype=np.uint64)
    vm.attrs.create("ny", NY, dtype=np.uint64)
    vm.attrs.create("nz", NZ, dtype=np.uint64)
    for name, val in (
        ("xmin", 0.0), ("xmax", 4.0), ("ymin", 0.0), ("ymax", 4.0),
        ("zmin", 0.0), ("zmax", 1.0),
    ):
        vm.attrs.create(name, val, dtype=np.float64)
    if coordinate_system:
        vm.attrs["coordinate_system"] = coordinate_system
    i = cells // (NY * NZ)
    rem = cells % (NY * NZ)
    vm.create_dataset("i", data=i.astype(np.uint64))
    vm.create_dataset("j", data=(rem // NZ).astype(np.uint64))
    vm.create_dataset("k", data=(rem % NZ).astype(np.uint64))
    vm.create_dataset("value", data=values.astype(np.int64))


def _write_rtm_file(path, camera, mask, block, voxel_cells, voxel_values,
                    sparse=False, rtm_name="with_reflections",
                    wavelength=WAVELENGTH):
    npix, nvox = block.shape
    with h5py.File(path, "w") as f:
        rtm = f.create_group("rtm")
        rtm.attrs["camera_name"] = camera
        rtm.attrs.create("npixel", npix, dtype=np.uint64)
        rtm.attrs.create("nvoxel", nvox, dtype=np.uint64)
        rtm.create_dataset("frame_mask", data=mask)
        g = rtm.create_group(rtm_name)
        g.attrs.create("wavelength", wavelength, dtype=np.float64)
        g.attrs.create("is_sparse", 1 if sparse else 0, dtype=np.int64)
        if sparse:
            rows, cols = np.nonzero(block)
            g.create_dataset("pixel_index", data=rows.astype(np.uint64))
            g.create_dataset("voxel_index", data=cols.astype(np.uint64))
            g.create_dataset("value", data=block[rows, cols].astype(np.float32))
        else:
            g.create_dataset("value", data=block.astype(np.float32))
        _write_voxel_map(rtm, voxel_cells, voxel_values)


def _write_image_file(path, camera, frames, times, wavelength=WAVELENGTH):
    with h5py.File(path, "w") as f:
        img = f.create_group("image")
        img.attrs["camera_name"] = camera
        img.attrs.create("wavelength", wavelength, dtype=np.float64)
        img.create_dataset("frame", data=np.asarray(frames, np.float64))
        img.create_dataset("time", data=np.asarray(times, np.float64))


def write_laplacian_file(path, nvoxel=NVOXEL, scale=0.1):
    rows, cols, vals = [], [], []
    for i in range(nvoxel):
        rows.append(i); cols.append(i); vals.append(2.0 * scale)
        if i > 0:
            rows.append(i); cols.append(i - 1); vals.append(-scale)
        if i < nvoxel - 1:
            rows.append(i); cols.append(i + 1); vals.append(-scale)
    with h5py.File(path, "w") as f:
        g = f.create_group("laplacian")
        g.attrs.create("nvoxel", nvoxel, dtype=np.uint64)
        g.create_dataset("i", data=np.asarray(rows, np.uint64))
        g.create_dataset("j", data=np.asarray(cols, np.uint64))
        g.create_dataset("value", data=np.asarray(vals, np.float32))


def frame_from_measurement(mask, g_cam):
    """Embed a per-masked-pixel measurement vector into a full 2-D frame."""
    frame = np.zeros(mask.shape)
    frame.ravel()[np.nonzero(mask.ravel())[0]] = g_cam
    return frame


def write_world(
    tmpdir,
    *,
    n_frames=4,
    seed=0,
    f_scale=None,
    jitter_b=0.003,
    rtm_name="with_reflections",
    with_laplacian=False,
):
    """Write the full fixture world; returns (paths, H_global, f_true, times).

    Measurements: g(t) = H @ (f_true * scale(t)) — each composite frame has a
    known ground truth.
    """
    rng = np.random.default_rng(seed + 100)
    H_a, H_b = make_rtm_matrices(seed)
    H = np.concatenate([H_a, H_b], axis=0)
    f_true = rng.uniform(0.5, 2.0, NVOXEL)

    times_a = 0.1 + 0.1 * np.arange(n_frames)
    times_b = times_a + jitter_b
    scales = f_scale or (1.0 + 0.1 * np.arange(n_frames))

    frames_a = np.stack([
        frame_from_measurement(MASK_A, H_a @ (f_true * s)) for s in scales
    ])
    frames_b = np.stack([
        frame_from_measurement(MASK_B, H_b @ (f_true * s)) for s in scales
    ])

    d = str(tmpdir)
    paths = {
        "rtm_a1": os.path.join(d, "rtm_a_seg1.h5"),
        "rtm_a2": os.path.join(d, "rtm_a_seg2.h5"),
        "rtm_b": os.path.join(d, "rtm_b.h5"),
        "img_a": os.path.join(d, "img_a.h5"),
        "img_b": os.path.join(d, "img_b.h5"),
        "laplacian": os.path.join(d, "laplacian.h5"),
        "output": os.path.join(d, "solution.h5"),
    }

    cells = np.arange(NVOXEL, dtype=np.int64)
    # camera A: two segments (voxels 0-7 dense, 8-15 sparse)
    _write_rtm_file(paths["rtm_a1"], CAM_A, MASK_A, H_a[:, :8],
                    cells[:8], cells[:8], sparse=False, rtm_name=rtm_name)
    _write_rtm_file(paths["rtm_a2"], CAM_A, MASK_A, H_a[:, 8:],
                    cells[8:], cells[:8], sparse=True, rtm_name=rtm_name)
    # camera B: one dense file covering all voxels
    _write_rtm_file(paths["rtm_b"], CAM_B, MASK_B, H_b,
                    cells, cells, sparse=False, rtm_name=rtm_name)

    _write_image_file(paths["img_a"], CAM_A, frames_a, times_a)
    _write_image_file(paths["img_b"], CAM_B, frames_b, times_b)

    if with_laplacian:
        write_laplacian_file(paths["laplacian"])

    return paths, H, f_true, times_a, np.asarray(scales)


class FakeDev:
    """Device stub carrying the process_index a pod would assign."""

    def __init__(self, process_index):
        self.process_index = int(process_index)


class FakeMesh:
    """Duck-typed jax.sharding.Mesh stand-in exposing exactly the surface
    multihost's partition helpers read (devices grid, axis_names, shape).
    Accepts a 1-D list of per-pixel-block process indices (single voxel
    shard) or a 2-D [pixel, voxel] object grid of FakeDev."""

    axis_names = ("pixels", "voxels")

    def __init__(self, procs):
        arr = np.asarray(procs, dtype=object)
        if arr.ndim == 1:
            arr = np.array([[FakeDev(p)] for p in procs], dtype=object)
        self.devices = arr
        self.shape = {"pixels": arr.shape[0], "voxels": arr.shape[1]}
