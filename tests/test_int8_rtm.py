"""int8-quantized RTM storage (opt-in, fused-sweep only).

The fused kernel dequantizes the integer codes exactly in VMEM
(ops/fused_sweep.py), so the loop performs full-fp32 SART on the quantized
matrix Hq = scale * codes; only the storage rounding of H (~1/254 of each
column max) and the per-row quantization of the out-of-loop guess/obs
projections (models/sart.py:int8_back_project) perturb the solve.
"""

import dataclasses

import numpy as np
import pytest

from sartsolver_tpu.config import SolverOptions

P, V = 24, 256


def _case(seed=0):
    rng = np.random.default_rng(seed)
    H = rng.uniform(0.1, 1.0, (P, V)).astype(np.float32)
    H[:, :3] = 0.0
    H[3, :] = 0.0
    f_true = rng.uniform(0.5, 2.0, V)
    g = H.astype(np.float64) @ f_true
    g[5] = -1.0  # saturated detector
    return H, g


def _solve(H, g, opts):
    from sartsolver_tpu.models.sart import make_problem, solve

    return solve(make_problem(H, None, opts=opts), g, opts=opts)


def test_quantize_roundtrip():
    from sartsolver_tpu.models.sart import quantize_rtm

    H, _ = _case()
    codes, scale = quantize_rtm(H)
    assert codes.dtype == np.int8 and scale.shape == (V,)
    Hq = np.asarray(codes, np.float32) * np.asarray(scale)[None, :]
    colmax = np.abs(H).max(axis=0)
    err = np.abs(Hq - H).max(axis=0)
    assert (err <= colmax / 254.0 + 1e-7).all()
    # all-zero columns round-trip to zero with scale 1
    assert (np.asarray(scale)[:3] == 1.0).all()
    assert (Hq[:, :3] == 0.0).all()


def test_problem_stats_match_quantized_matrix():
    from sartsolver_tpu.models.sart import make_problem, quantize_rtm

    H, _ = _case()
    opts = SolverOptions(rtm_dtype="int8", fused_sweep="interpret")
    prob = make_problem(H, None, opts=opts)
    codes, scale = quantize_rtm(H)
    Hq = np.asarray(codes, np.float64) * np.asarray(scale)[None, :]
    np.testing.assert_allclose(np.asarray(prob.ray_density), Hq.sum(0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(prob.ray_length), Hq.sum(1), rtol=1e-5)
    assert prob.rtm.dtype == np.int8 and prob.rtm_scale is not None


@pytest.mark.parametrize("logarithmic", [False, True])
def test_int8_solution_tracks_fp32(logarithmic):
    H, g = _case()
    base = SolverOptions(
        max_iterations=60, conv_tolerance=1e-10, logarithmic=logarithmic,
    )
    ref = _solve(H, g, base)
    res = _solve(H, g, dataclasses.replace(
        base, rtm_dtype="int8", fused_sweep="interpret"))
    assert int(res.status) == int(ref.status)
    a, b = np.asarray(res.solution), np.asarray(ref.solution)
    # solution of the quantized system: a few % of the fp32 solution norm
    assert np.linalg.norm(a - b) / np.linalg.norm(b) < 0.05
    # fitted-space agreement is tighter (the quantized system reproduces
    # the same measurements)
    fa, fb = H.astype(np.float64) @ a.astype(np.float64), H.astype(np.float64) @ b.astype(np.float64)
    assert np.abs(fa - fb).max() / np.abs(fb).max() < 0.01


def test_int8_requires_fused():
    from sartsolver_tpu.models.sart import make_problem, solve

    H, g = _case()
    opts = SolverOptions(rtm_dtype="int8", fused_sweep="off")
    prob = make_problem(H, None, opts=opts)
    with pytest.raises(ValueError, match="requires the fused sweep"):
        solve(prob, g, opts=opts)


def test_int8_validation():
    with pytest.raises(ValueError, match="dtype='float32'"):
        SolverOptions(rtm_dtype="int8", dtype="float64")
    # int32-accumulation bound of the integer projections
    from sartsolver_tpu.models.sart import INT8_MAX_CONTRACTION, make_problem

    huge = np.zeros((INT8_MAX_CONTRACTION + 1, 128), np.float32)
    with pytest.raises(ValueError, match="int32-accumulation"):
        make_problem(
            huge, None,
            opts=SolverOptions(rtm_dtype="int8", fused_sweep="interpret"),
        )


def test_int8_sharded_voxel_major_matches_single():
    """int8 through the sharded driver (voxel-major 1x2 mesh, interpret
    kernel) must match the single-device int8 solve: the on-device
    quantization, sharded scales and per-shard fused sweeps compose."""
    import jax

    from sartsolver_tpu.models.sart import make_problem, solve
    from sartsolver_tpu.parallel.mesh import make_mesh
    from sartsolver_tpu.parallel.sharded import DistributedSARTSolver

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices (virtual CPU mesh)")
    H, g = _case()
    # fixed iteration count (conv_tolerance=0): the out-of-loop guess /
    # fitted0 projections quantize per shard, so the convergence
    # trajectories differ at the ~1e-3 level and a tight stall tolerance
    # would stop the two runs at different iterations
    opts = SolverOptions(
        max_iterations=40, conv_tolerance=0.0,
        rtm_dtype="int8", fused_sweep="interpret",
    )
    single = solve(make_problem(H, None, opts=opts), g, opts=opts)
    mesh = make_mesh(1, 2, devices=jax.devices()[:2])
    solver = DistributedSARTSolver(H, None, opts=opts, mesh=mesh)
    sharded = solver.solve(g)
    assert int(sharded.status) == int(single.status)
    np.testing.assert_allclose(
        np.asarray(sharded.solution), np.asarray(single.solution),
        rtol=1e-2, atol=1e-4,
    )


def test_int8_pixel_sharded_matches_single():
    """int8 on a PIXEL-sharded (2, 1) mesh — the configuration PR 5's
    panel-psum scan unlocked (the driver used to refuse it outright). With
    a shared f0 seed the loop's exact per-panel dequantization must track
    the single-device int8 solve; the remaining refusal (fused_sweep='off')
    is pinned in tests/test_sharded_fused.py."""
    import jax

    from sartsolver_tpu.models.sart import make_problem, solve
    from sartsolver_tpu.parallel.mesh import make_mesh
    from sartsolver_tpu.parallel.sharded import DistributedSARTSolver

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices (virtual CPU mesh)")
    H, g = _case()
    opts = SolverOptions(
        max_iterations=40, conv_tolerance=0.0,
        rtm_dtype="int8", fused_sweep="interpret",
    )
    f0 = np.full(V, 0.5)
    single = solve(make_problem(H, None, opts=opts), g, f0=f0, opts=opts)
    solver = DistributedSARTSolver(
        H, None, opts=opts,
        mesh=make_mesh(2, 1, devices=jax.devices()[:2]),
    )
    sharded = solver.solve(g, f0=f0)
    assert int(sharded.status) == int(single.status)
    np.testing.assert_allclose(
        np.asarray(sharded.solution), np.asarray(single.solution),
        rtol=1e-5, atol=1e-7,
    )


def test_two_pass_ingest_matches_device_quantization(tmp_path):
    """read_and_quantize_rtm (host-side two-pass, 1-byte/element device
    footprint) must produce the same codes/scales as staging fp32 and
    quantizing on device, and solve identically through the driver."""
    import jax

    import fixtures as fx
    from sartsolver_tpu.io.hdf5files import (
        categorize_input_files, sort_rtm_files,
    )
    from sartsolver_tpu.parallel.mesh import make_mesh
    from sartsolver_tpu.parallel.multihost import read_and_quantize_rtm
    from sartsolver_tpu.parallel.sharded import DistributedSARTSolver

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices (virtual CPU mesh)")
    paths, H, f_true, times, scales_t = fx.write_world(str(tmp_path))
    rtm_files, _ = categorize_input_files(
        [paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"]])
    sorted_files = sort_rtm_files(rtm_files)
    mesh = make_mesh(1, 2, devices=jax.devices()[:2])
    P_, V_ = H.shape
    codes, scale = read_and_quantize_rtm(
        sorted_files, "with_reflections", P_, V_, mesh, chunk_rows=3)
    opts = SolverOptions(rtm_dtype="int8", fused_sweep="interpret",
                         max_iterations=30, conv_tolerance=0.0)
    pre = DistributedSARTSolver(codes, None, opts=opts, mesh=mesh,
                                npixel=P_, nvoxel=V_, rtm_scale=scale)
    dev = DistributedSARTSolver(H, None, opts=opts, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(pre.problem.rtm),
                                  np.asarray(dev.problem.rtm))
    np.testing.assert_allclose(np.asarray(pre.problem.rtm_scale),
                               np.asarray(dev.problem.rtm_scale), rtol=1e-6)
    g = H.astype(np.float64) @ f_true
    ra, rb = pre.solve(g), dev.solve(g)
    np.testing.assert_allclose(np.asarray(ra.solution),
                               np.asarray(rb.solution), rtol=1e-5, atol=1e-7)


def test_int8_chain_matches_per_frame():
    """solve_chain on int8 storage (interpret kernel) must reproduce the
    per-frame warm dispatch exactly — same statuses, iterations and
    solutions — including the carried fitted (which for int8 is the
    fused kernel's exact-dequant product, NOT the integer-projection
    approximation the recompute path would use; both chain and per-frame
    paths carry, so they stay identical)."""
    from sartsolver_tpu.parallel.mesh import make_mesh
    from sartsolver_tpu.parallel.sharded import DistributedSARTSolver

    H, g = _case()
    opts = SolverOptions(
        max_iterations=12, conv_tolerance=1e-10,
        rtm_dtype="int8", fused_sweep="interpret",
    )
    solver = DistributedSARTSolver(H, None, opts=opts, mesh=make_mesh(1, 1))
    frames = np.stack([g, g * 1.15, g * 0.85])

    refs = []
    warm = None
    for k in range(frames.shape[0]):
        warm = solver.solve_batch(frames[k][None], device_result=True,
                                  warm=warm)
        refs.append(warm)

    chained = solver.solve_chain(frames)
    for k, ref in enumerate(refs):
        assert int(chained.status[k]) == int(ref.status[0]), k
        assert int(chained.iterations[k]) == int(ref.iterations[0]), k
        np.testing.assert_array_equal(
            chained.fetch_solutions()[k], ref.fetch_solutions()[0],
            err_msg=f"frame {k}",
        )
