"""Sharded-vs-single-device equivalence on a virtual 8-device CPU mesh.

The reference's math guarantees mpirun -np 1 == -np N but never asserts it;
here it is asserted (SURVEY §4.3)."""

import numpy as np
import pytest

import jax

from sartsolver_tpu.config import SolverOptions
from sartsolver_tpu.models.sart import make_problem, solve
from sartsolver_tpu.ops.laplacian import make_laplacian
from sartsolver_tpu.parallel.mesh import make_mesh, row_block_partition
from sartsolver_tpu.parallel.sharded import DistributedSARTSolver

from test_sart_core import laplacian_1d_chain, make_case


def test_halo_laplacian_partition_matches_dense():
    """shard_laplacian_halo + sharded_penalty == dense L @ x, shard by
    shard, on a random sparse L with cross-block couplings; and the export
    table stays boundary-sized (the whole point vs a full gather)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from sartsolver_tpu.ops.laplacian import (
        make_laplacian, shard_laplacian_halo, sharded_penalty,
    )

    rng = np.random.default_rng(3)
    S, vb = 4, 32
    V = S * vb
    nnz = 300
    rows = rng.integers(0, V, nnz)
    # mostly-local couplings plus some genuine cross-block ones
    cols = np.clip(rows + rng.integers(-40, 41, nnz), 0, V - 1)
    vals = rng.standard_normal(nnz)
    lap = make_laplacian(rows, cols, vals, dtype="float64")
    slap = shard_laplacian_halo(lap, S, vb, np.float64)

    L = np.zeros((V, V))
    np.add.at(L, (rows, cols), vals)
    x = rng.standard_normal((2, V))
    want = x @ L.T  # [B, V]

    mesh = make_mesh(1, S)
    from sartsolver_tpu.parallel import shard_map

    got = jax.jit(shard_map(
        lambda sl, xb: sharded_penalty(
            type(slap)(*(a[0] for a in sl)), xb, "voxels"
        ),
        mesh=mesh,
        in_specs=(type(slap)(*(P("voxels", None),) * 7), P(None, "voxels")),
        out_specs=P(None, "voxels"),
        check_vma=False,
    ))(slap, x)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-12, atol=1e-12)
    # export table is the set of boundary cols, far smaller than a block
    assert 0 < slap.export_idx.shape[1] < vb


def test_halo_laplacian_block_diagonal_needs_no_exchange():
    """A block-diagonal L (all couplings within one shard's block) must
    partition with an empty halo — sharded_penalty then issues no
    collective at all."""
    from sartsolver_tpu.ops.laplacian import make_laplacian, shard_laplacian_halo

    S, vb = 4, 16
    rows = np.arange(S * vb)
    lap = make_laplacian(rows, rows, np.ones(S * vb), dtype="float32")
    slap = shard_laplacian_halo(lap, S, vb, np.float32)
    assert slap.rows_halo.shape[1] == 0
    assert slap.export_idx.shape[1] == 0


def test_row_block_partition_matches_reference_formula():
    """main.cpp:67-68: offset = r*(n/P) + min(r, n%P); count = n/P (+1)."""
    for npixel, nshards in [(100, 8), (17, 4), (8, 8), (7, 3)]:
        parts = row_block_partition(npixel, nshards)
        assert sum(c for _, c in parts) == npixel
        for rank, (off, cnt) in enumerate(parts):
            base, rem = divmod(npixel, nshards)
            assert off == rank * base + min(rank, rem)
            assert cnt == base + (1 if rank < rem else 0)
        # contiguous
        for (o1, c1), (o2, _) in zip(parts, parts[1:]):
            assert o1 + c1 == o2


@pytest.mark.parametrize("logarithmic", [False, True])
@pytest.mark.parametrize("with_laplacian", [False, True])
def test_sharded_equals_single_device(logarithmic, with_laplacian):
    H, g, _ = make_case(seed=11, P=52, V=40)  # 52 % 8 != 0 => padding path
    lap_np = laplacian_1d_chain(H.shape[1], 0.1) if with_laplacian else None
    opts = SolverOptions.cpu_parity(
        logarithmic=logarithmic, max_iterations=25, conv_tolerance=1e-12
    )
    lap = make_laplacian(*lap_np, dtype="float64") if lap_np else None

    res_single = solve(make_problem(H, lap, opts=opts), g, opts=opts)

    solver = DistributedSARTSolver(H, lap, opts=opts, mesh=make_mesh(8))
    res_shard = solver.solve(g)

    np.testing.assert_allclose(
        res_shard.solution, np.asarray(res_single.solution), rtol=1e-9, atol=1e-12
    )
    assert res_shard.status == int(res_single.status)
    assert res_shard.iterations == int(res_single.iterations)


def test_sharded_warm_start():
    H, g, _ = make_case(seed=12, P=48, V=32)
    opts = SolverOptions.cpu_parity(max_iterations=15, conv_tolerance=1e-12)
    f0 = np.full(H.shape[1], 0.5)
    res_single = solve(make_problem(H, opts=opts), g, f0=f0, opts=opts)
    solver = DistributedSARTSolver(H, opts=opts, mesh=make_mesh(8))
    res_shard = solver.solve(g, f0=f0)
    np.testing.assert_allclose(
        res_shard.solution, np.asarray(res_single.solution), rtol=1e-9
    )


def test_sharded_fp32_profile():
    """Device-default (fp32 + normalization) profile under sharding."""
    H, g, _ = make_case(seed=13, P=52, V=40)
    opts = SolverOptions(max_iterations=10, conv_tolerance=1e-12)
    res_single = solve(make_problem(H, opts=opts), g, opts=opts)
    solver = DistributedSARTSolver(H, opts=opts, mesh=make_mesh(8))
    res_shard = solver.solve(g)
    np.testing.assert_allclose(
        res_shard.solution, np.asarray(res_single.solution), rtol=2e-4, atol=1e-5
    )


def test_sharded_multiple_frames_warm_chain():
    """Frame loop with warm start (main.cpp:131-140) under sharding."""
    H, g, _ = make_case(seed=14, P=48, V=32)
    opts = SolverOptions.cpu_parity(max_iterations=10, conv_tolerance=1e-12)
    solver = DistributedSARTSolver(H, opts=opts, mesh=make_mesh(8))
    f = None
    for scale in (1.0, 1.1, 0.9):
        res = solver.solve(g * scale, f0=f)
        f = res.solution
        assert np.isfinite(f).all()


def _chain_device_vs_host(H, g, opts, scales, host_mesh, dev_mesh, *,
                          rtol, atol, iteration_parity):
    """Shared harness: warm-chain frames through the host round-trip path
    on ``host_mesh`` and through DeviceSolveResult chaining on
    ``dev_mesh``; assert statuses (and optionally iteration counts) match
    and solutions agree to tolerance. Returns the final device result."""
    from sartsolver_tpu.parallel.mesh import VOXEL_AXIS

    host_solver = DistributedSARTSolver(H, opts=opts, mesh=host_mesh)
    f = None
    refs = []
    for s in scales:
        res = host_solver.solve(g * s, f0=f)
        f = res.solution
        refs.append(res)

    dev_solver = DistributedSARTSolver(H, opts=opts, mesh=dev_mesh)
    warm = None
    for s, ref in zip(scales, refs):
        dres = dev_solver.solve_batch(
            (g * s)[None, :], device_result=True, warm=warm)
        assert int(dres.status[0]) == ref.status
        if iteration_parity:
            assert int(dres.iterations[0]) == ref.iterations
        # the chained carry must stay sharded over the device mesh's axes
        # (a regression gathering it to one device would still pass the
        # numeric checks)
        spec = dres.solution_norm.sharding.spec
        if dev_mesh.shape[VOXEL_AXIS] > 1:
            assert VOXEL_AXIS in jax.tree.leaves(tuple(spec))
        np.testing.assert_allclose(
            dres.fetch_solutions()[0], ref.solution, rtol=rtol, atol=atol)
        warm = dres
    return dev_solver, warm


def test_device_result_chain_matches_host_chain():
    """Device-resident warm chaining (DeviceSolveResult + warm=) must
    reproduce the host round-trip chain: same statuses/iterations, same
    solutions up to the one-fp32-ulp rescale difference in the initial
    guess. Also pins the packed scalar fetch and the lazy fetcher."""
    H, g, _ = make_case(seed=15, P=48, V=32)
    opts = SolverOptions(max_iterations=12, conv_tolerance=1e-12)
    # atol covers the guess-floor contract split: the host round-trip path
    # floors its seed at guess_floor (1e-7) while the carried device path
    # enters unfloored (models/sart fitted0 docs) — near-zero voxels then
    # differ by up to ~guess_floor-scale absolutely after a few iterations
    dev_solver, last = _chain_device_vs_host(
        H, g, opts, (1.0, 1.3, 0.8), make_mesh(8), make_mesh(8),
        rtol=2e-5, atol=1e-5, iteration_parity=True)
    # cached: second fetch returns the same host array
    assert last.fetch_solutions() is last.fetch_solutions()
    with pytest.raises(ValueError, match="not both"):
        dev_solver.solve_batch(g[None, :], f0=np.ones((1, H.shape[1])),
                               device_result=True, warm=last)


def test_device_result_chain_voxel_major_mesh():
    """Device chaining on a voxel-major (1, 8) mesh: the chained solution
    and the on-device rescale stay voxel-sharded across frames (asserted
    on the carry's sharding spec) and match the host-chained pixel-major
    reference."""
    H, g, _ = make_case(seed=16, P=48, V=256)
    opts = SolverOptions(max_iterations=10, conv_tolerance=1e-12)
    _chain_device_vs_host(
        H, g, opts, (1.0, 1.2), make_mesh(8, 1), make_mesh(1, 8),
        # psum reduction-order differences across mesh layouts perturb the
        # fp32 near-stall test: compare solutions loosely, not iterations
        rtol=2e-4, atol=1e-5, iteration_parity=False)


@pytest.mark.parametrize("mesh_shape", [(8, 1), (1, 8)])
@pytest.mark.parametrize("with_lap", [False, True])
@pytest.mark.parametrize("seed_mode", ["guess", "host_f0", "warm"])
def test_solve_chain_matches_per_frame_solves(mesh_shape, with_lap, seed_mode):
    """solve_chain (scan-over-frames, one device program) must reproduce
    the per-frame warm-start loop EXACTLY: same statuses, same iteration
    counts, same solutions — the chain is the same math dispatched once
    (VERDICT r2 next #1)."""
    H, g, _ = make_case(seed=17, P=48, V=64)
    lap = (make_laplacian(*laplacian_1d_chain(H.shape[1], 0.05),
                          dtype="float32") if with_lap else None)
    opts = SolverOptions(max_iterations=15, conv_tolerance=1e-10)
    solver = DistributedSARTSolver(H, lap, opts=opts, mesh=make_mesh(*mesh_shape))
    frames = np.stack([g, g * 1.2, g * 0.7, g * 1.05])

    f0_host = np.full(H.shape[1], 0.5) if seed_mode == "host_f0" else None
    warm0 = (solver.solve_chain(frames[:1] * 0.9)
             if seed_mode == "warm" else None)

    # reference: the per-frame device_result warm chain
    refs = []
    warm = warm0
    f0 = f0_host
    for k in range(frames.shape[0]):
        dres = solver.solve_batch(frames[k][None],
                                  None if f0 is None else f0[None],
                                  device_result=True, warm=warm)
        f0 = None
        warm = dres
        refs.append(dres)

    chained = solver.solve_chain(frames, f0=f0_host, warm=warm0)
    assert chained.status.shape == (4,)
    for k, ref in enumerate(refs):
        assert int(chained.status[k]) == int(ref.status[0]), k
        assert int(chained.iterations[k]) == int(ref.iterations[0]), k
        np.testing.assert_allclose(
            chained.fetch_solutions()[k], ref.fetch_solutions()[0],
            rtol=2e-6, atol=1e-8, err_msg=f"frame {k}",
        )

    # chain-to-chain warm handoff == one long chain
    two = solver.solve_chain(frames[2:], warm=solver.solve_chain(frames[:2],
                                                                 f0=f0_host,
                                                                 warm=warm0))
    for k in (2, 3):
        assert int(two.status[k - 2]) == int(chained.status[k])
        np.testing.assert_allclose(
            two.fetch_solutions()[k - 2], chained.fetch_solutions()[k],
            rtol=2e-6, atol=1e-8,
        )


def test_solve_chain_single_frame_and_errors():
    H, g, _ = make_case(seed=18, P=24, V=32)
    opts = SolverOptions(max_iterations=8, conv_tolerance=1e-10)
    solver = DistributedSARTSolver(H, opts=opts, mesh=make_mesh(8))
    one = solver.solve_chain(g[None])
    ref = solver.solve_batch(g[None], device_result=True)
    assert int(one.status[0]) == int(ref.status[0])
    assert int(one.iterations[0]) == int(ref.iterations[0])
    np.testing.assert_allclose(one.fetch_solutions()[0],
                               ref.fetch_solutions()[0], rtol=1e-7)
    with pytest.raises(ValueError, match="not both"):
        solver.solve_chain(g[None], f0=np.ones(H.shape[1]), warm=one)


@pytest.mark.parametrize("mesh_shape", [(4, 2), (2, 4), (1, 8)])
@pytest.mark.parametrize("logarithmic", [False, True])
def test_2d_mesh_equals_single_device(mesh_shape, logarithmic):
    """Column (voxel-axis) sharding: 2-D mesh result == single device.

    The voxel dimension deliberately doesn't divide the shard count in one
    case (40 voxels over 4x2 -> padding path on both axes)."""
    H, g, _ = make_case(seed=15, P=52, V=40)
    lap_np = laplacian_1d_chain(H.shape[1], 0.1)
    opts = SolverOptions.cpu_parity(
        logarithmic=logarithmic, max_iterations=20, conv_tolerance=1e-12
    )
    lap = make_laplacian(*lap_np, dtype="float64")

    res_single = solve(make_problem(H, lap, opts=opts), g, opts=opts)
    solver = DistributedSARTSolver(H, lap, opts=opts, mesh=make_mesh(*mesh_shape))
    res_shard = solver.solve(g)

    np.testing.assert_allclose(
        res_shard.solution, np.asarray(res_single.solution), rtol=1e-9, atol=1e-12
    )
    assert res_shard.status == int(res_single.status)
    assert res_shard.iterations == int(res_single.iterations)


def test_2d_mesh_warm_start_chain():
    H, g, _ = make_case(seed=16, P=48, V=32)
    opts = SolverOptions.cpu_parity(max_iterations=10, conv_tolerance=1e-12)
    solver_1d = DistributedSARTSolver(H, opts=opts, mesh=make_mesh(8, 1))
    solver_2d = DistributedSARTSolver(H, opts=opts, mesh=make_mesh(2, 4))
    f1 = f2 = None
    for scale in (1.0, 1.2):
        f1 = solver_1d.solve(g * scale, f0=f1).solution
        f2 = solver_2d.solve(g * scale, f0=f2).solution
        np.testing.assert_allclose(f2, f1, rtol=1e-9)


def test_choose_mesh_shape_heuristic():
    """VERDICT r1 #2: auto mesh goes voxel-major iff the fused sweep would
    engage on the per-device block; otherwise the reference's row-block
    layout."""
    from sartsolver_tpu.parallel.mesh import choose_mesh_shape

    # 'interpret'/'on' engage on any backend => voxel-major when aligned
    assert choose_mesh_shape(8, 800, 4096, SolverOptions(fused_sweep="interpret")) == (1, 8)
    assert choose_mesh_shape(8, 800, 4096, SolverOptions(fused_sweep="on")) == (1, 8)
    # fused off => pixel-major
    assert choose_mesh_shape(8, 800, 4096, SolverOptions(fused_sweep="off")) == (8, 1)
    # fp64 parity profile cannot fuse => pixel-major
    assert choose_mesh_shape(8, 800, 4096, SolverOptions.cpu_parity()) == (8, 1)
    # 'auto' on the CPU test backend never fuses => pixel-major
    assert choose_mesh_shape(8, 800, 4096, SolverOptions(fused_sweep="auto")) == (8, 1)
    # bf16 RTM storage composes with fusion
    assert choose_mesh_shape(
        8, 800, 4096, SolverOptions(fused_sweep="on", rtm_dtype="bfloat16")
    ) == (1, 8)
    # single device: trivial mesh
    assert choose_mesh_shape(1, 800, 4096, SolverOptions(fused_sweep="on")) == (1, 1)


@pytest.mark.parametrize("logarithmic", [False, True])
def test_voxel_major_fused_equals_unfused(logarithmic):
    """Fused sweep + voxel sharding at mesh>1 == unfused single device.

    The flagship multi-chip fusion configuration (VERDICT r1 #2): a (1, 8)
    voxel-major mesh where each shard runs the fused panel sweep over its
    column block and only the forward-projection psum crosses shards."""
    H, g, _ = make_case(seed=17, P=16, V=256, neg_pixels=2, zero_voxels=0,
                        zero_pixels=1)
    lap = make_laplacian(*laplacian_1d_chain(H.shape[1], 0.1), dtype="float32")
    opts_ref = SolverOptions(
        logarithmic=logarithmic, max_iterations=15, conv_tolerance=1e-12,
        fused_sweep="off",
    )
    opts_fused = SolverOptions(
        logarithmic=logarithmic, max_iterations=15, conv_tolerance=1e-12,
        fused_sweep="interpret",
    )
    res_ref = solve(make_problem(H, lap, opts=opts_ref), g, opts=opts_ref)
    solver = DistributedSARTSolver(H, lap, opts=opts_fused, mesh=make_mesh(1, 8))
    res = solver.solve(g)
    np.testing.assert_allclose(
        res.solution, np.asarray(res_ref.solution), rtol=2e-4, atol=1e-5
    )
    assert res.status == int(res_ref.status)
    assert res.iterations == int(res_ref.iterations)


@pytest.mark.parametrize("mesh_shape", [(8, 1), (2, 4)])
@pytest.mark.parametrize("profile", ["parity", "fp32"])
def test_local_measurement_staging_equals_global(mesh_shape, profile):
    """VERDICT r1 #5: per-process measurement staging (sharded g, global
    norm/||g||^2 from scalar reductions) == the replicated staging path."""
    H, g, _ = make_case(seed=18, P=52, V=40)
    if profile == "parity":
        opts = SolverOptions.cpu_parity(max_iterations=15, conv_tolerance=1e-12)
        rtol = 1e-9
    else:
        opts = SolverOptions(max_iterations=15, conv_tolerance=1e-12)
        rtol = 2e-4
    solver = DistributedSARTSolver(H, opts=opts, mesh=make_mesh(*mesh_shape))
    res_global = solver.solve(g)
    rng = solver.local_pixel_range()
    assert rng == (0, H.shape[0])  # single process owns every row block
    res_local = solver.solve(g, local=True)
    np.testing.assert_allclose(res_local.solution, res_global.solution,
                               rtol=rtol, atol=1e-12)
    assert res_local.status == res_global.status
    assert res_local.iterations == res_global.iterations


def test_process_pixel_range_partition():
    """Range arithmetic across simulated processes (device stubs carry the
    process_index a pod would assign)."""
    from sartsolver_tpu.parallel.multihost import process_pixel_range

    from fixtures import FakeMesh

    # this test process is jax.process_index() == 0: it sees the range of
    # the blocks labeled 0
    npixel = 52  # padded to 4 shards * ROW_ALIGN 8 -> 64, row_block 16
    assert process_pixel_range(FakeMesh([0, 0, 1, 1]), npixel) == (0, 32)
    assert process_pixel_range(FakeMesh([1, 0, 0, 1]), npixel) == (16, 32)
    # last block is partly padding: logical range clips at npixel
    assert process_pixel_range(FakeMesh([1, 1, 1, 0]), npixel) == (48, 4)
    # non-contiguous ownership -> None (caller falls back to full frames)
    assert process_pixel_range(FakeMesh([0, 1, 0, 1]), npixel) is None
    # no blocks owned -> empty range
    assert process_pixel_range(FakeMesh([1, 1, 1, 1]), npixel) == (0, 0)


def test_process_pixel_runs_partition():
    """Run-list arithmetic for non-contiguous device layouts (VERDICT r2
    #8): adjacent blocks merge, padding clips, gaps split runs."""
    from sartsolver_tpu.parallel.multihost import process_pixel_runs

    from fixtures import FakeMesh

    npixel = 52  # padded to 4 shards * ROW_ALIGN 8 -> 64, row_block 16
    assert process_pixel_runs(FakeMesh([0, 0, 1, 1]), npixel) == [(0, 32)]
    # interleaved ownership: two runs, nothing read in between
    assert process_pixel_runs(FakeMesh([0, 1, 0, 1]), npixel) == [
        (0, 16), (32, 16),
    ]
    # trailing block partly padding: clipped at npixel
    assert process_pixel_runs(FakeMesh([1, 0, 1, 0]), npixel) == [
        (16, 16), (48, 4),
    ]
    # padding-only ownership: no runs
    assert process_pixel_runs(FakeMesh([1, 1, 1, 0]), 8) == []


def test_all_processes_local_capable():
    """The relaxed slicing gate: non-contiguous layouts now stay local
    (multi-run); only a padding-only process forces replicated staging."""
    from sartsolver_tpu.parallel.multihost import all_processes_local_capable

    from fixtures import FakeMesh

    assert all_processes_local_capable(FakeMesh([0, 0, 1, 1]), 52)
    # non-contiguous ownership is fine now
    assert all_processes_local_capable(FakeMesh([0, 1, 0, 1]), 52)
    # process 1 owns only padding blocks (npixel=8 -> blocks 1..3 empty)
    assert not all_processes_local_capable(FakeMesh([0, 1, 1, 1]), 8)


def test_local_staging_multi_run_equals_full():
    """_stage_measurement_local over a split run list must stage the same
    sharded measurement as full-frame staging — the multi-run buffer
    lookup is what non-contiguous multihost layouts rely on."""
    H, g, _ = make_case(seed=21, P=48, V=32)
    opts = SolverOptions(max_iterations=6, conv_tolerance=1e-10)
    solver = DistributedSARTSolver(H, opts=opts, mesh=make_mesh(8))
    ref = solver.solve_batch(g[None], device_result=True)

    # simulate a non-contiguous layout: same coverage, split into three
    # unmerged runs (process_pixel_runs would merge these; the staging
    # code must not care)
    runs = [(0, 16), (16, 8), (24, 24)]
    solver.local_pixel_runs = lambda: runs
    got = solver.solve_batch(g[None], local=True, device_result=True)
    assert int(got.status[0]) == int(ref.status[0])
    assert int(got.iterations[0]) == int(ref.iterations[0])
    np.testing.assert_allclose(got.fetch_solutions()[0],
                               ref.fetch_solutions()[0], rtol=1e-7)


def test_close_releases_device_memory():
    """close() deletes the staged device arrays immediately, is
    idempotent, works as a context manager, and a closed solver refuses
    further solves with a clear error (VERDICT r3 next #5: a long-lived
    operator process must be able to load a second near-HBM-limit matrix
    into the same process)."""
    H, g, _ = make_case(seed=17, P=48, V=32)
    opts = SolverOptions.cpu_parity(max_iterations=10, conv_tolerance=1e-12)
    solver = DistributedSARTSolver(H, opts=opts, mesh=make_mesh(8))
    res = solver.solve(g)  # fetched to host before close
    arrays = [leaf for leaf in jax.tree_util.tree_leaves(solver.problem)
              if isinstance(leaf, jax.Array)]
    assert arrays
    solver.close()
    assert all(a.is_deleted() for a in arrays)
    solver.close()  # idempotent
    with pytest.raises(ValueError, match="closed"):
        solver.solve(g)
    assert np.isfinite(res.solution).all()  # host result survives

    # context-manager form, and a reload into the same process works
    with DistributedSARTSolver(H, opts=opts, mesh=make_mesh(8)) as s2:
        res2 = s2.solve(g)
    np.testing.assert_allclose(res2.solution, res.solution, rtol=1e-12)
    assert s2.problem is None


def test_dead_warm_buffers_rejected_closed_producer_ok():
    """A warm= seed with DELETED device buffers fails with an actionable
    ValueError, not an opaque dead-buffer XLA error deep inside dispatch
    (VERDICT r4 next #6). A merely CLOSED producer is not an error:
    close() releases the solver's staged problem arrays, not its results'
    buffers, so the still-alive result stays a legitimate foreign-warm
    seed."""
    H, g, _ = make_case(seed=18, P=48, V=32)
    opts = SolverOptions.cpu_parity(max_iterations=5, conv_tolerance=1e-12)
    producer = DistributedSARTSolver(H, opts=opts, mesh=make_mesh(8))
    warm = producer.solve_chain(g[None])
    host_seed = warm.fetch_solutions()
    consumer = DistributedSARTSolver(H, opts=opts, mesh=make_mesh(8))
    producer.close()
    # closed producer, alive buffers: works, and matches the host-f0 path
    res = consumer.solve_chain(g[None] * 1.1, warm=warm)
    ref = consumer.solve_chain(g[None] * 1.1, f0=host_seed[-1])
    np.testing.assert_allclose(
        res.fetch_solutions(), ref.fetch_solutions(), rtol=1e-9, atol=1e-12)
    # deleted device buffers: caught with a clear error on both paths
    warm2 = consumer.solve_chain(g[None])
    _ = warm2.fetch_solutions()  # materialize before deleting the source
    warm2.solution_norm.delete()
    with pytest.raises(ValueError, match="buffers have been deleted"):
        consumer.solve_chain(g[None], warm=warm2)
    with pytest.raises(ValueError, match="buffers have been deleted"):
        consumer.solve_batch(g[None], warm=warm2, device_result=True)
    consumer.close()


def test_foreign_warm_result_recomputes_fitted():
    """A warm result from a DIFFERENT solver (same shapes, different RTM)
    is a legitimate solution seed, but its carried fitted belongs to the
    other matrix — the receiving solver must recompute its setup sweep
    (guarded by `warm._solver is self`), matching the host-f0 path."""
    H_a, g, _ = make_case(seed=40, P=48, V=32)
    H_b = H_a * 1.7 + 0.05  # different matrix, same shape
    opts = SolverOptions(max_iterations=10, conv_tolerance=1e-12)
    solver_a = DistributedSARTSolver(H_a, opts=opts, mesh=make_mesh(8))
    solver_b = DistributedSARTSolver(H_b, opts=opts, mesh=make_mesh(8))

    res_a = solver_a.solve_batch(g[None], device_result=True)
    assert res_a.fitted_norm is not None
    cross = solver_b.solve_batch(g[None] * 1.1, device_result=True,
                                 warm=res_a)
    ref = solver_b.solve_batch(g[None] * 1.1,
                               f0=res_a.fetch_solutions(),
                               device_result=True)
    assert int(cross.status[0]) == int(ref.status[0])
    # both recompute fitted from their (floored) f0 — a foreign warm has
    # fitted0=None, so no floor is skipped; the only difference is the
    # fp64 host round trip vs the device rescale of the seed — solutions
    # agree to fp32 tolerance
    np.testing.assert_allclose(
        cross.fetch_solutions()[0], ref.fetch_solutions()[0],
        rtol=2e-4, atol=1e-5,
    )
