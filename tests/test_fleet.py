"""Fleet-scale serving matrix (docs/SERVING.md §10; `make fleet`).

Units: session-cache LRU/byte-budget eviction + forced-eviction hook +
counters, routing-table affinity/publish/read/torn-table behavior,
journal handoff markers through replay and compaction, fleet event-log
rotation, admission tenant-affinity (wrong-worker shed + handoff
bypass), `sartsolve submit` per-attempt routing re-resolution, and the
FleetController's failover / recovery / intake-routing state machines
driven directly against on-disk journals (no processes).

End-to-end: the fleet chaos campaign — M real workers under a real
controller, SIGKILL mid-commit-window (one seed also SIGKILLs the
controller mid-handoff and relaunches it), forced session evictions
under load — asserting exactly-once, byte-identical outputs and
counter continuity fleet-wide.
"""

import json
import os

import pytest

import fixtures as fx

from sartsolver_tpu.engine import request as req_mod
from sartsolver_tpu.engine import routing as routing_mod
from sartsolver_tpu.engine.admission import AdmissionController
from sartsolver_tpu.engine.cli import _submit_attempt, build_submit_parser
from sartsolver_tpu.engine.journal import RequestJournal
from sartsolver_tpu.engine.request import Request, parse_request
from sartsolver_tpu.engine.session import SessionCache, session_key
from sartsolver_tpu.obs import metrics as obs_metrics
from sartsolver_tpu.resilience.chaos import FleetSchedule, chaos_main
from sartsolver_tpu.resilience.supervisor import (
    DEFAULT_ROTATE_BYTES,
    FleetController,
    rotate_events,
)

# the bounded CI seed pair (make fleet): one plain worker-kill failover
# seed and one that also SIGKILLs the controller mid-handoff
FLEET_SEEDS = os.environ.get("SART_FLEET_SEEDS", "5,8")


def _req(rid, tenant="default", handoff=False):
    return Request(id=rid, tenant=tenant, time_range="",
                   deadline_s=None, submitted_unix=0.0, trace="",
                   handoff=handoff)


class _StubSession:
    """Minimal session: pinned byte size + close() tracking."""

    def __init__(self, key, nbytes=100):
        self.key = key
        self.nbytes = nbytes
        self.closed = False

    def close(self):
        self.closed = True


# ---------------------------------------------------------------------------
# session cache
# ---------------------------------------------------------------------------

def test_session_cache_lru_budget_eviction():
    obs_metrics.reset_registry()
    built = []
    cache = SessionCache(
        lambda key: built.append(key) or _StubSession(key),
        byte_budget=250,
    )
    a, b = cache.get("a"), cache.get("b")
    assert cache.resident_bytes() == 200
    cache.get("a")  # touch: "b" is now least-recently-attached
    c = cache.get("c")  # 300 bytes > 250 budget: evict LRU ("b")
    assert cache.keys() == ["a", "c"]
    assert b.closed and not a.closed and not c.closed
    assert built == ["a", "b", "c"]
    reg = obs_metrics.get_registry().snapshot()
    counters = {r["name"]: r["value"] for r in reg
                if r["kind"] == "counter"}
    assert counters["session_cache_hits_total"] == 1
    assert counters["session_cache_misses_total"] == 3
    assert counters["session_cache_evictions_total"] == 1
    gauges = {r["name"]: r["value"] for r in reg if r["kind"] == "gauge"}
    assert gauges["session_resident_bytes"] == 200.0


def test_session_cache_oversized_entry_stays_resident():
    """A single session larger than the budget must not thrash: it
    stays resident alone instead of being evicted on every attach."""
    obs_metrics.reset_registry()
    cache = SessionCache(lambda key: _StubSession(key, nbytes=1000),
                         byte_budget=250)
    cache.get("big")
    cache.get("big")
    assert cache.keys() == ["big"]


def test_session_cache_seed_prewarms_without_miss():
    obs_metrics.reset_registry()
    cache = SessionCache(lambda key: _StubSession(key), byte_budget=0)
    warm = _StubSession("default")
    cache.seed("default", warm)
    assert cache.lease(_req("r1")) is warm
    counters = {r["name"]: r["value"]
                for r in obs_metrics.get_registry().snapshot()
                if r["kind"] == "counter"}
    assert counters["session_cache_hits_total"] == 1
    assert "session_cache_misses_total" not in counters


def test_session_cache_forced_eviction_hook(monkeypatch):
    """SART_TEST_EVICT_EVERY=2: every 2nd lease pays a full rebuild of
    the target entry — the eviction-correctness drill's churn source."""
    monkeypatch.setenv("SART_TEST_EVICT_EVERY", "2")
    obs_metrics.reset_registry()
    builds = []
    cache = SessionCache(
        lambda key: builds.append(key) or _StubSession(key),
        byte_budget=0,
    )
    events = []
    cache._on_event = lambda kind, **data: events.append((kind, data))
    for i in range(4):
        cache.lease(_req(f"r{i}"))
    # leases 2 and 4 evicted first: 3 builds of the default key total
    assert builds == ["default"] * 3
    evicts = [d for k, d in events if k == "session-evict"]
    assert len(evicts) == 2
    assert all(d["reason"] == "test-forced" for d in evicts)


def test_session_cache_compile_reuse_counter():
    obs_metrics.reset_registry()
    cache = SessionCache(lambda key: _StubSession(key), byte_budget=0)
    cache.get("a")
    cache.evict("a")
    cache.get("a")  # rebuilt with a previously-seen key
    counters = {r["name"]: r["value"]
                for r in obs_metrics.get_registry().snapshot()
                if r["kind"] == "counter"}
    assert counters["session_cache_compile_reuse_total"] == 1


def test_session_key_pins_compiled_program_contract():
    assert session_key(14, 16, "float64", (2, 1)) == "14x16:float64:2x1"
    assert session_key(14, 16, "float64", None) == "14x16:float64:-"
    assert (session_key(14, 16, "float64", (2, 1))
            != session_key(14, 16, "float32", (2, 1)))


def test_session_cache_shutdown_closes_all():
    obs_metrics.reset_registry()
    cache = SessionCache(lambda key: _StubSession(key), byte_budget=0)
    sessions = [cache.get(k) for k in ("a", "b")]
    cache.close()
    assert len(cache) == 0
    assert all(s.closed for s in sessions)


# ---------------------------------------------------------------------------
# routing table
# ---------------------------------------------------------------------------

def test_tenant_worker_stable_and_in_range():
    # CRC32-based: stable across processes (a salted hash would scatter
    # tenants on every controller restart)
    assert routing_mod.tenant_worker("t0", 3) == \
        routing_mod.tenant_worker("t0", 3)
    assert routing_mod.tenant_worker("anything", 1) == 0
    seen = {routing_mod.tenant_worker(f"t{i}", 3) for i in range(64)}
    assert seen == {0, 1, 2}  # every shard reachable


def test_routing_publish_read_resolve(tmp_path):
    fleet = str(tmp_path)
    rows = [{"index": k, "ingest_dir": f"/w{k}/ingest",
             "http_port": 8600 + k, "state": "up"} for k in range(3)]
    routing_mod.publish_routing(fleet, rows,
                                responses_dir="/fleet/responses",
                                ingest_dir="/fleet/ingest")
    # readable via the dir OR the file path
    table = routing_mod.read_routing(fleet)
    assert table == routing_mod.read_routing(
        routing_mod.routing_path(fleet))
    assert table["size"] == 3
    assert table["responses_dir"] == "/fleet/responses"
    row = routing_mod.resolve_worker(table, "t5")
    assert row["index"] == routing_mod.tenant_worker("t5", 3)
    assert row["ingest_dir"] == f"/w{row['index']}/ingest"


def test_routing_torn_or_alien_table_reads_none(tmp_path):
    assert routing_mod.read_routing(str(tmp_path)) is None  # absent
    path = routing_mod.routing_path(str(tmp_path))
    with open(path, "w") as f:
        f.write('{"version": 1, "workers": [')  # torn mid-write
    assert routing_mod.read_routing(str(tmp_path)) is None
    with open(path, "w") as f:
        json.dump({"version": 99, "workers": []}, f)  # future schema
    assert routing_mod.read_routing(str(tmp_path)) is None


# ---------------------------------------------------------------------------
# journal handoff story
# ---------------------------------------------------------------------------

def test_journal_handoff_excludes_from_pending(tmp_path):
    j = RequestJournal(str(tmp_path / "journal.jsonl"))
    j.accepted(_req("a", tenant="t1"))
    j.accepted(_req("b", tenant="t2"))
    j.handoff("a", 2, trace_id="tr")
    completed, pending, handed = j.replay_full()
    assert not completed
    assert [r.id for r in pending] == ["b"]
    assert handed["a"]["target"] == 2
    assert handed["a"]["request"].tenant == "t1"
    # plain replay() agrees (the single-worker view)
    _, pending2 = j.replay()
    assert [r.id for r in pending2] == ["b"]


def test_journal_handoff_completed_wins(tmp_path):
    """A completed marker anywhere beats the handoff story — the id is
    done, nothing re-drives it."""
    j = RequestJournal(str(tmp_path / "journal.jsonl"))
    j.accepted(_req("a"))
    j.handoff("a", 1)
    j.completed(_req("a"), {"state": "done"})
    completed, pending, handed = j.replay_full()
    assert "a" in completed and not pending and not handed


def test_journal_compaction_preserves_handoff_story(tmp_path):
    """Dropping the handoff marker at compaction would resurrect the id
    as pending on the dead worker's next replay — re-driving a request
    the fleet already owns elsewhere."""
    j = RequestJournal(str(tmp_path / "journal.jsonl"))
    j.accepted(_req("gone", tenant="t9"))
    j.handoff("gone", 1)
    j.accepted(_req("keep"))
    j.accepted(_req("done"))
    j.completed(_req("done"), {"state": "done"})
    assert j.compact() > 0
    completed, pending, handed = j.replay_full()
    assert not completed  # completed records dropped (watermark owns them)
    assert [r.id for r in pending] == ["keep"]
    assert handed["gone"]["target"] == 1
    assert handed["gone"]["request"].tenant == "t9"


# ---------------------------------------------------------------------------
# event-log rotation
# ---------------------------------------------------------------------------

def test_rotate_events_keeps_newest_tail(tmp_path):
    path = str(tmp_path / "fleet.jsonl")
    lines = [json.dumps({"kind": "tick", "n": i}) + "\n"
             for i in range(500)]
    with open(path, "w") as f:
        f.writelines(lines)
    limit = 2048
    assert rotate_events(path, limit) > 0
    size = os.path.getsize(path)
    assert 0 < size <= limit
    kept = open(path).read().splitlines()
    # the newest records survive, whole lines only
    assert json.loads(kept[-1])["n"] == 499
    assert all(json.loads(ln)["n"] >= 400 for ln in kept)
    assert rotate_events(path, limit) == 0  # under limit: no-op
    assert rotate_events(path, 0) == 0  # rotation disabled
    assert DEFAULT_ROTATE_BYTES > 0


# ---------------------------------------------------------------------------
# admission tenant affinity
# ---------------------------------------------------------------------------

def test_admission_wrong_worker_shed_and_handoff_bypass():
    obs_metrics.reset_registry()
    tenant = "t-affinity"
    home = routing_mod.tenant_worker(tenant, 3)
    wrong = (home + 1) % 3
    adm = AdmissionController(affinity=(wrong, 3))
    assert adm.admit(_req("r1", tenant=tenant)) == \
        req_mod.REASON_WRONG_WORKER
    # the controller's failover re-drive bypasses affinity
    assert adm.admit(_req("r1", tenant=tenant, handoff=True)) is None
    # the home worker admits without any flag
    adm_home = AdmissionController(affinity=(home, 3))
    assert adm_home.admit(_req("r2", tenant=tenant)) is None


def test_admission_affinity_index_out_of_range():
    with pytest.raises(ValueError, match="out of range"):
        AdmissionController(affinity=(3, 3))


# ---------------------------------------------------------------------------
# submit routing re-resolution
# ---------------------------------------------------------------------------

def _attempt(fleet_dir, tenant):
    args = build_submit_parser().parse_args(
        ["--engine_dir", fleet_dir, "--id", "req-1",
         "--tenant", tenant, "--wait", "0"])
    payload = json.dumps({"id": "req-1", "tenant": tenant})
    return _submit_attempt(args, parse_request(payload), payload)


def test_submit_reresolves_routing_per_attempt(tmp_path):
    """Each submit attempt re-reads routing.json: after the tenant's
    worker goes down, the SAME submission falls back to the controller
    intake — the re-targeting `--retry` leans on."""
    obs_metrics.reset_registry()
    fleet = str(tmp_path)
    tenant = "t-routed"
    home = routing_mod.tenant_worker(tenant, 2)
    w_ingest = [str(tmp_path / f"w{k}-ingest") for k in range(2)]
    fallback = str(tmp_path / "fleet-ingest")
    for d in w_ingest + [fallback]:
        os.makedirs(d)
    rows = [{"index": k, "ingest_dir": w_ingest[k], "state": "up"}
            for k in range(2)]
    routing_mod.publish_routing(fleet, rows, ingest_dir=fallback)
    rec, code = _attempt(fleet, tenant)
    assert code == 0 and rec["state"] == "submitted"
    assert os.path.exists(os.path.join(w_ingest[home], "req-1.json"))
    # the affinity worker dies; the controller republishes
    rows[home]["state"] = "down"
    routing_mod.publish_routing(fleet, rows, ingest_dir=fallback)
    rec, code = _attempt(fleet, tenant)
    assert code == 0
    assert os.path.exists(os.path.join(fallback, "req-1.json"))


def test_submit_without_routing_uses_direct_dirs(tmp_path):
    """No routing.json: the classic single-worker addressing."""
    obs_metrics.reset_registry()
    os.makedirs(tmp_path / "ingest")
    rec, code = _attempt(str(tmp_path), "anyone")
    assert code == 0
    assert os.path.exists(tmp_path / "ingest" / "req-1.json")


# ---------------------------------------------------------------------------
# fleet controller (direct API: on-disk journals, no processes)
# ---------------------------------------------------------------------------

class _FakeProc:
    def __init__(self, pid=4242):
        self.pid = pid

    def poll(self):
        return None


def _controller(tmp_path, size=3):
    obs_metrics.reset_registry()
    return FleetController([], fleet_dir=str(tmp_path / "fleet"),
                           size=size)


def _mark_up(fc, k):
    fc.workers[k]["proc"] = _FakeProc(pid=5000 + k)
    fc.workers[k]["state"] = "up"


def test_fleet_failover_marker_first_then_restage(tmp_path, capsys):
    fc = _controller(tmp_path)
    _mark_up(fc, 1)
    j0 = fc._journal(0)
    j0.accepted(_req("a", tenant="t1"))
    j0.accepted(_req("done", tenant="t1"))
    j0.completed(_req("done"), {"state": "done"})
    # a partial output from the dead worker's interrupted attempt
    partial = os.path.join(fc.outputs_dir, "a.h5")
    open(partial, "wb").write(b"torn")
    fc._failover(0)
    # handoff marker landed in the DEAD worker's journal, target=1
    _, pending, handed = j0.replay_full()
    assert not pending and handed["a"]["target"] == 1
    # payload re-staged on the survivor with the affinity bypass set
    staged = os.path.join(fc.workers[1]["dir"], "ingest", "a.json")
    payload = json.load(open(staged))
    assert payload["handoff"] is True and payload["tenant"] == "t1"
    # the torn partial is gone (survivor writes it fresh)
    assert not os.path.exists(partial)
    # the completed request was NOT re-driven
    assert not os.path.exists(
        os.path.join(fc.workers[1]["dir"], "ingest", "done.json"))
    # routing now shows w0 down
    table = routing_mod.read_routing(fc.fleet_dir)
    assert [r["state"] for r in table["workers"]] == ["down", "up",
                                                      "down"]


def test_fleet_failover_no_survivor_skips(tmp_path, capsys):
    """Nobody alive to hand off to: the respawned worker replays its
    own journal — the handoff marker must NOT be written."""
    fc = _controller(tmp_path)
    j0 = fc._journal(0)
    j0.accepted(_req("a"))
    fc._failover(0)
    _, pending, handed = j0.replay_full()
    assert [r.id for r in pending] == ["a"] and not handed
    assert "handoff-skipped" in capsys.readouterr().err


def test_fleet_recover_restages_interrupted_handoff(tmp_path):
    """Controller crash between the handoff marker and the re-stage
    publish: a fresh incarnation's _recover() finishes the job — and a
    second pass is a no-op (needs_restage sees the staged copy)."""
    fc = _controller(tmp_path)
    j0 = fc._journal(0)
    j0.accepted(_req("a", tenant="t1"))
    j0.handoff("a", 2)  # marker durable, re-stage never happened
    fc2 = FleetController([], fleet_dir=fc.fleet_dir, size=3)
    fc2._recover()
    staged = os.path.join(fc2.workers[2]["dir"], "ingest", "a.json")
    assert json.load(open(staged))["handoff"] is True
    before = os.path.getmtime(staged)
    fc2._recover()  # idempotent: staged copy exists, no rewrite
    assert os.path.getmtime(staged) == before


def test_fleet_recover_skips_completed_anywhere(tmp_path):
    """The survivor already completed the handed-off request before the
    controller crashed: recovery must not resurrect it."""
    fc = _controller(tmp_path)
    fc._journal(0).accepted(_req("a"))
    fc._journal(0).handoff("a", 1)
    fc._journal(1).completed(_req("a", handoff=True), {"state": "done"})
    fc2 = FleetController([], fleet_dir=fc.fleet_dir, size=3)
    fc2._recover()
    assert not os.path.exists(
        os.path.join(fc2.workers[1]["dir"], "ingest", "a.json"))


def test_fleet_intake_routes_by_affinity(tmp_path):
    fc = _controller(tmp_path)
    for k in range(3):
        _mark_up(fc, k)
    tenant = "t-intake"
    home = routing_mod.tenant_worker(tenant, 3)
    with open(os.path.join(fc.ingest_dir, "r1.json"), "w") as f:
        json.dump({"id": "r1", "tenant": tenant}, f)
    with open(os.path.join(fc.ingest_dir, "torn.json"), "w") as f:
        f.write('{"id": "r2"')  # mid-write; picked up next pass
    assert fc._pump_intake() == 1
    routed = os.path.join(fc.workers[home]["dir"], "ingest", "r1.json")
    payload = json.load(open(routed))
    assert "handoff" not in payload  # affinity target: no bypass needed
    assert not os.path.exists(os.path.join(fc.ingest_dir, "r1.json"))
    assert os.path.exists(os.path.join(fc.ingest_dir, "torn.json"))


def test_fleet_intake_falls_back_to_survivor(tmp_path):
    fc = _controller(tmp_path)
    tenant = "t-intake"
    home = routing_mod.tenant_worker(tenant, 3)
    survivor = (home + 1) % 3
    _mark_up(fc, survivor)  # the affinity worker stays down
    with open(os.path.join(fc.ingest_dir, "r1.json"), "w") as f:
        json.dump({"id": "r1", "tenant": tenant}, f)
    assert fc._pump_intake() == 1
    routed = os.path.join(fc.workers[survivor]["dir"], "ingest",
                          "r1.json")
    assert json.load(open(routed))["handoff"] is True


def test_fleet_intake_holds_when_fleet_dark(tmp_path):
    """No worker alive: the request stays in the controller intake for
    the next loop instead of being dropped."""
    fc = _controller(tmp_path)
    with open(os.path.join(fc.ingest_dir, "r1.json"), "w") as f:
        json.dump({"id": "r1", "tenant": "t"}, f)
    assert fc._pump_intake() == 0
    assert os.path.exists(os.path.join(fc.ingest_dir, "r1.json"))


def test_fleet_pick_survivor_prefers_least_backlog(tmp_path):
    fc = _controller(tmp_path)
    for k in (1, 2):
        _mark_up(fc, k)
    for i in range(3):
        open(os.path.join(fc.workers[1]["dir"], "ingest",
                          f"q{i}.json"), "w").close()
    assert fc._pick_survivor(exclude=0) == 2
    assert fc._pick_survivor(exclude=2) == 1


# ---------------------------------------------------------------------------
# fleet chaos schedule + campaign
# ---------------------------------------------------------------------------

def test_fleet_schedule_deterministic():
    for seed in range(8):
        a, b = FleetSchedule(seed), FleetSchedule(seed)
        assert a.describe() == b.describe()
        assert a.evict_every == 2  # pinned: pigeonhole eviction guarantee
        assert a.window in FleetSchedule.WINDOWS
        assert a.occurrence in (1, 2)
    kills = {FleetSchedule(s).kill_controller_in_handoff
             for s in range(24)}
    assert kills == {True, False}  # both flavors reachable in CI range


def test_fleet_chaos_cli_rejects_bad_fleet_size(tmp_path):
    assert chaos_main(["--engine_dir", str(tmp_path), "--fleet", "1",
                       "--", "x.h5"]) == 1
    assert chaos_main(["--engine_dir", str(tmp_path), "--fleet", "-2",
                       "--", "x.h5"]) == 1


def test_fleet_chaos_campaign_ci_seed_set(tmp_path, capsys):
    """The ISSUE acceptance drill: M=3 real workers under a real
    controller, seeded SIGKILL inside a journal commit window (seed 8
    also kills the controller mid-handoff and relaunches it), forced
    session evictions throughout — exactly-once, byte-identical,
    counters continuous fleet-wide."""
    world = str(tmp_path / "world")
    os.makedirs(world)
    paths, *_ = fx.write_world(world, n_frames=4)
    report_path = str(tmp_path / "report.json")
    rc = chaos_main([
        "--engine_dir", str(tmp_path / "camp"), "--fleet", "3",
        "--seeds", FLEET_SEEDS, "--slo_ms", "300000",
        "--timeout", "280", "--report", report_path, "--",
        "--use_cpu", "-m", "40", "-c", "1e-12", "--lanes", "2",
        paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
        paths["img_a"], paths["img_b"],
    ])
    out = capsys.readouterr().out
    assert rc == 0, out
    report = json.load(open(report_path))
    assert report["verdict"] == "ok"
    assert report["fleet"] == 3
    assert len(report["passes"]) == len(FLEET_SEEDS.split(","))
    for verdict in report["passes"]:
        assert verdict["verdict"] == "ok"
        assert verdict["kills_fired"] >= 1  # every seed really killed
        assert verdict["evictions"] >= 1  # forced churn actually fired
        assert verdict["requests"] == 8  # 2*M + 2, exactly once each
        assert verdict["requests_total"] == {"completed": 8.0}
