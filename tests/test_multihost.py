"""Multi-host plumbing (parallel/multihost.py), exercised single-process.

A single-process run with 8 virtual CPU devices covers everything except
actual cross-process coordination: per-device striped RTM assembly,
pre-sharded solver construction, host staging, and result fetching all take
the same code paths they take on a pod (where the per-process device set is
a subset instead of everything).
"""

import numpy as np
import pytest

import fixtures as fx
from sartsolver_tpu.config import SolverOptions
from sartsolver_tpu.io import hdf5files as hf
from sartsolver_tpu.io.raytransfer import read_rtm_block
from sartsolver_tpu.parallel import multihost as mh
from sartsolver_tpu.parallel.mesh import make_mesh
from sartsolver_tpu.parallel.sharded import DistributedSARTSolver


@pytest.fixture
def world(tmp_path):
    return fx.write_world(tmp_path, with_laplacian=False)


def _sorted_matrix_files(paths):
    matrix_files, _ = hf.categorize_input_files(
        [paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
         paths["img_a"], paths["img_b"]]
    )
    return hf.sort_rtm_files(matrix_files)


@pytest.mark.parametrize("mesh_shape", [(4, 2), (8, 1), (2, 2)])
def test_read_and_shard_rtm_matches_full_read(world, mesh_shape):
    paths, H, *_ = world
    files = _sorted_matrix_files(paths)
    npixel, nvoxel = hf.get_total_rtm_size(files)

    import jax
    n_pix, n_vox = mesh_shape
    mesh = make_mesh(n_pix, n_vox, devices=jax.devices()[: n_pix * n_vox])
    global_rtm = mh.read_and_shard_rtm(
        files, "with_reflections", npixel, nvoxel, mesh, dtype="float32"
    )
    assembled = np.asarray(global_rtm)
    direct = read_rtm_block(files, "with_reflections", npixel, nvoxel, 0)
    np.testing.assert_array_equal(assembled[:npixel, :nvoxel], direct)
    # padding is zero (inert under the solver's masking)
    assert not assembled[npixel:, :].any()
    assert not assembled[:, nvoxel:].any()


def test_presharded_solver_matches_host_array_path(world):
    paths, H, f_true, times, scales = world
    files = _sorted_matrix_files(paths)
    npixel, nvoxel = hf.get_total_rtm_size(files)
    g = H @ (f_true * scales[0])

    import jax
    opts = SolverOptions(max_iterations=100, conv_tolerance=1e-7)
    mesh = make_mesh(4, 2, devices=jax.devices()[:8])

    host_solver = DistributedSARTSolver(
        read_rtm_block(files, "with_reflections", npixel, nvoxel, 0),
        opts=opts, mesh=mesh,
    )
    ref = host_solver.solve(g)

    global_rtm = mh.read_and_shard_rtm(
        files, "with_reflections", npixel, nvoxel, mesh, dtype="float32"
    )
    pre_solver = DistributedSARTSolver(
        global_rtm, opts=opts, mesh=mesh, npixel=npixel, nvoxel=nvoxel
    )
    res = pre_solver.solve(g)

    assert res.status == ref.status
    assert res.iterations == ref.iterations
    np.testing.assert_allclose(res.solution, ref.solution, rtol=1e-6, atol=1e-9)


def test_presharded_requires_logical_sizes(world):
    paths, *_ = world
    files = _sorted_matrix_files(paths)
    npixel, nvoxel = hf.get_total_rtm_size(files)
    import jax
    mesh = make_mesh(4, 2, devices=jax.devices()[:8])
    global_rtm = mh.read_and_shard_rtm(
        files, "with_reflections", npixel, nvoxel, mesh, dtype="float32"
    )
    with pytest.raises(ValueError, match="npixel/nvoxel"):
        DistributedSARTSolver(
            global_rtm, opts=SolverOptions(max_iterations=5), mesh=mesh
        )


def test_make_global_and_fetch_roundtrip():
    import jax
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(4, 2, devices=jax.devices()[:8])
    x = np.arange(16 * 256, dtype=np.float32).reshape(16, 256)
    g = mh.make_global(x, mesh, P("pixels", "voxels"))
    np.testing.assert_array_equal(mh.fetch(g), x)
    assert mh.is_primary()


def test_single_device_jax_array_rtm_accepted(world):
    """A plain (unsharded) JAX-resident RTM is host-stageable data, not a
    pre-sharded global array — the README's library-API pattern."""
    import jax.numpy as jnp

    paths, H, f_true, times, scales = world
    g = H @ (f_true * scales[0])
    opts = SolverOptions(max_iterations=50, conv_tolerance=1e-6)
    mesh = make_mesh(4, 2)
    ref = DistributedSARTSolver(H.astype(np.float32), opts=opts, mesh=mesh).solve(g)
    res = DistributedSARTSolver(jnp.asarray(H, jnp.float32), opts=opts, mesh=mesh).solve(g)
    assert res.iterations == ref.iterations
    np.testing.assert_allclose(res.solution, ref.solution, rtol=1e-6, atol=1e-9)


def test_presharded_on_1x1_mesh_honors_logical_sizes(world):
    """A 1x1 mesh yields an ordinary single-device padded array from
    read_and_shard_rtm; explicit npixel/nvoxel must still mark it as
    pre-sharded (regression: padded shape adopted as problem size)."""
    paths, H, f_true, times, scales = world
    files = _sorted_matrix_files(paths)
    npixel, nvoxel = hf.get_total_rtm_size(files)
    assert npixel % 8 != 0  # the regression needs a padded pixel count
    import jax
    mesh = make_mesh(1, 1, devices=jax.devices()[:1])
    global_rtm = mh.read_and_shard_rtm(
        files, "with_reflections", npixel, nvoxel, mesh, dtype="float32"
    )
    solver = DistributedSARTSolver(
        global_rtm, opts=SolverOptions(max_iterations=30, conv_tolerance=1e-6),
        mesh=mesh, npixel=npixel, nvoxel=nvoxel,
    )
    assert solver.npixel == npixel
    g = H @ (f_true * scales[0])
    res = solver.solve(g)
    assert np.isfinite(res.solution).all()


@pytest.mark.parametrize("chunk_rows", [1, 3, 100])
def test_chunked_ingest_matches_full_read(world, chunk_rows):
    """Bounded-chunk streaming assembles the same global RTM (VERDICT r1 #3)."""
    paths, H, *_ = world
    files = _sorted_matrix_files(paths)
    npixel, nvoxel = hf.get_total_rtm_size(files)
    import jax
    mesh = make_mesh(2, 4, devices=jax.devices()[:8])
    global_rtm = mh.read_and_shard_rtm(
        files, "with_reflections", npixel, nvoxel, mesh, dtype="float32",
        chunk_rows=chunk_rows,
    )
    direct = read_rtm_block(files, "with_reflections", npixel, nvoxel, 0)
    assembled = np.asarray(global_rtm)
    np.testing.assert_array_equal(assembled[:npixel, :nvoxel], direct)
    assert (assembled[npixel:] == 0).all()
    assert (assembled[:, nvoxel:] == 0).all()


def test_ingest_host_allocation_is_bounded(world, monkeypatch):
    """No read ever requests more rows than one chunk — the host never
    materializes a [npixel, nvoxel] array (reference parity:
    raytransfer.cpp:49 reads only the rank's block)."""
    paths, *_ = world
    files = _sorted_matrix_files(paths)
    npixel, nvoxel = hf.get_total_rtm_size(files)
    import jax
    from sartsolver_tpu.io import raytransfer as rt

    seen = []
    orig = rt.read_rtm_block

    def spy(files_, name, npixel_local, nvoxel_, offset, **kw):
        seen.append(npixel_local)
        return orig(files_, name, npixel_local, nvoxel_, offset, **kw)

    monkeypatch.setattr(mh, "read_rtm_block", spy)
    # voxel-major mesh: the row group spans ALL pixels — exactly the case
    # where unchunked reads would materialize the full matrix on host
    mesh = make_mesh(1, 8, devices=jax.devices()[:8])
    mh.read_and_shard_rtm(
        files, "with_reflections", npixel, nvoxel, mesh, dtype="float32",
        chunk_rows=4,
    )
    assert seen and max(seen) <= 4 < npixel


def test_read_and_shard_rtm_1d_mesh(world):
    """ADVICE r1: a 1-D ('pixels',) mesh must not crash the device walk."""
    paths, *_ = world
    files = _sorted_matrix_files(paths)
    npixel, nvoxel = hf.get_total_rtm_size(files)
    import jax
    from jax.sharding import Mesh

    mesh_1d = Mesh(np.array(jax.devices()[:4]), ("pixels",))
    global_rtm = mh.read_and_shard_rtm(
        files, "with_reflections", npixel, nvoxel, mesh_1d, dtype="float32"
    )
    direct = read_rtm_block(files, "with_reflections", npixel, nvoxel, 0)
    np.testing.assert_array_equal(
        np.asarray(global_rtm)[:npixel, :nvoxel], direct
    )


def test_broadcast_resume_state_single_process_passthrough():
    """Single-process: broadcast is the identity (the broadcast itself needs
    a real multi-process runtime; the CLI wiring is covered by test_cli's
    --multihost resume run)."""
    from sartsolver_tpu.io.solution import ResumeState

    state = ResumeState(np.array([1.0, 2.0]), np.ones(5))
    assert mh.broadcast_resume_state(state, 5) is state
    assert mh.broadcast_resume_state(None, 5) is None
