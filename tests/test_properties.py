"""Property-based tests (hypothesis) for host-side invariants.

The table-driven tests pin the reference's exact semantics on chosen
cases; these sweep randomized inputs for the invariants that must hold
for EVERY input — parser round-trips, quantization error bounds, mask
monotonicity — catching edge cases no table anticipates. Deterministic:
hypothesis derandomized with bounded examples so suite wall-time stays
flat.
"""

import math

import numpy as np
import pytest

# tier-1 must collect cleanly without the optional `test` extra installed;
# hypothesis-backed sweeps simply skip when it is absent
pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from sartsolver_tpu.config import SartInputError, parse_time_intervals

SET = settings(max_examples=120, deadline=None, derandomize=True)
# each example of the jit-backed properties compiles a fresh XLA program
# (distinct shapes / static thresholds) — keep their counts small so the
# suite wall-time stays flat
SET_JIT = settings(max_examples=25, deadline=None, derandomize=True)


def _fmt(x: float) -> str:
    return np.format_float_positional(x, trim="-")


@SET
@given(
    st.lists(
        st.tuples(
            st.floats(0.0, 1e6, allow_nan=False),  # start
            st.floats(1e-6, 1e6, allow_nan=False),  # stop - start
            st.floats(0.0, 1.0, allow_nan=False),  # step as frac of span
            st.floats(0.0, 1.0, allow_nan=False),  # threshold as frac of step
        ),
        min_size=1,
        max_size=4,
    ),
    st.booleans(),  # trailing comma
)
def test_parse_time_intervals_roundtrip(raw, trailing):
    """Any VALID interval list formats to a string that parses back to the
    same values (the parser accepts everything its grammar can express)."""
    intervals = []
    parts = []
    for start, span, step_f, thr_f in raw:
        stop = start + span
        # derive from the REPRESENTABLE span: fl(start+span)-start can be
        # below span, and step must satisfy step <= stop-start as floats
        span_repr = stop - start
        if span_repr <= 0:  # fully absorbed by rounding at huge start
            continue
        step = span_repr * step_f
        thr = step * thr_f
        intervals.append((start, stop, step, thr))
        parts.append(":".join(_fmt(v) for v in (start, stop, step, thr)))
    if not intervals:
        return
    s = ",".join(parts) + ("," if trailing else "")
    parsed = parse_time_intervals(s)
    assert len(parsed) == len(intervals)
    for got, want in zip(parsed, intervals):
        assert got == want  # exact: identical float64 literals round-trip


@SET
@given(st.floats(allow_nan=True), st.floats(allow_nan=True))
def test_parse_time_intervals_never_accepts_inverted(start, stop):
    """No numeric pair with stop <= start (or start < 0) ever parses —
    the validation cannot be dodged by weird float spellings."""
    if not (math.isfinite(start) and math.isfinite(stop)):
        return
    if stop > start >= 0:
        return
    with pytest.raises(SartInputError):
        parse_time_intervals(f"{_fmt(start)}:{_fmt(stop)}")


@SET
@given(st.text(alphabet="0123456789:,.- e", max_size=24))
def test_parse_time_intervals_total(s):
    """The parser either returns valid tuples or raises SartInputError —
    never any other exception, and every returned interval satisfies the
    documented invariants."""
    try:
        out = parse_time_intervals(s)
    except SartInputError:
        return
    assert out  # non-empty by contract
    for start, stop, step, thr in out:
        assert start >= 0 and stop > start
        assert step <= stop - start and thr <= step


@SET_JIT
@given(
    st.integers(2, 40),  # P
    st.integers(2, 60),  # V
    st.integers(0, 2**32 - 1),
)
def test_quantize_error_bound_any_matrix(P, V, seed):
    """Per-voxel symmetric int8 quantization: |Hq - H| <= colmax/254 for
    every column of every random non-negative matrix, zero columns get
    scale 1 and exact-zero codes (models/sart._quantize_sym contract)."""
    from sartsolver_tpu.models.sart import quantize_rtm

    rng = np.random.default_rng(seed)
    H = (rng.random((P, V), dtype=np.float32)
         * rng.choice([0.0, 1e-3, 1.0, 1e3], size=(1, V)).astype(np.float32))
    codes, scale = quantize_rtm(H)
    Hq = np.asarray(codes, np.float32) * np.asarray(scale)[None, :]
    colmax = np.abs(H).max(axis=0)
    err = np.abs(Hq - H).max(axis=0)
    # slack scales with the column magnitude: the fp32 scale division and
    # dequant multiply each contribute ~eps(colmax)-level rounding, which
    # an absolute 1e-12 cannot cover for colmax ~ 1e3 columns
    assert (err <= colmax / 254.0 + colmax * 1e-6 + 1e-12).all()
    zero = colmax == 0
    assert (np.asarray(scale)[zero] == 1.0).all()
    assert (Hq[:, zero] == 0.0).all()


@SET_JIT
@given(st.integers(0, 2**32 - 1), st.integers(1, 6))
def test_masking_monotone_in_threshold(seed, k):
    """Raising the ray-density threshold can only REMOVE voxels from the
    solve (masked voxels are exactly those the update zeroes) — Eq. 6
    monotonicity through the real solver."""
    import jax.numpy as jnp

    from sartsolver_tpu.config import SolverOptions
    from sartsolver_tpu.models.sart import make_problem, solve

    rng = np.random.default_rng(seed)
    P, V = 16, 24
    H = rng.random((P, V)).astype(np.float32)
    H[:, rng.choice(V, 4, replace=False)] *= 1e-4  # weakly-coupled voxels
    g = H.astype(np.float64) @ rng.uniform(0.5, 1.5, V)
    thresholds = sorted(np.quantile(H.sum(axis=0), [0.1 * k, 0.1 * k + 0.3]))
    supports = []
    for d in thresholds:
        opts = SolverOptions(max_iterations=3, conv_tolerance=1e-12,
                             ray_density_threshold=float(d))
        res = solve(make_problem(H, opts=opts), g, opts=opts)
        supports.append(np.asarray(res.solution) > 0)
    # support at the higher threshold is a subset of the lower one's
    assert not np.any(supports[1] & ~supports[0])


def _align(timelines, step, threshold):
    """Run the alignment core on bare timelines; returns the populated
    skeleton (no HDF5 involved)."""
    from sartsolver_tpu.io.image import CompositeImage

    ci = CompositeImage.__new__(CompositeImage)
    ci.frame_indices, ci.camera_time, ci.time = [], [], []
    timepairs = [[(float(t), i) for i, t in enumerate(tl)] for tl in timelines]
    ci._frame_indices_from_timepairs(timepairs, step, threshold)
    return ci


@SET
@given(
    st.integers(1, 3),  # cameras
    st.integers(0, 2**32 - 1),
    st.floats(0.0, 2.0),  # step factor (0 = auto-derive)
    st.floats(0.0, 1.0),  # threshold as fraction of step (0 = step)
)
def test_alignment_invariants(ncam, seed, step_f, thr_f):
    """Composite time alignment (image.cpp:110-196 port) on random
    asynchronous timelines: every emitted frame is complete and within
    the sync threshold, camera times are real timestamps of the chosen
    indices, each choice is the nearest frame of its camera to the tick,
    ticks strictly increase, and no consecutive duplicate tuples
    survive dedup."""
    from sartsolver_tpu.config import SartInputError
    from sartsolver_tpu.io.image import TIME_EPSILON

    rng = np.random.default_rng(seed)
    timelines = []
    for _ in range(ncam):
        n = int(rng.integers(1, 16))
        tl = np.sort(rng.uniform(0.0, 10.0, n))
        timelines.append(tl)
    base = max(np.diff(tl).min() if len(tl) > 1 else 1.0 for tl in timelines)
    step = float(base * step_f)  # 0.0 => auto-derive
    threshold = float(step * thr_f)  # 0.0 => use the step

    try:
        ci = _align(timelines, step, threshold)
    except SartInputError:
        return  # degenerate/empty outcomes are legal rejections

    eff_thr = threshold if threshold > 0 else (step if step > 0 else None)
    assert len(ci.time) == len(ci.frame_indices) == len(ci.camera_time)
    assert all(t1 > t0 for t0, t1 in zip(ci.time, ci.time[1:]))
    for k, (tick, idxs, ctimes) in enumerate(
        zip(ci.time, ci.frame_indices, ci.camera_time)
    ):
        assert len(idxs) == ncam
        for c in range(ncam):
            tl = timelines[c]
            assert 0 <= idxs[c] < len(tl)
            # the reported camera time IS the chosen frame's timestamp
            assert ctimes[c] == pytest.approx(tl[idxs[c]], abs=1e-8)
            delta = abs(tl[idxs[c]] - tick)
            if eff_thr is not None:
                # complete-frame rule: within the sync threshold
                assert delta <= eff_thr + 2 * TIME_EPSILON
            # nearest-frame rule (ties may go either way within epsilon)
            assert delta <= np.abs(tl - tick).min() + 2 * TIME_EPSILON
        if k > 0:
            assert idxs != ci.frame_indices[k - 1]  # dedup held


def _bare_grid(cls, nx, ny, nz, bounds, voxmap):
    g = cls.__new__(cls)
    g.nx, g.ny, g.nz = nx, ny, nz
    (g.xmin, g.xmax), (g.ymin, g.ymax), (g.zmin, g.zmax) = bounds
    g.dx = (g.xmax - g.xmin) / nx
    g.dy = (g.ymax - g.ymin) / ny
    g.dz = (g.zmax - g.zmin) / nz
    g.voxmap = voxmap
    g.nvox = int(voxmap.max()) + 1
    return g


@SET
@given(
    st.integers(1, 5), st.integers(1, 5), st.integers(1, 4),
    st.integers(0, 2**32 - 1),
)
def test_cartesian_lookup_cell_centers(nx, ny, nz, seed):
    """voxel_index at every cell CENTER returns that cell's map value;
    points outside the bounds return -1 (voxelgrid.cpp:236-250)."""
    from sartsolver_tpu.io.voxelgrid import CartesianVoxelGrid

    rng = np.random.default_rng(seed)
    lo = rng.uniform(-5, 5, 3)
    span = rng.uniform(0.5, 10, 3)
    bounds = [(float(lo[d]), float(lo[d] + span[d])) for d in range(3)]
    voxmap = np.full(nx * ny * nz, -1, np.int64)
    occupied = rng.random(voxmap.size) < 0.7
    voxmap[occupied] = np.arange(int(occupied.sum()))
    g = _bare_grid(CartesianVoxelGrid, nx, ny, nz, bounds, voxmap)

    for flat in range(voxmap.size):
        i, rem = divmod(flat, ny * nz)
        j, k = divmod(rem, nz)
        x = g.xmin + (i + 0.5) * g.dx
        y = g.ymin + (j + 0.5) * g.dy
        z = g.zmin + (k + 0.5) * g.dz
        assert g.voxel_index(x, y, z) == voxmap[flat]
    assert g.voxel_index(g.xmax + 1.0, g.ymin, g.zmin) == -1
    assert g.voxel_index(g.xmin - 1e-9 * max(1, abs(g.xmin)),
                         g.ymin, g.zmin) == -1


@SET
@given(
    st.integers(1, 4),  # radial cells
    # include counts whose cell width is NOT binary-exact (e.g. 360/19
    # rounds below the true quotient, so ny*dy < period and angles just
    # below the period can quotient to ny — the half-ulp spill the
    # lookup clamps)
    st.sampled_from([1, 2, 3, 4, 5, 7, 13, 19]),  # angular cells
    st.sampled_from([360.0, 180.0, 90.0, 60.0, 45.0]),  # sector period
    st.floats(0.0, 300.0),  # sector start (ymin)
    st.integers(-2, 2),  # extra whole periods on the probe angle
    st.integers(0, 2**32 - 1),
)
def test_cylindrical_lookup_cell_centers_periodic(nr, nphi, period, ymin,
                                                  wraps, seed):
    """Cylindrical voxel_index at every (r, phi, z) cell center — probed
    at phi + any whole number of periods — returns that cell's value:
    periodicity and sector grids with ymin > 0 (where the reference's
    wrap produced negative angular indices, C++ UB) both hold."""
    import math

    from sartsolver_tpu.io.voxelgrid import CylindricalVoxelGrid

    rng = np.random.default_rng(seed)
    r0 = rng.uniform(0.1, 2.0)
    bounds = [(r0, r0 + rng.uniform(0.5, 3.0)),
              (ymin, ymin + period), (-1.0, 1.0)]
    voxmap = np.arange(nr * nphi * 1, dtype=np.int64)
    g = _bare_grid(CylindricalVoxelGrid, nr, nphi, 1, bounds, voxmap)

    for flat in range(voxmap.size):
        i, j = divmod(flat, nphi)
        r = g.xmin + (i + 0.5) * g.dx
        phi = math.radians(g.ymin + (j + 0.5) * g.dy + wraps * period)
        x, y = r * math.cos(phi), r * math.sin(phi)
        assert g.voxel_index(x, y, 0.0) == voxmap[flat], (i, j)
    # boundary angles (cell edges +- ~1 ulp, incl. the sector origin from
    # below, where fmod(-eps)+period can round to exactly period) must
    # never index past the angular axis
    r_mid = g.xmin + 0.5 * g.dx
    for j in range(nphi + 1):
        for eps in (-1e-13, 0.0, 1e-13):
            ang = math.radians(g.ymin + j * g.dy + eps + wraps * period)
            out = g.voxel_index(r_mid * math.cos(ang),
                                r_mid * math.sin(ang), 0.0)
            assert 0 <= out < g.nvox
    # radius out of range -> -1
    assert g.voxel_index(g.xmax + 1.0, 0.0, 0.0) == -1


@SET
@given(st.integers(2, 5), st.integers(1, 4), st.integers(0, 2**32 - 1))
def test_voxelmap_stitching_any_split(n_cells_per_seg, n_segs, seed):
    """Stitching voxel-map segments with re-offsetting (voxelgrid.cpp:
    91-97): for ANY split of a grid's occupied cells into segment files
    (each segment's values locally 0-based), the stitched map equals the
    single-file map of the union with globally increasing values."""
    import h5py

    from sartsolver_tpu.io.voxelgrid import CartesianVoxelGrid

    rng = np.random.default_rng(seed)
    nx = ny = 4
    nz = 2
    total = n_cells_per_seg * n_segs
    if total > nx * ny * nz:
        return
    flats = rng.choice(nx * ny * nz, total, replace=False)
    import tempfile, os

    with tempfile.TemporaryDirectory() as td:
        names = []
        for s in range(n_segs):
            seg = np.sort(flats[s * n_cells_per_seg:(s + 1) * n_cells_per_seg])
            name = os.path.join(td, f"seg{s}.h5")
            names.append(name)
            with h5py.File(name, "w") as f:
                grp = f.create_group("rtm/voxel_map")
                for a, v in (("nx", nx), ("ny", ny), ("nz", nz)):
                    grp.attrs[a] = v
                i, rem = np.divmod(seg, ny * nz)
                j, k = np.divmod(rem, nz)
                grp.create_dataset("i", data=i)
                grp.create_dataset("j", data=j)
                grp.create_dataset("k", data=k)
                grp.create_dataset("value", data=np.arange(len(seg)))
        g = CartesianVoxelGrid()
        g.read_hdf5(names, "rtm/voxel_map")

    want = np.full(nx * ny * nz, -1, np.int64)
    v = 0
    for s in range(n_segs):
        seg = np.sort(flats[s * n_cells_per_seg:(s + 1) * n_cells_per_seg])
        for fl in seg:
            want[fl] = v
            v += 1
    np.testing.assert_array_equal(g.voxmap, want)
    assert g.nvox == total


@SET
@given(
    st.integers(1, 8),   # pixel shards
    st.integers(1, 3),   # voxel shards
    st.integers(1, 200),  # npixel (unaligned with blocks on purpose)
    st.integers(1, 4),   # process count
    st.integers(0, 2**32 - 1),
)
def test_pixel_run_partition_any_layout(n_pix, n_vox, npixel, n_proc, seed):
    """For ANY device->process assignment over ANY mesh shape, the
    per-process pixel runs tile [0, npixel) exactly as the device grid
    dictates: each logical row is covered once per distinct process
    holding its pixel block (the measurement is sharded over 'pixels'
    and replicated over 'voxels', so processes sharing a block via the
    voxel axis each stage those rows), runs are disjoint increasing and
    merged-contiguous per process, process_pixel_range agrees with the
    runs exactly when the process's blocks are contiguous, and
    all_processes_local_capable is True iff every process owns a logical
    row (multihost.py:370-443 — the arithmetic that places measurement
    rows across hosts, where a silent overlap/gap would mean wrong
    physics, not a crash)."""
    from unittest import mock

    from sartsolver_tpu.parallel import multihost as mh

    rng = np.random.default_rng(seed)
    owners = rng.integers(0, n_proc, size=n_pix * n_vox)
    owners[rng.integers(0, n_pix * n_vox)] = 0  # process 0 always exists
    import fixtures as fx

    grid = np.array([fx.FakeDev(int(p)) for p in owners],
                    dtype=object).reshape(n_pix, n_vox)
    mesh = fx.FakeMesh(grid)

    covered = np.zeros(npixel, np.int32)
    for proc in range(n_proc):
        with mock.patch.object(mh.jax, "process_index", return_value=proc):
            runs = mh.process_pixel_runs(mesh, npixel)
            rng_or_none = mh.process_pixel_range(mesh, npixel)
        last_end = -1
        for off, cnt in runs:
            assert cnt > 0 and off >= 0 and off + cnt <= npixel
            assert off > last_end  # disjoint, increasing, merged
            last_end = off + cnt
            covered[off:off + cnt] += 1
        total = sum(c for _, c in runs)
        if proc in owners:
            # range/runs consistency: a contiguous block set reports the
            # merged single range; a non-contiguous one reports None
            if rng_or_none is not None:
                o, c = rng_or_none
                assert c == total
                if runs:
                    assert (o, c) == (runs[0][0], total) and len(runs) == 1
        else:
            assert runs == [] and rng_or_none == (0, 0)
    # coverage: each row exactly once per distinct process holding its
    # pixel block (computed independently from the grid)
    from sartsolver_tpu.parallel.mesh import ROW_ALIGN, padded_size

    row_block = padded_size(npixel, n_pix * ROW_ALIGN) // n_pix
    expect_cov = np.zeros(npixel, np.int32)
    for r in range(npixel):
        expect_cov[r] = len({d.process_index for d in grid[r // row_block]})
    np.testing.assert_array_equal(covered, expect_cov)

    # all_processes_local_capable: True iff every process WITH DEVICES
    # owns at least one logical row
    per_proc_rows = {}
    for proc in {int(p) for p in owners}:
        with mock.patch.object(mh.jax, "process_index", return_value=proc):
            per_proc_rows[proc] = sum(
                c for _, c in mh.process_pixel_runs(mesh, npixel))
    expect = all(v > 0 for v in per_proc_rows.values())
    assert mh.all_processes_local_capable(mesh, npixel) == expect


@SET
@given(
    st.integers(1, 6),  # completed frames before the "crash"
    st.integers(1, 3),  # frames still to write after resume
    st.sets(st.sampled_from(
        ["value", "time", "status", "iterations", "time_camA", "time_camB"]
    )),  # datasets the mid-flush crash managed to extend with garbage
    st.integers(1, 3),  # torn rows
    st.integers(0, 2**32 - 1),
)
def test_resume_crash_consistency_any_torn_state(n_done, n_rest, torn,
                                                 extra, seed):
    """Crash consistency of the resume path for ANY torn file state: a
    mid-flush kill leaves an arbitrary subset of per-frame datasets
    extended with partial rows; resuming must (a) report exactly the
    frames every dataset completed, (b) truncate the torn tail, and (c)
    after appending the remaining frames, equal the uninterrupted run
    byte-for-byte."""
    import os
    import tempfile

    import h5py

    from sartsolver_tpu.io.solution import SolutionWriter, read_resume_state

    rng = np.random.default_rng(seed)
    V = 7
    cams = ["camA", "camB"]
    total = n_done + n_rest
    sols = rng.random((total, V))
    times = np.arange(total, dtype=np.float64) * 0.5

    def write(writer, lo, hi):
        for i in range(lo, hi):
            writer.add(sols[i], 0, times[i], [times[i], times[i] + 0.01],
                       iterations=i)

    with tempfile.TemporaryDirectory() as td:
        ref = os.path.join(td, "ref.h5")
        with SolutionWriter(ref, cams, V, max_cache_size=2) as w:
            write(w, 0, total)

        out = os.path.join(td, "out.h5")
        with SolutionWriter(out, cams, V, max_cache_size=2) as w:
            write(w, 0, n_done)
        # simulate the mid-flush kill: extend a subset with garbage rows
        with h5py.File(out, "r+") as f:
            for key in sorted(torn):
                d = f["solution"][key]
                if key == "value":
                    d.resize((n_done + extra, V))
                else:
                    d.resize((n_done + extra,))

        state = read_resume_state(out, cams, V)
        assert state is not None
        assert len(state.times) == n_done  # only fully-written frames count
        np.testing.assert_array_equal(state.times, times[:n_done])
        np.testing.assert_array_equal(state.last_solution, sols[n_done - 1])

        with SolutionWriter(out, cams, V, max_cache_size=2,
                            resume=state) as w:
            write(w, n_done, total)

        with h5py.File(ref, "r") as fr, h5py.File(out, "r") as fo:
            for key in fr["solution"]:
                np.testing.assert_array_equal(
                    fo["solution"][key][:], fr["solution"][key][:],
                    err_msg=key,
                )
