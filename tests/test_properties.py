"""Property-based tests (hypothesis) for host-side invariants.

The table-driven tests pin the reference's exact semantics on chosen
cases; these sweep randomized inputs for the invariants that must hold
for EVERY input — parser round-trips, quantization error bounds, mask
monotonicity — catching edge cases no table anticipates. Deterministic:
hypothesis derandomized with bounded examples so suite wall-time stays
flat.
"""

import math

import numpy as np
import pytest
from hypothesis import given, settings, strategies as st

from sartsolver_tpu.config import SartInputError, parse_time_intervals

SET = settings(max_examples=120, deadline=None, derandomize=True)
# each example of the jit-backed properties compiles a fresh XLA program
# (distinct shapes / static thresholds) — keep their counts small so the
# suite wall-time stays flat
SET_JIT = settings(max_examples=25, deadline=None, derandomize=True)


def _fmt(x: float) -> str:
    return np.format_float_positional(x, trim="-")


@SET
@given(
    st.lists(
        st.tuples(
            st.floats(0.0, 1e6, allow_nan=False),  # start
            st.floats(1e-6, 1e6, allow_nan=False),  # stop - start
            st.floats(0.0, 1.0, allow_nan=False),  # step as frac of span
            st.floats(0.0, 1.0, allow_nan=False),  # threshold as frac of step
        ),
        min_size=1,
        max_size=4,
    ),
    st.booleans(),  # trailing comma
)
def test_parse_time_intervals_roundtrip(raw, trailing):
    """Any VALID interval list formats to a string that parses back to the
    same values (the parser accepts everything its grammar can express)."""
    intervals = []
    parts = []
    for start, span, step_f, thr_f in raw:
        stop = start + span
        # derive from the REPRESENTABLE span: fl(start+span)-start can be
        # below span, and step must satisfy step <= stop-start as floats
        span_repr = stop - start
        if span_repr <= 0:  # fully absorbed by rounding at huge start
            continue
        step = span_repr * step_f
        thr = step * thr_f
        intervals.append((start, stop, step, thr))
        parts.append(":".join(_fmt(v) for v in (start, stop, step, thr)))
    if not intervals:
        return
    s = ",".join(parts) + ("," if trailing else "")
    parsed = parse_time_intervals(s)
    assert len(parsed) == len(intervals)
    for got, want in zip(parsed, intervals):
        assert got == want  # exact: identical float64 literals round-trip


@SET
@given(st.floats(allow_nan=True), st.floats(allow_nan=True))
def test_parse_time_intervals_never_accepts_inverted(start, stop):
    """No numeric pair with stop <= start (or start < 0) ever parses —
    the validation cannot be dodged by weird float spellings."""
    if not (math.isfinite(start) and math.isfinite(stop)):
        return
    if stop > start >= 0:
        return
    with pytest.raises(SartInputError):
        parse_time_intervals(f"{_fmt(start)}:{_fmt(stop)}")


@SET
@given(st.text(alphabet="0123456789:,.- e", max_size=24))
def test_parse_time_intervals_total(s):
    """The parser either returns valid tuples or raises SartInputError —
    never any other exception, and every returned interval satisfies the
    documented invariants."""
    try:
        out = parse_time_intervals(s)
    except SartInputError:
        return
    assert out  # non-empty by contract
    for start, stop, step, thr in out:
        assert start >= 0 and stop > start
        assert step <= stop - start and thr <= step


@SET_JIT
@given(
    st.integers(2, 40),  # P
    st.integers(2, 60),  # V
    st.integers(0, 2**32 - 1),
)
def test_quantize_error_bound_any_matrix(P, V, seed):
    """Per-voxel symmetric int8 quantization: |Hq - H| <= colmax/254 for
    every column of every random non-negative matrix, zero columns get
    scale 1 and exact-zero codes (models/sart._quantize_sym contract)."""
    from sartsolver_tpu.models.sart import quantize_rtm

    rng = np.random.default_rng(seed)
    H = (rng.random((P, V), dtype=np.float32)
         * rng.choice([0.0, 1e-3, 1.0, 1e3], size=(1, V)).astype(np.float32))
    codes, scale = quantize_rtm(H)
    Hq = np.asarray(codes, np.float32) * np.asarray(scale)[None, :]
    colmax = np.abs(H).max(axis=0)
    err = np.abs(Hq - H).max(axis=0)
    # slack scales with the column magnitude: the fp32 scale division and
    # dequant multiply each contribute ~eps(colmax)-level rounding, which
    # an absolute 1e-12 cannot cover for colmax ~ 1e3 columns
    assert (err <= colmax / 254.0 + colmax * 1e-6 + 1e-12).all()
    zero = colmax == 0
    assert (np.asarray(scale)[zero] == 1.0).all()
    assert (Hq[:, zero] == 0.0).all()


@SET_JIT
@given(st.integers(0, 2**32 - 1), st.integers(1, 6))
def test_masking_monotone_in_threshold(seed, k):
    """Raising the ray-density threshold can only REMOVE voxels from the
    solve (masked voxels are exactly those the update zeroes) — Eq. 6
    monotonicity through the real solver."""
    import jax.numpy as jnp

    from sartsolver_tpu.config import SolverOptions
    from sartsolver_tpu.models.sart import make_problem, solve

    rng = np.random.default_rng(seed)
    P, V = 16, 24
    H = rng.random((P, V)).astype(np.float32)
    H[:, rng.choice(V, 4, replace=False)] *= 1e-4  # weakly-coupled voxels
    g = H.astype(np.float64) @ rng.uniform(0.5, 1.5, V)
    thresholds = sorted(np.quantile(H.sum(axis=0), [0.1 * k, 0.1 * k + 0.3]))
    supports = []
    for d in thresholds:
        opts = SolverOptions(max_iterations=3, conv_tolerance=1e-12,
                             ray_density_threshold=float(d))
        res = solve(make_problem(H, opts=opts), g, opts=opts)
        supports.append(np.asarray(res.solution) > 0)
    # support at the higher threshold is a subset of the lower one's
    assert not np.any(supports[1] & ~supports[0])


def _align(timelines, step, threshold):
    """Run the alignment core on bare timelines; returns the populated
    skeleton (no HDF5 involved)."""
    from sartsolver_tpu.io.image import CompositeImage

    ci = CompositeImage.__new__(CompositeImage)
    ci.frame_indices, ci.camera_time, ci.time = [], [], []
    timepairs = [[(float(t), i) for i, t in enumerate(tl)] for tl in timelines]
    ci._frame_indices_from_timepairs(timepairs, step, threshold)
    return ci


@SET
@given(
    st.integers(1, 3),  # cameras
    st.integers(0, 2**32 - 1),
    st.floats(0.0, 2.0),  # step factor (0 = auto-derive)
    st.floats(0.0, 1.0),  # threshold as fraction of step (0 = step)
)
def test_alignment_invariants(ncam, seed, step_f, thr_f):
    """Composite time alignment (image.cpp:110-196 port) on random
    asynchronous timelines: every emitted frame is complete and within
    the sync threshold, camera times are real timestamps of the chosen
    indices, each choice is the nearest frame of its camera to the tick,
    ticks strictly increase, and no consecutive duplicate tuples
    survive dedup."""
    from sartsolver_tpu.config import SartInputError
    from sartsolver_tpu.io.image import TIME_EPSILON

    rng = np.random.default_rng(seed)
    timelines = []
    for _ in range(ncam):
        n = int(rng.integers(1, 16))
        tl = np.sort(rng.uniform(0.0, 10.0, n))
        timelines.append(tl)
    base = max(np.diff(tl).min() if len(tl) > 1 else 1.0 for tl in timelines)
    step = float(base * step_f)  # 0.0 => auto-derive
    threshold = float(step * thr_f)  # 0.0 => use the step

    try:
        ci = _align(timelines, step, threshold)
    except SartInputError:
        return  # degenerate/empty outcomes are legal rejections

    eff_thr = threshold if threshold > 0 else (step if step > 0 else None)
    assert len(ci.time) == len(ci.frame_indices) == len(ci.camera_time)
    assert all(t1 > t0 for t0, t1 in zip(ci.time, ci.time[1:]))
    for k, (tick, idxs, ctimes) in enumerate(
        zip(ci.time, ci.frame_indices, ci.camera_time)
    ):
        assert len(idxs) == ncam
        for c in range(ncam):
            tl = timelines[c]
            assert 0 <= idxs[c] < len(tl)
            # the reported camera time IS the chosen frame's timestamp
            assert ctimes[c] == pytest.approx(tl[idxs[c]], abs=1e-8)
            delta = abs(tl[idxs[c]] - tick)
            if eff_thr is not None:
                # complete-frame rule: within the sync threshold
                assert delta <= eff_thr + 2 * TIME_EPSILON
            # nearest-frame rule (ties may go either way within epsilon)
            assert delta <= np.abs(tl - tick).min() + 2 * TIME_EPSILON
        if k > 0:
            assert idxs != ci.frame_indices[k - 1]  # dedup held
