"""Concurrency drills: the lock-order detector and the races it guards.

Three layers (docs/STATIC_ANALYSIS.md SL1xx, docs/RESILIENCE.md runbook):

- ``utils/locking.py`` unit drills — disabled-path identity (a raw
  ``threading.Lock``, zero bookkeeping), the armed detector's
  acquisition-order graph, the deadlock-injection drill that must trip
  :class:`LockOrderViolation` (with both threads' stacks and a flight-
  ring event), and hold-time histograms in obs.
- race drills over the real shared stores, run under the armed detector
  (``SART_LOCK_DEBUG=1``): metrics-registry and flight-ring hammers, the
  prefetcher's close-vs-blocked-worker-put race, and the async writer's
  error-latch vs a concurrent flush.
- the signal-under-lock drill pinning the SIGUSR1 fix: a status poke
  landing while the main thread holds a metric/ring lock (mid-
  ``record_frame``) must complete via the non-blocking stale-snapshot
  path — with the old blocking snapshot this drill deadlocks.

Plus the lint wall-time budget: the SL1xx call-graph pass must keep the
package AST lint under 10 s.
"""

import json
import os
import signal
import threading
import time

import numpy as np
import pytest

from sartsolver_tpu.obs import flight as obs_flight
from sartsolver_tpu.obs import metrics as obs_metrics
from sartsolver_tpu.utils import locking
from sartsolver_tpu.utils.locking import LockOrderViolation, named_lock


@pytest.fixture
def lock_debug(monkeypatch):
    """Arm the detector and hand back a fresh registry whose instrument
    locks are instrumented (the mode latches at lock creation, so the
    registry must be built after the env is set). Restores a raw-lock
    registry afterwards so later tests keep the production shape."""
    monkeypatch.setenv("SART_LOCK_DEBUG", "1")
    locking.reset_order_state()
    registry = obs_metrics.reset_registry()
    yield registry
    monkeypatch.delenv("SART_LOCK_DEBUG")
    locking.reset_order_state()
    obs_metrics.reset_registry()


# ---------------------------------------------------------------------------
# named_lock: disabled path
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("value", ["1", "true", "on"])
def test_debug_switch_shares_the_boolean_env_vocabulary(monkeypatch, value):
    """SART_LOCK_DEBUG accepts exactly the shared boolean-switch
    spellings (utils.env_truthy — same list as SART_INTEGRITY); a
    divergent vocabulary would leave an operator's value armed on one
    switch and silently ignored on another."""
    from sartsolver_tpu.resilience import integrity

    monkeypatch.setenv("SART_LOCK_DEBUG", value)
    monkeypatch.setenv("SART_INTEGRITY", value)
    assert locking.debug_enabled()
    assert integrity.env_enabled()
    monkeypatch.setenv("SART_LOCK_DEBUG", "yes")  # not in the vocabulary
    assert not locking.debug_enabled()


def test_disabled_path_returns_raw_lock(monkeypatch):
    """Zero-overhead contract: with SART_LOCK_DEBUG unset the factory
    hands back a plain threading.Lock — no wrapper object, and using it
    grows no order-graph state."""
    monkeypatch.delenv("SART_LOCK_DEBUG", raising=False)
    locking.reset_order_state()
    lock = named_lock("drill.raw")
    assert type(lock) is type(threading.Lock())
    with lock:
        pass
    assert locking.order_graph() == {}
    assert not locking.debug_enabled()


def test_production_lock_sites_are_raw_by_default():
    """The migrated sites (metrics registry/instruments, flight ring)
    latch the production personality when the env is unset at
    construction — the tier-1 environment never pays detector cost."""
    assert not locking.debug_enabled()
    raw = type(threading.Lock())
    registry = obs_metrics.MetricsRegistry()
    assert type(registry._lock) is raw
    assert type(registry.counter("drill_raw_total")._lock) is raw
    assert type(obs_flight.FlightRecorder(max_events=8)._lock) is raw


# ---------------------------------------------------------------------------
# named_lock: armed detector
# ---------------------------------------------------------------------------


def test_instrumented_lock_basics(lock_debug):
    lock = named_lock("drill.basic")
    assert isinstance(lock, locking._InstrumentedLock)
    assert not lock.locked()
    with lock:
        assert lock.locked()
    assert not lock.locked()
    assert lock.acquire(blocking=False)
    assert not lock.acquire(blocking=False)  # held -> False, no raise
    lock.release()


def test_hold_time_histogram_lands_in_obs(lock_debug):
    lock = named_lock("drill.hold")
    with lock:
        time.sleep(0.01)
    snaps = [s for s in lock_debug.snapshot()
             if s["name"] == "lock_hold_seconds"
             and s["labels"].get("lock") == "drill.hold"]
    assert len(snaps) == 1
    assert snaps[0]["count"] == 1
    assert snaps[0]["sum"] >= 0.01


def test_order_graph_records_nesting(lock_debug):
    a, b = named_lock("drill.outer"), named_lock("drill.inner")
    with a:
        with b:
            pass
    assert "drill.inner" in locking.order_graph().get("drill.outer", set())


def test_deadlock_injection_drill_trips_detector(lock_debug):
    """The acceptance drill: thread 1 establishes A->B, the main thread
    then acquires B->A — a cycle that would deadlock under the losing
    interleaving. The detector must trip at acquire time (before
    blocking), name the cycle, carry both threads' stacks, and drop a
    lock_order_violation event into the flight ring."""
    ring = obs_flight.install(obs_flight.FlightRecorder(max_events=64))
    try:
        a, b = named_lock("drill.A"), named_lock("drill.B")

        def establish():
            with a:
                with b:
                    pass

        t = threading.Thread(target=establish, name="drill-establisher",
                             daemon=True)
        t.start()
        t.join(timeout=5)
        assert not t.is_alive()

        with b:
            with pytest.raises(LockOrderViolation) as exc:
                a.acquire()
        msg = str(exc.value)
        assert "drill.A" in msg and "drill.B" in msg
        assert "this thread's acquire stack" in msg
        assert "drill-establisher" in msg  # the other side's stack rode along
        events = [e for e in ring.snapshot()
                  if e["kind"] == "lock_order_violation"]
        assert events and "drill.A" in events[0]["message"]
        assert events[0]["cycle"][0] == events[0]["cycle"][-1]
    finally:
        obs_flight.uninstall()


def test_same_name_reacquire_is_a_violation(lock_debug):
    """Re-acquiring a held lock name is a self-deadlock for the same
    instance (threading.Lock is not reentrant) and an order hazard for
    two instances of one class — both trip."""
    lock = named_lock("drill.self")
    with lock:
        with pytest.raises(LockOrderViolation):
            lock.acquire()
    # released cleanly by the with-exit; usable again
    with lock:
        pass


def test_cross_thread_release_leaves_no_phantom_hold(lock_debug):
    """threading.Lock allows release from another thread (ownership
    handoff); the acquirer's thread-local hold entry can't be popped
    from there, so it is invalidated by generation instead — no false
    self-cycle on re-acquire, no phantom order edges afterwards."""
    lock = named_lock("drill.handoff")
    other = named_lock("drill.handoff.other")
    assert lock.acquire()
    t = threading.Thread(target=lock.release, daemon=True)
    t.start()
    t.join(timeout=5)
    assert not lock.locked()
    with lock:  # would be a false self-cycle with a phantom entry
        pass
    with other:  # would record a phantom handoff->other edge
        pass
    assert "drill.handoff.other" not in \
        locking.order_graph().get("drill.handoff", set())


def test_nonblocking_acquire_skips_order_check(lock_debug):
    """acquire(blocking=False) cannot deadlock, so the signal-context
    snapshot pattern must not trip the detector even against the
    recorded order."""
    a, b = named_lock("drill.nb.A"), named_lock("drill.nb.B")
    with a:
        with b:
            pass
    with b:
        assert a.acquire(blocking=False)  # A->B recorded; no violation
        a.release()


# ---------------------------------------------------------------------------
# race drills over the real shared stores (armed detector)
# ---------------------------------------------------------------------------


def _hammer(n_threads, worker):
    errors = []

    def run(k):
        try:
            worker(k)
        except BaseException as err:  # noqa: BLE001 - drills collect all
            errors.append(err)

    threads = [threading.Thread(target=run, args=(k,), daemon=True)
               for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in threads)
    assert not errors, errors


def test_metrics_registry_hammer(lock_debug):
    """8 writers inc/observe/set against concurrent blocking and
    non-blocking snapshots: no violation, no lost counts."""
    registry = lock_debug
    per_thread = 200

    def worker(k):
        c = registry.counter("hammer_total", thread=str(k))
        h = registry.histogram("hammer_seconds")
        g = registry.gauge("hammer_depth")
        for i in range(per_thread):
            c.inc()
            h.observe(0.001 * i)
            g.set_max(i)
            if i % 50 == 0:
                registry.snapshot()
                registry.snapshot(blocking=False)

    _hammer(8, worker)
    snaps = registry.snapshot()
    total = sum(s["value"] for s in snaps if s["name"] == "hammer_total")
    assert total == 8 * per_thread
    hist = next(s for s in snaps if s["name"] == "hammer_seconds")
    assert hist["count"] == 8 * per_thread


def test_flight_ring_hammer(lock_debug):
    """8 recorders against concurrent snapshots on a bounded ring: the
    total survives, every snapshot is a valid list."""
    ring = obs_flight.FlightRecorder(max_events=128)
    per_thread = 300

    def worker(k):
        for i in range(per_thread):
            ring.record("drill", thread=k, i=i)
            if i % 60 == 0:
                assert isinstance(ring.snapshot(), list)
                assert isinstance(ring.snapshot(blocking=False), list)

    _hammer(8, worker)
    assert ring.total == 8 * per_thread
    tail = ring.snapshot()
    assert len(tail) == 128  # bounded: ring keeps the newest


class _FakeComposite:
    """Minimal composite for prefetcher drills: no HDF5, tunable read
    latency so the worker can be caught blocked on a full queue."""

    def __init__(self, n=64, delay=0.0):
        self._n = n
        self._delay = delay

    def __len__(self):
        return self._n

    def frame(self, i):
        if self._delay:
            time.sleep(self._delay)
        return np.full(16, float(i), np.float64)

    def frame_time(self, i):
        return float(i)

    def camera_frame_time(self, i):
        return [float(i)]


def test_prefetcher_close_vs_blocked_put(lock_debug):
    """The known-delicate worker race, under the armed detector: close()
    while the worker is blocked putting into the full depth-1 queue must
    release the thread (no deadlock, no violation)."""
    from sartsolver_tpu.utils.prefetch import FramePrefetcher

    pf = FramePrefetcher(_FakeComposite(n=64), depth=1)
    deadline = time.monotonic() + 10
    while pf._queue.qsize() < 1 and time.monotonic() < deadline:
        time.sleep(0.005)  # worker fills the queue, then blocks in put
    assert pf._queue.qsize() >= 1
    pf.close()
    assert not pf._thread.is_alive()


def test_prefetcher_consume_all_under_detector(lock_debug):
    """Full stream drain with the armed detector: the worker's metric
    updates and beacons run instrumented end-to-end."""
    from sartsolver_tpu.utils.prefetch import FramePrefetcher

    with FramePrefetcher(_FakeComposite(n=16), depth=2) as frames:
        got = list(frames)
    assert len(got) == 16
    assert [item[1] for item in got] == [float(i) for i in range(16)]


class _LatchTestWriter:
    """Wrapped writer whose second add fails after a real delay — wide
    window for a concurrent close() to overlap the failing write."""

    def __init__(self):
        self.added = 0
        self.closed = False

    def add(self, solution, *rest):
        self.added += 1
        if self.added == 2:
            time.sleep(0.05)
            raise OSError("injected: output filesystem gone")

    def close(self):
        self.closed = True


def test_asyncwriter_error_latch_vs_concurrent_flush(lock_debug):
    """The second known-delicate race: the worker latches a write error
    while the producer is mid-flush (close). The latch must surface as
    the chained DeferredWriteError from close(), the worker must be
    joined, and the wrapped writer closed — no deadlock, no violation."""
    from sartsolver_tpu.utils.asyncwriter import (
        AsyncSolutionWriter,
        DeferredWriteError,
    )

    inner = _LatchTestWriter()
    w = AsyncSolutionWriter(inner, max_pending=8)
    sol = np.zeros(8, np.float64)
    for i in range(4):
        w.add(sol, 0, float(i), [float(i)])
    with pytest.raises(DeferredWriteError) as exc:
        w.close()
    assert isinstance(exc.value.__cause__, OSError)
    assert not w._thread.is_alive()
    assert inner.closed
    assert inner.added == 2  # the latch wrote nothing after the failure


# ---------------------------------------------------------------------------
# the signal-under-lock drill (SIGUSR1 fix pin)
# ---------------------------------------------------------------------------


needs_sigusr1 = pytest.mark.skipif(
    not hasattr(signal, "SIGUSR1"), reason="platform has no SIGUSR1"
)


@needs_sigusr1
def test_sigusr1_under_instrument_lock_completes(tmp_path):
    """A SIGUSR1 landing while the main thread holds a metric lock —
    exactly a signal mid-record_frame — must produce a status file via
    the non-blocking stale-snapshot path. With the old blocking
    snapshot this drill self-deadlocks (the handler waits on a lock
    whose owner resumes only after the handler returns)."""
    registry = obs_metrics.reset_registry()
    counter = registry.counter("drill_signal_total")
    counter.inc(7)
    path = str(tmp_path / "status.json")
    prev = obs_flight.install_status_handler(path)
    try:
        counter._lock.acquire()  # the interrupted bytecode's lock
        try:
            os.kill(os.getpid(), signal.SIGUSR1)
            time.sleep(0)  # a bytecode boundary: the handler runs here
        finally:
            counter._lock.release()
    finally:
        obs_flight.uninstall_status_handler(prev)
        obs_metrics.reset_registry()
    with open(path) as fh:
        rec = json.load(fh)
    assert rec["type"] == "status"
    # the stale read still carried the value (GIL-atomic field read)
    vals = [m["value"] for m in rec["metrics"]
            if m["name"] == "drill_signal_total"]
    assert vals == [7.0]


@needs_sigusr1
def test_sigusr1_under_registry_lock_completes(tmp_path):
    """Same drill against the registry-level lock (a signal landing
    mid-instrument-registration)."""
    registry = obs_metrics.reset_registry()
    registry.counter("drill_reg_total").inc()
    path = str(tmp_path / "status.json")
    prev = obs_flight.install_status_handler(path)
    try:
        registry._lock.acquire()
        try:
            os.kill(os.getpid(), signal.SIGUSR1)
            time.sleep(0)
        finally:
            registry._lock.release()
    finally:
        obs_flight.uninstall_status_handler(prev)
        obs_metrics.reset_registry()
    rec = json.load(open(path))
    assert rec["type"] == "status"
    assert any(m["name"] == "drill_reg_total" for m in rec["metrics"])


@needs_sigusr1
def test_sigusr1_under_armed_detector_completes(tmp_path, lock_debug):
    """The armed-detector half of the signal-under-lock contract: with
    SART_LOCK_DEBUG=1 every handler-side lock RELEASE would record a
    hold time through a blocking registry acquire — if the interrupted
    bytecode holds the registry lock, that blocks forever. The handler
    suppresses detector bookkeeping, so the poke completes even with
    the registry lock held by the interrupted frame."""
    registry = lock_debug
    registry.counter("drill_armed_total").inc(3)
    path = str(tmp_path / "status.json")
    prev = obs_flight.install_status_handler(path)
    try:
        registry._lock.acquire()  # instrumented: held by "the frame"
        try:
            os.kill(os.getpid(), signal.SIGUSR1)
            time.sleep(0)  # handler runs here, detector armed
        finally:
            registry._lock.release()
    finally:
        obs_flight.uninstall_status_handler(prev)
    rec = json.load(open(path))
    assert rec["type"] == "status"
    assert any(m["name"] == "drill_armed_total" for m in rec["metrics"])


def test_crash_bundle_under_ring_lock_completes(tmp_path):
    """The crash hook fires while the process may be wedged holding the
    flight-ring lock; the bundle write must settle for a stale ring,
    not hang alongside the wedge."""
    ring = obs_flight.install(obs_flight.FlightRecorder(max_events=32))
    try:
        ring.record("drill", message="before the wedge")
        path = str(tmp_path / "crash.json")
        ring._lock.acquire()
        try:
            assert obs_flight.write_crash_bundle(path, "drill wedge")
        finally:
            ring._lock.release()
        rec = json.load(open(path))
        assert rec["type"] == "flight"
        assert rec["reason"] == "drill wedge"
        assert any(e["kind"] == "drill" for e in rec["ring"])
    finally:
        obs_flight.uninstall()


def test_nonblocking_snapshot_values_match_blocking():
    """The stale fallback is a degraded *path*, not degraded data: with
    no contention both forms must agree exactly."""
    registry = obs_metrics.MetricsRegistry()
    registry.counter("eq_total").inc(3)
    registry.histogram("eq_seconds").observe(0.5)
    registry.gauge("eq_depth").set(2)
    assert registry.snapshot() == registry.snapshot(blocking=False)


# ---------------------------------------------------------------------------
# lint integration: SL1xx on the package, wall-time budget
# ---------------------------------------------------------------------------


def test_package_self_lint_clean_with_only_sl1xx():
    """Acceptance: the package self-lint passes with SL101–SL105 enabled
    — run the concurrency family alone so a regression in it cannot
    hide behind the SL0xx catalogue."""
    import sartsolver_tpu
    from sartsolver_tpu.analysis.concurrency import CONCURRENCY_RULES
    from sartsolver_tpu.analysis.rules import lint_paths

    pkg = os.path.dirname(os.path.abspath(sartsolver_tpu.__file__))
    findings = lint_paths([pkg], rules=CONCURRENCY_RULES)
    assert not findings, "\n".join(f.format() for f in findings)


def test_lint_walltime_budget():
    """The SL103 call-graph pass rides inside every `sartsolve lint`:
    the package AST lint (all families) must stay under 10 s."""
    import sartsolver_tpu
    from sartsolver_tpu.analysis.rules import lint_paths

    pkg = os.path.dirname(os.path.abspath(sartsolver_tpu.__file__))
    t0 = time.perf_counter()
    lint_paths([pkg])
    elapsed = time.perf_counter() - t0
    assert elapsed < 10.0, f"package AST lint took {elapsed:.1f}s"
