"""Property sweeps for the ingest layer (VERDICT r4 next #1).

Random multi-camera, multi-segment worlds are materialized as real HDF5
files and thrown at the discovery/sort/consistency gate and the RTM window
reader. The oracles are re-derived independently in this file from the
reference sources, not from the implementation under test:

- hdf5files.cpp:46-103  — per-camera segment sort by min flattened
  voxel-map index, cameras in name order (std::map);
- hdf5files.cpp:106-218 — frame-mask equality across a camera's segments;
  voxel-map stitching with per-segment value re-offsetting, overlap and
  cross-camera equality checks;
- hdf5files.cpp:279-346 — camera-set match, first-pair wavelength
  threshold, frame-resolution match;
- raytransfer.cpp:27-127 — window reads over the sorted camera/segment
  layout (cameras advance the pixel axis, segments the voxel axis; sparse
  segments scatter-ASSIGN their triplets).

The same technique found four real defects in round 4 (alignment/voxel-
grid/resume layers); these sweeps close the remaining unswept ground.
"""

import os
import tempfile

import h5py
import numpy as np
import pytest

# tier-1 must collect cleanly without the optional `test` extra installed;
# hypothesis-backed sweeps simply skip when it is absent
pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from sartsolver_tpu.config import SartInputError
from sartsolver_tpu.io import hdf5files as hf
from sartsolver_tpu.io.raytransfer import read_rtm_block

# IO-heavy examples (several HDF5 files each): keep counts moderate so the
# suite wall-time stays flat; derandomized for reproducibility.
SET_IO = settings(max_examples=40, deadline=None, derandomize=True)

RTM_NAME = "with_reflections"


def _write_rtm_segment(path, camera, grid, cells, values, frame_mask,
                       wavelength, seg_matrix, sparse):
    """One RTM segment file in the reference schema (hdf5files.py header)."""
    nx, ny, nz = grid
    npixel, nvox_seg = seg_matrix.shape
    with h5py.File(path, "w") as f:
        rtm = f.create_group("rtm")
        rtm.attrs["camera_name"] = camera
        rtm.attrs["npixel"] = np.uint64(npixel)
        rtm.attrs["nvoxel"] = np.uint64(nvox_seg)
        rtm.create_dataset("frame_mask", data=frame_mask.astype(np.uint8))
        grp = rtm.create_group(RTM_NAME)
        grp.attrs["wavelength"] = float(wavelength)
        grp.attrs["is_sparse"] = int(sparse)
        if sparse:
            r, c = np.nonzero(seg_matrix)
            grp.create_dataset("pixel_index", data=r.astype(np.uint64))
            grp.create_dataset("voxel_index", data=c.astype(np.uint64))
            grp.create_dataset("value", data=seg_matrix[r, c])
        else:
            grp.create_dataset("value", data=seg_matrix)
        vm = rtm.create_group("voxel_map")
        vm.attrs["nx"] = np.uint64(nx)
        vm.attrs["ny"] = np.uint64(ny)
        vm.attrs["nz"] = np.uint64(nz)
        i, rem = np.divmod(np.asarray(cells, np.int64), ny * nz)
        j, k = np.divmod(rem, nz)
        vm.create_dataset("i", data=i.astype(np.uint64))
        vm.create_dataset("j", data=j.astype(np.uint64))
        vm.create_dataset("k", data=k.astype(np.uint64))
        vm.create_dataset("value", data=np.asarray(values, np.int64))


def _write_image(path, camera, wavelength, h, w, T=2):
    with h5py.File(path, "w") as f:
        img = f.create_group("image")
        img.attrs["camera_name"] = camera
        img.attrs["wavelength"] = float(wavelength)
        img.create_dataset("frame", data=np.zeros((T, h, w)))
        img.create_dataset("time", data=np.arange(T, dtype=np.float64))


def _build_world(rng, td, *, n_cam=None, n_seg=None, min_cells_per_seg=1,
                 wavelength=400.0, image_wavelength=None):
    """A random valid world: n_cam cameras sharing one occupied-cell
    partition into n_seg segments (identical partition + identical local
    values across cameras => identical stitched voxel maps, the validity
    condition the reference demands). Returns everything a test needs to
    compute expected results independently."""
    nx, ny, nz = (int(rng.integers(2, 5)) for _ in range(3))
    ncell = nx * ny * nz
    n_cam = n_cam if n_cam is not None else int(rng.integers(1, 4))
    n_seg = n_seg if n_seg is not None else int(rng.integers(1, 4))
    n_occ = int(rng.integers(min_cells_per_seg * n_seg, ncell + 1))
    occ = rng.choice(ncell, n_occ, replace=False)
    # split into n_seg parts of >= min_cells_per_seg cells each
    sizes = np.full(n_seg, min_cells_per_seg)
    for _ in range(n_occ - sizes.sum()):
        sizes[rng.integers(n_seg)] += 1
    seg_cells = np.split(occ, np.cumsum(sizes)[:-1])
    seg_values = [rng.permutation(len(c)) for c in seg_cells]
    # expected SORTED segment order: by min flat voxel index
    # (hdf5files.cpp:78-81); disjoint non-empty cell sets => unique keys
    order = np.argsort([int(c.min()) for c in seg_cells])

    letters = list(rng.permutation(list("ABCDEF")))[:n_cam]
    cameras = sorted(f"cam{l}" for l in letters)

    world = {
        "grid": (nx, ny, nz),
        "cameras": cameras,
        "order": order,
        "seg_cells": seg_cells,
        "seg_values": seg_values,
        "rtm_files": {},      # camera -> files in ORIGINAL segment order
        "expected_sorted": {},  # camera -> files in expected sorted order
        "seg_mats": {},       # (camera, original segment idx) -> float32
        "masks": {},
        "npixel": {},
        "image_files": {},
        "mask_hw": {},
    }
    img_wvl = wavelength if image_wavelength is None else image_wavelength
    for cam in cameras:
        h, w = int(rng.integers(1, 4)), int(rng.integers(1, 4))
        mask = (rng.random((h, w)) < 0.7).astype(np.uint8)
        npixel = int(rng.integers(1, 6))
        world["masks"][cam] = mask
        world["mask_hw"][cam] = (h, w)
        world["npixel"][cam] = npixel
        paths = []
        for s, (cells, values) in enumerate(zip(seg_cells, seg_values)):
            m = (rng.random((npixel, len(cells))).astype(np.float32)
                 * (rng.random((npixel, len(cells))) < 0.6))
            world["seg_mats"][(cam, s)] = m
            path = os.path.join(td, f"rtm_{cam}_s{s}.h5")
            _write_rtm_segment(
                path, cam, (nx, ny, nz), cells, values, mask,
                wavelength, m, sparse=bool(rng.integers(2)),
            )
            paths.append(path)
        world["rtm_files"][cam] = paths
        world["expected_sorted"][cam] = [paths[s] for s in order]
        ipath = os.path.join(td, f"img_{cam}.h5")
        _write_image(ipath, cam, img_wvl, h, w)
        world["image_files"][cam] = ipath
    return world


def _assemble_global(world):
    """Ground-truth global dense RTM, assembled directly from the segment
    matrices with the reference's layout rules: sorted cameras advance the
    pixel axis, sorted segments advance the voxel axis."""
    order = world["order"]
    col_sizes = [len(world["seg_cells"][s]) for s in order]
    nvoxel = sum(col_sizes)
    npixel = sum(world["npixel"][c] for c in world["cameras"])
    G = np.zeros((npixel, nvoxel), np.float32)
    r0 = 0
    for cam in world["cameras"]:
        c0 = 0
        for s, w in zip(order, col_sizes):
            G[r0:r0 + world["npixel"][cam], c0:c0 + w] = world["seg_mats"][(cam, s)]
            c0 += w
        r0 += world["npixel"][cam]
    return G


def _all_files_shuffled(world, rng):
    files = [p for paths in world["rtm_files"].values() for p in paths]
    files += list(world["image_files"].values())
    return list(rng.permutation(files))


# ---------------------------------------------------------------------------
# hdf5files: sort order, acceptance, sizes
# ---------------------------------------------------------------------------

@SET_IO
@given(st.integers(0, 2**32 - 1))
def test_sort_and_accept_random_worlds(seed):
    """For ANY shuffled presentation of a valid world: categorization
    splits RTM/image correctly, cameras come out in name order, segments
    in min-flat-voxel-index order, every consistency gate passes, and the
    global sizes equal the independently computed sums."""
    rng = np.random.default_rng(seed)
    with tempfile.TemporaryDirectory() as td:
        world = _build_world(rng, td)
        shuffled = _all_files_shuffled(world, rng)

        matrix_files, image_files = hf.categorize_input_files(shuffled)
        assert sorted(matrix_files) == sorted(
            p for paths in world["rtm_files"].values() for p in paths)
        assert sorted(image_files) == sorted(world["image_files"].values())

        smf = hf.sort_rtm_files(matrix_files)
        assert list(smf) == world["cameras"]  # name order (std::map)
        for cam in world["cameras"]:
            assert smf[cam] == world["expected_sorted"][cam], cam

        sif = hf.sort_image_files(image_files)
        assert list(sif) == world["cameras"]
        assert sif == {c: world["image_files"][c] for c in world["cameras"]}

        hf.check_group_attribute_consistency(
            matrix_files, f"rtm/{RTM_NAME}", ["wavelength"])
        hf.check_group_attribute_consistency(
            matrix_files, "rtm/voxel_map", ["nx", "ny", "nz"])
        hf.check_rtm_frame_consistency(smf)
        hf.check_rtm_voxel_consistency(smf)
        hf.check_group_attribute_consistency(image_files, "image", ["wavelength"])
        hf.check_rtm_image_consistency(smf, sif, RTM_NAME, 1.0)

        npixel, nvoxel = hf.get_total_rtm_size(smf)
        assert npixel == sum(world["npixel"][c] for c in world["cameras"])
        assert nvoxel == sum(len(c) for c in world["seg_cells"])

        masks = hf.read_rtm_frame_masks(smf)
        for cam in world["cameras"]:
            np.testing.assert_array_equal(
                masks[cam], world["masks"][cam].ravel())


# ---------------------------------------------------------------------------
# hdf5files: every corrupted world is rejected with the right diagnostic
# ---------------------------------------------------------------------------

def _corrupt_overlap(world, rng):
    """Duplicate a cell of one sorted segment into another segment of the
    same camera => stitching must hit 'overlapping voxel maps' whichever
    segment order the (possibly changed) sort keys produce."""
    cam = world["cameras"][int(rng.integers(len(world["cameras"])))]
    order = world["order"]
    src = world["seg_cells"][order[0]]
    # never duplicate src's MINIMUM cell: that would tie the victim's sort
    # key with src's, and the per-camera {min_index: file} map silently
    # drops one file on a key collision (exactly like the reference's
    # std::map, hdf5files.cpp:83-87) — the overlap would vanish with it
    candidates = src[src != src.min()]
    dup_cell = int(candidates[int(rng.integers(len(candidates)))])
    victim_path = world["rtm_files"][cam][order[1]]
    with h5py.File(victim_path, "r+") as f:
        vm = f["rtm/voxel_map"]
        nx, ny, nz = (int(vm.attrs[a]) for a in ("nx", "ny", "nz"))
        i, rem = divmod(dup_cell, ny * nz)
        j, k = divmod(rem, nz)
        for name, extra in (("i", i), ("j", j), ("k", k),
                            ("value", len(vm["value"]))):
            data = np.append(np.asarray(vm[name]), extra)
            del vm[name]
            vm.create_dataset(name, data=data)
    return "overlapping voxel maps"


def _corrupt_cross_camera(world, rng):
    """Swap two voxel-map values inside one non-first camera's segment:
    still overlap-free, but the stitched map no longer equals the first
    camera's => 'different voxel maps'."""
    cam = world["cameras"][-1]
    seg_sizes = [len(c) for c in world["seg_cells"]]
    s = int(np.argmax(seg_sizes))  # the guaranteed >=2-cell segment
    path = world["rtm_files"][cam][s]
    with h5py.File(path, "r+") as f:
        vm = f["rtm/voxel_map"]
        vals = np.asarray(vm["value"])
        vals[0], vals[1] = vals[1], vals[0]
        vm["value"][...] = vals
    return "different voxel maps"


def _corrupt_mask(world, rng):
    cam = world["cameras"][int(rng.integers(len(world["cameras"])))]
    path = world["rtm_files"][cam][1]  # any non-unique segment file
    with h5py.File(path, "r+") as f:
        mask = np.asarray(f["rtm/frame_mask"])
        mask.flat[int(rng.integers(mask.size))] ^= 1
        f["rtm/frame_mask"][...] = mask
    return "different frame masks"


def _corrupt_resolution(world, rng):
    cam = world["cameras"][int(rng.integers(len(world["cameras"])))]
    h, w = world["mask_hw"][cam]
    _write_image(world["image_files"][cam], cam, 400.0, h, w + 1)
    return "resolution"


CORRUPTIONS = {
    "overlap": _corrupt_overlap,
    "cross_camera": _corrupt_cross_camera,
    "mask": _corrupt_mask,
    "resolution": _corrupt_resolution,
}


@SET_IO
@given(st.integers(0, 2**32 - 1), st.sampled_from(sorted(CORRUPTIONS)))
def test_corrupted_worlds_rejected(seed, mode):
    """Every corruption class is rejected with the reference's diagnostic
    (hdf5files.cpp:106-218, 279-346), from ANY random base world."""
    rng = np.random.default_rng(seed)
    with tempfile.TemporaryDirectory() as td:
        # >=2 cameras (cross-camera check), >=2 segments (overlap/mask),
        # and at least one segment with >=2 cells (value swap)
        world = _build_world(rng, td, n_cam=int(rng.integers(2, 4)),
                             n_seg=int(rng.integers(2, 4)),
                             min_cells_per_seg=2)
        fragment = CORRUPTIONS[mode](world, rng)

        matrix_files = [p for paths in world["rtm_files"].values() for p in paths]
        smf = hf.sort_rtm_files(matrix_files)
        sif = hf.sort_image_files(list(world["image_files"].values()))
        with pytest.raises(SartInputError, match=fragment):
            hf.check_rtm_frame_consistency(smf)
            hf.check_rtm_voxel_consistency(smf)
            hf.check_rtm_image_consistency(smf, sif, RTM_NAME, 1.0)


@SET_IO
@given(st.integers(0, 2**32 - 1))
def test_camera_set_mismatch_rejected(seed):
    """Missing/extra/duplicate image files fail with the exact reference
    message shapes (hdf5files.cpp:247-294)."""
    rng = np.random.default_rng(seed)
    with tempfile.TemporaryDirectory() as td:
        world = _build_world(rng, td, n_cam=2)
        matrix_files = [p for paths in world["rtm_files"].values() for p in paths]
        smf = hf.sort_rtm_files(matrix_files)
        cam0, cam1 = world["cameras"]

        # missing image for cam1
        sif = hf.sort_image_files([world["image_files"][cam0]])
        with pytest.raises(SartInputError, match=f"No image file for {cam1}"):
            hf.check_rtm_image_consistency(smf, sif, RTM_NAME, 1.0)

        # extra image for an unknown camera
        extra = os.path.join(td, "img_extra.h5")
        _write_image(extra, "camZZ", 400.0, 2, 2)
        sif = hf.sort_image_files(
            list(world["image_files"].values()) + [extra])
        with pytest.raises(SartInputError, match="No RTM file for camZZ"):
            hf.check_rtm_image_consistency(smf, sif, RTM_NAME, 1.0)

        # two image files claiming the same camera
        dup = os.path.join(td, "img_dup.h5")
        _write_image(dup, cam0, 400.0, 2, 2)
        with pytest.raises(SartInputError, match="share the same diagnostic view"):
            hf.sort_image_files(list(world["image_files"].values()) + [dup])


@SET_IO
@given(
    st.integers(0, 2**32 - 1),
    st.floats(100.0, 1000.0),           # RTM wavelength
    st.floats(0.0, 10.0),               # threshold
    st.floats(0.0, 2.0),                # |delta| as a fraction of threshold
    st.sampled_from([-1.0, 1.0]),       # delta sign
)
def test_wavelength_threshold_straddle(seed, wvl, threshold, frac, sign):
    """Acceptance flips exactly at |rtm_wvl - img_wvl| > threshold
    (hdf5files.cpp:296-315), for deltas straddling the threshold from
    either side — computed on the same float64 values the files store."""
    rng = np.random.default_rng(seed)
    img_wvl = wvl + sign * threshold * frac
    with tempfile.TemporaryDirectory() as td:
        world = _build_world(rng, td, n_cam=1, n_seg=1, wavelength=wvl,
                             image_wavelength=img_wvl)
        smf = hf.sort_rtm_files(
            [p for paths in world["rtm_files"].values() for p in paths])
        sif = hf.sort_image_files(list(world["image_files"].values()))
        should_reject = abs(wvl - img_wvl) > threshold
        if should_reject:
            with pytest.raises(SartInputError, match="not within"):
                hf.check_rtm_image_consistency(smf, sif, RTM_NAME, threshold)
        else:
            hf.check_rtm_image_consistency(smf, sif, RTM_NAME, threshold)


# ---------------------------------------------------------------------------
# raytransfer: any window read equals the dense-assembly slice
# ---------------------------------------------------------------------------

def _draw_window(rng, total):
    lo = int(rng.integers(0, total))
    hi = int(rng.integers(lo + 1, total + 1))
    return lo, hi - lo


@SET_IO
@given(st.integers(0, 2**32 - 1), st.integers(1, 4))
def test_rtm_window_reads_match_dense_assembly(seed, n_windows):
    """read_rtm_block over ANY (row, column) window — aligned or not with
    camera/segment boundaries, dense and sparse segments mixed — equals
    the corresponding slice of the independently assembled global matrix
    (raytransfer.cpp:27-127 semantics), bit-exact in float32."""
    rng = np.random.default_rng(seed)
    with tempfile.TemporaryDirectory() as td:
        world = _build_world(rng, td)
        G = _assemble_global(world)
        smf = {c: world["expected_sorted"][c] for c in world["cameras"]}
        npix, nvox = G.shape
        for _ in range(n_windows):
            op, npl = _draw_window(rng, npix)
            ov, nvl = _draw_window(rng, nvox)
            mat = read_rtm_block(
                smf, RTM_NAME, npl, nvox, op,
                offset_voxel=ov, nvoxel_local=nvl,
            )
            np.testing.assert_array_equal(
                mat, G[op:op + npl, ov:ov + nvl],
                err_msg=f"window rows[{op}:{op+npl}] cols[{ov}:{ov+nvl}]",
            )
        # full-matrix read as the degenerate window
        np.testing.assert_array_equal(
            read_rtm_block(smf, RTM_NAME, npix, nvox, 0), G)


@SET_IO
@given(st.integers(0, 2**32 - 1), st.integers(1, 3))
def test_rtm_chunked_sparse_cache_equivalence(seed, chunk_rows):
    """The one-pass sparse cache is transparent: chunked row reads through
    a shared cache (the striped-ingest pattern), repeated reads (cache
    hits via the searchsorted path), reads OUTSIDE the cached window
    (must bypass, not come back empty), and a zero byte budget (over-
    budget fallback) all reproduce the dense-assembly slices exactly."""
    rng = np.random.default_rng(seed)
    with tempfile.TemporaryDirectory() as td:
        world = _build_world(rng, td)
        G = _assemble_global(world)
        smf = {c: world["expected_sorted"][c] for c in world["cameras"]}
        npix, nvox = G.shape
        r0, nr = _draw_window(rng, npix)
        c0, nc = _draw_window(rng, nvox)
        cache = {}
        cache_rows, cache_cols = (r0, r0 + nr), (c0, c0 + nc)
        for lo in range(r0, r0 + nr, chunk_rows):
            n = min(chunk_rows, r0 + nr - lo)
            mat = read_rtm_block(
                smf, RTM_NAME, n, nvox, lo,
                offset_voxel=c0, nvoxel_local=nc,
                sparse_cache=cache, cache_rows=cache_rows,
                cache_cols=cache_cols,
            )
            np.testing.assert_array_equal(mat, G[lo:lo + n, c0:c0 + nc])
        # repeat the first chunk: pure cache-hit path
        n = min(chunk_rows, nr)
        mat = read_rtm_block(
            smf, RTM_NAME, n, nvox, r0, offset_voxel=c0, nvoxel_local=nc,
            sparse_cache=cache, cache_rows=cache_rows, cache_cols=cache_cols,
        )
        np.testing.assert_array_equal(mat, G[r0:r0 + n, c0:c0 + nc])
        # a read with a DIFFERENT window through the same cache dict must
        # bypass the (mismatched) cached entries, not return empty blocks
        mat = read_rtm_block(
            smf, RTM_NAME, npix, nvox, 0,
            sparse_cache=cache, cache_rows=(0, npix), cache_cols=(0, nvox),
        )
        np.testing.assert_array_equal(mat, G)
        # zero byte budget: every segment takes the over-budget fallback
        saved = os.environ.get("SART_SPARSE_CACHE_MB")
        os.environ["SART_SPARSE_CACHE_MB"] = "0"
        try:
            cache2 = {}
            mat = read_rtm_block(
                smf, RTM_NAME, nr, nvox, r0, offset_voxel=c0,
                nvoxel_local=nc, sparse_cache=cache2,
                cache_rows=cache_rows, cache_cols=cache_cols,
            )
            np.testing.assert_array_equal(mat, G[r0:r0 + nr, c0:c0 + nc])
            mat = read_rtm_block(  # second pass: cached None => re-read
                smf, RTM_NAME, nr, nvox, r0, offset_voxel=c0,
                nvoxel_local=nc, sparse_cache=cache2,
                cache_rows=cache_rows, cache_cols=cache_cols,
            )
            np.testing.assert_array_equal(mat, G[r0:r0 + nr, c0:c0 + nc])
        finally:
            if saved is None:
                del os.environ["SART_SPARSE_CACHE_MB"]
            else:
                os.environ["SART_SPARSE_CACHE_MB"] = saved
