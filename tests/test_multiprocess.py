"""REAL two-process distributed runs (JAX multi-controller over Gloo).

The rest of the suite tests distribution on a single process with 8
virtual devices; these tests launch two actual processes so the
cross-process paths run for real: `jax.distributed.initialize`, the
serialized striped ingest barrier, per-process measurement slicing
(`all_processes_sliceable` is True here: 2 procs x 1 device, contiguous
row blocks), process-0-only output writing, and the resume broadcast.

Equivalent of the reference's `mpirun -np 2 sartsolver` against
`-np 1` (main.cpp:63-68) — which its math assumes but never asserts.
"""

import os
import socket
import subprocess
import sys

import h5py
import numpy as np
import pytest

import fixtures as fx

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_pair(paths, outfile, port, *extra, timeout=240):
    inputs = [paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
              paths["img_a"], paths["img_b"]]
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # no tunnel in child procs
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(_HERE, "mp_worker.py"),
             str(rank), "2", str(port), outfile, *extra, "--", *inputs],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for rank in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    assert all(p.returncode == 0 for p in procs), (
        f"worker rc={[p.returncode for p in procs]}\n"
        f"--- rank0 ---\n{outs[0][-3000:]}\n--- rank1 ---\n{outs[1][-3000:]}"
    )
    return outs


@pytest.fixture
def world(tmp_path):
    return fx.write_world(tmp_path, with_laplacian=True)


def test_two_process_run_matches_single(world, tmp_path):
    paths, H, f_true, times, scales = world

    # single-process reference via the CLI in-process (same flags)
    from sartsolver_tpu.cli import main
    ref_out = str(tmp_path / "ref.h5")
    assert main([
        "-o", ref_out, paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
        paths["img_a"], paths["img_b"], "--use_cpu", "-m", "100", "-c", "1e-8",
        "-l", paths["laplacian"], "-b", "0.001",
    ]) == 0

    mp_out = str(tmp_path / "mp.h5")
    outs = _run_pair(paths, mp_out, _free_port(), "-l", paths["laplacian"], "-b", "0.001")
    # process 0 prints the frame lines, process 1 must not
    assert outs[0].count("Processed in:") == len(times)
    assert outs[1].count("Processed in:") == 0

    with h5py.File(ref_out, "r") as fr, h5py.File(mp_out, "r") as fm:
        ref, got = fr["solution/value"][:], fm["solution/value"][:]
        np.testing.assert_allclose(got, ref, rtol=1e-9, atol=1e-12)
        np.testing.assert_array_equal(
            fm["solution/status"][:], fr["solution/status"][:]
        )
        assert "voxel_map" in fm


def test_two_process_resume(world, tmp_path):
    paths, H, f_true, times, scales = world
    mp_out = str(tmp_path / "mp_resume.h5")
    # first half of the series...
    _run_pair(paths, mp_out, _free_port(), "-t", "0:0.25")
    with h5py.File(mp_out, "r") as f:
        n_first = f["solution/value"].shape[0]
    assert 0 < n_first < len(times)
    # ...then resume across processes: process 0 reads, broadcasts
    outs = _run_pair(paths, mp_out, _free_port(), "--resume")
    assert outs[0].count("Processed in:") == len(times) - n_first
    with h5py.File(mp_out, "r") as f:
        assert f["solution/value"].shape[0] == len(times)
