"""REAL two-process distributed runs (JAX multi-controller over Gloo).

The rest of the suite tests distribution on a single process with 8
virtual devices; these tests launch two actual processes so the
cross-process paths run for real: `jax.distributed.initialize`, the
serialized striped ingest barrier, per-process measurement slicing
(`all_processes_local_capable` is True here: 2 procs x 1 device,
contiguous row blocks), process-0-only output writing, and the resume
broadcast.

Equivalent of the reference's `mpirun -np 2 sartsolver` against
`-np 1` (main.cpp:63-68) — which its math assumes but never asserts.
"""

import os
import socket
import subprocess
import sys

import h5py
import numpy as np
import pytest

import fixtures as fx
import mp_support

# Environment gate (tests/mp_support.py): on jaxlib builds whose CPU
# backend has no multiprocess collectives every test here would fail on
# "Multiprocess computations aren't implemented on the CPU backend" —
# an environment limitation, so skip (not fail) with the reason visible;
# SART_MP_TESTS=1 force-runs on a capable build.
pytestmark = pytest.mark.skipif(
    not mp_support.multiprocess_collectives_supported(),
    reason=mp_support.SKIP_REASON,
)

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_world(inputs, outfile, port, *extra, nproc=2, timeout=240,
               env_extra=None):
    """Launch ``nproc`` real mp_worker processes on one coordinator."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # no tunnel in child procs
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(env_extra or {})
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(_HERE, "mp_worker.py"),
             str(rank), str(nproc), str(port), outfile, *extra,
             "--", *inputs],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for rank in range(nproc)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    assert all(p.returncode == 0 for p in procs), (
        f"worker rc={[p.returncode for p in procs]}\n" + "\n".join(
            f"--- rank{i} ---\n{o[-3000:]}" for i, o in enumerate(outs))
    )
    return outs


def _run_pair(paths, outfile, port, *extra, timeout=240):
    inputs = [paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
              paths["img_a"], paths["img_b"]]
    return _run_world(inputs, outfile, port, *extra, timeout=timeout)


@pytest.fixture
def world(tmp_path):
    return fx.write_world(tmp_path, with_laplacian=True)


def test_two_process_run_matches_single(world, tmp_path):
    paths, H, f_true, times, scales = world

    # single-process reference via the CLI in-process (same flags)
    from sartsolver_tpu.cli import main
    ref_out = str(tmp_path / "ref.h5")
    assert main([
        "-o", ref_out, paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
        paths["img_a"], paths["img_b"], "--use_cpu", "-m", "100", "-c", "1e-8",
        "-l", paths["laplacian"], "-b", "0.001",
    ]) == 0

    mp_out = str(tmp_path / "mp.h5")
    outs = _run_pair(paths, mp_out, _free_port(), "-l", paths["laplacian"], "-b", "0.001")
    # process 0 prints the frame lines, process 1 must not
    assert outs[0].count("Processed in:") == len(times)
    assert outs[1].count("Processed in:") == 0

    with h5py.File(ref_out, "r") as fr, h5py.File(mp_out, "r") as fm:
        ref, got = fr["solution/value"][:], fm["solution/value"][:]
        np.testing.assert_allclose(got, ref, rtol=1e-9, atol=1e-12)
        np.testing.assert_array_equal(
            fm["solution/status"][:], fr["solution/status"][:]
        )
        assert "voxel_map" in fm


def _write_wide_world(tmp_path, monkeypatch, V=512, npix=16):
    """One-camera world wide enough for 128-aligned column blocks:
    voxels [0, V/2) dense, [V/2, V) sparse, 2 frames."""
    monkeypatch.setattr(fx, "NX", V // 16)
    monkeypatch.setattr(fx, "NY", 16)
    monkeypatch.setattr(fx, "NZ", 1)
    rng = np.random.default_rng(7)
    mask = np.ones((4, 4), np.int64)
    H = rng.uniform(0.1, 1.0, (npix, V)).astype(np.float32)
    half = V // 2
    H[:, half:] *= rng.random((npix, half)) < 0.5  # genuinely sparse half
    cells = np.arange(V, dtype=np.int64)
    p = {
        "seg_dense": str(tmp_path / "wide_dense.h5"),
        "seg_sparse": str(tmp_path / "wide_sparse.h5"),
        "img": str(tmp_path / "wide_img.h5"),
    }
    fx._write_rtm_file(p["seg_dense"], "camW", mask, H[:, :half],
                       cells[:half], cells[:half], sparse=False)
    fx._write_rtm_file(p["seg_sparse"], "camW", mask, H[:, half:],
                       cells[half:], cells[:half], sparse=True)
    f_true = rng.uniform(0.5, 2.0, V)
    times = np.array([0.1, 0.2])
    frames = np.stack([
        fx.frame_from_measurement(mask, H @ (f_true * s))
        for s in (1.0, 1.2)
    ])
    fx._write_image_file(p["img"], "camW", frames, times)
    return p, H, times


def test_two_process_voxel_major_column_striped(tmp_path, monkeypatch):
    """Voxel-major mesh across two REAL processes (VERDICT r2 next #2):
    the column-striped ingest must (a) reproduce the single-process
    solution, and (b) read per host only its own columns' bytes — the
    property that makes voxel-major (and with it the fused sweep)
    reachable beyond one host. Block 0 is the dense segment, block 1 the
    sparse one, so the byte accounting separates exactly."""
    p, H, times = _write_wide_world(tmp_path, monkeypatch)
    inputs = [p["seg_dense"], p["seg_sparse"], p["img"]]

    from sartsolver_tpu.cli import main
    ref_out = str(tmp_path / "ref_vm.h5")
    assert main([
        "-o", ref_out, *inputs, "--use_cpu", "-m", "100", "-c", "1e-8",
        "--pixel_shards", "1", "--voxel_shards", "1",
    ]) == 0

    mp_out = str(tmp_path / "mp_vm.h5")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(_HERE, "mp_worker.py"),
             str(rank), "2", str(port), mp_out,
             "--pixel_shards", "1", "--voxel_shards", "2", "--", *inputs],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for rank in range(2)
    ]
    outs = [pp.communicate(timeout=240)[0] for pp in procs]
    assert all(pp.returncode == 0 for pp in procs), (
        f"rc={[pp.returncode for pp in procs]}\n{outs[0][-2000:]}\n"
        f"{outs[1][-2000:]}"
    )

    with h5py.File(ref_out, "r") as fr, h5py.File(mp_out, "r") as fm:
        np.testing.assert_allclose(
            fm["solution/value"][:], fr["solution/value"][:],
            rtol=1e-9, atol=1e-12,
        )

    byte_counts = []
    for out in outs:
        lines = [ln for ln in out.splitlines()
                 if ln.startswith("INGEST_DATA_BYTES=")]
        assert lines, out[-2000:]
        byte_counts.append(int(lines[-1].split("=")[1]))
    npix, V = H.shape
    half = V // 2
    # process 0 (columns [0, 256)) reads exactly the dense payload and no
    # sparse triplets; process 1 reads only the sparse segment's triplets,
    # once (not once per chunk)
    nnz = np.count_nonzero(H[:, half:])
    assert byte_counts[0] == npix * half * 4, byte_counts
    assert byte_counts[1] == nnz * (8 + 8 + 4), (byte_counts, nnz)


def test_two_process_int8_voxel_major(tmp_path, monkeypatch):
    """int8 RTM storage across two REAL processes on a voxel-major mesh:
    the two-pass quantized ingest computes per-column scales process-
    locally (complete columns per process) and must reproduce the
    single-process int8 solve."""
    p, H, times = _write_wide_world(tmp_path, monkeypatch)
    inputs = [p["seg_dense"], p["seg_sparse"], p["img"]]

    from sartsolver_tpu.cli import main
    ref_out = str(tmp_path / "ref_i8.h5")
    assert main([
        "-o", ref_out, *inputs, "-m", "1000",
        "--rtm_dtype", "int8", "--fused_sweep", "interpret",
        "--pixel_shards", "1", "--voxel_shards", "1",
    ]) == 0

    mp_out = str(tmp_path / "mp_i8.h5")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(_HERE, "mp_worker.py"),
             str(rank), "2", str(port), mp_out, "--no_default_profile",
             "-m", "1000",  # argparse last-wins over the worker's default
             "--rtm_dtype", "int8", "--fused_sweep", "interpret",
             "--pixel_shards", "1", "--voxel_shards", "2", "--", *inputs],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for rank in range(2)
    ]
    outs = [pp.communicate(timeout=300)[0] for pp in procs]
    assert all(pp.returncode == 0 for pp in procs), (
        f"rc={[pp.returncode for pp in procs]}\n{outs[0][-2000:]}\n"
        f"{outs[1][-2000:]}"
    )
    with h5py.File(ref_out, "r") as fr, h5py.File(mp_out, "r") as fm:
        assert (fm["solution/status"][:] == 0).all()
        assert (fr["solution/status"][:] == 0).all()
        ref, got = fr["solution/value"][:], fm["solution/value"][:]
        # same quantized system (process-local scales == global scales);
        # psum ordering across shards shifts the fp32 stall iteration, so
        # compare converged reconstructions in fitted space
        for i in range(ref.shape[0]):
            fit_ref, fit_got = H @ ref[i], H @ got[i]
            rel = np.linalg.norm(fit_got - fit_ref) / np.linalg.norm(fit_ref)
            assert rel < 0.01, (i, rel)


def test_two_process_chained_matches_serial(world, tmp_path):
    """The device-chained warm-start frame loop across two REAL processes
    (VERDICT r3 next #1): `--chain_frames 2` (two chains of two frames,
    with a device-side chain-to-chain handoff) must bit-match
    `--chain_frames 1` (per-frame dispatch) — same solutions, statuses,
    and iteration counts in the written file. This is the reference's
    core workload (the serial warm-started loop, main.cpp:131-140) at
    rank count 2 with the one-round-trip-per-K-frames dispatch."""
    paths, H, f_true, times, scales = world

    serial_out = str(tmp_path / "mp_serial.h5")
    _run_pair(paths, serial_out, _free_port(), "--chain_frames", "1")

    chain_out = str(tmp_path / "mp_chain.h5")
    outs = _run_pair(paths, chain_out, _free_port(), "--chain_frames", "2")
    # chain flushes print one line per real frame, process 0 only
    assert outs[0].count("Processed in:") == len(times)
    assert outs[1].count("Processed in:") == 0
    assert "average over chain" in outs[0]

    with h5py.File(serial_out, "r") as fs, h5py.File(chain_out, "r") as fc:
        np.testing.assert_array_equal(
            fc["solution/value"][:], fs["solution/value"][:]
        )
        np.testing.assert_array_equal(
            fc["solution/status"][:], fs["solution/status"][:]
        )
        np.testing.assert_array_equal(
            fc["solution/iterations"][:], fs["solution/iterations"][:]
        )
        assert "voxel_map" in fc


def test_two_process_batched_matches_per_frame(world, tmp_path):
    """The batched --no_guess path across two REAL processes with device
    results (replicated lazy fetch): `--batch_frames 2` (two groups of
    two independent frames, tail untouched here since 4 % 2 == 0) must
    bit-match per-frame dispatch with the same flags."""
    paths, H, f_true, times, scales = world

    one_out = str(tmp_path / "mp_b1.h5")
    _run_pair(paths, one_out, _free_port(), "--no_guess")

    bat_out = str(tmp_path / "mp_b2.h5")
    outs = _run_pair(paths, bat_out, _free_port(),
                     "--no_guess", "--batch_frames", "2")
    assert outs[0].count("Processed in:") == len(times)
    assert "average over batch" in outs[0]

    with h5py.File(one_out, "r") as fo, h5py.File(bat_out, "r") as fb:
        # gemv (B=1) vs gemm (B=2) may legally reorder the contraction;
        # the single-process suite's CPU bound is rtol=1e-9 (test_batched)
        np.testing.assert_allclose(
            fb["solution/value"][:], fo["solution/value"][:], rtol=1e-9
        )
        np.testing.assert_array_equal(
            fb["solution/status"][:], fo["solution/status"][:]
        )
        np.testing.assert_array_equal(
            fb["solution/iterations"][:], fo["solution/iterations"][:]
        )


@pytest.mark.parametrize("nproc,pixel_shards,voxel_shards,timeout", [
    (4, 2, 2, 300),
    # one more doubling of the 2/4-process evidence; slowest case on a
    # single host core (8 workers time-slice), kept to one scenario
    (8, 2, 4, 700),
])
def test_n_process_2d_mesh_matches_single(world, tmp_path, nproc,
                                          pixel_shards, voxel_shards,
                                          timeout):
    """FOUR and EIGHT real processes on 2-D ('pixels','voxels') meshes
    (VERDICT r3 next #6 — prior real-process evidence stopped at 2):
    row-and-column sharded ingest, halo Laplacian, local measurement
    staging, and the default chained frame loop must reproduce the
    single-process run."""
    paths, H, f_true, times, scales = world
    inputs = [paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
              paths["img_a"], paths["img_b"]]

    from sartsolver_tpu.cli import main
    ref_out = str(tmp_path / "ref_n.h5")
    assert main([
        "-o", ref_out, *inputs, "--use_cpu", "-m", "100", "-c", "1e-8",
        "-l", paths["laplacian"], "-b", "0.001",
        "--pixel_shards", "1", "--voxel_shards", "1",
    ]) == 0

    mp_out = str(tmp_path / "mp_n.h5")
    outs = _run_world(
        inputs, mp_out, _free_port(),
        "-l", paths["laplacian"], "-b", "0.001",
        "--pixel_shards", str(pixel_shards),
        "--voxel_shards", str(voxel_shards),
        nproc=nproc, timeout=timeout,
    )
    assert outs[0].count("Processed in:") == len(times)
    for out in outs[1:]:
        assert out.count("Processed in:") == 0
    with h5py.File(ref_out, "r") as fr, h5py.File(mp_out, "r") as fm:
        np.testing.assert_allclose(
            fm["solution/value"][:], fr["solution/value"][:],
            rtol=1e-9, atol=1e-12,
        )
        np.testing.assert_array_equal(
            fm["solution/status"][:], fr["solution/status"][:]
        )


def test_four_process_1x4_int8_byte_accounting(tmp_path, monkeypatch):
    """FOUR processes, voxel-major 1x4 mesh, int8 two-pass quantized
    ingest: per-process I/O must stay proportional to its own columns
    (dense owners read exactly their hyperslab; sparse owners read the
    triplets once), and the solve must reproduce the single-process int8
    run in fitted space."""
    p, H, times = _write_wide_world(tmp_path, monkeypatch)
    inputs = [p["seg_dense"], p["seg_sparse"], p["img"]]

    from sartsolver_tpu.cli import main
    ref_out = str(tmp_path / "ref_i84.h5")
    assert main([
        "-o", ref_out, *inputs, "-m", "1000",
        "--rtm_dtype", "int8", "--fused_sweep", "interpret",
        "--pixel_shards", "1", "--voxel_shards", "1",
    ]) == 0

    mp_out = str(tmp_path / "mp_i84.h5")
    outs = _run_world(
        inputs, mp_out, _free_port(), "--no_default_profile",
        "-m", "1000", "--rtm_dtype", "int8", "--fused_sweep", "interpret",
        "--pixel_shards", "1", "--voxel_shards", "4",
        nproc=4, timeout=360,
    )
    with h5py.File(ref_out, "r") as fr, h5py.File(mp_out, "r") as fm:
        assert (fm["solution/status"][:] == 0).all()
        ref, got = fr["solution/value"][:], fm["solution/value"][:]
        for i in range(ref.shape[0]):
            fit_ref, fit_got = H @ ref[i], H @ got[i]
            rel = np.linalg.norm(fit_got - fit_ref) / np.linalg.norm(fit_ref)
            assert rel < 0.01, (i, rel)

    byte_counts = []
    for out in outs:
        lines = [ln for ln in out.splitlines()
                 if ln.startswith("INGEST_DATA_BYTES=")]
        assert lines, out[-2000:]
        byte_counts.append(int(lines[-1].split("=")[1]))
    npix, V = H.shape
    half = V // 2
    nnz = np.count_nonzero(H[:, half:])
    # V=512 over 4 shards: 128-column blocks; procs 0-1 own the dense
    # segment's halves and read their hyperslab TWICE (the int8 ingest is
    # two-pass: column maxima, then quantized staging); procs 2-3 own the
    # sparse segment's halves and read its triplets ONCE — the shared
    # sparse cache serves pass 2
    assert byte_counts[0] == 2 * npix * 128 * 4, byte_counts
    assert byte_counts[1] == 2 * npix * 128 * 4, byte_counts
    assert byte_counts[2] == nnz * (8 + 8 + 4), (byte_counts, nnz)
    assert byte_counts[3] == nnz * (8 + 8 + 4), (byte_counts, nnz)


def test_two_process_chain_host_fetch_fallback(world, tmp_path):
    """SART_REPLICATE_FETCH_LIMIT=0 forces the over-budget path: the
    chained solution is allgathered to the HOST on the main thread
    instead of replicated on device (the guard that keeps voxel-sharded
    near-HBM-limit runs from a replicated-solution footprint). Results
    must be identical to the device-replicated path."""
    paths, H, f_true, times, scales = world

    rep_out = str(tmp_path / "mp_rep.h5")
    _run_pair(paths, rep_out, _free_port(), "--chain_frames", "2")

    host_out = str(tmp_path / "mp_hostfetch.h5")
    inputs = [paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
              paths["img_a"], paths["img_b"]]
    _run_world(inputs, host_out, _free_port(), "--chain_frames", "2",
               env_extra={"SART_REPLICATE_FETCH_LIMIT": "0"})
    with h5py.File(rep_out, "r") as fr, h5py.File(host_out, "r") as fh:
        np.testing.assert_array_equal(
            fh["solution/value"][:], fr["solution/value"][:]
        )
        np.testing.assert_array_equal(
            fh["solution/iterations"][:], fr["solution/iterations"][:]
        )


def test_four_process_resume(world, tmp_path):
    """Resume across FOUR processes on a pixel-major 4x1 mesh, where two
    processes own only padding rows (the replicated-staging fallback):
    process 0 reads the file and broadcasts; everyone skips the same
    frames."""
    paths, H, f_true, times, scales = world
    inputs = [paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
              paths["img_a"], paths["img_b"]]
    mp_out = str(tmp_path / "mp4_resume.h5")
    _run_world(inputs, mp_out, _free_port(), "-t", "0:0.25",
               "--pixel_shards", "4", nproc=4, timeout=300)
    with h5py.File(mp_out, "r") as f:
        n_first = f["solution/value"].shape[0]
    assert 0 < n_first < len(times)
    outs = _run_world(inputs, mp_out, _free_port(), "--resume",
                      "--pixel_shards", "4", nproc=4, timeout=300)
    assert outs[0].count("Processed in:") == len(times) - n_first
    with h5py.File(mp_out, "r") as f:
        assert f["solution/value"].shape[0] == len(times)


def test_resume_broadcast_bit_exact():
    """broadcast_resume_state must return the EXACT float64 state process
    0 read from the file, with x64 at its default (disabled) setting —
    the CLI broadcasts before --use_cpu enables x64, and a naive fp64
    broadcast silently downcasts to fp32 there (times lose 29 bits, the
    warm seed drifts ~5e-8; found by tests/test_killdrill.py's 2-process
    drill). Runs a real 2-process exchange."""
    worker = r"""
import os, sys
rank = int(sys.argv[1]); port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
from sartsolver_tpu.parallel import multihost as mh
mh.initialize(f"127.0.0.1:{port}", 2, rank)
import numpy as np
from sartsolver_tpu.io.solution import ResumeState
rng = np.random.default_rng(7)
times = rng.uniform(0, 10, 5)          # generic fp64, not fp32-exact
last = rng.uniform(0.0, 2.0, 16)
state = ResumeState(times, last) if rank == 0 else None
out = mh.broadcast_resume_state(state, 16)
assert out.times.dtype == np.float64 and out.last_solution.dtype == np.float64
np.testing.assert_array_equal(out.times, times)
np.testing.assert_array_equal(out.last_solution, last)
print("BCAST_OK", flush=True)
"""
    port = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen([sys.executable, "-c", worker, str(rank), str(port)],
                         env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
        for rank in range(2)
    ]
    try:
        outs = [p.communicate(timeout=240)[0] for p in procs]
    finally:
        for p in procs:  # never leak live workers on a timeout
            if p.poll() is None:
                p.kill()
    assert all(p.returncode == 0 for p in procs), "\n".join(o[-2000:] for o in outs)
    assert all("BCAST_OK" in o for o in outs)


def test_two_process_resume(world, tmp_path):
    paths, H, f_true, times, scales = world
    mp_out = str(tmp_path / "mp_resume.h5")
    # first half of the series...
    _run_pair(paths, mp_out, _free_port(), "-t", "0:0.25")
    with h5py.File(mp_out, "r") as f:
        n_first = f["solution/value"].shape[0]
    assert 0 < n_first < len(times)
    # ...then resume across processes: process 0 reads, broadcasts
    outs = _run_pair(paths, mp_out, _free_port(), "--resume")
    assert outs[0].count("Processed in:") == len(times) - n_first
    with h5py.File(mp_out, "r") as f:
        assert f["solution/value"].shape[0] == len(times)


def test_two_process_parallel_read_matches_serialized(world, tmp_path):
    """--parallel_read (all hosts read their stripes at once, the
    reference's arguments.cpp:164-167) must produce the same output as
    the default barrier-serialized round-robin ingest (main.cpp:78-86) —
    ingest order cannot influence the solve."""
    paths, H, f_true, times, scales = world

    ser_out = str(tmp_path / "mp_ser.h5")
    _run_pair(paths, ser_out, _free_port())

    par_out = str(tmp_path / "mp_par.h5")
    _run_pair(paths, par_out, _free_port(), "--parallel_read")

    with h5py.File(ser_out, "r") as fs, h5py.File(par_out, "r") as fp:
        np.testing.assert_array_equal(
            fp["solution/value"][:], fs["solution/value"][:]
        )
        np.testing.assert_array_equal(
            fp["solution/status"][:], fs["solution/status"][:]
        )
