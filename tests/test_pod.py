"""Pod fault-tolerance matrix (docs/RESILIENCE.md §11; `make pod`).

Units: solve-checkpoint encode/decode round trips (bit-exact arrays,
extension dtypes), store compaction and torn-tail/CRC fallback, the
pod-wide consistency intersection, file-mode barrier payload exchange,
dead-peer detection with per-host attribution, liveness-extended waits,
the stop-agreement exchange, and the pod-qualified `site@i` fault
grammar.

End-to-end: checkpoint-off byte-identity (the tentpole's zero-cost
contract), a single-process SIGKILL inside the held-open pre-append
window resumed from the previous durable stride, and the seeded
`sartsolve chaos --pod 2` campaign on the bounded CI seed pair — one
mid-checkpoint kill (torn record: the pod falls back one stride) and
one mid-stride-barrier kill — judged on survivor exit-3 attribution,
byte-identity and stride-progress monotonicity.

Plus the drift guard: the fault-site table documented in
docs/RESILIENCE.md §1 must list exactly `faults.FAULT_SITES`.
"""

import json
import os
import re
import signal
import subprocess
import sys
import threading
import time

import h5py
import numpy as np
import pytest

import fixtures as fx

from sartsolver_tpu.obs import metrics
from sartsolver_tpu.parallel import multihost as mh
from sartsolver_tpu.resilience import faults, podckpt
from sartsolver_tpu.resilience.chaos import PodSchedule, chaos_main

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
_DOCS = os.path.join(_REPO, "docs")

# the bounded CI seed pair (make pod): seed 0 kills inside the held-open
# checkpoint append (torn record -> one-stride fallback), seed 3 at a
# stride rendezvous; SART_POD_SEEDS widens it
POD_SEEDS = os.environ.get("SART_POD_SEEDS", "0,3")

N_FRAMES = 10


# ---------------------------------------------------------------------------
# checkpoint payload round trip
# ---------------------------------------------------------------------------

def test_encode_decode_roundtrip_bit_exact():
    rng = np.random.default_rng(7)
    state = {
        "f": rng.standard_normal((3, 5)),                   # float64
        "w": rng.standard_normal((4,)).astype(np.float32),
        "iters": np.arange(6, dtype=np.int32).reshape(2, 3),
        "mask": np.array([True, False, True]),
        "scalar": np.float64(0.1 + 0.2),
        "count": np.int64(41),
        "nested": {"lanes": [np.arange(3), {"tk": 1.25}], "tag": "s"},
        "empty": np.zeros((0, 2)),
        "plain": [1, "two", None, 3.5],
    }
    back = podckpt.decode_state(
        json.loads(json.dumps(podckpt.encode_state(state)))
    )
    for key in ("f", "w", "iters", "mask", "empty"):
        assert back[key].dtype == state[key].dtype
        assert np.array_equal(back[key], state[key])
    assert back["scalar"] == state["scalar"]  # exact: raw repr via item()
    assert back["count"] == 41
    assert np.array_equal(back["nested"]["lanes"][0], np.arange(3))
    assert back["nested"]["lanes"][1]["tk"] == 1.25
    assert back["plain"] == [1, "two", None, 3.5]


def test_encode_decode_extension_dtype():
    """bfloat16 (an ml_dtypes extension dtype whose .str does not
    round-trip through np.dtype) survives via its registered name."""
    jnp = pytest.importorskip("jax.numpy")
    arr = np.asarray([1.5, -2.25, 3.0], dtype=jnp.bfloat16)
    back = podckpt.decode_state(
        json.loads(json.dumps(podckpt.encode_state(arr)))
    )
    assert back.dtype == arr.dtype
    assert np.array_equal(back.view(np.uint16), arr.view(np.uint16))


def test_decoded_arrays_writable():
    back = podckpt.decode_state(podckpt.encode_state(np.arange(4)))
    back[0] = 99  # restore paths mutate lane bookkeeping in place
    assert back[0] == 99


# ---------------------------------------------------------------------------
# per-host store: save/load, compaction, torn tail, CRC
# ---------------------------------------------------------------------------

def _state(serial):
    return {"serial_echo": serial, "f": np.full((2, 2), float(serial))}


def test_store_save_load_and_compaction(tmp_path):
    store = podckpt.SolveCheckpointStore(str(tmp_path / "ck"))
    for serial in range(1, 7):
        store.save(serial, _state(serial))
    # compacted on every save: only the newest KEEP_RECORDS survive
    assert store.serials() == [4, 5, 6]
    with open(store.path) as f:
        assert len([ln for ln in f if ln.strip()]) == podckpt.KEEP_RECORDS
    snap = store.load(5)
    assert snap["serial_echo"] == 5
    assert np.array_equal(snap["f"], np.full((2, 2), 5.0))
    assert store.load(1) is None  # rotated out


def test_store_torn_tail_falls_back(tmp_path):
    store = podckpt.SolveCheckpointStore(str(tmp_path / "ck"))
    store.save(1, _state(1))
    store.save(2, _state(2))
    with open(store.path, "a") as f:
        f.write('{"v": 1, "serial": 3, "crc": 123, "state": {"tr')
    assert store.serials() == [1, 2]  # torn append invisible
    assert store.load(3) is None


@pytest.mark.parametrize("step", [1, 7, 23])
def test_store_torn_tail_property(tmp_path, step):
    """Truncating the file at ANY byte inside the last record always
    falls back to the previous serial — no cut point yields a wrong or
    extra record (the journal torn-tail semantic)."""
    base = str(tmp_path / "ck")
    store = podckpt.SolveCheckpointStore(base)
    store.save(1, _state(1))
    store.save(2, _state(2))
    with open(store.path, "rb") as f:
        blob = f.read()
    second = blob.index(b"\n") + 1  # first byte of record 2
    for cut in range(second, len(blob), step):
        with open(store.path, "wb") as f:
            f.write(blob[:cut])
        got = store.serials()
        if cut == len(blob) - 1:  # only the newline missing: still valid
            assert got in ([1], [1, 2])
        else:
            assert got == [1], (cut, got)
    with open(store.path, "wb") as f:
        f.write(blob)
    assert store.serials() == [1, 2]


def test_store_crc_rejects_tampered_state(tmp_path):
    store = podckpt.SolveCheckpointStore(str(tmp_path / "ck"))
    store.save(1, _state(1))
    store.save(2, _state(2))
    with open(store.path) as f:
        lines = f.readlines()
    # flip a byte of record 2's payload: the header CRC no longer matches
    lines[-1] = lines[-1].replace(
        '"state": {', '"state": {"__rot__": 1, ', 1
    )
    with open(store.path, "w") as f:
        f.writelines(lines)
    assert store.serials() == [1]


def test_host_path_layout():
    assert podckpt.host_path("base", 0, 1) == "base"
    assert podckpt.host_path("base", 1, 4) == "base.h1of4.jsonl"


def test_newest_consistent_serial(tmp_path):
    base = str(tmp_path / "pod.ck")
    h0 = podckpt.SolveCheckpointStore(base, 0, 2)
    h1 = podckpt.SolveCheckpointStore(base, 1, 2)
    for serial in (1, 2, 3):
        h0.save(serial, _state(serial))
    h1.save(1, _state(1))
    h1.save(2, _state(2))
    # h1 died before appending serial 3: the pod falls back one stride
    assert podckpt.newest_consistent_serial(base, 2) == 2
    # a torn tail on h1's newest drops it from the intersection too
    with open(h1.path, "rb+") as f:
        blob = f.read()
        f.seek(0)
        f.truncate()
        f.write(blob[:-10])
    assert podckpt.newest_consistent_serial(base, 2) == 1
    # a host with no file at all: nothing is consistent
    assert podckpt.newest_consistent_serial(base, 3) is None
    # single-process pods read the plain base path
    solo = podckpt.SolveCheckpointStore(base)
    solo.save(9, _state(9))
    assert podckpt.newest_consistent_serial(base, 1) == 9


def test_store_counts_writes():
    before = metrics.get_registry().counter("solve_ckpt_written_total").value
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        podckpt.SolveCheckpointStore(os.path.join(td, "ck")).save(
            1, _state(1)
        )
    after = metrics.get_registry().counter("solve_ckpt_written_total").value
    assert after == before + 1


# ---------------------------------------------------------------------------
# pod identity + file-mode barriers
# ---------------------------------------------------------------------------

def test_pod_identity_env_forms(monkeypatch):
    monkeypatch.setenv("SART_POD_PROCESS", "1/3")
    assert mh.pod_identity() == (1, 3)
    monkeypatch.setenv("SART_POD_PROCESS", "2")  # bare index: count 1
    assert mh.pod_identity() == (2, 1)
    monkeypatch.setenv("SART_POD_PROCESS", "x/y")  # malformed: runtime
    assert mh.pod_identity() == (0, 1)
    monkeypatch.delenv("SART_POD_PROCESS")
    assert mh.pod_identity() == (0, 1)


def test_barrier_timeout_env(monkeypatch, capsys):
    monkeypatch.delenv("SART_POD_BARRIER_TIMEOUT", raising=False)
    assert mh.barrier_timeout() == 300.0
    monkeypatch.setenv("SART_POD_BARRIER_TIMEOUT", "12.5")
    assert mh.barrier_timeout() == 12.5
    monkeypatch.setenv("SART_POD_BARRIER_TIMEOUT", "0")
    assert mh.barrier_timeout() == 0.0  # deadline disabled
    monkeypatch.setenv("SART_POD_BARRIER_TIMEOUT", "soon")
    assert mh.barrier_timeout() == 300.0  # malformed: loud default
    assert "SART_POD_BARRIER_TIMEOUT" in capsys.readouterr().err


def test_file_barrier_exchanges_payloads(tmp_path):
    bdir = str(tmp_path)
    rows = [None, None]

    def arrive(k):
        rows[k] = mh._file_barrier(bdir, "b.one", k, 2, {"host": k}, 30)

    threads = [threading.Thread(target=arrive, args=(k,))
               for k in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert rows[0] == rows[1] == [{"host": 0}, {"host": 1}]


def test_file_barrier_names_dead_host(tmp_path):
    """A peer that never arrived and has no (or a stale) liveness beacon
    is declared dead at the deadline, with per-host attribution, and the
    timeout counter burns."""
    before = metrics.get_registry().counter(
        "pod_barrier_timeouts_total").value
    start = time.monotonic()
    with pytest.raises(mh.PodBarrierTimeout) as err:
        mh._file_barrier(str(tmp_path), "b.dead", 0, 3, None, 0.6)
    elapsed = time.monotonic() - start
    assert err.value.missing == [1, 2]
    assert "h1, h2" in str(err.value)
    assert "b.dead" in str(err.value)
    assert 0.5 <= elapsed < 5.0  # the deadline, not the 4x hard cap
    after = metrics.get_registry().counter(
        "pod_barrier_timeouts_total").value
    assert after == before + 1


def test_file_barrier_fresh_beacon_extends_wait(tmp_path):
    """A missing peer whose liveness beacon stays fresh (alive but slow)
    extends the wait past the deadline; once the beacon goes stale the
    barrier still gives up — bounded by the 4x hard cap."""
    bdir = str(tmp_path)
    stop = time.monotonic() + 0.9

    def beacon():
        while time.monotonic() < stop:
            mh._touch_alive(bdir, 1)
            time.sleep(0.1)

    t = threading.Thread(target=beacon, daemon=True)
    t.start()
    start = time.monotonic()
    with pytest.raises(mh.PodBarrierTimeout) as err:
        mh._file_barrier(bdir, "b.slow", 0, 2, None, 0.4)
    elapsed = time.monotonic() - start
    t.join(timeout=10)
    assert err.value.missing == [1]
    assert elapsed >= 0.8  # waited past the 0.4s deadline on liveness
    assert elapsed < 4 * 0.4 + 2.0


def test_file_barrier_torn_payload_is_none_row(tmp_path):
    bdir = str(tmp_path)
    with open(os.path.join(bdir, "b.torn.h1.json"), "w") as f:
        f.write('{"half')  # peer arrived, payload torn: benign
    rows = mh._file_barrier(bdir, "b.torn", 0, 2, {"ok": 1}, 5)
    assert rows == [{"ok": 1}, None]


def test_pod_barrier_single_process_no_io(monkeypatch):
    monkeypatch.delenv("SART_POD_PROCESS", raising=False)
    assert mh.pod_barrier("solo", payload=7) == [7]


def test_pod_barrier_no_seam_degrades_local(monkeypatch):
    """Identity claims peers but no coordination seam exists (env typo:
    SART_POD_PROCESS without a barrier dir on a single-process runtime):
    degrade to a local answer instead of wedging."""
    monkeypatch.setenv("SART_POD_PROCESS", "0/2")
    monkeypatch.delenv("SART_POD_BARRIER_DIR", raising=False)
    assert mh.pod_barrier("degraded", payload=5) == [5, None]


def test_agree_stop_file_mode(monkeypatch, tmp_path):
    bdir = str(tmp_path)
    monkeypatch.setenv("SART_POD_PROCESS", "0/2")
    monkeypatch.setenv("SART_POD_BARRIER_DIR", bdir)
    monkeypatch.setenv("SART_POD_BARRIER_TIMEOUT", "10")
    monkeypatch.setattr(mh, "_stop_seq", 0)
    # peer h1 votes stop at the first boundary exchange
    with open(os.path.join(bdir, "agree_stop.1.h1.json"), "w") as f:
        f.write("1")
    assert mh.agree_stop(False) is True
    # next boundary: neither stops — sequence numbering keeps the
    # exchanges distinct within one incarnation
    with open(os.path.join(bdir, "agree_stop.2.h1.json"), "w") as f:
        f.write("0")
    assert mh.agree_stop(False) is False


# ---------------------------------------------------------------------------
# pod-qualified fault grammar
# ---------------------------------------------------------------------------

def test_fault_pod_qualifier_arms_only_target(monkeypatch):
    monkeypatch.setenv("SART_POD_PROCESS", "1/2")
    armed = faults.parse_fault_spec("io.flush@1:io:1")
    assert set(armed) == {"io.flush"}  # keyed by the bare site
    assert faults.parse_fault_spec("io.flush@0:io:1") == {}
    monkeypatch.delenv("SART_POD_PROCESS")
    assert set(faults.parse_fault_spec("io.flush@0:io:1")) == {"io.flush"}


def test_fault_pod_qualifier_validates_on_every_host(monkeypatch):
    monkeypatch.setenv("SART_POD_PROCESS", "0/2")
    # a typo'd entry for ANOTHER host still fails loudly here
    with pytest.raises(ValueError, match="Unknown fault site"):
        faults.parse_fault_spec("io.flsh@1:io:1")
    with pytest.raises(ValueError, match="pod qualifier"):
        faults.parse_fault_spec("io.flush@x:io:1")
    with pytest.raises(ValueError, match=">= 0"):
        faults.parse_fault_spec("io.flush@-1:io:1")


def test_pod_schedule_deterministic():
    for seed in range(8):
        a, b = PodSchedule(seed), PodSchedule(seed)
        assert a.describe() == b.describe()
        assert a.victim in (0, 1)
        assert a.window in PodSchedule.WINDOWS
    # both kill windows are reachable across a small seed range
    assert {PodSchedule(s).window for s in range(8)} == {"stride", "ckpt"}


# ---------------------------------------------------------------------------
# documentation drift guard
# ---------------------------------------------------------------------------

def test_resilience_doc_site_table_matches_registry():
    """docs/RESILIENCE.md §1's site table is the operator's SART_FAULT
    reference — it must list exactly the registry's sites (PRs keep
    adding seams; this is the drift alarm)."""
    text = open(os.path.join(_DOCS, "RESILIENCE.md")).read()
    section = text.split("## 1. Fault injection")[1].split("\n## ")[0]
    documented = set(re.findall(r"^\| `([a-z0-9_.]+)` \|", section,
                                flags=re.M))
    assert documented == set(faults.FAULT_SITES), (
        f"undocumented sites: {sorted(set(faults.FAULT_SITES) - documented)}; "
        f"stale doc rows: {sorted(documented - set(faults.FAULT_SITES))}"
    )


def test_manual_documents_pod_surface():
    """The MANUAL's flag/env tables carry the pod fault-tolerance
    surface: the checkpoint flag, the barrier deadline, and the
    pod-qualified SART_FAULT grammar."""
    text = open(os.path.join(_DOCS, "MANUAL.md")).read()
    for needle in ("--solve_ckpt_stride", "SART_POD_BARRIER_TIMEOUT",
                   "site[@i]", "SART_SOLVE_CKPT_FILE",
                   "SART_POD_BARRIER_DIR"):
        assert needle in text, f"MANUAL.md lost {needle!r}"


# ---------------------------------------------------------------------------
# end-to-end: subprocess drills
# ---------------------------------------------------------------------------

def _env(extra=None):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    for key in ("SART_FAULT", "SART_POD_PROCESS", "SART_POD_BARRIER_DIR",
                "SART_TEST_POD_MARKERS", "SART_TEST_SOLVE_CKPT_DELAY",
                "SART_SOLVE_CKPT_FILE"):
        env.pop(key, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PYTHONUNBUFFERED"] = "1"
    env.update(extra or {})
    return env


def _cli_cmd(paths, outfile, *extra):
    # the scheduler path (--batch_frames > 1 + --no_guess) is the
    # checkpointable one; fixed iterations keep every run bit-stable
    return [
        sys.executable, "-m", "sartsolver_tpu.cli", "-o", outfile,
        paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
        paths["img_a"], paths["img_b"],
        "--use_cpu", "-m", "40", "-c", "1e-12",
        "-l", paths["laplacian"], "-b", "0.001",
        "--max_cached_solutions", "1", "--no_guess",
        "--batch_frames", "4",
        *extra,
    ]


def _read_solution(path):
    with h5py.File(path, "r") as f:
        data = {k: np.array(f["solution"][k]) for k in f["solution"]}
        data["completed"] = int(f["solution"].attrs["completed"])
    return data


def _assert_identical(got, want, what):
    assert got["completed"] == want["completed"] == N_FRAMES, what
    for key in sorted(want):
        if key == "completed":
            continue
        assert np.array_equal(got[key], want[key]), f"{what}:{key}"


@pytest.fixture(scope="module")
def pod_world(tmp_path_factory):
    """Synthetic world + an undisturbed checkpoint-OFF reference run
    (which also warms the persistent compile cache for every drill)."""
    td = tmp_path_factory.mktemp("pod_world")
    paths, *_ = fx.write_world(td, with_laplacian=True, n_frames=N_FRAMES)
    ref = str(td / "reference.h5")
    proc = subprocess.run(_cli_cmd(paths, ref), env=_env(),
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-4000:]
    return paths, _read_solution(ref), td


def test_checkpoint_off_byte_identity(pod_world):
    """--solve_ckpt_stride is host-side only: the solution file of a
    checkpointing run equals the checkpoint-off reference byte for byte,
    and the sidecar lands where SART_SOLVE_CKPT_FILE points."""
    paths, want, td = pod_world
    out = str(td / "ckpt_on.h5")
    sidecar = str(td / "custom.solveckpt")
    proc = subprocess.run(
        _cli_cmd(paths, out, "--solve_ckpt_stride", "2"),
        env=_env({"SART_SOLVE_CKPT_FILE": sidecar}),
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    _assert_identical(_read_solution(out), want, "ckpt-on")
    store = podckpt.SolveCheckpointStore(sidecar)
    assert store.serials(), "no solve checkpoints were written"
    assert len(store.serials()) <= podckpt.KEEP_RECORDS


def test_solve_ckpt_stride_validation(pod_world):
    paths, _want, td = pod_world
    out = str(td / "invalid.h5")
    # checkpointing rides the continuous-batching scheduler only
    proc = subprocess.run(
        _cli_cmd(paths, out, "--solve_ckpt_stride", "2",
                 "--no_continuous_batching"),
        env=_env(), capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    assert "solve_ckpt_stride" in proc.stderr


def test_solo_kill_in_ckpt_window_then_resume(pod_world):
    """Single-process leg: SIGKILL inside the held-open pre-append
    window of stride serial 2 — the record is NOT durable, --resume
    restores serial 1 (the previous durable stride), completes
    byte-identically, and the artifact accounts exactly one resume."""
    paths, want, td = pod_world
    out = str(td / "solo_killed.h5")
    env = _env({"SART_TEST_SOLVE_CKPT_DELAY": "0.6",
                "SART_TEST_POD_MARKERS": "1"})
    # stride 1: serial 1 is durable before the serial-2 append the kill
    # lands in — the resume must restore 1, the one-append fallback
    proc = subprocess.Popen(
        _cli_cmd(paths, out, "--solve_ckpt_stride", "1"), env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True,
    )
    guard = threading.Timer(300, proc.kill)
    guard.start()
    try:
        for line in proc.stderr:
            if line.strip() == "SART_SOLVE_CKPT_POINT pre-append serial=2":
                proc.kill()
                break
        else:
            raise AssertionError("run ended before the serial-2 append")
        proc.stderr.read()
    finally:
        guard.cancel()
        proc.wait(timeout=60)
    assert proc.returncode == -signal.SIGKILL

    art = str(td / "solo_resume.jsonl")
    done = subprocess.run(
        _cli_cmd(paths, out, "--solve_ckpt_stride", "1", "--resume",
                 "--metrics_out", art),
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert done.returncode == 0, done.stderr[-4000:]
    _assert_identical(_read_solution(out), want, "solo-resume")
    resumed = re.findall(r"SART_POD_POINT resume serial=(\d+)",
                         done.stderr)
    assert resumed == ["1"], done.stderr[-2000:]
    # stride markers are pod-only (the stride_barrier closure needs a
    # pod seam); single-process progress shows up in the sidecar store:
    # the resumed run must have appended strides PAST the restored one.
    store = podckpt.SolveCheckpointStore(out + ".solveckpt", 0, 1)
    assert store.serials() and max(store.serials()) > 1

    from sartsolver_tpu.obs.cli import metrics_main

    assert metrics_main(["--check", art]) == 0
    counters = {}
    with open(art) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("type") == "metric" and rec.get("kind") == "counter":
                counters[rec["name"]] = rec["value"]
    assert counters.get("solve_ckpt_resumed_total") == 1
    assert counters.get("solve_ckpt_written_total", 0) >= 1


def test_pod_chaos_ci_seed_pair(pod_world, tmp_path, capsys,
                                monkeypatch):
    """`sartsolve chaos --pod 2` on the CI seed pair: seeded SIGKILL of
    one fake-pod host mid-checkpoint (seed 0) and mid-stride (seed 3),
    survivors exit 3 via the coordinated barrier deadline naming the
    dead host, the pod resumes from the newest consistent checkpoint
    without repeating a stride, outputs byte-identical."""
    paths, _want, _td = pod_world
    # short deadline: the campaign's worker env copies ours (setdefault)
    monkeypatch.setenv("SART_POD_BARRIER_TIMEOUT", "10")
    report_path = str(tmp_path / "report.json")
    rc = chaos_main([
        "--engine_dir", str(tmp_path / "camp"),
        "--pod", "2", "--seeds", POD_SEEDS, "--timeout", "280",
        "--report", report_path, "--",
        paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
        paths["img_a"], paths["img_b"],
        "--use_cpu", "-m", "40", "-c", "1e-12",
        "-l", paths["laplacian"], "-b", "0.001",
        "--max_cached_solutions", "1", "--no_guess",
        "--batch_frames", "4",
    ])
    out = capsys.readouterr().out
    assert rc == 0, out
    report = json.load(open(report_path))
    assert report["verdict"] == "ok"
    assert len(report["passes"]) == len(POD_SEEDS.split(","))
    for verdict in report["passes"]:
        assert verdict["verdict"] == "ok"
        assert verdict["hosts"] == 2
        assert verdict["resumed_serial"] >= 1
        if verdict["window"].startswith("ckpt"):
            # killed mid-append: that serial never became durable
            assert verdict["resumed_serial"] < verdict["killed_serial"]


def test_pod_chaos_cli_usage_errors(capsys):
    assert chaos_main(["--engine_dir", "/tmp/x", "--pod", "1",
                       "--", "f.h5"]) == 1
    assert "--pod" in capsys.readouterr().err
    assert chaos_main(["--engine_dir", "/tmp/x", "--pod", "2",
                       "--fleet", "2", "--", "f.h5"]) == 1
    assert "pick one" in capsys.readouterr().err
