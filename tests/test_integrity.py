"""Numerical-integrity layer (docs/RESILIENCE.md §8): `make sdc`.

The drill matrix proves every leg of the SDC contract:

- **clean, no false positives** — integrity-on solves across dtypes /
  shapes / seeds / both solver variants never trip the ABFT check and
  produce bit-identical solutions to integrity-off runs (hypothesis);
- **guaranteed detection** — any single injected perturbation whose
  induced checksum residual exceeds the dtype tolerance is flagged
  SDC_DETECTED the same solve (hypothesis, margin-scaled flips);
- **corrupt-fault drills** — the `corrupt` fault kind at the ingest
  stripe (digest re-read, clean output), the device-resident buffer
  (recompute → FAILED → quarantine exit 3) and the scheduler lane path,
  end-to-end through the real CLI, exactly like `oom`/`hang` drill their
  layers;
- **escalation policy** — recompute-once accounting, the terminal-frame
  abort threshold, resident re-audit and post-upload verification;
- **satellites** — per-frame solution checksums verified on --resume,
  the non-finite-pixel counter, multi-site fault specs.
"""

import json
import os

import h5py
import numpy as np
import pytest

import fixtures as fx
from sartsolver_tpu.cli import main
from sartsolver_tpu.config import SDC_DETECTED, SartInputError, SolverOptions
from sartsolver_tpu.models.sart import (
    make_problem,
    prepare_measurement,
    solve_normalized_batch,
)
from sartsolver_tpu.resilience import faults, integrity
from sartsolver_tpu.resilience.failures import (
    EXIT_INFRASTRUCTURE,
    EXIT_PARTIAL,
    FRAME_FAILED,
)
from sartsolver_tpu.resilience.retry import reset_retry_stats

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_slate(monkeypatch):
    """No armed faults, fresh retry stats, fast backoff, and the
    integrity switch back to its env default after every test."""
    monkeypatch.setenv("SART_RETRY_BASE_DELAY", "0.001")
    monkeypatch.setenv("SART_RETRY_MAX_DELAY", "0.002")
    monkeypatch.delenv("SART_FAULT", raising=False)
    monkeypatch.delenv("SART_INTEGRITY", raising=False)
    faults.clear_faults()
    reset_retry_stats()
    yield
    faults.clear_faults()
    reset_retry_stats()
    integrity._state["enabled"] = None


@pytest.fixture
def world(tmp_path):
    return fx.write_world(tmp_path)


def run_cli(paths, *extra):
    return main([
        "-o", paths["output"],
        paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
        paths["img_a"], paths["img_b"],
        "-m", "100",
        *extra,
    ])


def _read_out(paths):
    with h5py.File(paths["output"], "r") as f:
        return (f["solution/value"][:], f["solution/status"][:],
                f["solution/iterations"][:])


def _problem(seed, P, V, opts):
    rng = np.random.default_rng(seed)
    H = rng.uniform(0.1, 1.0, (P, V)).astype(np.float32)
    f_true = rng.uniform(0.5, 2.0, V)
    g = H @ f_true
    g64, msq, _norm = prepare_measurement(g, opts)
    problem = make_problem(H, opts=opts)
    return H, problem, jnp.asarray(g64, jnp.float32)[None, :], msq


def _solve(problem, g_n, msq, opts):
    return solve_normalized_batch(
        problem, g_n, jnp.asarray([msq], jnp.float32),
        jnp.zeros((1, problem.rtm.shape[1]), jnp.float32),
        opts=opts, axis_name=None, voxel_axis=None, use_guess=True,
    )


# ---------------------------------------------------------------------------
# ABFT tolerance properties (hypothesis)
# ---------------------------------------------------------------------------

try:
    from hypothesis import given, settings, strategies as st

    HAVE_HYP = True
    # each example compiles fresh XLA programs (distinct shapes) — keep
    # the counts small so the suite wall-time stays flat (SET_JIT
    # convention of tests/test_properties.py)
    SET_JIT = settings(max_examples=12, deadline=None, derandomize=True)
except ImportError:  # pragma: no cover - optional extra
    HAVE_HYP = False


def test_abft_tolerance_shape():
    """Tolerance grows with extent and loosens for lossy storage."""
    t32 = integrity.abft_tolerance("float32", None, 64, 512)
    assert 0 < t32 < 1e-2
    assert integrity.abft_tolerance("float32", None, 640, 5120) > t32
    assert integrity.abft_tolerance("float32", "bfloat16", 64, 512) > t32
    assert integrity.abft_tolerance("float32", "int8", 64, 512) > t32
    assert (integrity.abft_tolerance("float64", None, 64, 512)
            < t32)  # fp64 compute tightens the band


if HAVE_HYP:

    @SET_JIT
    @given(
        st.integers(0, 2**31 - 1),
        st.sampled_from([(16, 24), (24, 40), (8, 56)]),
        st.sampled_from([None, "bfloat16"]),
        st.booleans(),  # logarithmic
    )
    def test_abft_clean_runs_never_trip(seed, shape, rtm_dtype, log):
        """Zero false positives: an integrity-on solve of a clean random
        problem never reports SDC and matches the integrity-off solve
        bit for bit (the check is a pure observer)."""
        P, V = shape
        base = dict(max_iterations=40, logarithmic=log,
                    rtm_dtype=rtm_dtype, fused_sweep="off")
        off = SolverOptions(**base)
        on = SolverOptions(**base, integrity=True)
        _H, problem, g_n, msq = _problem(seed, P, V, off)
        r_off = _solve(problem, g_n, msq, off)
        r_on = _solve(problem, g_n, msq, on)
        assert int(r_on.status[0]) != SDC_DETECTED
        assert int(r_on.status[0]) == int(r_off.status[0])
        np.testing.assert_array_equal(
            np.asarray(r_on.solution), np.asarray(r_off.solution)
        )

    @SET_JIT
    @given(
        st.integers(0, 2**31 - 1),
        st.sampled_from([(16, 24), (24, 40)]),
        st.integers(0, 1000),  # perturbed column (mod V)
        st.floats(8.0, 1e4),  # margin above the tolerance magnitude
    )
    def test_abft_detects_flip_above_tolerance(seed, shape, col, margin):
        """Guaranteed detection: perturb ONE matrix entry by a delta whose
        induced checksum residual exceeds the dtype tolerance (scaled by
        `margin`), leaving the uploaded ray stats stale — the solve must
        flag SDC_DETECTED and keep its solution finite (the last
        consistent iterate)."""
        P, V = shape
        j = col % V
        opts_off = SolverOptions(max_iterations=20, fused_sweep="off")
        opts_on = SolverOptions(max_iterations=20, fused_sweep="off",
                                integrity=True)
        H, problem, g_n, msq = _problem(seed, P, V, opts_off)
        # probe the clean solve's scale: iterate magnitudes and the
        # checksum reference both come from the fitted sums
        probe = _solve(problem, g_n, msq,
                       SolverOptions(max_iterations=1, fused_sweep="off"))
        f1 = np.asarray(probe.solution)[0]
        ref = float(np.sum(H.astype(np.float64) @ f1)) + 1.0
        tol = integrity.abft_tolerance("float32", None, P, V)
        # delta * f_j is the residual a stale rho sees; f is bounded below
        # by the update's structure on this all-positive problem
        f_floor = max(float(f1[j]), 1e-3)
        delta = margin * tol * ref / f_floor
        H2 = H.copy()
        H2[0, j] += np.float32(delta)
        corrupted = problem._replace(rtm=jnp.asarray(H2))
        res = _solve(corrupted, g_n, msq, opts_on)
        assert int(res.status[0]) == SDC_DETECTED
        assert np.isfinite(np.asarray(res.solution)).all()


# ---------------------------------------------------------------------------
# fault kinds: corrupt / take_corrupt / multi-site specs
# ---------------------------------------------------------------------------

def test_corrupt_kind_finite_and_dtype_preserving():
    faults.inject(faults.SITE_RTM_INGEST, "corrupt", count=1)
    arr = np.full((3, 2), 2.0, np.float32)
    out = faults.corrupt(faults.SITE_RTM_INGEST, arr)
    assert out.dtype == np.float32 and out is not arr
    assert np.isfinite(out).all()
    assert out.reshape(-1)[0] == np.float32(2.0 * 256 + 1)
    assert (out.reshape(-1)[1:] == 2.0).all()
    # capped after one trip: identity, no copy
    assert faults.corrupt(faults.SITE_RTM_INGEST, arr) is arr
    # corrupt faults never raise through fire()
    faults.clear_faults()
    faults.inject(faults.SITE_RTM_INGEST, "corrupt", count=5)
    faults.fire(faults.SITE_RTM_INGEST)


def test_take_corrupt_only_for_corrupt_kind():
    assert not faults.take_corrupt(faults.SITE_DEVICE_BUFFER)
    faults.inject(faults.SITE_DEVICE_BUFFER, "corrupt", count=1)
    assert faults.take_corrupt(faults.SITE_DEVICE_BUFFER)
    assert not faults.take_corrupt(faults.SITE_DEVICE_BUFFER)  # capped
    faults.clear_faults()
    faults.inject(faults.SITE_DEVICE_BUFFER, "error")
    assert not faults.take_corrupt(faults.SITE_DEVICE_BUFFER)


def test_multi_site_spec_arms_ingest_and_solve_in_one_run():
    """One SART_FAULT string arms independent drills at several sites."""
    armed = faults.parse_fault_spec(
        "hdf5.rtm_ingest:corrupt:1:1, device.buffer:corrupt:1:2, "
        "solve.dispatch:error:0.5:3"
    )
    assert set(armed) == {"hdf5.rtm_ingest", "device.buffer",
                          "solve.dispatch"}
    assert armed["device.buffer"].kind == "corrupt"
    assert armed["device.buffer"].count == 2


def test_duplicate_site_spec_rejected():
    with pytest.raises(ValueError, match="armed twice"):
        faults.parse_fault_spec("io.flush:io:1, io.flush:error:1")


# ---------------------------------------------------------------------------
# escalation policy + resident verification units
# ---------------------------------------------------------------------------

def test_sdc_escalation_threshold_and_events():
    events = []
    policy = integrity.SdcEscalation(on_event=events.append,
                                     abort_threshold=2)
    policy.detected()
    policy.note_recompute()
    policy.record_terminal(0.1)  # below threshold: no raise
    with pytest.raises(integrity.PersistentCorruptionError):
        policy.record_terminal(0.2)
    assert any("quarantine" in e for e in events)
    # the terminal frame times travel in the event — the operator must
    # know which rows to distrust
    assert any("0.1" in e and "0.2" in e for e in events)


def test_sdc_escalation_resident_failure_raises_immediately():
    policy = integrity.SdcEscalation(abort_threshold=99)
    with pytest.raises(integrity.PersistentCorruptionError,
                       match="resident"):
        policy.resident_failure("re-audit mismatch")


def test_reaudit_detects_resident_corruption():
    from sartsolver_tpu.parallel.mesh import make_mesh
    from sartsolver_tpu.parallel.sharded import DistributedSARTSolver

    rng = np.random.default_rng(3)
    H = rng.uniform(0.1, 1.0, (16, 24)).astype(np.float32)
    opts = SolverOptions(max_iterations=10, fused_sweep="off",
                         integrity=True)
    solver = DistributedSARTSolver(H, opts=opts, mesh=make_mesh(1, 1))
    try:
        assert solver.reaudit_ray_stats() == []
        faults.inject(faults.SITE_DEVICE_BUFFER, "corrupt", count=1)
        solver._maybe_corrupt_resident()
        issues = solver.reaudit_ray_stats()
        assert issues and "ray_density" in "; ".join(issues)
    finally:
        solver.close()


def test_sparse_cache_population_verifies_against_second_read(monkeypatch):
    """The one-pass sparse ingest cache serves later stripe reads from
    memory, so the stripe-level double-read compare would digest the same
    buffer twice — the segment must instead be verified at
    cache-population time against a genuine second disk read. A loader
    whose two reads disagree raises StripeDigestError BEFORE the cache
    insert (so the ingest retry re-reads fresh), and the mismatch counter
    increments."""
    from sartsolver_tpu.io import raytransfer as rt
    from sartsolver_tpu.obs import metrics as obs_metrics

    pix = np.arange(6, dtype=np.int64)
    vox = np.arange(6, dtype=np.int64) % 4
    val = np.linspace(0.1, 0.6, 6).astype(np.float32)
    calls = {"n": 0}

    def flaky_loader(group, filename, sp, sv, nvoxel, dtype):
        calls["n"] += 1
        if calls["n"] == 2:  # the verification read of the first attempt
            bad = val.copy()
            bad[0] *= 256.0
            return pix.copy(), vox.copy(), bad
        return pix.copy(), vox.copy(), val.copy()

    monkeypatch.setattr(rt, "_load_sparse_segment", flaky_loader)
    integrity.configure(True)
    cache: dict = {}
    ctr = obs_metrics.get_registry().counter("stripe_digest_mismatch_total")
    before = ctr.value
    with pytest.raises(integrity.StripeDigestError, match="sparse"):
        rt._sparse_segment_window(None, "seg.h5", 0, 0, 4, np.float32,
                                  cache, None, None)
    assert ctr.value == before + 1
    assert not any(k != rt._CACHE_BYTES_KEY for k in cache)  # no insert
    # the retry's fresh attempt (reads 3+4 agree) populates and verifies
    (p, v, a), cached = rt._sparse_segment_window(
        None, "seg.h5", 0, 0, 4, np.float32, cache, None, None
    )
    np.testing.assert_array_equal(a, val)
    assert calls["n"] == 4
    # later stripe reads serve from the now-verified cache, no disk read
    (_, _, a2), cached2 = rt._sparse_segment_window(
        None, "seg.h5", 0, 0, 4, np.float32, cache, None, None
    )
    assert cached2 and calls["n"] == 4
    np.testing.assert_array_equal(a2, val)


def test_genuine_divergence_classifies_diverged_not_sdc():
    """Integrity AND the divergence guard armed, a genuinely diverging
    solve (explicit-Euler-unstable Laplacian weight): the non-finite
    checksum trips the ABFT compare vacuously, but that signature belongs
    to the guard — the frame must end DIVERGED via the rollback ladder,
    bit-identical to the guard-only run, never SDC_DETECTED (which would
    recompute deterministically and quarantine a healthy session)."""
    from sartsolver_tpu.config import DIVERGED
    from sartsolver_tpu.models.sart import solve
    from sartsolver_tpu.ops.laplacian import make_laplacian

    rng = np.random.default_rng(3)
    H = rng.uniform(0.1, 1.0, (16, 12)).astype(np.float32)
    g = H @ rng.uniform(0.5, 2.0, 12)
    V = H.shape[1]
    rows, cols, vals = [], [], []
    for i in range(V):
        rows.append(i); cols.append(i); vals.append(2.0)
        if i > 0:
            rows.append(i); cols.append(i - 1); vals.append(-1.0)
        if i < V - 1:
            rows.append(i); cols.append(i + 1); vals.append(-1.0)
    lap = make_laplacian(np.asarray(rows), np.asarray(cols),
                         np.asarray(vals, np.float32), dtype="float32")
    kw = dict(max_iterations=500, conv_tolerance=1e-6, beta_laplace=0.8,
              divergence_recovery=6, divergence_threshold=1e3)
    o_guard = SolverOptions(**kw)
    o_both = SolverOptions(integrity=True, **kw)
    r_guard = solve(make_problem(H, lap, opts=o_guard), g, opts=o_guard)
    r_both = solve(make_problem(H, lap, opts=o_both), g, opts=o_both)

    assert int(r_both.status) == DIVERGED
    assert int(r_both.status) == int(r_guard.status)
    assert int(r_both.iterations) == int(r_guard.iterations)
    np.testing.assert_array_equal(np.asarray(r_both.solution),
                                  np.asarray(r_guard.solution))


def test_ingest_stats_verify_and_tamper(world):
    """read_and_shard_rtm feeds the accumulator; the post-upload check
    passes on a clean ingest and flags a tampered accumulator."""
    paths, H, *_ = world
    from sartsolver_tpu.io import hdf5files as hf
    from sartsolver_tpu.parallel.mesh import make_mesh
    from sartsolver_tpu.parallel.multihost import read_and_shard_rtm
    from sartsolver_tpu.parallel.sharded import DistributedSARTSolver

    matrix_files, _ = hf.categorize_input_files(
        [paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
         paths["img_a"], paths["img_b"]]
    )
    sorted_matrix_files = hf.sort_rtm_files(matrix_files)
    npixel, nvoxel = hf.get_total_rtm_size(sorted_matrix_files)
    stats = integrity.IngestStats(npixel, nvoxel)
    mesh = make_mesh(1, 1)
    rtm = read_and_shard_rtm(
        sorted_matrix_files, "with_reflections", npixel, nvoxel, mesh,
        dtype="float32", ingest_stats=stats,
    )
    opts = SolverOptions(max_iterations=5, fused_sweep="off",
                         integrity=True)
    solver = DistributedSARTSolver(rtm, opts=opts, mesh=mesh,
                                   npixel=npixel, nvoxel=nvoxel)
    try:
        assert solver.verify_ray_stats(stats) == []
        stats.colsum[0] += 1.0  # a flipped staging byte would look so
        issues = solver.verify_ray_stats(stats)
        assert issues and "ray_density" in issues[0]
    finally:
        solver.close()


# ---------------------------------------------------------------------------
# CLI drill matrix (the `corrupt` fault kind end-to-end)
# ---------------------------------------------------------------------------

def test_cli_clean_integrity_run_identical(world):
    """Integrity on over a clean run: exit 0, zero detections, output
    bit-identical to the integrity-off run (the layer is an observer)."""
    paths, *_ = world
    assert run_cli(paths) == 0
    clean = _read_out(paths)
    metrics = paths["output"] + ".jsonl"
    assert run_cli(paths, "--integrity", "--metrics_out", metrics) == 0
    got = _read_out(paths)
    np.testing.assert_array_equal(got[0], clean[0])
    np.testing.assert_array_equal(got[1], clean[1])
    counters = {
        r["name"]: r["value"]
        for r in (json.loads(line) for line in open(metrics))
        if r.get("type") == "metric" and r.get("kind") == "counter"
    }
    # the three integrity counters are registered AND zero on clean runs
    assert counters.get("sdc_detected_total") == 0
    assert counters.get("integrity_recomputes_total") == 0
    assert counters.get("stripe_digest_mismatch_total") == 0


def test_cli_ingest_corrupt_detected_and_rereads(world, monkeypatch):
    """Drill leg 1 — ingest: a corrupted stripe read is caught by the
    digest re-read and retried clean; output identical to a clean run,
    exit 0. Without --integrity the same fault silently poisons the
    solutions — proving the detection is the integrity layer's."""
    paths, *_ = world
    assert run_cli(paths) == 0
    clean = _read_out(paths)

    metrics = paths["output"] + ".jsonl"
    monkeypatch.setenv("SART_FAULT", "hdf5.rtm_ingest:corrupt:1:1")
    faults.reset()
    assert run_cli(paths, "--integrity", "--metrics_out", metrics) == 0
    got = _read_out(paths)
    np.testing.assert_array_equal(got[0], clean[0])
    mismatches = [
        r["value"] for r in (json.loads(line) for line in open(metrics))
        if r.get("type") == "metric"
        and r.get("name") == "stripe_digest_mismatch_total"
    ]
    assert mismatches and mismatches[0] >= 1

    faults.reset()  # re-arm: fresh trip budget for the integrity-off leg
    rc = main([
        "-o", paths["output"],
        paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
        paths["img_a"], paths["img_b"], "-m", "100",
    ])
    assert rc == 0
    silent = _read_out(paths)
    assert not np.array_equal(silent[0], clean[0])


def test_cli_device_buffer_corrupt_quarantines(world, monkeypatch, capsys):
    """Drill leg 2 — resident buffer: a corrupted device-resident RTM
    trips the in-solve ABFT check; the recompute reproduces it, frames
    FAIL, and the default threshold quarantines the run with the
    infrastructure exit and a quarantine event."""
    paths, *_ = world
    metrics = paths["output"] + ".jsonl"
    monkeypatch.setenv("SART_FAULT", "device.buffer:corrupt:1:1")
    faults.reset()
    rc = run_cli(paths, "--integrity", "--metrics_out", metrics)
    assert rc == EXIT_INFRASTRUCTURE
    assert "Quarantined" in capsys.readouterr().err
    recs = [json.loads(line) for line in open(metrics)]
    events = [r["message"] for r in recs if r.get("type") == "event"]
    assert any("quarantine" in e for e in events)
    detected = [r["value"] for r in recs
                if r.get("name") == "sdc_detected_total"]
    recomputes = [r["value"] for r in recs
                  if r.get("name") == "integrity_recomputes_total"]
    assert detected and detected[0] >= 1
    assert recomputes and recomputes[0] >= 1


def test_cli_device_buffer_corrupt_isolated_at_high_threshold(
    world, monkeypatch
):
    """Same resident corruption with the abort threshold raised: every
    frame fails through per-frame isolation (FAILED rows), the run
    completes with the partial exit — the documented middle rung."""
    paths, *_ = world
    monkeypatch.setenv("SART_FAULT", "device.buffer:corrupt:1:1")
    monkeypatch.setenv("SART_SDC_ABORT_THRESHOLD", "99")
    faults.reset()
    rc = run_cli(paths, "--integrity")
    assert rc == EXIT_PARTIAL
    _, status, _ = _read_out(paths)
    assert (status == FRAME_FAILED).all()


def test_cli_sched_lane_corrupt_quarantines(world, monkeypatch, capsys):
    """Drill leg 3 — scheduler lanes: the continuous-batching path
    escalates SDC lanes (requeue-once, then FAILED) and the threshold
    quarantines, same contract as the grouped loops."""
    paths, *_ = world
    monkeypatch.setenv("SART_FAULT", "device.buffer:corrupt:1:1")
    faults.reset()
    rc = run_cli(paths, "--integrity", "--no_guess",
                 "--batch_frames", "2")
    assert rc == EXIT_INFRASTRUCTURE
    assert "Quarantined" in capsys.readouterr().err


def test_cli_integrity_off_leaves_programs_untouched(world):
    """The acceptance identity: with the layer off (default) nothing in
    the pipeline changes — rerunning the classic matrix produces the
    same bytes whether the build carries the integrity code or not is
    pinned by goldens; here: off-run output equals pre-layer output
    semantics (status/iterations identical across two off runs)."""
    paths, *_ = world
    assert run_cli(paths) == 0
    first = _read_out(paths)
    assert run_cli(paths) == 0
    second = _read_out(paths)
    np.testing.assert_array_equal(first[0], second[0])
    np.testing.assert_array_equal(first[2], second[2])


# ---------------------------------------------------------------------------
# satellites: solution checksums, nonfinite counter
# ---------------------------------------------------------------------------

def test_solution_checksum_roundtrip_and_corruption(tmp_path):
    from sartsolver_tpu.io.solution import (
        SolutionWriter, read_resume_state, row_checksum,
    )

    path = str(tmp_path / "sol.h5")
    rows = [np.arange(8, dtype=np.float64) + i for i in range(3)]
    with SolutionWriter(path, ["camA"], 8, max_cache_size=2) as w:
        for i, row in enumerate(rows):
            w.add(row, 0, 0.1 * (i + 1), [0.1 * (i + 1)], iterations=5)
    state = read_resume_state(path, ["camA"], 8)
    assert state is not None and len(state.times) == 3
    np.testing.assert_array_equal(state.last_solution, rows[-1])
    with h5py.File(path, "r") as f:
        stored = f["solution/checksum"][:]
    assert all(
        np.uint32(stored[i]) == row_checksum(rows[i]) for i in range(3)
    )
    # corrupt one row's bytes behind the checksum's back
    with h5py.File(path, "r+") as f:
        f["solution/value"][1, 3] += 1e-9
    with pytest.raises(SartInputError, match="checksum"):
        read_resume_state(path, ["camA"], 8)


def test_solution_checksum_legacy_file_resumes(tmp_path):
    """Files from before the checksum dataset keep resuming (and keep
    appending without one)."""
    from sartsolver_tpu.io.solution import SolutionWriter, read_resume_state

    path = str(tmp_path / "legacy.h5")
    with SolutionWriter(path, ["camA"], 4) as w:
        w.add(np.ones(4), 0, 0.1, [0.1])
    with h5py.File(path, "r+") as f:
        del f["solution/checksum"]
    state = read_resume_state(path, ["camA"], 4)
    assert state is not None and len(state.times) == 1
    with SolutionWriter(path, ["camA"], 4, resume=state) as w:
        w.add(2 * np.ones(4), 0, 0.2, [0.2])
    state = read_resume_state(path, ["camA"], 4)
    assert len(state.times) == 2


def test_cli_resume_refuses_corrupt_row(world, capsys):
    paths, *_ = world
    assert run_cli(paths) == 0
    with h5py.File(paths["output"], "r+") as f:
        f["solution/value"][0, 0] += 1.0
    rc = run_cli(paths, "--resume")
    assert rc == 1
    assert "checksum" in capsys.readouterr().err


def test_prepare_measurement_counts_nonfinite_pixels():
    from sartsolver_tpu.models.sart import reset_nonfinite_warning
    from sartsolver_tpu.obs import metrics as obs_metrics

    registry = obs_metrics.reset_registry()
    # the warning is once-per-RUN now (not once-per-location like the
    # old Python-dedup behavior); start this test's "run" fresh
    reset_nonfinite_warning()
    opts = SolverOptions()
    g = np.ones(16)
    g[3] = np.nan
    g[7] = np.inf
    with pytest.warns(RuntimeWarning, match="non-finite"):
        g64, msq, norm = prepare_measurement(g, opts)
    assert registry.counter("nonfinite_pixels_total").value == 2
    # the poisoned pixels must not poison the normalization factor (the
    # finite pixels define the scale; NaN additionally stays out of
    # ||g||^2, while inf flows into msq for the solver's input guard)
    assert np.isfinite(norm)
    # clean frames touch neither counter nor warning machinery
    prepare_measurement(np.ones(16), opts)
    assert registry.counter("nonfinite_pixels_total").value == 2
