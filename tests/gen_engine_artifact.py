"""Generate a real serving-engine --metrics_out artifact.

Used by ``make bench-smoke``'s engine gate: runs a real ``sartsolve
serve`` pass (in-process, the same serve_main the CLI dispatches) over
the synthetic world with a few requests pre-staged in the ingest dir
and the JSONL sink enabled, then exits on idle. The artifact carries
the engine's queue-wait histogram and admitted/deadline-miss counters,
so ``sartsolve metrics --diff --threshold`` can gate queue-wait and
deadline-miss rates run-over-run (docs/SERVING.md §6). Exits with the
serve exit code (0 expected).

With the ``supervised`` mode argument the pass runs the REAL
``sartsolve serve --supervised`` in a subprocess and SIGKILLs the
worker once inside a journal commit window: the supervisor restarts it,
the state checkpoint merges the first incarnation's engine metrics into
the second, and the final artifact therefore carries CUMULATIVE
queue-wait/deadline/SLO series across the induced crash — `make
bench-smoke` gates the same four engine metrics on it run-over-run
(docs/SERVING.md §9).

Usage: gen_engine_artifact.py WORLD_DIR ARTIFACT.jsonl [supervised]
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)  # fixtures.py
sys.path.insert(0, os.path.dirname(_here))  # the repo checkout itself

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import fixtures as fx  # noqa: E402
from sartsolver_tpu.engine.cli import serve_main  # noqa: E402


def run(world_dir: str, artifact: str, mode: str = "") -> int:
    paths, *_ = fx.write_world(world_dir, n_frames=6)
    eng = os.path.join(world_dir, "engine")
    ingest = os.path.join(eng, "ingest")
    os.makedirs(ingest, exist_ok=True)
    # three tenants' worth of queued work; generous deadlines that a
    # healthy smoke run never misses (a zero miss rate is the stable
    # baseline the gate watches for movement)
    requests = [
        {"id": "smoke-a", "tenant": "a", "deadline_s": 300},
        {"id": "smoke-b", "tenant": "b", "time_range": "0.05:0.35"},
        {"id": "smoke-c", "tenant": "c", "deadline_s": 300},
    ]
    for i, payload in enumerate(requests):
        with open(os.path.join(ingest, f"{i}-{payload['id']}.json"),
                  "w") as f:
            json.dump(payload, f)
    serve_argv = [
        "--engine_dir", eng, "--use_cpu", "-m", "60", "-c", "1e-8",
        "--lanes", "2", "--idle_exit", "0.5", "--poll_interval", "0.05",
        # generous SLO target (like the deadlines): a healthy smoke run
        # burns zero budget, so the --diff burn gate watches a stable
        # zero baseline; the queue-wait p99 gate rides the same artifact
        "--slo_ms", "300000",
        "--metrics_out", artifact,
        paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
        paths["img_a"], paths["img_b"],
    ]
    if mode != "supervised":
        return serve_main(serve_argv)
    return _run_supervised(serve_argv)


def _run_supervised(serve_argv) -> int:
    """Supervised pass with one induced crash: SIGKILL the worker in the
    first 'dispatched' journal window, let the supervisor restart it,
    and return once the second incarnation drains and exits 0 — the
    artifact it finalizes carries both incarnations' engine metrics
    (merged through the state checkpoint)."""
    import re
    import signal
    import subprocess
    import threading

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONUNBUFFERED"] = "1"
    env["SART_TEST_JOURNAL_DELAY"] = "0.4"
    cmd = [sys.executable, "-m", "sartsolver_tpu.cli", "serve",
           "--supervised", "--restart_backoff", "0.05", *serve_argv]
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    guard = threading.Timer(280, proc.kill)
    guard.start()
    worker_pid = None
    killed = False
    try:
        for line in proc.stdout:
            sys.stderr.write(line)
            m = re.search(r"worker-spawn pid=(\d+)", line)
            if m:
                worker_pid = int(m.group(1))
            if (not killed and worker_pid is not None
                    and "SART_JOURNAL_POINT dispatched" in line):
                os.kill(worker_pid, signal.SIGKILL)
                killed = True
        rc = proc.wait(timeout=280)
    finally:
        guard.cancel()
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    if not killed:
        print("gen_engine_artifact: supervised pass never reached the "
              "kill window", file=sys.stderr)
        return 1
    return rc


if __name__ == "__main__":
    sys.exit(run(sys.argv[1], sys.argv[2],
                 sys.argv[3] if len(sys.argv) > 3 else ""))
