"""Generate a real serving-engine --metrics_out artifact.

Used by ``make bench-smoke``'s engine gate: runs a real ``sartsolve
serve`` pass (in-process, the same serve_main the CLI dispatches) over
the synthetic world with a few requests pre-staged in the ingest dir
and the JSONL sink enabled, then exits on idle. The artifact carries
the engine's queue-wait histogram and admitted/deadline-miss counters,
so ``sartsolve metrics --diff --threshold`` can gate queue-wait and
deadline-miss rates run-over-run (docs/SERVING.md §6). Exits with the
serve exit code (0 expected).

Usage: gen_engine_artifact.py WORLD_DIR ARTIFACT.jsonl
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)  # fixtures.py
sys.path.insert(0, os.path.dirname(_here))  # the repo checkout itself

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import fixtures as fx  # noqa: E402
from sartsolver_tpu.engine.cli import serve_main  # noqa: E402


def run(world_dir: str, artifact: str) -> int:
    paths, *_ = fx.write_world(world_dir, n_frames=6)
    eng = os.path.join(world_dir, "engine")
    ingest = os.path.join(eng, "ingest")
    os.makedirs(ingest, exist_ok=True)
    # three tenants' worth of queued work; generous deadlines that a
    # healthy smoke run never misses (a zero miss rate is the stable
    # baseline the gate watches for movement)
    requests = [
        {"id": "smoke-a", "tenant": "a", "deadline_s": 300},
        {"id": "smoke-b", "tenant": "b", "time_range": "0.05:0.35"},
        {"id": "smoke-c", "tenant": "c", "deadline_s": 300},
    ]
    for i, payload in enumerate(requests):
        with open(os.path.join(ingest, f"{i}-{payload['id']}.json"),
                  "w") as f:
            json.dump(payload, f)
    return serve_main([
        "--engine_dir", eng, "--use_cpu", "-m", "60", "-c", "1e-8",
        "--lanes", "2", "--idle_exit", "0.5", "--poll_interval", "0.05",
        # generous SLO target (like the deadlines): a healthy smoke run
        # burns zero budget, so the --diff burn gate watches a stable
        # zero baseline; the queue-wait p99 gate rides the same artifact
        "--slo_ms", "300000",
        "--metrics_out", artifact,
        paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
        paths["img_a"], paths["img_b"],
    ])


if __name__ == "__main__":
    sys.exit(run(sys.argv[1], sys.argv[2]))
