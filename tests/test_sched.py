"""Continuous batching (sartsolver_tpu/sched/, docs/PERFORMANCE.md §8):
scheduler edge cases, masked-lane byte parity against the dense grouped
loop, the one-compiled-program contract, failure/OOM/stop policy, and
the CLI + obs integration."""

import json

import h5py
import numpy as np
import pytest

import fixtures as fx

from sartsolver_tpu.cli import main
from sartsolver_tpu.config import DIVERGED, SolverOptions
from sartsolver_tpu.obs import metrics as obs_metrics
from sartsolver_tpu.ops.laplacian import make_laplacian
from sartsolver_tpu.parallel.mesh import make_mesh
from sartsolver_tpu.parallel.sharded import DistributedSARTSolver
from sartsolver_tpu.resilience import faults
from sartsolver_tpu.resilience.failures import FrameFailure
from sartsolver_tpu.sched import ContinuousBatcher


# ---------------------------------------------------------------------------
# harness: a tiny solver + a mixed-convergence frame set + both loops
# ---------------------------------------------------------------------------

P_PIX, V_VOX = 24, 16


def _mixed_case(n, seed=0, spread=True):
    """(H, frames): per-frame iteration counts genuinely vary (SART
    converges low spatial frequencies first, so frames whose truth
    carries more fine structure straggle)."""
    rng = np.random.default_rng(seed)
    H = rng.uniform(0.1, 1.0, (P_PIX, V_VOX)).astype(np.float32)
    x = np.arange(V_VOX) / V_VOX
    base = 1.0 + 0.5 * np.sin(2 * np.pi * x)
    rough = np.sin(2 * np.pi * 6.5 * x)
    amps = np.geomspace(1e-3, 3.0, n) if spread else np.zeros(n)
    rng.shuffle(amps)
    frames = []
    for i in range(n):
        f_i = np.maximum(base + amps[i] * rough, 1e-3)
        g_i = H.astype(np.float64) @ f_i
        frames.append(np.maximum(
            g_i * (1.0 + 1e-3 * rng.standard_normal(P_PIX)), 0.0))
    return H, frames


def _opts(**kw):
    kw.setdefault("max_iterations", 300)
    kw.setdefault("conv_tolerance", 1e-6)
    kw.setdefault("schedule_stride", 8)
    return SolverOptions(**kw)


def _solver(H, opts, lap=None):
    return DistributedSARTSolver(H, lap, opts=opts, mesh=make_mesh(1, 1))


def _run_sched(solver, items, lanes, **kw):
    """Drive the batcher; returns (results ordered-by-emission, stats).
    Each result is ("ok", ftime, status, iters, solution) or
    ("failed", ftime, error)."""
    out = []

    def on_result(ftime, _ct, status, iters, _conv, fetcher, _ms):
        out.append(("ok", ftime, status, iters, fetcher()))

    def on_failed(ftime, _ct, err):
        out.append(("failed", ftime, err))

    batcher = ContinuousBatcher(solver, lanes=lanes, on_result=on_result,
                                on_failed=on_failed, **kw)
    stats = batcher.run(iter(items))
    return out, stats


def _run_dense(solver, frames, K):
    """The CLI's classic run-to-slowest group loop: frame-order groups of
    K, dark-frame tail padding, per-frame rows."""
    sols, statuses, iters = [], [], []
    for s in range(0, len(frames), K):
        stack = np.stack(frames[s:s + K])
        n = stack.shape[0]
        if n < K:
            stack = np.concatenate(
                [stack, np.zeros((K - n, stack.shape[1]))], axis=0)
        res = solver.solve_batch(stack, device_result=True)
        sols.append(res.fetch_solutions()[:n])
        statuses.extend(res.status[:n].tolist())
        iters.extend(res.iterations[:n].tolist())
    return np.concatenate(sols), statuses, iters


def _items(frames):
    return [(fr, float(i), [float(i)]) for i, fr in enumerate(frames)]


# ---------------------------------------------------------------------------
# parity + edge cases (ISSUE 6 satellite: scheduler edge-case coverage)
# ---------------------------------------------------------------------------

def test_masked_lane_byte_parity_vs_dense_grouped():
    """THE contract: every retired lane's solution/status/iteration count
    is byte-identical to the dense run-to-slowest loop solving the same
    frame order — on a frame set whose iteration counts genuinely spread
    (otherwise the test proves nothing about masking)."""
    H, frames = _mixed_case(10, seed=1)
    opts = _opts()
    with _solver(H, opts) as solver:
        want_sol, want_st, want_it = _run_dense(solver, frames, 4)
        got, stats = _run_sched(solver, _items(frames), lanes=4)
    assert [r[0] for r in got] == ["ok"] * 10
    # emission is frame order by contract
    assert [r[1] for r in got] == [float(i) for i in range(10)]
    assert [r[2] for r in got] == want_st
    assert [r[3] for r in got] == want_it
    np.testing.assert_array_equal(np.stack([r[4] for r in got]), want_sol)
    # the workload really is mixed — otherwise retirement never fires
    # before the group drains and the parity is vacuous
    assert max(want_it) >= 2 * min(want_it)
    assert stats.frames == 10 and stats.solved == 10
    assert stats.backfilled >= 10  # every frame occupied a lane
    assert 0.0 < stats.occupancy <= 1.0


def test_tail_drain_below_full_batch():
    """Backfill at exhaustion: fewer frames than lanes — the tail drains
    through the same fixed-shape program with the leftover lanes inert,
    and the results match the dense loop's padded group bitwise."""
    H, frames = _mixed_case(2, seed=2)
    opts = _opts()
    with _solver(H, opts) as solver:
        want_sol, want_st, want_it = _run_dense(solver, frames, 5)
        got, stats = _run_sched(solver, _items(frames), lanes=5)
    assert [r[2] for r in got] == want_st
    assert [r[3] for r in got] == want_it
    np.testing.assert_array_equal(np.stack([r[4] for r in got]), want_sol)
    assert stats.frames == 2 and stats.backfilled == 2


def test_all_lanes_converge_in_one_stride():
    """A stride longer than any frame's iteration count: every occupied
    lane retires at its first control return (the device while loop exits
    early once all lanes are done — no dead iterations to the stride
    cap), and each refill generation costs exactly one stride."""
    H, frames = _mixed_case(6, seed=3)
    opts = _opts(schedule_stride=10_000)
    with _solver(H, opts) as solver:
        want_sol, want_st, want_it = _run_dense(solver, frames, 3)
        got, stats = _run_sched(solver, _items(frames), lanes=3)
    assert [r[2] for r in got] == want_st
    assert [r[3] for r in got] == want_it
    np.testing.assert_array_equal(np.stack([r[4] for r in got]), want_sol)
    # 6 frames / 3 lanes = 2 generations = 2 strides
    assert stats.strides == 2
    # early exit: the device ran to the slowest lane, not to the stride
    assert stats.loop_steps <= max(want_it) * 2


def test_schedule_stride_one():
    """stride=1 (retirement checked every iteration) stays byte-correct —
    the degenerate maximum-overhead point of the stride trade-off."""
    H, frames = _mixed_case(4, seed=4)
    opts = _opts(schedule_stride=1, max_iterations=60)
    with _solver(H, opts) as solver:
        want_sol, want_st, want_it = _run_dense(solver, frames, 2)
        got, _stats = _run_sched(solver, _items(frames), lanes=2)
    assert [r[3] for r in got] == want_it
    np.testing.assert_array_equal(np.stack([r[4] for r in got]), want_sol)


def test_divergence_recovery_rollback_inside_masked_batch():
    """The rollback/relaxation ladder runs per lane inside the masked
    batch: a genuinely diverging configuration (explicit-Euler-unstable
    Laplacian weight) ends DIVERGED with a finite iterate, healthy lanes
    alongside it are untouched, and every lane is byte-identical to the
    dense guarded loop on the same frame order."""
    H, frames = _mixed_case(6, seed=5)
    rows, cols, vals = [], [], []
    for i in range(V_VOX):
        rows.append(i); cols.append(i); vals.append(2.0)
        if i > 0:
            rows.append(i); cols.append(i - 1); vals.append(-1.0)
        if i < V_VOX - 1:
            rows.append(i); cols.append(i + 1); vals.append(-1.0)
    lap = make_laplacian(np.asarray(rows), np.asarray(cols),
                         np.asarray(vals, np.float32), dtype="float32")
    opts = _opts(max_iterations=120, beta_laplace=0.8,
                 divergence_recovery=4, divergence_threshold=1e3)
    with _solver(H, opts, lap=lap) as solver:
        want_sol, want_st, want_it = _run_dense(solver, frames, 3)
        got, _stats = _run_sched(solver, _items(frames), lanes=3)
    assert DIVERGED in want_st  # the ladder genuinely exhausted
    assert [r[2] for r in got] == want_st
    assert [r[3] for r in got] == want_it
    np.testing.assert_array_equal(np.stack([r[4] for r in got]), want_sol)
    assert np.isfinite(np.stack([r[4] for r in got])).all()


def test_nan_poisoned_frame_diverges_in_lane():
    """The refill branch's pre-flight input guard (recovery mode): a NaN
    frame pre-fails DIVERGED in its lane with zero iterations while its
    neighbours solve exactly as in a clean run."""
    H, frames = _mixed_case(5, seed=6)
    bad = frames[2].copy()
    bad[0] = np.nan
    poisoned = frames[:2] + [bad] + frames[3:]
    opts = _opts(divergence_recovery=2)
    with _solver(H, opts) as solver:
        clean, _ = _run_sched(solver, _items(frames), lanes=2)
        got, _ = _run_sched(solver, _items(poisoned), lanes=2)
    assert got[2][2] == DIVERGED and got[2][3] == 0
    np.testing.assert_array_equal(got[2][4], 0.0)
    for i in (0, 1, 3, 4):
        np.testing.assert_array_equal(got[i][4], clean[i][4])


def test_one_compiled_program_across_occupancies():
    """The fixed batch shape is the whole point: a run whose occupancy
    visits full, partial and single-lane states must leave exactly ONE
    compiled stride program in the jit cache — no per-occupancy
    recompiles."""
    H, frames = _mixed_case(7, seed=7)
    opts = _opts()
    with _solver(H, opts) as solver:
        _run_sched(solver, _items(frames), lanes=3)
        assert solver._sched_fn()._cache_size() == 1


# ---------------------------------------------------------------------------
# failure policy
# ---------------------------------------------------------------------------

def test_frame_failure_items_flow_through_in_order():
    """Prefetcher FrameFailure items take a sequence slot (no lane) and
    come out interleaved at their frame position."""
    H, frames = _mixed_case(4, seed=8)
    err = OSError("unreadable")
    items = [_items(frames)[0],
             FrameFailure(None, 1.0, [1.0], err),
             *_items(frames)[1:]]
    items[2] = (items[2][0], 2.0, [2.0])
    items[3] = (items[3][0], 3.0, [3.0])
    items[4] = (items[4][0], 4.0, [4.0])
    opts = _opts()
    with _solver(H, opts) as solver:
        got, stats = _run_sched(solver, items, lanes=2)
    assert [r[0] for r in got] == ["ok", "failed", "ok", "ok", "ok"]
    assert [r[1] for r in got] == [0.0, 1.0, 2.0, 3.0, 4.0]
    assert got[1][2] is err
    assert stats.failed == 1 and stats.solved == 4 and stats.frames == 5


def test_dispatch_fault_fails_inflight_lanes_and_continues():
    """A recoverable (non-OOM) dispatch fault fails exactly the in-flight
    lanes — the dense loop's 'the group produced nothing' — and the run
    continues on fresh lanes."""
    H, frames = _mixed_case(6, seed=9)
    opts = _opts()
    faults.reset()
    faults.inject(faults.SITE_SOLVE, "error", count=1)
    try:
        with _solver(H, opts) as solver:
            got, stats = _run_sched(solver, _items(frames), lanes=2)
    finally:
        faults.reset()
    # first stride's 2 lanes fail; the rest solve
    kinds = [r[0] for r in got]
    assert kinds[:2] == ["failed", "failed"] and kinds[2:] == ["ok"] * 4
    assert [r[1] for r in got] == [float(i) for i in range(6)]
    assert stats.failed == 2 and stats.solved == 4
    assert stats.leftover is None


def test_dispatch_fault_raises_without_isolation():
    H, frames = _mixed_case(3, seed=10)
    opts = _opts()
    faults.reset()
    faults.inject(faults.SITE_SOLVE, "error", count=1)
    try:
        with _solver(H, opts) as solver:
            with pytest.raises(faults.InjectedFault):
                _run_sched(solver, _items(frames), lanes=2, isolate=False)
    finally:
        faults.reset()


def test_oom_hands_unemitted_frames_back_in_order():
    """Device OOM: the one failure a fixed lane count cannot absorb. The
    scheduler returns every un-emitted frame (in-flight AND buffered
    out-of-order completions) in frame order for the classic loop's
    halving ladder, and the frames re-solve to the right answers."""
    H, frames = _mixed_case(6, seed=11)
    opts = _opts(max_iterations=800)
    faults.reset()
    faults.inject(faults.SITE_SOLVE, "oom", count=1)
    try:
        with _solver(H, opts) as solver:
            items = iter(_items(frames))
            got, stats = _run_sched(solver, items, lanes=2)
            assert got == []  # nothing emitted before the first dispatch
            assert stats.leftover is not None
            assert stats.oom_error is not None
            # the two in-flight frames come back in frame order; the rest
            # of the stream was never consumed (the CLI fallback chains
            # leftover + the live iterator)
            assert [it[1] for it in stats.leftover] == [0.0, 1.0]
            assert len(list(items)) == 4
            faults.reset()
            # the CLI fallback path: the same items re-solve dense
            _sol, st, _ = _run_dense(
                solver, [it[0] for it in stats.leftover], 1)
            assert st == [0] * 2
    finally:
        faults.reset()


def test_stop_check_drains_inflight_and_truncates_queue():
    """A stop request at a stride boundary ends backfilling; the lanes
    already in flight drain to full convergence (their results emitted),
    the rest of the queue is left unread."""
    H, frames = _mixed_case(8, seed=12)
    opts = _opts(schedule_stride=2)
    polls = {"n": 0}

    def stop_after_two():
        polls["n"] += 1
        return polls["n"] > 2

    with _solver(H, opts) as solver:
        got, stats = _run_sched(solver, _items(frames), lanes=2,
                                stop_check=stop_after_two)
    assert stats.interrupted
    # the 2 in-flight lanes drained; the queue's tail was never read
    assert 0 < len(got) < 8
    assert all(r[0] == "ok" and r[2] == 0 for r in got)


def test_stop_during_tail_drain_is_not_interrupted():
    """A stop request landing AFTER the queue is exhausted cannot
    truncate anything — the in-flight lanes drain to completion and every
    frame is emitted, so the run must NOT report interrupted (exit 4
    would make a supervisor requeue a finished job; same contract as the
    classic loop's last-boundary check)."""
    H, frames = _mixed_case(3, seed=12)
    opts = _opts(schedule_stride=2)
    polls = {"n": 0}

    def stop_after_first_poll():
        polls["n"] += 1
        return polls["n"] > 1

    with _solver(H, opts) as solver:
        # lanes > frames: the first intake exhausts the stream, so every
        # stop poll after the first lands during the tail drain
        got, stats = _run_sched(solver, _items(frames), lanes=4,
                                stop_check=stop_after_first_poll)
    assert not stats.interrupted
    assert len(got) == 3
    assert all(r[0] == "ok" and r[2] == 0 for r in got)


def test_lane_and_stride_validation():
    H, frames = _mixed_case(1, seed=13)
    with pytest.raises(ValueError, match="schedule_stride"):
        _opts(schedule_stride=0)
    with _solver(H, _opts()) as solver:
        with pytest.raises(ValueError, match="[Ll]ane count"):
            solver.sched_lanes(0)
        with pytest.raises(ValueError, match="[Ll]ane count"):
            ContinuousBatcher(solver, lanes=0, on_result=lambda *a: None,
                              on_failed=lambda *a: None)
    # closed solver: the lane entry points refuse like solve_batch does
    with pytest.raises(ValueError, match="closed"):
        solver.sched_lanes(2)


def test_scheduler_occupancy_accounting_beats_run_to_slowest():
    """The accounting itself (not wall clock — deterministic on CI): on a
    straggler-heavy stream (one slow frame per ~8, the bench.py
    straggler distribution in miniature) the scheduler's useful-
    iteration occupancy is >= 1.5x the dense loop's run-to-slowest
    occupancy."""
    rng = np.random.default_rng(0)
    H = rng.uniform(0.1, 1.0, (P_PIX, V_VOX)).astype(np.float32)
    x = np.arange(V_VOX) / V_VOX
    base = 1.0 + 0.5 * np.sin(2 * np.pi * x)
    rough = np.sin(2 * np.pi * 6.5 * x)
    n = 24
    amps = np.full(n, 1e-3)
    # one straggler (~3x the iterations) leading every dense group of 4:
    # the run-to-slowest loop pads 3 fast lanes per group while the
    # scheduler retires and backfills them
    amps[::4] = 3.0
    frames = [
        np.maximum(
            H.astype(np.float64) @ np.maximum(base + a * rough, 1e-3)
            * (1.0 + 1e-3 * rng.standard_normal(P_PIX)), 0.0)
        for a in amps
    ]
    opts = _opts(conv_tolerance=1e-5, max_iterations=800,
                 schedule_stride=4)
    with _solver(H, opts) as solver:
        _, statuses, iters = _run_dense(solver, frames, 4)
        # dense capacity: every group runs to its slowest frame
        cap = sum(max(iters[s:s + 4]) * 4
                  for s in range(0, len(frames), 4))
        dense_occ = sum(iters) / cap
        _, stats = _run_sched(solver, _items(frames), lanes=4)
    assert statuses == [0] * n
    assert stats.useful_iters == sum(iters)  # identical useful work
    assert stats.occupancy >= 1.5 * dense_occ


# ---------------------------------------------------------------------------
# CLI + obs integration
# ---------------------------------------------------------------------------

@pytest.fixture
def world(tmp_path):
    return fx.write_world(tmp_path, n_frames=5)


def run_cli(paths, *extra):
    return main([
        "-o", paths["output"],
        paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
        paths["img_a"], paths["img_b"],
        "--use_cpu", "-m", "300", "-c", "1e-6", "--no_guess",
        *extra,
    ])


def _read_solution(path):
    with h5py.File(path, "r") as f:
        return {k: np.array(f["solution"][k]) for k in f["solution"]}


def test_cli_scheduled_matches_classic_loop_bitwise(world):
    """--batch_frames N runs the scheduler by default; its solution file
    equals --no_continuous_batching's dataset for dataset, byte for
    byte."""
    paths, *_ = world
    assert run_cli(paths, "--batch_frames", "3") == 0
    sched = _read_solution(paths["output"])
    assert run_cli(paths, "--batch_frames", "3",
                   "--no_continuous_batching") == 0
    dense = _read_solution(paths["output"])
    assert set(sched) == set(dense)
    for key in sched:
        np.testing.assert_array_equal(sched[key], dense[key])


def test_cli_schedule_stride_flag_and_env(world, monkeypatch):
    paths, *_ = world
    # flag wins over env; both byte-identical to the default (the stride
    # never changes per-lane math, only control-return cadence)
    assert run_cli(paths, "--batch_frames", "2") == 0
    want = _read_solution(paths["output"])
    monkeypatch.setenv("SART_SCHEDULE_STRIDE", "3")
    assert run_cli(paths, "--batch_frames", "2") == 0
    got_env = _read_solution(paths["output"])
    assert run_cli(paths, "--batch_frames", "2",
                   "--schedule_stride", "5") == 0
    got_flag = _read_solution(paths["output"])
    for key in want:
        np.testing.assert_array_equal(want[key], got_env[key])
        np.testing.assert_array_equal(want[key], got_flag[key])
    with pytest.raises(SystemExit):
        run_cli(paths, "--schedule_stride", "0")
    monkeypatch.setenv("SART_SCHEDULE_STRIDE", "-2")
    assert run_cli(paths, "--batch_frames", "2") == 1  # SartInputError
    # malformed values fail loudly too — an operator typo on a perf knob
    # must not silently run at the default stride
    monkeypatch.setenv("SART_SCHEDULE_STRIDE", "1e2")
    assert run_cli(paths, "--batch_frames", "2") == 1


def test_cli_scheduler_oom_falls_back_to_classic_ladder(world):
    """A device OOM inside the scheduler hands the stream back to the
    classic grouped loop at half the lane count — the run completes with
    every frame solved (the fixed-shape scheduler cannot halve itself)."""
    paths, *_ = world
    faults.reset()
    faults.inject(faults.SITE_SOLVE, "oom", count=1, prob=1.0)
    try:
        assert run_cli(paths, "--batch_frames", "4") == 0
    finally:
        faults.reset()
    out = _read_solution(paths["output"])
    assert list(out["status"]) == [0] * 5
    # parity with the never-faulted classic loop
    assert run_cli(paths, "--batch_frames", "2",
                   "--no_continuous_batching") == 0
    dense = _read_solution(paths["output"])
    np.testing.assert_array_equal(out["value"], dense["value"])


def test_cli_scheduler_oom_after_stream_exhausted(world):
    """OOM fallback when the prefetcher is already drained: with more
    lanes than frames the intake consumes the whole stream (end sentinel
    included) before the first dispatch, so the fallback must continue
    the batcher's own iterator — re-iterating the prefetcher would block
    forever on an empty queue."""
    paths, *_ = world
    faults.reset()
    faults.inject(faults.SITE_SOLVE, "oom", count=1, prob=1.0)
    try:
        assert run_cli(paths, "--batch_frames", "8") == 0
    finally:
        faults.reset()
    out = _read_solution(paths["output"])
    assert list(out["status"]) == [0] * 5
    assert run_cli(paths, "--batch_frames", "2",
                   "--no_continuous_batching") == 0
    dense = _read_solution(paths["output"])
    np.testing.assert_array_equal(out["value"], dense["value"])


def test_cli_scheduler_metrics_artifact(world, tmp_path, monkeypatch):
    """--metrics_out carries the scheduler's occupancy gauge/counters and
    the iterations_to_converge histogram; the artifact validates; the
    trace has solve.dispatch spans (the scheduler dispatches through the
    same dispatch_guarded wrapper as the classic loop)."""
    paths, *_ = world
    art = str(tmp_path / "run.jsonl")
    trace_out = str(tmp_path / "run.trace.json")
    monkeypatch.setenv("SART_TRACE_EVENTS", trace_out)
    assert run_cli(paths, "--batch_frames", "2", "--metrics_out", art) == 0
    with open(trace_out) as fh:
        trace = json.load(fh)
    dispatch_spans = [e for e in trace["traceEvents"]
                      if e.get("name") == "solve.dispatch"]
    assert len(dispatch_spans) >= 1  # one per scheduler stride
    with open(art) as fh:
        records = [json.loads(line) for line in fh if line.strip()]
    metric = {
        (r["name"], tuple(sorted((r.get("labels") or {}).items()))): r
        for r in records if r.get("type") == "metric"
    }
    occ = metric[("sched_lane_occupancy", ())]
    assert 0.0 < occ["value"] <= 1.0
    assert metric[("sched_lanes_retired_total", ())]["value"] == 5
    assert metric[("sched_lanes_backfilled_total", ())]["value"] == 5
    assert metric[("sched_strides_total", ())]["value"] >= 1
    hist = metric[("iterations_to_converge", ())]
    assert hist["kind"] == "histogram" and hist["count"] == 5
    assert hist["min"] >= 1
    # the artifact passes the schema/run-contract check
    from sartsolver_tpu.obs.cli import metrics_main

    assert metrics_main(["--check", art]) == 0


def test_metrics_diff_gates_convergence_drift(tmp_path):
    """`sartsolve metrics --diff --threshold` exits 2 when the mean
    iterations_to_converge drifts past the threshold — in either
    direction — and 0 within it."""
    from sartsolver_tpu.obs.cli import metrics_main

    from sartsolver_tpu.obs import schema

    def artifact(name, iters):
        reg = obs_metrics.MetricsRegistry()
        h = reg.histogram("iterations_to_converge")
        for i in iters:
            h.observe(i)
        recs = [schema.make_meta_record(created_unix=1.0),
                schema.make_frame_record(0.0, 0, "converged",
                                         int(iters[0]), 1.0, 0.5, "sched")]
        recs += [{"type": "metric", **snap} for snap in reg.snapshot()]
        recs.append(schema.make_summary_record(
            1, {"converged": 1}, wall_s=1.0))
        path = str(tmp_path / name)
        with open(path, "w") as fh:
            for rec in recs:
                fh.write(json.dumps(rec) + "\n")
        return path

    a = artifact("a.jsonl", [100, 100])
    slower = artifact("slower.jsonl", [160, 160])  # +60%
    faster = artifact("faster.jsonl", [40, 40])  # -60%
    same = artifact("same.jsonl", [104, 104])  # +4%
    assert metrics_main(["--diff", a, slower, "--threshold", "25"]) == 2
    assert metrics_main(["--diff", a, faster, "--threshold", "25"]) == 2
    assert metrics_main(["--diff", a, same, "--threshold", "25"]) == 0
    assert metrics_main(["--diff", a, slower]) == 0  # report-only


def test_metrics_diff_gates_straggler_headline(tmp_path):
    """The BENCH artifact's occupancy-weighted straggler throughput is a
    gated rate: a drop past the threshold exits 2."""
    from sartsolver_tpu.obs.cli import metrics_main

    def bench(name, occ_rate):
        rec = {"type": "bench", "schema": 1, "metric": "m", "value": 100.0,
               "unit": "iter/s", "vs_baseline": 1.0,
               "detail": {"straggler": {"occ_frame_iter_s": occ_rate,
                                        "occupancy": 0.9}}}
        path = str(tmp_path / name)
        with open(path, "w") as fh:
            fh.write(json.dumps(rec) + "\n")
        return path

    old = bench("old.json", 1000.0)
    bad = bench("bad.json", 500.0)
    ok = bench("ok.json", 950.0)
    assert metrics_main(["--diff", old, bad, "--threshold", "30"]) == 2
    assert metrics_main(["--diff", old, ok, "--threshold", "30"]) == 0
