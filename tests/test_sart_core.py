"""Numerical tests: jitted solver vs the independent NumPy fp64 oracle.

Mirrors the reference's implicit dual-backend oracle strategy (its fp64 CPU
solver validates its fp32 CUDA solver on identical inputs)."""

import numpy as np
import pytest

from sartsolver_tpu.config import MAX_ITERATIONS_EXCEEDED, SUCCESS, SolverOptions
from sartsolver_tpu.models.oracle import solve_oracle
from sartsolver_tpu.models.sart import make_problem, solve
from sartsolver_tpu.ops.laplacian import make_laplacian


def make_case(seed=0, P=48, V=32, neg_pixels=3, zero_voxels=2, zero_pixels=2, noise=0.01):
    """Random dense RTM with masked voxels/pixels and saturated detectors."""
    rng = np.random.default_rng(seed)
    H = rng.uniform(0.0, 1.0, (P, V))
    H[:, rng.choice(V, zero_voxels, replace=False)] = 0.0  # dead voxels
    H[rng.choice(P, zero_pixels, replace=False), :] = 0.0  # dead pixels
    f_true = rng.uniform(0.5, 2.0, V)
    g = H @ f_true + noise * rng.standard_normal(P)
    g = np.abs(g)
    g[rng.choice(P, neg_pixels, replace=False)] = -1.0  # saturated
    return H, g, f_true


def laplacian_1d_chain(V, scale=1.0):
    """Simple second-difference chain over voxel index as COO triplets."""
    rows, cols, vals = [], [], []
    for i in range(V):
        rows.append(i); cols.append(i); vals.append(2.0 * scale)
        if i > 0:
            rows.append(i); cols.append(i - 1); vals.append(-1.0 * scale)
        if i < V - 1:
            rows.append(i); cols.append(i + 1); vals.append(-1.0 * scale)
    return np.array(rows), np.array(cols), np.array(vals)


@pytest.mark.parametrize("logarithmic", [False, True])
@pytest.mark.parametrize("with_laplacian", [False, True])
def test_fp64_parity_with_oracle(logarithmic, with_laplacian):
    """fp64 device path must match the fp64 oracle to near machine precision."""
    H, g, _ = make_case(seed=1)
    lap_np = laplacian_1d_chain(H.shape[1], 0.1) if with_laplacian else None

    opts = SolverOptions.cpu_parity(
        logarithmic=logarithmic, max_iterations=40, conv_tolerance=1e-12
    )
    lap = make_laplacian(*lap_np, dtype="float64") if lap_np else None
    problem = make_problem(H, lap, opts=opts)
    res = solve(problem, g, opts=opts)

    # log_epsilon matched to the device profile: the reference's 1e-100 is
    # below emulated-f64 range, and with a Laplacian the floored voxels'
    # log(f) couples into neighbors, so the value must agree on both sides.
    f_ref, status_ref, iters_ref, _ = solve_oracle(
        H, g, lap_np, logarithmic=logarithmic,
        max_iterations=40, conv_tolerance=1e-12, log_epsilon=opts.log_epsilon,
    )
    np.testing.assert_allclose(np.asarray(res.solution), f_ref, rtol=1e-9, atol=1e-12)
    assert int(res.status) == status_ref
    assert int(res.iterations) == iters_ref


@pytest.mark.parametrize("logarithmic", [False, True])
def test_fp32_device_path_tracks_oracle(logarithmic):
    """fp32 normalized path (CUDA-equivalent) stays close to the fp64 oracle."""
    H, g, _ = make_case(seed=2)
    opts = SolverOptions(
        logarithmic=logarithmic, max_iterations=15, conv_tolerance=1e-12,
        # align guess semantics with the CPU oracle; normalization itself is
        # mathematically transparent
        mask_negative_guess=False, guess_floor=0.0 if not logarithmic else 1e-30,
        log_epsilon=1e-30,
    )
    problem = make_problem(H, opts=opts)
    res = solve(problem, g, opts=opts)

    f_ref, _, _, _ = solve_oracle(
        H, g, logarithmic=logarithmic, max_iterations=15, conv_tolerance=1e-12
    )
    np.testing.assert_allclose(np.asarray(res.solution), f_ref, rtol=5e-3, atol=5e-4)


def test_convergence_status_success():
    H, g, f_true = make_case(seed=3, noise=0.0, neg_pixels=0)
    opts = SolverOptions.cpu_parity(max_iterations=2000, conv_tolerance=1e-7)
    problem = make_problem(H, opts=opts)
    res = solve(problem, g, opts=opts)
    assert int(res.status) == SUCCESS
    assert int(res.iterations) < 2000
    fitted = H @ np.asarray(res.solution)
    # reconstruction reproduces the measurement on unmasked pixels
    mask = (H.sum(axis=1) > 1e-6) & (g >= 0)
    np.testing.assert_allclose(fitted[mask], g[mask], rtol=0.05, atol=0.05)


def test_max_iterations_exceeded_status():
    H, g, _ = make_case(seed=4)
    opts = SolverOptions.cpu_parity(max_iterations=3, conv_tolerance=1e-15)
    problem = make_problem(H, opts=opts)
    res = solve(problem, g, opts=opts)
    assert int(res.status) == MAX_ITERATIONS_EXCEEDED
    assert int(res.iterations) == 3


def test_warm_start_matches_oracle():
    H, g, _ = make_case(seed=5)
    f0 = np.full(H.shape[1], 0.7)
    opts = SolverOptions.cpu_parity(max_iterations=20, conv_tolerance=1e-12)
    problem = make_problem(H, opts=opts)
    res = solve(problem, g, f0=f0, opts=opts)
    f_ref, _, _, _ = solve_oracle(H, g, f0=f0, max_iterations=20, conv_tolerance=1e-12)
    np.testing.assert_allclose(np.asarray(res.solution), f_ref, rtol=1e-9)


def test_masked_voxels_stay_zero_linear():
    H, g, _ = make_case(seed=6, zero_voxels=4)
    opts = SolverOptions.cpu_parity(max_iterations=10, conv_tolerance=1e-12)
    problem = make_problem(H, opts=opts)
    res = solve(problem, g, opts=opts)
    dead = H.sum(axis=0) <= opts.ray_density_threshold
    assert dead.any()
    np.testing.assert_array_equal(np.asarray(res.solution)[dead], 0.0)


def test_saturated_pixels_excluded():
    """A saturated (negative) measurement must not influence the solution."""
    H, g, _ = make_case(seed=7, neg_pixels=0)
    opts = SolverOptions.cpu_parity(max_iterations=10, conv_tolerance=1e-12)
    g_sat = g.copy()
    g_sat[5] = -1.0  # saturate one detector
    H_dropped = np.delete(H, 5, axis=0)
    g_dropped = np.delete(g_sat, 5)

    res_sat = solve(make_problem(H, opts=opts), g_sat, opts=opts)
    res_drop = solve(make_problem(H_dropped, opts=opts), g_dropped, opts=opts)
    # ray_length/ray_density differ (they include row 5), so compare against
    # the oracle on identical inputs instead of exact equality.
    f_ref, _, _, _ = solve_oracle(H, g_sat, max_iterations=10, conv_tolerance=1e-12)
    np.testing.assert_allclose(np.asarray(res_sat.solution), f_ref, rtol=1e-9)
    # and the saturated pixel's removal only matters through ray stats:
    assert np.isfinite(np.asarray(res_drop.solution)).all()


def test_guess_floor_applied_on_device_profile():
    """CUDA path floors any starting solution at 1e-7 incl. masked voxels
    (sartsolver_cuda.cpp:180)."""
    H, g, _ = make_case(seed=8, zero_voxels=3)
    opts = SolverOptions(max_iterations=1, conv_tolerance=1e-12)
    problem = make_problem(H, opts=opts)
    res = solve(problem, g, opts=opts)
    assert np.isfinite(np.asarray(res.solution)).all()


def test_bfloat16_rtm_tracks_fp32():
    """rtm_dtype=bfloat16 (half HBM traffic) stays within bf16-mantissa
    error of the fp32 solution."""
    import dataclasses

    import jax.numpy as jnp

    from sartsolver_tpu.config import SolverOptions
    from sartsolver_tpu.models.sart import make_problem, solve

    rng = np.random.default_rng(7)
    P, V = 48, 256
    H = rng.uniform(0.1, 1.0, (P, V)).astype(np.float32)
    f_true = rng.uniform(0.5, 2.0, V)
    g = H.astype(np.float64) @ f_true

    base = SolverOptions(max_iterations=40, conv_tolerance=1e-12)
    ref = solve(make_problem(H, opts=base), g, opts=base)
    bf = dataclasses.replace(base, rtm_dtype="bfloat16")
    problem = make_problem(H, opts=bf)
    assert problem.rtm.dtype == jnp.bfloat16
    res = solve(problem, g, opts=bf)

    ref_sol = np.asarray(ref.solution, np.float64)
    bf_sol = np.asarray(res.solution, np.float64)
    rel = np.linalg.norm(bf_sol - ref_sol) / np.linalg.norm(ref_sol)
    assert rel < 0.03, f"bf16 deviates {rel:.3%} from fp32"
    # ray stats are computed in fp32 regardless of storage dtype
    assert problem.ray_density.dtype == jnp.float32


class TestPreciseConvergence:
    """fp64 accumulation of the convergence metric (Eq. 5) on the fp32
    device path (SolverOptions.precise_convergence, VERDICT r2 #7)."""

    def _case(self):
        rng = np.random.default_rng(11)
        P, V = 64, 256
        H = rng.uniform(0.1, 1.0, (P, V)).astype(np.float32)
        f_true = rng.uniform(0.5, 2.0, V)
        g = H.astype(np.float64) @ f_true * (
            1.0 + 0.01 * rng.standard_normal(P)
        )
        return H, np.abs(g)

    def test_metric_matches_fp64_recomputation(self):
        """The reported convergence value must equal an fp64 host
        recomputation from the returned solution to ~fp32-ulp, for both
        metric modes (the fp32 mode's larger drift is what the precise
        mode exists to remove; at this small P both are tight)."""
        import dataclasses

        H, g = self._case()
        opts = SolverOptions(max_iterations=25, conv_tolerance=1e-12)
        problem = make_problem(H, opts=opts)
        for precise in (True, False):
            o = dataclasses.replace(opts, precise_convergence=precise)
            res = solve(problem, g, opts=o)
            fitted = H.astype(np.float64) @ np.asarray(res.solution, np.float64)
            msq = np.sum(np.where(g > 0, g, 0.0) ** 2)
            conv_ref = (msq - np.sum(fitted**2)) / msq
            norm = g.max()
            # res.convergence is in normalized units; msq/fsq scale as
            # 1/norm^2, which cancels in the ratio
            assert abs(float(res.convergence) - conv_ref) < 5e-6, (
                precise, float(res.convergence), conv_ref,
            )

    def test_compensated_path_without_x64(self):
        """Library users run with jax_enable_x64 False; the compensated
        float-float path (no private APIs — VERDICT r3 weak #3) must
        compile and agree with the x64 fp64 path."""
        import jax

        H, g = self._case()
        opts = SolverOptions(max_iterations=20, conv_tolerance=1e-12)
        problem = make_problem(H, opts=opts)
        res_on = solve(problem, g, opts=opts)
        assert jax.config.jax_enable_x64  # conftest enables it
        jax.config.update("jax_enable_x64", False)
        try:
            problem32 = make_problem(H, opts=opts)
            res_off = solve(problem32, g, opts=opts)
        finally:
            jax.config.update("jax_enable_x64", True)
        np.testing.assert_allclose(
            np.asarray(res_on.solution), np.asarray(res_off.solution),
            rtol=1e-6,
        )
        assert int(res_on.iterations) == int(res_off.iterations)

    def test_sumsq_accumulation_quality(self):
        """``_sumsq_precise`` must land within 1 fp32 ulp of an fp64
        reference on wide mixed-magnitude vectors. Plain fp32 summation
        (the behavior a silent regression would reintroduce, VERDICT r3
        next #3) misses this bound reliably at this width — so this test
        goes red if the compensated path ever degrades.

        The discriminator is a host-side SEQUENTIAL fp32 accumulation
        (np.cumsum), not XLA's ``jnp.sum``: backends are free to lower a
        plain reduce as a pairwise/vectorized tree, and CPU XLA's happens
        to land at ~0.9 ulp on this data — narrowly inside the bound, so
        using it as the discriminator made the assertion flip on backend
        scheduling rather than on the property under test (the pre-PR-5
        known failure). Sequential accumulation is the canonical "plain
        fp32" semantics and misses the bound by ~1300 ulp here on every
        seed — backend-independent, since it never touches XLA."""
        import jax
        import jax.numpy as jnp

        from sartsolver_tpu.models.sart import _sumsq_precise

        jax.config.update("jax_enable_x64", False)
        try:
            precise = jax.jit(lambda v: _sumsq_precise(v, jnp.float32))
            worst_naive_ulp = 0.0
            for seed in range(5):
                rng = np.random.default_rng(seed)
                x = np.exp(rng.uniform(-7, 2, (2, (1 << 17) - 3))
                           ).astype(np.float32)
                ref = np.sum(x.astype(np.float64) ** 2, axis=1)
                ulp = np.spacing(ref.astype(np.float32)).astype(np.float64)
                got = np.asarray(precise(x), np.float64)
                assert np.all(np.abs(got - ref) <= ulp), (
                    seed, (np.abs(got - ref) / ulp).max()
                )
                seq = np.cumsum((x * x).astype(np.float32), axis=1,
                                dtype=np.float32)[:, -1]
                err = np.abs(seq.astype(np.float64) - ref)
                worst_naive_ulp = max(worst_naive_ulp, (err / ulp).max())
            # discriminator: the plain sequential-fp32 accumulation this
            # guards against measurably fails the same bound on the same
            # data (by orders of magnitude, not marginally)
            assert worst_naive_ulp > 1.0, worst_naive_ulp
        finally:
            jax.config.update("jax_enable_x64", True)

    def test_stop_iteration_matches_oracle_without_x64(self):
        """The integrated discriminator (VERDICT r3 next #3): with x64
        off — the configuration real library users run, where the
        compensated path is what feeds the stall test — the tight-tol
        stop iteration must stay in the fp64 oracle's class."""
        import jax

        H, g = self._case()
        tol = 1e-7
        opts = SolverOptions(
            max_iterations=400, conv_tolerance=tol,
            mask_negative_guess=False, guess_floor=0.0,
        )
        _, status_ref, iters_ref, _ = solve_oracle(
            H, g, max_iterations=400, conv_tolerance=tol,
        )
        jax.config.update("jax_enable_x64", False)
        try:
            res = solve(make_problem(H, opts=opts), g, opts=opts)
        finally:
            jax.config.update("jax_enable_x64", True)
        assert int(res.status) == status_ref
        assert abs(int(res.iterations) - iters_ref) <= 1, (
            int(res.iterations), iters_ref,
        )

    def test_stop_iteration_agrees_with_oracle_where_fp32_drifts(self):
        """On a larger problem near a tight tolerance, the precise metric
        must reproduce the fp64 oracle's stop iteration exactly."""
        H, g = self._case()
        tol = 1e-7
        opts = SolverOptions(
            max_iterations=400, conv_tolerance=tol,
            mask_negative_guess=False, guess_floor=0.0,
        )
        res = solve(make_problem(H, opts=opts), g, opts=opts)
        _, status_ref, iters_ref, _ = solve_oracle(
            H, g, max_iterations=400, conv_tolerance=tol,
        )
        assert int(res.status) == status_ref
        # the fp32 *updates* still perturb the iterate slightly, so allow
        # a 1-iteration shift; the metric itself no longer adds noise
        assert abs(int(res.iterations) - iters_ref) <= 1, (
            int(res.iterations), iters_ref,
        )


class TestRelaxationSchedule:
    """alpha_k = relaxation * decay^k (SolverOptions.relaxation_decay).

    The pinning property: an N-iteration scheduled solve must equal N
    chained 1-iteration solves whose fixed relaxation is alpha * decay^k —
    each SART iteration depends on the schedule only through its own
    alpha_k, so the unrolled chain is an independent implementation of the
    same math.
    """

    @pytest.mark.parametrize("logarithmic", [False, True])
    @pytest.mark.parametrize("fused", ["off", "interpret"])
    def test_matches_unrolled_fixed_alpha_chain(self, logarithmic, fused):
        import dataclasses

        H, g, _ = make_case(seed=21, P=24, V=256, neg_pixels=2,
                            zero_voxels=1, zero_pixels=1)
        alpha, decay, n = 0.9, 0.7, 4
        base = SolverOptions(
            relaxation=alpha, relaxation_decay=decay, logarithmic=logarithmic,
            max_iterations=n, conv_tolerance=0.0, fused_sweep=fused,
        )
        problem = make_problem(H, opts=base)
        res_sched = solve(problem, g, opts=base)
        assert int(res_sched.iterations) == n

        f = None
        for k in range(n):
            step = dataclasses.replace(
                base, relaxation=alpha * decay**k, relaxation_decay=1.0,
                max_iterations=1,
            )
            # k=0 uses the same initial guess as the scheduled run
            res = solve(problem, g, f0=f, opts=step)
            f = np.asarray(res.solution)
        np.testing.assert_allclose(
            np.asarray(res_sched.solution), f, rtol=3e-5, atol=1e-7
        )

    def test_decay_one_traces_the_default_program(self):
        """decay == 1.0 must be trace-time inert: the solver jaxpr is
        byte-identical to the default options' jaxpr (no schedule ops in
        the loop), while any decay < 1 traces a different program.
        (End-to-end counterpart: a default CLI run after this feature is
        bit-identical to one from before it.)"""
        import functools

        import jax
        import jax.numpy as jnp

        from sartsolver_tpu.models.sart import solve_normalized_batch

        H, _, _ = make_case(seed=22, P=24, V=128)

        def jaxpr_text(decay):
            opts = SolverOptions(max_iterations=8, conv_tolerance=0.0,
                                 relaxation=0.9, relaxation_decay=decay)
            problem = make_problem(H, opts=opts)
            fn = functools.partial(
                solve_normalized_batch, problem,
                opts=opts, axis_name=None, voxel_axis=None, use_guess=True,
            )
            args = (jnp.ones((1, H.shape[0]), jnp.float32),
                    jnp.ones((1,), jnp.float32),
                    jnp.zeros((1, H.shape[1]), jnp.float32))
            return str(jax.make_jaxpr(fn)(*args))

        default = jaxpr_text(1.0)
        scheduled = jaxpr_text(0.9)
        # the linear solver has no pow anywhere; the schedule's decay^k is
        # exactly one — so its presence IS the scheduled branch having
        # been traced, regardless of which side regresses
        assert "pow" not in default
        assert "pow" in scheduled
        assert default != scheduled

    def test_decay_validation(self):
        with pytest.raises(ValueError, match="relaxation_decay"):
            SolverOptions(relaxation_decay=0.0)
        with pytest.raises(ValueError, match="relaxation_decay"):
            SolverOptions(relaxation_decay=1.5)


class TestFittedCarry:
    """The warm-start fitted carry (models/sart fitted0/return_fitted):
    warm frames skip their setup forward projection by reusing the
    previous loop's exit product ``fitted == H @ f_final``."""

    def _run(self, fitted0=None, return_fitted=False, use_guess=False,
             logarithmic=False, seed=30):
        import jax.numpy as jnp
        from sartsolver_tpu.models.sart import solve_normalized_batch

        H, g, _ = make_case(seed=seed)
        opts = SolverOptions(
            max_iterations=12, conv_tolerance=1e-10, logarithmic=logarithmic
        )
        problem = make_problem(H.astype(np.float32), opts=opts)
        gn = np.where(g > 0, g, -1.0)
        norm = gn.max()
        msq = np.sum(np.where(gn > 0, gn, 0.0) ** 2) / norm**2
        g_dev = jnp.asarray((gn / norm)[None, :], jnp.float32)
        f0 = jnp.full((1, H.shape[1]), 0.4, jnp.float32)
        return problem, dict(
            g=g_dev, msq=jnp.asarray([msq], jnp.float32), f0=f0,
            opts=opts, axis_name=None, voxel_axis=None,
            use_guess=use_guess, fitted0=fitted0,
            return_fitted=return_fitted,
        )

    @pytest.mark.parametrize("logarithmic", [False, True])
    def test_exit_fitted_is_forward_projection(self, logarithmic):
        from sartsolver_tpu.models.sart import solve_normalized_batch

        problem, kw = self._run(return_fitted=True, logarithmic=logarithmic)
        res, fitted = solve_normalized_batch(problem, kw.pop("g"),
                                             kw.pop("msq"), kw.pop("f0"), **kw)
        H32 = np.asarray(problem.rtm, np.float32)
        np.testing.assert_allclose(
            np.asarray(fitted)[0], H32 @ np.asarray(res.solution)[0],
            rtol=2e-5, atol=1e-6,
        )

    @pytest.mark.parametrize("logarithmic", [False, True])
    def test_supplied_fitted0_reproduces_default_bitwise(self, logarithmic):
        """Passing the exact product the impl would compute must give a
        bit-identical solve — the carry changes WHERE the setup product
        comes from, never the loop's math."""
        from sartsolver_tpu.models.sart import solve_normalized_batch
        from sartsolver_tpu.ops.projection import forward_project

        problem, kw = self._run(logarithmic=logarithmic)
        g, msq, f0 = kw.pop("g"), kw.pop("msq"), kw.pop("f0")
        base = solve_normalized_batch(problem, g, msq, f0, **kw)
        # f0 = 0.4 everywhere sits above every floor, so the base path's
        # guess floor is a no-op and the carried path (which skips floors
        # by contract) starts from the identical f0 — the two runs must
        # then be bit-identical, pinning that fitted0 only changes WHERE
        # the setup product comes from, never the loop's math
        kw["fitted0"] = forward_project(
            problem.rtm, f0, accum_dtype=np.float32
        )
        carried = solve_normalized_batch(problem, g, msq, f0, **kw)
        np.testing.assert_array_equal(
            np.asarray(carried.solution), np.asarray(base.solution))
        assert int(carried.iterations[0]) == int(base.iterations[0])
        assert int(carried.status[0]) == int(base.status[0])

    def test_carried_start_skips_guess_floor(self):
        """A carried warm start enters unfloored (exact zeros preserved),
        bit-matching a guess_floor=0 recompute run — the floor guards
        arbitrary user seeds, not the solver's own loop-exit solutions."""
        import dataclasses
        import jax.numpy as jnp
        from sartsolver_tpu.models.sart import solve_normalized_batch
        from sartsolver_tpu.ops.projection import forward_project

        problem, kw = self._run()
        g, msq, _ = kw.pop("g"), kw.pop("msq"), kw.pop("f0")
        f0 = jnp.full((1, np.asarray(problem.rtm).shape[1]), 0.4, jnp.float32)
        f0 = f0.at[0, :5].set(0.0)  # clamp-produced exact zeros
        assert kw["opts"].guess_floor > 0  # the default path WOULD floor
        kw["fitted0"] = forward_project(problem.rtm, f0,
                                        accum_dtype=jnp.float32)
        carried = solve_normalized_batch(problem, g, msq, f0, **kw)

        kw_nf = dict(kw, fitted0=None,
                     opts=dataclasses.replace(kw["opts"], guess_floor=0.0))
        base = solve_normalized_batch(problem, g, msq, f0, **kw_nf)
        np.testing.assert_array_equal(
            np.asarray(carried.solution), np.asarray(base.solution))
        assert int(carried.iterations[0]) == int(base.iterations[0])

    def test_fitted0_with_use_guess_rejected(self):
        import jax.numpy as jnp
        from sartsolver_tpu.models.sart import solve_normalized_batch

        problem, kw = self._run(use_guess=True)
        kw["fitted0"] = jnp.zeros((1, np.asarray(problem.rtm).shape[0]),
                                  jnp.float32)
        with pytest.raises(ValueError, match="use_guess"):
            solve_normalized_batch(problem, kw.pop("g"), kw.pop("msq"),
                                   kw.pop("f0"), **kw)

    def test_carry_skips_setup_sweep_in_hlo(self):
        """The carried variant's lowered HLO must contain exactly one fewer
        dot_general than the recomputed variant (the setup forward
        projection) — pins that the carry actually removes the RTM read."""
        import jax
        from sartsolver_tpu.models.sart import _solve_normalized_batch_impl

        problem, kw = self._run()
        g, msq, f0 = kw.pop("g"), kw.pop("msq"), kw.pop("f0")
        kw.pop("fitted0"), kw.pop("return_fitted")

        def count(fitted0):
            args = (problem, g, msq, f0) + (
                () if fitted0 is None else (fitted0,))

            def fn(problem, g, msq, f0, *rest):
                return _solve_normalized_batch_impl(
                    problem, g, msq, f0,
                    fitted0=rest[0] if rest else None, **kw)

            return jax.jit(fn).lower(*args).as_text().count("dot_general")

        import jax.numpy as jnp
        fitted0 = jnp.ones((1, np.asarray(problem.rtm).shape[0]), jnp.float32)
        n_recompute, n_carried = count(None), count(fitted0)
        assert n_carried == n_recompute - 1, (n_recompute, n_carried)
