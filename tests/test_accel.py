"""Convergence-acceleration drill matrix (docs/PERFORMANCE.md §9).

Pins the ISSUE 10 contracts for ordered-subsets SART and Nesterov/FISTA
momentum:

- the DEFAULT path (os_subsets=1, momentum off) is byte-identical to the
  classic sweep — solutions equal bit-for-bit and the lowered HLO text is
  unchanged;
- the Eq. 6 invariants (non-negativity clamp, ray-density masking) hold
  for every accelerated variant across dtypes and mesh layouts
  (hypothesis property sweep + explicit sharded legs);
- os_subsets must divide the pixel extent, and explicit fused modes are
  rejected with os_subsets > 1;
- the accelerated log solve converges in FEWER iterations at the same
  stall tolerance and lands on the unaccelerated stall point (parity);
- relaxation precedence: relaxation * decay^k folds exactly as documented
  (numpy mirror), momentum restarts never touch relaxation, and an armed
  divergence guard that never trips is byte-identical to guard-off;
- rollback composition: a diverging frame under momentum freezes DIVERGED
  on a finite iterate while its batch peers converge unaffected;
- continuous batching: retired-lane results are byte-identical to the
  non-scheduled batch for accelerated variants, per-lane momentum state
  rides SchedState, and ONE compiled stride program serves every
  occupancy;
- the new compile-audit entries (os_sweep / momentum_sweep /
  log_accel_sweep) are registered with committed goldens.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

from sartsolver_tpu.config import SolverOptions  # noqa: E402
from sartsolver_tpu.models.sart import (  # noqa: E402
    SARTProblem,
    _solve_normalized_batch_impl,
    compute_ray_stats,
    make_problem,
    solve_normalized_batch,
)

P, V = 32, 128


def _problem(seed=0, banded=True, dead_voxels=(), **opts_kw):
    """Small dense problem; ``dead_voxels`` get all-zero columns so the
    ray-density mask (Eq. 6) has something to mask."""
    rng = np.random.default_rng(seed)
    H = rng.random((P, V)).astype(np.float32) * 0.9 + 0.1
    if banded:
        ii = np.arange(P, dtype=np.float32)[:, None] / P
        jj = np.arange(V, dtype=np.float32)[None, :] / V
        H = H * (np.exp(-((ii - jj) ** 2) * 100.0) + 0.02)
    for v in dead_voxels:
        H[:, v] = 0.0
    f_true = (1.0 + 0.5 * np.sin(2 * np.pi * np.arange(V) / V)).astype(
        np.float64
    )
    g = H.astype(np.float64) @ f_true
    norm = g.max()
    g_n = (g / norm).astype(np.float32)
    msq = np.float32((np.where(g > 0, g, 0) ** 2).sum() / norm**2)
    opts = SolverOptions(
        max_iterations=200, conv_tolerance=1e-5, fused_sweep="off",
        **opts_kw,
    )
    problem = make_problem(H, opts=opts)
    return problem, g_n, msq, opts, f_true, norm


def _solve(problem, g_n, msq, opts, B=1):
    res = solve_normalized_batch(
        problem, jnp.asarray(np.tile(g_n, (B, 1))),
        jnp.full((B,), msq, jnp.float32),
        jnp.zeros((B, V), jnp.float32), opts=opts, axis_name=None,
        voxel_axis=None, use_guess=True,
    )
    return (np.asarray(res.solution), np.asarray(res.iterations),
            np.asarray(res.status))


# ---------------------------------------------------------------------------
# default-path identity
# ---------------------------------------------------------------------------


def test_default_path_bit_identical():
    """os_subsets=1 + momentum off must be byte-identical to an opts
    object that never heard of the accelerators — solutions AND the
    lowered program text."""
    problem, g_n, msq, opts, _, _ = _problem()
    explicit = SolverOptions(
        max_iterations=200, conv_tolerance=1e-5, fused_sweep="off",
        os_subsets=1, momentum="off",
    )
    sol_a, it_a, _ = _solve(problem, g_n, msq, opts)
    sol_b, it_b, _ = _solve(problem, g_n, msq, explicit)
    assert np.array_equal(sol_a, sol_b)
    assert np.array_equal(it_a, it_b)

    def lower(o):
        import functools

        return jax.jit(functools.partial(
            _solve_normalized_batch_impl, opts=o, axis_name=None,
            voxel_axis=None, use_guess=True,
        )).lower(
            problem, jax.ShapeDtypeStruct((1, P), jnp.float32),
            jax.ShapeDtypeStruct((1,), jnp.float32),
            jax.ShapeDtypeStruct((1, V), jnp.float32),
        ).as_text()

    assert lower(opts) == lower(explicit)


# ---------------------------------------------------------------------------
# validation
# ---------------------------------------------------------------------------


def test_options_validation():
    with pytest.raises(ValueError, match="os_subsets"):
        SolverOptions(os_subsets=0)
    with pytest.raises(ValueError, match="momentum"):
        SolverOptions(momentum="heavy-ball")
    for mode in ("on", "interpret"):
        with pytest.raises(ValueError, match="os_subsets"):
            SolverOptions(os_subsets=4, fused_sweep=mode)
    # auto/off compose fine
    SolverOptions(os_subsets=4, fused_sweep="auto")
    SolverOptions(os_subsets=4, momentum="nesterov")


def test_os_subsets_must_divide_pixels():
    problem, g_n, msq, _, _, _ = _problem()
    opts = SolverOptions(
        max_iterations=5, conv_tolerance=1e-5, fused_sweep="off",
        os_subsets=5,  # P = 32, 32 % 5 != 0
    )
    with pytest.raises(ValueError, match="divide"):
        _solve(problem, g_n, msq, opts)


# ---------------------------------------------------------------------------
# invariants across the variant matrix
# ---------------------------------------------------------------------------

VARIANTS = [
    dict(os_subsets=4),
    dict(momentum="nesterov"),
    dict(os_subsets=4, momentum="nesterov"),
    dict(logarithmic=True, os_subsets=4),
    dict(logarithmic=True, momentum="nesterov"),
    dict(logarithmic=True, os_subsets=4, momentum="nesterov"),
]


@pytest.mark.parametrize("kw", VARIANTS,
                         ids=lambda kw: "-".join(f"{k}={v}" for k, v in
                                                 sorted(kw.items())))
@pytest.mark.parametrize("rtm_dtype", [None, "bfloat16", "int8"])
def test_invariants_variant_matrix(kw, rtm_dtype):
    """Non-negativity and ray-density masking hold for every accelerated
    variant and storage dtype; solutions stay finite and converge."""
    if rtm_dtype == "int8" and kw.get("os_subsets", 1) == 1:
        pytest.skip("int8 without OS requires the fused sweep (own tests)")
    dead = (3, 70)
    # guess_floor=0 so the linear masking assertion below sees an exact
    # zero at dead voxels (the default floor would hold them at 1e-7 —
    # also never updated, just less crisp to assert); the log path keeps
    # its unconditional log_epsilon floor either way
    problem, g_n, msq, opts, _, _ = _problem(
        dead_voxels=dead, rtm_dtype=rtm_dtype, guess_floor=0.0, **kw
    )
    sol, iters, status = _solve(problem, g_n, msq, opts)
    assert np.all(np.isfinite(sol))
    assert status[0] == 0, f"did not converge: {iters[0]} iterations"
    if kw.get("logarithmic"):
        # the multiplicative update keeps a positive iterate positive
        live = np.ones(V, bool)
        live[list(dead)] = False
        assert np.all(sol[0, live] > 0)
    else:
        assert np.all(sol[0] >= 0)
    # Eq. 6: a voxel below the ray-density threshold is never updated —
    # the zero initial guess stays exactly zero there (log: the guess
    # floor value survives unchanged, see make_problem/guess floors)
    if not kw.get("logarithmic"):
        assert np.all(sol[0, list(dead)] == 0.0)


try:
    import hypothesis  # noqa: F401

    HAVE_HYPOTHESIS = True
except ImportError:
    HAVE_HYPOTHESIS = False

if HAVE_HYPOTHESIS:
    from hypothesis import given, settings, strategies as st

    @settings(max_examples=10, deadline=None, derandomize=True)
    @given(
        seed=st.integers(0, 2**16),
        os_subsets=st.sampled_from([1, 2, 4, 8]),
        momentum=st.sampled_from(["off", "nesterov"]),
        logarithmic=st.booleans(),
    )
    def test_invariants_property(seed, os_subsets, momentum, logarithmic):
        """Hypothesis sweep: clamp/masking/finiteness invariants for any
        problem seed under subset cycling and momentum extrapolation."""
        problem, g_n, msq, opts, _, _ = _problem(
            seed=seed, dead_voxels=(7,), os_subsets=os_subsets,
            momentum=momentum, logarithmic=logarithmic, guess_floor=0.0,
        )
        sol, _, _ = _solve(problem, g_n, msq, opts)
        assert np.all(np.isfinite(sol))
        if logarithmic:
            assert np.all(sol[0, np.arange(V) != 7] > 0)
        else:
            assert np.all(sol[0] >= 0)
            assert sol[0, 7] == 0.0


# ---------------------------------------------------------------------------
# acceleration + parity
# ---------------------------------------------------------------------------


def test_accelerated_log_fewer_iterations_and_parity():
    """The headline contract: the accelerated log solve reaches the SAME
    stall tolerance in fewer iterations and lands on the unaccelerated
    stall point (both are eps-stationary points of one problem)."""
    problem, g_n, msq, base, _, _ = _problem(seed=3, logarithmic=True)
    accel = SolverOptions(
        max_iterations=200, conv_tolerance=1e-5, fused_sweep="off",
        logarithmic=True, os_subsets=4, momentum="nesterov",
    )
    sol_b, it_b, st_b = _solve(problem, g_n, msq, base)
    sol_a, it_a, st_a = _solve(problem, g_n, msq, accel)
    assert st_b[0] == 0 and st_a[0] == 0
    assert it_a[0] < it_b[0], (it_a[0], it_b[0])
    rel = np.linalg.norm(sol_a - sol_b) / np.linalg.norm(sol_b)
    assert rel < 0.05, rel


def test_momentum_accelerates_linear():
    problem, g_n, msq, base, _, _ = _problem(seed=5)
    accel = SolverOptions(
        max_iterations=200, conv_tolerance=1e-5, fused_sweep="off",
        momentum="nesterov",
    )
    _, it_b, st_b = _solve(problem, g_n, msq, base)
    _, it_a, st_a = _solve(problem, g_n, msq, accel)
    assert st_b[0] == 0 and st_a[0] == 0
    assert it_a[0] < it_b[0], (it_a[0], it_b[0])


# ---------------------------------------------------------------------------
# relaxation precedence (config.py contract)
# ---------------------------------------------------------------------------


def test_relaxation_decay_fold_matches_numpy_mirror():
    """Pinned precedence: iteration k's step scale is relaxation * decay^k
    (one multiplicative product), with momentum extrapolating AROUND that
    scale, never into it. A numpy mirror of the documented semantics must
    match the device loop to fp32 tolerance at a fixed iteration count."""
    rng = np.random.default_rng(11)
    H = rng.random((P, V)).astype(np.float32) * 0.9 + 0.1
    g = H.astype(np.float64) @ (np.ones(V) * 0.5)
    norm = g.max()
    g_n = (g / norm).astype(np.float32)
    msq = np.float32((g / norm).dot(g / norm))
    relax, decay, iters = 0.5, 0.8, 4

    for mom in ("off", "nesterov"):
        opts = SolverOptions(
            max_iterations=iters, conv_tolerance=0.0, fused_sweep="off",
            relaxation=relax, relaxation_decay=decay, momentum=mom,
            guess_floor=0.0,
        )
        problem = make_problem(H, opts=opts)
        sol, _, _ = _solve(problem, g_n, msq, opts)

        H64 = H.astype(np.float64)
        length = H64.sum(1)
        dens = H64.sum(0)
        f = (H64.T @ g_n.astype(np.float64)) / dens  # Eq. 4 guess
        f_prev, tk = f.copy(), 1.0
        for k in range(iters):
            if mom == "nesterov":
                t_next = 0.5 * (1 + np.sqrt(1 + 4 * tk * tk))
                beta = (tk - 1) / t_next
                y = f + beta * (f - f_prev)
            else:
                y = f
            w = (g_n - H64 @ y) / length
            # THE pinned fold: base relaxation rides the inverse density,
            # decay^k scales the pixel weights — one product
            f_new = np.maximum(
                y + (H64.T @ (w * decay**k)) * (relax / dens), 0
            )
            if mom == "nesterov":
                rs = np.dot(y - f_new, f_new - f) > 0
                tk = 1.0 if rs else t_next
                f_prev = f
            f = f_new
        np.testing.assert_allclose(sol[0], f, rtol=2e-4, atol=2e-5)


def test_armed_guard_untripped_is_identical():
    """An armed divergence guard that never fires composes with the
    accelerators as a no-op: ascale = 1 folds exactly, so solutions are
    byte-identical to guard-off — the precedence product's third factor
    is inert until a rollback."""
    for kw in (dict(os_subsets=4, momentum="nesterov"),
               dict(logarithmic=True, os_subsets=4, momentum="nesterov")):
        problem, g_n, msq, off, _, _ = _problem(seed=7, **kw)
        armed = SolverOptions(
            max_iterations=200, conv_tolerance=1e-5, fused_sweep="off",
            divergence_recovery=3, **kw,
        )
        sol_off, it_off, _ = _solve(problem, g_n, msq, off)
        sol_on, it_on, _ = _solve(problem, g_n, msq, armed)
        assert np.array_equal(sol_off, sol_on)
        assert np.array_equal(it_off, it_on)


def test_momentum_rollback_composition():
    """A frame whose iterate explodes under momentum freezes DIVERGED on
    a finite iterate (the rollback target is never an extrapolated
    point), while a healthy frame in the same batch converges to exactly
    its solo solution."""
    problem, g_n, msq, _, _, _ = _problem(seed=9)
    opts = SolverOptions(
        max_iterations=50, conv_tolerance=1e-5, fused_sweep="off",
        momentum="nesterov", divergence_recovery=2,
        divergence_threshold=1.001,
    )
    # frame 0 healthy; frame 1's measurement is inflated 10x while its
    # declared ||g||^2 is not — as the solve fits the inflated data,
    # ||Hf||^2 crosses threshold * max(msq, 1) and the guard trips until
    # the ladder exhausts (the linear clamp makes a true NaN explosion
    # hard to stage; the metric-vs-measurement mismatch is the drill)
    g2 = np.stack([g_n, g_n * 10.0])
    msq2 = np.asarray([msq, msq], np.float32)
    f0 = np.zeros((2, V), np.float32)
    res = solve_normalized_batch(
        problem, jnp.asarray(g2), jnp.asarray(msq2), jnp.asarray(f0),
        opts=opts, axis_name=None, voxel_axis=None, use_guess=False,
    )
    status = np.asarray(res.status)
    sol = np.asarray(res.solution)
    assert status[1] == -2  # DIVERGED after the ladder exhausted
    assert np.all(np.isfinite(sol))
    assert status[0] == 0
    solo = solve_normalized_batch(
        problem, jnp.asarray(g_n[None]), jnp.asarray([msq]),
        jnp.zeros((1, V), jnp.float32), opts=opts, axis_name=None,
        voxel_axis=None, use_guess=False,
    )
    # B=2 vs B=1 changes the gemm reduction order (not the math): the
    # healthy frame matches its solo solve to reduction tolerance
    np.testing.assert_allclose(sol[0], np.asarray(solo.solution)[0],
                               rtol=1e-5, atol=1e-7)


# ---------------------------------------------------------------------------
# sharded layouts + continuous batching composition
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kw", [
    dict(os_subsets=4, momentum="nesterov"),
    dict(logarithmic=True, os_subsets=4, momentum="nesterov"),
])
@pytest.mark.parametrize("mesh_shape", [(2, 1), (1, 2), (2, 2)])
def test_sharded_accel_matches_single_device(kw, mesh_shape):
    """Accelerated solves agree across mesh layouts: the subset psums and
    the momentum restart's voxel-axis reduction reproduce the one-device
    result within fp32 reduction tolerance."""
    from sartsolver_tpu.parallel.mesh import make_mesh
    from sartsolver_tpu.parallel.sharded import DistributedSARTSolver

    problem, g_n, msq, opts, _, norm = _problem(seed=13, **kw)
    sol_1, it_1, _ = _solve(problem, g_n, msq, opts)

    rng = np.random.default_rng(13)
    H = rng.random((P, V)).astype(np.float32) * 0.9 + 0.1
    ii = np.arange(P, dtype=np.float32)[:, None] / P
    jj = np.arange(V, dtype=np.float32)[None, :] / V
    H = H * (np.exp(-((ii - jj) ** 2) * 100.0) + 0.02)
    solver = DistributedSARTSolver(
        H, opts=opts, mesh=make_mesh(*mesh_shape)
    )
    try:
        res = solver.solve(np.asarray(g_n, np.float64) * norm)
        np.testing.assert_allclose(
            res.solution / norm, sol_1[0], rtol=5e-4, atol=5e-5
        )
        assert abs(int(res.iterations) - int(it_1[0])) <= 2
    finally:
        solver.close()


def test_sched_accel_parity_and_one_program():
    """Continuous batching with accelerators on: retired lanes are
    byte-identical to the non-scheduled batch path (per-lane momentum
    state in SchedState), and ONE compiled stride program serves every
    occupancy across refills."""
    from sartsolver_tpu.parallel.mesh import make_mesh
    from sartsolver_tpu.parallel.sharded import DistributedSARTSolver
    from sartsolver_tpu.sched import ContinuousBatcher

    rng = np.random.default_rng(21)
    H = rng.random((P, V)).astype(np.float32) * 0.9 + 0.1
    ii = np.arange(P, dtype=np.float32)[:, None] / P
    jj = np.arange(V, dtype=np.float32)[None, :] / V
    H = H * (np.exp(-((ii - jj) ** 2) * 100.0) + 0.02)
    N = 6
    frames = []
    for i in range(N):
        f_i = np.maximum(
            1.0 + 0.5 * np.sin(2 * np.pi * np.arange(V) / V + i), 1e-3
        )
        frames.append(np.maximum(H.astype(np.float64) @ f_i, 0.0))
    opts = SolverOptions(
        max_iterations=300, conv_tolerance=1e-5, fused_sweep="off",
        schedule_stride=8, os_subsets=4, momentum="nesterov",
    )
    solver = DistributedSARTSolver(H, opts=opts, mesh=make_mesh(2, 1))
    try:
        base_sols, base_its = [], []
        for s in range(0, N, 2):
            res = solver.solve_batch(np.stack(frames[s:s + 2]),
                                     device_result=True)
            base_sols.append(res.fetch_solutions())
            base_its.append(res.iterations)
        base_sols = np.concatenate(base_sols)
        base_its = np.concatenate(base_its)

        got = {}
        batcher = ContinuousBatcher(
            solver, lanes=2,
            on_result=lambda ft, _ct, st, it, _cv, fe, _ms:
                got.__setitem__(int(ft), (st, it, fe)),
            on_failed=lambda ft, _ct, e:
                (_ for _ in ()).throw(RuntimeError(str(e))),
        )
        batcher.run((frames[i], float(i), ()) for i in range(N))
        for i in range(N):
            assert got[i][1] == base_its[i], (i, got[i][1], base_its[i])
            assert np.array_equal(got[i][2](), base_sols[i]), i
        assert solver._sched_fn()._cache_size() == 1
    finally:
        solver.close()


# ---------------------------------------------------------------------------
# metrics tooling: variant guard + tts gate
# ---------------------------------------------------------------------------


def _run_artifact(os_subsets, iters):
    from sartsolver_tpu.obs import schema

    return [
        schema.make_meta_record(os_subsets=os_subsets, momentum="off",
                                logarithmic=False),
        schema.make_frame_record(0.0, 0, "SUCCESS", iters, 10.0, 1e-6,
                                 "g0", os_subsets=os_subsets,
                                 momentum="off", logarithmic=False),
        {"type": "metric", "kind": "histogram",
         "name": "iterations_to_converge", "labels": {},
         "count": 1, "sum": float(iters), "min": float(iters),
         "max": float(iters)},
        schema.make_summary_record(1, {"SUCCESS": 1}),
    ]


def test_metrics_diff_variant_guard():
    """`sartsolve metrics --diff` must never compare convergence behavior
    across solver variants silently: mismatched os_subsets/momentum meta
    skips the iterations/solve-ms gates with a loud note."""
    from sartsolver_tpu.obs import cli as obs_cli

    old = obs_cli.summarize(_run_artifact(1, 100))
    new = obs_cli.summarize(_run_artifact(4, 30))
    delta = obs_cli.diff(old, new)
    assert delta["iterations_to_converge_mean_pct"] is None
    assert delta["solve_ms_mean_pct"] is None
    assert any("variant differs" in n for n in delta["notes"])
    # same variant on both sides: the gates run
    same = obs_cli.diff(obs_cli.summarize(_run_artifact(4, 100)),
                        obs_cli.summarize(_run_artifact(4, 30)))
    assert same["iterations_to_converge_mean_pct"] is not None
    assert not any("variant differs" in n for n in same["notes"])


def test_metrics_tts_gate_direction():
    """The tts log iteration speedup is a rate: a drop is the regression
    direction, and a one-sided section produces the loud skip-note."""
    from sartsolver_tpu.obs import cli as obs_cli, schema

    def bench_art(speedup):
        return [schema.make_bench_record(
            "iter_s", 100.0, "iter/s", 1.0,
            {"tts": {"log": {"iter_speedup": speedup, "iters_base": 11,
                             "iters_accel": 3, "parity": True}}},
        )]

    delta = obs_cli.diff(obs_cli.summarize(bench_art(3.6)),
                         obs_cli.summarize(bench_art(2.0)))
    assert delta["tts_log_speedup_pct"] == pytest.approx(-44.44, abs=0.1)
    one_sided = obs_cli.diff(
        obs_cli.summarize(bench_art(3.6)),
        obs_cli.summarize([schema.make_bench_record(
            "iter_s", 100.0, "iter/s", 1.0, {})]),
    )
    assert one_sided["tts_log_speedup_pct"] is None
    assert any("tts" in n and "skipped" in n for n in one_sided["notes"])
    # parity=False in the NEW artifact is a hard correctness gate, even
    # with a better-looking speedup (fewer iterations to a wrong answer)
    bad = obs_cli.summarize([schema.make_bench_record(
        "iter_s", 100.0, "iter/s", 1.0,
        {"tts": {"log": {"iter_speedup": 9.0, "iters_base": 11,
                         "iters_accel": 1, "parity": False}}},
    )])
    gated = obs_cli.diff(obs_cli.summarize(bench_art(3.6)), bad)
    assert gated["tts_parity_failed"] == ["log"]


def test_metrics_variant_from_frame_records():
    """A frame-sliced artifact (no meta line) still declares its variant
    through the per-frame fields, so the mismatch guard fires."""
    from sartsolver_tpu.obs import cli as obs_cli

    sliced_old = _run_artifact(1, 100)[1:]  # drop the meta record
    sliced_new = _run_artifact(4, 30)[1:]
    delta = obs_cli.diff(obs_cli.summarize(sliced_old),
                         obs_cli.summarize(sliced_new))
    assert delta["iterations_to_converge_mean_pct"] is None
    assert any("variant differs" in n for n in delta["notes"])


# ---------------------------------------------------------------------------
# audit entries
# ---------------------------------------------------------------------------


def test_accel_audit_entries_registered_with_goldens():
    from sartsolver_tpu.analysis import registry

    names = set(registry.load_registered_entries())
    for entry in ("os_sweep", "momentum_sweep", "log_accel_sweep"):
        assert entry in names, f"audit entry {entry} not registered"
        base = os.path.join(
            os.path.dirname(registry.__file__), "goldens", f"{entry}.cpu"
        )
        assert os.path.exists(base + ".json"), f"missing golden for {entry}"
        assert os.path.exists(base + ".cost.json"), (
            f"missing cost golden for {entry}"
        )
