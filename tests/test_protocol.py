"""Crash-point model checker drills (analysis/protocol.py).

The checker's job is to FAIL when the exactly-once protocol regresses,
so beyond the green exhaustive pass the drills here re-introduce the
bugs this PR (and PR 15) fixed and assert the checker catches each:

- the PR 15 replay gate (republish on *missing* response only — the
  real kill leaves a stale ``pending`` response behind);
- the recount path disabled (a kill between the completed marker and
  the next checkpoint silently loses outcome counters);
- the response publish dropping ``fsync=True`` (a crash straddling the
  rename publishes a torn "atomic" file).

Plus the torn-write drills for the atomicio primitives the checker
leans on: the self-sealing append (a torn tail must never swallow the
next record — the checker found exactly that bug on its first
exhaustive pass) and journal replay under truncation at every byte,
and the startup orphan-tmp sweep with its server metric.
"""

import json
import os

import pytest

import sartsolver_tpu.analysis.protocol as ap
import sartsolver_tpu.engine.protocol as ep
from sartsolver_tpu.engine.journal import RequestJournal
from sartsolver_tpu.engine.request import Request
from sartsolver_tpu.utils import atomicio

@pytest.fixture(autouse=True)
def _shm_tmpdir(monkeypatch):
    # the drills spin up hundreds of fsync-heavy scratch dirs; tmpfs
    # makes that free without weakening the check (the crash states are
    # constructed, not produced by real power loss)
    if os.path.isdir("/dev/shm"):
        import tempfile

        monkeypatch.setenv("TMPDIR", "/dev/shm")
        tempfile.tempdir = None
        yield
        tempfile.tempdir = None
    else:
        yield


# ---------------------------------------------------------------------------
# the exhaustive pass
# ---------------------------------------------------------------------------


def test_exhaustive_pass_green_and_reaches_every_effect_point():
    rep = ap.run_protocol_check(byte_stride=3)
    assert rep.ok, "\n".join(rep.violations)
    assert rep.commit_order_ok
    # the workload arms every declared effect point at least once — a
    # point the checker cannot reach is a hole in the exhaustiveness
    # claim
    armed = set(rep.scenarios_by_effect)
    declared = {p.name for p in ep.PROTOCOL}
    assert armed == declared
    # every append effect contributes multiple torn-byte states
    assert rep.scenarios_by_effect["journal.completed"] > 10
    assert rep.scenarios_by_effect["state.checkpoint"] > 10


def test_enumeration_dwarfs_the_sampled_chaos_campaign():
    """Acceptance: the checker's crash states must outnumber the chaos
    campaign's sampled kill windows (CI seed set x max kills per seed)
    by a wide margin — exhaustive vs sampled is the whole point."""
    rep = ap.run_protocol_check(byte_stride=6)
    ci_seeds = os.environ.get("SART_CHAOS_SEEDS", "3,5").split(",")
    sampled = len([s for s in ci_seeds if s.strip()]) * 2  # max_kills=2
    assert rep.scenarios_total > 10 * sampled
    # and stride 1 (make protocol) covers every byte: strictly more
    # scenarios than any thinned run
    assert rep.scenarios_total > rep.effects_armed


def test_report_maps_violations_to_chaos_windows(monkeypatch):
    """A violation at a chaos-sampled effect names the kill window so
    the runbook can cross-reference `sartsolve chaos` output."""
    monkeypatch.setattr(ep, "uncounted_completed",
                        lambda completed, counted: [])
    rep = ap.run_protocol_check(byte_stride=30)
    assert not rep.ok
    assert any("chaos kill window: ckpt" in v for v in rep.violations)


# ---------------------------------------------------------------------------
# re-broken-bug regression drills
# ---------------------------------------------------------------------------


def test_pr15_missing_only_republish_gate_is_caught(monkeypatch):
    """Re-break the PR 15 replay bug: gate the republish on a MISSING
    response only. The kill after the completed-marker fsync leaves the
    stale `pending` acceptance response behind, and the checker must
    see it survive recovery."""

    def broken(outcome, prev, *, response_ttl_s, now=None):
        import time as _t

        if not outcome:
            return False
        now = _t.time() if now is None else now
        done = float(outcome.get("journal_unix") or now)
        fresh = (not response_ttl_s) or (now - done < response_ttl_s)
        return bool(fresh and prev is None)  # <- the bug

    monkeypatch.setattr(ep, "needs_republish", broken)
    rep = ap.run_protocol_check(byte_stride=30)
    assert not rep.ok
    assert any("stuck in state 'pending'" in v for v in rep.violations)


def test_disabled_recount_loses_counters_and_is_caught(monkeypatch):
    monkeypatch.setattr(ep, "uncounted_completed",
                        lambda completed, counted: [])
    rep = ap.run_protocol_check(byte_stride=30)
    assert not rep.ok
    assert any("counters" in v for v in rep.violations)


def test_response_publish_without_fsync_is_caught(monkeypatch):
    """Re-break the server bug this PR fixed: response publishes with
    fsync=False. The shim then models the rename landing with only a
    data prefix durable, and the checker must flag the torn published
    response BEFORE recovery even runs (clients read at any instant)."""
    monkeypatch.setattr(ap, "RESPONSE_FSYNC", False)
    rep = ap.run_protocol_check(byte_stride=30)
    assert not rep.ok
    assert any("torn" in v and "atomic-publish" in v
               for v in rep.violations)


# ---------------------------------------------------------------------------
# atomicio torn-write drills
# ---------------------------------------------------------------------------


def test_append_seals_a_torn_tail(tmp_path):
    """The bug the checker found on its first exhaustive pass: a torn
    final line has no newline, and a plain append would concatenate the
    next record onto it — one garbage line swallowing BOTH records.
    append_line must seal the tail so the new record survives."""
    path = str(tmp_path / "log.jsonl")
    atomicio.append_line(path, json.dumps({"n": 1}) + "\n")
    with open(path, "a") as f:
        f.write('{"n": 2, "torn')  # kill -9 mid-append
    atomicio.append_line(path, json.dumps({"n": 3}) + "\n")
    lines = open(path).read().splitlines()
    parsed = []
    for ln in lines:
        try:
            parsed.append(json.loads(ln))
        except ValueError:
            parsed.append(None)
    assert parsed[0] == {"n": 1}
    assert parsed[-1] == {"n": 3}, "record after a torn tail was lost"
    assert parsed.count(None) == 1  # the torn line, sealed on its own


def test_append_after_every_truncation_point(tmp_path):
    """Property drill: whatever prefix of the file a crash leaves, the
    next append_line lands a parseable final record."""
    base = str(tmp_path / "base.jsonl")
    for i in range(3):
        atomicio.append_line(base, json.dumps({"i": i}) + "\n")
    data = open(base, "rb").read()
    rec = json.dumps({"i": "after"}) + "\n"
    for cut in range(len(data) + 1):
        path = str(tmp_path / f"cut{cut}.jsonl")
        with open(path, "wb") as f:
            f.write(data[:cut])
        atomicio.append_line(path, rec)
        last = open(path).read().splitlines()[-1]
        assert json.loads(last) == {"i": "after"}


def test_journal_replay_tolerates_truncation_at_every_byte(tmp_path):
    """The real journal + real replay under every torn-tail state: no
    exception, and the recovered story is always a consistent prefix
    (never a completed id the journal prefix does not contain)."""
    j = RequestJournal(str(tmp_path / "journal.jsonl"))
    reqs = [Request(id=f"r{i}", trace=f"t{i}") for i in range(3)]
    for r in reqs:
        j.accepted(r)
        j.dispatched(r)
        j.completed(r, {"status": "completed"})
    data = open(j.path, "rb").read()
    all_ids = {r.id for r in reqs}
    prev_known = -1
    for cut in range(len(data) + 1):
        p = str(tmp_path / "cut.jsonl")
        with open(p, "wb") as f:
            f.write(data[:cut])
        completed, pending = RequestJournal(p).replay()
        known = set(completed) | {r.id for r in pending}
        assert known <= all_ids
        # longer prefixes never know fewer requests
        assert len(known) >= prev_known
        prev_known = len(known)
    assert prev_known == 3


def test_sweep_orphans_removes_only_tmp_files(tmp_path):
    d = str(tmp_path)
    open(os.path.join(d, "keep.json"), "w").write("{}")
    open(os.path.join(d, "a.json.123.tmp"), "w").write("debris")
    open(os.path.join(d, "b.json.456.tmp"), "w").write("debris")
    os.makedirs(os.path.join(d, "sub.tmp"))  # directory: not swept
    assert atomicio.sweep_orphans(d) == 2
    assert sorted(os.listdir(d)) == ["keep.json", "sub.tmp"]
    assert atomicio.sweep_orphans(os.path.join(d, "missing")) == 0


def test_server_startup_sweep_counts_into_retention_metric(tmp_path):
    """The server's startup sweep removes publish debris from all three
    durable dirs and counts it into engine_retention_deleted_total
    (same family as the TTL sweep — one dashboard, both reclaim
    paths). EngineServer.__init__ never touches the session, so a
    bare object() stands in."""
    from sartsolver_tpu.engine.server import EngineServer
    from sartsolver_tpu.obs import metrics as obs_metrics

    eng = str(tmp_path / "engine")
    server = EngineServer(object(), engine_dir=eng, lanes=1)
    os.makedirs(os.path.join(eng, "traces"), exist_ok=True)
    for rel in ("journal.jsonl.77.tmp", "responses/r1.json.77.tmp",
                "traces/r1.trace.json.77.tmp"):
        with open(os.path.join(eng, rel), "w") as f:
            f.write("debris")

    def _swept(snapshot):
        return sum(
            s["value"] for s in snapshot
            if s["name"] == "engine_retention_deleted_total"
            and s["labels"].get("dir") in ("engine", "responses",
                                           "traces"))

    before = _swept(obs_metrics.get_registry().snapshot())
    server._sweep_orphan_tmp()
    after = _swept(obs_metrics.get_registry().snapshot())
    assert after - before == 3
    assert not [n for n in os.listdir(eng) if n.endswith(".tmp")]
    assert not os.listdir(os.path.join(eng, "responses"))


def test_supervisor_event_append_is_sealed_and_fsynced(tmp_path):
    """Satellite: supervisor.jsonl appends ride atomicio (flush+fsync
    + torn-tail seal) — the record of a crash must survive the crash,
    and a torn tail from the previous incarnation must not swallow the
    restart's first event."""
    from sartsolver_tpu.resilience.supervisor import Supervisor

    sup = Supervisor.__new__(Supervisor)
    sup.events_path = str(tmp_path / "supervisor.jsonl")
    sup.prom_path = str(tmp_path / "supervisor.prom")
    with open(sup.events_path, "w") as f:
        f.write('{"kind": "worker-exit", "torn')  # previous crash
    sup._event("respawn", attempt=1)
    lines = open(sup.events_path).read().splitlines()
    assert json.loads(lines[-1])["kind"] == "respawn"
