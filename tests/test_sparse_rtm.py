"""Block-sparse RTM drill matrix (docs/PERFORMANCE.md §10, `make sparse`).

Parity gates, in decreasing strictness:

- **Sweep-level bit parity** — skipping an all-zero voxel panel is
  bit-neutral: the sparse panel sweep with the real occupancy index is
  ``array_equal`` to the same sweep with a full (dense-equivalent)
  index across multi-iteration compositions, every update closure, and
  the gather fallback. This is the "skipping changes nothing" proof the
  eps=0 mode rests on.
- **Solver-level parity** — end-to-end solves against the classic dense
  paths agree in iteration counts/statuses exactly and in values to the
  reassociation tolerance (``utils.fused_parity.PARITY_RTOL`` — XLA may
  regroup the dense comparator's reductions differently, the same bound
  the fused-vs-unfused gate uses), across linear/log/int8 x meshes x
  os_subsets/momentum.
- **eps > 0** — the thresholded solve is residual-matched: it fits the
  measurement about as well as dense while the dropped tiles' voxels
  mask out via the Eq. 6 stats of the thresholded operator.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sartsolver_tpu.config import SolverOptions
from sartsolver_tpu.models.sart import (
    FUSED_ENGAGEMENT,
    make_problem,
    make_sparse_problem,
    solve_normalized_batch,
)
from sartsolver_tpu.ops.fused_sweep import (
    sparse_gather_sweep,
    sparse_panel_sweep,
)
from sartsolver_tpu.ops.sparse import (
    TILE_COLS,
    TILE_ROWS,
    TileMaxStats,
    TileOccupancy,
    build_tile_occupancy,
    threshold_matrix,
)
from sartsolver_tpu.utils.fused_parity import PARITY_RTOL

P, V, BS = 32, 512, 128  # 4 voxel panels of 128; panels 1 and 3 empty


def _world(seed=0, empty_panels=(1, 3)):
    rng = np.random.default_rng(seed)
    H = (rng.random((P, V), dtype=np.float32) * 0.9 + 0.1)
    for j in empty_panels:
        H[:, j * BS:(j + 1) * BS] = 0.0
    f_true = rng.random(V).astype(np.float32) + 0.5
    G = (H.astype(np.float64) @ f_true.astype(np.float64))[None, :]
    norm = G.max()
    msq = float(np.sum(np.where(G > 0, G, 0.0) ** 2) / norm ** 2)
    g = (G / norm).astype(np.float32)
    return H, g, msq


def _solve(H, g, msq, opts, tile_occupancy=None, B=1, axis_name=None):
    if opts.sparse_epsilon() is not None and tile_occupancy is None:
        problem, tile_occupancy = make_sparse_problem(H, opts=opts)
    else:
        problem = make_problem(H, opts=opts)
    gd = jnp.asarray(np.broadcast_to(g, (B, g.shape[1])).copy())
    msqd = jnp.full((B,), msq, jnp.float32)
    f0 = jnp.zeros((B, H.shape[1]), jnp.float32)
    return solve_normalized_batch(
        problem, gd, msqd, f0, opts=opts, axis_name=axis_name,
        voxel_axis=None, use_guess=True, tile_occupancy=tile_occupancy,
    )


# --------------------------------------------------------------------------
# tile-occupancy index units
# --------------------------------------------------------------------------


def test_occupancy_build_and_queries():
    H, _, _ = _world()
    occ = build_tile_occupancy(H)
    assert occ.grid_shape == (P // TILE_ROWS, V // TILE_COLS)
    assert occ.occupancy_fraction() == pytest.approx(0.5)
    np.testing.assert_array_equal(
        occ.col_panel_occupied(BS), [True, False, True, False]
    )
    # coarser panels: a panel is occupied if ANY covered tile is
    np.testing.assert_array_equal(
        occ.col_panel_occupied(2 * BS), [True, True]
    )
    occ.verify()  # round trip through its own digest


def test_occupancy_digest_guards_the_packed_bits():
    H, _, _ = _world()
    occ = build_tile_occupancy(H)
    payload = occ.to_payload()
    assert TileOccupancy.from_payload(payload) == occ
    # a flipped bit in the packed grid must fail the digest, not
    # silently skip (or densify) tiles
    tampered = dict(payload)
    raw = bytearray(bytes.fromhex(tampered["packed_hex"]))
    raw[0] ^= 0x80
    tampered["packed_hex"] = bytes(raw).hex()
    with pytest.raises(ValueError, match="digest"):
        TileOccupancy.from_payload(tampered)


def test_chunked_tile_stats_match_one_shot_and_are_idempotent():
    H, _, _ = _world(seed=3)
    one_shot = build_tile_occupancy(H, epsilon=0.01)
    stats = TileMaxStats(P, V)
    rng = np.random.default_rng(7)
    # arbitrary, unaligned, OVERLAPPING chunk windows (double reads are
    # free: max-accumulation is idempotent)
    for _ in range(40):
        r0, c0 = int(rng.integers(0, P - 1)), int(rng.integers(0, V - 1))
        h = int(rng.integers(1, P - r0 + 1))
        w = int(rng.integers(1, V - c0 + 1))
        stats.add(H[r0:r0 + h, c0:c0 + w], r0, c0)
    stats.add(H, 0, 0)  # ensure full coverage
    stats.add(H, 0, 0)  # and a verbatim double read
    assert stats.occupancy(0.01) == one_shot


def test_nan_poisoned_matrix_refuses_an_index():
    """One non-finite RTM entry must fail the occupancy build loudly —
    a NaN threshold would compare False against every tile and the
    sparse solve would silently skip the whole matrix."""
    H, _, _ = _world()
    H = H.copy()
    H[3, 7] = np.nan
    with pytest.raises(ValueError, match="non-finite"):
        build_tile_occupancy(H)


def test_threshold_matrix_zeroes_dropped_tiles_only():
    H, _, _ = _world(seed=5)
    # bury sub-threshold noise in panel 1 (otherwise empty)
    H[:, BS:2 * BS] = 1e-6
    occ = build_tile_occupancy(H, epsilon=1e-3)
    assert occ.occupancy_fraction() == pytest.approx(0.5)
    Ht = threshold_matrix(H, occ)
    assert np.all(Ht[:, BS:2 * BS] == 0)
    np.testing.assert_array_equal(Ht[:, :BS], H[:, :BS])
    # a fully-occupied index drops nothing: the same object comes back
    full = TileOccupancy.from_mask(
        np.ones(build_tile_occupancy(H).grid_shape, bool), rows=P, cols=V
    )
    assert threshold_matrix(H, full) is H


def test_ingest_round_trip_through_block_reader(tmp_path):
    """The occupancy accumulated by the chunked HDF5 reader equals the
    one-shot index of the assembled matrix — through the fixture world's
    multi-camera, multi-segment (dense + sparse-COO) layout."""
    from fixtures import NPIXEL, NVOXEL, write_world

    from sartsolver_tpu.io.raytransfer import read_rtm_block

    paths, H, *_ = write_world(tmp_path)
    files = {"camA": [paths["rtm_a1"], paths["rtm_a2"]],
             "camB": [paths["rtm_b"]]}
    stats = TileMaxStats(NPIXEL, NVOXEL)
    for r0 in range(0, NPIXEL, 3):  # deliberately unaligned chunks
        n = min(3, NPIXEL - r0)
        read_rtm_block(files, "with_reflections", n, NVOXEL, r0,
                       tile_stats=stats)
    assert stats.occupancy(0.0) == build_tile_occupancy(
        H.astype(np.float32)
    )


def test_ingest_tile_stats_ride_the_striped_shard_read():
    """multihost.make_tile_stats fed through read_and_shard_rtm covers
    the PADDED grid (padding panels born unoccupied) and matches the
    host-built index of the padded matrix."""
    from fixtures import NPIXEL, NVOXEL

    from sartsolver_tpu.parallel.mesh import make_mesh
    from sartsolver_tpu.parallel.multihost import (
        make_tile_stats,
        read_and_shard_rtm,
    )

    pytest.importorskip("h5py")
    import tempfile

    from fixtures import write_world

    with tempfile.TemporaryDirectory() as d:
        paths, H, *_ = write_world(d)
        files = {"camA": [paths["rtm_a1"], paths["rtm_a2"]],
                 "camB": [paths["rtm_b"]]}
        mesh = make_mesh(1, 1)
        stats = make_tile_stats(NPIXEL, NVOXEL, mesh)
        rtm = read_and_shard_rtm(
            files, "with_reflections", NPIXEL, NVOXEL, mesh,
            dtype="float32", tile_stats=stats,
        )
        occ = stats.occupancy(0.0)
        padded = np.zeros((stats.rows, stats.cols), np.float32)
        padded[:NPIXEL, :NVOXEL] = H
        assert occ == build_tile_occupancy(padded)
        np.testing.assert_array_equal(
            np.asarray(rtm)[:NPIXEL, :NVOXEL], H.astype(np.float32)
        )


# --------------------------------------------------------------------------
# sweep-level bit parity: skipping an all-zero panel is bit-neutral
# --------------------------------------------------------------------------


def _compose_sweeps(sweep_fn, rtm, w0, f0, aux, update_fn, n=3):
    """n chained sweeps (the while-loop shape), executed op-by-op: each
    primitive compiles standalone, so both variants run IDENTICAL
    kernels on identical inputs and the comparison pins the math-level
    bit-neutrality of the skip (one whole-program jit instead would let
    XLA fuse the two differently-shaped programs differently and
    reassociate reductions — that end-to-end reassociation bound is the
    solver-level drill's PARITY_RTOL gate)."""
    f, w, fitted = f0, w0, None
    for _ in range(n):
        f, fitted = sweep_fn(rtm, w, f, aux, update_fn)
        w = (1.0 - fitted) * 0.25
    return f, fitted


@pytest.mark.parametrize("closure", ["linear", "log"])
@pytest.mark.parametrize("host", ["static", "gather"])
def test_sparse_sweep_bit_identical_to_dense_equivalent(closure, host):
    import functools

    H, _, _ = _world(seed=1)
    occ = build_tile_occupancy(H)
    full = TileOccupancy.from_mask(
        np.ones(occ.grid_shape, bool), rows=P, cols=V
    )
    rng = np.random.default_rng(2)
    w0 = jnp.asarray(rng.standard_normal((1, P)).astype(np.float32))
    f0 = jnp.asarray(rng.random((1, V), np.float32) + 0.5)
    if closure == "linear":
        invd = jnp.asarray(rng.random((1, V), np.float32))
        aux = [invd]
        update_fn = lambda f, bp, invd_p: jnp.maximum(f + invd_p * bp, 0)
    else:
        obs = jnp.asarray(rng.random((1, V), np.float32))
        aux = [obs]
        update_fn = lambda f, bp, obs_p: f * (
            (obs_p + 1e-7) / (bp + 1e-7)
        )

    def host_fn(o):
        if host == "static":
            return functools.partial(
                sparse_panel_sweep, occupancy=o, panel_voxels=BS
            )
        ids = jnp.asarray(
            np.nonzero(o.col_panel_occupied(BS))[0].astype(np.int32)
        )
        return functools.partial(
            sparse_gather_sweep, panel_ids=ids, panel_voxels=BS
        )

    Hd = jnp.asarray(H)
    a_f, a_fit = _compose_sweeps(host_fn(occ), Hd, w0, f0, aux, update_fn)
    b_f, b_fit = _compose_sweeps(host_fn(full), Hd, w0, f0, aux, update_fn)
    np.testing.assert_array_equal(np.asarray(a_f), np.asarray(b_f))
    np.testing.assert_array_equal(np.asarray(a_fit), np.asarray(b_fit))


def test_gather_sweep_bit_identical_to_static_skip():
    H, _, _ = _world(seed=4)
    occ = build_tile_occupancy(H)
    rng = np.random.default_rng(5)
    w0 = jnp.asarray(rng.standard_normal((2, P)).astype(np.float32))
    f0 = jnp.asarray(rng.random((2, V), np.float32))
    invd = jnp.asarray(rng.random((1, V), np.float32))
    upd = lambda f, bp, invd_p: jnp.maximum(f + invd_p * bp, 0)
    ids = jnp.asarray(
        np.nonzero(occ.col_panel_occupied(BS))[0].astype(np.int32)
    )
    a = jax.jit(lambda r, w, f: sparse_panel_sweep(
        r, w, f, [invd], upd, occupancy=occ, panel_voxels=BS
    ))(jnp.asarray(H), w0, f0)
    b = jax.jit(lambda r, w, f: sparse_gather_sweep(
        r, w, f, [invd], upd, panel_ids=ids, panel_voxels=BS
    ))(jnp.asarray(H), w0, f0)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_sparse_sweep_int8_panelwise_dequant():
    """int8 storage through the sparse panel sweep: codes dequantize per
    panel, scales ride as aux 0 (fwd_scale) — parity vs the dense-
    equivalent full index is bitwise, like fp32."""
    from sartsolver_tpu.models.sart import quantize_rtm

    H, _, _ = _world(seed=6)
    codes, scale = jax.jit(quantize_rtm)(jnp.asarray(H))
    occ = build_tile_occupancy(np.asarray(codes))
    full = TileOccupancy.from_mask(
        np.ones(occ.grid_shape, bool), rows=P, cols=V
    )
    rng = np.random.default_rng(7)
    w0 = jnp.asarray(rng.standard_normal((1, P)).astype(np.float32))
    f0 = jnp.asarray(rng.random((1, V), np.float32))
    sc = scale[None, :]
    upd = lambda f, bp, s_p, invd_p: jnp.maximum(f + invd_p * bp * s_p, 0)
    invd = jnp.asarray(rng.random((1, V), np.float32))

    def run(o):
        # op-by-op for bitwise comparability (see _compose_sweeps)
        return sparse_panel_sweep(
            codes, w0, f0, [sc, invd], upd, occupancy=o, panel_voxels=BS,
            fwd_scale=0,
        )

    for x, y in zip(run(occ), run(full)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# --------------------------------------------------------------------------
# solver-level drill matrix (eps = 0)
# --------------------------------------------------------------------------

VARIANTS = {
    "linear": {},
    "log": dict(logarithmic=True),
    "os4": dict(os_subsets=4),
    "momentum": dict(momentum="nesterov"),
    "os4_log_momentum": dict(os_subsets=4, logarithmic=True,
                             momentum="nesterov"),
    "int8": dict(rtm_dtype="int8"),
    "int8_os4": dict(rtm_dtype="int8", os_subsets=4),
    "decay": dict(relaxation_decay=0.95),
    "integrity": dict(integrity=True),
    "recovery": dict(divergence_recovery=2),
}


def _assert_parity(res_s, res_d, label):
    a = np.asarray(res_s.solution)
    c = np.asarray(res_d.solution)
    scale = max(float(np.max(np.abs(c))), 1.0)
    d = float(np.max(np.abs(a - c)))
    assert d <= PARITY_RTOL * scale, (label, d, scale)
    np.testing.assert_array_equal(
        np.asarray(res_s.iterations), np.asarray(res_d.iterations)
    )
    np.testing.assert_array_equal(
        np.asarray(res_s.status), np.asarray(res_d.status)
    )


@pytest.mark.parametrize("variant", sorted(VARIANTS))
def test_eps0_solver_parity_vs_dense(variant):
    kw = VARIANTS[variant]
    H, g, msq = _world()
    opts_s = SolverOptions(
        max_iterations=25, conv_tolerance=0.0, sparse_rtm="auto",
        fused_panel_voxels=BS, **kw,
    )
    dkw = dict(kw)
    if kw.get("rtm_dtype") == "int8" and kw.get("os_subsets", 1) == 1:
        dkw["fused_sweep"] = "interpret"  # the dense int8 comparator
    opts_d = SolverOptions(max_iterations=25, conv_tolerance=0.0, **dkw)
    res_s = _solve(H, g, msq, opts_s, B=2)
    engaged = FUSED_ENGAGEMENT["last"]
    assert engaged in ("sparse-panel", "os-subset-sparse"), (variant,
                                                            engaged)
    res_d = _solve(H, g, msq, opts_d, B=2)
    _assert_parity(res_s, res_d, variant)


def _raw_frames(H, n=1, seed=21):
    rng = np.random.default_rng(seed)
    f_true = rng.random(H.shape[1]) + 0.5
    return [
        H.astype(np.float64) @ (f_true * (1.0 + 0.1 * k))
        for k in range(n)
    ]


@pytest.mark.parametrize("mesh_shape", [(2, 1), (4, 1)])
def test_eps0_parity_on_pixel_sharded_meshes(mesh_shape):
    """(N, 1) meshes: the sparse panel sweep psums occupied panels only;
    results match the dense sharded solver at the reassociation bound."""
    from sartsolver_tpu.parallel.mesh import make_mesh
    from sartsolver_tpu.parallel.sharded import DistributedSARTSolver

    if len(jax.devices()) < mesh_shape[0]:
        pytest.skip(f"needs {mesh_shape[0]} devices")
    H, _, _ = _world()
    meas = np.stack(_raw_frames(H, 1))
    sols = {}
    for mode in ("auto", "off"):
        opts = SolverOptions(
            max_iterations=25, conv_tolerance=0.0, sparse_rtm=mode,
            fused_panel_voxels=BS if mode == "auto" else None,
            fused_sweep="off" if mode == "off" else "auto",
        )
        solver = DistributedSARTSolver(
            H, opts=opts, mesh=make_mesh(*mesh_shape)
        )
        if mode == "auto":
            assert solver._tile_occupancy is not None
        res = solver.solve_batch(meas)
        sols[mode] = np.asarray(res.solution)[0]
        if mode == "auto":
            assert FUSED_ENGAGEMENT["last"] == "sparse-panel"
        solver.close()
    scale = max(float(np.max(np.abs(sols["off"]))), 1.0)
    d = float(np.max(np.abs(sols["auto"] - sols["off"])))
    assert d <= PARITY_RTOL * scale, (d, scale)


def test_sparse_auto_declines_on_voxel_sharded_mesh():
    """2-D / voxel-sharded meshes: the static panel skip is not SPMD-
    uniform, so 'auto' declines (dense paths, parity trivially) and an
    explicit threshold refuses loudly."""
    from sartsolver_tpu.config import SartInputError
    from sartsolver_tpu.parallel.mesh import make_mesh
    from sartsolver_tpu.parallel.sharded import DistributedSARTSolver

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    H, _, _ = _world()
    meas = np.stack(_raw_frames(H, 1))
    opts = SolverOptions(max_iterations=10, conv_tolerance=0.0,
                         sparse_rtm="auto")
    solver = DistributedSARTSolver(H, opts=opts, mesh=make_mesh(1, 2))
    assert solver._tile_occupancy is None
    res = solver.solve_batch(meas)
    assert np.isfinite(np.asarray(res.solution)).all()
    solver.close()
    with pytest.raises(SartInputError, match="voxel axis"):
        DistributedSARTSolver(
            H,
            opts=SolverOptions(max_iterations=10, conv_tolerance=0.0,
                               sparse_rtm="0.0"),
            mesh=make_mesh(1, 2),
        )


def test_gather_fallback_engages_past_the_unroll_bound(monkeypatch):
    """Occupied-panel counts past SPARSE_STATIC_UNROLL_MAX route through
    the fori_loop gather host; results stay within the parity bound and
    the engagement record says so."""
    import sartsolver_tpu.models.sart as sart_mod

    monkeypatch.setattr(sart_mod, "SPARSE_STATIC_UNROLL_MAX", 1)
    H, g, msq = _world(seed=9)
    opts_s = SolverOptions(max_iterations=25, conv_tolerance=0.0,
                           sparse_rtm="auto", fused_panel_voxels=BS)
    res_s = _solve(H, g, msq, opts_s)
    assert FUSED_ENGAGEMENT["last"] == "sparse-gather"
    opts_d = SolverOptions(max_iterations=25, conv_tolerance=0.0)
    res_d = _solve(H, g, msq, opts_d)
    _assert_parity(res_s, res_d, "gather")


def test_os_cycle_declines_past_the_unroll_cap(monkeypatch):
    """The OS subset cycle has no gather form, so an occupied-panel
    count past SPARSE_STATIC_UNROLL_MAX declines ('auto' runs the dense
    cycle; explicit raises) instead of unrolling a dot per panel."""
    import sartsolver_tpu.models.sart as sart_mod

    monkeypatch.setattr(sart_mod, "SPARSE_STATIC_UNROLL_MAX", 1)
    H, g, msq = _world(seed=27)
    opts = SolverOptions(max_iterations=10, conv_tolerance=0.0,
                         sparse_rtm="auto", fused_panel_voxels=BS,
                         os_subsets=4)
    res = _solve(H, g, msq, opts)  # 2 occupied panels > cap of 1
    assert FUSED_ENGAGEMENT["last"] == "os-subset"  # declined to dense
    assert np.isfinite(np.asarray(res.solution)).all()
    problem, occ = make_sparse_problem(
        H, opts=SolverOptions(max_iterations=10, conv_tolerance=0.0,
                              sparse_rtm="0.0", fused_panel_voxels=BS,
                              os_subsets=4),
    )
    with pytest.raises(ValueError, match="UNROLL_MAX"):
        solve_normalized_batch(
            problem, jnp.asarray(g), jnp.asarray([msq], jnp.float32),
            jnp.zeros((1, V), jnp.float32),
            opts=SolverOptions(max_iterations=10, conv_tolerance=0.0,
                               sparse_rtm="0.0", fused_panel_voxels=BS,
                               os_subsets=4),
            axis_name=None, voxel_axis=None, use_guess=True,
            tile_occupancy=occ,
        )


def test_explicit_threshold_without_index_raises():
    H, g, msq = _world()
    opts = SolverOptions(max_iterations=5, conv_tolerance=0.0,
                         sparse_rtm="0.001")
    problem = make_problem(H, opts=opts)
    with pytest.raises(ValueError, match="no tile-occupancy index"):
        solve_normalized_batch(
            problem, jnp.asarray(g), jnp.asarray([msq], jnp.float32),
            jnp.zeros((1, V), jnp.float32), opts=opts, axis_name=None,
            voxel_axis=None, use_guess=True,
        )


# --------------------------------------------------------------------------
# eps > 0: residual-matched parity, Eq. 6 self-consistency
# --------------------------------------------------------------------------


def test_eps_threshold_is_residual_matched_and_self_consistent():
    H, g, msq = _world(seed=11)
    # sub-threshold noise tiles in the otherwise-empty panels: eps must
    # drop them; the solve then runs on the thresholded operator
    rng = np.random.default_rng(12)
    H = H.copy()
    H[:, BS:2 * BS] = rng.random((P, BS), dtype=np.float32) * 1e-5
    eps = 1e-3
    opts_s = SolverOptions(max_iterations=60, conv_tolerance=1e-6,
                           sparse_rtm=str(eps), fused_panel_voxels=BS)
    problem, occ = make_sparse_problem(H, opts=opts_s)
    assert occ.occupancy_fraction() == pytest.approx(0.5)
    assert occ.threshold == pytest.approx(eps * np.abs(H).max(), rel=1e-6)
    # Eq. 6 self-consistency: the dropped tiles' voxels have ZERO ray
    # density in the problem (stats computed from the thresholded
    # operator), so they mask out exactly like dark voxels
    dens = np.asarray(problem.ray_density)
    assert np.all(dens[BS:2 * BS] == 0)
    gd = jnp.asarray(g)
    msqd = jnp.asarray([msq], jnp.float32)
    f0 = jnp.zeros((1, V), jnp.float32)
    res_s = solve_normalized_batch(
        problem, gd, msqd, f0, opts=opts_s, axis_name=None,
        voxel_axis=None, use_guess=True, tile_occupancy=occ,
    )
    assert FUSED_ENGAGEMENT["last"] == "sparse-panel"
    opts_d = SolverOptions(max_iterations=60, conv_tolerance=1e-6)
    res_d = _solve(H, g, msq, opts_d)
    sol_s = np.asarray(res_s.solution)[0].astype(np.float64)
    sol_d = np.asarray(res_d.solution)[0].astype(np.float64)
    assert np.isfinite(sol_s).all()
    # residual-matched: the thresholded solve fits the measurement about
    # as well as dense (the dropped energy is ~eps-sized)
    g64 = np.asarray(g[0], np.float64)
    r_s = np.linalg.norm(
        g64 - threshold_matrix(H, occ).astype(np.float64) @ sol_s
    )
    r_d = np.linalg.norm(g64 - H.astype(np.float64) @ sol_d)
    assert r_s <= 1.2 * r_d + 1e-3


def test_cli_integrity_with_threshold_skips_ray_stats_verify(
    tmp_path, capsys,
):
    """--integrity x a tile-dropping threshold: the post-upload host-vs-
    device rho/lambda compare is SKIPPED with a note (host sums include
    the dropped entries, the device matrix is thresholded — comparing
    them would quarantine a healthy run), and the run completes."""
    import fixtures as fx

    from sartsolver_tpu.cli import main as cli_main

    NP_, NV = 16, 256
    rng = np.random.default_rng(0)
    H = (rng.random((NP_, NV)) * 0.9 + 0.1).astype(np.float32)
    H[:, 128:] = 1e-5  # sub-threshold tiles: eps=0.01 DROPS them
    mask = np.ones((4, 4), np.int64)
    cells = np.arange(NV, dtype=np.int64)
    old = fx.NX, fx.NY, fx.NZ
    fx.NX, fx.NY, fx.NZ = 16, 16, 1
    try:
        fx._write_rtm_file(str(tmp_path / "rtm.h5"), "cam", mask, H,
                           cells, cells)
        f_true = rng.random(NV) + 0.5
        frames = np.stack([fx.frame_from_measurement(
            mask, H.astype(np.float64) @ f_true)])
        fx._write_image_file(str(tmp_path / "img.h5"), "cam", frames,
                             [0.1])
    finally:
        fx.NX, fx.NY, fx.NZ = old
    rc = cli_main([
        "-o", str(tmp_path / "out.h5"),
        str(tmp_path / "rtm.h5"), str(tmp_path / "img.h5"),
        "-m", "50", "--integrity", "--sparse_rtm", "0.01",
    ])
    err = capsys.readouterr().err
    assert rc == 0, err
    assert "ray-stats verification skipped" in err


def test_all_empty_rows_and_columns_mask_cleanly():
    """Eq. 6 masking on degenerate operators: all-zero pixel rows and
    voxel columns (inside occupied panels AND as whole panels) produce
    finite solutions matching the dense path."""
    H, g, msq = _world(seed=13)
    H = H.copy()
    H[5, :] = 0.0  # dead pixel row
    H[:, 7] = 0.0  # dead voxel column inside an occupied panel
    g = g.copy()
    opts_s = SolverOptions(max_iterations=25, conv_tolerance=0.0,
                           sparse_rtm="auto", fused_panel_voxels=BS)
    res_s = _solve(H, g, msq, opts_s)
    assert np.isfinite(np.asarray(res_s.solution)).all()
    res_d = _solve(H, g, msq,
                   SolverOptions(max_iterations=25, conv_tolerance=0.0))
    _assert_parity(res_s, res_d, "masking")


def test_fully_empty_operator_is_benign():
    """Every panel empty: the sweep degenerates to the elementwise
    update with zero fitted — no crash, finite output."""
    H = np.zeros((P, V), np.float32)
    g = np.full((1, P), 0.5, np.float32)
    opts = SolverOptions(max_iterations=5, conv_tolerance=0.0,
                         sparse_rtm="auto", fused_panel_voxels=BS)
    res = _solve(H, g, 1.0, opts)
    assert np.isfinite(np.asarray(res.solution)).all()


# --------------------------------------------------------------------------
# scheduler composition: one compiled program, occupancy static
# --------------------------------------------------------------------------


def test_sched_cache_size_pinned_under_sparse_state():
    """Continuous batching with the sparse sweep: occupancy is per-RTM
    static state, so refills/retirements at every lane occupancy reuse
    ONE compiled stride program (the scheduler contract), and retired
    lane results match the dense scheduler run."""
    from sartsolver_tpu.parallel.mesh import make_mesh
    from sartsolver_tpu.parallel.sharded import DistributedSARTSolver

    H, _, _ = _world(seed=17)
    frames = _raw_frames(H, 6, seed=23)
    sols = {}
    for mode in ("auto", "off"):
        opts = SolverOptions(
            max_iterations=40, conv_tolerance=1e-5, schedule_stride=4,
            sparse_rtm=mode,
            fused_panel_voxels=BS if mode == "auto" else None,
        )
        solver = DistributedSARTSolver(H, opts=opts, mesh=make_mesh(1, 1))
        lanes = solver.sched_lanes(2)
        results = {}
        queue = list(enumerate(frames))
        occupant: dict = {}
        for _ in range(200):  # bounded: 6 frames x <=10 strides each
            refills = []
            for b in range(2):
                if b not in occupant and queue:
                    idx, frame = queue.pop(0)
                    refills.append((b, frame))
                    occupant[b] = idx
            if not occupant:
                break
            solver.sched_step(lanes, refills)
            done, *_ = lanes.scalars()
            for b in list(occupant):
                if done[b]:
                    results[occupant.pop(b)] = lanes.lane_solution_fetcher(
                        b
                    )()
        assert not queue and not occupant
        assert solver._sched_fn()._cache_size() == 1
        sols[mode] = results
        solver.close()
    assert sorted(sols["off"]) == list(range(6))
    for idx in sols["off"]:
        a, c = sols["auto"][idx], sols["off"][idx]
        scale = max(float(np.max(np.abs(c))), 1.0)
        assert float(np.max(np.abs(a - c))) <= PARITY_RTOL * scale, idx


# --------------------------------------------------------------------------
# observability + audit pins
# --------------------------------------------------------------------------


def test_sparse_metrics_are_recorded():
    from sartsolver_tpu.obs import metrics as obs_metrics

    H, g, msq = _world(seed=19)
    opts = SolverOptions(max_iterations=3, conv_tolerance=0.0,
                         sparse_rtm="auto", fused_panel_voxels=BS)
    _solve(H, g, msq, opts)
    reg = obs_metrics.get_registry()
    assert reg.gauge("rtm_tile_occupancy").value == pytest.approx(0.5)
    assert reg.counter(
        "sparse_tiles_skipped_total", path="sparse_panel"
    ).value > 0


def test_sparse_audit_entries_pass_their_goldens():
    import jax as _jax

    from sartsolver_tpu.analysis.audit import run_compile_audit

    if _jax.default_backend() != "cpu":
        pytest.skip("goldens are checked in for the cpu backend")
    reports = run_compile_audit(
        entries=["sparse_panel_sweep", "sharded_sparse_panel_sweep"]
    )
    for r in reports:
        assert r.status in ("ok", "skipped"), r.format()


def test_sparse_cost_golden_pins_occupancy_scaling():
    """THE densification tripwire: the 50%-occupancy entry's loop must
    cost about half the dense two-matmul entry's whole-module FLOPs —
    a silent dense fallback roughly doubles it, far outside the
    committed band."""
    import jax as _jax

    from sartsolver_tpu.analysis.audit import load_cost_golden

    if _jax.default_backend() != "cpu":
        pytest.skip("goldens are checked in for the cpu backend")
    sparse = load_cost_golden("sparse_panel_sweep", "cpu")
    dense = load_cost_golden("sweep", "cpu")
    assert sparse is not None and dense is not None
    ratio = float(sparse["flops"]) / float(dense["flops"])
    # loop flops halve; the one-time dense setup keeps the module total
    # above exactly 0.5 — densification would push this past ~1.0
    assert 0.45 <= ratio <= 0.75, ratio


def test_options_validation():
    with pytest.raises(ValueError, match="sparse_rtm"):
        SolverOptions(sparse_rtm="1.5")
    with pytest.raises(ValueError, match="sparse_rtm"):
        SolverOptions(sparse_rtm="nonsense")
    with pytest.raises(ValueError, match="sparse_rtm"):
        SolverOptions(sparse_rtm="auto", fused_sweep="on")
    assert SolverOptions(sparse_rtm="0.01").sparse_epsilon() == 0.01
    assert SolverOptions(sparse_rtm="auto").sparse_epsilon() == 0.0
    assert SolverOptions().sparse_epsilon() is None
    assert SolverOptions(sparse_rtm="0.01").sparse_explicit()
    assert not SolverOptions(sparse_rtm="auto").sparse_explicit()


def test_nonfinite_warning_rearms_per_run():
    """The prepare_measurement non-finite warning fires once per RUN,
    not once per process: reset_nonfinite_warning re-arms it (the
    serving engine resets per request, the CLI per run)."""
    import warnings

    from sartsolver_tpu.models.sart import (
        prepare_measurement,
        reset_nonfinite_warning,
    )
    from sartsolver_tpu.obs import metrics as obs_metrics

    opts = SolverOptions()
    bad = np.array([1.0, np.nan, 2.0])
    before = obs_metrics.get_registry().counter(
        "nonfinite_pixels_total"
    ).value
    reset_nonfinite_warning()
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        prepare_measurement(bad, opts)
        assert any("non-finite" in str(w.message) for w in rec)
        rec.clear()
        # latched: a second frame in the SAME run stays quiet...
        prepare_measurement(bad, opts)
        assert not rec
        # ...but the NEXT run (or serving request) warns again
        reset_nonfinite_warning()
        prepare_measurement(bad, opts)
        assert any("non-finite" in str(w.message) for w in rec)
    after = obs_metrics.get_registry().counter(
        "nonfinite_pixels_total"
    ).value
    # the counter never latches: every call counts its pixels
    assert after == before + 3
