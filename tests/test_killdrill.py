"""End-to-end SIGKILL + SIGTERM + resume drills (VERDICT r4 next #3;
docs/RESILIENCE.md §5).

The property tests prove resume recovery over SYNTHETICALLY torn files;
this drill executes the real pipeline under real kills: the `sartsolve`
CLI runs in a subprocess, is SIGKILLed at several points — including
DETERMINISTICALLY inside a flush window, via the `SART_TEST_FLUSH_DELAY`
markers solution.py emits ("torn": per-frame datasets at unequal lengths;
"pre-counter": data fsynced, counter stale) — and is then re-run with
`--resume`. The final file must equal an uninterrupted run's: values,
statuses, times, per-camera times, iteration counts, voxel map. This
exercises the async-writer -> flush-counter -> truncate-and-resume chain
end-to-end, single-process and as a real 2-process multihost run.

The SIGTERM drills exercise the graceful-preemption path at the same
deterministic flush-window markers: the first signal must drain the
in-flight group, flush, and exit with the documented code 4 leaving a
resumable file whose `--resume` completion is byte-identical to an
uninterrupted run; a second signal must abort immediately (death by the
signal).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import h5py
import numpy as np
import pytest

import fixtures as fx
import mp_support

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)

N_FRAMES = 10


def _env(flush_delay=None):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # no tunnel in child procs
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    if flush_delay is not None:
        env["SART_TEST_FLUSH_DELAY"] = str(flush_delay)
    else:
        env.pop("SART_TEST_FLUSH_DELAY", None)
    return env


def _cli_cmd(paths, outfile, *extra):
    return [
        sys.executable, "-m", "sartsolver_tpu.cli", "-o", outfile,
        paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
        paths["img_a"], paths["img_b"],
        # conv_tolerance below reach + fixed cap: every frame runs exactly
        # 40 iterations in both the uninterrupted and the resumed run, so
        # the comparison is deterministic. (Near the convergence stall the
        # resumed run's host-seeded warm start — a documented ~ulp-scale
        # seed-path difference, MANUAL §8 — can shift the stopping
        # iteration and drift values at conv-tolerance scale; that
        # semantic is pinned elsewhere, this drill targets the
        # write/flush/resume pipeline.)
        "--use_cpu", "-m", "40", "-c", "1e-12",
        "-l", paths["laplacian"], "-b", "0.001",
        # flush every frame, chain 2 frames per device program: maximal
        # write granularity while the chained warm-start loop stays on
        "--max_cached_solutions", "1", "--chain_frames", "2",
        *extra,
    ]


def _read_solution(path):
    with h5py.File(path, "r") as f:
        data = {k: f[f"solution/{k}"][:] for k in f["solution"]}
        data["voxel_map"] = f["voxel_map/value"][:]
        data["completed"] = int(f["solution"].attrs["completed"])
    return data


def _assert_files_equal(got, want):
    assert got["completed"] == want["completed"] == N_FRAMES
    for key in want:
        if key == "completed":
            continue
        if key == "value":
            np.testing.assert_allclose(
                got[key], want[key], rtol=1e-12, atol=1e-14, err_msg=key)
        else:
            np.testing.assert_array_equal(got[key], want[key], err_msg=key)


def _kill_at_marker(cmd, env, marker, occurrence, timeout=300):
    """Run the CLI, SIGKILL it the moment the flush hook announces the
    requested commit point for the ``occurrence``-th time."""
    import threading

    proc = subprocess.Popen(
        cmd, env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        text=True,
    )
    # watchdog: a child wedged BEFORE any stderr line would block the
    # readline loop forever; killing it on the deadline turns that into
    # EOF -> the loop's else-branch raises
    watchdog = threading.Timer(timeout, proc.kill)
    watchdog.start()
    seen = 0
    try:
        for line in proc.stderr:
            if line.strip() == f"SART_FLUSH_POINT {marker}":
                seen += 1
                if seen >= occurrence:
                    proc.kill()
                    break
        else:
            raise AssertionError(
                f"run exited (or hit the {timeout}s watchdog) before "
                f"marker {marker!r} x{occurrence} (saw {seen})")
    finally:
        watchdog.cancel()
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=60)
    assert proc.returncode == -signal.SIGKILL
    return seen


@pytest.fixture(scope="module")
def drill_world(tmp_path_factory):
    td = tmp_path_factory.mktemp("killdrill")
    paths, *_ = fx.write_world(td, with_laplacian=True, n_frames=N_FRAMES)
    # uninterrupted reference run (also warms the persistent compile
    # cache, so the killed/resumed runs below spend their time in the
    # frame loop, not in XLA)
    ref_out = str(td / "reference.h5")
    t0 = time.monotonic()
    subprocess.run(
        _cli_cmd(paths, ref_out), env=_env(), check=True, timeout=600,
        stdout=subprocess.DEVNULL,
    )
    duration = time.monotonic() - t0
    return paths, _read_solution(ref_out), duration, td


@pytest.mark.parametrize("marker,occurrence", [
    ("torn", 1),          # first flush: datasets at unequal lengths
    ("torn", 3),          # mid-series flush
    ("pre-counter", 2),   # data durable, counter one flush behind
])
def test_kill_inside_flush_window_then_resume(drill_world, marker,
                                              occurrence, tmp_path):
    """SIGKILL landed INSIDE a flush window (deterministically, via the
    commit-point markers); --resume must truncate the torn tail and
    reproduce the uninterrupted run exactly."""
    paths, want, _, _ = drill_world
    out = str(tmp_path / "out.h5")
    _kill_at_marker(
        _cli_cmd(paths, out), _env(flush_delay=2.0), marker, occurrence)
    # the kill landed mid-run: the file exists and is partial
    assert os.path.exists(out)
    with h5py.File(out, "r") as f:
        n_before = min(f[f"solution/{k}"].shape[0]
                       for k in ("value", "time", "status"))
    assert n_before < N_FRAMES
    rc = subprocess.run(
        _cli_cmd(paths, out, "--resume"), env=_env(), timeout=600,
        stdout=subprocess.DEVNULL,
    ).returncode
    assert rc == 0
    _assert_files_equal(_read_solution(out), want)


@pytest.mark.parametrize("fraction", [0.3, 0.6, 0.9])
def test_kill_at_random_point_then_resume(drill_world, fraction, tmp_path):
    """Wall-clock kills at several points of the run (ingest, early
    frames, late frames — wherever the fraction lands); --resume always
    completes the series to the uninterrupted result."""
    paths, want, duration, _ = drill_world
    out = str(tmp_path / "out.h5")
    proc = subprocess.Popen(
        _cli_cmd(paths, out), env=_env(flush_delay=0.05),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    time.sleep(max(0.2, fraction * duration))
    if proc.poll() is None:
        proc.kill()
        proc.wait(timeout=60)
        # the child can win the race and exit cleanly between poll() and
        # the SIGKILL landing (seen at fraction 0.9): that is the same
        # benign case as the poll()-not-None branch — a complete file,
        # which --resume below treats as a no-op
        assert proc.returncode in (0, -signal.SIGKILL)
    rc = subprocess.run(
        _cli_cmd(paths, out, "--resume"), env=_env(), timeout=600,
        stdout=subprocess.DEVNULL,
    ).returncode
    assert rc == 0
    _assert_files_equal(_read_solution(out), want)


# ---------------------------------------------------------------------------
# graceful-stop (SIGTERM) drills — docs/RESILIENCE.md §5
# ---------------------------------------------------------------------------

def _sigterm_env(flush_delay):
    """SIGTERM drills need the stop to land while frame groups REMAIN
    undispatched: with the default 16-deep writer queue the solve loop
    races ~all groups ahead of the slow (delayed) flushes and a signal
    at a flush marker would find the loop already finished — a completed
    run correctly exits 0, not 4. SART_WRITER_QUEUE=1 backpressures the
    solve loop onto the writer, pinning it at most ~2 groups past the
    marker so the boundary stop is deterministic."""
    env = _env(flush_delay=flush_delay)
    env["SART_WRITER_QUEUE"] = "1"
    return env


def _sigterm_at_marker(cmd, env, marker, occurrence, timeout=300):
    """Run the CLI, SIGTERM it the moment the flush hook announces the
    requested commit point for the ``occurrence``-th time, then let it
    drain and exit on its own. Returns (returncode, remaining stderr)."""
    import threading

    proc = subprocess.Popen(
        cmd, env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        text=True,
    )
    watchdog = threading.Timer(timeout, proc.kill)
    watchdog.start()
    seen = 0
    try:
        for line in proc.stderr:
            if line.strip() == f"SART_FLUSH_POINT {marker}":
                seen += 1
                if seen >= occurrence:
                    proc.send_signal(signal.SIGTERM)
                    break
        else:
            raise AssertionError(
                f"run exited (or hit the {timeout}s watchdog) before "
                f"marker {marker!r} x{occurrence} (saw {seen})")
        # drain stderr to EOF so the draining child never blocks on a
        # full pipe, then wait for the graceful exit
        rest = proc.stderr.read()
        proc.wait(timeout=timeout)
    finally:
        watchdog.cancel()
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=60)
    return proc.returncode, rest


@pytest.mark.parametrize("marker,occurrence", [
    ("torn", 1),          # first flush: datasets at unequal lengths
    ("torn", 3),          # mid-series flush
    ("pre-counter", 2),   # data durable, counter one flush behind
])
def test_sigterm_at_flush_window_exits_4_then_resumes(drill_world, marker,
                                                      occurrence, tmp_path):
    """SIGTERM landed while a flush window was open: the run must drain
    the in-flight group and async writer, exit 4, and leave a file whose
    --resume completion reproduces the uninterrupted run exactly."""
    paths, want, _, _ = drill_world
    out = str(tmp_path / "out.h5")
    rc, rest = _sigterm_at_marker(
        _cli_cmd(paths, out), _sigterm_env(0.5), marker, occurrence)
    assert rc == 4, rest
    assert "Interrupted by SIGTERM" in rest
    assert "resumable" in rest
    # the stopped file is a consistent prefix: every dataset agrees with
    # the committed counter (the drain may have completed any number of
    # frames — even all of them, if the signal landed late)
    assert os.path.exists(out)
    with h5py.File(out, "r") as f:
        completed = int(f["solution"].attrs["completed"])
        for key in ("value", "time", "status"):
            assert f[f"solution/{key}"].shape[0] >= completed
    assert completed <= N_FRAMES
    rc = subprocess.run(
        _cli_cmd(paths, out, "--resume"), env=_env(), timeout=600,
        stdout=subprocess.DEVNULL,
    ).returncode
    assert rc == 0
    _assert_files_equal(_read_solution(out), want)


def test_second_sigterm_aborts_immediately(drill_world, tmp_path):
    """The escape hatch: after the first SIGTERM begins a graceful drain,
    a second one must kill the process NOW (death by the signal), not
    wait for the drain."""
    import threading

    paths, _, _, _ = drill_world
    out = str(tmp_path / "out.h5")
    # long flush windows keep the run (and its drain) alive while the
    # two signals land
    proc = subprocess.Popen(
        _cli_cmd(paths, out), env=_sigterm_env(2.0),
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True,
    )
    watchdog = threading.Timer(300, proc.kill)
    watchdog.start()
    try:
        for line in proc.stderr:
            if line.strip().startswith("SART_FLUSH_POINT"):
                proc.send_signal(signal.SIGTERM)
                break
        else:
            raise AssertionError("run exited before any flush marker")
        for line in proc.stderr:
            if "received SIGTERM" in line:  # handler confirmed the first
                proc.send_signal(signal.SIGTERM)
                break
        else:
            raise AssertionError("first SIGTERM was never acknowledged")
        proc.stderr.read()
        proc.wait(timeout=120)
    finally:
        watchdog.cancel()
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=60)
    assert proc.returncode == -signal.SIGTERM


# ---------------------------------------------------------------------------
# 2-process multihost variant
# ---------------------------------------------------------------------------

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _mp_cmd(rank, port, outfile, paths, *extra):
    return [
        sys.executable, os.path.join(_HERE, "mp_worker.py"),
        str(rank), "2", str(port), outfile,
        "-l", paths["laplacian"], "-b", "0.001",
        # argparse keeps the LAST occurrence: override mp_worker's default
        # profile with the same deterministic fixed-iteration setup as the
        # single-process drill (see _cli_cmd)
        "-m", "40", "-c", "1e-12",
        "--max_cached_solutions", "1", "--chain_frames", "2",
        *extra,
        "--", paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
        paths["img_a"], paths["img_b"],
    ]


def _mp_env(flush_delay=None):
    env = _env(flush_delay)
    # mp_worker sets its own JAX_PLATFORMS/XLA_FLAGS (1 device/process)
    env.pop("XLA_FLAGS", None)
    return env


def _run_mp_pair(paths, outfile, *extra, env=None, timeout=360):
    port = _free_port()
    procs = [
        subprocess.Popen(
            _mp_cmd(rank, port, outfile, paths, *extra),
            env=env or _mp_env(), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )
        for rank in range(2)
    ]
    try:
        outs = [p.communicate(timeout=timeout)[0] for p in procs]
    finally:
        for p in procs:  # never leak live workers on a timeout
            if p.poll() is None:
                p.kill()
    assert all(p.returncode == 0 for p in procs), (
        "\n".join(o[-2000:] for o in outs))
    return outs


def test_two_process_kill_then_resume(drill_world):
    """The multihost leg: a real 2-process run is SIGKILLed mid-series —
    deterministically inside a flush window via rank 0's commit-point
    marker (only process 0 writes output) — then resumed by a fresh
    2-process run; the final file equals an uninterrupted 2-process
    run's."""
    # environment gate, checked lazily so the single-process drills in
    # this module never pay the two-process probe (tests/mp_support.py)
    if not mp_support.multiprocess_collectives_supported():
        pytest.skip(mp_support.SKIP_REASON)
    paths, _, _, td = drill_world
    ref_out = str(td / "mp_reference.h5")
    _run_mp_pair(paths, ref_out)
    want = _read_solution(ref_out)

    out = str(td / "mp_killed.h5")
    port = _free_port()
    env = _mp_env(flush_delay=2.0)
    procs = [
        subprocess.Popen(
            _mp_cmd(rank, port, out, paths), env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE if rank == 0 else subprocess.DEVNULL,
            text=bool(rank == 0),
        )
        for rank in range(2)
    ]
    try:
        for line in procs[0].stderr:
            if line.strip() == "SART_FLUSH_POINT torn":
                break
        else:
            raise AssertionError("rank 0 exited before any flush marker")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            p.wait(timeout=60)
    assert procs[0].returncode == -signal.SIGKILL

    _run_mp_pair(paths, out, "--resume")
    _assert_files_equal(_read_solution(out), want)

# ---------------------------------------------------------------------------
# Pod legs (docs/RESILIENCE.md §11): a dead pod peer must release the
# survivors through the barrier DEADLINE (exit 3, bundle naming the
# missing host) — never hang them — and a whole-pod --resume must land
# byte-identical. One leg drives the fake-pod file seam mid-stride, one
# drives the real 2-process runtime mid-RTM-ingest-turn.
# ---------------------------------------------------------------------------


def _pod_cmd(paths, outfile, *extra):
    # the in-solve checkpoint path rides the continuous-batching
    # scheduler, which needs --batch_frames > 1 (and therefore
    # --no_guess); otherwise the same deterministic fixed-iteration
    # profile as _cli_cmd
    return [
        sys.executable, "-m", "sartsolver_tpu.cli", "-o", outfile,
        paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
        paths["img_a"], paths["img_b"],
        "--use_cpu", "-m", "40", "-c", "1e-12",
        "-l", paths["laplacian"], "-b", "0.001",
        "--max_cached_solutions", "1", "--no_guess",
        "--batch_frames", "4",
        *extra,
    ]


def _pod_env(k, n, bdir, ckpt_base):
    env = _env()
    for key in [k for k in env if k.startswith(("SART_POD", "SART_FAULT",
                                                "SART_TEST", "SART_SOLVE"))]:
        env.pop(key)
    env["SART_POD_PROCESS"] = f"{k}/{n}"
    env["SART_POD_BARRIER_DIR"] = bdir
    env["SART_POD_BARRIER_TIMEOUT"] = "10"
    env["SART_TEST_POD_MARKERS"] = "1"
    # ONE shared checkpoint base: per-host output files would otherwise
    # derive per-host default sidecars and the cross-host consistency
    # intersection would always be empty
    env["SART_SOLVE_CKPT_FILE"] = ckpt_base
    return env


def test_pod_kill_mid_stride_survivor_exits_then_resumes(drill_world,
                                                         tmp_path):
    """Fake-pod leg: SIGKILL one of two lockstep hosts the moment it
    announces stride serial 2. The survivor exits EXIT_INFRASTRUCTURE(3)
    at the next barrier deadline with a crash bundle naming the dead
    host; a whole-pod --resume on a FRESH barrier dir restores the
    in-solve checkpoint and finishes byte-identical to a solo run."""
    import threading

    paths, _, _, _ = drill_world
    td = str(tmp_path)
    # the pod flag set differs from the module reference (--batch_frames
    # scheduler path), so the byte-identity oracle is a solo run with
    # exactly these flags — fake-pod lockstep computes the same series
    solo = os.path.join(td, "pod_solo.h5")
    subprocess.run(_pod_cmd(paths, solo), env=_env(), check=True,
                   timeout=600, stdout=subprocess.DEVNULL)
    want = _read_solution(solo)

    ckpt_base = os.path.join(td, "pod.solveckpt")
    bdir = os.path.join(td, "barrier_kill")
    os.makedirs(bdir)
    outs = [os.path.join(td, f"pod_h{k}.h5") for k in range(2)]

    def cmd(k, *x):
        return _pod_cmd(paths, outs[k], "--solve_ckpt_stride", "2", *x)

    procs = [
        subprocess.Popen(cmd(k), env=_pod_env(k, 2, bdir, ckpt_base),
                         stdout=subprocess.DEVNULL,
                         stderr=subprocess.PIPE, text=True)
        for k in range(2)
    ]
    victim = procs[1]

    def watch_victim():
        for line in victim.stderr:
            if line.strip() == "SART_POD_POINT stride serial=2":
                victim.kill()
                break
        victim.stderr.close()

    watcher = threading.Thread(target=watch_victim)
    watcher.start()
    try:
        err0 = procs[0].communicate(timeout=300)[1]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    watcher.join(timeout=60)
    victim.wait(timeout=60)
    assert victim.returncode == -signal.SIGKILL
    # released by the barrier DEADLINE — exit 3, not a hang, not the
    # watchdog release valve (which would exit 2)
    assert procs[0].returncode == 3, err0[-4000:]
    assert "Aborted at a pod barrier" in err0, err0[-4000:]
    assert "h1" in err0, err0[-4000:]
    with open(outs[0] + ".crash.json") as f:
        bundle = json.load(f)
    assert "h1" in bundle["reason"], bundle["reason"]
    assert bundle["status"]["host"] == "0/2"

    # elastic resume: fresh EMPTY barrier dir — stale arrival files from
    # the killed incarnation would satisfy its rendezvous instantly
    bdir2 = os.path.join(td, "barrier_resume")
    os.makedirs(bdir2)
    procs = [
        subprocess.Popen(cmd(k, "--resume"),
                         env=_pod_env(k, 2, bdir2, ckpt_base),
                         stdout=subprocess.DEVNULL,
                         stderr=subprocess.PIPE, text=True)
        for k in range(2)
    ]
    errs = [p.communicate(timeout=300)[1] for p in procs]
    assert all(p.returncode == 0 for p in procs), (
        "\n".join(e[-2000:] for e in errs))
    for k in range(2):
        assert "SART_POD_POINT resume serial=" in errs[k], (
            f"h{k} did not restore a solve checkpoint\n" + errs[k][-2000:])
        _assert_files_equal(_read_solution(outs[k]), want)


def test_pod_mp_kill_mid_ingest_turn_survivor_exits_then_resumes(
        drill_world, tmp_path):
    """Real-runtime pod leg: a 2-process run serializes RTM ingest
    host-by-host; SIGKILL rank 1 inside ITS read turn. Rank 0 must be
    released by the ``rtm_read_turn`` barrier deadline (exit 3, output
    naming h1), and a fresh 2-process --resume lands byte-identical."""
    if not mp_support.multiprocess_collectives_supported():
        pytest.skip(mp_support.SKIP_REASON)
    paths, _, _, _ = drill_world
    td = str(tmp_path)
    ref_out = os.path.join(td, "mp_pod_ref.h5")
    _run_mp_pair(paths, ref_out)
    want = _read_solution(ref_out)

    out = os.path.join(td, "mp_pod_killed.h5")
    bdir = os.path.join(td, "mp_barrier")
    os.makedirs(bdir)
    env = _mp_env()
    env["SART_POD_BARRIER_DIR"] = bdir
    env["SART_POD_BARRIER_TIMEOUT"] = "10"
    env["SART_TEST_POD_MARKERS"] = "1"
    port = _free_port()
    procs = [
        subprocess.Popen(
            _mp_cmd(rank, port, out, paths), env=env,
            stdout=subprocess.PIPE if rank == 0 else subprocess.DEVNULL,
            stderr=(subprocess.STDOUT if rank == 0 else subprocess.PIPE),
            text=True,
        )
        for rank in range(2)
    ]
    try:
        for line in procs[1].stderr:
            if line.strip() == "SART_POD_POINT ingest turn=1":
                procs[1].kill()
                break
        else:
            raise AssertionError("rank 1 exited before its ingest turn")
        out0 = procs[0].communicate(timeout=120)[0]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            p.wait(timeout=60)
    assert procs[1].returncode == -signal.SIGKILL
    assert procs[0].returncode == 3, out0[-4000:]
    assert "pod barrier" in out0, out0[-4000:]
    assert "h1" in out0, out0[-4000:]

    # the kill landed pre-solve: no output rows yet — --resume on the
    # (possibly absent) file degrades to a fresh run, same bytes
    _run_mp_pair(paths, out, "--resume")
    _assert_files_equal(_read_solution(out), want)
