"""Time-interval grammar + parameter validation vs arguments.cpp semantics."""

import math

import pytest

from sartsolver_tpu.config import SolverOptions, parse_time_intervals


class TestParseTimeIntervals:
    def test_empty_means_all_times(self):
        assert parse_time_intervals("") == [(0.0, math.inf, 0.0, 0.0)]

    def test_single_interval(self):
        assert parse_time_intervals("20.5:40.1") == [(20.5, 40.1, 0.0, 0.0)]

    def test_multi_interval_with_step_and_threshold(self):
        # Shape of the reference's docstring example (arguments.cpp:92) with a
        # step that passes its own validation — the literal example
        # "45.2:51:15:0.05" violates arguments.cpp:60 (step > interval), a
        # reference doc defect we keep rejecting.
        out = parse_time_intervals("20.5:40.1, 45.2:51:1.5:0.05")
        assert out == [(20.5, 40.1, 0.0, 0.0), (45.2, 51.0, 1.5, 0.05)]
        with pytest.raises(ValueError):
            parse_time_intervals("45.2:51:15:0.05")

    def test_trailing_comma_allowed(self):
        assert parse_time_intervals("1:2,") == [(1.0, 2.0, 0.0, 0.0)]

    def test_step_only(self):
        assert parse_time_intervals("0:10:2") == [(0.0, 10.0, 2.0, 0.0)]

    @pytest.mark.parametrize(
        "bad",
        [
            "5",  # fewer than 2 fields
            "1:2:3:4:5",  # more than 4 fields
            "-1:2",  # negative start
            "3:2",  # stop <= start
            "2:2",  # stop <= start
            "0:10:11",  # step > interval
            "0:10:2:3",  # threshold > step
            "a:b",  # non-numeric
        ],
    )
    def test_invalid(self, bad):
        with pytest.raises(ValueError):
            parse_time_intervals(bad)


class TestSolverOptions:
    def test_defaults_match_reference_cli(self):
        o = SolverOptions()
        assert o.ray_density_threshold == 1.0e-6
        assert o.ray_length_threshold == 1.0e-6
        assert o.max_iterations == 2000
        assert o.conv_tolerance == 1.0e-5
        assert o.beta_laplace == 2.0e-2
        assert o.relaxation == 1.0
        assert not o.logarithmic

    @pytest.mark.parametrize(
        "kw",
        [
            {"ray_density_threshold": -1},
            {"ray_length_threshold": -0.5},
            {"conv_tolerance": -1e-6},
            {"beta_laplace": -1e-3},
            {"relaxation": 0},
            {"relaxation": 1.5},
            {"max_iterations": 0},
            # iteration counts pack through fp32 in the device-result path
            # (exact only to 2^24) — guarded at construction
            {"max_iterations": 2**24 + 1},
            {"dtype": "int8"},
        ],
    )
    def test_validation(self, kw):
        with pytest.raises(ValueError):
            SolverOptions(**kw)

    def test_cpu_parity_profile(self):
        o = SolverOptions.cpu_parity()
        assert o.dtype == "float64" and not o.normalize
        assert o.guess_floor == 0.0 and not o.mask_negative_guess
        olog = SolverOptions.cpu_parity(logarithmic=True)
        # 1e-30, not the reference's 1e-100: emulated f64 has fp32 range.
        assert olog.guess_floor == 1.0e-30 and olog.log_epsilon == 1.0e-30

    def test_hashable_for_jit_static(self):
        assert hash(SolverOptions()) == hash(SolverOptions())


def test_conv_tolerance_zero_disables_early_stop():
    """conv_tolerance=0 is the fixed-iteration benchmarking switch: the
    stall test |dC| < 0.0 can never fire (bit-exact stalls pass any
    positive tolerance), so the loop runs exactly max_iterations."""
    import numpy as np

    from sartsolver_tpu.config import MAX_ITERATIONS_EXCEEDED, SolverOptions
    from sartsolver_tpu.models.sart import make_problem, solve

    opts = SolverOptions(max_iterations=7, conv_tolerance=0.0)
    rng = np.random.default_rng(0)
    H = rng.uniform(0.1, 1.0, (16, 128)).astype(np.float32)
    g = H.astype(np.float64) @ rng.uniform(0.5, 2.0, 128)
    res = solve(make_problem(H, None, opts=opts), g, opts=opts)
    assert int(res.iterations) == 7
    assert int(res.status) == MAX_ITERATIONS_EXCEEDED


class TestPhaseTimer:
    def test_accumulates_and_formats(self):
        from sartsolver_tpu.utils.timing import PhaseTimer

        t = PhaseTimer()
        t.add("ingest", 1.5)
        t.add("solve", 0.25)
        t.add("solve", 0.35)
        out = t.summary()
        assert out.startswith("timing summary")
        assert "ingest" in out and "1500.0 ms" in out
        # multi-hit phases report the total and the per-hit average
        assert "600.0 ms" in out and "300.0 ms avg over 2" in out

    def test_empty(self):
        from sartsolver_tpu.utils.timing import PhaseTimer

        assert "no phases" in PhaseTimer().summary()
