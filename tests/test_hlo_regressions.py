"""Compiled-HLO regression guards.

The SART loop's performance envelope is set by exactly two streams of the
RTM per iteration (one with the fused sweep). Round 2 found XLA
materializing a full transposed COPY of the RTM inside the while body —
``solution @ rtm.T`` does not get its transpose folded when the RTM is a
loop parameter — costing ~30x the matmul pair. These tests lower the real
solver and assert no matrix-sized transpose/copy lives inside the loop, so
the pathology cannot silently return with a refactor or a JAX upgrade.
"""

import functools
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sartsolver_tpu.config import SolverOptions
from sartsolver_tpu.models.sart import (
    SARTProblem, compute_ray_stats, solve_normalized_batch,
)
from sartsolver_tpu.ops.laplacian import make_laplacian

P, V = 128, 1024


def _computations(txt: str) -> dict:
    """HLO text split into {computation_name: [lines]}."""
    comps: dict = {}
    current = None
    for line in txt.splitlines():
        # header params can be TUPLE-typed (nested parens — e.g. a while
        # body taking one tuple param), so don't try to match the params
        # with [^)]*; name + open paren + '->' + '{' identifies a header
        m = re.match(r"\s*(?:ENTRY\s+)?(%?[\w.\-]+)\s*\(.*->.*{", line)
        if m:
            current = m.group(1).lstrip("%")
            comps[current] = []
        elif current is not None:
            comps[current].append(line)
    return comps


def _while_body_names(txt: str) -> set:
    """Computation names referenced as a while op's body= attribute."""
    names = set()
    for m in re.finditer(r"while\([^)]*\).*?body=%?([\w.\-]+)", txt):
        names.add(m.group(1))
    return names


def _matrix_sized_loop_copies(txt: str, threshold: int) -> list:
    """Transpose/copy ops of >= threshold elements INSIDE while bodies.

    Parses the body computations a `while` op actually references (plus
    their nested fusions) instead of substring-matching "while" on each
    line: metadata-less copies inside the body are caught, and hoisted
    loop-invariant copies outside it are not flagged.
    """
    comps = _computations(txt)
    bodies = _while_body_names(txt)
    assert bodies, "no while loop found in HLO — did the solver change?"

    # include computations (fusions) called from a body computation
    reachable = set()
    frontier = [b for b in bodies]
    while frontier:
        name = frontier.pop()
        if name in reachable or name not in comps:
            continue
        reachable.add(name)
        for line in comps[name]:
            for m in re.finditer(r"(?:calls=|to_apply=)%?([\w.\-]+)", line):
                frontier.append(m.group(1))

    bad = []
    for name in reachable:
        for line in comps.get(name, []):
            if "transpose" not in line and " copy(" not in line and "copy." not in line.split("=")[0]:
                continue
            m = re.search(r"(?:f32|f64|bf16|s8)\[([0-9,]+)\]", line)
            if m and np.prod([int(x) for x in m.group(1).split(",")]) >= threshold:
                bad.append(f"{name}: {line.strip()}")
    return bad


@pytest.mark.parametrize("logarithmic", [False, True])
@pytest.mark.parametrize("batch", [1, 8])
def test_no_rtm_copy_inside_iteration_loop(logarithmic, batch):
    rng = np.random.default_rng(0)
    rtm = jnp.asarray(rng.random((P, V), np.float32))
    dens, length = compute_ray_stats(rtm, dtype=jnp.float32)
    li = np.arange(V)
    lap = make_laplacian(
        np.r_[li, li[1:]], np.r_[li, li[:-1]],
        np.r_[np.full(V, 2.0), np.full(V - 1, -1.0)].astype(np.float32),
    )
    prob = SARTProblem(rtm, dens, length, lap)
    opts = SolverOptions(
        max_iterations=4, conv_tolerance=1e-30, fused_sweep="off",
        logarithmic=logarithmic,
    )
    g = jnp.ones((batch, P), jnp.float32)
    msq = jnp.ones(batch, jnp.float32)
    f0 = jnp.zeros((batch, V), jnp.float32)
    fn = jax.jit(functools.partial(
        solve_normalized_batch, opts=opts, axis_name=None, voxel_axis=None,
        use_guess=True,
    ))
    txt = fn.lower(prob, g, msq, f0).compile().as_text()
    bad = _matrix_sized_loop_copies(txt, P * V)
    assert not bad, (
        "matrix-sized transpose/copy inside the iteration loop "
        "(each one re-streams the tens-of-GB RTM every iteration):\n"
        + "\n".join(bad[:5])
    )


@pytest.mark.parametrize("mesh_shape", [(8, 1), (1, 8)])
def test_no_rtm_copy_inside_sharded_loop(mesh_shape):
    from jax.sharding import NamedSharding, PartitionSpec as PS

    from sartsolver_tpu.parallel.mesh import make_mesh
    from sartsolver_tpu.parallel.sharded import DistributedSARTSolver

    H = np.random.default_rng(1).random((P, V), np.float32)
    opts = SolverOptions(max_iterations=4, conv_tolerance=1e-30,
                         fused_sweep="off")
    s = DistributedSARTSolver(H, opts=opts, mesh=make_mesh(*mesh_shape))
    g = jax.device_put(
        np.ones((1, s.padded_npixel), np.float32),
        NamedSharding(s.mesh, PS(None, "pixels")),
    )
    f0 = jax.device_put(
        np.zeros((1, s.padded_nvoxel), np.float32),
        NamedSharding(s.mesh, PS(None, "voxels")),
    )
    txt = s._batch_fn(True).lower(
        s.problem, g, jnp.ones(1, jnp.float32), f0
    ).compile().as_text()
    local = (s.padded_npixel // mesh_shape[0]) * (s.padded_nvoxel // mesh_shape[1])
    bad = _matrix_sized_loop_copies(txt, local)
    assert not bad, "\n".join(bad[:5])


def _loop_collectives(txt: str, op: str, threshold: int) -> list:
    """Collective ops (e.g. "all-gather") of >= threshold output elements
    inside while bodies (same body-reachability walk as the copy guard)."""
    comps = _computations(txt)
    bodies = _while_body_names(txt)
    assert bodies, "no while loop found in HLO — did the solver change?"
    reachable = set()
    frontier = [b for b in bodies]
    while frontier:
        name = frontier.pop()
        if name in reachable or name not in comps:
            continue
        reachable.add(name)
        for line in comps[name]:
            for m in re.finditer(r"(?:calls=|to_apply=|body=|condition=)%?([\w.\-]+)", line):
                frontier.append(m.group(1))
    bad = []
    for name in reachable:
        for line in comps.get(name, []):
            if f"{op}(" not in line and f"{op}-start" not in line:
                continue
            m = re.search(r"(?:f32|f64|bf16|s8)\[([0-9,]+)\]", line)
            if m and np.prod([int(x) for x in m.group(1).split(",")]) >= threshold:
                bad.append(f"{name}: {line.strip()}")
    return bad


def test_no_full_solution_gather_inside_voxel_sharded_loop():
    """Voxel sharding exists to shed the replicated-solution footprint; the
    Laplacian penalty must therefore not all_gather [B, V_global] every
    iteration (VERDICT r2 weak #1). The halo partition's boundary table for
    a chain Laplacian is [B, 2*n_shards] — assert nothing V_global-sized
    is gathered inside the while body."""
    from jax.sharding import NamedSharding, PartitionSpec as PS

    from sartsolver_tpu.parallel.mesh import make_mesh
    from sartsolver_tpu.parallel.sharded import DistributedSARTSolver

    H = np.random.default_rng(1).random((P, V), np.float32)
    li = np.arange(V)
    lap = make_laplacian(
        np.r_[li, li[1:]], np.r_[li, li[:-1]],
        np.r_[np.full(V, 2.0), np.full(V - 1, -1.0)].astype(np.float32),
    )
    opts = SolverOptions(max_iterations=4, conv_tolerance=1e-30,
                         fused_sweep="off")
    s = DistributedSARTSolver(H, lap, opts=opts, mesh=make_mesh(1, 8))
    g = jax.device_put(
        np.ones((1, s.padded_npixel), np.float32),
        NamedSharding(s.mesh, PS(None, "pixels")),
    )
    f0 = jax.device_put(
        np.zeros((1, s.padded_nvoxel), np.float32),
        NamedSharding(s.mesh, PS(None, "voxels")),
    )
    txt = s._batch_fn(True).lower(
        s.problem, g, jnp.ones(1, jnp.float32), f0
    ).compile().as_text()
    bad = _loop_collectives(txt, "all-gather", s.padded_nvoxel)
    assert not bad, (
        "V_global-sized all-gather inside the voxel-sharded iteration "
        "loop (the halo Laplacian exists to remove this):\n" + "\n".join(bad[:5])
    )


def test_no_codes_copy_inside_int8_loop():
    """The int8 loop must stream only the 1-byte codes: no matrix-sized
    transpose/copy (s8 or dequantized f32/bf16) may live inside the while
    body — a dequantized matrix copy would erase the 4x bandwidth win."""
    from sartsolver_tpu.models.sart import make_problem

    opts = SolverOptions(
        max_iterations=4, conv_tolerance=0.0,
        rtm_dtype="int8", fused_sweep="interpret",
    )
    rng = np.random.default_rng(0)
    prob = make_problem(
        rng.random((P, V)).astype(np.float32), None, opts=opts)
    g = jnp.ones((1, P), jnp.float32)
    msq = jnp.ones(1, jnp.float32)
    f0 = jnp.zeros((1, V), jnp.float32)
    fn = jax.jit(functools.partial(
        solve_normalized_batch, opts=opts, axis_name=None, voxel_axis=None,
        use_guess=True,
    ))
    txt = fn.lower(prob, g, msq, f0).compile().as_text()
    bad = _matrix_sized_loop_copies(txt, P * V)
    assert not bad, (
        "matrix-sized transpose/copy inside the int8 iteration loop:\n"
        + "\n".join(bad[:5])
    )
