"""Compiled-HLO regression guards, driven by the compile-audit API.

The SART loop's performance envelope is set by exactly two streams of the
RTM per iteration (one with the fused sweep). Round 2 found XLA
materializing a full transposed COPY of the RTM inside the while body —
``solution @ rtm.T`` does not get its transpose folded when the RTM is a
loop parameter — costing ~30x the matmul pair. These tests lower the real
solver and assert no matrix-sized transpose/copy (nor oversized gather or
convert) lives inside the loop, so the pathology cannot silently return
with a refactor or a JAX upgrade.

The HLO parsing and invariant checks that used to be hand-rolled here now
live in ``sartsolver_tpu.analysis`` (hlo.py + audit.py): each test builds
the same lowering as before, declares its invariants as an
:class:`~sartsolver_tpu.analysis.registry.AuditEntry`, and asserts
``check_invariants`` finds nothing — the exact machinery ``sartsolve lint
--self`` runs over the registered hot entry points.
"""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sartsolver_tpu.analysis import hlo
from sartsolver_tpu.analysis.audit import check_invariants
from sartsolver_tpu.analysis.registry import AuditEntry
from sartsolver_tpu.config import SolverOptions
from sartsolver_tpu.models.sart import (
    SARTProblem, compute_ray_stats, solve_normalized_batch,
)
from sartsolver_tpu.ops.laplacian import make_laplacian

P, V = 128, 1024


def _spec(name, **invariants) -> AuditEntry:
    """Ad-hoc audit entry for a test-local lowering (build never called).

    ``allow_f64=True``: the test harness enables x64 process-wide
    (conftest.py), which legitimately routes the precise-convergence
    accumulation through f64; the *registered* entries pin the no-f64
    invariant under the production fp32 profile (audit.py disables x64
    while lowering them)."""
    invariants.setdefault("allow_f64", True)
    return AuditEntry(
        name=name, build=lambda: None, description=name, **invariants
    )


def _chain_laplacian(dtype=np.float32):
    li = np.arange(V)
    return make_laplacian(
        np.r_[li, li[1:]], np.r_[li, li[:-1]],
        np.r_[np.full(V, 2.0), np.full(V - 1, -1.0)].astype(dtype),
    )


@pytest.mark.parametrize("logarithmic", [False, True])
@pytest.mark.parametrize("batch", [1, 8])
def test_no_rtm_copy_inside_iteration_loop(logarithmic, batch):
    rng = np.random.default_rng(0)
    rtm = jnp.asarray(rng.random((P, V), np.float32))
    dens, length = compute_ray_stats(rtm, dtype=jnp.float32)
    prob = SARTProblem(rtm, dens, length, _chain_laplacian())
    opts = SolverOptions(
        max_iterations=4, conv_tolerance=1e-30, fused_sweep="off",
        logarithmic=logarithmic,
    )
    g = jnp.ones((batch, P), jnp.float32)
    msq = jnp.ones(batch, jnp.float32)
    f0 = jnp.zeros((batch, V), jnp.float32)
    fn = jax.jit(functools.partial(
        solve_normalized_batch, opts=opts, axis_name=None, voxel_axis=None,
        use_guess=True,
    ))
    txt = fn.lower(prob, g, msq, f0).compile().as_text()
    violations = check_invariants(txt, _spec(
        "iteration-loop", loop_copy_threshold=P * V,
    ))
    assert not violations, (
        "matrix-sized transpose/copy inside the iteration loop "
        "(each one re-streams the tens-of-GB RTM every iteration):\n"
        + "\n".join(violations)
    )


@pytest.mark.parametrize("mesh_shape", [(8, 1), (1, 8)])
def test_no_rtm_copy_inside_sharded_loop(mesh_shape):
    from jax.sharding import NamedSharding, PartitionSpec as PS

    from sartsolver_tpu.parallel.mesh import make_mesh
    from sartsolver_tpu.parallel.sharded import DistributedSARTSolver

    H = np.random.default_rng(1).random((P, V), np.float32)
    opts = SolverOptions(max_iterations=4, conv_tolerance=1e-30,
                         fused_sweep="off")
    s = DistributedSARTSolver(H, opts=opts, mesh=make_mesh(*mesh_shape))
    g = jax.device_put(
        np.ones((1, s.padded_npixel), np.float32),
        NamedSharding(s.mesh, PS(None, "pixels")),
    )
    f0 = jax.device_put(
        np.zeros((1, s.padded_nvoxel), np.float32),
        NamedSharding(s.mesh, PS(None, "voxels")),
    )
    txt = s._batch_fn(True).lower(
        s.problem, g, jnp.ones(1, jnp.float32), f0
    ).compile().as_text()
    local = (s.padded_npixel // mesh_shape[0]) * (s.padded_nvoxel // mesh_shape[1])
    violations = check_invariants(txt, _spec(
        "sharded-loop", loop_copy_threshold=local,
    ))
    assert not violations, "\n".join(violations)


def test_no_full_solution_gather_inside_voxel_sharded_loop():
    """Voxel sharding exists to shed the replicated-solution footprint; the
    Laplacian penalty must therefore not all_gather [B, V_global] every
    iteration (VERDICT r2 weak #1). The halo partition's boundary table for
    a chain Laplacian is [B, 2*n_shards] — budget the loop at zero
    V_global-sized all-gathers via the audit's sized-op search."""
    from jax.sharding import NamedSharding, PartitionSpec as PS

    from sartsolver_tpu.parallel.mesh import make_mesh
    from sartsolver_tpu.parallel.sharded import DistributedSARTSolver

    H = np.random.default_rng(1).random((P, V), np.float32)
    opts = SolverOptions(max_iterations=4, conv_tolerance=1e-30,
                         fused_sweep="off")
    s = DistributedSARTSolver(
        H, _chain_laplacian(), opts=opts, mesh=make_mesh(1, 8)
    )
    g = jax.device_put(
        np.ones((1, s.padded_npixel), np.float32),
        NamedSharding(s.mesh, PS(None, "pixels")),
    )
    f0 = jax.device_put(
        np.zeros((1, s.padded_nvoxel), np.float32),
        NamedSharding(s.mesh, PS(None, "voxels")),
    )
    txt = s._batch_fn(True).lower(
        s.problem, g, jnp.ones(1, jnp.float32), f0
    ).compile().as_text()
    assert hlo.while_body_names(txt), "no while loop found in HLO"
    bad = hlo.sized_loop_ops(txt, ("all-gather",), s.padded_nvoxel)
    assert not bad, (
        "V_global-sized all-gather inside the voxel-sharded iteration "
        "loop (the halo Laplacian exists to remove this):\n" + "\n".join(bad[:5])
    )


def test_no_codes_copy_inside_int8_loop():
    """The int8 loop must stream only the 1-byte codes: no matrix-sized
    transpose/copy (s8 or dequantized f32/bf16) may live inside the while
    body — a dequantized matrix copy would erase the 4x bandwidth win."""
    from sartsolver_tpu.models.sart import make_problem

    opts = SolverOptions(
        max_iterations=4, conv_tolerance=0.0,
        rtm_dtype="int8", fused_sweep="interpret",
    )
    rng = np.random.default_rng(0)
    prob = make_problem(
        rng.random((P, V)).astype(np.float32), None, opts=opts)
    g = jnp.ones((1, P), jnp.float32)
    msq = jnp.ones(1, jnp.float32)
    f0 = jnp.zeros((1, V), jnp.float32)
    fn = jax.jit(functools.partial(
        solve_normalized_batch, opts=opts, axis_name=None, voxel_axis=None,
        use_guess=True,
    ))
    txt = fn.lower(prob, g, msq, f0).compile().as_text()
    violations = check_invariants(txt, _spec(
        "int8-loop", loop_copy_threshold=P * V,
    ))
    assert not violations, (
        "matrix-sized transpose/copy inside the int8 iteration loop:\n"
        + "\n".join(violations)
    )


def test_sweep_has_no_loop_collectives_single_device():
    """The single-device sweep must not compile collectives into the loop
    at all — the budget mechanism the registered entries declare, exercised
    here end to end against a fresh lowering."""
    rng = np.random.default_rng(0)
    rtm = jnp.asarray(rng.random((P, V), np.float32))
    dens, length = compute_ray_stats(rtm, dtype=jnp.float32)
    prob = SARTProblem(rtm, dens, length, None)
    opts = SolverOptions(max_iterations=4, conv_tolerance=1e-30,
                         fused_sweep="off")
    fn = jax.jit(functools.partial(
        solve_normalized_batch, opts=opts, axis_name=None, voxel_axis=None,
        use_guess=True,
    ))
    txt = fn.lower(
        prob, jnp.ones((1, P), jnp.float32), jnp.ones(1, jnp.float32),
        jnp.zeros((1, V), jnp.float32),
    ).compile().as_text()
    violations = check_invariants(txt, _spec(
        "single-device-sweep",
        loop_collective_budget={
            "all-reduce": 0, "all-gather": 0, "all-to-all": 0,
            "collective-permute": 0,
        },
    ))
    assert not violations, "\n".join(violations)
