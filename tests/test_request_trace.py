"""Request-lifecycle observatory matrix (docs/OBSERVABILITY.md §10;
`make trace`).

- **Span completeness** — a real in-process serve run with tracing
  active yields, for every request, one Perfetto-loadable per-request
  section holding the full lifecycle: admission -> queue.wait ->
  journal markers -> session.attach -> sched.stride (with lane index,
  iterations-this-stride and occupancy) -> lane.retire -> io.write ->
  request.done; the trace id joins journal markers, response records
  and frame records.
- **Scrape parity** — the `--http_port` /metrics endpoint is byte-
  equivalent to the Prometheus textfile sink rendered from the same
  registry snapshot; /healthz (liveness) / /readyz (readiness) and /status serve the admission state and
  the live status snapshot from the non-blocking forms.
- **Disabled identity** — without `--http_port`/tracing a serve run
  creates no endpoint, no traces directory and no new threads.
- **SLO accounting** — fixed-bucket quantile estimates (p50/p95/p99)
  with exact cross-host merge, the error-budget counter pair, and the
  `sartsolve metrics --diff` p99 queue-wait / SLO-burn gates
  (zero-baseline-safe with loud skip notes).
- **Crash attribution** — the crash bundle's engine section names the
  in-flight trace ids and their last span; after a SIGKILL the journal
  markers carry the trace ids of whatever was in flight.
"""

import json
import os
import random
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

import fixtures as fx

from sartsolver_tpu.engine import admission as adm_mod
from sartsolver_tpu.engine.request import parse_request
from sartsolver_tpu.obs import metrics as obs_metrics
from sartsolver_tpu.obs import sinks as obs_sinks
from sartsolver_tpu.obs import trace as obs_trace

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)

SOLVE_FLAGS = ["--use_cpu", "-m", "40", "-c", "1e-12"]


# ---------------------------------------------------------------------------
# trace ids
# ---------------------------------------------------------------------------

def test_trace_id_passthrough_and_assignment():
    # client-propagated id rides the payload verbatim
    req = parse_request('{"id": "a", "trace": "client.span-1"}')
    assert req.trace == "client.span-1"
    # absent -> assigned at parse time, stable through to_dict round trip
    req = parse_request('{"id": "b"}')
    assert req.trace and len(req.trace) == 16
    assert parse_request(json.dumps(req.to_dict())).trace == req.trace
    # malformed ids are a client error, not an engine abort
    from sartsolver_tpu.engine.request import RequestError

    with pytest.raises(RequestError):
        parse_request('{"id": "c", "trace": "no spaces"}')
    with pytest.raises(RequestError):
        parse_request('{"id": "c", "trace": ""}')


# ---------------------------------------------------------------------------
# quantile estimates (obs/metrics.py fixed buckets)
# ---------------------------------------------------------------------------

def test_histogram_quantiles_accuracy_and_merge():
    reg = obs_metrics.MetricsRegistry()
    h = reg.histogram("w")
    rng = random.Random(7)
    vals = [rng.uniform(0.001, 2.0) for _ in range(2000)]
    for v in vals:
        h.observe(v)
    ordered = sorted(vals)
    snap = h.snapshot()
    for q, key in obs_metrics.QUANTILES:
        true = ordered[int(q * len(ordered)) - 1]
        assert abs(snap[key] / true - 1) < 0.15, (key, snap[key], true)
    # extremes are exact: estimates clamp into the observed range
    one = reg.histogram("one")
    one.observe(0.123)
    s = one.snapshot()
    assert s["p50"] == s["p99"] == 0.123
    # cross-host merge is exact on the fixed layout: merging the same
    # snapshot twice doubles every bucket and keeps the estimates
    reg2 = obs_metrics.MetricsRegistry()
    reg2.merge_snapshot(reg.snapshot())
    reg2.merge_snapshot(reg.snapshot())
    h2 = reg2.histogram("w")
    assert h2.count == 2 * len(vals)
    assert sum(h2.buckets.values()) == 2 * len(vals)
    for q, key in obs_metrics.QUANTILES:
        assert h2.snapshot()[key] == pytest.approx(snap[key])
    # a pre-bucket snapshot (older artifact generation) merges its
    # moments and simply contributes no buckets
    reg2.merge_snapshot([{"kind": "histogram", "name": "w", "labels": {},
                          "count": 5, "sum": 1.0, "min": 0.1,
                          "max": 0.5}])
    assert reg2.histogram("w").count == 2 * len(vals) + 5
    # zero, overflow and inf land in the edge buckets without error
    edge = reg.histogram("edge")
    edge.observe(0.0)
    edge.observe(1e9)
    assert edge.snapshot()["p99"] == 1e9  # clamped to max
    edge.observe(float("inf"))  # previously only moments absorbed inf
    assert edge.snapshot()["count"] == 3
    # a merge from a bucket-less generation must not skew the estimate
    # toward max: quantiles come from the bucketed subsample
    mixed = obs_metrics.MetricsRegistry().histogram("mix")
    for _ in range(10):
        mixed.observe(0.1)
    mixed.merge({"kind": "histogram", "name": "mix", "labels": {},
                 "count": 1000, "sum": 10.0, "min": 0.001, "max": 50.0})
    assert mixed.snapshot()["p50"] == pytest.approx(0.1, rel=0.15)


def test_trace_buffer_track_cap():
    """A saturated buffer stops allocating request tracks (and their
    metadata rows): a resident server's track table is bounded by the
    same SART_TRACE_MAX_EVENTS cap as the events."""
    buf = obs_trace.TraceBuffer(max_events=4)
    buf.add_request_instant("t1", "a")  # metadata + instant = 2 events
    buf.add_request_instant("t1", "b")  # 3
    buf.add_request_instant("t2", "a")  # 4 (track t2's metadata) + drop
    for i in range(20):
        buf.add_request_instant(f"late-{i}", "x")  # all dropped
    assert len(buf._tracks) <= 4
    chrome = buf.to_chrome()
    assert len(chrome["traceEvents"]) == 4
    assert chrome["otherData"]["dropped_events"] >= 20
    assert buf.request_events("late-5") is None


def test_prometheus_renders_quantile_series():
    reg = obs_metrics.MetricsRegistry()
    reg.histogram("engine_queue_wait_s").observe(0.25)
    text = obs_sinks.render_prometheus(reg.snapshot())
    for suffix in ("_p50", "_p95", "_p99"):
        assert f"sart_engine_queue_wait_s{suffix}" in text
        assert f"# HELP sart_engine_queue_wait_s{suffix} " in text
    # a quantile-less snapshot (older generation) renders without the
    # series — no None samples, no crash
    legacy = [{"kind": "histogram", "name": "engine_queue_wait_s",
               "labels": {}, "count": 1, "sum": 0.25, "min": 0.25,
               "max": 0.25}]
    text = obs_sinks.render_prometheus(legacy)
    assert "_p99" not in text


# ---------------------------------------------------------------------------
# in-process serve run with tracing active: span completeness
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def world(tmp_path_factory):
    td = tmp_path_factory.mktemp("trace_world")
    paths, *_ = fx.write_world(str(td), n_frames=4)
    return paths


@pytest.fixture(scope="module")
def session(world):
    from sartsolver_tpu.cli import _validate
    from sartsolver_tpu.engine.cli import build_serve_parser
    from sartsolver_tpu.engine.session import ResidentSession

    args = build_serve_parser().parse_args([
        "--engine_dir", "/nonexistent-unused", *SOLVE_FLAGS,
        world["rtm_a1"], world["rtm_a2"], world["rtm_b"],
        world["img_a"], world["img_b"],
    ])
    _validate(args)
    return ResidentSession.build(args)


def _run_server(session, eng_dir, requests, **kw):
    from sartsolver_tpu.engine.server import EngineServer

    os.makedirs(os.path.join(eng_dir, "ingest"), exist_ok=True)
    for i, payload in enumerate(requests):
        with open(os.path.join(eng_dir, "ingest",
                               f"{i:03d}-{payload['id']}.json"),
                  "w") as f:
            json.dump(payload, f)
    admission = kw.pop("admission", None)
    if admission is None:
        admission = adm_mod.AdmissionController(max_queue=16)
    server = EngineServer(
        session, engine_dir=eng_dir, lanes=kw.pop("lanes", 2),
        admission=admission, poll_interval=0.05,
        idle_exit=kw.pop("idle_exit", 0.4), **kw,
    )
    rc = server.run()
    return server, rc


def test_serve_run_span_completeness(session, tmp_path):
    """One traced serve round trip: the request's track holds the full
    lifecycle and lands as a standalone Perfetto-loadable file; the
    trace id joins journal markers and the response record."""
    obs_metrics.reset_registry()
    buf = obs_trace.install(obs_trace.TraceBuffer())
    eng = str(tmp_path / "eng")
    try:
        server, rc = _run_server(session, eng, [
            {"id": "traced", "tenant": "a", "trace": "trace-0001"},
        ])
    finally:
        obs_trace.uninstall()
    assert rc == 0

    # response + journal carry the trace id
    with open(os.path.join(eng, "responses", "traced.json")) as f:
        resp = json.load(f)
    assert resp["trace"] == "trace-0001"
    assert resp["outcome"]["trace"] == "trace-0001"
    markers = {}
    with open(os.path.join(eng, "journal.jsonl")) as f:
        for line in f:
            rec = json.loads(line)
            markers[rec["marker"]] = rec
    for marker in ("accepted", "dispatched", "completed"):
        assert markers[marker]["trace"] == "trace-0001", marker

    # the per-request section is complete and self-contained
    payload = buf.request_events("trace-0001")
    names = [e["name"] for e in payload["traceEvents"]]
    for expected in ("thread_name", "admission", "queue.wait",
                     "journal.accepted", "journal.dispatched",
                     "journal.completed", "session.attach",
                     "sched.stride", "lane.retire", "io.write",
                     "request.done"):
        assert expected in names, (expected, names)
    # every event sits on the request's one track, tagged with the id
    tids = {e["tid"] for e in payload["traceEvents"]}
    assert len(tids) == 1
    strides = [e for e in payload["traceEvents"]
               if e["name"] == "sched.stride"]
    for ev in strides:
        assert {"lane", "iters", "stride", "occupancy"} <= set(ev["args"])
        assert ev["args"]["trace"] == "trace-0001"
    assert sum(e["args"]["iters"] for e in strides) > 0
    retire = [e for e in payload["traceEvents"]
              if e["name"] == "lane.retire"]
    # SUCCESS or MAX_ITERATIONS depending on the tiny world's seed —
    # what the pin cares about is the per-lane retirement attribution
    assert retire and retire[0]["args"]["status"] in (0, -1)
    assert retire[0]["args"]["iterations"] > 0

    # ... and was published next to the outputs, loadable on its own
    path = os.path.join(eng, "traces", "traced.trace.json")
    with open(path) as f:
        published = json.load(f)
    assert published["otherData"]["trace"] == "trace-0001"
    assert [e["name"] for e in published["traceEvents"]] == names


def test_trace_rides_metrics_artifact(session, tmp_path):
    """Frame records in the run artifact carry the request trace id
    (the engine threads it through record_frame; FAILED rows take the
    same path), so a sliced artifact still attributes every frame to
    its request."""
    from sartsolver_tpu.obs.run import RunTelemetry

    obs_metrics.reset_registry()
    telem = RunTelemetry(jsonl_path=str(tmp_path / "run.jsonl"))
    eng = str(tmp_path / "eng")
    server, rc = _run_server(session, eng, [
        {"id": "ok1", "tenant": "a", "trace": "tr-ok"},
    ], telemetry=telem)
    assert rc == 0
    telem.finalize(None)
    frames = []
    with open(str(tmp_path / "run.jsonl")) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("type") == "frame":
                frames.append(rec)
    assert frames and all(fr["trace"] == "tr-ok" for fr in frames)


# ---------------------------------------------------------------------------
# live endpoints: scrape parity, health states, status, top over http
# ---------------------------------------------------------------------------

def _get(url, timeout=5.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read()


def test_scrape_vs_textfile_byte_parity(tmp_path):
    """/metrics is rendered from the same snapshot by the same renderer
    as the Prometheus textfile sink — byte-equivalent, family for
    family (ISSUE acceptance)."""
    from sartsolver_tpu.engine.httpd import EngineHTTPServer

    reg = obs_metrics.MetricsRegistry()
    reg.counter("frames_total", status="success").inc(3)
    reg.gauge("engine_lanes").set(2)
    reg.histogram("engine_queue_wait_s").observe(0.05)
    reg.histogram("engine_queue_wait_s", tenant="a").observe(0.05)
    frozen = reg.snapshot()

    srv = EngineHTTPServer(
        0, metrics_snapshot=lambda: frozen,
        health=lambda: ("ok", None),
        status=lambda: {"type": "status"},
    )
    srv.start()
    try:
        code, scraped = _get(f"http://127.0.0.1:{srv.port}/metrics")
        assert code == 200
        prom_path = str(tmp_path / "metrics.prom")
        obs_sinks.PromSink(prom_path).write(frozen)
        with open(prom_path, "rb") as f:
            textfile = f.read()
        assert scraped == textfile
        # 404 for anything else
        with pytest.raises(urllib.error.HTTPError):
            _get(f"http://127.0.0.1:{srv.port}/nope")
    finally:
        srv.stop()


def test_endpoints_on_live_engine(session, tmp_path, monkeypatch):
    """A real serve loop with --http_port: /healthz is pure liveness
    (live 200 while the worker answers), /readyz tracks the admission
    state (ready -> draining 503, docs/SERVING.md §9), /status carries
    the engine section, /metrics scrapes, and `sartsolve top
    http://...` renders live (with the --once exit-1 contract once the
    engine is gone)."""
    from sartsolver_tpu.engine.server import EngineServer
    from sartsolver_tpu.obs import flight as obs_flight
    from sartsolver_tpu.obs.cli import render_top, top_main
    from sartsolver_tpu.resilience import shutdown

    obs_metrics.reset_registry()
    obs_metrics.get_registry().histogram(
        "engine_queue_wait_s").observe(0.01)
    eng = str(tmp_path / "eng")
    os.makedirs(os.path.join(eng, "ingest"), exist_ok=True)
    server = EngineServer(
        session, engine_dir=eng, lanes=2,
        admission=adm_mod.AdmissionController(max_queue=4),
        poll_interval=0.05, idle_exit=0.0, http_port=0,
    )
    stop = {"flag": False}
    monkeypatch.setattr(shutdown, "stop_requested",
                        lambda: stop["flag"])
    t = threading.Thread(target=server.run, daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 30
        while server.http is None and time.monotonic() < deadline:
            time.sleep(0.02)
        assert server.http is not None
        base = f"http://127.0.0.1:{server.http.port}"
        code, body = _get(base + "/healthz")
        assert code == 200 and json.loads(body)["status"] == "live"
        code, body = _get(base + "/readyz")
        assert code == 200 and json.loads(body)["status"] == "ready"
        code, body = _get(base + "/status")
        assert code == 200
        rec = json.loads(body)
        assert rec["type"] == "status" and "engine" in rec
        code, body = _get(base + "/metrics")
        assert code == 200
        assert b"sart_engine_queue_wait_s_p99" in body
        # top renders the live endpoint (status header + prom families)
        screen = render_top(base)
        assert "engine" in screen and "sart_engine_queue_wait_s" in screen
        assert top_main([base, "--once"]) == 0
        stop["flag"] = True
    finally:
        stop["flag"] = True
        t.join(timeout=60)
    assert not t.is_alive()
    assert server.http is None  # endpoint torn down with the loop
    # after the stop the readiness state is draining...
    assert server._ready()[0] == "draining"
    # ...and the /readyz mapping for that state is 503 with the
    # byte-stable reason, while /healthz stays live — the process IS
    # alive (pinned on a standalone endpoint — the live loop exits the
    # same iteration it flips the flag, so the window is not reliably
    # observable)
    from sartsolver_tpu.engine.httpd import EngineHTTPServer

    srv = EngineHTTPServer(
        0, metrics_snapshot=lambda: [], health=server._health,
        ready=server._ready, status=lambda: {},
    )
    srv.start()
    try:
        code, body = _get(f"http://127.0.0.1:{srv.port}/healthz")
        assert code == 200 and json.loads(body)["status"] == "live"
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(f"http://127.0.0.1:{srv.port}/readyz")
        assert exc.value.code == 503
        rec = json.loads(exc.value.read())
        assert rec["status"] == "not-ready" and rec["reason"] == "draining"
    finally:
        srv.stop()
    # unreachable endpoint: the --once probe must report failure
    assert top_main([f"http://127.0.0.1:1/", "--once"]) == 1


def test_http_port_bind_failure_is_input_error(session, tmp_path,
                                               monkeypatch):
    """An unbindable --http_port (EADDRINUSE) is a config problem: the
    serve loop exits with the polite input-error code, not a traceback
    plus a misleading crash bundle. (The short bind-retry budget exists
    for supervised respawns racing a dead worker's lingering port —
    shrunk here so the permanently-held port fails fast.)"""
    import socket

    from sartsolver_tpu.engine.server import EngineServer

    monkeypatch.setenv("SART_HTTP_BIND_RETRY_S", "0.2")
    obs_metrics.reset_registry()
    holder = socket.socket()
    holder.bind(("127.0.0.1", 0))
    holder.listen(1)
    try:
        server = EngineServer(
            session, engine_dir=str(tmp_path / "eng"), lanes=2,
            admission=adm_mod.AdmissionController(max_queue=4),
            idle_exit=0.2, http_port=holder.getsockname()[1],
        )
        assert server.run() == 1
        assert server.http is None
    finally:
        holder.close()


def test_disabled_path_identity(session, tmp_path):
    """Without --http_port/tracing: no traces dir, no endpoint object,
    no extra threads after the run (ISSUE acceptance)."""
    obs_metrics.reset_registry()
    before = threading.active_count()
    eng = str(tmp_path / "eng")
    server, rc = _run_server(session, eng, [
        {"id": "plain", "tenant": "a"},
    ])
    assert rc == 0
    assert server.http is None
    assert not os.path.exists(os.path.join(eng, "traces"))
    assert threading.active_count() == before
    # the lifecycle surfaces stay: trace ids in journal + response even
    # with the trace BUFFER off (ids are host bookkeeping, spans are
    # the opt-in part)
    with open(os.path.join(eng, "responses", "plain.json")) as f:
        assert json.load(f)["trace"]


# ---------------------------------------------------------------------------
# SLO accounting: counter pair + summarize + --diff gates
# ---------------------------------------------------------------------------

def test_slo_counter_pair_on_live_run(session, tmp_path):
    """--slo_ms accounting on a real run: a generous target burns no
    budget; a 0.001 ms target breaches on every request."""
    obs_metrics.reset_registry()
    eng = str(tmp_path / "eng")
    _run_server(session, eng, [{"id": "s1", "tenant": "a"}],
                slo_ms=10 * 60 * 1000.0)
    snap = {(s["name"], tuple(sorted(s["labels"].items()))): s
            for s in obs_metrics.get_registry().snapshot()}
    assert snap[("engine_slo_ok_total", (("tenant", "a"),))]["value"] == 1
    assert ("engine_slo_breach_total", (("tenant", "a"),)) not in snap

    obs_metrics.reset_registry()
    eng2 = str(tmp_path / "eng2")
    _run_server(session, eng2, [{"id": "s2", "tenant": "b"}],
                slo_ms=0.001)
    snap = {(s["name"], tuple(sorted(s["labels"].items()))): s
            for s in obs_metrics.get_registry().snapshot()}
    key = ("engine_slo_breach_total", (("tenant", "b"),))
    assert snap[key]["value"] == 1


def _slo_artifact(path, *, p99, breaches=0, oks=10, with_slo=True,
                  with_quantiles=True):
    from sartsolver_tpu.obs import schema

    # mean pinned at 0.05 whatever the p99 does: the p99 gate must trip
    # on a regressed TAIL the mean gate cannot see
    hist = {"type": "metric", "kind": "histogram",
            "name": "engine_queue_wait_s", "labels": {},
            "count": 100, "sum": 100 * 0.05,
            "min": 0.01, "max": p99}
    if with_quantiles:
        hist.update({"p50": 0.05, "p95": 0.08, "p99": p99,
                     "buckets": {str(obs_metrics.bucket_index(p99)): 100}})
    records = [
        schema.make_meta_record(created_unix=1.0),
        hist,
        {"type": "metric", "kind": "counter",
         "name": "engine_admitted_total", "labels": {}, "value": 10},
        {"type": "metric", "kind": "counter",
         "name": "engine_deadline_miss_total", "labels": {}, "value": 0},
    ]
    if with_slo:
        records += [
            {"type": "metric", "kind": "counter",
             "name": "engine_slo_ok_total", "labels": {"tenant": "a"},
             "value": oks},
            {"type": "metric", "kind": "counter",
             "name": "engine_slo_breach_total",
             "labels": {"tenant": "a"}, "value": breaches},
            {"type": "metric", "kind": "gauge",
             "name": "engine_slo_target_ms", "labels": {},
             "value": 100.0},
        ]
    records.append(schema.make_summary_record(0, {}, wall_s=1.0))
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")


def test_metrics_slo_summary_and_p99_gate(tmp_path, capsys):
    from sartsolver_tpu.obs.cli import _load, metrics_main, summarize

    old = str(tmp_path / "old.jsonl")
    new = str(tmp_path / "new.jsonl")
    _slo_artifact(old, p99=0.1, breaches=0)
    summary = summarize(_load(old)[0])
    eng = summary["engine"]
    assert eng["queue_wait_p99_s"] == pytest.approx(0.1)
    assert eng["slo"]["burn_rate"] == 0.0
    assert eng["slo"]["target_ms"] == 100.0

    # p99 within threshold passes; past it trips exit 2 with the named
    # gate even when the MEAN stays put
    _slo_artifact(new, p99=0.12, breaches=0)
    assert metrics_main(["--diff", old, new, "--threshold", "60"]) == 0
    capsys.readouterr()
    _slo_artifact(new, p99=0.5, breaches=0)
    assert metrics_main(["--diff", old, new, "--threshold", "60"]) == 2
    assert "queue-wait p99" in capsys.readouterr().err

    # SLO burn rising past the point threshold trips its gate
    _slo_artifact(new, p99=0.1, breaches=9, oks=1)
    assert metrics_main(["--diff", old, new, "--threshold", "60"]) == 2
    assert "error-budget burn" in capsys.readouterr().err

    # zero-baseline / pre-quantile artifacts: loud skip note, exit 0
    legacy = str(tmp_path / "legacy.jsonl")
    _slo_artifact(legacy, p99=0.1, with_slo=False, with_quantiles=False)
    assert metrics_main(["--diff", legacy, new, "--threshold",
                         "1000"]) == 0
    err = capsys.readouterr().err
    assert "p99" in err and "skipped" in err
    assert "SLO accounting missing" in err


# ---------------------------------------------------------------------------
# crash attribution: bundle engine section + SIGKILL journal triage
# ---------------------------------------------------------------------------

def test_crash_bundle_names_inflight_traces(session, tmp_path):
    """The crash bundle's engine section carries the live request
    table — id, trace id, last span — through the non-blocking
    status-snapshot path the watchdog crash hook uses (the stage-3
    os._exit leg writes exactly this record)."""
    from sartsolver_tpu.engine.server import EngineServer
    from sartsolver_tpu.obs import flight as obs_flight
    from sartsolver_tpu.resilience import watchdog

    obs_metrics.reset_registry()
    server = EngineServer(
        session, engine_dir=str(tmp_path / "eng"), lanes=2,
        admission=adm_mod.AdmissionController(max_queue=4),
    )
    req = parse_request('{"id": "wedged", "trace": "tr-wedged"}')
    server._set_span(req, "solve")
    server._active_ids.append("wedged")
    watchdog.set_engine_status_provider(server._status)
    try:
        bundle_path = str(tmp_path / "crash.json")
        assert obs_flight.write_crash_bundle(bundle_path,
                                             "watchdog abort (drill)")
        with open(bundle_path) as f:
            bundle = json.load(f)
        table = bundle["status"]["engine"]["requests"]
        assert table["wedged"] == {"trace": "tr-wedged", "span": "solve"}
        assert "wedged" in bundle["status"]["engine"]["active_requests"]
    finally:
        watchdog.set_engine_status_provider(None)


def test_sigkill_journal_names_inflight_trace(tmp_path):
    """SIGKILL a real serve inside the dispatched journal window;
    triage reads the journal: the in-flight request's accepted and
    dispatched markers carry its trace id, the completed marker is
    absent — "which requests were in flight when it died"."""
    td = tmp_path / "world"
    td.mkdir()
    paths, *_ = fx.write_world(str(td), n_frames=3)
    eng = str(tmp_path / "eng")
    os.makedirs(os.path.join(eng, "ingest"))
    with open(os.path.join(eng, "ingest", "0-k.json"), "w") as f:
        json.dump({"id": "kill1", "trace": "tr-kill1"}, f)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONUNBUFFERED"] = "1"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["SART_TEST_JOURNAL_DELAY"] = "1.5"
    env.pop("SART_FAULT", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "sartsolver_tpu.cli", "serve",
         "--engine_dir", eng, *SOLVE_FLAGS, "--lanes", "2",
         "--poll_interval", "0.05",
         paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
         paths["img_a"], paths["img_b"]],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        deadline = time.monotonic() + 300
        for line in proc.stdout:
            if "SART_JOURNAL_POINT dispatched" in line:
                proc.kill()
                break
            assert time.monotonic() < deadline, "no dispatched window"
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    markers = []
    with open(os.path.join(eng, "journal.jsonl")) as f:
        for line in f:
            try:
                markers.append(json.loads(line))
            except ValueError:
                pass  # torn tail: the kill window's own contract
    by_marker = {m["marker"]: m for m in markers}
    assert by_marker["accepted"]["trace"] == "tr-kill1"
    assert by_marker["dispatched"]["trace"] == "tr-kill1"
    assert "completed" not in by_marker
