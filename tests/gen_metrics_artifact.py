"""Generate a real --metrics_out artifact from a synthetic world.

Used by ``make obs``: runs the full CLI over the test fixtures with the
JSONL sink enabled and leaves the artifact at argv[2] (world files under
argv[1]), so the drill can then run ``sartsolve metrics --check`` /
summarize against an artifact produced by the actual pipeline, not a
hand-built one. Exits with the CLI's exit code (0 expected).
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)  # fixtures.py
sys.path.insert(0, os.path.dirname(_here))  # the repo checkout itself

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import fixtures as fx  # noqa: E402
from sartsolver_tpu.cli import main  # noqa: E402


def run(world_dir: str, artifact: str) -> int:
    paths, *_ = fx.write_world(world_dir, with_laplacian=True)
    return main([
        "-o", paths["output"],
        paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
        paths["img_a"], paths["img_b"],
        "--use_cpu", "-m", "300", "-c", "1e-6",
        "--metrics_out", artifact,
    ])


if __name__ == "__main__":
    sys.exit(run(sys.argv[1], sys.argv[2]))
