"""IO layer tests on synthetic fixtures (discovery, sorting, consistency,
RTM block reads, composite alignment, solution round trip)."""

import os

import numpy as np
import pytest
import h5py

from sartsolver_tpu.io import hdf5files as hf
from sartsolver_tpu.io.image import CompositeImage
from sartsolver_tpu.io.laplacian_io import read_laplacian
from sartsolver_tpu.io.raytransfer import read_rtm_block
from sartsolver_tpu.io.solution import SolutionWriter
from sartsolver_tpu.io.voxelgrid import (
    CARTESIAN, CYLINDRICAL, CartesianVoxelGrid, CylindricalVoxelGrid,
    get_coordinate_system_hdf5, make_voxel_grid,
)

import fixtures as fx


@pytest.fixture
def world(tmp_path):
    return fx.write_world(tmp_path, with_laplacian=True)


def all_input_files(paths):
    return [paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
            paths["img_a"], paths["img_b"]]


class TestDiscovery:
    def test_categorize(self, world):
        paths = world[0]
        m, i = hf.categorize_input_files(all_input_files(paths))
        assert sorted(m) == sorted([paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"]])
        assert sorted(i) == sorted([paths["img_a"], paths["img_b"]])

    def test_categorize_rejects_unknown(self, world, tmp_path):
        bad = str(tmp_path / "bad.h5")
        with h5py.File(bad, "w") as f:
            f.create_group("mystery")
        with pytest.raises(hf.SartInputError, match="neither an RTM"):
            hf.categorize_input_files([bad])

    def test_sort_rtm_files_by_voxel_offset(self, world):
        paths = world[0]
        m, _ = hf.categorize_input_files(all_input_files(paths))
        sorted_m = hf.sort_rtm_files(m)
        assert list(sorted_m) == [fx.CAM_A, fx.CAM_B]  # std::map name order
        assert sorted_m[fx.CAM_A] == [paths["rtm_a1"], paths["rtm_a2"]]

    def test_total_size(self, world):
        paths = world[0]
        m, _ = hf.categorize_input_files(all_input_files(paths))
        npix, nvox = hf.get_total_rtm_size(hf.sort_rtm_files(m))
        assert (npix, nvox) == (fx.NPIXEL, fx.NVOXEL)

    def test_consistency_checks_pass(self, world):
        paths = world[0]
        m, i = hf.categorize_input_files(all_input_files(paths))
        sm = hf.sort_rtm_files(m)
        si = hf.sort_image_files(i)
        hf.check_group_attribute_consistency(m, "rtm/with_reflections", ["wavelength"])
        hf.check_group_attribute_consistency(m, "rtm/voxel_map", ["nx", "ny", "nz"])
        hf.check_rtm_frame_consistency(sm)
        hf.check_rtm_voxel_consistency(sm)
        hf.check_rtm_image_consistency(sm, si, "with_reflections", 50.0)

    def test_wavelength_threshold_enforced(self, world):
        paths = world[0]
        with h5py.File(paths["img_a"], "r+") as f:
            f["image"].attrs.modify("wavelength", fx.WAVELENGTH + 10.0)
        with h5py.File(paths["img_b"], "r+") as f:
            f["image"].attrs.modify("wavelength", fx.WAVELENGTH + 10.0)
        m, i = hf.categorize_input_files(all_input_files(paths))
        sm, si = hf.sort_rtm_files(m), hf.sort_image_files(i)
        with pytest.raises(hf.SartInputError, match="threshold"):
            hf.check_rtm_image_consistency(sm, si, "with_reflections", 1.0)
        # within threshold passes
        hf.check_rtm_image_consistency(sm, si, "with_reflections", 50.0)

    def test_overlapping_voxel_maps_rejected(self, world, tmp_path):
        paths = world[0]
        # duplicate segment 1 => overlapping maps for camA
        import shutil
        dup = str(tmp_path / "dup.h5")
        shutil.copy(paths["rtm_a1"], dup)
        sm = hf.sort_rtm_files([paths["rtm_a1"], paths["rtm_a2"], dup])
        # same sort key collides; build by hand to force both files in
        sm[fx.CAM_A] = [paths["rtm_a1"], dup]
        with pytest.raises(hf.SartInputError, match="overlapping"):
            hf.check_rtm_voxel_consistency(sm)

    def test_duplicate_image_camera_rejected(self, world, tmp_path):
        paths = world[0]
        import shutil
        dup = str(tmp_path / "dup_img.h5")
        shutil.copy(paths["img_a"], dup)
        with pytest.raises(hf.SartInputError, match="share the same diagnostic view"):
            hf.sort_image_files([paths["img_a"], dup])

    def test_missing_image_camera(self, world):
        paths = world[0]
        m, i = hf.categorize_input_files(all_input_files(paths))
        sm = hf.sort_rtm_files(m)
        si = hf.sort_image_files([paths["img_a"]])
        with pytest.raises(hf.SartInputError, match="No image file for"):
            hf.check_rtm_image_consistency(sm, si, "with_reflections", 50.0)


class TestRTMBlockReader:
    def test_full_read_matches_ground_truth(self, world):
        paths, H, *_ = world
        m, _ = hf.categorize_input_files(all_input_files(paths))
        sm = hf.sort_rtm_files(m)
        block = read_rtm_block(sm, "with_reflections", fx.NPIXEL, fx.NVOXEL, 0)
        np.testing.assert_allclose(block, H, rtol=1e-6)

    def test_partial_blocks_tile_the_matrix(self, world):
        """Row-block reads across ranks reassemble to the full matrix —
        the reference's per-rank read pattern (raytransfer.cpp:49-118)."""
        paths, H, *_ = world
        m, _ = hf.categorize_input_files(all_input_files(paths))
        sm = hf.sort_rtm_files(m)
        from sartsolver_tpu.parallel.mesh import row_block_partition
        parts = row_block_partition(fx.NPIXEL, 4)
        rebuilt = np.concatenate([
            read_rtm_block(sm, "with_reflections", cnt, fx.NVOXEL, off)
            for off, cnt in parts
        ])
        np.testing.assert_allclose(rebuilt, H, rtol=1e-6)

    def test_column_range_blocks_tile_the_matrix(self, world):
        """(row, column)-block reads reassemble to the full matrix —
        column striping is what lets a voxel-major multi-host mesh read
        only its own columns (round 3). The column cuts deliberately
        straddle the dense/sparse segment boundary and skip segments."""
        paths, H, *_ = world
        m, _ = hf.categorize_input_files(all_input_files(paths))
        sm = hf.sort_rtm_files(m)
        row_cuts = [(0, 5), (5, fx.NPIXEL - 5)]
        col_cuts = [(0, 3), (3, 7), (10, fx.NVOXEL - 10)]
        for r0, nr in row_cuts:
            for c0, nc in col_cuts:
                block = read_rtm_block(
                    sm, "with_reflections", nr, fx.NVOXEL, r0,
                    offset_voxel=c0, nvoxel_local=nc,
                )
                np.testing.assert_allclose(
                    block, H[r0:r0 + nr, c0:c0 + nc], rtol=1e-6,
                    err_msg=f"rows {r0}+{nr}, cols {c0}+{nc}",
                )

    def test_one_pass_sparse_cache(self, world):
        """With a sparse_cache, chunked row reads load each sparse
        segment's triplet arrays ONCE (O(nnz + chunks) I/O, the
        reference's one-pass scatter, raytransfer.cpp:67-91) instead of
        per chunk — asserted via READ_STATS byte accounting — and produce
        identical blocks."""
        from sartsolver_tpu.io import raytransfer as rt

        paths, H, *_ = world
        m, _ = hf.categorize_input_files(all_input_files(paths))
        sm = hf.sort_rtm_files(m)
        chunks = [(i, 2) for i in range(0, fx.NPIXEL, 2)]

        def run(cache):
            rt.READ_STATS["data_bytes"] = 0
            blocks = [
                read_rtm_block(sm, "with_reflections", n, fx.NVOXEL, off,
                               sparse_cache=cache,
                               cache_rows=(0, fx.NPIXEL) if cache is not None
                               else None)
                for off, n in chunks
            ]
            return np.concatenate(blocks), rt.READ_STATS["data_bytes"]

        got_plain, bytes_plain = run(None)
        got_cached, bytes_cached = run({})
        np.testing.assert_allclose(got_cached, got_plain, rtol=0)
        np.testing.assert_allclose(got_cached, H, rtol=1e-6)
        # the sparse segment's nnz-sized arrays were pulled once, not once
        # per touching chunk (dense hyperslab bytes are identical in both
        # runs, so the delta is exactly the avoided triplet re-reads)
        assert bytes_cached < bytes_plain
        H_a = fx.make_rtm_matrices(0)[0]
        n_touch = sum(1 for off, _n in chunks if off < H_a.shape[0])
        nnz = np.count_nonzero(H_a[:, 8:])
        triplet_bytes = nnz * (8 + 8 + 4)
        assert bytes_plain - bytes_cached == (n_touch - 1) * triplet_bytes

    def test_sparse_cache_two_segments(self, tmp_path):
        """Two sparse segments through ONE cache (regression: the byte-
        budget scan must skip the cached window metadata; with >= 2
        sparse segments it used to crash on the second)."""
        rng = np.random.default_rng(5)
        npix, half = 8, 8
        H = rng.uniform(0.1, 1.0, (npix, 2 * half)).astype(np.float32)
        H *= rng.random(H.shape) < 0.6
        cells = np.arange(2 * half, dtype=np.int64)
        mask = np.ones((2, 4), np.int64)
        p1 = str(tmp_path / "s1.h5")
        p2 = str(tmp_path / "s2.h5")
        fx._write_rtm_file(p1, "camX", mask, H[:, :half], cells[:half],
                           cells[:half], sparse=True)
        fx._write_rtm_file(p2, "camX", mask, H[:, half:], cells[half:],
                           cells[:half], sparse=True)
        sm = hf.sort_rtm_files([p1, p2])
        cache = {}
        blocks = [
            read_rtm_block(sm, "with_reflections", 2, 2 * half, off,
                           sparse_cache=cache, cache_rows=(0, npix))
            for off in range(0, npix, 2)
        ]
        np.testing.assert_allclose(np.concatenate(blocks), H, rtol=1e-6)
        from sartsolver_tpu.io.raytransfer import _CACHE_BYTES_KEY

        # both segments cached independently (+ the running byte total)
        segs = {k: v for k, v in cache.items() if k != _CACHE_BYTES_KEY}
        assert len(segs) == 2
        assert cache[_CACHE_BYTES_KEY] == sum(
            arr.nbytes for entry in segs.values() for arr in entry[:3]
        )

    def test_sparse_cache_budget_fallback(self, world, monkeypatch):
        """A zero byte budget disables caching (entry None) but keeps
        results correct via per-chunk re-reads."""
        paths, H, *_ = world
        m, _ = hf.categorize_input_files(all_input_files(paths))
        sm = hf.sort_rtm_files(m)
        monkeypatch.setenv("SART_SPARSE_CACHE_MB", "0")
        cache = {}
        block = read_rtm_block(sm, "with_reflections", fx.NPIXEL, fx.NVOXEL,
                               0, sparse_cache=cache)
        np.testing.assert_allclose(block, H, rtol=1e-6)
        assert None in cache.values()


class TestLaplacian:
    def test_read_and_sorted(self, world):
        paths = world[0]
        rows, cols, vals = read_laplacian(paths["laplacian"], fx.NVOXEL)
        flat = rows * fx.NVOXEL + cols
        assert np.all(np.diff(flat) > 0)
        # diagonal entries present with value 0.2
        diag = vals[rows == cols]
        np.testing.assert_allclose(diag, 0.2, rtol=1e-6)

    def test_nvoxel_mismatch(self, world):
        paths = world[0]
        with pytest.raises(ValueError, match="different number of voxels"):
            read_laplacian(paths["laplacian"], fx.NVOXEL + 1)


class TestVoxelGrid:
    def test_round_trip(self, world, tmp_path):
        paths = world[0]
        grid = make_voxel_grid([paths["rtm_a1"], paths["rtm_a2"]], "rtm/voxel_map")
        assert grid.nvoxel == fx.NVOXEL
        assert grid.coordsys == CARTESIAN
        # every cell mapped (full 4x4x1 world)
        assert (grid.voxel_map >= 0).all()

        out = str(tmp_path / "out.h5")
        with h5py.File(out, "w"):
            pass
        grid.write_hdf5(out, "voxel_map")
        grid2 = CartesianVoxelGrid()
        grid2.read_hdf5([out], "voxel_map")
        np.testing.assert_array_equal(grid2.voxel_map, grid.voxel_map)

    def test_cartesian_lookup(self, world):
        paths = world[0]
        grid = make_voxel_grid([paths["rtm_b"]], "rtm/voxel_map")
        # cell (i=1, j=2, k=0) center: x in [1,2), y in [2,3)
        expected = grid.voxel_map[1 * fx.NY * fx.NZ + 2 * fx.NZ + 0]
        assert grid.voxel_index(1.5, 2.5, 0.5) == expected
        assert grid.voxel_index(-0.1, 0.5, 0.5) == -1
        assert grid.voxel_index(4.0, 0.5, 0.5) == -1

    def test_cylindrical_lookup(self, tmp_path):
        """r in [1,3), phi in [0,90) deg (4 sectors), z in [0,1)."""
        path = str(tmp_path / "cyl.h5")
        with h5py.File(path, "w") as f:
            rtm = f.create_group("rtm")
            vm = rtm.create_group("voxel_map")
            for name, val in (("nx", 2), ("ny", 4), ("nz", 1)):
                vm.attrs.create(name, val, dtype=np.uint64)
            for name, val in (("xmin", 1.0), ("xmax", 3.0), ("ymin", 0.0),
                              ("ymax", 90.0), ("zmin", 0.0), ("zmax", 1.0)):
                vm.attrs.create(name, val, dtype=np.float64)
            vm.attrs["coordinate_system"] = "cylindrical"
            cells = np.arange(8, dtype=np.int64)
            vm.create_dataset("i", data=(cells // 4).astype(np.uint64))
            vm.create_dataset("j", data=(cells % 4).astype(np.uint64))
            vm.create_dataset("k", data=np.zeros(8, np.uint64))
            vm.create_dataset("value", data=cells)

        assert get_coordinate_system_hdf5(path, "rtm/voxel_map") == CYLINDRICAL
        grid = CylindricalVoxelGrid()
        grid.read_hdf5([path], "rtm/voxel_map")
        # point at r=2.5, phi=100deg -> phi mod 90 = 10deg -> i=1, j=0
        x = 2.5 * np.cos(np.deg2rad(100))
        y = 2.5 * np.sin(np.deg2rad(100))
        assert grid.voxel_index(x, y, 0.5) == 4
        # out of radial range
        assert grid.voxel_index(0.1, 0.0, 0.5) == -1

    def test_cylindrical_rejects_cartesian(self, world):
        paths = world[0]
        grid = CylindricalVoxelGrid()
        with pytest.raises(ValueError, match="cannot read Cartesian"):
            grid.read_hdf5([paths["rtm_a1"]], "rtm/voxel_map")


class TestCompositeImage:
    def make_ci(self, world, time_intervals=((0.0, np.inf, 0.0, 0.0),),
                npixel=fx.NPIXEL, offset=0):
        paths = world[0]
        m, i = hf.categorize_input_files(all_input_files(paths))
        sm, si = hf.sort_rtm_files(m), hf.sort_image_files(i)
        masks = hf.read_rtm_frame_masks(sm)
        return CompositeImage(si, masks, list(time_intervals), npixel, offset)

    def test_aligns_all_frames(self, world):
        paths, H, f_true, times, scales = world
        ci = self.make_ci(world)
        assert len(ci) == len(times)
        # composite measurement equals H @ f_true * scale for each frame
        for t in range(len(times)):
            g = ci.frame(t)
            np.testing.assert_allclose(g, H @ (f_true * scales[t]), rtol=1e-5)

    def test_iterator_protocol(self, world):
        ci = self.make_ci(world)
        count = 0
        while (frame := ci.next_frame()) is not None:
            assert frame.shape == (fx.NPIXEL,)
            count += 1
        assert count == len(ci)

    def test_time_interval_selection(self, world):
        paths, H, f_true, times, scales = world
        ci = self.make_ci(world, time_intervals=[(0.15, 0.35, 0.0, 0.0)])
        assert len(ci) == 2  # frames at 0.2, 0.3

    def test_pixel_slicing(self, world):
        """Rank-local slices concatenate to the full composite frame
        (image.cpp:282-321)."""
        paths, H, f_true, times, scales = world
        full = self.make_ci(world).frame(0)
        from sartsolver_tpu.parallel.mesh import row_block_partition
        parts = row_block_partition(fx.NPIXEL, 3)
        pieces = [
            self.make_ci(world, npixel=cnt, offset=off).frame(0)
            for off, cnt in parts
        ]
        np.testing.assert_allclose(np.concatenate(pieces), full)

    def test_small_cache_still_streams(self, world):
        ci = self.make_ci(world)
        ci.max_cache_size = 1
        frames = []
        while (frame := ci.next_frame()) is not None:
            frames.append(frame)
        assert len(frames) == len(ci)

    def test_async_clock_within_threshold(self, world):
        """Camera times reflect each camera's actual clock."""
        ci = self.make_ci(world)
        ci.frame(0)
        cam_times = ci.camera_frame_time()
        assert abs(cam_times[0] - cam_times[1]) > 0  # jitter preserved

    def test_unsynchronized_camera_drops_frames(self, tmp_path):
        """A camera frame farther than the threshold kills the composite."""
        paths, H, f_true, times, scales = fx.write_world(
            tmp_path, jitter_b=0.049)
        m, i = hf.categorize_input_files(
            [paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
             paths["img_a"], paths["img_b"]])
        sm, si = hf.sort_rtm_files(m), hf.sort_image_files(i)
        masks = hf.read_rtm_frame_masks(sm)
        # threshold 0.01 < jitter 0.049 => no composite frames possible
        with pytest.raises(ValueError, match="No composite images"):
            CompositeImage(si, masks, [(0.0, 10.0, 0.1, 0.01)], fx.NPIXEL, 0)


class TestCompositeImagePixelRuns:
    def test_non_contiguous_runs_match_full_frame_slices(self, world):
        """pixel_runs=[...] emits the concatenation of the full frame's
        slices and caches only sum(counts) pixels — per-host cache memory
        proportional to its own rows (VERDICT r2 #8)."""
        from sartsolver_tpu.io.image import CompositeImage

        paths, H, *_ = world
        m, imgs = hf.categorize_input_files(all_input_files(paths))
        sm = hf.sort_rtm_files(m)
        si = hf.sort_image_files(imgs)
        masks = hf.read_rtm_frame_masks(sm)

        full = CompositeImage(si, masks, [(0.0, 1.0, 0.0, 0.0)], fx.NPIXEL)
        runs = [(2, 4), (9, 3)]  # straddles the camera A/B boundary (8)
        part = CompositeImage(
            si, masks, [(0.0, 1.0, 0.0, 0.0)], fx.NPIXEL, pixel_runs=runs,
        )
        assert len(part) == len(full)
        for i in range(len(full)):
            want = np.concatenate([
                full.frame(i)[off:off + cnt] for off, cnt in runs
            ])
            np.testing.assert_array_equal(part.frame(i), want)
        # cache holds only the runs' pixels
        assert part._cached_frames.shape[1] == sum(c for _, c in runs)

    def test_empty_runs_rejected(self, world):
        from sartsolver_tpu.io.image import CompositeImage

        paths, *_ = world
        m, imgs = hf.categorize_input_files(all_input_files(paths))
        si = hf.sort_image_files(imgs)
        masks = hf.read_rtm_frame_masks(hf.sort_rtm_files(m))
        with pytest.raises(ValueError):
            CompositeImage(si, masks, [(0.0, 1.0, 0.0, 0.0)], fx.NPIXEL,
                           pixel_runs=[])


class TestSolutionWriter:
    def test_create_extend_round_trip(self, tmp_path):
        out = str(tmp_path / "sol.h5")
        rng = np.random.default_rng(0)
        sols = rng.uniform(size=(5, fx.NVOXEL))
        with SolutionWriter(out, [fx.CAM_A, fx.CAM_B], fx.NVOXEL,
                            max_cache_size=2) as w:
            for t in range(5):
                w.add(sols[t], status=(0 if t % 2 == 0 else -1),
                      time=0.1 * t, camera_time=[0.1 * t, 0.1 * t + 0.003],
                      iterations=10 + t)

        with h5py.File(out, "r") as f:
            np.testing.assert_allclose(f["solution/value"][:], sols)
            np.testing.assert_allclose(f["solution/time"][:], 0.1 * np.arange(5))
            np.testing.assert_array_equal(
                f["solution/status"][:], [0, -1, 0, -1, 0])
            np.testing.assert_array_equal(
                f["solution/iterations"][:], 10 + np.arange(5))
            np.testing.assert_allclose(
                f[f"solution/time_{fx.CAM_B}"][:], 0.1 * np.arange(5) + 0.003)
            assert f["solution/value"].maxshape == (None, fx.NVOXEL)

    @pytest.mark.parametrize("kwargs", [
        {"nvoxel": 0}, {"nvoxel": -1},
        {"max_cache_size": 0}, {"max_cache_size": -3},
    ])
    def test_rejects_non_positive_sizes(self, tmp_path, kwargs):
        """Regression: the constructor used equality checks (== 0), so a
        NEGATIVE nvoxel/max_cache_size slipped through — into dataset
        shapes and a flush cadence that never fires."""
        full = {"nvoxel": fx.NVOXEL, "max_cache_size": 10, **kwargs}
        with pytest.raises(ValueError, match="must be positive"):
            SolutionWriter(str(tmp_path / "bad.h5"), [fx.CAM_A],
                           full["nvoxel"],
                           max_cache_size=full["max_cache_size"])

    def test_resume_into_pre_iterations_file(self, tmp_path):
        """Resuming into a file written before the `iterations` extension
        (dataset absent) must keep appending without it."""
        out = str(tmp_path / "old.h5")
        with SolutionWriter(out, [fx.CAM_A], fx.NVOXEL, max_cache_size=10) as w:
            w.add(np.zeros(fx.NVOXEL), 0, 0.0, [0.0])
        with h5py.File(out, "r+") as f:
            del f["solution/iterations"]  # simulate a pre-extension file
        from sartsolver_tpu.io.solution import read_resume_state

        state = read_resume_state(out, [fx.CAM_A], fx.NVOXEL)
        assert state is not None and len(state.times) == 1
        with SolutionWriter(out, [fx.CAM_A], fx.NVOXEL, max_cache_size=10,
                            resume=state) as w:
            w.add(np.ones(fx.NVOXEL), 0, 0.1, [0.1], iterations=5)
        with h5py.File(out, "r") as f:
            assert f["solution/value"].shape[0] == 2
            assert "iterations" not in f["solution"]


class TestAlignmentTieBreaks:
    """Table-driven pins for the subtle branches of the composite-frame
    alignment (reference image.cpp:148-196): dedup of a frame picked by two
    adjacent ticks, and moving a deduped frame's time to the closer tick."""

    def _single_cam_world(self, tmp_path, frame_times):
        d = str(tmp_path)
        rtm = os.path.join(d, "rtm.h5")
        img = os.path.join(d, "img.h5")
        H_b = fx.make_rtm_matrices()[1]
        cells = np.arange(fx.NVOXEL, dtype=np.int64)
        fx._write_rtm_file(rtm, fx.CAM_B, fx.MASK_B, H_b, cells, cells)
        frames = np.stack([
            fx.frame_from_measurement(fx.MASK_B, np.full(fx.NPIX_B, 1.0 + t))
            for t in frame_times
        ])
        fx._write_image_file(img, fx.CAM_B, frames, frame_times)
        m, i = hf.categorize_input_files([rtm, img])
        sm, si = hf.sort_rtm_files(m), hf.sort_image_files(i)
        masks = hf.read_rtm_frame_masks(sm)
        return si, masks

    def test_dedup_keeps_single_entry(self, tmp_path):
        """A frame within threshold of two adjacent ticks is emitted once,
        at the tick it is closest to."""
        si, masks = self._single_cam_world(tmp_path, [0.1, 0.3])
        ci = CompositeImage(si, masks, [(0.0, 1.0, 0.1, 0.1)], fx.NPIX_B, 0)
        np.testing.assert_allclose(ci.time, [0.1, 0.3], atol=1e-12)

    def test_dedup_moves_time_to_closer_tick(self, tmp_path):
        """image.cpp:158: same frame set, smaller total delta => the
        composite time moves to the closer tick.

        The grid anchors at the earliest frame time, so frame 0 pins the
        ticks at 0.0, 0.1, ... Frame 1 at 0.26 bids on tick 0.2 (|0.06|)
        and tick 0.3 (|0.04|): the deduped composite moves to 0.3.
        """
        si, masks = self._single_cam_world(tmp_path, [0.0, 0.26])
        ci = CompositeImage(si, masks, [(0.0, 1.0, 0.1, 0.1)], fx.NPIX_B, 0)
        np.testing.assert_allclose(ci.time, [0.0, 0.3], atol=1e-12)
        np.testing.assert_allclose(ci.camera_time, [[0.0], [0.26]], atol=1e-12)

    def test_exact_tie_prefers_earlier_tick(self, tmp_path):
        si, masks = self._single_cam_world(tmp_path, [0.0, 0.25])
        ci = CompositeImage(si, masks, [(0.0, 1.0, 0.1, 0.1)], fx.NPIX_B, 0)
        # frame 1 equidistant from ticks 0.2 and 0.3: TIME_EPSILON keeps
        # the earlier tick
        np.testing.assert_allclose(ci.time, [0.0, 0.2], atol=1e-12)


def _wait_for_latch(w, timeout=10.0):
    """Wait until the async writer's worker latched its first error."""
    import time as _t

    deadline = _t.monotonic() + timeout
    while w._error is None and _t.monotonic() < deadline:
        _t.sleep(0.01)
    assert w._error is not None


class TestAsyncSolutionWriter:
    def test_matches_synchronous_writer(self, tmp_path):
        from sartsolver_tpu.utils.asyncwriter import AsyncSolutionWriter

        rng = np.random.default_rng(3)
        sols = rng.uniform(size=(7, fx.NVOXEL))
        sync_out = str(tmp_path / "sync.h5")
        async_out = str(tmp_path / "async.h5")

        with SolutionWriter(sync_out, [fx.CAM_A], fx.NVOXEL, max_cache_size=3) as w:
            for t in range(7):
                w.add(sols[t], -(t % 2), 0.1 * t, [0.1 * t])
        with AsyncSolutionWriter(
            SolutionWriter(async_out, [fx.CAM_A], fx.NVOXEL, max_cache_size=3)
        ) as w:
            for t in range(7):
                w.add(sols[t], -(t % 2), 0.1 * t, [0.1 * t])

        with h5py.File(sync_out) as a, h5py.File(async_out) as b:
            for key in ("value", "time", "status", f"time_{fx.CAM_A}"):
                np.testing.assert_array_equal(
                    a[f"solution/{key}"][:], b[f"solution/{key}"][:]
                )

    def test_lazy_callable_solution_resolved_on_worker(self, tmp_path):
        """A callable solution (DeviceSolveResult.solution_fetcher) must be
        resolved on the worker thread and written like a plain array."""
        import threading

        from sartsolver_tpu.utils.asyncwriter import AsyncSolutionWriter

        out = str(tmp_path / "lazy.h5")
        caller = threading.get_ident()
        resolved_on = []
        value = np.linspace(0.0, 1.0, fx.NVOXEL)

        def fetch():
            resolved_on.append(threading.get_ident())
            return value

        with AsyncSolutionWriter(
            SolutionWriter(out, [fx.CAM_A], fx.NVOXEL, max_cache_size=2)
        ) as w:
            w.add(fetch, 0, 0.5, [0.5])
        with h5py.File(out) as f:
            np.testing.assert_allclose(f["solution/value"][0], value)
        assert resolved_on and resolved_on[0] != caller

    def test_write_error_surfaces(self):
        """A latched write error surfaces on a later add()/close() as a
        CHAINED wrapper: the original exception (with its worker-side
        traceback) is __cause__, and every surfacing site raises a fresh
        object instead of re-raising — and thereby mutating — the latched
        one."""
        from sartsolver_tpu.utils.asyncwriter import (
            AsyncSolutionWriter, DeferredWriteError,
        )

        class Exploding:
            def add(self, *a):
                raise OSError("disk full")

            def close(self):
                pass

        w = AsyncSolutionWriter(Exploding())
        w.add(np.zeros(4), 0, 0.0, [0.0])
        with pytest.raises(DeferredWriteError, match="disk full") as exc:
            for _ in range(50):  # error latches on a subsequent add or close
                w.add(np.zeros(4), 0, 0.0, [0.0])
            w.close()
        assert isinstance(exc.value.__cause__, OSError)

    def test_latched_error_traceback_not_stacked_across_raises(self):
        """Regression: _check() used to re-raise the SAME latched object
        from every call site, growing its traceback by a surfacing-site
        segment per raise; the wrapper keeps the original traceback
        pristine and each surfaced error is a distinct object."""
        import traceback as tb_mod

        from sartsolver_tpu.utils.asyncwriter import (
            AsyncSolutionWriter, DeferredWriteError,
        )

        class Exploding:
            def add(self, *a):
                raise OSError("disk full")

            def close(self):
                pass

        w = AsyncSolutionWriter(Exploding())
        w.add(np.zeros(4), 0, 0.0, [0.0])
        _wait_for_latch(w)

        def surface():
            with pytest.raises(DeferredWriteError) as exc:
                w.add(np.zeros(4), 0, 0.0, [0.0])
            return exc.value

        first, second = surface(), surface()
        assert first is not second
        assert first.__cause__ is second.__cause__  # one original error
        # the original traceback must not have accumulated surfacing-site
        # frames between the two raises
        depth = len(tb_mod.extract_tb(first.__cause__.__traceback__))
        assert len(
            tb_mod.extract_tb(second.__cause__.__traceback__)) == depth

    def test_output_write_error_cause_keeps_type(self):
        """An OutputWriteError latched by the worker must surface AS an
        OutputWriteError (the CLI's exit-code mapping keys on the type),
        still chained to the original."""
        from sartsolver_tpu.resilience.failures import OutputWriteError
        from sartsolver_tpu.utils.asyncwriter import AsyncSolutionWriter

        class FlushFails:
            def add(self, *a):
                raise OutputWriteError("flush of x failed; resumable")

            def close(self):
                pass

        w = AsyncSolutionWriter(FlushFails())
        w.add(np.zeros(4), 0, 0.0, [0.0])
        _wait_for_latch(w)
        with pytest.raises(OutputWriteError, match="resumable") as exc:
            w.add(np.zeros(4), 0, 0.0, [0.0])
        assert isinstance(exc.value.__cause__, OutputWriteError)
        assert exc.value is not exc.value.__cause__

    def test_buffer_copied_before_queueing(self, tmp_path):
        from sartsolver_tpu.utils.asyncwriter import AsyncSolutionWriter

        out = str(tmp_path / "copy.h5")
        buf = np.ones(fx.NVOXEL)
        with AsyncSolutionWriter(
            SolutionWriter(out, [fx.CAM_A], fx.NVOXEL, max_cache_size=10)
        ) as w:
            w.add(buf, 0, 0.0, [0.0])
            buf[:] = -99.0  # mutate after submission
        with h5py.File(out) as f:
            np.testing.assert_array_equal(f["solution/value"][0], np.ones(fx.NVOXEL))


class TestAsyncWriterErrorExit:
    """Round-4 exception-exit semantics: a consumer failure finishes
    writing every already-queued frame (complete, ordered, contiguous —
    dropping them only costs --resume recompute), while KeyboardInterrupt
    drops the queue so no further blocking work runs on a possibly wedged
    backend."""

    def _writer_with_gate(self):
        import threading

        gate = threading.Event()
        entered = threading.Event()

        class GatedWriter:
            def __init__(self):
                self.added = []
                self.closed = False

            def add(self, *a):
                entered.set()  # worker is now parked inside frame 0
                gate.wait(10)
                self.added.append(a)

            def close(self):
                self.closed = True

        return GatedWriter(), gate, entered

    def _run(self, exc_type):
        import threading

        from sartsolver_tpu.utils.asyncwriter import AsyncSolutionWriter

        inner, gate, entered = self._writer_with_gate()
        w = AsyncSolutionWriter(inner)
        for t in range(3):
            w.add(np.zeros(4), 0, float(t), [float(t)])
        # handshake: wait until the worker is parked INSIDE frame 0's add
        # (frames 1-2 are definitely still queued), then let __exit__ make
        # its keep-or-drop decision — its drain runs in microseconds, so
        # a 2 s timer opening the gate cannot race it
        assert entered.wait(10)
        threading.Timer(2.0, gate.set).start()
        w.__exit__(exc_type, exc_type(), None)
        return inner

    def test_generic_error_writes_queued_frames(self):
        inner = self._run(OSError)
        assert len(inner.added) == 3  # every queued frame written
        assert [a[2] for a in inner.added] == [0.0, 1.0, 2.0]  # in order
        assert inner.closed

    def test_keyboard_interrupt_drops_queued_frames(self):
        inner = self._run(KeyboardInterrupt)
        # only the in-flight frame finishes; queued ones are dropped
        assert len(inner.added) <= 1
        assert inner.closed
