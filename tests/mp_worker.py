"""Worker for the real two-process distributed test (test_multiprocess.py).

Runs the full `sartsolve` CLI under an actual JAX multi-controller runtime
(2 processes x 1 virtual CPU device), which exercises the cross-process
code paths the single-process suite can only approximate: striped
serialized RTM ingest with the global barrier, per-process measurement
slicing, process-0-only output, and the resume-state broadcast.

Usage: python mp_worker.py <rank> <nproc> <port> <outfile> <extra...> -- <inputs...>
"""

import os
import sys


def main() -> int:
    rank = int(sys.argv[1])
    nproc = int(sys.argv[2])
    port = sys.argv[3]
    outfile = sys.argv[4]
    sep = sys.argv.index("--")
    extra = sys.argv[5:sep]
    inputs = sys.argv[sep + 1:]

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"

    from sartsolver_tpu.parallel import multihost as mh

    mh.initialize(f"127.0.0.1:{port}", nproc, rank)

    from sartsolver_tpu.cli import main as cli_main

    # "--no_default_profile" marker: drop --use_cpu so extras can select
    # device-profile-only features (e.g. --rtm_dtype int8)
    extra = list(extra)
    profile = ["--use_cpu", "-c", "1e-8"]
    if "--no_default_profile" in extra:
        extra.remove("--no_default_profile")
        profile = []
    rc = cli_main([
        "-o", outfile, *inputs, "-m", "100", *profile,
        "--multihost", *extra,
    ])
    # ingest byte accounting for the column-striping test (per-host I/O
    # must be proportional to its share of the matrix)
    from sartsolver_tpu.io.raytransfer import READ_STATS

    print(f"INGEST_DATA_BYTES={READ_STATS['data_bytes']}", flush=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())
