"""Worker for the real two-process distributed test (test_multiprocess.py).

Runs the full `sartsolve` CLI under an actual JAX multi-controller runtime
(2 processes x 1 virtual CPU device), which exercises the cross-process
code paths the single-process suite can only approximate: striped
serialized RTM ingest with the global barrier, per-process measurement
slicing, process-0-only output, and the resume-state broadcast.

Usage: python mp_worker.py <rank> <nproc> <port> <outfile> <extra...> -- <inputs...>
"""

import os
import sys


def main() -> int:
    rank = int(sys.argv[1])
    nproc = int(sys.argv[2])
    port = sys.argv[3]
    outfile = sys.argv[4]
    sep = sys.argv.index("--")
    extra = sys.argv[5:sep]
    inputs = sys.argv[sep + 1:]

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"

    from sartsolver_tpu.parallel import multihost as mh

    mh.initialize(f"127.0.0.1:{port}", nproc, rank)

    from sartsolver_tpu.cli import main as cli_main

    return cli_main([
        "-o", outfile, *inputs, "--use_cpu", "-m", "100", "-c", "1e-8",
        "--multihost", *extra,
    ])


if __name__ == "__main__":
    sys.exit(main())
