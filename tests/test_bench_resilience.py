"""bench.py must always deliver a real headline (VERDICT r3 next #2).

Round 3's driver bench stalled after 12/14 configs and the watchdog
recorded headline value 0.0 even though its own partial data held a valid
538 iter/s number. These tests pin the two defenses added in round 4:

- the watchdog payload reports the best COMPLETED config (a real value,
  marked degraded), not 0.0, whenever any config finished;
- a hang inside one sweep/converge item kills only the worker subprocess:
  the item is recorded as failed, the worker restarts on the remainder,
  and the final JSON carries a real nonzero headline.
"""

import json
import os
import subprocess
import sys

import pytest

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
_BENCH = os.path.join(_REPO, "bench.py")


def _load_bench():
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_under_test", _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_watchdog_payload_uses_best_completed_config():
    bench = _load_bench()
    bench._partial.clear()
    bench._partial.update({
        "bar_iter_s": 176.6,
        "unit_ctx": "8192x65536 ",
        "sweep_partial": [
            {"fused": "compiled", "rtm_dtype": "bfloat16", "B": 1,
             "loop_iter_s": 538.0, "frame_iter_s": 538.0, "hbm_frac": 0.7},
            {"fused": "compiled", "rtm_dtype": "int8", "B": 1,
             "loop_iter_s": 924.3, "frame_iter_s": 924.3, "hbm_frac": 0.6},
            {"fused": "off", "rtm_dtype": "float32", "B": 8,
             "error": "stalled"},
        ],
    })
    payload = bench._watchdog_payload(600.0)
    # real value (the best non-int8 B=1 config), not 0.0
    assert payload["value"] == 538.0
    assert payload["vs_baseline"] == pytest.approx(538.0 / 176.6, abs=1e-3)
    assert "degraded" in payload["detail"]
    assert "partial sweep" in payload["unit"]


def test_watchdog_payload_zero_only_when_nothing_completed():
    bench = _load_bench()
    bench._partial.clear()
    bench._partial.update({
        "bar_iter_s": 176.6,
        "sweep_partial": [{"fused": "auto", "rtm_dtype": "float32", "B": 1,
                           "error": "boom"}],
    })
    payload = bench._watchdog_payload(600.0)
    assert payload["value"] == 0.0
    assert "UNAVAILABLE" in payload["unit"]


def test_injected_stall_still_produces_nonzero_headline(tmp_path):
    """End-to-end: one converge item hangs forever; the per-item timeout
    kills the worker, the item is recorded, the worker restarts for the
    remaining item, and the final JSON line carries the real sweep
    headline."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # no tunnel: pure-CPU bench
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update({
        "SART_BENCH_NPIXEL": "64",
        "SART_BENCH_NVOXEL": "256",
        "SART_BENCH_ITERS": "5",
        "SART_BENCH_TEST_STALL": "converge:linear",  # worker hangs here
        "SART_BENCH_CONVERGE_TIMEOUT": "5",
        "SART_BENCH_PROBE_RETRIES": "1",
    })
    out = subprocess.run(
        [sys.executable, _BENCH], env=env, capture_output=True, text=True,
        timeout=420, cwd=str(tmp_path),
    )
    assert out.returncode == 0, out.stderr[-3000:]
    line = [ln for ln in out.stdout.splitlines() if ln.startswith("{")][-1]
    payload = json.loads(line)
    assert payload["value"] > 0, payload
    assert payload["detail"]["hung_configs"] == ["converge:linear"], payload
    # the stalled item is recorded as an error, the OTHER converge item
    # completed on the restarted worker
    conv = payload["detail"]["time_to_converge"]
    assert "error" in conv["linear"], conv
    assert conv["log"].get("status") is not None, conv
    # fused-path provenance recorded in the artifact (VERDICT r3 next #4)
    assert payload["detail"]["headline_fused"] == "off"  # CPU: no fusion
    assert all("fused" in r for r in payload["detail"]["sweep"])


def test_select_headline_prefers_honest_b1():
    """The headline must be the best B=1 non-int8 config (apples-to-apples
    with the reference's one-frame loop; int8 solves a perturbed system),
    falling back to int8 only when nothing else completed."""
    bench = _load_bench()
    ok = [
        {"rtm_dtype": "int8", "B": 1, "loop_iter_s": 900.0,
         "fused": "compiled"},
        {"rtm_dtype": "bfloat16", "B": 32, "loop_iter_s": 600.0,
         "fused": "compiled"},
        {"rtm_dtype": "bfloat16", "B": 1, "loop_iter_s": 538.0,
         "fused": "compiled"},
        {"rtm_dtype": "float32", "B": 1, "loop_iter_s": 300.0,
         "fused": "compiled"},
    ]
    head = bench._select_headline(ok)
    assert (head["rtm_dtype"], head["B"], head["loop_iter_s"]) == (
        "bfloat16", 1, 538.0)
    # int8-only partial sweep still produces a (labeled) headline
    assert bench._select_headline([ok[0]])["rtm_dtype"] == "int8"
    # no B=1 completed: best frame-honest config wins
    assert bench._select_headline([ok[1]])["B"] == 32
