"""Static-analysis subsystem tests (sartsolver_tpu/analysis).

For every AST rule: one fixture snippet seeding a true positive and one
near-miss that must stay clean — the rule's precision contract. Plus the
compile-audit machinery (registry completeness, invariant detection on a
violating module, golden verify/mismatch round trip) and the package
self-lint, which makes `sartsolve lint --self` part of the tier-1 verify
path: a new hazard in the package fails the suite, not just the CLI.
"""

import json
import os
import textwrap

import numpy as np
import pytest

from sartsolver_tpu.analysis.rules import ALL_RULES, lint_source

# ---------------------------------------------------------------------------
# rule fixtures: (rule_id, true-positive snippet, near-miss snippet)
# ---------------------------------------------------------------------------

_HEADER = "import jax\nimport jax.numpy as jnp\nimport numpy as np\n"

RULE_FIXTURES = {
    "SL001": (
        # TP: jitted function branches on a traced (unannotated) parameter
        """
        @jax.jit
        def update(x, threshold):
            if threshold > 0:
                return x * 2
            return x
        """,
        # near miss: the branched-on parameter is static
        """
        import functools

        @functools.partial(jax.jit, static_argnames=("flag",))
        def update(x, flag):
            if flag:
                return x * 2
            return x
        """,
    ),
    "SL002": (
        # TP: per-step .item() on a jnp-produced value inside a loop
        """
        def drain(n):
            total = jnp.zeros(())
            out = []
            for k in range(n):
                total = total + jnp.sin(k)
                out.append(total.item())
            return out
        """,
        # near miss: the sync happens once, after the loop
        """
        def drain(n):
            total = jnp.zeros(())
            for k in range(n):
                total = total + jnp.sin(k)
            return total.item()
        """,
    ),
    "SL003": (
        # TP: dtype-defaulting constructor without an explicit dtype
        """
        def buffers(n):
            return jnp.zeros((n, 4))
        """,
        # near miss: dtype passed (positionally)
        """
        def buffers(n):
            return jnp.zeros((n, 4), jnp.float32)
        """,
    ),
    "SL004": (
        # TP: state-update jit with no donation
        """
        rescale_state = jax.jit(lambda f, s: f * s)
        """,
        # near miss: donation declared
        """
        rescale_state = jax.jit(lambda f, s: f * s, donate_argnums=0)
        """,
    ),
    "SL005": (
        # TP: traced parameter used as a shape -> concretization error /
        # forced-static recompile hazard
        """
        @jax.jit
        def pad_to(x, n):
            return x + jnp.zeros(n)
        """,
        # near miss: the shape-feeding parameter is static
        """
        import functools

        @functools.partial(jax.jit, static_argnums=(1,))
        def pad_to(x, n):
            return x + jnp.zeros(n)
        """,
    ),
    "SL006": (
        # TP: bare except around anything
        """
        def solve(x):
            try:
                return jnp.linalg.norm(x)
            except:
                return None
        """,
        # near miss: a typed handler
        """
        def solve(x):
            try:
                return jnp.linalg.norm(x)
            except ValueError:
                return None
        """,
    ),
    "SL007": (
        # TP: a dense matmul against the RTM outside the operator layer
        # bypasses the block-sparse tile-skip and fused-sweep dispatch
        """
        def fit(problem, f):
            return jnp.matmul(problem.rtm, f)

        def bp(rtm, w):
            return w @ rtm
        """,
        # near miss: the same products routed through the operator layer,
        # a matmul on non-RTM operands, and a contraction against an
        # rtm-prefixed METADATA vector (the int8 scale is not the matrix)
        """
        from sartsolver_tpu.ops.projection import back_project, forward_project

        def fit(problem, f):
            return forward_project(problem.rtm, f)

        def bp(rtm, w):
            return back_project(rtm, w)

        def unrelated(a, b):
            return a @ b

        def rescale(w, rtm_scale):
            return jnp.dot(w, rtm_scale)

        def residual(rtm, w, basis):
            return back_project(rtm, w) @ basis
        """,
    ),
    # ---- concurrency family (docs/STATIC_ANALYSIS.md SL1xx) -------------
    "SL101": (
        # TP: attribute declared guarded accessed without the lock
        """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # guarded by: self._lock

            def bad(self):
                self._items.append(1)
        """,
        # near miss: held via `with`, an acquire-if guard, or a *_locked
        # helper
        """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # guarded by: self._lock

            def good(self):
                with self._lock:
                    self._items.append(1)

            def snapshot(self):
                if self._lock.acquire(blocking=False):
                    try:
                        return list(self._items)
                    finally:
                        self._lock.release()
                return None

            def _drain_locked(self):
                return list(self._items)
        """,
    ),
    "SL102": (
        # TP: blocking sleep inside a lock body
        """
        import threading
        import time

        _lock = threading.Lock()

        def f():
            with _lock:
                time.sleep(1)
        """,
        # near miss: the blocking work moved outside the lock body
        """
        import threading
        import time

        _lock = threading.Lock()

        def f():
            with _lock:
                x = 1
            time.sleep(1)
            return x
        """,
    ),
    "SL103": (
        # TP: a signal handler reaching a blocking `with _lock:` through
        # a same-module call
        """
        import signal
        import threading

        _lock = threading.Lock()

        def _snap():
            with _lock:
                return 1

        def _handler(signum, frame):
            _snap()

        signal.signal(signal.SIGUSR1, _handler)
        """,
        # near miss: the handler path uses a non-blocking acquire with a
        # stale fallback (the obs/flight.py pattern)
        """
        import signal
        import threading

        _lock = threading.Lock()

        def _snap():
            if _lock.acquire(blocking=False):
                try:
                    return 1
                finally:
                    _lock.release()
            return None

        def _handler(signum, frame):
            _snap()

        signal.signal(signal.SIGUSR1, _handler)
        """,
    ),
    "SL104": (
        # TP: module global rebound outside the module's lock
        """
        import threading

        _lock = threading.Lock()
        _cache = {}

        def reset():
            global _cache
            _cache = {}
        """,
        # near miss: rebound under the lock
        """
        import threading

        _lock = threading.Lock()
        _cache = {}

        def reset():
            global _cache
            with _lock:
                _cache = {}
        """,
    ),
    "SL105": (
        # TP: Thread without an explicit daemon= choice
        """
        import threading

        def start():
            t = threading.Thread(target=print)
            t.start()
            return t
        """,
        # near miss: explicit daemon
        """
        import threading

        def start():
            t = threading.Thread(target=print, daemon=True)
            t.start()
            return t
        """,
    ),
    # SL2xx durability discipline: the family's full TP + near-miss
    # matrix (derived locals, commit-order anchoring, checkpoint-boundary
    # call graphs) lives in tests/test_durability_lint.py; these pairs
    # keep the one-catalogue convention here
    "SL201": (
        # TP: raw append to a # durable:-declared path
        """
        class J:
            def __init__(self, path):
                self.path = path  # durable: journal
            def append(self, line):
                with open(self.path, 'a') as f:
                    f.write(line)
        """,
        # near miss: reading the durable path is fine
        """
        class J:
            def __init__(self, path):
                self.path = path  # durable: journal
            def replay(self):
                with open(self.path) as f:
                    return f.read()
        """,
    ),
    "SL202": (
        # TP: rename publish whose tmp handle was never fsynced
        """
        import os

        def publish(path, data):
            tmp = path + '.tmp'
            with open(tmp, 'w') as f:
                f.write(data)
            os.replace(tmp, path)
        """,
        # near miss: fsync before the rename
        """
        import os

        def publish(path, data):
            tmp = path + '.tmp'
            with open(tmp, 'w') as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        """,
    ),
    "SL203": (
        # TP: response published before the completed marker commits
        """
        import os

        class S:
            def __init__(self, d, journal):
                self.responses_dir = d  # durable: response
                self.journal = journal
            def _respond(self, rid, body):
                p = os.path.join(self.responses_dir, rid + '.json')
                write_json_atomic(p, body)
            def _finish(self, req, outcome):
                self._respond(req.id, {'state': 'done'})
                self.journal.completed(req, outcome)
        """,
        # near miss: completed marker first, response second
        """
        import os

        class S:
            def __init__(self, d, journal):
                self.responses_dir = d  # durable: response
                self.journal = journal
            def _respond(self, rid, body):
                p = os.path.join(self.responses_dir, rid + '.json')
                write_json_atomic(p, body)
            def _finish(self, req, outcome):
                self.journal.completed(req, outcome)
                self._respond(req.id, {'state': 'done'})
        """,
    ),
    "SL204": (
        # TP: wall clock reachable from replay
        """
        import time

        class S:
            def _replay(self):
                self._note()
            def _note(self):
                return time.time()
        """,
        # near miss: sorted listing, clock only outside replay paths
        """
        import os, time

        class S:
            def restore_state(self):
                for name in sorted(os.listdir(self.d)):
                    pass
            def heartbeat(self):
                return time.time()
        """,
    ),
    "SL205": (
        # TP: checkpointed state mutated on a path that never
        # reaches the declared boundary
        """
        class S:
            def __init__(self):
                # checkpointed by: _save_state
                self.counters = {}
            def _save_state(self):
                pass
            def handle(self):
                self.counters['x'] = 1
        """,
        # near miss: the mutation reaches the boundary
        """
        class S:
            def __init__(self):
                # checkpointed by: _save_state
                self.counters = {}
            def _save_state(self):
                pass
            def handle(self):
                self.counters['x'] = 1
                self._save_state()
        """,
    ),
}


def _lint_snippet(snippet: str):
    return lint_source("fixture.py", _HEADER + textwrap.dedent(snippet))


@pytest.mark.parametrize("rule_id", sorted(RULE_FIXTURES))
def test_rule_true_positive(rule_id):
    positive, _ = RULE_FIXTURES[rule_id]
    hits = [f for f in _lint_snippet(positive) if f.rule == rule_id]
    assert hits, f"{rule_id} missed its seeded violation"


@pytest.mark.parametrize("rule_id", sorted(RULE_FIXTURES))
def test_rule_near_miss_stays_clean(rule_id):
    _, negative = RULE_FIXTURES[rule_id]
    hits = [f for f in _lint_snippet(negative) if f.rule == rule_id]
    assert not hits, (
        f"{rule_id} false positive on its near-miss fixture: "
        + "; ".join(f.message for f in hits)
    )


def test_rule_catalogue_complete():
    ids = {r.id for r in ALL_RULES}
    assert ids == set(RULE_FIXTURES), (
        "every rule needs a TP/near-miss fixture pair (and vice versa)"
    )
    for r in ALL_RULES:
        assert r.severity in ("error", "warning", "info")
        assert r.title and r.hint


def test_broad_except_around_device_code_warns():
    """SL006's second mode: `except Exception` is only flagged when the
    try body actually runs device code, and at warning severity."""
    flagged = _lint_snippet(
        """
        def probe(x):
            try:
                return jnp.dot(x, x)
            except Exception:
                return None
        """
    )
    hits = [f for f in flagged if f.rule == "SL006"]
    assert hits and hits[0].severity == "warning"
    clean = _lint_snippet(
        """
        def probe(path):
            try:
                return open(path).read()
            except Exception:
                return None
        """
    )
    assert not [f for f in clean if f.rule == "SL006"]


def test_sl007_blesses_the_operator_layer_paths():
    """SL007's path allowance: the same raw RTM contraction that is an
    error in solver code is the operator layer's JOB inside
    ops/fused_sweep.py, ops/projection.py, and anywhere under the
    pluggable sartsolver_tpu/operators/ package (a backend's
    forward/back IS the contraction everything else routes through)."""
    src = _HEADER + textwrap.dedent(
        """
        def forward(rtm, f):
            return rtm @ f
        """
    )
    tripped = lint_source("sartsolver_tpu/models/sart.py", src)
    assert [f for f in tripped if f.rule == "SL007"]
    for blessed in (
        "sartsolver_tpu/ops/projection.py",
        "sartsolver_tpu/ops/fused_sweep.py",
        "sartsolver_tpu/operators/dense.py",
        "sartsolver_tpu/operators/implicit.py",
        "/abs/checkout/sartsolver_tpu/operators/tileskip.py",
    ):
        clean = lint_source(blessed, src)
        assert not [f for f in clean if f.rule == "SL007"], blessed
    # near miss: a sibling package NAMED like the operators dir does not
    # inherit the blessing (containment is on the package path, not the
    # word "operators")
    near = lint_source("sartsolver_tpu/sched/operators_report.py", src)
    assert [f for f in near if f.rule == "SL007"]


def test_sl101_acquire_guard_covers_body_not_else():
    """The `if lock.acquire(...):` guard holds the lock only in the `if`
    BODY; the else branch is the failed-acquire path — a guarded access
    there is exactly the data race the rule exists for."""
    findings = _lint_snippet(
        """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # guarded by: self._lock

            def snap(self):
                if self._lock.acquire(blocking=False):
                    try:
                        return list(self._items)
                    finally:
                        self._lock.release()
                else:
                    return list(self._items)
        """
    )
    hits = [f for f in findings if f.rule == "SL101"]
    assert len(hits) == 1, findings
    assert "without" in hits[0].message


def test_sl101_declarations_stay_inside_their_class():
    """Guarded-by declarations must not bleed across nested-class
    boundaries: an inner class's declaration says nothing about the
    outer class's same-named attribute (different `self`), and vice
    versa."""
    findings = _lint_snippet(
        """
        import threading

        class Outer:
            def __init__(self):
                self._buf = []  # plain, unguarded

            class Inner:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._buf = []  # guarded by: self._lock

                def bad(self):
                    return list(self._buf)

            def touch(self):
                return list(self._buf)  # Outer's _buf: not declared
        """
    )
    hits = [f for f in findings if f.rule == "SL101"]
    # exactly Inner.bad — never Outer.touch
    assert len(hits) == 1, findings
    assert "Inner.bad" in hits[0].message


def test_sl101_nested_function_access_reported_once():
    """A guarded access inside a closure within a method is one finding
    (attributed to the closure's own pass), not one per enclosing
    scope."""
    findings = _lint_snippet(
        """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # guarded by: self._lock

            def f(self):
                def g():
                    return list(self._items)
                return g
        """
    )
    hits = [f for f in findings if f.rule == "SL101"]
    assert len(hits) == 1, findings


def test_sl103_requires_a_real_signal_import():
    """Only calls through the stdlib `signal` module (any alias) count
    as handler registrations — a user-defined pubsub `signal(name,
    receiver)` helper must not put every receiver's locks at error
    severity."""
    pubsub = _lint_snippet(
        """
        import threading

        _lock = threading.Lock()

        def signal(name, receiver):
            return (name, receiver)

        def notify():
            with _lock:
                return 1

        signal("frame.done", notify)
        """
    )
    assert not [f for f in pubsub if f.rule == "SL103"], pubsub
    aliased = _lint_snippet(
        """
        import signal as sig
        import threading

        _lock = threading.Lock()

        def _handler(signum, frame):
            with _lock:
                return 1

        sig.signal(sig.SIGUSR1, _handler)
        """
    )
    assert [f for f in aliased if f.rule == "SL103"], aliased


def test_sl104_scoped_per_function():
    """A nested function is its own scope: a same-named LOCAL must not
    be flagged via the enclosing function's `global`, and a nested
    function's own unlocked global rebind is exactly one finding."""
    findings = _lint_snippet(
        """
        import threading

        _lock = threading.Lock()
        _cache = {}

        def outer():
            global _cache
            with _lock:
                _cache = {}

            def helper():
                _cache = {"local": True}  # helper's local, not the global
                return _cache

            return helper

        def maker():
            def inner():
                global _cache
                _cache = {}  # one defect
            return inner
        """
    )
    hits = [f for f in findings if f.rule == "SL104"]
    assert len(hits) == 1, findings


def test_sl102_nested_locks_one_finding_per_call():
    """A single blocking call under nested locks is one finding, not one
    per enclosing `with` (suppressing it must cost one comment)."""
    findings = _lint_snippet(
        """
        import threading
        import time

        _a_lock = threading.Lock()
        _b_lock = threading.Lock()

        def f():
            with _a_lock:
                with _b_lock:
                    time.sleep(1)
        """
    )
    assert len([f for f in findings if f.rule == "SL102"]) == 1, findings


def test_acquire_guard_must_be_the_direct_test():
    """A negated guard selects its body on the FAILED acquire, and a
    compound test may not evaluate the acquire at all — neither body is
    lock-held. SL101 must flag the guarded access on the failed-acquire
    path; SL102 must NOT flag blocking work there."""
    findings = _lint_snippet(
        """
        import threading
        import time

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # guarded by: self._lock

            def bad_negated(self):
                if not self._lock.acquire(blocking=False):
                    return list(self._items)
                try:
                    return list(self._items)  # sart-lint: disable=SL101
                finally:
                    self._lock.release()

            def bad_compound(self, flag):
                if flag and self._lock.acquire(blocking=False):
                    return list(self._items)
                return None

        _mlock = threading.Lock()

        def backoff():
            if not _mlock.acquire(blocking=False):
                time.sleep(0.1)  # lock NOT held: fine
                return False
            _mlock.release()
            return True
        """
    )
    sl101 = [f for f in findings if f.rule == "SL101"]
    assert len(sl101) == 2, findings  # both non-held reads flagged
    assert not [f for f in findings if f.rule == "SL102"]


def test_inline_suppression_and_severity_override():
    src = _HEADER + textwrap.dedent(
        """
        def a(n):
            return jnp.zeros((n, 4))

        def b(n):
            return jnp.zeros((n, 4))  # sart-lint: disable=SL003
        """
    )
    findings = lint_source("fixture.py", src)
    assert [f.rule for f in findings] == ["SL003"], findings
    off = lint_source("fixture.py", src,
                      severity_overrides={"SL003": "off"})
    assert not off
    hard = lint_source("fixture.py", src,
                       severity_overrides={"SL003": "error"})
    assert hard and hard[0].severity == "error"


def test_severity_override_parsing():
    from sartsolver_tpu.config import SartInputError, parse_severity_overrides

    assert parse_severity_overrides("") == {}
    assert parse_severity_overrides("SL004=error, SL003=off") == {
        "SL004": "error", "SL003": "off"
    }
    with pytest.raises(SartInputError):
        parse_severity_overrides("SL004")
    with pytest.raises(SartInputError):
        parse_severity_overrides("SL004=loud")
    with pytest.raises(SartInputError):
        # a typoed rule id must fail loudly, not silently do nothing
        parse_severity_overrides("SL04=off")


def test_lint_cli_rejects_unknown_rule_override(capsys):
    from sartsolver_tpu.analysis.cli import lint_main

    assert lint_main(["--list-rules", "--severity", "SL999=off"]) == 1
    assert "SL999" in capsys.readouterr().err


def test_opcode_parsing_handles_tuples_layouts_and_comments():
    """The audit's loop invariants are only as good as the opcode parser:
    tuple-result ops (a `while`, XLA's combined all-reduce), TPU tiled
    layouts (`{1,0:T(8,128)}`), and /*index=N*/ comments in wide tuple
    types must all still yield the opcode — a None here makes every loop
    invariant pass vacuously."""
    from sartsolver_tpu.analysis.hlo import opcode_of

    cases = [
        ("%copy.1 = f32[128,1024]{1,0:T(8,128)} copy(%a)", "copy"),
        ("%ar = (f32[512]{0}, f32[512]{0}) all-reduce(%a, %b), "
         "to_apply=%add", "all-reduce"),
        ("%w.1 = (f32[1,1024]{1,0}, pred[1]{0}, /*index=5*/s32[1]{0}) "
         "while((f32[1,1024]{1,0}, pred[1]{0}, s32[1]{0}) %init), "
         "condition=%cond, body=%body", "while"),
        ("  ROOT %r = (f64[256,512], s32[]) tuple(%m, %i)", "tuple"),
        ("%cv = bf16[128,256]{1,0:T(8,128)(2,1)} convert(s8[128,256] "
         "%codes)", "convert"),
        ("%f = f32[8]{0} fusion(%a), kind=kLoop, calls=%fc", "fusion"),
        ("%c = f32[] constant(0)", "constant"),
    ]
    for line, want in cases:
        assert opcode_of(line) == want, (line, opcode_of(line))


def test_aliased_params_parses_compiled_alias_table():
    """The compiled-side donation corroboration: the module header's
    input_output_alias table maps outputs to donated parameter indices."""
    from sartsolver_tpu.analysis.hlo import aliased_params

    txt = (
        "HloModule jit_f, is_scheduled=true, input_output_alias={ {}: "
        "(0, {}, may-alias), {1}: (3, {}, must-alias) }, "
        "entry_computation_layout={(f32[8,8]{1,0})->f32[8,8]{1,0}}\n"
        "ENTRY %main () -> f32[] {\n  ROOT %c = f32[] constant(0)\n}\n"
    )
    assert aliased_params(txt) == {0, 3}
    assert aliased_params("HloModule plain\n") == set()


def test_lint_survives_unreadable_and_unparseable_files(tmp_path):
    """One bad file (non-UTF-8, or a null byte) must become an SL000
    finding, not a traceback that kills the whole run."""
    from sartsolver_tpu.analysis.rules import lint_paths

    (tmp_path / "latin.py").write_bytes(b"# caf\xe9\nx = 1\n")
    (tmp_path / "nul.py").write_bytes(b"x = 1\x00\n")
    (tmp_path / "ok.py").write_text("import jax.numpy as jnp\n\n"
                                    "def f(n):\n    return jnp.zeros((n,))\n")
    findings = lint_paths([str(tmp_path)])
    rules = sorted(f.rule for f in findings)
    assert rules.count("SL000") == 2, findings
    assert "SL003" in rules  # the healthy file was still linted


def test_sharded_golden_loop_histogram_counts_collectives():
    """The checked-in sharded golden must actually contain the loop's two
    designed all-reduces — i.e. the parser sees collectives inside the
    while body (guards against a parser regression re-hiding them)."""
    import jax

    from sartsolver_tpu.analysis.audit import GOLDENS_DIR

    if jax.default_backend() != "cpu":
        pytest.skip("goldens are checked in for the cpu backend")
    path = os.path.join(GOLDENS_DIR, "sharded_batch.cpu.json")
    with open(path) as fh:
        golden = json.load(fh)
    assert golden["histogram"].get("while", 0) >= 1
    assert golden["loop_histogram"].get("all-reduce", 0) == 2


# ---------------------------------------------------------------------------
# compile audit
# ---------------------------------------------------------------------------


def test_registry_has_the_hot_entry_points():
    from sartsolver_tpu.analysis.registry import load_registered_entries

    entries = load_registered_entries()
    assert {"sweep", "fused_sweep", "sharded_batch"} <= set(entries)
    # the donation-aliasing invariant must be carried by at least one entry
    assert any(e.min_donated_args > 0 for e in entries.values())


def test_compile_audit_invariants_pass():
    """Every registered entry lowers, compiles, and satisfies its declared
    invariants (golden comparison exercised separately)."""
    from sartsolver_tpu.analysis.audit import run_compile_audit

    reports = run_compile_audit(skip_goldens=True)
    assert reports
    bad = [r.format() for r in reports if r.failed]
    assert not bad, "\n".join(bad)
    assert sum(r.status == "ok" for r in reports) >= 3


def test_compile_audit_verifies_checked_in_goldens():
    import jax

    from sartsolver_tpu.analysis.audit import GOLDENS_DIR, run_compile_audit

    if jax.default_backend() != "cpu":
        pytest.skip("goldens are checked in for the cpu backend")
    reports = run_compile_audit()
    by_status = {r.name: r for r in reports}
    for name in ("sweep", "fused_sweep", "sharded_batch"):
        assert by_status[name].status == "ok", by_status[name].format()
        assert os.path.exists(
            os.path.join(GOLDENS_DIR, f"{name}.cpu.json"))


def test_audit_detects_violations_and_golden_drift(tmp_path):
    """Feed the checker a module that violates every loop invariant, and
    verify golden mismatch/missing detection against a scratch dir."""
    from sartsolver_tpu.analysis.audit import (
        check_invariants, run_entry, signature,
    )
    from sartsolver_tpu.analysis.registry import AuditEntry, AUDIT_REGISTRY

    bad_hlo = textwrap.dedent("""\
        HloModule bad, entry_computation_layout={()->f32[]}

        %body (p: (f64[256,512], s32[])) -> (f64[256,512], s32[]) {
          %p = (f64[256,512], s32[]) parameter(0)
          %m = f64[256,512] get-tuple-element((f64[256,512], s32[]) %p), index=0
          %t = f64[512,256] transpose(f64[256,512] %m), dimensions={1,0}
          %c = f64[256,512] convert(f64[256,512] %m)
          %ar = f64[256,512] all-reduce(f64[256,512] %c), to_apply=%body
          %i = s32[] constant(1)
          ROOT %r = (f64[256,512], s32[]) tuple(%m, %i)
        }

        %cond (p: (f64[256,512], s32[])) -> pred[] {
          %p = (f64[256,512], s32[]) parameter(0)
          ROOT %lt = pred[] constant(true)
        }

        ENTRY %main () -> f32[] {
          %init = (f64[256,512], s32[]) tuple()
          %w = (f64[256,512], s32[]) while((f64[256,512], s32[]) %init), condition=%cond, body=%body
          ROOT %out = f32[] constant(0)
        }
        """)
    entry = AuditEntry(
        name="synthetic", build=lambda: None, description="synthetic",
        loop_copy_threshold=256 * 512,
        loop_convert_threshold=256 * 512,
        loop_collective_budget={"all-reduce": 0},
        min_donated_args=1,
    )
    violations = check_invariants(bad_hlo, entry, lowered_text="module {}")
    kinds = "\n".join(violations)
    assert "f64 ops" in kinds
    assert "transpose/copy" in kinds
    assert "convert" in kinds
    assert "all-reduce" in kinds and "budget" in kinds
    assert "donation" in kinds
    assert len(violations) == 5

    # golden round trip on a real (small) registered entry
    name = "sweep"
    entry = AUDIT_REGISTRY[name]
    scratch = str(tmp_path)
    missing = run_entry(entry, goldens_dir=scratch)
    assert missing.status == "golden-missing"
    updated = run_entry(entry, goldens_dir=scratch, update_goldens=True)
    assert updated.status == "updated"
    ok = run_entry(entry, goldens_dir=scratch)
    assert ok.status == "ok", ok.format()
    # corrupt the op-histogram golden (NOT the cost golden, which now
    # sits beside it) -> mismatch with a readable diff
    path = next(os.path.join(scratch, f) for f in os.listdir(scratch)
                if not f.endswith(".cost.json"))
    with open(path) as fh:
        golden = json.load(fh)
    golden["histogram"]["dot"] = golden["histogram"].get("dot", 0) + 7
    with open(path, "w") as fh:
        json.dump(golden, fh)
    drift = run_entry(entry, goldens_dir=scratch)
    assert drift.status == "golden-mismatch"
    assert any("dot" in v for v in drift.violations)


# ---------------------------------------------------------------------------
# cost/memory goldens (performance observatory, docs/OBSERVABILITY.md §8)
# ---------------------------------------------------------------------------


def test_every_entry_has_a_cost_golden():
    """Acceptance: every audited entry point carries a committed cost
    record that parses as the versioned obs ``cost`` schema."""
    import jax

    from sartsolver_tpu.analysis.audit import GOLDENS_DIR
    from sartsolver_tpu.analysis.registry import load_registered_entries
    from sartsolver_tpu.obs import schema

    if jax.default_backend() != "cpu":
        pytest.skip("cost goldens are checked in for the cpu backend")
    for name in load_registered_entries():
        path = os.path.join(GOLDENS_DIR, f"{name}.cpu.cost.json")
        assert os.path.exists(path), f"{name} has no cost golden"
        with open(path) as fh:
            rec = json.load(fh)
        assert schema.validate_record(rec) == [], name
        assert rec["entry"] == name
        # CPU XLA implements both analysis halves: a null here means the
        # extraction silently lost a capability
        assert rec["flops"] is not None and rec["flops"] > 0, name
        assert rec["bytes_accessed"] is not None, name
        assert rec["peak_bytes"] is not None, name


def test_diff_cost_band_and_null_semantics():
    """The tolerance band gates BOTH directions, and a null on exactly
    one side is a drift (a capability change is a re-baseline, never a
    silent pass)."""
    from sartsolver_tpu.analysis.audit import diff_cost

    golden = {"flops": 1000.0, "bytes_accessed": 500.0,
              "argument_bytes": None, "output_bytes": 10.0,
              "temp_bytes": 1.0, "peak_bytes": 11.0}
    same = dict(golden)
    assert diff_cost(golden, same, rtol=0.5) == []
    # inside the band: jitter passes
    jitter = dict(golden, flops=1400.0)
    assert diff_cost(golden, jitter, rtol=0.5) == []
    # the silent 2x growth the tentpole exists to catch
    grown = dict(golden, flops=2100.0)
    msgs = diff_cost(golden, grown, rtol=0.5)
    assert len(msgs) == 1 and "flops" in msgs[0] and "band" in msgs[0]
    # an unexplained halving trips too (work traced away)
    shrunk = dict(golden, bytes_accessed=100.0)
    assert any("bytes_accessed" in m for m in
               diff_cost(golden, shrunk, rtol=0.5))
    # null-on-one-side is a drift with a re-baseline hint
    lost = dict(golden, flops=None)
    msgs = diff_cost(golden, lost, rtol=0.5)
    assert any("null on one side" in m for m in msgs)
    # null on BOTH sides is agreement (backend without that half)
    assert diff_cost(dict(golden, flops=None),
                     dict(golden, flops=None), rtol=0.5) == []


def test_cost_drift_fails_audit_like_histogram_drift(tmp_path):
    """A cost golden drifted past the entry's band fails run_entry with
    golden-mismatch — the audit verdict, not a warning."""
    from sartsolver_tpu.analysis.audit import run_entry
    from sartsolver_tpu.analysis.registry import AUDIT_REGISTRY

    entry = AUDIT_REGISTRY["sweep"]
    scratch = str(tmp_path)
    assert run_entry(entry, goldens_dir=scratch,
                     update_goldens=True).status == "updated"
    cost_path = os.path.join(scratch, "sweep.cpu.cost.json")
    with open(cost_path) as fh:
        rec = json.load(fh)
    rec["flops"] = rec["flops"] * 4  # a silent 4x FLOP growth
    with open(cost_path, "w") as fh:
        json.dump(rec, fh)
    drift = run_entry(entry, goldens_dir=scratch)
    assert drift.status == "golden-mismatch"
    assert any("flops" in v for v in drift.violations)
    assert "cost drifted" in drift.detail
    # a cost-golden deletion is golden-missing, with the re-baseline cmd
    os.remove(cost_path)
    gone = run_entry(entry, goldens_dir=scratch)
    assert gone.status == "golden-missing"
    assert "--update-cost-goldens" in gone.detail


def test_update_cost_goldens_leaves_histograms_untouched(tmp_path):
    """--update-cost-goldens re-baselines ONLY the cost records: the
    op-histogram signature files stay byte-identical (mtime included is
    too strong; bytes is the contract)."""
    from sartsolver_tpu.analysis.audit import run_entry
    from sartsolver_tpu.analysis.registry import AUDIT_REGISTRY

    entry = AUDIT_REGISTRY["sweep"]
    scratch = str(tmp_path)
    run_entry(entry, goldens_dir=scratch, update_goldens=True)
    hist_path = os.path.join(scratch, "sweep.cpu.json")
    cost_path = os.path.join(scratch, "sweep.cpu.cost.json")
    hist_before = open(hist_path, "rb").read()
    # poison the histogram golden: a cost-only rebaseline must not heal
    # (i.e. rewrite) it
    with open(hist_path, "wb") as fh:
        fh.write(hist_before + b"\n")
    with open(cost_path, "w") as fh:
        fh.write("{}")
    rep = run_entry(entry, goldens_dir=scratch, update_cost_goldens=True)
    assert rep.status == "updated"
    assert open(hist_path, "rb").read() == hist_before + b"\n"
    assert json.load(open(cost_path))["type"] == "cost"
    # ...but a REAL histogram drift is still verified first: the
    # cost-only rebaseline reports the mismatch and rewrites nothing
    hist = json.loads(hist_before)
    hist["histogram"]["dot"] = hist["histogram"].get("dot", 0) + 7
    with open(hist_path, "w") as fh:
        json.dump(hist, fh)
    with open(cost_path, "w") as fh:
        fh.write("{}")
    rep = run_entry(entry, goldens_dir=scratch, update_cost_goldens=True)
    assert rep.status == "golden-mismatch"
    assert open(cost_path).read() == "{}"  # drift blocked the rewrite


def test_audit_report_carries_cost_record():
    """EntryReport.cost rides along with the verdict (the --json lint
    output's attribution payload)."""
    from sartsolver_tpu.analysis.audit import run_entry
    from sartsolver_tpu.analysis.registry import AUDIT_REGISTRY
    from sartsolver_tpu.obs import schema

    rep = run_entry(AUDIT_REGISTRY["sweep"], skip_goldens=True)
    assert rep.status == "ok"
    assert rep.cost is not None
    assert schema.validate_record(rep.cost) == []
    assert rep.cost["entry"] == "sweep"


def test_while_loop_required_guard():
    """An entry whose loop got traced away must fail, not vacuously pass."""
    from sartsolver_tpu.analysis.audit import check_invariants
    from sartsolver_tpu.analysis.registry import AuditEntry

    no_loop = "ENTRY %main () -> f32[] {\n  ROOT %c = f32[] constant(0)\n}\n"
    entry = AuditEntry(
        name="x", build=lambda: None, description="x",
        loop_copy_threshold=1,
    )
    violations = check_invariants(no_loop, entry)
    assert violations and "while" in violations[0]


# ---------------------------------------------------------------------------
# package self-lint (the verify-path hook: new hazards fail the suite)
# ---------------------------------------------------------------------------


def test_package_self_lint_clean():
    import sartsolver_tpu
    from sartsolver_tpu.analysis.rules import lint_paths

    pkg = os.path.dirname(os.path.abspath(sartsolver_tpu.__file__))
    findings = lint_paths([pkg])
    errors = [f.format() for f in findings if f.severity == "error"]
    assert not errors, (
        "error-severity lint findings in the package (fix, or annotate "
        "deliberate ones with `# sart-lint: disable=...`):\n"
        + "\n".join(errors)
    )
    # warnings/infos must be fixed or explicitly annotated too — the
    # first-self-run contract; new ones need a conscious decision
    assert not [f.format() for f in findings], (
        "unannotated lint findings in the package:\n"
        + "\n".join(f.format() for f in findings)
    )


def test_lint_cli_end_to_end(tmp_path, capsys):
    from sartsolver_tpu.analysis.cli import lint_main

    bad = tmp_path / "bad.py"
    bad.write_text(_HEADER + textwrap.dedent(
        """
        @jax.jit
        def update(x, threshold):
            if threshold > 0:
                return x * 2
            return x
        """
    ))
    rc = lint_main([str(bad)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "SL001" in out
    good = tmp_path / "good.py"
    good.write_text(_HEADER + "def f(x):\n    return jnp.sin(x)\n")
    assert lint_main([str(good)]) == 0
    assert lint_main(["--list-rules"]) == 0
    assert "SL001" in capsys.readouterr().out


def test_lint_cli_json_output(tmp_path, capsys):
    from sartsolver_tpu.analysis.cli import lint_main

    f = tmp_path / "m.py"
    f.write_text(_HEADER + "def b(n):\n    return jnp.zeros((n, 4))\n")
    rc = lint_main([str(f), "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0  # warnings don't fail
    assert payload["warnings"] == 1
    assert payload["findings"][0]["rule"] == "SL003"


# ---------------------------------------------------------------------------
# --select / --ignore rule-family filters (CI staging knob)
# ---------------------------------------------------------------------------

# seeds one SL003 (jnp ctor without dtype) and one SL102 (sleep under
# lock): one finding per family, so the filters' effect is observable
_TWO_FAMILY_SRC = _HEADER + textwrap.dedent(
    """
    import threading
    import time

    _lock = threading.Lock()

    def b(n):
        with _lock:
            time.sleep(1)
        return jnp.zeros((n, 4))
    """
)


def test_lint_select_and_ignore_family_filters(tmp_path, capsys):
    from sartsolver_tpu.analysis.cli import lint_main

    f = tmp_path / "m.py"
    f.write_text(_TWO_FAMILY_SRC)

    def rules_found(argv):
        rc = lint_main(argv + ["--json"])
        payload = json.loads(capsys.readouterr().out)
        return rc, payload

    _, both = rules_found([str(f)])
    assert {x["rule"] for x in both["findings"]} == {"SL003", "SL102"}
    assert both["select"] == [] and both["ignore"] == []

    _, sl1 = rules_found([str(f), "--select", "SL1"])
    assert {x["rule"] for x in sl1["findings"]} == {"SL102"}
    assert sl1["select"] == ["SL1"]
    # the metadata names exactly the rules that ran: staged-gate CI can
    # assert the family it meant to enable was actually in effect
    assert sl1["rules"] == ["SL101", "SL102", "SL103", "SL104", "SL105"]

    _, ignored = rules_found([str(f), "--ignore", "SL1"])
    assert {x["rule"] for x in ignored["findings"]} == {"SL003"}
    assert ignored["ignore"] == ["SL1"]
    assert not any(r.startswith("SL1") for r in ignored["rules"])

    _, mixed = rules_found([str(f), "--select", "SL003,SL1",
                            "--ignore", "SL104"])
    assert {x["rule"] for x in mixed["findings"]} == {"SL003", "SL102"}
    assert "SL104" not in mixed["rules"] and "SL103" in mixed["rules"]


def test_lint_select_filters_list_rules(capsys):
    from sartsolver_tpu.analysis.cli import lint_main

    assert lint_main(["--list-rules", "--select", "SL1"]) == 0
    out = capsys.readouterr().out
    assert "SL101" in out and "SL105" in out
    assert "SL001" not in out


def test_lint_rejects_vacuous_family_prefix(capsys):
    """A typo'd family that matches nothing must fail loudly — a gate
    silently selecting zero rules would pass forever."""
    from sartsolver_tpu.analysis.cli import lint_main

    assert lint_main(["--list-rules", "--select", "SL9"]) == 1
    assert "SL9" in capsys.readouterr().err
    assert lint_main(["--list-rules", "--ignore", "bogus"]) == 1
    assert "bogus" in capsys.readouterr().err


def test_lint_rejects_filter_combination_selecting_nothing(capsys):
    """Individually-valid prefixes whose combination leaves zero rules
    (ignore-everything, or select and ignore the same family) are the
    same vacuous gate — loud exit 1, not a forever-green lint."""
    from sartsolver_tpu.analysis.cli import lint_main

    assert lint_main(["--list-rules", "--ignore", "SL"]) == 1
    assert "no rules to run" in capsys.readouterr().err
    assert lint_main(["--list-rules", "--select", "SL1",
                      "--ignore", "SL1"]) == 1
    assert "no rules to run" in capsys.readouterr().err


def test_sl102_fires_inside_acquire_guard_body_only():
    """The acquire-`if` form holds the lock in its body — blocking work
    there is flagged like a `with` body; the else branch (failed
    acquire) is not."""
    flagged = _lint_snippet(
        """
        import threading
        import time

        _lock = threading.Lock()

        def f():
            if _lock.acquire(blocking=False):
                try:
                    time.sleep(1)
                finally:
                    _lock.release()
            else:
                time.sleep(2)
        """
    )
    hits = [f for f in flagged if f.rule == "SL102"]
    assert len(hits) == 1, flagged
    assert "acquire" in hits[0].message
