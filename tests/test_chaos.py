"""Chaos campaign harness matrix (docs/SERVING.md §9; `make chaos`).

Units: schedule determinism (same seed -> same faults + kill plan),
window-marker line parsing, CLI usage errors, and the judge's teeth (a
doctored journal with a double-solved request must violate the
exactly-once invariant — the gate is not vacuous).

End-to-end: `sartsolve chaos` on the bounded CI seed set against the
synthetic world — randomized transient faults + SIGKILLs inside the
journal/checkpoint/response commit windows of a REAL supervised serve,
asserting every accepted request reaches exactly one outcome, outputs
stay byte-identical to an undisturbed run, restarts stay within the
kill budget, and counter/SLO continuity holds across incarnations.
"""

import json
import os

import pytest

import fixtures as fx

from sartsolver_tpu.resilience import chaos as chaos_mod
from sartsolver_tpu.resilience.chaos import (
    FAULT_POOL,
    CampaignError,
    ChaosCampaign,
    FaultSchedule,
    chaos_main,
    line_window,
)

# the bounded CI seed set (make chaos); SART_CHAOS_SEEDS widens it
CI_SEEDS = os.environ.get("SART_CHAOS_SEEDS", "3,5")


# ---------------------------------------------------------------------------
# units
# ---------------------------------------------------------------------------

def test_schedule_deterministic_per_seed():
    for seed in range(8):
        a, b = FaultSchedule(seed), FaultSchedule(seed)
        assert a.describe() == b.describe()
        assert a.fault_spec() == b.fault_spec()
        assert a.window_env() == b.window_env()
    # different seeds explore different schedules
    assert len({FaultSchedule(s).fault_spec() for s in range(16)}) > 1


def test_schedule_draws_from_safe_pool_only():
    from sartsolver_tpu.resilience.faults import parse_fault_spec

    sites = {site for site, _kind in FAULT_POOL}
    for seed in range(16):
        sched = FaultSchedule(seed)
        armed = parse_fault_spec(sched.fault_spec())  # valid spec
        assert set(armed) <= sites
        for window, occurrence in sched.kills:
            assert window in chaos_mod.KILL_WINDOWS
            assert 1 <= occurrence <= 3


def test_line_window_parsing():
    assert line_window("SART_JOURNAL_POINT accepted\n") == "accepted"
    assert line_window("SART_JOURNAL_POINT pre-flush\n") == "pre-flush"
    assert line_window("SART_CKPT_POINT pre-append\n") == "ckpt"
    # only COMPLETION responses are the 'response' kill window —
    # acceptance responses are written first and would shadow it
    assert line_window("SART_RESPONSE_POINT r1 state=done\n") \
        == "response"
    assert line_window("SART_RESPONSE_POINT r1 state=pending\n") is None
    assert line_window("SART_RESPONSE_POINT r1 state=none\n") is None
    assert line_window("engine: session resident\n") is None


def test_chaos_cli_usage_errors(capsys):
    assert chaos_main(["--engine_dir", "/tmp/x"]) == 1  # no serve args
    assert "after --" in capsys.readouterr().err
    assert chaos_main(["--engine_dir", "/tmp/x", "--seeds", "nope",
                       "--", "f.h5"]) == 1
    assert chaos_main(["--engine_dir", "/tmp/x", "--requests", "0",
                       "--", "f.h5"]) == 1


def test_judge_catches_double_solve(tmp_path):
    """The exactly-once gate has teeth: a journal showing two completed
    markers for one id violates the invariant loudly."""
    campaign = ChaosCampaign(
        root=str(tmp_path), serve_args=["x.h5"],
        requests=[{"id": "a", "tenant": "t0"}],
        slo_ms=None, timeout=10.0,
    )
    campaign.reference = {"a": {"datasets": {}, "status": "completed"}}
    seed_dir = str(tmp_path / "seed0")
    os.makedirs(seed_dir)
    with open(os.path.join(seed_dir, "journal.jsonl"), "w") as f:
        f.write(json.dumps({"marker": "accepted", "id": "a",
                            "unix": 1.0, "request": {"id": "a"}}) + "\n")
        for _ in range(2):  # double solve
            f.write(json.dumps({"marker": "completed", "id": "a",
                                "unix": 2.0, "outcome": {}}) + "\n")
    with pytest.raises(CampaignError, match="double-solved"):
        campaign._judge(seed_dir, FaultSchedule(0), kills_fired=1,
                        text="")


def test_judge_catches_lost_request(tmp_path):
    campaign = ChaosCampaign(
        root=str(tmp_path), serve_args=["x.h5"],
        requests=[{"id": "a", "tenant": "t0"}],
        slo_ms=None, timeout=10.0,
    )
    campaign.reference = {"a": {"datasets": {}, "status": "completed"}}
    seed_dir = str(tmp_path / "seed0")
    os.makedirs(seed_dir)
    with open(os.path.join(seed_dir, "journal.jsonl"), "w") as f:
        f.write(json.dumps({"marker": "accepted", "id": "a",
                            "unix": 1.0, "request": {"id": "a"}}) + "\n")
    with pytest.raises(CampaignError, match="journal shows"):
        campaign._judge(seed_dir, FaultSchedule(0), kills_fired=0,
                        text="")


# ---------------------------------------------------------------------------
# the campaign (ISSUE acceptance: full CI seed set)
# ---------------------------------------------------------------------------

def test_chaos_campaign_ci_seed_set(tmp_path, capsys):
    """Randomized fault schedules + SIGKILLs against the real supervised
    engine: the ISSUE's acceptance invariants, on the bounded seed set
    `make chaos` runs."""
    world = str(tmp_path / "world")
    os.makedirs(world)
    paths, *_ = fx.write_world(world, n_frames=4)
    report_path = str(tmp_path / "report.json")
    rc = chaos_main([
        "--engine_dir", str(tmp_path / "camp"),
        "--seeds", CI_SEEDS, "--requests", "4",
        "--slo_ms", "300000", "--timeout", "280",
        "--report", report_path, "--",
        "--use_cpu", "-m", "40", "-c", "1e-12", "--lanes", "2",
        paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
        paths["img_a"], paths["img_b"],
    ])
    out = capsys.readouterr().out
    assert rc == 0, out
    report = json.load(open(report_path))
    assert report["verdict"] == "ok"
    assert len(report["passes"]) == len(CI_SEEDS.split(","))
    for verdict in report["passes"]:
        assert verdict["verdict"] == "ok"
        assert verdict["kills_fired"] >= 1  # every seed really killed
        assert verdict["restarts"] <= verdict["kills_fired"]
        assert verdict["requests_total"] == {"completed": 4.0}
