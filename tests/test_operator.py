"""Matrix-free projection operators (sartsolver_tpu/operators/,
docs/PERFORMANCE.md §11; `make operator`).

Four layers, outermost last:

- geometry records: round trip, validation taxonomy, the name-sorted
  pixel-row convention, frame masks and the voxel-map surface;
- the operator contract: payload/spec/resident-bytes/cache-key for the
  dense and implicit backends, and the implicit kernels (forward / back
  / ray stats / OS subset densities) against the matrix they claim to
  apply;
- solver parity: the implicit DistributedSARTSolver against a dense
  solver on the materialized matrix across linear/log, ordered subsets,
  momentum, divergence recovery, continuous batching and a pixel-sharded
  mesh — identical statuses and iteration counts, solutions within the
  fused-parity tolerance;
- the serving engine: request admission of inline geometry, session
  key/byte accounting, a geometry-built ResidentSession driven through
  the ContinuousBatcher, and one real `sartsolve serve` process solving
  a `submit --geometry` request on its own implicit session;
- the factored backend (operators/lowrank.py, PERFORMANCE.md §12): the
  same contract/kernel/parity/restriction drills over the low-rank +
  sparse H ~= S + U V^T operator, plus its quality gate, rank
  determinism, and the `--lowrank_rtm` session path.
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import threading

import h5py
import numpy as np
import pytest

import fixtures as fx

from sartsolver_tpu.config import DIVERGED, SartInputError, SolverOptions
from sartsolver_tpu.operators import (
    DenseOperator,
    ImplicitOperator,
    TileSkipOperator,
)
from sartsolver_tpu.operators.geometry import (
    GeometryVoxelGrid,
    load_geometry,
    parse_geometry,
    save_geometry,
)
from sartsolver_tpu.operators.implicit import (
    ImplicitSpec,
    implicit_back,
    implicit_forward,
    implicit_ray_stats,
    implicit_subset_density,
    pick_implicit_panel,
)
from sartsolver_tpu.operators.lowrank import (
    DEFAULT_TOL,
    LowRankOperator,
    build_lowrank_operator,
    lowrank_back,
    lowrank_forward,
    lowrank_ray_stats,
    lowrank_static_decline_reason,
    lowrank_subset_density,
    randomized_svd,
    split_sparse_core,
)
from sartsolver_tpu.parallel.mesh import COL_ALIGN, make_mesh, padded_size
from sartsolver_tpu.parallel.sharded import DistributedSARTSolver
from sartsolver_tpu.sched import ContinuousBatcher
from sartsolver_tpu.utils.fused_parity import PARITY_RTOL

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)

# The canonical test geometry: the fixture world's two cameras (camA
# 3x4, camB 2x3 — the io/hdf5files.py row order is camA then camB) over
# a 4x4x4 unit grid. 18 pixel rows, 64 voxels: underdetermined, so every
# assertion below compares implicit vs DENSE-on-the-materialized-matrix,
# never vs a ground truth the data cannot pin down.
GEO_DICT = {
    "format": "sart-geometry",
    "version": 1,
    "grid": {"shape": [4, 4, 4], "origin": [0.0, 0.0, 0.0],
             "spacing": [1.0, 1.0, 1.0]},
    "cameras": [
        {"name": "camA", "rows": 3, "cols": 4,
         "position": [-6.0, 2.1, 2.2], "target": [2.0, 2.0, 2.0],
         "up": [0.0, 0.0, 1.0], "pitch": 0.8},
        {"name": "camB", "rows": 2, "cols": 3,
         "position": [2.2, -6.0, 1.9], "target": [2.0, 2.0, 2.0],
         "up": [0.0, 0.0, 1.0], "pitch": 0.9},
    ],
}


def _record():
    return parse_geometry(json.loads(json.dumps(GEO_DICT)))


def _case(seed=0):
    """(record, operator, H fp64, g fp64): a consistent measurement on
    the canonical geometry."""
    rec = _record()
    op = ImplicitOperator(rec)
    H = op.materialize().astype(np.float64)
    rng = np.random.default_rng(seed)
    f_true = rng.uniform(0.5, 1.5, rec.nvoxel)
    return rec, op, H, H @ f_true


# ---------------------------------------------------------------------------
# geometry records
# ---------------------------------------------------------------------------

def test_geometry_roundtrip(tmp_path):
    rec = _record()
    path = str(tmp_path / "geom.json")
    save_geometry(rec, path)
    back = load_geometry(path)
    assert back == rec
    assert ImplicitOperator(back).cache_key() == \
        ImplicitOperator(rec).cache_key()
    np.testing.assert_array_equal(back.build_rays(), rec.build_rays())


def test_geometry_cameras_sorted_by_name():
    """Pixel-row order is the repo-wide convention (cameras sorted by
    name, row-major within each camera) regardless of record order."""
    shuffled = json.loads(json.dumps(GEO_DICT))
    shuffled["cameras"].reverse()
    rec = parse_geometry(shuffled)
    assert rec.camera_names == ("camA", "camB")
    np.testing.assert_array_equal(rec.build_rays(), _record().build_rays())
    # rays are unit-direction (origin xyz, direction xyz) rows
    rays = rec.build_rays()
    assert rays.shape == (rec.npixel, 6)
    np.testing.assert_allclose(
        np.linalg.norm(rays[:, 3:], axis=1), 1.0, rtol=1e-12)


def _mutate(path, value):
    payload = json.loads(json.dumps(GEO_DICT))
    node = payload
    *parents, leaf = path
    for key in parents:
        node = node[key]
    if value is _DROP:
        del node[leaf]
    else:
        node[leaf] = value
    return payload


_DROP = object()

BAD_RECORDS = [
    (["format"], "sart-rtm", "format"),
    (["version"], 99, "version"),
    (["grid"], _DROP, "grid"),
    (["grid", "shape"], [4, 4], "grid.shape"),
    (["grid", "shape"], [4, 0, 4], "grid.shape"),
    (["grid", "spacing"], [1.0, -1.0, 1.0], "grid.spacing"),
    (["grid", "spacing"], _DROP, "grid.spacing"),
    (["cameras"], [], "cameras"),
    (["cameras", 0, "name"], "", "name"),
    (["cameras", 0, "rows"], 0, "rows"),
    (["cameras", 0, "pitch"], 0.0, "pitch"),
    (["cameras", 0, "position"], [2.0, 2.0, 2.0], "coincide"),
    (["cameras", 0, "up"], [-8.0, 0.1, 0.2], "parallel"),
    (["cameras", 0, "position"], [1.0, "x", 0.0], "position"),
    (["cameras", 1, "name"], "camA", "unique"),
]


@pytest.mark.parametrize("path,value,match", BAD_RECORDS,
                         ids=[m for *_, m in BAD_RECORDS])
def test_geometry_validation(path, value, match):
    with pytest.raises(SartInputError, match=match):
        parse_geometry(_mutate(path, value))


def test_geometry_rejects_non_json_and_unknown_version_text():
    with pytest.raises(SartInputError, match="JSON"):
        parse_geometry("{not json")
    with pytest.raises(SartInputError, match="object"):
        parse_geometry([1, 2, 3])


def test_geometry_frame_masks_and_voxel_grid():
    rec = _record()
    masks = rec.frame_masks()
    assert set(masks) == {"camA", "camB"}
    assert masks["camA"].shape == (3, 4) and masks["camA"].all()
    assert masks["camB"].shape == (2, 3) and masks["camB"].all()
    grid = GeometryVoxelGrid(rec)
    assert grid.nvox == rec.nvoxel == 64
    np.testing.assert_array_equal(grid.voxmap, np.arange(64))
    assert (grid.nx, grid.ny, grid.nz) == (4, 4, 4)
    assert grid.xmax == pytest.approx(4.0)


# ---------------------------------------------------------------------------
# the operator contract
# ---------------------------------------------------------------------------

def test_operator_identity_and_accounting():
    rec, op, H, _g = _case()
    assert op.kind == "implicit"
    assert op.shape == (18, 64)
    payload = op.payload()
    assert payload.shape == (18, 6) and payload.dtype == np.float32
    # the whole point: rays are O(npixel) bytes, the matrix is O(P*V)
    assert op.resident_nbytes() == 18 * 6 * 4 == 432
    dense = DenseOperator(H.astype(np.float32))
    assert dense.resident_nbytes() == 18 * 64 * 4
    assert op.resident_nbytes() < dense.resident_nbytes() / 10
    # cache keys pin backend + shapes + dtype + geometry digest
    key = op.cache_key()
    assert key.startswith("implicit:18x64:float32:")
    assert key == ImplicitOperator(_record()).cache_key()
    moved = json.loads(json.dumps(GEO_DICT))
    moved["cameras"][0]["position"][0] -= 0.5
    assert ImplicitOperator(parse_geometry(moved)).cache_key() != key
    assert dense.cache_key() != key
    np.testing.assert_array_equal(dense.materialize(),
                                  H.astype(np.float32))


def test_implicit_spec_validation():
    with pytest.raises(ValueError, match="multiply out"):
        ImplicitSpec(grid_shape=(4, 4, 4), origin=(0, 0, 0),
                     spacing=(1, 1, 1), nvoxel=128, grid_voxels=65,
                     panel_voxels=128)
    with pytest.raises(ValueError, match="smaller than the"):
        ImplicitSpec(grid_shape=(8, 8, 8), origin=(0, 0, 0),
                     spacing=(1, 1, 1), nvoxel=128, grid_voxels=512,
                     panel_voxels=128)
    with pytest.raises(ValueError, match="divide"):
        ImplicitSpec(grid_shape=(4, 4, 4), origin=(0, 0, 0),
                     spacing=(1, 1, 1), nvoxel=128, grid_voxels=64,
                     panel_voxels=96)


def test_pick_implicit_panel():
    assert pick_implicit_panel(128) == 128
    assert pick_implicit_panel(1024) == 1024
    # 2048 splits into two 1024 panels; 1280 into 256-wide panels
    assert pick_implicit_panel(2048) == 1024
    assert 1280 % pick_implicit_panel(1280) == 0
    assert pick_implicit_panel(1280) % COL_ALIGN == 0
    with pytest.raises(ValueError, match="multiple"):
        pick_implicit_panel(100)


def test_matrix_entries_are_ray_segment_lengths():
    """Physical sanity of the slab kernel: entries are nonnegative, a
    ray's row sum equals its chord length through the grid (at most the
    grid diagonal), and rays that miss the grid give all-zero rows."""
    _rec, op, H, _g = _case()
    assert (H >= 0).all()
    # every live entry is at most one voxel's diagonal
    assert H.max() <= np.sqrt(3.0) + 1e-6
    chords = H.sum(axis=1)
    assert chords.max() <= np.sqrt(3.0) * 4 + 1e-6
    # the two cameras look at the grid center: most rays hit
    assert (chords > 0).sum() >= 12


def test_implicit_kernels_match_materialized_matrix():
    """forward/back/ray-stats/subset-density against the dense matrix
    the operator claims to apply, including padded rows and columns."""
    rec, op, H, _g = _case()
    spec = op.spec()
    V_pad = spec.nvoxel
    assert V_pad == padded_size(64, COL_ALIGN) == 128
    rays = np.zeros((24, 6), np.float32)  # 6 zero-padded ray rows
    rays[:18] = op.payload()
    rng = np.random.default_rng(1)
    f = np.zeros(V_pad, np.float32)
    f[:64] = rng.uniform(0.0, 2.0, 64)
    got = np.asarray(implicit_forward(rays, f, spec))
    want = H @ f[:64].astype(np.float64)
    np.testing.assert_allclose(got[:18], want, rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(got[18:], 0.0)

    w = rng.uniform(0.0, 1.0, 24).astype(np.float32)
    w[18:] = 0.0
    got_b = np.asarray(implicit_back(rays, w, spec))
    want_b = H.T @ w[:18].astype(np.float64)
    np.testing.assert_allclose(got_b[:64], want_b, rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(got_b[64:], 0.0)

    dens, length = implicit_ray_stats(rays, spec)
    np.testing.assert_allclose(np.asarray(dens)[:64], H.sum(axis=0),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(dens)[64:], 0.0)
    np.testing.assert_allclose(np.asarray(length)[:18], H.sum(axis=1),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(length)[18:], 0.0)

    # OS subsets: subset t is ray rows t::os — the dense reshape stacking
    sub = np.asarray(implicit_subset_density(rays, spec, 3))
    H_pad = np.zeros((24, 128))
    H_pad[:18, :64] = H
    want_sub = H_pad.reshape(8, 3, 128).sum(axis=0)
    np.testing.assert_allclose(sub, want_sub, rtol=1e-5, atol=1e-6)


def test_batched_forward_matches_per_frame():
    rec, op, _H, _g = _case()
    spec = op.spec()
    rays = op.payload()
    rng = np.random.default_rng(2)
    fb = rng.uniform(0.0, 1.0, (3, spec.nvoxel)).astype(np.float32)
    got = np.asarray(implicit_forward(rays, fb, spec))
    for b in range(3):
        np.testing.assert_allclose(
            got[b], np.asarray(implicit_forward(rays, fb[b], spec)),
            rtol=1e-6, atol=1e-7)


# ---------------------------------------------------------------------------
# solver parity matrix: implicit vs dense-on-the-materialized-matrix
# ---------------------------------------------------------------------------

def _opts(**kw):
    # conv_tolerance=0.0 disables the stall test outright so both
    # backends run to max_iterations: at any positive tolerance the
    # |conv - conv_prev| comparison sits on an fp32 noise boundary (an
    # exact plateau on one backend but not the other) and the two can
    # retire iterations apart — exactly the flake the fused-parity
    # harness avoids too
    kw.setdefault("max_iterations", 40)
    kw.setdefault("conv_tolerance", 0.0)
    kw.setdefault("fused_sweep", "off")
    return SolverOptions(**kw)


def _assert_parity(imp_res, ref_res, nvoxel=64, rtol=PARITY_RTOL):
    assert int(imp_res.status) == int(ref_res.status)
    assert int(imp_res.iterations) == int(ref_res.iterations)
    a = np.asarray(imp_res.solution)[:nvoxel]
    b = np.asarray(ref_res.solution)[:nvoxel]
    scale = max(np.max(np.abs(b)), 1e-12)
    assert np.max(np.abs(a - b)) <= rtol * scale


PARITY_LEGS = [
    ("linear", {}),
    ("log", {"logarithmic": True}),
    ("os", {"os_subsets": 3}),
    ("momentum", {"momentum": "nesterov"}),
    ("auto-declines", {"fused_sweep": "auto", "sparse_rtm": "auto"}),
]


@pytest.mark.parametrize("name,kw", PARITY_LEGS,
                         ids=[n for n, _ in PARITY_LEGS])
def test_parity_vs_dense(name, kw):
    """Same opts, same measurements, same mesh: the matrix-free solve
    must land on the dense solve's answer with identical per-frame
    statuses and iteration counts (fused-parity tolerance)."""
    _rec, op, H, g = _case()
    opts = _opts(**kw)
    imp = DistributedSARTSolver(operator=op, opts=opts,
                                mesh=make_mesh(1, 1))
    dense = DistributedSARTSolver(H.astype(np.float32), opts=opts,
                                  mesh=make_mesh(1, 1))
    try:
        for scale in (1.0, 1.3):
            _assert_parity(imp.solve(g * scale), dense.solve(g * scale))
    finally:
        imp.close()
        dense.close()


def test_parity_pixel_sharded_mesh():
    """Implicit on a (4, 1) pixel-sharded mesh vs dense single-device:
    one tolerance covers both the backend and the sharding."""
    _rec, op, H, g = _case()
    opts = _opts()
    imp = DistributedSARTSolver(operator=op, opts=opts,
                                mesh=make_mesh(4, 1))
    dense = DistributedSARTSolver(H.astype(np.float32), opts=opts,
                                  mesh=make_mesh(1, 1))
    try:
        _assert_parity(imp.solve(g), dense.solve(g))
        # warm-started chain, the CLI's frame loop shape
        w_imp = imp.solve(g * 1.2, f0=imp.solve(g).solution)
        w_dense = dense.solve(g * 1.2, f0=dense.solve(g).solution)
        _assert_parity(w_imp, w_dense)
    finally:
        imp.close()
        dense.close()


def test_parity_divergence_recovery():
    """The rollback/relaxation ladder walks identically matrix-free.
    The convergence metric is scale-invariant (Eq. 5 normalizes by
    ||g||^2), so the deterministic trigger is a non-finite metric: a
    NaN-poisoned measurement exhausts the ladder to DIVERGED on both
    backends, same iteration count, finite iterates."""
    _rec, op, H, g = _case()
    opts = _opts(divergence_recovery=3)
    imp = DistributedSARTSolver(operator=op, opts=opts,
                                mesh=make_mesh(1, 1))
    dense = DistributedSARTSolver(H.astype(np.float32), opts=opts,
                                  mesh=make_mesh(1, 1))
    try:
        g_bad = g.copy()
        g_bad[4] = np.nan
        ri = imp.solve(g_bad)
        rd = dense.solve(g_bad)
        assert int(ri.status) == int(rd.status) == DIVERGED
        assert int(ri.iterations) == int(rd.iterations)
        assert np.isfinite(np.asarray(ri.solution)).all()
        assert np.isfinite(np.asarray(rd.solution)).all()
        # and clean data still solves cleanly with recovery armed
        _assert_parity(imp.solve(g), dense.solve(g))
    finally:
        imp.close()
        dense.close()


def test_parity_continuous_batching():
    """ContinuousBatcher lanes over the implicit solver vs the same
    batcher over the dense solver: emission order, statuses, iteration
    counts identical; solutions within the parity tolerance."""
    _rec, op, H, g = _case()
    rng = np.random.default_rng(3)
    frames = [np.maximum(g * s + 0.01 * rng.standard_normal(18), 0.0)
              for s in (1.0, 0.7, 1.4, 1.1, 0.9)]
    items = [(fr, float(i), [float(i)]) for i, fr in enumerate(frames)]
    opts = _opts(schedule_stride=4)

    def _drive(solver):
        out = []

        def on_result(ftime, _ct, status, iters, _conv, fetcher, _ms):
            out.append((ftime, status, iters, fetcher()))

        def on_failed(ftime, _ct, err):
            raise AssertionError(f"frame {ftime} failed: {err}")

        b = ContinuousBatcher(solver, lanes=2, on_result=on_result,
                              on_failed=on_failed)
        b.run(iter(list(items)))
        return out

    imp = DistributedSARTSolver(operator=op, opts=opts,
                                mesh=make_mesh(2, 1))
    dense = DistributedSARTSolver(H.astype(np.float32), opts=opts,
                                  mesh=make_mesh(2, 1))
    try:
        got = _drive(imp)
        want = _drive(dense)
    finally:
        imp.close()
        dense.close()
    assert [r[:3] for r in got] == [r[:3] for r in want]
    for (_t, _s, _i, a), (_t2, _s2, _i2, b) in zip(got, want):
        a, b = np.asarray(a)[:64], np.asarray(b)[:64]
        assert np.max(np.abs(a - b)) <= \
            PARITY_RTOL * max(np.max(np.abs(b)), 1e-12)


# ---------------------------------------------------------------------------
# implicit-mode restrictions (all polite input errors)
# ---------------------------------------------------------------------------

RESTRICTION_LEGS = [
    ("voxel-sharded", {}, (1, 2), "voxel-sharded"),
    ("int8", {"rtm_dtype": "int8"}, (1, 1), "int8"),
    ("integrity", {"integrity": True}, (1, 1), "integrity"),
    ("sparse-explicit", {"sparse_rtm": "1e-8"}, (1, 1), "block-"),
    ("fused-on", {"fused_sweep": "on"}, (1, 1), "fused_sweep"),
    ("fused-interpret", {"fused_sweep": "interpret"}, (1, 1),
     "fused_sweep"),
]


@pytest.mark.parametrize("name,kw,mesh_shape,match", RESTRICTION_LEGS,
                         ids=[leg[0] for leg in RESTRICTION_LEGS])
def test_implicit_restrictions(name, kw, mesh_shape, match):
    _rec, op, _H, _g = _case()
    base = dict(max_iterations=5, conv_tolerance=1e-30)
    if "fused_sweep" not in kw:
        base["fused_sweep"] = "off"
    with pytest.raises(SartInputError, match=match):
        DistributedSARTSolver(operator=op, opts=SolverOptions(**base, **kw),
                              mesh=make_mesh(*mesh_shape))


def test_implicit_rejects_laplacian_and_matrix_conflicts():
    from sartsolver_tpu.ops.laplacian import make_laplacian

    _rec, op, H, _g = _case()
    lap = make_laplacian(np.array([0]), np.array([0]),
                         np.array([1.0], np.float32), dtype="float32")
    with pytest.raises(SartInputError, match="beta_laplace"):
        DistributedSARTSolver(operator=op, laplacian=lap, opts=_opts(),
                              mesh=make_mesh(1, 1))
    with pytest.raises(ValueError, match="not both"):
        DistributedSARTSolver(H.astype(np.float32), operator=op,
                              opts=_opts(), mesh=make_mesh(1, 1))


# ---------------------------------------------------------------------------
# engine integration: request admission, session accounting, CLI, serve
# ---------------------------------------------------------------------------

def test_request_carries_validated_geometry():
    from sartsolver_tpu.engine.request import RequestError, parse_request

    req = parse_request({"id": "g1", "geometry": GEO_DICT})
    # stored canonicalized (validated + name-sorted), so the journal's
    # replay rebuilds the identical operator byte-for-byte
    assert req.geometry == _record().to_dict()
    assert req.to_dict()["geometry"] == req.geometry
    bad = json.loads(json.dumps(GEO_DICT))
    bad["version"] = 7
    with pytest.raises(RequestError, match="geometry"):
        parse_request({"id": "g2", "geometry": bad})
    assert parse_request({"id": "p1"}).geometry is None


def _image_files_for(rec, tmp, n_frames=2):
    """Write image files matching the geometry's cameras, frame t scaled
    by (1 + 0.1 t), measurement consistent with the materialized H."""
    H = ImplicitOperator(rec).materialize().astype(np.float64)
    rng = np.random.default_rng(0)
    g = H @ rng.uniform(0.5, 1.5, rec.nvoxel)
    paths, off = [], 0
    for cam in rec.cameras:
        block = g[off:off + cam.npixel]
        frames = [block.reshape(cam.rows, cam.cols) * (1.0 + 0.1 * t)
                  for t in range(n_frames)]
        times = [0.1 + 0.1 * t for t in range(n_frames)]
        p = os.path.join(tmp, f"img_{cam.name}.h5")
        fx._write_image_file(p, cam.name, frames, times)
        paths.append(p)
        off += cam.npixel
    return paths, g


def _geometry_args(paths, geo_path, **kw):
    ns = argparse.Namespace(
        input_files=list(paths), geometry=geo_path, laplacian_file=None,
        logarithmic=False, ray_density_threshold=0.0,
        ray_length_threshold=0.0, conv_tolerance=0.0, beta_laplace=0.0,
        relaxation=1.0, relaxation_decay=1.0, max_iterations=40,
        divergence_recovery=False, integrity=False, os_subsets=1,
        momentum="off", fused_sweep="off", use_cpu=False, rtm_dtype=None,
        sparse_rtm="off", pixel_shards=2, voxel_shards=None,
        max_cached_frames=10, raytransfer_name="with_reflections",
        wavelength_threshold=1.0, batch_frames=None,
    )
    for k, v in kw.items():
        setattr(ns, k, v)
    return ns


def test_geometry_session_accounting_and_batched_parity(tmp_path):
    """ResidentSession.build from a geometry record: the session's cache
    key is the operator's, its byte charge is the ray table (not a
    phantom RTM), and a request attached through the ContinuousBatcher
    solves to dense parity with identical statuses."""
    from sartsolver_tpu.engine.request import parse_request
    from sartsolver_tpu.engine.session import (
        ResidentSession, key_of, session_nbytes,
    )

    rec = _record()
    geo_path = str(tmp_path / "geom.json")
    save_geometry(rec, geo_path)
    paths, g = _image_files_for(rec, str(tmp_path))
    sess = ResidentSession.build(_geometry_args(paths, geo_path))
    try:
        assert session_nbytes(sess) == 432  # 18 rays x 6 x fp32
        assert session_nbytes(sess) < 18 * 64 * 4  # << dense RTM
        key = key_of(sess)
        assert key.startswith("implicit:18x64:float32:")
        assert key.endswith(":2x1")  # mesh shape rides the cache key
        req = parse_request({"id": "r1", "geometry": GEO_DICT})
        image = sess.attach(req)
        assert sess.n_frames(image) == 2

        dense = DistributedSARTSolver(
            ImplicitOperator(rec).materialize().astype(np.float32),
            opts=_opts(), mesh=make_mesh(2, 1))
        results = {}

        def on_result(ftime, _ct, status, iters, _conv, fetcher, _ms):
            results[ftime] = (status, iters, fetcher())

        def on_failed(ftime, _ct, err):
            raise AssertionError(f"frame {ftime} failed: {err}")

        b = ContinuousBatcher(sess.solver, lanes=2, on_result=on_result,
                              on_failed=on_failed)
        b.run(iter(list(sess.frame_items(image, None))))
        assert len(results) == 2
        for t, (status, iters, sol) in sorted(results.items()):
            scale = 1.0 + 0.1 * round((t - 0.1) / 0.1)
            ref = dense.solve(g * scale)
            assert int(status) == int(ref.status)
            assert int(iters) == int(ref.iterations)
            a = np.asarray(sol)[:64]
            bref = np.asarray(ref.solution)[:64]
            assert np.max(np.abs(a - bref)) <= \
                PARITY_RTOL * max(np.max(np.abs(bref)), 1e-12)
        dense.close()
    finally:
        sess.close()


def test_dense_session_accounting_unchanged(tmp_path):
    """The default (matrix-file) session keeps the legacy session_key
    string and the npixel*nvoxel byte estimate — the operator layer must
    not perturb dense serving identity."""
    from sartsolver_tpu.cli import _validate
    from sartsolver_tpu.engine.cli import build_serve_parser
    from sartsolver_tpu.engine.session import (
        ResidentSession, key_of, session_key, session_nbytes,
    )

    paths, *_ = fx.write_world(str(tmp_path), n_frames=2)
    args = build_serve_parser().parse_args([
        "--engine_dir", "/nonexistent-unused", "--use_cpu", "-m", "10",
        paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
        paths["img_a"], paths["img_b"],
    ])
    _validate(args)
    sess = ResidentSession.build(args)
    try:
        assert sess.operator is not None and sess.operator.kind in (
            "dense", "tileskip")
        if sess.operator.kind == "dense":
            dtype = sess.opts.rtm_dtype or sess.opts.dtype
            assert key_of(sess) == session_key(
                sess.npixel, sess.nvoxel, dtype, sess.mesh_shape)
        assert session_nbytes(sess) == sess.operator.resident_nbytes()
        assert session_nbytes(sess) >= \
            sess.npixel * sess.nvoxel * 4  # the full matrix footprint
    finally:
        sess.close()


def test_cli_geometry_end_to_end(tmp_path):
    """One-shot `sartsolve --geometry`: solves image files matrix-free,
    writes the standard solution HDF5, warm-start chain at dense
    parity."""
    from sartsolver_tpu.cli import main

    rec = _record()
    geo_path = str(tmp_path / "geom.json")
    save_geometry(rec, geo_path)
    paths, g = _image_files_for(rec, str(tmp_path))
    out = str(tmp_path / "sol.h5")
    # the CLI requires a positive tolerance; 1e-30 never trips the stall
    # test at 40 iterations on this data, and the parity assertion below
    # compares solutions only (2e-4 dwarfs a +/-1 iteration wobble)
    code = main(["--geometry", geo_path, "-o", out,
                 "--max_iterations", "40", "--conv_tolerance", "1e-30",
                 "--fused_sweep", "off", *paths])
    assert code == 0
    with h5py.File(out, "r") as f:
        sol = f["solution/value"][...]
        times = f["solution/time"][...]
    assert sol.shape == (2, 64)
    np.testing.assert_allclose(times, [0.1, 0.2], atol=1e-9)

    dense = DistributedSARTSolver(
        ImplicitOperator(rec).materialize().astype(np.float32),
        opts=_opts(), mesh=make_mesh(1, 1))
    prev = None
    for i, scale in enumerate((1.0, 1.1)):
        res = dense.solve(g * scale, f0=prev)
        prev = res.solution
        ref = np.asarray(res.solution)[:64]
        assert np.max(np.abs(sol[i] - ref)) <= \
            PARITY_RTOL * max(np.max(np.abs(ref)), 1e-12)
    dense.close()


def test_cli_geometry_rejects_matrix_files(tmp_path):
    """--geometry replaces the RTM files: passing both is a polite input
    error, not a silent preference."""
    from sartsolver_tpu.cli import main

    rec = _record()
    geo_path = str(tmp_path / "geom.json")
    save_geometry(rec, geo_path)
    paths, *_ = fx.write_world(str(tmp_path), n_frames=2)
    out = str(tmp_path / "sol.h5")
    assert main(["--geometry", geo_path, "-o", out,
                 paths["rtm_a1"], paths["img_a"], paths["img_b"]]) == 1
    # and a geometry whose cameras don't match the image files fails
    other = json.loads(json.dumps(GEO_DICT))
    other["cameras"][1]["name"] = "camC"
    other_path = str(tmp_path / "geom2.json")
    with open(other_path, "w") as f:
        json.dump(other, f)
    assert main(["--geometry", other_path, "-o", out,
                 paths["img_a"], paths["img_b"]]) == 1


# ---------------------------------------------------------------------------
# real-process serve + submit --geometry
# ---------------------------------------------------------------------------

def _env():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONUNBUFFERED"] = "1"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("SART_TEST_JOURNAL_DELAY", None)
    env.pop("SART_FAULT", None)
    return env


def test_serve_submit_geometry_attach(tmp_path):
    """THE acceptance drill: a real `sartsolve serve` resident on the
    dense world accepts `submit --geometry`, builds the request its own
    implicit session (432 resident bytes vs the dense session's KBs),
    solves it to completion, and keys it by geometry digest."""
    td = str(tmp_path)
    paths, *_ = fx.write_world(td, n_frames=4)
    eng = os.path.join(td, "eng")
    geo_path = os.path.join(td, "geom.json")
    save_geometry(_record(), geo_path)
    env = _env()
    serve_cmd = [
        sys.executable, "-m", "sartsolver_tpu.cli", "serve",
        "--engine_dir", eng, "--use_cpu", "-m", "40", "-c", "1e-12",
        "--lanes", "2", "--poll_interval", "0.05",
        paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
        paths["img_a"], paths["img_b"],
    ]
    proc = subprocess.Popen(serve_cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    lines = []
    for line in proc.stdout:
        lines.append(line)
        if "session resident" in line:
            break
    else:
        proc.kill()
        raise AssertionError("serve never became resident:\n"
                             + "".join(lines))
    threading.Thread(target=lambda: lines.extend(proc.stdout),
                     daemon=True).start()
    try:
        for rid, extra in (("d1", []), ("g1", ["--geometry", geo_path])):
            done = subprocess.run(
                [sys.executable, "-m", "sartsolver_tpu.cli", "submit",
                 "--engine_dir", eng, "--id", rid, *extra,
                 "--wait", "120"],
                env=env, capture_output=True, text=True, timeout=180)
            assert done.returncode == 0, done.stdout + done.stderr
            rec = json.loads(done.stdout)
            assert rec["outcome"]["status"] == "completed", rec
            assert rec["outcome"]["frames"] == 4
        out = os.path.join(eng, "outputs", "g1.h5")
        with h5py.File(out, "r") as f:
            sol = f["solution/value"][...]
        assert sol.shape[-1] == 64 and np.isfinite(sol).all()
    finally:
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
    assert rc == 4
    text = "".join(lines)
    # the geometry request got its OWN implicit session, charged at ray-
    # table bytes, keyed by the record digest
    assert "operator=implicit" in text
    assert "resident_bytes=432" in text
    assert "session-attach: key=geometry:" in text


# ---------------------------------------------------------------------------
# factored backend: low-rank + sparse H ~= S + U V^T (operators/lowrank.py)
# ---------------------------------------------------------------------------

_LOWRANK_CACHE = {}


def _lowrank_case():
    """A 1024x512 matrix built to factor: a dense random core on the
    first 256 voxel columns (every 8x128 tile there is above the 5%
    threshold) plus a rank-2 low-amplitude floor everywhere (max entry
    ~0.035 * max|H| — below the tile threshold, so the right half of S
    is exactly zero and the residual is exactly the planted factor).
    Cached module-wide: the build runs the rSVD and the 20-iteration
    solve-parity gate once."""
    if "case" not in _LOWRANK_CACHE:
        rng = np.random.default_rng(7)
        P, V, r = 1024, 512, 2
        core = np.zeros((P, V), np.float32)
        core[:, :256] = (rng.random((P, 256)) * 0.9 + 0.1).astype(
            np.float32)
        u_f = (0.003 * rng.standard_normal((P, r))).astype(np.float32)
        v_f = rng.standard_normal((V, r)).astype(np.float32)
        H = core + (u_f @ v_f.T).astype(np.float32)
        op, reason = build_lowrank_operator(H, rank=2)
        assert reason is None and op is not None
        g = (H.astype(np.float64)
             @ rng.uniform(0.5, 1.5, V)).astype(np.float32)
        _LOWRANK_CACHE["case"] = (H, op, g)
    return _LOWRANK_CACHE["case"]


def test_lowrank_operator_identity_and_accounting():
    H, op, _g = _lowrank_case()
    assert op.kind == "lowrank"
    assert op.shape == (1024, 512) and op.rank == 2
    # the core kept whole tiles of H exactly; the floor-only half is
    # exactly zero — the factors carry it instead
    S = op.payload()
    assert S.dtype == np.float32 and S.shape == (1024, 512)
    np.testing.assert_array_equal(S[:, :256], H[:, :256])
    assert (S[:, 256:] == 0.0).all()
    U, V = op.factors()
    assert U.shape == (1024, 2) and V.shape == (512, 2)
    assert U.dtype == np.float32 and V.dtype == np.float32
    # resident bytes: the sparse core plus two skinny factors
    assert op.resident_nbytes() == (1024 * 512 + (1024 + 512) * 2) * 4
    # materialize round-trips H within the Frobenius gate
    M = op.materialize()
    assert np.linalg.norm(M - H) / np.linalg.norm(H) <= DEFAULT_TOL
    np.testing.assert_allclose(M, S + U @ V.T, rtol=1e-6, atol=1e-7)
    # the staged spec skips the factored half: one occupied 256-voxel
    # panel, one skippable
    spec = op.spec()
    assert spec.nvoxel == 512 and spec.panel_voxels == 256
    assert spec.occ_panels == (True, False)
    # cache key pins backend, shapes, dtype, rank and content digest
    key = op.cache_key()
    assert key.startswith("lowrank:1024x512:float32:2:")
    assert key != DenseOperator(H).cache_key()
    H2 = H.copy()
    H2[0, 0] += 0.25
    op2, _ = build_lowrank_operator(H2, rank=2, check_parity=False)
    assert op2.cache_key() != key


def test_lowrank_kernels_match_materialized_matrix():
    """forward/back/ray-stats/subset-density of the composed kernels
    against the fp64 matrix they claim to apply — including the
    statically skipped panel, which must contribute exact zeros."""
    _H, op, _g = _lowrank_case()
    spec = op.spec()
    S = op.payload()
    U, V = op.factors()
    M = S.astype(np.float64) + U.astype(np.float64) @ V.astype(
        np.float64).T
    rng = np.random.default_rng(3)
    f = rng.uniform(0.0, 2.0, 512).astype(np.float32)
    got = np.asarray(lowrank_forward(S, U, V, f, spec))
    np.testing.assert_allclose(got, M @ f.astype(np.float64),
                               rtol=1e-5, atol=1e-4)
    fb = rng.uniform(0.0, 2.0, (3, 512)).astype(np.float32)
    got_b = np.asarray(lowrank_forward(S, U, V, fb, spec))
    np.testing.assert_allclose(got_b, fb.astype(np.float64) @ M.T,
                               rtol=1e-5, atol=1e-4)

    w = rng.uniform(0.0, 1.0, 1024).astype(np.float32)
    got_bp = np.asarray(lowrank_back(S, U, V, w, spec))
    np.testing.assert_allclose(got_bp, M.T @ w.astype(np.float64),
                               rtol=1e-5, atol=1e-4)

    dens, length = lowrank_ray_stats(S, U, V, spec)
    np.testing.assert_allclose(np.asarray(dens), M.sum(axis=0),
                               rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(np.asarray(length), M.sum(axis=1),
                               rtol=1e-5, atol=1e-4)

    # OS subsets: subset t is pixel rows t::os on S and U alike
    sub = np.asarray(lowrank_subset_density(S, U, V, spec, 4))
    want = M.reshape(256, 4, 512).sum(axis=0)
    np.testing.assert_allclose(sub, want, rtol=1e-5, atol=1e-4)


def test_lowrank_rank_determinism():
    """Fixed-seed randomized SVD: two factorizations of the same
    residual are byte-identical, so the operator's cache key — and the
    warm-pool hit it buys — is reproducible across sessions."""
    H, op, _g = _lowrank_case()
    S, _occ = split_sparse_core(H)
    residual = H - S
    U1, V1 = randomized_svd(residual, 2)
    U2, V2 = randomized_svd(residual, 2)
    assert U1.tobytes() == U2.tobytes()
    assert V1.tobytes() == V2.tobytes()
    op2, reason = build_lowrank_operator(H, rank=2, check_parity=False)
    assert reason is None
    assert op2.cache_key() == op.cache_key()
    assert op2.factors()[0].tobytes() == op.factors()[0].tobytes()


LOWRANK_PARITY_LEGS = [
    ("linear", {}, 512),
    # the log leg compares the core-determined voxels only: the right
    # half is constrained by nothing but the rank-2 floor (two
    # constraints for 256 voxels), and log-SART's multiplicative
    # updates amplify fp32 rounding along those null directions — the
    # same drift two dense summation orders show. The determined half
    # agrees to ~6e-7.
    ("log", {"logarithmic": True}, 256),
    ("os", {"os_subsets": 4}, 512),
    ("momentum", {"momentum": "nesterov"}, 512),
]


@pytest.mark.parametrize("name,kw,nvox", LOWRANK_PARITY_LEGS,
                         ids=[n for n, *_ in LOWRANK_PARITY_LEGS])
def test_lowrank_parity_vs_dense(name, kw, nvox):
    """The factored solve against the dense solve of the original H:
    identical statuses and iteration counts, solutions within the
    fused-parity tolerance."""
    H, op, g = _lowrank_case()
    opts = _opts(**kw)
    fac = DistributedSARTSolver(operator=op, opts=opts,
                                mesh=make_mesh(1, 1))
    dense = DistributedSARTSolver(H, opts=opts, mesh=make_mesh(1, 1))
    try:
        for scale in (1.0, 1.3):
            _assert_parity(fac.solve(g * scale),
                           dense.solve(g * scale), nvoxel=nvox)
    finally:
        fac.close()
        dense.close()


def test_lowrank_parity_int8_dequantized_oracle():
    """The int8 factored path quantizes S per voxel and each factor per
    rank component; the in-loop dequant is exact (codes @ (scale * f)).
    So a dense fp32 solver on the DEQUANTIZED staged operator is a
    strict oracle: the int8 composed solve must match it to fp-rounding
    precision, not merely to quantization error (~0.4% here)."""
    H, op, g = _lowrank_case()
    fac = DistributedSARTSolver(
        operator=op, opts=_opts(rtm_dtype="int8"), mesh=make_mesh(1, 1))
    try:
        pr = fac.problem
        codes = np.asarray(pr.rtm, np.float32)
        scale = np.asarray(pr.rtm_scale, np.float32)
        fs = np.asarray(pr.factor_scale, np.float32)
        M_dq = codes * scale[None, :] \
            + (np.asarray(pr.factor_u, np.float32) * fs[0]) \
            @ (np.asarray(pr.factor_v, np.float32) * fs[1]).T
        # sanity: the dequantized operator is H to int8 precision
        assert 1e-4 < np.max(np.abs(M_dq - H)) / np.abs(H).max() < 0.01
        ref = DistributedSARTSolver(M_dq.astype(np.float32),
                                    opts=_opts(), mesh=make_mesh(1, 1))
        try:
            for s in (1.0, 1.3):
                _assert_parity(fac.solve(g * s), ref.solve(g * s),
                               nvoxel=512)
        finally:
            ref.close()
    finally:
        fac.close()


def test_lowrank_parity_pixel_sharded():
    """A (4, 1) pixel-sharded factored solve (U row-sharded with S, V
    replicated, ONE bp psum) against the single-device dense solve,
    single and batched."""
    H, op, g = _lowrank_case()
    fac = DistributedSARTSolver(operator=op, opts=_opts(),
                                mesh=make_mesh(4, 1))
    dense = DistributedSARTSolver(H, opts=_opts(), mesh=make_mesh(1, 1))
    try:
        _assert_parity(fac.solve(g), dense.solve(g), nvoxel=512)
        batch = np.stack([g, g * 1.3])
        got = fac.solve_batch(batch)
        for b, scale in enumerate((1.0, 1.3)):
            ref = dense.solve(g * scale)
            assert int(np.asarray(got.status)[b]) == int(ref.status)
            a = np.asarray(got.solution)[b, :512]
            r = np.asarray(ref.solution)[:512]
            assert np.max(np.abs(a - r)) <= \
                PARITY_RTOL * max(np.max(np.abs(r)), 1e-12)
    finally:
        fac.close()
        dense.close()


def test_lowrank_quality_gate():
    """The gate refuses BEFORE staging: an explicit rank below the
    planted rank fails the Frobenius check loudly, out-of-range and
    non-integer ranks are input errors, and 'auto' on a matrix with no
    sub-threshold tile declines with a reason instead of factoring
    noise."""
    H, _op, _g = _lowrank_case()
    with pytest.raises(SartInputError, match="factorization gate"):
        build_lowrank_operator(H, rank=1)
    with pytest.raises(SartInputError, match="must lie in"):
        build_lowrank_operator(H, rank=0)
    with pytest.raises(SartInputError, match="must lie in"):
        build_lowrank_operator(H, rank=10_000)
    with pytest.raises(SartInputError, match="positive integer"):
        build_lowrank_operator(H, rank="three")
    rng = np.random.default_rng(11)
    flat = (rng.random((64, 128)) * 0.9 + 0.1).astype(np.float32)
    op, reason = build_lowrank_operator(flat, rank="auto")
    assert op is None and "no tile fell below" in reason


def test_lowrank_restrictions_and_int8_admission():
    """Mode restrictions mirror the implicit backend's — EXCEPT int8,
    which the factored path supports (it is the one backend that
    quantizes S and the factors separately)."""
    _H, op, g = _lowrank_case()
    legs = [
        ({}, (1, 2), "voxel"),
        ({"integrity": True}, (1, 1), "integrity"),
        ({"sparse_rtm": "1e-8"}, (1, 1), "tile-thresholds"),
    ]
    for kw, mesh_shape, match in legs:
        base = dict(max_iterations=5, conv_tolerance=1e-30)
        if "fused_sweep" not in kw:
            base["fused_sweep"] = "off"
        with pytest.raises(SartInputError, match=match):
            DistributedSARTSolver(operator=op,
                                  opts=SolverOptions(**base, **kw),
                                  mesh=make_mesh(*mesh_shape))
    # forced Pallas fusion is a CONFIG error once lowrank_rtm rides the
    # options; at the solver layer the operator refuses it directly
    for mode in ("on", "interpret"):
        with pytest.raises(SartInputError, match="fused_sweep"):
            DistributedSARTSolver(
                operator=op,
                opts=SolverOptions(max_iterations=5,
                                   conv_tolerance=1e-30,
                                   fused_sweep=mode),
                mesh=make_mesh(1, 1))
    from sartsolver_tpu.ops.laplacian import make_laplacian
    lap = make_laplacian(np.array([0]), np.array([0]),
                         np.array([1.0], np.float32), dtype="float32")
    with pytest.raises(SartInputError, match="beta_laplace"):
        DistributedSARTSolver(operator=op, laplacian=lap, opts=_opts(),
                              mesh=make_mesh(1, 1))
    with pytest.raises(ValueError, match="not both"):
        DistributedSARTSolver(np.zeros((4, 4), np.float32), operator=op,
                              opts=_opts(), mesh=make_mesh(1, 1))
    # int8 is ADMITTED (contrast test_implicit_restrictions): smoke a
    # short solve to force staging
    s = DistributedSARTSolver(
        operator=op,
        opts=_opts(max_iterations=3, rtm_dtype="int8"),
        mesh=make_mesh(1, 1))
    try:
        assert np.isfinite(np.asarray(s.solve(g).solution)).all()
    finally:
        s.close()


def test_lowrank_static_decline_reason():
    """One shared flag-only decline predicate for the CLI and the
    serving engine — knowable before the whole-matrix read."""
    opts = _opts()
    assert lowrank_static_decline_reason(opts) is None
    assert "multi-process" in lowrank_static_decline_reason(
        opts, process_count=2)
    assert "voxel-sharded" in lowrank_static_decline_reason(
        opts, n_voxel_shards=2)
    assert "checksum" in lowrank_static_decline_reason(
        _opts(integrity=True))
    assert "beta_laplace" in lowrank_static_decline_reason(
        opts, has_laplacian=True)
    # and the config layer refuses contradictory flag pairs outright
    with pytest.raises(ValueError, match="lowrank_rtm"):
        SolverOptions(lowrank_rtm="0")
    with pytest.raises(ValueError, match="factored"):
        SolverOptions(lowrank_rtm="auto", fused_sweep="on")
    with pytest.raises(ValueError, match="sparse_rtm"):
        SolverOptions(lowrank_rtm="auto", sparse_rtm="1e-8")


def test_lowrank_session_build_and_cache_key(tmp_path):
    """`--lowrank_rtm <rank>` through the real CLI arg path: the
    ResidentSession stages a factored operator, keys the warm pool by
    the lowrank cache key, charges factored bytes, and solves the world
    fixture's frames to finite solutions. `--lowrank_rtm auto` on the
    same dense-as-it-gets fixture declines LOUDLY and falls back to the
    materialized path."""
    from sartsolver_tpu.cli import _validate, build_parser
    from sartsolver_tpu.engine.session import (
        ResidentSession, key_of, session_nbytes,
    )

    paths, H, f_true, _times, _scales = fx.write_world(
        str(tmp_path), n_frames=2)
    inputs = [paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
              paths["img_a"], paths["img_b"]]
    args = build_parser().parse_args([
        *inputs, "--max_iterations", "10", "--conv_tolerance", "1e-12",
        "--fused_sweep", "off", "--pixel_shards", "1",
        "--lowrank_rtm", "14"])
    _validate(args)
    sess = ResidentSession.build(args)
    try:
        assert sess.operator.kind == "lowrank"
        key = key_of(sess)
        assert key.startswith("lowrank:14x16:float32:14:")
        assert key.endswith(":1x1")
        assert key == sess.operator.cache_key() + ":1x1"
        assert session_nbytes(sess) == \
            sess.operator.resident_nbytes() == (14 * 16 + 30 * 14) * 4
        res = sess.solver.solve(np.asarray(H @ f_true, np.float32))
        assert np.isfinite(np.asarray(res.solution)).all()
    finally:
        sess.close()

    args = build_parser().parse_args([
        *inputs, "--max_iterations", "10", "--conv_tolerance", "1e-12",
        "--fused_sweep", "off", "--pixel_shards", "1",
        "--lowrank_rtm", "auto"])
    _validate(args)
    sess = ResidentSession.build(args)
    try:
        assert sess.operator is None or sess.operator.kind != "lowrank"
    finally:
        sess.close()
