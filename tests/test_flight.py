"""Performance observatory: live introspection + roofline accounting
(sartsolver_tpu/obs/flight.py, obs/roofline.py, docs/OBSERVABILITY.md
§8-§9).

Drills the gap between the per-frame heartbeat and the post-mortem
artifact: the SIGUSR1 status snapshot (through the real CLI, poked from
outside while an injected hang holds the run open), the crash bundle on
the abnormal exit paths (watchdog abort, SDC quarantine, exit-4 stop,
and the stage-3 ``os._exit`` that only the crash hook survives), the
``sartsolve top`` renderer, and the roofline utilization math that
``bench.py`` and the cost goldens share.

``make flight`` runs exactly this module.
"""

import json
import os
import signal
import subprocess
import sys
import time

import h5py
import numpy as np
import pytest

import fixtures as fx
from sartsolver_tpu.cli import main
from sartsolver_tpu.obs import flight, metrics, roofline, schema
from sartsolver_tpu.obs.cli import metrics_main, render_top, top_main
from sartsolver_tpu.resilience import faults, shutdown, watchdog
from sartsolver_tpu.resilience.failures import (
    EXIT_INFRASTRUCTURE,
    EXIT_INTERRUPTED,
    EXIT_PARTIAL,
    RunSummary,
)
from sartsolver_tpu.resilience.retry import reset_retry_stats

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """Fresh faults/taps/providers, fast retries, bounded hang release,
    no introspection paths leaking between tests."""
    monkeypatch.setenv("SART_RETRY_BASE_DELAY", "0.001")
    monkeypatch.setenv("SART_RETRY_MAX_DELAY", "0.002")
    monkeypatch.setenv("SART_HANG_RELEASE", "60")
    for var in ("SART_FAULT", "SART_STATUS_FILE", "SART_FLIGHT_BUNDLE",
                "SART_FLIGHT_EVENTS", "SART_HEARTBEAT_FILE",
                "SART_WATCHDOG_TIMEOUT", "SART_PEAK_MXU_TFLOPS",
                "SART_PEAK_HBM_GBS"):
        monkeypatch.delenv(var, raising=False)
    faults.clear_faults()
    reset_retry_stats()
    yield
    faults.clear_faults()
    reset_retry_stats()
    flight.uninstall()
    watchdog.set_sched_status_provider(None)
    watchdog.set_crash_hook(None)


@pytest.fixture
def world(tmp_path):
    return fx.write_world(tmp_path, with_laplacian=True)


def run_cli(paths, *extra):
    return main([
        "-o", paths["output"],
        paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
        paths["img_a"], paths["img_b"],
        "--use_cpu", "-m", "300", "-c", "1e-6",
        *extra,
    ])


def _arm_watchdog(monkeypatch, timeout="2", grace="60"):
    monkeypatch.setenv("SART_WATCHDOG_TIMEOUT", timeout)
    monkeypatch.setenv("SART_WATCHDOG_GRACE", grace)


# ---------------------------------------------------------------------------
# roofline accounting (obs/roofline.py)
# ---------------------------------------------------------------------------

def test_device_peaks_table_and_overrides(monkeypatch):
    v5e = roofline.device_peaks("tpu", "TPU v5 lite", ndev=4)
    assert v5e["per_device_hbm_gbs"] == 819.0
    assert v5e["hbm_bytes_s"] == 819.0e9 * 4
    assert v5e["source"].startswith("table:")
    cpu = roofline.device_peaks("cpu", "cpu")
    assert cpu["source"] == "cpu"
    unknown = roofline.device_peaks("tpu", "TPU v99")
    assert unknown["source"] == "default"
    monkeypatch.setenv("SART_PEAK_MXU_TFLOPS", "100")
    monkeypatch.setenv("SART_PEAK_HBM_GBS", "1000")
    over = roofline.device_peaks("tpu", "TPU v5 lite", ndev=2)
    assert over["source"] == "env"
    assert over["mxu_flops_s"] == 100e12 * 2
    assert over["hbm_bytes_s"] == 1000e9 * 2


def test_utilization_math_and_bound():
    peaks = {"mxu_flops_s": 1e12, "hbm_bytes_s": 1e11,
             "per_device_tflops": 1.0, "per_device_hbm_gbs": 100.0,
             "ndev": 1, "source": "test"}
    # 1e9 FLOP + 1e9 bytes at 50 iter/s: 5% of the MXU, 50% of HBM —
    # intensity 1 flop/byte, ridge 10 -> HBM-bound
    u = roofline.utilization(1e9, 1e9, 50.0, peaks)
    assert u["mxu_util"] == pytest.approx(0.05)
    assert u["hbm_util"] == pytest.approx(0.5)
    assert u["arithmetic_intensity"] == pytest.approx(1.0)
    assert u["ridge_intensity"] == pytest.approx(10.0)
    assert u["bound"] == "hbm"
    # 100 flops/byte is above the ridge: the MXU is the wall
    assert roofline.utilization(1e11, 1e9, 1.0, peaks)["bound"] == "mxu"


def test_sweep_cost_model_scales_with_reads():
    # the fused sweep reads the RTM once per iteration, the two-matmul
    # path twice: same FLOPs, ~half the bytes
    P, V, B = 1000, 2000, 4
    flops1, bytes1 = roofline.sweep_cost_model(P, V, B, 4, reads=1)
    flops2, bytes2 = roofline.sweep_cost_model(P, V, B, 4, reads=2)
    assert flops1 == flops2 == 4.0 * B * P * V
    assert bytes2 - bytes1 == P * V * 4
    # int8 storage quarters the dominant term
    _, bytes_i8 = roofline.sweep_cost_model(P, V, B, 1, reads=1)
    assert bytes_i8 < bytes1 / 2


def test_compiled_cost_numbers_tolerant_extraction():
    jax = pytest.importorskip("jax")
    compiled = jax.jit(lambda x: x @ x).lower(
        np.ones((16, 16), np.float32)).compile()
    out = roofline.compiled_cost_numbers(compiled)
    # CPU jaxlib implements both halves; every figure lands non-null
    assert out["argument_bytes"] == 16 * 16 * 4
    assert out["output_bytes"] == 16 * 16 * 4
    assert out["flops"] and out["flops"] >= 2 * 16 ** 3 * 0.5
    # and nothing blows up on an object with neither API
    class _Bare:
        pass
    bare = roofline.compiled_cost_numbers(_Bare())
    assert all(v is None for v in bare.values())


# ---------------------------------------------------------------------------
# flight ring + status snapshot
# ---------------------------------------------------------------------------

def test_flight_ring_is_bounded():
    rec = flight.FlightRecorder(max_events=4)
    for i in range(10):
        rec.record("event", i=i)
    tail = rec.snapshot()
    assert len(tail) == 4
    assert [e["i"] for e in tail] == [6, 7, 8, 9]  # newest kept
    assert rec.total == 10


def test_flight_ring_taps_beacons():
    rec = flight.install(flight.FlightRecorder(max_events=16))
    try:
        watchdog.beacon("solve.dispatch")
        flight.record_event("event", "ladder engaged")
    finally:
        flight.uninstall()
    kinds = [e["kind"] for e in rec.snapshot()]
    assert "beacon" in kinds and "event" in kinds
    beacon = next(e for e in rec.snapshot() if e["kind"] == "beacon")
    assert beacon["phase"] == "solve.dispatch"
    # uninstalled: no longer fed
    n = rec.total
    watchdog.beacon("solve.dispatch")
    assert rec.total == n


def test_status_snapshot_validates_and_carries_sched(tmp_path):
    watchdog.beacon("solve.dispatch")
    watchdog.set_sched_status_provider(
        lambda: {"occupancy": 0.5, "lanes": [1], "strides": 3}
    )
    try:
        rec = flight.write_status(str(tmp_path / "s.json"))
    finally:
        watchdog.set_sched_status_provider(None)
    assert rec["type"] == "status"
    assert schema.validate_record(rec) == []
    assert rec["sched"]["occupancy"] == 0.5
    assert rec["last_beacon"]["phase"] == "solve.dispatch"
    assert rec["beacon_ages"]["solve.dispatch"] >= 0
    on_disk = json.load(open(tmp_path / "s.json"))
    assert on_disk == rec
    # the snapshot file passes `sartsolve metrics --check`
    assert metrics_main(["--check", str(tmp_path / "s.json")]) == 0


def test_sigusr1_handler_in_process(tmp_path, capsys):
    path = str(tmp_path / "status.json")
    prev = flight.install_status_handler(path)
    try:
        os.kill(os.getpid(), signal.SIGUSR1)
        deadline = time.time() + 5
        while not os.path.exists(path) and time.time() < deadline:
            time.sleep(0.02)
    finally:
        flight.uninstall_status_handler(prev)
    rec = json.load(open(path))
    assert rec["type"] == "status" and schema.validate_record(rec) == []
    assert "sartsolve status:" in capsys.readouterr().err


def test_crash_bundle_roundtrip(tmp_path):
    flight.install(flight.FlightRecorder(max_events=8))
    watchdog.beacon("ingest.rtm")
    summary = RunSummary()
    summary.record_status(0, 1.5)
    summary.record_status(-3, 2.5)
    path = str(tmp_path / "crash.json")
    assert flight.write_crash_bundle(path, "watchdog abort: test",
                                     summary) is True
    rec = json.load(open(path))
    assert rec["type"] == "flight"
    assert schema.validate_record(rec) == []
    assert rec["reason"] == "watchdog abort: test"
    assert any(e["kind"] == "beacon" for e in rec["ring"])
    assert rec["partial"]["frames"] == 2
    assert rec["partial"]["by_status"]["failed"] == 1
    assert rec["partial"]["failed_times"] == [2.5]
    assert metrics_main(["--check", path]) == 0
    # a failed write is a False, never a raise (abort paths call this)
    assert flight.write_crash_bundle(
        str(tmp_path / "no/such/dir/x.json"), "r") is False


# ---------------------------------------------------------------------------
# crash bundles through the real CLI
# ---------------------------------------------------------------------------

def _read_bundle(paths):
    path = paths["output"] + ".crash.json"
    assert os.path.exists(path), "crash bundle missing"
    rec = json.load(open(path))
    assert rec["type"] == "flight"
    assert schema.validate_record(rec) == []
    return rec


def test_cli_watchdog_abort_writes_crash_bundle(world, monkeypatch,
                                                capsys):
    """Abnormal-exit leg 1: a hang before the frame loop exists (the
    Laplacian staging device.put) is interrupted by the watchdog and
    aborts exit 3 — and the flight bundle lands next to the output with
    the abort reason and the ring's beacon tail."""
    paths, *_ = world
    _arm_watchdog(monkeypatch)
    faults.inject(faults.SITE_DEVICE_PUT, "hang", count=1)
    rc = run_cli(paths, "-l", paths["laplacian"], "-b", "0.001")
    assert rc == EXIT_INFRASTRUCTURE
    assert "crash bundle written" in capsys.readouterr().err
    rec = _read_bundle(paths)
    assert rec["reason"].startswith("watchdog abort:")
    assert any(e["kind"] == "beacon" for e in rec["ring"])
    assert rec["status"]["frames_done"] >= 0


def test_cli_quarantine_writes_crash_bundle(world, monkeypatch, capsys):
    """Abnormal-exit leg 2: an SDC quarantine (resident corruption the
    recompute reproduces) exits 3 with a bundle whose partial accounting
    shows the terminal frames an operator must distrust."""
    paths, *_ = world
    monkeypatch.setenv("SART_FAULT", "device.buffer:corrupt:1:1")
    faults.reset()
    rc = run_cli(paths, "--integrity")
    assert rc == EXIT_INFRASTRUCTURE
    assert "Quarantined" in capsys.readouterr().err
    rec = _read_bundle(paths)
    assert rec["reason"].startswith("SDC quarantine:")
    assert rec["partial"]["by_status"].get("failed", 0) >= 1


def test_cli_stop_writes_crash_bundle(world, monkeypatch, capsys):
    """Abnormal-exit leg 3: a graceful stop that truncated the run
    (exit 4) records where it stopped — triage before the requeue."""
    paths, *_ = world
    monkeypatch.setattr(shutdown, "stop_requested", lambda: True)
    rc = run_cli(paths)
    assert rc == EXIT_INTERRUPTED
    assert "resumable" in capsys.readouterr().err
    rec = _read_bundle(paths)
    assert rec["reason"].startswith("interrupted by")


def test_cli_clean_run_writes_no_introspection_files(world):
    """Disabled-path identity: a healthy, unsignaled run leaves no
    status file and no crash bundle behind."""
    paths, *_ = world
    assert run_cli(paths) == 0
    assert not os.path.exists(paths["output"] + ".crash.json")
    assert not os.path.exists(paths["output"] + ".status.json")


def test_crash_hook_survives_hard_abort_in_subprocess(tmp_path):
    """The stage-3 ``os._exit(3)`` skips every finally block — the
    watchdog's crash hook is the bundle's only chance, and it must land
    before the process dies."""
    bundle = str(tmp_path / "hard.crash.json")
    code = (
        "import time\n"
        "from sartsolver_tpu.obs import flight\n"
        "from sartsolver_tpu.resilience import watchdog\n"
        "flight.install()\n"
        "watchdog.set_crash_hook(\n"
        f"    lambda reason: flight.write_crash_bundle({bundle!r}, reason))\n"
        "watchdog.beacon('ingest.rtm')\n"
        "wd = watchdog.Watchdog(timeout=0.3, grace=0.3, poll=0.05)\n"
        "wd.start()\n"
        "time.sleep(60)\n"  # C-level stall: only the hard abort ends it
        "print('unreachable')\n"
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True,
        text=True, timeout=120,
    )
    assert proc.returncode == EXIT_INFRASTRUCTURE
    assert "unreachable" not in proc.stdout
    assert "crash bundle written" in proc.stderr
    rec = json.load(open(bundle))
    assert rec["type"] == "flight"
    assert "watchdog hard abort" in rec["reason"]
    assert any(e.get("phase") == "ingest.rtm" for e in rec["ring"])


def test_cli_sigusr1_snapshot_through_real_cli(world, tmp_path):
    """The headline drill: poke a LIVE run (held open by an injected
    hang at solve.dispatch) with ``kill -USR1`` from outside and read
    the snapshot it publishes — no restart, no extra flags."""
    paths, *_ = world
    status = str(tmp_path / "live.status.json")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["SART_FAULT"] = "solve.dispatch:hang:1:1"
    env["SART_HANG_RELEASE"] = "45"
    env["SART_WATCHDOG_TIMEOUT"] = "8"
    env["SART_WATCHDOG_GRACE"] = "120"
    env["SART_STATUS_FILE"] = status
    # Pre-ignore SIGUSR1 so signals sent before the CLI installs its
    # handler are harmless (the default action would kill the child),
    # and announce readiness — a signal during interpreter startup,
    # before even SIG_IGN is in place, would still be fatal. main() then
    # replaces SIG_IGN with the real snapshot handler.
    ready = str(tmp_path / "ready")
    wrapper = (
        "import signal, sys\n"
        "signal.signal(signal.SIGUSR1, signal.SIG_IGN)\n"
        f"open({ready!r}, 'w').write('ready')\n"
        "from sartsolver_tpu.cli import main\n"
        "sys.exit(main(sys.argv[1:]))\n"
    )
    cmd = [
        sys.executable, "-c", wrapper, "-o", paths["output"],
        paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
        paths["img_a"], paths["img_b"],
        "--use_cpu", "-m", "40", "-c", "1e-12",
    ]
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.DEVNULL,
                            stderr=subprocess.PIPE, text=True)
    got_snapshot = False
    try:
        deadline = time.time() + 120
        while time.time() < deadline and proc.poll() is None:
            if os.path.exists(ready):
                break
            time.sleep(0.05)
        # poke until a snapshot lands: the injected hang holds the first
        # solve open for the watchdog's 8 s, so the live window is wide
        while time.time() < deadline and proc.poll() is None:
            proc.send_signal(signal.SIGUSR1)
            time.sleep(0.25)
            if os.path.exists(status):
                got_snapshot = True
                break
        _, stderr = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    assert got_snapshot, f"no snapshot appeared; stderr:\n{stderr}"
    rec = json.load(open(status))
    assert rec["type"] == "status"
    assert schema.validate_record(rec) == []
    assert rec["pid"] == proc.pid
    assert "sartsolve status:" in stderr
    # the hung frame failed through the watchdog, the rest solved
    assert proc.returncode == EXIT_PARTIAL


# ---------------------------------------------------------------------------
# `sartsolve top`
# ---------------------------------------------------------------------------

def test_top_renders_status_snapshot(tmp_path, capsys):
    path = str(tmp_path / "s.json")
    watchdog.beacon("solve.dispatch")
    watchdog.set_sched_status_provider(
        lambda: {"occupancy": 0.75, "lanes": [2, 5], "strides": 9}
    )
    try:
        flight.write_status(path)
    finally:
        watchdog.set_sched_status_provider(None)
    assert main(["top", path, "--once"]) == 0
    out = capsys.readouterr().out
    assert "frames_done" in out
    assert "solve.dispatch" in out
    assert "occupancy 0.75" in out
    assert "2,5" in out


def test_top_renders_heartbeat_and_prom(tmp_path, capsys):
    hb = tmp_path / "hb"
    hb.write_text("phase=solve.dispatch frames=7 serial=21 "
                  "occupancy=0.875 lanes=1,3 unix=1.5\n")
    assert top_main([str(hb), "--once"]) == 0
    out = capsys.readouterr().out
    assert "solve.dispatch" in out and "0.875" in out
    r = metrics.MetricsRegistry()
    r.counter("frames_total", status="converged").inc(4)
    from sartsolver_tpu.obs import sinks
    prom = tmp_path / "run.prom"
    prom.write_text(sinks.render_prometheus(r.snapshot()))
    assert top_main([str(prom), "--once"]) == 0
    out = capsys.readouterr().out
    assert "sart_frames_total" in out and "4" in out


def test_top_unrecognized_and_missing_paths_fail_in_once_mode(
        tmp_path, capsys):
    """--once is the scripting probe: a screen that could not render
    (missing file, garbage content) must exit 1, not report healthy."""
    junk = tmp_path / "junk"
    junk.write_text("what even is this\n")
    assert top_main([str(junk), "--once"]) == 1
    assert "unrecognized" in capsys.readouterr().out
    assert top_main([str(tmp_path / "gone"), "--once"]) == 1
    assert "gone" in capsys.readouterr().out


def test_top_caps_body_lines(tmp_path):
    r = metrics.MetricsRegistry()
    for i in range(50):
        r.gauge(f"g{i:02d}").set(i)
    from sartsolver_tpu.obs import sinks
    prom = tmp_path / "big.prom"
    prom.write_text(sinks.render_prometheus(r.snapshot()))
    screen = render_top(str(prom), max_lines=10)
    lines = screen.splitlines()
    assert len(lines) == 11  # 10 + the "+N more" marker
    assert "more" in lines[-1]
