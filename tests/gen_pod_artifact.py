"""Generate real --metrics_out artifacts for the pod fault-tolerance
counters (docs/RESILIENCE.md §11).

Used by ``make bench-smoke``: both artifacts come from the actual CLI,
not hand-built records —

* ``argv[2]`` (resume artifact): a run is SIGKILLed inside the held-open
  checkpoint-append window, then resumed to completion; the completed
  run's artifact must account ``solve_ckpt_written_total`` and
  ``solve_ckpt_resumed_total``.
* ``argv[3]`` (barrier artifact): a lone fake-pod host (its peer never
  launches) is released by the pod-barrier deadline and exits
  EXIT_INFRASTRUCTURE(3); the abort path still finalizes the artifact,
  which must account ``pod_barrier_timeouts_total``.

World files land under ``argv[1]``. Exits non-zero when either pass
misbehaves (wrong exit code, kill window never reached).
"""

import os
import signal
import subprocess
import sys
import threading

_here = os.path.dirname(os.path.abspath(__file__))
_repo = os.path.dirname(_here)
sys.path.insert(0, _here)  # fixtures.py

import fixtures as fx  # noqa: E402

N_FRAMES = 10


def _env(extra=None):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    for key in [k for k in env if k.startswith(("SART_POD", "SART_FAULT",
                                                "SART_TEST", "SART_SOLVE"))]:
        env.pop(key)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = _repo + os.pathsep + env.get("PYTHONPATH", "")
    env["PYTHONUNBUFFERED"] = "1"
    env.update(extra or {})
    return env


def _cmd(paths, outfile, *extra):
    return [
        sys.executable, "-m", "sartsolver_tpu.cli", "-o", outfile,
        paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
        paths["img_a"], paths["img_b"],
        "--use_cpu", "-m", "40", "-c", "1e-12",
        "-l", paths["laplacian"], "-b", "0.001",
        "--max_cached_solutions", "1", "--no_guess",
        "--batch_frames", "4",
        *extra,
    ]


def _kill_at_marker(cmd, env, marker, timeout=300):
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.DEVNULL,
                            stderr=subprocess.PIPE, text=True)
    watchdog = threading.Timer(timeout, proc.kill)
    watchdog.start()
    try:
        for line in proc.stderr:
            if line.strip() == marker:
                proc.kill()
                break
        else:
            raise SystemExit(f"gen_pod_artifact: run ended before "
                             f"marker {marker!r}")
        proc.stderr.read()
    finally:
        watchdog.cancel()
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=60)
    if proc.returncode != -signal.SIGKILL:
        raise SystemExit(f"gen_pod_artifact: kill pass exited "
                         f"{proc.returncode}, expected SIGKILL")


def run(world_dir: str, resume_artifact: str, barrier_artifact: str) -> int:
    import pathlib

    paths, *_ = fx.write_world(pathlib.Path(world_dir),
                               with_laplacian=True, n_frames=N_FRAMES)

    # pass 1: kill inside the serial-2 append window (stride 1 makes
    # serial 1 durable first), then resume with the JSONL sink armed
    out = os.path.join(world_dir, "pod_metrics.h5")
    kill_env = _env({"SART_TEST_POD_MARKERS": "1",
                     "SART_TEST_SOLVE_CKPT_DELAY": "0.6"})
    _kill_at_marker(_cmd(paths, out, "--solve_ckpt_stride", "1"),
                    kill_env, "SART_SOLVE_CKPT_POINT pre-append serial=2")
    done = subprocess.run(
        _cmd(paths, out, "--solve_ckpt_stride", "1", "--resume",
             "--metrics_out", resume_artifact),
        env=kill_env, timeout=600, stdout=subprocess.DEVNULL)
    if done.returncode != 0:
        raise SystemExit(f"gen_pod_artifact: resume pass exited "
                         f"{done.returncode}")

    # pass 2: a lone fake-pod host whose peer never arrives — the
    # barrier deadline must release it with exit 3, and the abort path
    # must still finalize the artifact
    bdir = os.path.join(world_dir, "lone_barrier")
    os.makedirs(bdir)
    lone = subprocess.run(
        _cmd(paths, os.path.join(world_dir, "pod_lone.h5"),
             "--solve_ckpt_stride", "2", "--metrics_out",
             barrier_artifact),
        env=_env({"SART_POD_PROCESS": "0/2",
                  "SART_POD_BARRIER_DIR": bdir,
                  "SART_POD_BARRIER_TIMEOUT": "2"}),
        timeout=600, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    if lone.returncode != 3:
        raise SystemExit(f"gen_pod_artifact: lone-host pass exited "
                         f"{lone.returncode}, expected 3")
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1], sys.argv[2], sys.argv[3]))
