"""Self-healing service matrix (docs/SERVING.md §9; `make chaos`).

Units: checkpoint store round trip + torn-tail/corruption property
(random state -> write -> truncate -> restore equals the last
consistent state), crash-loop breaker schedule, backoff/exit
classification, admission state export/restore across the wall clock,
journal completed-id compaction, retry_after hints, the
/healthz-liveness vs /readyz-readiness split, and the retention sweep.

End-to-end: quarantine + SLO burn survive an engine restart through the
state checkpoint (in-process, fresh registry per incarnation); the
restart-storm drill drives a REAL `sartsolve serve --supervised` whose
worker crash-loops on schedule — the breaker opens (lame duck: healthz
503 + machine-readable crash-loop rejections with retry hints), clears
when the window passes, and the next worker serves; `submit --retry`
honors the hint against a real lame-duck engine.
"""

import json
import os
import re
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import fixtures as fx

from sartsolver_tpu.engine import admission as adm_mod
from sartsolver_tpu.engine import request as req_mod
from sartsolver_tpu.engine import state as state_mod
from sartsolver_tpu.engine.journal import RequestJournal
from sartsolver_tpu.engine.request import parse_request
from sartsolver_tpu.obs import metrics as obs_metrics
from sartsolver_tpu.resilience import faults
from sartsolver_tpu.resilience.supervisor import (
    CrashLoopBreaker,
    classify_exit,
    restart_backoff,
)

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)

SOLVE_FLAGS = ["--use_cpu", "-m", "40", "-c", "1e-12"]


# ---------------------------------------------------------------------------
# checkpoint store
# ---------------------------------------------------------------------------

def _random_state(rng):
    tenants = {
        f"t{i}": {"failures": int(rng.integers(0, 5)),
                  "quarantined_unix": float(rng.uniform(0, 2e9))}
        for i in range(int(rng.integers(0, 4)))
    }
    return {
        "lanes": int(rng.integers(1, 9)),
        "admission": {
            "tenants": tenants,
            "seen_ids": [f"id{int(j)}"
                         for j in rng.integers(0, 1000, size=5)],
            "degraded_reason": (None if rng.random() < 0.5
                                else "device OOM"),
        },
        "metrics": [
            {"kind": "counter", "name": "engine_slo_ok_total",
             "labels": {"tenant": "a"},
             "value": float(rng.integers(0, 100))},
        ],
    }


def test_checkpoint_torn_tail_property(tmp_path):
    """Random state -> write -> truncate the tail at EVERY byte offset
    inside the last record -> restore equals the last state whose
    record survived intact (ISSUE satellite). No offset may ever
    restore garbage or raise."""
    rng = np.random.default_rng(42)
    path = str(tmp_path / "state.jsonl")
    store = state_mod.StateStore(path)
    states = [_random_state(rng) for _ in range(3)]
    offsets = [0]
    for st in states:
        store.save(st)
        offsets.append(os.path.getsize(path))
    blob = open(path, "rb").read()
    # stride through every truncation point of the final record (and a
    # few inside earlier ones) — restore must equal the last record
    # that remains complete
    for cut in list(range(offsets[2], offsets[3] + 1, 7)) + [
            offsets[1] + 3, offsets[2] - 1]:
        with open(path, "wb") as f:
            f.write(blob[:cut])
        got = state_mod.StateStore(path).load()
        # a record is durable once its JSON bytes are all down — the
        # trailing newline is framing, not payload
        intact = [i for i in range(3) if offsets[i + 1] - 1 <= cut]
        want = states[intact[-1]] if intact else None
        assert got == want, f"cut at {cut}"
    # a flipped byte mid-file invalidates only that record
    with open(path, "wb") as f:
        f.write(blob)
    flip = offsets[2] + (offsets[3] - offsets[2]) // 2
    corrupted = bytearray(blob)
    # flip inside the last record's state payload digits
    corrupted[flip] = ord("9") if corrupted[flip] != ord("9") else ord("8")
    with open(path, "wb") as f:
        f.write(bytes(corrupted))
    got = state_mod.StateStore(path).load()
    assert got in (states[1], states[2])  # never garbage, never None


def test_checkpoint_compaction_and_serial(tmp_path):
    store = state_mod.StateStore(str(tmp_path / "s.jsonl"))
    for i in range(10):
        store.save({"i": i})
    size_before = store.size()
    store.compact()
    assert store.size() < size_before
    assert len(open(store.path).readlines()) == 1
    fresh = state_mod.StateStore(store.path)
    assert fresh.load() == {"i": 9}
    assert fresh.serial == 10  # serial survives compaction
    fresh.save({"i": 10})
    assert state_mod.StateStore(store.path).load() == {"i": 10}


def test_checkpoint_fault_site_retries(tmp_path, monkeypatch):
    monkeypatch.setenv("SART_RETRY_BASE_DELAY", "0.01")
    store = state_mod.StateStore(str(tmp_path / "s.jsonl"))
    with faults.injected(faults.SITE_STATE_CHECKPOINT, "io", 1.0,
                         count=2):
        store.save({"ok": True})
    assert store.load() == {"ok": True}


def test_metrics_capture_restore_merge():
    obs_metrics.reset_registry()
    reg = obs_metrics.get_registry()
    reg.counter("engine_slo_ok_total", tenant="a").inc(3)
    reg.histogram("engine_queue_wait_s").observe(0.5)
    reg.gauge("engine_queue_depth").set(7)  # gauges NOT carried
    reg.counter("frames_total").inc()  # non-engine families NOT carried
    snap = state_mod.capture_metrics(reg)
    names = {s["name"] for s in snap}
    assert names == {"engine_slo_ok_total", "engine_queue_wait_s"}
    fresh = obs_metrics.reset_registry()
    fresh.counter("engine_slo_ok_total", tenant="a").inc(2)
    state_mod.restore_metrics(fresh, snap)
    assert fresh.counter("engine_slo_ok_total", tenant="a").value == 5
    assert fresh.histogram("engine_queue_wait_s").count == 1


# ---------------------------------------------------------------------------
# breaker / backoff / exit classification
# ---------------------------------------------------------------------------

def test_crash_loop_breaker_opens_and_clears_on_schedule():
    b = CrashLoopBreaker(threshold=3, window_s=10.0)
    b.record(0.0)
    b.record(2.0)
    assert not b.open(2.0)
    b.record(4.0)
    assert b.open(4.0)
    # clears exactly when the crash holding the count at threshold ages
    # out of the window (the first one here)
    assert b.remaining_s(4.0) == pytest.approx(6.0)
    assert b.open(9.9)
    assert not b.open(10.1)
    assert b.remaining_s(10.1) == 0.0


def test_restart_backoff_bounded():
    assert restart_backoff(1, 1.0, 30.0) == 1.0
    assert restart_backoff(4, 1.0, 30.0) == 8.0
    assert restart_backoff(20, 1.0, 30.0) == 30.0  # capped
    assert restart_backoff(0, 1.0, 30.0) == 0.0


def test_classify_exit_vocabulary():
    assert classify_exit(-signal.SIGKILL) == "signal:SIGKILL"
    assert classify_exit(-signal.SIGSEGV) == "signal:SIGSEGV"
    assert classify_exit(3) == "infrastructure"
    assert classify_exit(7) == "exit:7"


# ---------------------------------------------------------------------------
# admission state export/restore
# ---------------------------------------------------------------------------

def test_admission_state_roundtrip_quarantine_wall_clock():
    """A quarantined tenant exported at T stays quarantined in a fresh
    controller for the REMAINING cooldown — downtime between crash and
    restart counts against it (wall-clock deadlines)."""
    obs_metrics.reset_registry()
    clock = {"t": 100.0}
    adm = adm_mod.AdmissionController(
        max_queue=8, quarantine_after=1, quarantine_cooldown=50.0,
        clock=lambda: clock["t"],
    )
    r = parse_request({"id": "q1", "tenant": "noisy"})
    assert adm.admit(r) is None
    adm.note_dispatched(r)
    adm.note_outcome(r, req_mod.REQ_FAILED)
    assert adm.quarantined_tenants() == ["noisy"]
    assert adm.quarantine_left_s("noisy") == pytest.approx(50.0)
    state = adm.export_state()
    assert "q1" in state["seen_ids"]
    # fresh controller (fresh monotonic origin), restored
    clock2 = {"t": 7.0}
    adm2 = adm_mod.AdmissionController(
        max_queue=8, quarantine_after=1,
        clock=lambda: clock2["t"],
    )
    adm2.restore_state(state)
    assert adm2.quarantined_tenants() == ["noisy"]
    assert adm2.admit(parse_request({"id": "q2", "tenant": "noisy"})) \
        == req_mod.REASON_TENANT_QUARANTINED
    # the dedup watermark survived too
    assert adm2.admit(parse_request({"id": "q1", "tenant": "calm"})) \
        == req_mod.REASON_DUPLICATE
    # cooldown expiry readmits (the restored deadline, not a fresh one)
    clock2["t"] = 7.0 + 51.0
    assert adm2.admit(parse_request({"id": "q3", "tenant": "noisy"})) \
        is None


def test_admission_state_streak_survives():
    obs_metrics.reset_registry()
    adm = adm_mod.AdmissionController(max_queue=8, quarantine_after=3)
    for i in range(2):
        r = parse_request({"id": f"f{i}", "tenant": "shaky"})
        adm.admit(r)
        adm.note_dispatched(r)
        adm.note_outcome(r, req_mod.REQ_FAILED)
    adm2 = adm_mod.AdmissionController(max_queue=8, quarantine_after=3)
    adm2.restore_state(adm.export_state())
    # one more failure in the NEW incarnation completes the streak
    r = parse_request({"id": "f2", "tenant": "shaky"})
    adm2.admit(r)
    adm2.note_dispatched(r)
    adm2.note_outcome(r, req_mod.REQ_FAILED)
    assert adm2.quarantined_tenants() == ["shaky"]


# ---------------------------------------------------------------------------
# journal compaction
# ---------------------------------------------------------------------------

def test_journal_compaction_drops_completed_keeps_pending(tmp_path):
    j = RequestJournal(str(tmp_path / "j.jsonl"))
    done = parse_request({"id": "done", "tenant": "a"})
    run1 = parse_request({"id": "run1", "tenant": "b", "deadline_s": 9})
    run2 = parse_request({"id": "run2", "tenant": "b", "trace": "tr-2"})
    j.accepted(done)
    j.dispatched(done)
    j.completed(done, {"status": "completed"})
    j.accepted(run1)
    j.dispatched(run1)
    j.accepted(run2)
    before = j.size()
    reclaimed = j.compact()
    assert reclaimed > 0 and j.size() < before
    completed, pending = j.replay()
    assert not completed
    assert [r.id for r in pending] == ["run1", "run2"]
    assert pending[0].deadline_s == 9  # payload survives compaction
    assert pending[1].trace == "tr-2"  # trace id survives compaction
    assert j.compact() >= 0  # idempotent


# ---------------------------------------------------------------------------
# in-process engine drills (shared resident session)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def world(tmp_path_factory):
    td = tmp_path_factory.mktemp("selfheal_world")
    paths, *_ = fx.write_world(str(td), n_frames=4)
    return paths


@pytest.fixture(scope="module")
def session(world):
    from sartsolver_tpu.cli import _validate
    from sartsolver_tpu.engine.cli import build_serve_parser
    from sartsolver_tpu.engine.session import ResidentSession

    args = build_serve_parser().parse_args([
        "--engine_dir", "/nonexistent-unused", *SOLVE_FLAGS,
        world["rtm_a1"], world["rtm_a2"], world["rtm_b"],
        world["img_a"], world["img_b"],
    ])
    _validate(args)
    return ResidentSession.build(args)


def _run_server(session, eng_dir, requests, *, idle_exit=0.4, **kw):
    from sartsolver_tpu.engine.server import EngineServer

    os.makedirs(os.path.join(eng_dir, "ingest"), exist_ok=True)
    for i, payload in enumerate(requests):
        with open(os.path.join(eng_dir, "ingest",
                               f"{i:03d}-{payload['id']}.json"),
                  "w") as f:
            json.dump(payload, f)
    admission = kw.pop("admission", None)
    if admission is None:
        admission = adm_mod.AdmissionController(
            max_queue=kw.pop("max_queue", 16),
            quarantine_after=kw.pop("quarantine_after", 3),
            quarantine_cooldown=kw.pop("quarantine_cooldown", 60.0),
        )
    server = EngineServer(
        session, engine_dir=eng_dir, lanes=kw.pop("lanes", 2),
        admission=admission, poll_interval=0.05, idle_exit=idle_exit,
        **kw,
    )
    rc = server.run()
    return server, rc


def _response(eng_dir, rid):
    with open(os.path.join(eng_dir, "responses", f"{rid}.json")) as f:
        return json.load(f)


def test_quarantine_and_slo_survive_restart(session, tmp_path):
    """The ISSUE acceptance e2e: a quarantined tenant stays quarantined
    across a crash (fresh process state restored from the checkpoint),
    and SLO burn / request counters are continuous — each incarnation
    resets the registry like a real restart does."""
    eng = str(tmp_path / "eng")
    # incarnation 1: the noisy tenant fails its way into quarantine
    obs_metrics.reset_registry()
    with faults.injected(faults.SITE_SESSION_ATTACH, "error", 1.0,
                         count=1):
        server1, rc = _run_server(
            session, eng, [{"id": "n1", "tenant": "noisy"}],
            quarantine_after=1, slo_ms=300000.0,
        )
    assert rc == 0
    assert _response(eng, "n1")["outcome"]["status"] == "failed"
    assert server1.admission.quarantined_tenants() == ["noisy"]

    # incarnation 2: fresh registry + fresh admission controller, same
    # engine dir — the checkpoint must restore the quarantine
    obs_metrics.reset_registry()
    server2, rc = _run_server(
        session, eng, [{"id": "n2", "tenant": "noisy"},
                       {"id": "c1", "tenant": "calm"}],
        quarantine_after=1, slo_ms=300000.0,
    )
    assert rc == 0
    n2 = _response(eng, "n2")
    assert n2["reason"] == req_mod.REASON_TENANT_QUARANTINED
    assert n2["retry_after_s"] > 0  # remaining cooldown rides the reply
    assert _response(eng, "c1")["outcome"]["status"] == "completed"
    # counter continuity: the requests_total family accounts BOTH
    # incarnations (failed n1 + completed c1), and SLO burn continues
    reg = obs_metrics.get_registry()
    assert reg.counter("engine_requests_total", outcome="failed").value \
        == 1
    assert reg.counter("engine_requests_total",
                       outcome="completed").value == 1
    slo = (reg.counter("engine_slo_ok_total", tenant="noisy").value
           + reg.counter("engine_slo_ok_total", tenant="calm").value
           + reg.counter("engine_slo_breach_total",
                         tenant="noisy").value
           + reg.counter("engine_slo_breach_total", tenant="calm").value)
    assert slo == 2  # n1 + c1, across the restart


def test_oom_lane_ladder_survives_restart(session, tmp_path):
    obs_metrics.reset_registry()
    eng = str(tmp_path / "eng")
    with faults.injected(faults.SITE_SOLVE, "oom", 1.0, count=1):
        server1, rc = _run_server(session, eng,
                                  [{"id": "o1", "tenant": "a"}], lanes=2)
    assert rc == 0 and server1.lanes == 1
    obs_metrics.reset_registry()
    server2, rc = _run_server(session, eng,
                              [{"id": "o2", "tenant": "a"}], lanes=2)
    assert rc == 0
    assert server2.lanes == 1  # sticky across the restart
    assert server2.admission.degraded_reason is not None


def test_queue_full_rejection_carries_retry_after(session, tmp_path):
    obs_metrics.reset_registry()
    eng = str(tmp_path / "eng")
    _run_server(session, eng, [
        {"id": "r1", "tenant": "a"},
        {"id": "r2", "tenant": "a"},
        {"id": "r3", "tenant": "a"},
    ], max_queue=1, max_cycle_requests=1)
    shed = [
        _response(eng, rid) for rid in ("r1", "r2", "r3")
        if _response(eng, rid).get("reason") == req_mod.REASON_QUEUE_FULL
    ]
    assert shed and all(r["retry_after_s"] >= 1.0 for r in shed)


def test_journal_startup_compaction_and_response_ttl(session, tmp_path):
    """Round 1 completes a request; round 2 starts up with rotation
    enabled -> the completed records compact away while the dedup
    watermark (checkpoint) still rejects the duplicate; an aged
    response file is swept by the TTL."""
    obs_metrics.reset_registry()
    eng = str(tmp_path / "eng")
    _run_server(session, eng, [{"id": "keep", "tenant": "a"}])
    j = RequestJournal(os.path.join(eng, "journal.jsonl"))
    completed, _ = j.replay()
    assert set(completed) == {"keep"}
    # age the response file beyond the TTL
    resp = os.path.join(eng, "responses", "keep.json")
    old = time.time() - 3600
    os.utime(resp, (old, old))

    obs_metrics.reset_registry()
    server2, rc = _run_server(
        session, eng, [{"id": "keep", "tenant": "a"},
                       {"id": "new", "tenant": "a"}],
        response_ttl_s=60.0, idle_exit=0.4,
    )
    # startup compaction dropped the completed story...
    completed, pending = j.replay()
    assert set(completed) == {"new"} and not pending
    # ...but the checkpointed watermark still treats the resubmission
    # as the duplicate it is: recorded outcome answered, never re-run
    keep = _response(eng, "keep")
    assert keep.get("duplicate") is True
    assert keep["outcome"]["status"] == "completed"
    assert _response(eng, "new")["outcome"]["status"] == "completed"
    # force one sweep past the throttle and check the aged file went
    server2._last_sweep = 0.0
    os.utime(resp, (old, old))
    server2._sweep_retention()
    assert not os.path.exists(resp)
    assert os.path.exists(os.path.join(eng, "responses", "new.json"))


def test_compaction_skipped_when_checkpoint_fails(session, tmp_path,
                                                  monkeypatch):
    """Journal compaction drops completed ids ONLY once their dedup
    watermark is durable in the checkpoint — a failing checkpoint must
    keep the fat journal (or a restart could re-solve a resubmitted
    completed request)."""
    from sartsolver_tpu.engine.server import EngineServer

    obs_metrics.reset_registry()
    eng = str(tmp_path / "eng")
    _run_server(session, eng, [{"id": "c1", "tenant": "a"}])
    j = RequestJournal(os.path.join(eng, "journal.jsonl"))
    completed, _ = j.replay()
    assert set(completed) == {"c1"}
    monkeypatch.setenv("SART_RETRY_ATTEMPTS", "1")
    obs_metrics.reset_registry()
    server = EngineServer(
        session, engine_dir=eng, lanes=2,
        admission=adm_mod.AdmissionController(max_queue=4),
    )
    with faults.injected(faults.SITE_STATE_CHECKPOINT, "io", 1.0):
        server._rotate_journal(startup=True)
    completed, _ = j.replay()
    assert set(completed) == {"c1"}  # completed story preserved
    reg = obs_metrics.get_registry()
    assert reg.counter("engine_checkpoint_failures_total").value >= 1
    # with the checkpoint healthy again, the same call compacts
    server._rotate_journal(startup=True)
    completed, _ = j.replay()
    assert not completed


def test_replay_skips_expired_response_republish(session, tmp_path):
    """A response the TTL sweep deleted must not come back (with a
    fresh mtime and another full TTL) just because its completed
    record still sits in the journal at restart."""
    obs_metrics.reset_registry()
    eng = str(tmp_path / "eng")
    _run_server(session, eng, [{"id": "aged", "tenant": "a"}])
    os.unlink(os.path.join(eng, "responses", "aged.json"))
    # age the completed marker two hours into the past
    jp = os.path.join(eng, "journal.jsonl")
    lines = []
    for line in open(jp):
        rec = json.loads(line)
        if rec.get("marker") == "completed":
            rec["unix"] = time.time() - 7200
        lines.append(json.dumps(rec) + "\n")
    with open(jp, "w") as f:
        f.writelines(lines)
    obs_metrics.reset_registry()
    _run_server(session, eng, [], idle_exit=0.2,
                response_ttl_s=3600.0, journal_rotate_bytes=0)
    assert not os.path.exists(
        os.path.join(eng, "responses", "aged.json")
    )


def test_replay_republishes_missing_response(session, tmp_path):
    """A kill after the completed marker but before the response write
    (the mid-response-write chaos window) must not leave the submitter
    polling forever: restart republishes from the journaled outcome —
    both when the response file is GONE and when it still shows the
    stale acceptance verdict (the real kill leaves 'pending' behind)."""
    obs_metrics.reset_registry()
    eng = str(tmp_path / "eng")
    # rotation off: these restarts must find the completed record in
    # the journal (startup compaction would consume it between runs)
    _run_server(session, eng, [{"id": "gone", "tenant": "a"}],
                journal_rotate_bytes=0)
    os.unlink(os.path.join(eng, "responses", "gone.json"))
    obs_metrics.reset_registry()
    _run_server(session, eng, [], idle_exit=0.2, journal_rotate_bytes=0)
    rec = _response(eng, "gone")
    assert rec["state"] == "done" and rec.get("republished") is True
    assert rec["outcome"]["status"] == "completed"
    # stale-pending variant: overwrite with the acceptance response
    with open(os.path.join(eng, "responses", "gone.json"), "w") as f:
        json.dump({"unix": 1.0, "id": "gone", "verdict": "accepted",
                   "state": "pending", "tenant": "a"}, f)
    obs_metrics.reset_registry()
    _run_server(session, eng, [], idle_exit=0.2, journal_rotate_bytes=0)
    rec = _response(eng, "gone")
    assert rec["state"] == "done" and rec.get("republished") is True


# ---------------------------------------------------------------------------
# /healthz liveness vs /readyz readiness
# ---------------------------------------------------------------------------

def _get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.read()


def test_healthz_liveness_vs_readyz_readiness(session, tmp_path):
    """Pinned byte-stable vocabulary (ISSUE satellite): /healthz answers
    live-200 whatever the admission state; /readyz flips not-ready with
    the machine-readable reason for draining and degraded."""
    from sartsolver_tpu.engine.httpd import EngineHTTPServer
    from sartsolver_tpu.engine.server import EngineServer

    obs_metrics.reset_registry()
    server = EngineServer(
        session, engine_dir=str(tmp_path / "eng"), lanes=2,
        admission=adm_mod.AdmissionController(max_queue=4),
    )
    srv = EngineHTTPServer(
        0, metrics_snapshot=lambda: [], health=server._health,
        ready=server._ready, status=lambda: {},
    )
    srv.start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        code, body = _get(base + "/healthz")
        assert code == 200 and json.loads(body) == {"status": "live"}
        code, body = _get(base + "/readyz")
        assert code == 200 and json.loads(body) == {"status": "ready"}
        # degraded: live stays 200, ready goes 503/degraded
        server.admission.set_degraded("device OOM; lanes halved to 1")
        assert _get(base + "/healthz")[0] == 200
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(base + "/readyz")
        assert exc.value.code == 503
        rec = json.loads(exc.value.read())
        assert rec["status"] == "not-ready"
        assert rec["reason"] == req_mod.REASON_DEGRADED
        # draining outranks degraded; healthz still live
        server._draining = True
        assert _get(base + "/healthz")[0] == 200
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(base + "/readyz")
        assert json.loads(exc.value.read())["reason"] \
            == req_mod.REASON_DRAINING
    finally:
        srv.stop()


def test_lame_duck_never_clobbers_done_response(tmp_path):
    """A resubmission of a COMPLETED id arriving during lame duck is a
    duplicate: the recorded outcome must survive (the engine's
    never-clobber contract), not be overwritten with a crash-loop
    rejection."""
    from sartsolver_tpu.resilience.supervisor import Supervisor

    obs_metrics.reset_registry()
    eng = str(tmp_path / "eng")
    sup = Supervisor([], engine_dir=eng)
    done_rec = {"unix": 1.0, "id": "dup1", "verdict": "accepted",
                "state": "done", "outcome": {"status": "completed"}}
    with open(os.path.join(eng, "responses", "dup1.json"), "w") as f:
        json.dump(done_rec, f)
    for rid in ("dup1", "new1"):
        with open(os.path.join(eng, "ingest", f"{rid}.json"), "w") as f:
            json.dump({"id": rid, "tenant": "a"}, f)
    n = sup._reject_ingest(remaining_s=9.0)
    # the new id got the crash-loop rejection; the completed one kept
    # its recorded outcome and its ingest file was consumed
    assert n == 1
    assert not os.listdir(os.path.join(eng, "ingest"))
    assert json.load(open(os.path.join(
        eng, "responses", "dup1.json"))) == done_rec
    new1 = json.load(open(os.path.join(eng, "responses", "new1.json")))
    assert new1["reason"] == req_mod.REASON_CRASH_LOOP
    assert new1["retry_after_s"] == 9.0


# ---------------------------------------------------------------------------
# restart storm: real supervised process
# ---------------------------------------------------------------------------

def _env(extra=None):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONUNBUFFERED"] = "1"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    for k in ("SART_FAULT", "SART_TEST_JOURNAL_DELAY",
              "SART_TEST_SERVE_CRASH"):
        env.pop(k, None)
    for k, v in (extra or {}).items():
        env[k] = v
    return env


def _supervised_cmd(paths, eng_dir, *extra):
    return [
        sys.executable, "-m", "sartsolver_tpu.cli", "serve",
        "--engine_dir", eng_dir, *SOLVE_FLAGS,
        "--lanes", "2", "--poll_interval", "0.05", "--supervised",
        *extra,
        paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
        paths["img_a"], paths["img_b"],
    ]


def test_restart_storm_breaker_opens_and_clears(world, tmp_path):
    """The restart-storm drill (ISSUE satellite): a crash-looping worker
    trips the breaker on schedule -> lame duck (healthz 503 crash-loop,
    ingest rejected with the machine-readable reason + retry hint,
    engine_crash_loop gauge up) -> the window clears, the fixed worker
    serves, SIGTERM drains through the supervisor with exit 4."""
    eng = str(tmp_path / "eng")
    marker = str(tmp_path / "crash.marker")
    open(marker, "w").write("boom")
    env = _env({"SART_TEST_SERVE_CRASH": marker})
    proc = subprocess.Popen(
        _supervised_cmd(
            world, eng,
            "--restart_backoff", "0.05", "--restart_backoff_max", "0.2",
            "--crash_loop_window", "25", "--crash_loop_threshold", "3",
            "--http_port", "0",
        ),
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    guard = threading.Timer(240, proc.kill)
    guard.start()
    lines = []
    state = {"resident": False, "port": None}
    try:
        lame_seen = False
        for line in proc.stdout:
            lines.append(line)
            if "lame-duck-enter" in line and not lame_seen:
                lame_seen = True
                # worker is gone: the marker can come off so the breaker
                # half-open spawn succeeds after the window clears
                os.unlink(marker)
                # journals-but-refuses: a request arriving now gets the
                # crash-loop rejection with a retry hint
                ingest = os.path.join(eng, "ingest")
                os.makedirs(ingest, exist_ok=True)
                with open(os.path.join(ingest, "ld1.json.tmp"),
                          "w") as f:
                    json.dump({"id": "ld1", "tenant": "a"}, f)
                os.replace(os.path.join(ingest, "ld1.json.tmp"),
                           os.path.join(ingest, "ld1.json"))
            m = re.search(r"lame-duck-endpoint port=(\d+)", line)
            if m:
                state["port"] = int(m.group(1))
                with pytest.raises(urllib.error.HTTPError) as exc:
                    _get(f"http://127.0.0.1:{state['port']}/healthz")
                assert exc.value.code == 503
                assert json.loads(exc.value.read())["status"] \
                    == req_mod.REASON_CRASH_LOOP
                with pytest.raises(urllib.error.HTTPError) as exc:
                    _get(f"http://127.0.0.1:{state['port']}/readyz")
                rec = json.loads(exc.value.read())
                assert rec == {"status": "not-ready",
                               "reason": req_mod.REASON_CRASH_LOOP,
                               "detail": rec["detail"]}
            if "session resident" in line:
                state["resident"] = True
                proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
    finally:
        guard.cancel()
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    text = "".join(lines)
    assert rc == 4, text[-4000:]
    assert lame_seen and state["resident"], text[-4000:]
    assert "lame-duck-exit" in text
    # three on-schedule crashes, classified
    assert text.count("worker-crash code=") == 3
    assert "reason=infrastructure" in text
    # the lame-duck rejection landed machine-readable with a hint
    rec = _response(eng, "ld1")
    assert rec["verdict"] == "rejected"
    assert rec["reason"] == req_mod.REASON_CRASH_LOOP
    assert rec["retry_after_s"] >= 1.0
    # durable supervisor artifacts: events journal + prom textfile
    kinds = [json.loads(ln)["kind"]
             for ln in open(os.path.join(eng, "supervisor.jsonl"))]
    assert sum(k == "worker-crash" for k in kinds) == 3
    assert "lame-duck-enter" in kinds and "lame-duck-exit" in kinds
    prom = open(os.path.join(eng, "supervisor.prom")).read()
    assert 'sart_engine_restarts_total{reason="infrastructure"} 3' \
        in prom
    assert "sart_engine_crash_loop" in prom
    # the supervisor crash bundle names the breaker
    bundle = json.load(open(os.path.join(eng, "supervisor.crash.json")))
    assert "crash-loop" in bundle["reason"]


def test_supervisor_config_error_is_final(world, tmp_path):
    """A worker that exits 1 (flag error) must NOT be restarted — the
    supervisor surfaces the config problem instead of looping."""
    res = subprocess.run(
        _supervised_cmd(world, str(tmp_path / "eng"),
                        "--restart_backoff", "0.05", "--lanes", "0"),
        env=_env(), capture_output=True, text=True, timeout=120,
    )
    assert res.returncode == 1
    text = res.stdout + res.stderr
    assert "worker-config-error" in text
    assert "worker-crash" not in text


def test_submit_retry_honors_hint_against_lame_duck(world, tmp_path):
    """`submit --retry` against a crash-looping engine: the first
    attempt is rejected crash-loop with a hint; once the breaker clears
    and the worker serves, a retry completes the request."""
    eng = str(tmp_path / "eng")
    marker = str(tmp_path / "crash.marker")
    open(marker, "w").write("boom")
    env = _env({"SART_TEST_SERVE_CRASH": marker})
    proc = subprocess.Popen(
        _supervised_cmd(
            world, eng,
            "--restart_backoff", "0.05", "--restart_backoff_max", "0.2",
            "--crash_loop_window", "20", "--crash_loop_threshold", "2",
        ),
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    guard = threading.Timer(240, proc.kill)
    guard.start()
    lines = []
    submit = {"res": None}
    try:
        for line in proc.stdout:
            lines.append(line)
            if "lame-duck-enter" in line and submit["res"] is None:
                os.unlink(marker)

                def do_submit():
                    submit["res"] = subprocess.run(
                        [sys.executable, "-m", "sartsolver_tpu.cli",
                         "submit", "--engine_dir", eng, "--id", "rt1",
                         "--tenant", "a", "--wait", "120",
                         "--retry", "8"],
                        env=_env({"SART_RETRY_BASE_DELAY": "0.2",
                                  "SART_RETRY_DEADLINE": "180"}),
                        capture_output=True, text=True, timeout=200,
                    )
                    # retries done: drain the engine so the test ends
                    proc.send_signal(signal.SIGTERM)

                threading.Thread(target=do_submit, daemon=True).start()
        rc = proc.wait(timeout=200)
    finally:
        guard.cancel()
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    res = submit["res"]
    assert res is not None and res.returncode == 0, (
        (res.stdout + res.stderr if res else "no submit result")
        + "".join(lines)[-3000:]
    )
    rec = json.loads(res.stdout)
    assert rec["outcome"]["status"] == "completed"
    assert "rejected (crash-loop); retry" in res.stderr
    assert rc == 4
