"""Serving-engine matrix (docs/SERVING.md; `make serve`).

In-process legs share one resident session (module fixture): request
parsing + fault site, journal append/replay (torn tails, retry),
admission policy (bounded queue, quotas, quarantine, degraded,
draining), the deadline shed drill (over-deadline request retires at a
stride boundary with the distinct status while the co-batched request
completes), OOM lane degradation, replay determinism, the engine
status/heartbeat/top surfaces, and the `sartsolve metrics` engine
gates.

Real-process legs drive the actual ``sartsolve serve`` binary:
submit/duplicate/SIGTERM-drain lifecycle, the crash-replay matrix
(SIGKILL inside each journal marker window, restart, byte-identical
outputs, no request lost or double-solved), and the fault-injection
sites drilled end-to-end through admission/retry/quarantine.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import h5py
import numpy as np
import pytest

import fixtures as fx

from sartsolver_tpu.engine import admission as adm_mod
from sartsolver_tpu.engine import journal as journal_mod
from sartsolver_tpu.engine import request as req_mod
from sartsolver_tpu.engine.request import Request, RequestError, parse_request
from sartsolver_tpu.obs import metrics as obs_metrics
from sartsolver_tpu.resilience import faults
from sartsolver_tpu.resilience.failures import (
    DEADLINE_EXCEEDED,
    status_name,
)

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)


# ---------------------------------------------------------------------------
# request parsing
# ---------------------------------------------------------------------------

def test_parse_request_roundtrip():
    req = parse_request(json.dumps({
        "id": "r1", "tenant": "diag-a", "time_range": "0.1:0.3",
        "deadline_s": 2.5,
    }))
    assert req.id == "r1" and req.tenant == "diag-a"
    assert req.deadline_s == 2.5 and req.time_range == "0.1:0.3"
    assert req.submitted_unix > 0
    # to_dict round-trips through the journal's accepted record
    again = parse_request(req.to_dict())
    assert again.id == req.id and again.deadline_s == req.deadline_s


@pytest.mark.parametrize("payload", [
    "not json",
    json.dumps(["list"]),
    json.dumps({"tenant": "t"}),                      # missing id
    json.dumps({"id": "bad id!"}),                    # bad id charset
    json.dumps({"id": "r", "unknown_field": 1}),      # unknown field
    json.dumps({"id": "r", "deadline_s": -1}),        # bad deadline
    json.dumps({"id": "r", "time_range": "5:1"}),     # bad range
    json.dumps({"id": "r", "tenant": 7}),             # bad tenant type
])
def test_parse_request_rejects(payload):
    with pytest.raises(RequestError):
        parse_request(payload)


def test_parse_request_default_deadline():
    req = parse_request(json.dumps({"id": "r"}), default_deadline_s=9.0)
    assert req.deadline_s == 9.0
    req = parse_request(json.dumps({"id": "r", "deadline_s": 1.5}),
                        default_deadline_s=9.0)
    assert req.deadline_s == 1.5


def test_parse_request_fault_site():
    """The request.parse site models a torn payload read: armed io
    faults surface as OSError (the server's malformed-rejection leg)."""
    with faults.injected(faults.SITE_REQUEST_PARSE, "io", 1.0, count=1):
        with pytest.raises(OSError):
            parse_request(json.dumps({"id": "ok"}))
        parse_request(json.dumps({"id": "ok"}))  # count exhausted


# ---------------------------------------------------------------------------
# journal
# ---------------------------------------------------------------------------

def test_journal_roundtrip_and_replay(tmp_path):
    j = journal_mod.RequestJournal(str(tmp_path / "j.jsonl"))
    r1 = parse_request({"id": "r1", "tenant": "a", "deadline_s": 5})
    r2 = parse_request({"id": "r2", "tenant": "b"})
    r3 = parse_request({"id": "r3", "tenant": "b"})
    j.accepted(r1)
    j.dispatched(r1)
    j.completed(r1, {"status": "completed"})
    j.accepted(r2)
    j.dispatched(r2)  # dispatched but never completed -> replays
    j.accepted(r3)    # accepted only -> replays
    completed, pending = j.replay()
    assert set(completed) == {"r1"}
    assert [r.id for r in pending] == ["r2", "r3"]
    assert pending[0].tenant == "b"
    # r1's payload details survived the journal round trip
    with open(j.path) as f:
        first = json.loads(f.readline())
    assert first["request"]["deadline_s"] == 5


def test_journal_ignores_torn_tail(tmp_path):
    j = journal_mod.RequestJournal(str(tmp_path / "j.jsonl"))
    j.accepted(parse_request({"id": "r1"}))
    with open(j.path, "a") as f:
        f.write('{"marker": "completed", "id": "r1", "out')  # torn append
    completed, pending = j.replay()
    assert not completed and [r.id for r in pending] == ["r1"]


def test_journal_append_fault_retries(tmp_path, monkeypatch):
    """Transient journal I/O faults retry in place; the marker still
    lands (the engine never proceeds unjournaled)."""
    monkeypatch.setenv("SART_RETRY_BASE_DELAY", "0.01")
    j = journal_mod.RequestJournal(str(tmp_path / "j.jsonl"))
    with faults.injected(faults.SITE_JOURNAL_APPEND, "io", 1.0, count=2):
        j.accepted(parse_request({"id": "r1"}))
    completed, pending = j.replay()
    assert [r.id for r in pending] == ["r1"]


def test_journal_crash_window_announces(tmp_path, monkeypatch, capfd):
    monkeypatch.setenv("SART_TEST_JOURNAL_DELAY", "0.01")
    j = journal_mod.RequestJournal(str(tmp_path / "j.jsonl"))
    r = parse_request({"id": "r1"})
    j.accepted(r)
    j.dispatched(r)
    j.completed(r, {})
    err = capfd.readouterr().err
    assert "SART_JOURNAL_POINT accepted" in err
    assert "SART_JOURNAL_POINT dispatched" in err
    assert "SART_JOURNAL_POINT pre-flush" in err


# ---------------------------------------------------------------------------
# admission policy
# ---------------------------------------------------------------------------

def _req(rid, tenant="t"):
    return parse_request({"id": rid, "tenant": tenant})


def test_admission_queue_and_quota():
    obs_metrics.reset_registry()
    adm = adm_mod.AdmissionController(max_queue=2, max_per_tenant=1)
    assert adm.admit(_req("a1", "a")) is None
    # tenant quota before global capacity
    assert adm.admit(_req("a2", "a")) == req_mod.REASON_TENANT_QUOTA
    assert adm.admit(_req("b1", "b")) is None
    assert adm.admit(_req("c1", "c")) == req_mod.REASON_QUEUE_FULL
    # duplicates rejected even after completion
    adm.note_dispatched(_req("a1", "a"))
    adm.note_outcome(_req("a1", "a"), req_mod.REQ_COMPLETED)
    assert adm.admit(_req("a1", "a")) == req_mod.REASON_DUPLICATE
    # draining outranks everything
    assert adm.admit(_req("z", "z"), draining=True) \
        == req_mod.REASON_DRAINING


def test_admission_quarantine_and_cooldown():
    obs_metrics.reset_registry()
    clock = {"t": 0.0}
    adm = adm_mod.AdmissionController(
        max_queue=8, quarantine_after=2, quarantine_cooldown=10.0,
        clock=lambda: clock["t"],
    )
    for i, outcome in enumerate(
            (req_mod.REQ_FAILED, req_mod.REQ_PARTIAL)):
        r = _req(f"bad{i}", "noisy")
        assert adm.admit(r) is None
        adm.note_dispatched(r)
        adm.note_outcome(r, outcome)
    # two consecutive failures -> quarantined; other tenants unaffected
    assert adm.admit(_req("bad2", "noisy")) \
        == req_mod.REASON_TENANT_QUARANTINED
    assert adm.admit(_req("ok1", "calm")) is None
    assert adm.quarantined_tenants() == ["noisy"]
    # cooldown expiry readmits
    clock["t"] = 11.0
    assert adm.admit(_req("bad3", "noisy")) is None
    # a completed request resets the failure streak
    adm.note_dispatched(_req("bad3", "noisy"))
    adm.note_outcome(_req("bad3", "noisy"), req_mod.REQ_COMPLETED)
    r = _req("bad4", "noisy")
    assert adm.admit(r) is None
    adm.note_dispatched(r)
    adm.note_outcome(r, req_mod.REQ_FAILED)
    assert adm.admit(_req("bad5", "noisy")) is None  # streak is 1, not 3


def test_admission_deadline_shed_not_quarantined():
    obs_metrics.reset_registry()
    adm = adm_mod.AdmissionController(max_queue=8, quarantine_after=1)
    r = _req("d1", "t")
    assert adm.admit(r) is None
    adm.note_dispatched(r)
    adm.note_outcome(r, req_mod.REQ_SHED_DEADLINE)
    # a deadline miss is pool congestion, not the tenant's fault
    assert adm.admit(_req("d2", "t")) is None


def test_admission_degraded_mode():
    obs_metrics.reset_registry()
    adm = adm_mod.AdmissionController(max_queue=4)
    adm.set_degraded("device OOM; lanes halved to 1")
    assert adm.admit(_req("a")) is None  # below the degraded watermark
    assert adm.admit(_req("b")) is None
    assert adm.admit(_req("c")) == req_mod.REASON_DEGRADED
    adm.set_degraded(None)
    assert adm.admit(_req("c2")) is None


def test_status_taxonomy():
    assert DEADLINE_EXCEEDED == -5
    assert status_name(DEADLINE_EXCEEDED) == "deadline"


# ---------------------------------------------------------------------------
# in-process engine drills (shared resident session)
# ---------------------------------------------------------------------------

SOLVE_FLAGS = ["--use_cpu", "-m", "40", "-c", "1e-12"]


@pytest.fixture(scope="module")
def world(tmp_path_factory):
    td = tmp_path_factory.mktemp("engine_world")
    paths, H, f_true, times, scales = fx.write_world(str(td), n_frames=4)
    return paths


@pytest.fixture(scope="module")
def session(world):
    from sartsolver_tpu.cli import _validate
    from sartsolver_tpu.engine.cli import build_serve_parser
    from sartsolver_tpu.engine.session import ResidentSession

    args = build_serve_parser().parse_args([
        "--engine_dir", "/nonexistent-unused", *SOLVE_FLAGS,
        world["rtm_a1"], world["rtm_a2"], world["rtm_b"],
        world["img_a"], world["img_b"],
    ])
    _validate(args)
    return ResidentSession.build(args)


def _run_server(session, eng_dir, requests, *, lanes=2, idle_exit=0.4,
                **kw):
    from sartsolver_tpu.engine.server import EngineServer

    os.makedirs(os.path.join(eng_dir, "ingest"), exist_ok=True)
    for i, payload in enumerate(requests):
        with open(os.path.join(eng_dir, "ingest",
                               f"{i:03d}-{payload['id']}.json"),
                  "w") as f:
            json.dump(payload, f)
    admission = kw.pop("admission", None)
    if admission is None:
        admission = adm_mod.AdmissionController(
            max_queue=kw.pop("max_queue", 16),
            max_per_tenant=kw.pop("max_per_tenant", 0),
            quarantine_after=kw.pop("quarantine_after", 3),
            quarantine_cooldown=kw.pop("quarantine_cooldown", 60.0),
        )
    server = EngineServer(
        session, engine_dir=eng_dir, lanes=lanes, admission=admission,
        poll_interval=0.05, idle_exit=idle_exit, **kw,
    )
    rc = server.run()
    return server, rc


def _response(eng_dir, rid):
    with open(os.path.join(eng_dir, "responses", f"{rid}.json")) as f:
        return json.load(f)


def _solution(path):
    with h5py.File(path, "r") as f:
        return {k: f[f"solution/{k}"][:] for k in f["solution"]}


def test_engine_serves_requests_and_matches_cli(session, world, tmp_path):
    """Two requests solved against the resident session; the full-range
    request's output is byte-identical to the one-shot CLI's scheduler
    path over the same frames (lane parity), and a re-run in a fresh
    engine dir reproduces the bytes (replay determinism)."""
    obs_metrics.reset_registry()
    eng = str(tmp_path / "eng")
    server, rc = _run_server(session, eng, [
        {"id": "all", "tenant": "a"},
        {"id": "head", "tenant": "b", "time_range": "0.05:0.25"},
    ])
    assert rc == 0
    out = _response(eng, "all")["outcome"]
    assert out["status"] == "completed" and out["frames"] == 4
    assert _response(eng, "head")["outcome"]["frames"] == 2
    # journal is a complete accepted->dispatched->completed story
    completed, pending = journal_mod.RequestJournal(
        os.path.join(eng, "journal.jsonl")).replay()
    assert set(completed) == {"all", "head"} and not pending

    # parity with the one-shot CLI's continuous-batching path
    from sartsolver_tpu.cli import main as cli_main

    cli_out = str(tmp_path / "cli.h5")
    assert cli_main([
        "-o", cli_out, *SOLVE_FLAGS, "--no_guess", "--batch_frames", "2",
        world["rtm_a1"], world["rtm_a2"], world["rtm_b"],
        world["img_a"], world["img_b"],
    ]) == 0
    a = _solution(os.path.join(eng, "outputs", "all.h5"))
    b = _solution(cli_out)
    for key in sorted(b):
        np.testing.assert_array_equal(a[key], b[key], err_msg=key)

    # replay determinism: a second engine run writes identical bytes
    eng2 = str(tmp_path / "eng2")
    _run_server(session, eng2, [{"id": "all", "tenant": "a"}])
    c = _solution(os.path.join(eng2, "outputs", "all.h5"))
    for key in sorted(a):
        np.testing.assert_array_equal(a[key], c[key], err_msg=key)


def test_engine_deadline_shed_while_cobatched_completes(world, tmp_path):
    """The deadline drill (ISSUE acceptance): an over-deadline request
    retires at a stride boundary with the distinct status while the
    co-batched request completes normally."""
    from sartsolver_tpu.cli import _validate
    from sartsolver_tpu.engine.cli import build_serve_parser
    from sartsolver_tpu.engine.session import ResidentSession

    obs_metrics.reset_registry()
    # a convergence-proof problem (tolerance below reach, huge cap) so
    # the deadline reliably expires mid-solve
    args = build_serve_parser().parse_args([
        "--engine_dir", "/unused", "--use_cpu", "-m", "20000",
        "-c", "1e-300", "--schedule_stride", "8",
        world["rtm_a1"], world["rtm_a2"], world["rtm_b"],
        world["img_a"], world["img_b"],
    ])
    _validate(args)
    slow_session = ResidentSession.build(args)
    eng = str(tmp_path / "eng")
    server, rc = _run_server(slow_session, eng, [
        {"id": "hurried", "tenant": "a", "deadline_s": 0.8},
        {"id": "patient", "tenant": "b"},
    ], lanes=2)
    assert rc == 0
    hurried = _response(eng, "hurried")["outcome"]
    patient = _response(eng, "patient")["outcome"]
    assert hurried["status"] == "shed-deadline"
    assert set(hurried["by_status"]) == {"deadline"}
    assert patient["status"] == "completed"
    sol = _solution(os.path.join(eng, "outputs", "hurried.h5"))
    assert (sol["status"] == DEADLINE_EXCEEDED).all()
    reg = obs_metrics.get_registry()
    assert reg.counter("engine_deadline_miss_total").value >= 1
    assert reg.counter("sched_deadline_shed_total").value >= 1


def test_engine_queue_full_rejects_machine_readable(session, tmp_path):
    obs_metrics.reset_registry()
    eng = str(tmp_path / "eng")
    server, rc = _run_server(session, eng, [
        {"id": "q1", "tenant": "a"},
        {"id": "q2", "tenant": "a"},
        {"id": "q3", "tenant": "a"},
    ], max_queue=1, max_cycle_requests=1)
    assert rc == 0
    verdicts = {rid: _response(eng, rid) for rid in ("q1", "q2", "q3")}
    assert verdicts["q1"]["verdict"] == "accepted"
    shed = [r for r in verdicts.values()
            if r.get("reason") == req_mod.REASON_QUEUE_FULL]
    assert len(shed) == 2  # the scan found them beyond the bounded queue
    reg = obs_metrics.get_registry()
    assert reg.counter("engine_shed_total",
                       reason=req_mod.REASON_QUEUE_FULL).value == 2


def test_engine_attach_fault_quarantines_tenant(session, tmp_path):
    """session.attach faults fail the request (FAILED outcome, no
    engine abort) and consecutive failures quarantine only that
    tenant. Requests arrive sequentially (quarantine is judged on
    outcomes, so the failing ones must complete before the next
    admission) — the admission controller persists across the serve
    passes, like one resident engine fed over time."""
    obs_metrics.reset_registry()
    eng = str(tmp_path / "eng")
    adm = adm_mod.AdmissionController(max_queue=16, quarantine_after=2)
    with faults.injected(faults.SITE_SESSION_ATTACH, "error", 1.0,
                         count=2):
        _run_server(session, eng, [{"id": "n1", "tenant": "noisy"}],
                    admission=adm)
        _run_server(session, eng, [{"id": "n2", "tenant": "noisy"}],
                    admission=adm)
    _run_server(session, eng, [{"id": "n3", "tenant": "noisy"},
                               {"id": "c1", "tenant": "calm"}],
                admission=adm)
    assert _response(eng, "n1")["outcome"]["status"] == "failed"
    assert _response(eng, "n2")["outcome"]["status"] == "failed"
    assert _response(eng, "n3")["reason"] \
        == req_mod.REASON_TENANT_QUARANTINED
    assert _response(eng, "c1")["outcome"]["status"] == "completed"


def test_engine_oom_halves_lanes_and_degrades(session, tmp_path):
    """A device OOM mid-cycle: the lane count halves (sticky), the
    leftover frames still solve, and admission flips degraded."""
    obs_metrics.reset_registry()
    eng = str(tmp_path / "eng")
    with faults.injected(faults.SITE_SOLVE, "oom", 1.0, count=1):
        server, rc = _run_server(session, eng, [
            {"id": "o1", "tenant": "a"},
        ], lanes=2)
    assert rc == 0
    assert server.lanes == 1
    assert server.admission.degraded_reason is not None
    out = _response(eng, "o1")["outcome"]
    assert out["status"] == "completed" and out["frames"] == 4


def test_engine_status_heartbeat_and_top(session, tmp_path, monkeypatch):
    """The engine view reaches all three surfaces: the status snapshot
    (SIGUSR1 / crash bundle), the heartbeat line, `sartsolve top`."""
    from sartsolver_tpu.engine.server import EngineServer
    from sartsolver_tpu.obs import flight as obs_flight
    from sartsolver_tpu.obs.cli import render_top
    from sartsolver_tpu.resilience import watchdog

    obs_metrics.reset_registry()
    eng = str(tmp_path / "eng")
    server = EngineServer(
        session, engine_dir=eng, lanes=2,
        admission=adm_mod.AdmissionController(max_queue=4),
    )
    server.admission.admit(_req("s1", "a"))
    server._active_ids.append("s0")
    watchdog.set_engine_status_provider(server._status)
    try:
        rec = obs_flight.status_snapshot()
        assert rec["engine"]["queue_depth"] == 1
        assert rec["engine"]["admitted"] == 1
        assert rec["engine"]["active_requests"] == ["s0"]
        status_path = str(tmp_path / "status.json")
        obs_flight.write_status(status_path)
        screen = render_top(status_path)
        assert "engine: queue 1" in screen
        assert "s0" in screen
        hb = str(tmp_path / "hb")
        monkeypatch.setenv("SART_HEARTBEAT_FILE", hb)
        watchdog.beacon(watchdog.PHASE_FRAME_DONE)
        line = open(hb).read()
        assert "queue=1" in line and "admitted=1" in line \
            and "requests=s0" in line
    finally:
        watchdog.set_engine_status_provider(None)
    assert watchdog.engine_status() is None


# ---------------------------------------------------------------------------
# `sartsolve metrics` engine gates
# ---------------------------------------------------------------------------

def _engine_artifact(path, queue_wait_mean, miss, admitted):
    from sartsolver_tpu.obs import schema

    records = [
        schema.make_meta_record(created_unix=1.0),
        {"type": "metric", "kind": "histogram",
         "name": "engine_queue_wait_s", "labels": {},
         "count": 4, "sum": 4 * queue_wait_mean,
         "min": queue_wait_mean, "max": queue_wait_mean},
        {"type": "metric", "kind": "counter",
         "name": "engine_admitted_total", "labels": {},
         "value": admitted},
        {"type": "metric", "kind": "counter",
         "name": "engine_deadline_miss_total", "labels": {},
         "value": miss},
        schema.make_summary_record(0, {}, wall_s=1.0),
    ]
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")


def test_metrics_engine_summary_and_gates(tmp_path, capsys):
    from sartsolver_tpu.obs.cli import metrics_main, summarize, _load

    old = str(tmp_path / "old.jsonl")
    new = str(tmp_path / "new.jsonl")
    _engine_artifact(old, queue_wait_mean=0.1, miss=0, admitted=10)
    summary = summarize(_load(old)[0])
    assert summary["engine"]["queue_wait_mean_s"] == pytest.approx(0.1)
    assert summary["engine"]["deadline_miss_rate"] == 0.0
    # within threshold: queue wait +50%, no misses
    _engine_artifact(new, queue_wait_mean=0.15, miss=0, admitted=10)
    assert metrics_main(["--diff", old, new, "--threshold", "60"]) == 0
    # queue-wait regression past the threshold fails the gate
    _engine_artifact(new, queue_wait_mean=0.5, miss=0, admitted=10)
    assert metrics_main(["--diff", old, new, "--threshold", "60"]) == 2
    assert "queue-wait regression" in capsys.readouterr().err
    # deadline-miss rate rising past the point threshold fails the gate
    _engine_artifact(new, queue_wait_mean=0.1, miss=9, admitted=10)
    assert metrics_main(["--diff", old, new, "--threshold", "60"]) == 2
    assert "deadline-miss rate" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# real-process drills
# ---------------------------------------------------------------------------

def _env(extra=None):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONUNBUFFERED"] = "1"  # the drills watch live stdout lines
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("SART_TEST_JOURNAL_DELAY", None)
    env.pop("SART_FAULT", None)
    for k, v in (extra or {}).items():
        env[k] = v
    return env


def _serve_cmd(paths, eng_dir, *extra):
    return [
        sys.executable, "-m", "sartsolver_tpu.cli", "serve",
        "--engine_dir", eng_dir, *SOLVE_FLAGS,
        "--lanes", "2", "--poll_interval", "0.05", *extra,
        paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
        paths["img_a"], paths["img_b"],
    ]


def _submit_cmd(eng_dir, *extra):
    return [sys.executable, "-m", "sartsolver_tpu.cli", "submit",
            "--engine_dir", eng_dir, *extra]


def _start_serve(cmd, env, timeout=120):
    proc = subprocess.Popen(
        cmd, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    deadline = time.monotonic() + timeout
    lines = []

    for line in proc.stdout:
        lines.append(line)
        if "session resident" in line:
            return proc, lines
        if time.monotonic() > deadline:
            break
    proc.kill()
    raise AssertionError(
        "serve process never became resident:\n" + "".join(lines)
    )


def _drain_stdout(proc, sink):
    t = threading.Thread(
        target=lambda: sink.extend(proc.stdout), daemon=True
    )
    t.start()
    return t


@pytest.fixture(scope="module")
def drill_world(tmp_path_factory):
    td = tmp_path_factory.mktemp("engine_drill")
    paths, *_ = fx.write_world(str(td), n_frames=4)
    return paths


def test_serve_submit_lifecycle_and_sigterm(drill_world, tmp_path):
    """One real serve process: dir submit with --wait completes; a
    duplicate id is rejected with the machine-readable reason at exit-
    code parity; a malformed submit fails locally with exit 1; SIGTERM
    drains and exits 4."""
    eng = str(tmp_path / "eng")
    env = _env()
    proc, lines = _start_serve(_serve_cmd(drill_world, eng), env)
    _drain_stdout(proc, lines)
    try:
        done = subprocess.run(
            _submit_cmd(eng, "--id", "life1", "--tenant", "demo",
                        "--wait", "90"),
            env=env, capture_output=True, text=True, timeout=120,
        )
        assert done.returncode == 0, done.stderr
        rec = json.loads(done.stdout)
        assert rec["outcome"]["status"] == "completed"
        assert rec["outcome"]["frames"] == 4

        # idempotent resubmission: the completed id's recorded outcome
        # is returned (never re-run, never clobbered) with the
        # duplicate flag set
        dup = subprocess.run(
            _submit_cmd(eng, "--id", "life1", "--wait", "60"),
            env=env, capture_output=True, text=True, timeout=90,
        )
        assert dup.returncode == 0, dup.stdout + dup.stderr
        dup_rec = json.loads(dup.stdout)
        assert dup_rec.get("duplicate") is True
        assert dup_rec["outcome"]["status"] == "completed"
        # and the original's response record survived intact
        assert _response(eng, "life1")["outcome"]["frames"] == 4

        bad = subprocess.run(
            _submit_cmd(eng, "--id", "bad name!"),
            env=env, capture_output=True, text=True, timeout=90,
        )
        assert bad.returncode == 1
    finally:
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
    assert rc == 4
    text = "".join(lines)
    assert "draining" in text


CRASH_REQUESTS = [
    {"id": "cr1", "tenant": "a", "time_range": "0.05:0.25"},
    {"id": "cr2", "tenant": "b"},
]


@pytest.fixture(scope="module")
def crash_reference(drill_world, tmp_path_factory):
    """Uninterrupted reference outputs for the crash matrix (one real
    serve run shared by every marker leg)."""
    ref = str(tmp_path_factory.mktemp("crash_ref"))
    os.makedirs(os.path.join(ref, "ingest"), exist_ok=True)
    for i, payload in enumerate(CRASH_REQUESTS):
        with open(os.path.join(ref, "ingest", f"{i}-r.json"), "w") as f:
            json.dump(payload, f)
    res = subprocess.run(
        _serve_cmd(drill_world, ref, "--idle_exit", "1"),
        env=_env(), capture_output=True, text=True, timeout=300,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    return {
        r["id"]: _solution(os.path.join(ref, "outputs",
                                        f"{r['id']}.h5"))
        for r in CRASH_REQUESTS
    }


@pytest.mark.parametrize("marker", ["accepted", "dispatched", "pre-flush"])
def test_crash_replay_matrix(drill_world, crash_reference, tmp_path,
                             marker):
    """SIGKILL the real serve process inside a journal marker window,
    restart, and assert: no request lost, none double-solved, outputs
    byte-identical to an uninterrupted run (ISSUE acceptance)."""
    requests = CRASH_REQUESTS
    ref_out = crash_reference
    env = _env()

    # kill run: the journal windows are held open; SIGKILL inside the
    # first occurrence of the target marker
    eng = str(tmp_path / "eng")
    os.makedirs(os.path.join(eng, "ingest"))
    for i, payload in enumerate(requests):
        with open(os.path.join(eng, "ingest", f"{i}-r.json"), "w") as f:
            json.dump(payload, f)
    kill_env = _env({"SART_TEST_JOURNAL_DELAY": "1.5"})
    proc = subprocess.Popen(
        _serve_cmd(drill_world, eng),
        env=kill_env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True,
    )
    watchdog_timer = threading.Timer(240, proc.kill)
    watchdog_timer.start()
    try:
        for line in proc.stdout:
            if f"SART_JOURNAL_POINT {marker}" in line:
                proc.kill()
                break
    finally:
        watchdog_timer.cancel()
    assert proc.wait(timeout=60) == -signal.SIGKILL

    # restart without the windows: replay must finish exactly the
    # unfinished requests
    rc = subprocess.run(
        _serve_cmd(drill_world, eng, "--idle_exit", "1"),
        env=env, capture_output=True, text=True, timeout=300,
    ).returncode
    assert rc == 0
    completed, pending = journal_mod.RequestJournal(
        os.path.join(eng, "journal.jsonl")).replay()
    assert set(completed) == {"cr1", "cr2"} and not pending
    # solved exactly once: one completed marker per id
    with open(os.path.join(eng, "journal.jsonl")) as f:
        markers = [json.loads(ln) for ln in f if ln.strip()
                   and ln.strip().endswith("}")]
    n_completed = {}
    for rec in markers:
        if rec.get("marker") == "completed":
            n_completed[rec["id"]] = n_completed.get(rec["id"], 0) + 1
    assert n_completed == {"cr1": 1, "cr2": 1}
    for rid, ref_sol in ref_out.items():
        got = _solution(os.path.join(eng, "outputs", f"{rid}.h5"))
        for key in sorted(ref_sol):
            np.testing.assert_array_equal(
                got[key], ref_sol[key],
                err_msg=f"{marker}/{rid}/{key} not byte-identical",
            )


def test_serve_fault_sites_end_to_end(drill_world, tmp_path):
    """The three engine fault sites drilled through the real serve
    process in one resident run, exercised via sequential submits so
    the retry/shed/quarantine legs are judged on real outcomes:
    request.parse -> malformed rejection; journal.append -> in-place
    retry recovery; session.attach -> FAILED outcomes that quarantine
    the tenant (and only that tenant)."""
    eng = str(tmp_path / "eng")
    env = _env({
        "SART_FAULT": "request.parse:io:1:1,journal.append:io:1:2,"
                      "session.attach:error:1:2",
        "SART_RETRY_BASE_DELAY": "0.01",
    })
    submit_env = _env()
    proc, lines = _start_serve(
        _serve_cmd(drill_world, eng, "--quarantine_after", "2"), env,
    )
    _drain_stdout(proc, lines)
    try:
        def submit(rid, tenant):
            return subprocess.run(
                _submit_cmd(eng, "--id", rid, "--tenant", tenant,
                            "--wait", "90"),
                env=submit_env, capture_output=True, text=True,
                timeout=120,
            )

        # parse fault trips on the first payload: rejected malformed
        # (response keyed by the ingest file stem, i.e. the id)
        p1 = submit("p1", "noisy")
        assert p1.returncode == 1, p1.stdout + p1.stderr
        assert json.loads(p1.stdout)["reason"] \
            == req_mod.REASON_MALFORMED
        # attach faults fail two requests -> tenant quarantined; the
        # journal's own injected append faults retry in place underneath
        f1 = submit("f1", "noisy")
        assert f1.returncode == 3, f1.stdout + f1.stderr
        assert json.loads(f1.stdout)["outcome"]["status"] == "failed"
        f2 = submit("f2", "noisy")
        assert json.loads(f2.stdout)["outcome"]["status"] == "failed"
        f3 = submit("f3", "noisy")
        assert f3.returncode == 3
        assert json.loads(f3.stdout)["reason"] \
            == req_mod.REASON_TENANT_QUARANTINED
        ok = submit("ok", "calm")
        assert ok.returncode == 0, ok.stdout + ok.stderr
        assert json.loads(ok.stdout)["outcome"]["status"] == "completed"
    finally:
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
    assert rc == 4
    # the journal survived its injected append faults via retry: every
    # accepted request has a consistent record
    completed, pending = journal_mod.RequestJournal(
        os.path.join(eng, "journal.jsonl")).replay()
    assert set(completed) == {"f1", "f2", "ok"} and not pending
