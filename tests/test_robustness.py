"""Regression tests for edge cases found in review: degenerate inputs must
fail fast (or degrade gracefully), never traceback."""

import numpy as np
import h5py
import pytest

from sartsolver_tpu.config import SolverOptions, parse_time_intervals
from sartsolver_tpu.models.sart import make_problem, solve
from sartsolver_tpu.io.image import CompositeImage
from sartsolver_tpu.cli import main

from test_sart_core import make_case
import fixtures as fx


def test_all_zero_frame_does_not_crash():
    """Dark frame (all zeros): norm and msq guards must avoid 0/0 (the
    reference NaNs such a frame; we degrade to a finite solve that still
    terminates on the stall test)."""
    H, _, _ = make_case(seed=21)
    g = np.zeros(H.shape[0])
    opts = SolverOptions(max_iterations=500, conv_tolerance=1e-6)
    res = solve(make_problem(H, opts=opts), g, opts=opts)
    assert np.isfinite(np.asarray(res.solution)).all()
    assert np.isfinite(float(res.convergence))
    # must not spin all 500 iterations on a no-signal frame
    assert int(res.iterations) < 500


def test_all_negative_frame_does_not_crash():
    H, _, _ = make_case(seed=22)
    g = np.full(H.shape[0], -1.0)
    opts = SolverOptions(max_iterations=3, conv_tolerance=1e-6)
    res = solve(make_problem(H, opts=opts), g, opts=opts)
    assert np.isfinite(np.asarray(res.solution)).all()


def test_log_warm_start_with_zeros_is_floored():
    """Log path must floor a warm start containing exact zeros — otherwise
    log(0) = -inf poisons the Laplacian penalty and zero voxels can never
    recover multiplicatively (reference floors unconditionally,
    sartsolver.cpp:263)."""
    H, g, _ = make_case(seed=23, neg_pixels=0)
    f0 = np.zeros(H.shape[1])  # e.g. clamped linear solution
    opts = SolverOptions(logarithmic=True, guess_floor=0.0,
                         max_iterations=5, conv_tolerance=1e-12)
    res = solve(make_problem(H, opts=opts), g, f0=f0, opts=opts)
    sol = np.asarray(res.solution)
    assert np.isfinite(sol).all()
    assert (sol > 0).any()


def test_degenerate_timelines_fail_fast(tmp_path):
    """Single-frame cameras at different times: no step can be derived;
    must raise a clean error, not ZeroDivisionError."""
    paths, *_ = fx.write_world(tmp_path, n_frames=1, jitter_b=0.05)
    from sartsolver_tpu.io import hdf5files as hf
    m, i = hf.categorize_input_files(
        [paths["rtm_a1"], paths["rtm_a2"], paths["rtm_b"],
         paths["img_a"], paths["img_b"]])
    sm, si = hf.sort_rtm_files(m), hf.sort_image_files(i)
    masks = hf.read_rtm_frame_masks(sm)
    with pytest.raises(ValueError, match="time step"):
        CompositeImage(si, masks, [(0.0, 10.0, 0.0, 0.0)], fx.NPIXEL, 0)


def test_empty_middle_time_segment_rejected():
    with pytest.raises(ValueError, match="Unable to recognize"):
        parse_time_intervals("20:30,,40:50")
    # trailing comma still fine
    assert len(parse_time_intervals("20:30,")) == 1


def test_cli_pixel_shards_validated(tmp_path, capsys):
    paths, *_ = fx.write_world(tmp_path)
    for bad in ("0", "-1"):
        with pytest.raises(SystemExit):
            main(["--pixel_shards", bad, paths["rtm_b"], paths["img_b"]])
    assert "pixel_shards" in capsys.readouterr().err


def test_cli_missing_attr_exits_1(tmp_path, capsys):
    """Openable HDF5 file with a missing attribute: message + exit 1, not a
    KeyError traceback."""
    paths, *_ = fx.write_world(tmp_path)
    with h5py.File(paths["rtm_b"], "r+") as f:
        del f["rtm"].attrs["camera_name"]
    rc = main([paths["rtm_b"], paths["img_b"]])
    assert rc == 1
    assert "Missing dataset or attribute" in capsys.readouterr().err
