"""Environment gate for REAL multi-process (multi-controller) tests.

jaxlib's CPU backend only gained multiprocess collectives in later
releases; on builds without them (e.g. jaxlib 0.4.37) every computation
spanning processes dies with ``INVALID_ARGUMENT: Multiprocess
computations aren't implemented on the CPU backend`` — an *environment*
limitation, not a regression in this repo. The 13 multiprocess tests and
the two-process killdrill used to FAIL on such builds, burying real
regressions in a known-red tier-1; they now consult this probe and SKIP
with an explicit reason instead, so tier-1 is green wherever the code is
actually testable and the multi-controller paths light back up
automatically on a capable jaxlib.

The probe is full-fidelity: two real processes initialize the JAX
distributed runtime on a free local port and run one cross-process
allgather — exactly the first collective every gated test would issue.
Result is cached per session (one ~10 s probe when unsupported, then
free). Overrides for CI hygiene:

- ``SART_MP_TESTS=1`` — skip the probe, force the tests to RUN (a build
  that claims support must prove it);
- ``SART_MP_TESTS=0`` — skip the probe, force the tests to SKIP.
"""

from __future__ import annotations

import functools
import os
import socket
import subprocess
import sys

SKIP_REASON = (
    "jaxlib CPU backend lacks multiprocess collectives in this "
    "environment (probe failed: cross-process computations are "
    "unimplemented); set SART_MP_TESTS=1 to force-run"
)

_PROBE_SRC = """
import sys
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=sys.argv[1],
    num_processes=2,
    process_id=int(sys.argv[2]),
)
import jax.numpy as jnp
from jax.experimental import multihost_utils
out = multihost_utils.process_allgather(jnp.ones((1,), jnp.float32))
assert out.shape[0] == 2, out.shape
print("MP_COLLECTIVES_OK")
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@functools.lru_cache(maxsize=None)
def multiprocess_collectives_supported() -> bool:
    """True when a real 2-process CPU collective works here (cached)."""
    forced = os.environ.get("SART_MP_TESTS", "")
    if forced == "1":
        return True
    if forced == "0":
        return False
    coordinator = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # no tunnel plugin in children
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _PROBE_SRC, coordinator, str(rank)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for rank in range(2)
    ]
    try:
        outs = [p.communicate(timeout=120)[0] for p in procs]
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        return False
    return all(p.returncode == 0 for p in procs) and all(
        "MP_COLLECTIVES_OK" in out for out in outs
    )
