"""utils/cache.py — shared compilation-cache configuration (ADVICE r2:
the default must live under the user's own tree with safe permissions, and
both entry points must honor the same opt-out)."""

import os
import stat

import pytest

from sartsolver_tpu.utils.cache import (
    configure_compilation_cache,
    default_cache_dir,
)


@pytest.fixture
def clean_env(monkeypatch):
    monkeypatch.delenv("SART_COMPILATION_CACHE", raising=False)
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
    monkeypatch.delenv("XDG_CACHE_HOME", raising=False)


def test_default_under_user_cache_tree(clean_env, monkeypatch, tmp_path):
    monkeypatch.setenv("XDG_CACHE_HOME", str(tmp_path / "xdg"))
    assert default_cache_dir() == str(tmp_path / "xdg" / "sartsolver" / "jax")
    warned = []
    d = configure_compilation_cache(warn=warned.append)
    assert d == default_cache_dir() and not warned
    mode = stat.S_IMODE(os.stat(d).st_mode)
    assert not (mode & (stat.S_IWGRP | stat.S_IWOTH))


def test_empty_opt_out_disables(clean_env, monkeypatch):
    monkeypatch.setenv("SART_COMPILATION_CACHE", "")
    assert configure_compilation_cache(warn=lambda m: None) is None


def test_jax_env_var_honored(clean_env, monkeypatch, tmp_path):
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", str(tmp_path / "jc"))
    assert configure_compilation_cache(warn=lambda m: None) == str(tmp_path / "jc")


@pytest.mark.skipif(not hasattr(os, "getuid"), reason="POSIX only")
def test_world_writable_dir_refused(clean_env, monkeypatch, tmp_path):
    unsafe = tmp_path / "unsafe"
    unsafe.mkdir()
    os.chmod(unsafe, 0o777)
    monkeypatch.setenv("SART_COMPILATION_CACHE", str(unsafe))
    warned = []
    assert configure_compilation_cache(warn=warned.append) is None
    assert warned and "refusing" in warned[0]
