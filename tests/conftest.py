"""Test environment: force CPU backend with 8 virtual devices so the
sharded ('pixels',) / ('pixels','voxels') code paths run without TPU
hardware (the JAX equivalent of testing mpirun -np 8 on one box), and enable
x64 so the fp64 CPU-parity path is exercisable."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The axon TPU-tunnel plugin registers itself in every interpreter via
# sitecustomize and intercepts backend lookup; when the tunnel is slow or
# down it can block even pure-CPU runs. Tests are CPU-only by design, so
# drop the non-CPU factories before any backend is instantiated.
from jax._src import xla_bridge as _xb  # noqa: E402

for _name in list(_xb._backend_factories):
    # Keep the built-in backends registered — Pallas's import registers
    # lowering rules for platform "tpu" and fails if the platform vanished —
    # but drop third-party tunnel plugins (axon) that can hang at init.
    if _name not in ("cpu", "tpu", "cuda", "rocm"):
        _xb._backend_factories.pop(_name, None)

# sitecustomize imports jax before this file runs, so JAX_PLATFORMS=axon from
# the outer environment is already latched into the config — override it too.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
