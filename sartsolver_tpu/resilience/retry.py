"""Bounded retry with exponential backoff, jitter, and a per-site deadline.

HPC tomography pipelines treat transient I/O stalls as expected events
(arXiv:2003.12677 §4, arXiv:2304.12934): a torn HDF5 read over NFS or a
coordinator that is still coming up usually succeeds on the second
attempt. This module wraps exactly three call sites (composite frame
reads, RTM stripe ingest, ``jax.distributed.initialize``) in a retry loop
that is **bounded three ways** — attempt count, per-attempt backoff
ceiling, and a wall-clock deadline for the whole site — so a *permanent*
failure still surfaces promptly as :class:`RetriesExhausted` for the
caller's degradation path (per-frame isolation, or a clean
infrastructure exit).

Knobs (environment, read per call so tests can monkeypatch):

- ``SART_RETRY_ATTEMPTS`` (default 3): total attempts, 1 = no retry.
- ``SART_RETRY_BASE_DELAY`` (default 0.05 s): first backoff.
- ``SART_RETRY_MAX_DELAY`` (default 2 s): backoff ceiling.
- ``SART_RETRY_DEADLINE`` (default 60 s): give up retrying once this much
  wall clock has elapsed at the site, even with attempts left.

Backoff jitter is seeded per (site, process): the per-process component
is what actually de-synchronizes a pod's hosts retrying the same stripe
(same-site seeds alone would give every host the identical backoff
sequence), while the stable site component keeps the sequences
well-spread across sites within one process. Reproducibility of *trip
patterns* lives in the fault registry (resilience/faults.py), which is
seeded stably — retry timing is allowed to vary run-to-run, trip
placement is not.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, Dict, Optional, Tuple, Type

import numpy as np


class RetriesExhausted(RuntimeError):
    """Every attempt at a retried site failed; ``__cause__`` is the last
    error. Deliberately NOT an ``OSError``: the CLI maps an escaped
    exhaustion to the infrastructure exit code, not the polite
    input-error exit."""

    def __init__(self, site: str, attempts: int, last: BaseException):
        super().__init__(
            f"{site}: {attempts} attempt(s) failed; last error: "
            f"{type(last).__name__}: {last}"
        )
        self.site = site
        self.attempts = attempts


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Retry shape for one site; :meth:`from_env` is the production path."""

    attempts: int = 3
    base_delay: float = 0.05
    max_delay: float = 2.0
    jitter: float = 0.1  # +- fraction of the backoff
    deadline: float = 60.0  # wall-clock budget for all attempts at the site

    @classmethod
    def from_env(cls) -> "RetryPolicy":
        return cls(
            attempts=max(1, int(os.environ.get("SART_RETRY_ATTEMPTS", "3"))),
            base_delay=float(os.environ.get("SART_RETRY_BASE_DELAY", "0.05")),
            max_delay=float(os.environ.get("SART_RETRY_MAX_DELAY", "2")),
            deadline=float(os.environ.get("SART_RETRY_DEADLINE", "60")),
        )

    def backoff(self, attempt: int, rng: np.random.Generator) -> float:
        """Delay before retry ``attempt`` (1-based): exponential, capped,
        jittered."""
        delay = min(self.base_delay * (2.0 ** (attempt - 1)), self.max_delay)
        if self.jitter > 0:
            delay *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return max(delay, 0.0)


# site -> {"attempts": total calls of fn, "recoveries": successes after at
# least one failure, "exhausted": RetriesExhausted raised}. Feeds the
# end-of-run resilience summary.
_STATS: Dict[str, Dict[str, int]] = {}


def retry_stats() -> Dict[str, Dict[str, int]]:
    return {site: dict(v) for site, v in _STATS.items()}


def reset_retry_stats() -> None:
    _STATS.clear()


def retry_call(
    fn: Callable,
    *,
    site: str,
    policy: Optional[RetryPolicy] = None,
    retry_on: Tuple[Type[BaseException], ...] = (OSError,),
    sleep: Callable[[float], None] = time.sleep,
):
    """Call ``fn()`` with the site's retry policy.

    Only ``retry_on`` exceptions are retried — anything else (an internal
    bug) propagates from the first attempt. Exhaustion (attempts, or the
    wall-clock deadline) raises :class:`RetriesExhausted` chaining the
    last error.
    """
    policy = policy or RetryPolicy.from_env()
    stats = _STATS.setdefault(
        site, {"attempts": 0, "recoveries": 0, "exhausted": 0}
    )
    from sartsolver_tpu.resilience.faults import site_seed

    # stable site component + process component (see module docstring)
    rng = np.random.default_rng([site_seed(site), os.getpid()])
    start = time.monotonic()
    last: Optional[BaseException] = None
    attempt = 0
    for attempt in range(1, policy.attempts + 1):
        stats["attempts"] += 1
        try:
            result = fn()
        except retry_on as err:
            last = err
            if (attempt >= policy.attempts
                    or time.monotonic() - start >= policy.deadline):
                break
            sleep(policy.backoff(attempt, rng))
            continue
        if attempt > 1:
            stats["recoveries"] += 1
        return result
    stats["exhausted"] += 1
    raise RetriesExhausted(site, attempt, last) from last
