"""Adaptive OOM degradation: a batch-halving ladder on dispatch failure.

A dispatch that dies with the runtime's ``RESOURCE_EXHAUSTED`` is almost
never a reason to lose frames: the same frames usually solve at a
smaller frame-group size (half the measurement/solution batch on
device). The CLI's grouped frame loops consult a :class:`GroupSizeLadder`
around every dispatch:

- an OOM **halves** the current group size and re-solves the *same*
  frames at the reduced size — no frame is skipped, no row reordered
  (the chain loop's warm carry is untouched: the failed dispatch never
  updated it);
- the reduction **sticks** for the rest of the run (the memory did not
  come back; re-probing the old size would OOM every group) and is
  reported in the end-of-run resilience summary;
- at group size 1 the ladder is exhausted and the failure falls through
  to the existing per-frame isolation (a FRAME_FAILED row, or an abort
  under ``--fail_fast``).

The ladder is pure host-side control flow: with no OOM the dispatched
programs are exactly the ones the undegraded run compiles, and with the
layer "disabled" (nothing ever trips) the traced programs are
byte-identical — pinned by the ``guarded_dispatch`` compile-audit entry
below, whose golden signature must equal ``sharded_batch``'s.

Deterministic testing: the ``oom`` fault kind
(``SART_FAULT=solve.dispatch:oom:1:2``) raises
:class:`~sartsolver_tpu.resilience.faults.InjectedOOM`, whose message
carries the same ``RESOURCE_EXHAUSTED`` marker XLA uses, so
:func:`is_resource_exhausted` matches injected and real OOMs by one rule.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

from sartsolver_tpu.resilience.faults import InjectedOOM

# Substrings marking a device allocation failure in the runtime's error
# text. XLA raises "RESOURCE_EXHAUSTED: Out of memory while trying to
# allocate ..." (jaxlib XlaRuntimeError); the lowercase "out of memory"
# alternative catches allocator messages that drop the status prefix.
_OOM_MARKERS = ("resource_exhausted", "out of memory")


def is_resource_exhausted(err: BaseException) -> bool:
    """True when ``err`` is a device out-of-memory — injected or real."""
    if isinstance(err, InjectedOOM):
        return True
    text = str(err).lower()
    return any(marker in text for marker in _OOM_MARKERS)


class GroupSizeLadder:
    """Current frame-group size plus the halving history.

    ``on_event`` (optional) receives one human-readable line per halving
    — the CLI wires it to the run summary and stderr.
    """

    def __init__(
        self,
        size: int,
        on_event: Optional[Callable[[str], None]] = None,
    ):
        if size < 1:
            raise ValueError("Group size must be positive.")
        self.initial = int(size)
        self.size = int(size)
        self.events: List[Tuple[int, int]] = []  # (from, to) per halving
        self._on_event = on_event
        # telemetry (obs/metrics.py): the current ladder level as a gauge
        # plus one counter tick per halving — host-side bookkeeping only
        from sartsolver_tpu.obs import metrics as obs_metrics

        registry = obs_metrics.get_registry()
        self._size_gauge = registry.gauge("frame_group_size")
        self._oom_counter = registry.counter("oom_degradations_total")
        self._size_gauge.set(self.size)

    @property
    def degraded(self) -> bool:
        return self.size != self.initial

    def note_oom(self, err: BaseException) -> bool:
        """Record an OOM at the current size. Returns True when the
        ladder halved (caller re-dispatches the same frames at
        ``self.size``), False when already at 1 (exhausted — fall through
        to per-frame isolation)."""
        if self.size <= 1:
            return False
        new = self.size // 2
        self.events.append((self.size, new))
        if self._on_event is not None:
            self._on_event(
                f"device OOM at frame-group size {self.size} "
                f"({type(err).__name__}); re-solving the same frames at "
                f"{new} — the reduction sticks for the rest of the run"
            )
        self.size = new
        self._size_gauge.set(new)
        self._oom_counter.inc()
        return True

    def summary(self) -> Optional[str]:
        """One summary line for the run accounting, or None when the
        ladder never tripped."""
        if not self.events:
            return None
        path = " -> ".join(
            [str(self.events[0][0])] + [str(new) for _, new in self.events]
        )
        return (
            f"oom degradation: frame-group size {path} "
            f"({len(self.events)} event(s); reduced size kept for the "
            "rest of the run)"
        )


def dispatch_guarded(
    dispatch: Callable[[], object],
    *,
    ladder: Optional[GroupSizeLadder] = None,
):
    """Run one dispatch under the availability wrappers: a dispatch-phase
    beacon for the hang watchdog, and OOM classification for the ladder.

    Returns ``(result, None)`` on success and ``(None, err)`` after an
    OOM that halved the ladder (the caller re-stacks the same frames at
    ``ladder.size`` and dispatches again). Every other exception — and an
    OOM with the ladder exhausted or absent — propagates unchanged, so
    the caller's isolation semantics are exactly the unwrapped ones.
    """
    from sartsolver_tpu.obs import trace as obs_trace
    from sartsolver_tpu.resilience import watchdog

    watchdog.beacon(watchdog.PHASE_DISPATCH)
    try:
        with obs_trace.span("solve.dispatch"):
            return dispatch(), None
    except Exception as err:
        if (
            ladder is not None
            and is_resource_exhausted(err)
            and ladder.note_oom(err)
        ):
            return None, err
        raise


# --------------------------------------------------------------------------
# compile-audit self-registration (analysis/registry.py): the dispatch
# path the CLI actually runs is now wrapped by the availability layer
# (beacon + ladder above). The wrappers are host-only by design; this
# entry lowers the sharded batched solve THROUGH dispatch_guarded with a
# live (untripped) ladder and a running beacon, and its golden signature
# is asserted byte-equal to the unwrapped `sharded_batch` entry's
# (tests/test_availability.py) — the machine-checked form of "with the
# layer disabled the traced programs are identical".

from sartsolver_tpu.analysis.registry import (  # noqa: E402
    AUDIT_P as _AUDIT_P,
    AUDIT_V as _AUDIT_V,
    register_audit_entry as _register_audit_entry,
)

_AUDIT_SHARDS = 2


@_register_audit_entry(
    "guarded_dispatch",
    description="sharded batched solve dispatched through the "
                "availability layer (watchdog beacon + OOM ladder armed, "
                "nothing tripped); golden must equal sharded_batch's",
    loop_copy_threshold=(_AUDIT_P // _AUDIT_SHARDS) * _AUDIT_V,
    loop_convert_threshold=(_AUDIT_P // _AUDIT_SHARDS) * _AUDIT_V,
    loop_collective_budget={
        "all-reduce": 2, "all-gather": 0, "all-to-all": 0,
        "collective-permute": 0,
    },
    min_devices=_AUDIT_SHARDS,
)
def _audit_guarded_dispatch():
    from sartsolver_tpu.parallel.sharded import _audit_sharded_batch

    ladder = GroupSizeLadder(2)
    lowered, err = dispatch_guarded(_audit_sharded_batch, ladder=ladder)
    assert err is None and not ladder.degraded  # nothing tripped
    return lowered


__all__ = [
    "GroupSizeLadder",
    "dispatch_guarded",
    "is_resource_exhausted",
]
