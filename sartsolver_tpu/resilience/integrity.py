"""End-to-end numerical integrity: ABFT checks, ingest digests, SDC policy.

Every other resilience layer in this package reacts to failures that
*announce themselves* — exceptions, hangs, non-finite metrics. Silent data
corruption does none of that: a flipped bit in the device-resident RTM, a
torn HDF5 stripe, or a bad MXU product produces a perfectly finite, merely
*wrong* solution that then warm-starts every following frame. This module
is the detection-and-escalation side of docs/RESILIENCE.md §8; the
device-side checks it parameterizes live in ``models/sart.py``.

Three detection mechanisms, all off by default (``SolverOptions.integrity``
/ ``--integrity`` / ``SART_INTEGRITY=1``; with the layer off every traced
program and every ingest byte is identical to a build without it):

1. **In-solve ABFT** (algorithm-based fault tolerance): the linear-algebra
   identities ``sum(Hf) == rho . f`` (rho = ``ray_density``, the column
   sums) and ``sum(H^T w) == lambda . w`` (lambda = ``ray_length``, the row
   sums) hold *exactly* for the stored matrix, for any vector — so a
   per-iteration comparison of two already-needed reductions against an
   fp-derived tolerance (:func:`abft_tolerance`) detects a corrupted
   resident matrix or a bad matmul product the same iteration it happens,
   at the cost of two dot products and two scalar compares per frame. The
   checksum dot folds into the existing convergence all-reduce on sharded
   meshes (``jnp.stack`` before the psum), so the audited per-iteration
   collective budget is unchanged (``sharded_integrity_batch`` golden).
2. **Ingest stripe digests**: every RTM stripe is read twice and the CRC32
   of the two byte streams compared (:func:`stripe_digest`); a torn or
   corrupted read will not reproduce byte-for-byte, so a mismatch raises
   :class:`StripeDigestError` *inside* the existing retry policy — the
   stripe is simply re-read. Post-upload, the device-computed rho/lambda
   are verified against host-side sums accumulated during the ingest
   (:class:`IngestStats` / :func:`verify_ray_stats`), catching staging and
   quantization corruption before the first solve.
3. **Resident re-audit**: rho/lambda recomputed from the device-resident
   matrix every ``SART_INTEGRITY_REAUDIT`` frames and compared bit-for-bit
   against the upload-time snapshot
   (``DistributedSARTSolver.reaudit_ray_stats``) — resident bit rot that
   predates any solve's ABFT trip is caught between frames.

Escalation (:class:`SdcEscalation`), wired into the existing taxonomy:
a detected frame is **recomputed once** (a transient MXU fault does not
reproduce); a frame that trips again is **FAILED** through the per-frame
isolation path (status -3 row, run continues, exit 2); once
``SART_SDC_ABORT_THRESHOLD`` frames have failed terminally — or a resident
re-audit / post-upload verification mismatches — the run **aborts** with
:class:`PersistentCorruptionError` (infrastructure exit 3, resumable file)
and a quarantine event in telemetry, because a corrupted resident session
poisons every request it serves.

Telemetry: ``sdc_detected_total``, ``integrity_recomputes_total``,
``stripe_digest_mismatch_total`` counters (docs/OBSERVABILITY.md) plus
quarantine events in the run summary and ``--metrics_out`` artifact.
"""

from __future__ import annotations

import math
import os
import zlib
from typing import List, Optional

import numpy as np

from sartsolver_tpu.utils.locking import named_lock


class IntegrityError(RuntimeError):
    """A frame's silent-data-corruption detection survived its recompute;
    the frame is escalated into the per-frame isolation path (a FAILED
    status row) with this as the recorded error."""


#: the one user-facing diagnostic for a reproduced in-solve detection —
#: shared by the grouped loops (cli.py) and the scheduler path so the
#: identical condition reads identically whichever loop hit it
SDC_REPRODUCED = (
    "silent data corruption detected in-solve and reproduced "
    "by the recompute"
)


class PersistentCorruptionError(RuntimeError):
    """Corruption that recomputing cannot clear: the resident matrix (or
    the output of its staging) is wrong, so every further solve is
    poisoned. The CLI maps this to the infrastructure exit code 3 (the
    output file stays resumable) and records a quarantine event."""


class StripeDigestError(OSError):
    """The two reads of one RTM stripe disagreed byte-for-byte — a torn or
    corrupted read. An ``OSError`` so the existing ``hdf5.rtm_ingest``
    retry policy re-reads the stripe instead of aborting."""


# ---------------------------------------------------------------------------
# enablement
# ---------------------------------------------------------------------------

_state = {"enabled": None}  # None: not configured, read SART_INTEGRITY
_lock = named_lock("resilience.integrity")


def configure(enabled: bool) -> None:
    """Set the process-wide ingest-integrity switch (the CLI calls this
    from ``--integrity``; the in-solve check is per-``SolverOptions``)."""
    with _lock:
        _state["enabled"] = bool(enabled)


def env_enabled() -> bool:
    """The ``SART_INTEGRITY`` environment switch alone, ignoring any
    :func:`configure` call (the CLI folds it into its per-run decision
    before configuring). Accepted values are the shared boolean-switch
    list (:func:`sartsolver_tpu.utils.env_truthy`)."""
    from sartsolver_tpu.utils import env_truthy

    return env_truthy("SART_INTEGRITY")


def enabled() -> bool:
    """Whether ingest-side integrity (stripe digests) is on. Defaults to
    the ``SART_INTEGRITY`` environment variable so library users get the
    same switch the CLI exposes."""
    val = _state["enabled"]
    if val is None:
        return env_enabled()
    return val


# ---------------------------------------------------------------------------
# ABFT tolerance
# ---------------------------------------------------------------------------

def abft_tolerance(
    compute_dtype, rtm_dtype: Optional[str], npixel: int, nvoxel: int
) -> float:
    """Relative tolerance of the in-solve ABFT residual, per dtype.

    Both sides of each identity are sums of ``npixel * nvoxel``
    non-negative products (the RTM and the iterates are non-negative, so
    there is no cancellation): the accumulated rounding error is bounded
    by ``~eps * n * |sum|`` worst-case and ``~eps * sqrt(n) * |sum|`` for
    the blocked/pairwise reductions XLA actually emits. The tolerance uses
    the square-root law with a 64x safety factor — wide enough that clean
    solves never trip across dtypes/shapes/seeds (pinned by the
    ``tests/test_integrity.py`` hypothesis suite), tight enough that any
    single flip whose induced residual exceeds it is detected the same
    iteration. bf16/int8 storage get a further 4x: their ray stats and
    dequantized products round through extra fp32 steps.
    """
    eps = float(np.finfo(np.dtype(compute_dtype)).eps)
    factor = 4.0 if rtm_dtype in ("bfloat16", "int8") else 1.0
    return 64.0 * factor * eps * math.sqrt(float(npixel + nvoxel) + 1.0)


# ---------------------------------------------------------------------------
# ingest digests + host-side ray-stats accumulation
# ---------------------------------------------------------------------------

def stripe_digest(array: np.ndarray) -> int:
    """CRC32 of an array's bytes (order-stable: contiguous C layout)."""
    return zlib.crc32(np.ascontiguousarray(array).tobytes()) & 0xFFFFFFFF


def digest_mismatch(what: str) -> None:
    """The ONE detect-count-raise convention for ingest double-read
    digest mismatches: increment ``stripe_digest_mismatch_total`` and
    raise :class:`StripeDigestError` (an ``OSError``, so the existing
    ``hdf5.rtm_ingest`` retry policy re-reads instead of aborting).
    Shared by the stripe-level compare (``parallel/multihost.py``) and
    the sparse-cache population compare (``io/raytransfer.py``)."""
    from sartsolver_tpu.obs import metrics as obs_metrics

    obs_metrics.get_registry().counter(
        "stripe_digest_mismatch_total"
    ).inc()
    raise StripeDigestError(
        f"{what} read twice with different bytes (torn or corrupted "
        "read); retrying"
    )


def storage_round(values: np.ndarray, rtm_dtype) -> np.ndarray:
    """fp64 view of ``values`` after rounding through the on-device
    storage dtype — what the device's ray-stat reductions will actually
    sum. int8 is handled by the caller (codes need their scales)."""
    jd = np.dtype("float32") if rtm_dtype is None else None
    if jd is None:
        name = str(rtm_dtype)
        if name == "bfloat16":
            import ml_dtypes  # jax's own dtype package — always present

            return np.asarray(values, ml_dtypes.bfloat16).astype(np.float64)
        jd = np.dtype(name)
    return np.asarray(values, jd).astype(np.float64)


class IngestStats:
    """Host-side rho/lambda accumulator filled during the chunked ingest.

    ``add(values, r0, c0)`` takes one logical block of the matrix in the
    *storage-rounded* fp64 representation (``storage_round``, or
    dequantized int8 codes) at logical offset ``(r0, c0)``; every logical
    element must be added exactly once. The absolute sums scale the
    verification tolerance (:func:`verify_ray_stats`).
    """

    def __init__(self, npixel: int, nvoxel: int):
        self.npixel, self.nvoxel = int(npixel), int(nvoxel)
        self.colsum = np.zeros(nvoxel, np.float64)
        self.rowsum = np.zeros(npixel, np.float64)
        self.colabs = np.zeros(nvoxel, np.float64)
        self.rowabs = np.zeros(npixel, np.float64)

    def add(self, values: np.ndarray, r0: int, c0: int) -> None:
        v = np.asarray(values, np.float64)
        n, m = v.shape
        self.colsum[c0:c0 + m] += v.sum(axis=0)
        self.rowsum[r0:r0 + n] += v.sum(axis=1)
        av = np.abs(v)
        self.colabs[c0:c0 + m] += av.sum(axis=0)
        self.rowabs[r0:r0 + n] += av.sum(axis=1)


def verify_ray_stats(
    stats: IngestStats,
    ray_density: np.ndarray,
    ray_length: np.ndarray,
    *,
    rtm_dtype: Optional[str] = None,
) -> List[str]:
    """Compare device-computed rho/lambda against the ingest accumulator.

    Returns a list of mismatch descriptions (empty = verified). The
    tolerance covers the device's fp32 reductions against the host's fp64
    ones — relative to the *absolute* column/row mass, so sparse columns
    do not false-positive on cancellation they cannot have, and it grows
    with the reduction length like :func:`abft_tolerance` (the device's
    blocked fp32 sums accumulate ``~eps32 * sqrt(n)`` relative error, so
    a fixed band would spuriously quarantine a clean many-megapixel
    ingest at startup). int8 gets a wider floor: its device stats
    multiply an exact int32 sum by an fp32 scale, and the host
    dequantizes through the same fp32 scales in fp64.
    """
    floor = 1e-3 if rtm_dtype == "int8" else 1e-4
    eps32 = float(np.finfo(np.float32).eps)
    out: List[str] = []
    for name, host, habs, dev, length in (
        ("ray_density", stats.colsum, stats.colabs,
         np.asarray(ray_density, np.float64)[: stats.nvoxel],
         stats.npixel),
        ("ray_length", stats.rowsum, stats.rowabs,
         np.asarray(ray_length, np.float64)[: stats.npixel],
         stats.nvoxel),
    ):
        rel = max(floor, 32.0 * eps32 * math.sqrt(float(length) + 1.0))
        err = np.abs(host - dev)
        bad = err > rel * (habs + 1.0)
        if bad.any():
            worst = int(np.argmax(err / (habs + 1.0)))
            out.append(
                f"{name}: {int(bad.sum())} element(s) beyond the "
                f"{rel:g}-relative band (worst at index {worst}: host "
                f"{host[worst]:.9g} vs device {dev[worst]:.9g})"
            )
    return out


# ---------------------------------------------------------------------------
# escalation policy
# ---------------------------------------------------------------------------

class SdcEscalation:
    """Host-side escalation of in-solve SDC detections
    (docs/RESILIENCE.md §8): recompute once → FAILED row → quarantine
    abort after ``SART_SDC_ABORT_THRESHOLD`` terminal frames (default 2 —
    two frames corrupt even after recomputing means the *resident* state
    is corrupt, not the transient).

    The three integrity counters are registered up front so a clean
    integrity-on run's artifact shows them at zero (a dashboard can tell
    "nothing detected" from "layer off").
    """

    def __init__(self, *, on_event=None, abort_threshold: Optional[int] = None):
        from sartsolver_tpu.obs import metrics as obs_metrics

        registry = obs_metrics.get_registry()
        self._detected = registry.counter("sdc_detected_total")
        self._recomputes = registry.counter("integrity_recomputes_total")
        registry.counter("stripe_digest_mismatch_total")
        self._on_event = on_event
        self._terminal = 0
        self._terminal_times: List[float] = []
        self.threshold = (
            int(os.environ.get("SART_SDC_ABORT_THRESHOLD", "2"))
            if abort_threshold is None else int(abort_threshold)
        )

    def _event(self, message: str) -> None:
        if self._on_event is not None:
            self._on_event(message)

    def detected(self, n: int = 1) -> None:
        """Record n in-solve SDC detections (pre-escalation)."""
        self._detected.inc(n)

    def note_recompute(self, n_frames: int = 1) -> None:
        """A detected frame (or group) is being re-solved once."""
        self._recomputes.inc(n_frames)

    def record_terminal(self, frame_time: float) -> None:
        """A frame stayed corrupt through its recompute: it becomes a
        FAILED row; raise :class:`PersistentCorruptionError` once the
        abort threshold is reached (quarantine the session). The frame
        times travel in the quarantine event so the operator knows which
        rows to distrust."""
        self._terminal += 1
        self._terminal_times.append(float(frame_time))
        if self.threshold > 0 and self._terminal >= self.threshold:
            shown = ", ".join(f"{t:g}" for t in self._terminal_times[:8])
            if self._terminal > 8:
                shown += ", ..."
            msg = (
                f"quarantine: {self._terminal} frame(s) failed their SDC "
                f"recompute (t = {shown}; persistent silent data "
                "corruption — resident matrix or device state); aborting "
                "the session"
            )
            self._event(msg)
            raise PersistentCorruptionError(msg)

    def resident_failure(self, detail: str) -> None:
        """A resident re-audit or post-upload rho/lambda verification
        mismatched: the session state is provably corrupt — quarantine
        immediately, no recompute can help."""
        msg = f"quarantine: resident integrity verification failed ({detail})"
        self._event(msg)
        raise PersistentCorruptionError(msg)
