"""Hang watchdog: per-phase progress beacons, stack dumps, escalation.

A fleet-operated solver dies three ways that the PR-2 fault paths do not
cover: preemption (shutdown.py), device OOM (degrade.py) — and the worst
one, the *silent hang*: a wedged device runtime, a stalled NFS mount or a
deadlocked collective leaves the process alive but making no progress,
invisible to a scheduler until its global walltime expires. This module
turns "no progress" into a first-class, recoverable event:

- **Beacons** — the pipeline's host phases announce the start of their
  work with :func:`beacon`: frame prefetch (``utils/prefetch.py``),
  host→device staging and solve dispatch (``parallel/sharded.py``,
  ``models/sart.py``), result fetch (``DeviceSolveResult``), output flush
  (``io/solution.py``) and per-frame completion (``cli.py``). A beacon is
  one tuple assignment plus a clock read — nanoseconds, no lock (the GIL
  makes the assignment atomic) — and NOTHING is ever traced: with the
  watchdog disabled the compiled programs are byte-identical (the
  ``guarded_dispatch`` compile-audit golden pins this).
- **Monitor** — :class:`Watchdog` (armed by ``SART_WATCHDOG_TIMEOUT``
  seconds; unset/0 = off) runs a daemon thread that watches the beacon
  and escalates in stages once ``timeout`` seconds pass without a new
  beacon anywhere (the pipeline's threads beacon concurrently, so "which
  thread is stuck" cannot be read off the last beacon — a finished
  prefetcher's beacon can postdate the dispatch that hung; the staged
  ladder needs no such attribution):

  1. dump every thread's stack to stderr, then raise
     :class:`~sartsolver_tpu.resilience.failures.WatchdogTimeout`
     asynchronously into the **main thread** — the frame-loop owner,
     where the three dispatch-side hang hazards (``device.put``,
     ``solve.dispatch``, result fetch) live. An interrupted frame
     escalates through the existing taxonomy: per-frame isolation
     absorbs it as a FRAME_FAILED row; ``--fail_fast``/multihost runs
     abort with EXIT_INFRASTRUCTURE.
  2. after ``SART_WATCHDOG_GRACE`` more seconds without progress (the
     main thread may be wedged inside a C call, where an async
     exception stays pending), interrupt every **registered worker
     thread** (prefetcher, async writer) — a hung prefetch read becomes
     a FrameFailure, a hung lazy fetch/flush latches as a write error,
     and either unblocks the main thread (which then raises its pending
     interrupt: a clean resumable abort).
  3. after another grace without progress, dump stacks once more and
     hard-exit with EXIT_INFRASTRUCTURE — the output file is
     crash-consistent (killdrill model), and "never a deadlocked
     process" is the contract.
- **Heartbeat** — when ``SART_HEARTBEAT_FILE`` is set, every
  frame-completion beacon touches that file, so an *external* supervisor
  (Kubernetes liveness probe, a pod babysitter) gets a progress signal
  without parsing stdout.

Knobs (environment):

- ``SART_WATCHDOG_TIMEOUT`` (seconds; unset/0 disables): beacon-silence
  threshold. Must exceed the slowest legitimate beacon gap — the first
  frame's XLA compile is the usual worst case (the persistent compile
  cache shrinks it on warm starts).
- ``SART_WATCHDOG_GRACE`` (default ``max(timeout, 5)``): extra seconds
  after the async interrupt before the hard abort.
- ``SART_HEARTBEAT_FILE`` (optional): path touched on each frame.
"""

from __future__ import annotations

import ctypes
import os
import sys
import threading
import time
import traceback
import weakref
from typing import Callable, Optional, Tuple

from sartsolver_tpu.resilience.failures import (
    EXIT_INFRASTRUCTURE,
    WatchdogTimeout,
)

# Beacon phase names (free-form strings are fine; these are the pipeline's
# canonical five plus the per-frame completion tick).
PHASE_PREFETCH = "prefetch"
PHASE_STAGE = "device.put"
PHASE_DISPATCH = "solve.dispatch"
PHASE_FETCH = "result.fetch"
PHASE_FLUSH = "io.flush"
PHASE_FRAME_DONE = "frame.done"

# (phase, serial, monotonic time, owning thread ident). The serial makes
# progress detection independent of clock resolution; the whole-tuple
# swap keeps readers consistent without a lock.
_last: Tuple[str, int, float, int] = ("start", 0, 0.0, 0)
_serial = 0
# completed frames this run (the heartbeat file's progress counter) and
# the last *work* phase (any beacon that is not the frame-done tick) — a
# supervisor reading the heartbeat wants "where is it", and at write time
# the most recent beacon is always frame.done itself
_frames_done = 0
_last_work_phase = "start"
# last beacon per phase: phase -> (serial, monotonic time). The SIGUSR1
# status snapshot (obs/flight.py) reads per-phase ages off this — "the
# prefetcher last moved 0.1 s ago but the dispatch is 40 s stale" is the
# attribution the single _last tuple cannot give.
_last_by_phase: dict = {}

# Observability taps (obs/trace.py spans, obs/flight.py ring): every
# beacon is mirrored into each installed tap. One global emptiness check
# when disabled — beacons stay nanoseconds, and NOTHING here is ever
# traced (the compile-audit goldens pin that).
_taps: dict = {}
_tap_seq: Tuple[Callable[[str, int, float, int], None], ...] = ()

# Threads that volunteered for async interruption (prefetcher / async
# writer workers — they catch the exception and degrade their stream).
# WeakSet: a worker that exits without unregistering just vanishes.
_interruptible: "weakref.WeakSet[threading.Thread]" = weakref.WeakSet()


def add_beacon_tap(
    key: str, tap: Callable[[str, int, float, int], None]
) -> None:
    """Install a keyed beacon observer. Taps must be cheap and
    exception-free — they run inside every beacon."""
    global _tap_seq
    _taps[key] = tap
    _tap_seq = tuple(_taps.values())


def remove_beacon_tap(key: str) -> None:
    global _tap_seq
    _taps.pop(key, None)
    _tap_seq = tuple(_taps.values())


def set_beacon_tap(
    tap: Optional[Callable[[str, int, float, int], None]]
) -> None:
    """The trace buffer's single-slot API (obs/trace.py), kept as a view
    over the keyed taps: install (or with None remove) the ``trace``
    tap without touching any other observer (the flight ring)."""
    if tap is None:
        remove_beacon_tap("trace")
    else:
        add_beacon_tap("trace", tap)


def frames_done() -> int:
    """Frames completed (``frame.done`` beacons) since process start."""
    return _frames_done


def beacon(phase: str) -> None:
    """Announce the start of host-side work in ``phase``.

    Called from multiple threads; always recorded (so a watchdog can
    attach mid-run), costs one clock read + two dict/tuple assignments
    when no heartbeat file or tap is configured.
    """
    global _last, _serial, _frames_done, _last_work_phase
    _serial += 1
    now = time.monotonic()
    ident = threading.get_ident()
    _last = (phase, _serial, now, ident)
    _last_by_phase[phase] = (_serial, now)
    if phase == PHASE_FRAME_DONE:
        _frames_done += 1
        path = os.environ.get("SART_HEARTBEAT_FILE")
        if path:
            _write_heartbeat(path)
    else:
        _last_work_phase = phase
    taps = _tap_seq
    if taps:
        for tap in taps:
            try:
                tap(phase, _serial, now, ident)
            except Exception:  # observability must never hurt the run
                pass


def last_beacon() -> Tuple[str, int, float, int]:
    """The most recent beacon (phase, serial, monotonic time, thread id)."""
    return _last


def beacon_ages() -> dict:
    """Seconds since the last beacon of each phase seen so far (the
    SIGUSR1 status snapshot's per-phase staleness table).

    Worker threads insert first-occurrence phases concurrently; a dict
    iteration racing such an insert raises RuntimeError, which would
    silently cost the crash bundle its snapshot — the shared
    ``stale_read`` fallback (utils/locking.py) retries the copy."""
    from sartsolver_tpu.utils.locking import stale_read

    items = stale_read(lambda: list(_last_by_phase.items()), default=[])
    now = time.monotonic()
    return {
        phase: round(now - t, 3)
        for phase, (_serial_, t) in sorted(items)
    }


# Live scheduler view (sched/scheduler.py registers a provider while the
# continuous batcher drives the run): occupancy + in-flight lane serials
# for the heartbeat line and the SIGUSR1 status snapshot. A provider
# must be cheap and exception-tolerant — it runs inside the per-frame
# heartbeat write.
_sched_status: Optional[Callable[[], Optional[dict]]] = None

# Live engine view (engine/server.py registers a provider while a serve
# process runs): queue depth, admitted/shed totals, active request ids,
# per-tenant occupancy — so a SIGUSR1 poke at a wedged daemon attributes
# the stall to a request, not just a pipeline phase (docs/SERVING.md).
_engine_status: Optional[Callable[[], Optional[dict]]] = None

# Crash hook (obs/flight.py): called with a reason string immediately
# before the stage-3 ``os._exit`` so the flight recorder can flush its
# crash bundle — the one abort path no ``finally`` block survives.
_crash_hook: Optional[Callable[[str], None]] = None


def set_sched_status_provider(
    provider: Optional[Callable[[], Optional[dict]]]
) -> None:
    global _sched_status
    _sched_status = provider


def sched_status() -> Optional[dict]:
    """The live scheduler view ({occupancy, lanes, strides}), or None
    when the continuous batcher is not driving."""
    provider = _sched_status
    if provider is None:
        return None
    try:
        return provider()
    except Exception:  # observability must never hurt the run
        return None


def set_engine_status_provider(
    provider: Optional[Callable[[], Optional[dict]]]
) -> None:
    global _engine_status
    _engine_status = provider


def engine_status() -> Optional[dict]:
    """The live serving-engine view ({queue_depth, admitted, shed,
    active_requests, ...}), or None outside a serve process. Providers
    must be cheap and exception-tolerant (heartbeat + signal context)."""
    provider = _engine_status
    if provider is None:
        return None
    try:
        return provider()
    except Exception:  # observability must never hurt the run
        return None


def set_crash_hook(hook: Optional[Callable[[str], None]]) -> None:
    global _crash_hook
    _crash_hook = hook


def _fire_crash_hook(reason: str, timeout: float = 5.0) -> None:
    """Run the crash hook in a bounded daemon thread. The hook writes a
    file, and the filesystem may be EXACTLY what is wedged — the hard
    abort must reach ``os._exit`` whether or not the bundle lands, so
    the write gets ``timeout`` seconds and is then abandoned."""
    hook = _crash_hook
    if hook is None:
        return

    def run() -> None:
        try:
            hook(reason)
        except Exception:  # the bundle must never mask the abort
            pass

    t = threading.Thread(target=run, name="sart-crash-hook", daemon=True)
    try:
        t.start()
        t.join(timeout)
    except Exception:
        pass


def _write_heartbeat(path: str) -> None:
    """Write progress state into the heartbeat file (advisory: failures
    never hurt the run).

    The file carries WHERE the run is, not just that it is alive: the
    last pipeline phase that ran before this frame completed, the
    completed-frame counter and the beacon serial — plus, when the
    continuous-batching scheduler is driving (the default batched path),
    ``occupancy=`` and the in-flight lane serials, so a supervisor sees
    lane health, not just frame count — one ``key=value`` line parseable
    without any schema machinery. The mtime contract is unchanged —
    still one touch per completed frame — so ``find -mmin``-style
    liveness probes keep working. Published via temp-file + rename: the
    supervisor reads at arbitrary instants, and an in-place truncating
    write would expose an empty/partial file between the truncate and
    the write.
    """
    try:
        sched = sched_status()
        extra = ""
        # pod identity (parallel/multihost.export_pod_identity): on a
        # multi-process run every host writes an otherwise identical
        # line — host=k/n lets an external supervisor attribute a
        # stalled pod to the wedged host. Env-read keeps this jax-free;
        # absent (single-process), the line is byte-unchanged.
        host = os.environ.get("SART_POD_PROCESS")
        if host:
            extra += f" host={host}"
        if sched:
            occ = sched.get("occupancy")
            if occ is not None:
                extra += f" occupancy={float(occ):.3f}"
            lanes = sched.get("lanes")
            if lanes is not None:
                extra += " lanes=" + (
                    ",".join(str(s) for s in lanes) if lanes else "-"
                )
        engine = engine_status()
        if engine:
            # the serving-engine view (docs/SERVING.md): a supervisor
            # reading the heartbeat sees queue pressure and shed/
            # quarantine totals, same key=value line contract
            extra += (
                f" queue={engine.get('queue_depth', 0)}"
                f" admitted={engine.get('admitted', 0)}"
                f" shed={engine.get('shed', 0)}"
            )
            active = engine.get("active_requests")
            if active is not None:
                extra += " requests=" + (
                    ",".join(str(r) for r in active) if active else "-"
                )
        from sartsolver_tpu.utils import atomicio

        # fsync=False: the heartbeat is advisory and high-frequency —
        # a torn line after a machine crash only costs one staleness
        # reading, while an fsync per beat would tax the solve loop
        atomicio.write_atomic(
            path,
            f"phase={_last_work_phase} frames={_frames_done} "
            f"serial={_serial}{extra} unix={time.time():.3f}\n",
            fsync=False,
        )
    except OSError:
        pass


def register_interruptible(thread: threading.Thread) -> None:
    """Mark ``thread`` as safe to receive the watchdog's async
    ``WatchdogTimeout`` (it catches the exception and degrades its
    stream instead of dying silently)."""
    _interruptible.add(thread)


def unregister_interruptible(thread: threading.Thread) -> None:
    _interruptible.discard(thread)


def _async_raise(thread_ident: int) -> bool:
    """Raise ``WatchdogTimeout`` in the thread with ``thread_ident``.

    CPython delivers the exception at the next bytecode boundary — which
    is exactly what un-sticks a cooperative stall (the injected ``hang``
    fault's sleep loop, a Python-level retry spin). A thread blocked
    inside a C call (a wedged XLA fetch, ``Thread.join``) will not see it
    until the call returns; the monitor's grace-period hard abort covers
    that case.
    """
    res = ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(thread_ident), ctypes.py_object(WatchdogTimeout)
    )
    if res > 1:  # pragma: no cover - "should never happen" per CPython docs
        # more than one thread state modified: revoke to avoid collateral
        ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(thread_ident), None
        )
        return False
    return res == 1


def _async_revoke(thread_ident: int) -> None:
    """Clear a still-pending async ``WatchdogTimeout`` for a thread.

    A stage-1 interrupt aimed at a thread inside a C call stays PENDING
    until that call returns. If the stall then resolves on its own (a
    legitimately slow compile/write finished — beacons resumed) the
    pending exception would otherwise detonate at some arbitrary later
    bytecode of a healthy run. Revoking is a no-op when the exception
    was already delivered."""
    ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(thread_ident), None
    )


def dump_stacks(out=None) -> None:
    """Write every live thread's stack to ``out`` (default stderr)."""
    out = out if out is not None else sys.stderr
    names = {t.ident: t.name for t in threading.enumerate()}
    lines = ["sartsolve watchdog: thread stacks:"]
    for ident, frame in sys._current_frames().items():
        lines.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        lines.extend(
            line.rstrip("\n") for line in traceback.format_stack(frame)
        )
    out.write("\n".join(lines) + "\n")
    out.flush()


class Watchdog:
    """Monitor thread escalating beacon silence (module docstring).

    ``hard_exit=False`` replaces the final ``os._exit`` with an event
    record — for in-process tests, where killing the interpreter would
    take the test runner with it.
    """

    def __init__(
        self,
        timeout: float,
        *,
        grace: Optional[float] = None,
        poll: Optional[float] = None,
        on_event: Optional[Callable[[str], None]] = None,
        hard_exit: bool = True,
    ):
        if timeout <= 0:
            raise ValueError("Watchdog timeout must be positive.")
        self.timeout = float(timeout)
        self.grace = float(grace) if grace is not None else max(timeout, 5.0)
        self._poll = poll if poll is not None else min(timeout / 4.0, 1.0)
        self._on_event = on_event
        self._hard_exit = hard_exit
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._main_interrupted = False  # stage-1 interrupt possibly pending
        self.fired = 0  # escalations (observability / tests)
        self.hard_aborted = False  # only observable with hard_exit=False

    @classmethod
    def from_env(
        cls, on_event: Optional[Callable[[str], None]] = None
    ) -> Optional["Watchdog"]:
        """A watchdog per ``SART_WATCHDOG_TIMEOUT``, or None when unset/0."""
        timeout = float(os.environ.get("SART_WATCHDOG_TIMEOUT", "0") or 0)
        if timeout <= 0:
            return None
        grace_env = os.environ.get("SART_WATCHDOG_GRACE")
        return cls(
            timeout,
            grace=float(grace_env) if grace_env else None,
            on_event=on_event,
        )

    def start(self) -> "Watchdog":
        beacon("watchdog.start")  # the watch begins from a fresh beacon
        self._thread = threading.Thread(
            target=self._run, name="sart-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._revoke_main()

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _note(self, message: str) -> None:
        sys.stderr.write(f"sartsolve watchdog: {message}\n")
        sys.stderr.flush()
        if self._on_event is not None:
            try:
                self._on_event(f"watchdog: {message}")
            except Exception:  # the event sink must never kill the monitor
                pass

    def _run(self) -> None:
        seen = last_beacon()
        progressed_at = time.monotonic()
        stage = 0  # 0 watching, 1 main interrupted, 2 workers interrupted
        stage_at = progressed_at
        while not self._stop.wait(self._poll):
            now = time.monotonic()
            cur = last_beacon()
            if cur[1] != seen[1]:  # serial moved: progress
                seen = cur
                progressed_at = now
                stage = 0
                # the stall resolved on its own (a slow-but-healthy
                # compile/write finished): a stage-1 interrupt still
                # pending in a C call must not detonate later
                self._revoke_main()
                continue
            stalled = now - progressed_at
            if stage == 0:
                if stalled < self.timeout:
                    continue
                # stage 1: dump everything, interrupt the frame loop —
                # per-frame isolation turns a hung staging/dispatch/fetch
                # into a FRAME_FAILED row and the run continues
                self.fired += 1
                self._note(
                    f"no progress for {stalled:.1f}s (last beacon: phase "
                    f"{cur[0]!r}); dumping thread stacks and interrupting "
                    "the stuck frame"
                )
                dump_stacks()
                self._interrupt_main()
                stage, stage_at = 1, now
            elif stage == 1 and now - stage_at >= self.grace:
                # stage 2: the main thread may be wedged inside a C call
                # (async exceptions stay pending there); interrupting the
                # worker threads un-sticks a hung read/fetch/flush and,
                # by completing the handoff, lets the main thread's
                # pending interrupt fire
                self._note(
                    f"still no progress {stalled:.1f}s in; interrupting "
                    "worker threads"
                )
                self._interrupt_workers()
                stage, stage_at = 2, now
            elif stage == 2 and now - stage_at >= self.grace:
                # stage 3: nothing can be un-stuck from in-process
                self._note(
                    f"still no progress {stalled:.1f}s in; aborting with "
                    f"exit {EXIT_INFRASTRUCTURE} — the output file is "
                    "resumable (--resume)"
                )
                dump_stacks()
                self.hard_aborted = True
                # flush the flight recorder's crash bundle (obs/flight.py)
                # NOW: os._exit skips every finally block, so this hook is
                # the bundle's only chance on the hard-abort path
                _fire_crash_hook(
                    f"watchdog hard abort: no progress for {stalled:.1f}s "
                    f"(last beacon: phase {cur[0]!r})"
                )
                if self._hard_exit:
                    # os._exit: no atexit/finally — anything those would
                    # flush is exactly what is wedged; the solution file
                    # is crash-consistent by construction
                    os._exit(EXIT_INFRASTRUCTURE)
                return

    def _interrupt_main(self) -> None:
        main = threading.main_thread()
        if main.ident is not None and main.is_alive():
            if _async_raise(main.ident):
                self._main_interrupted = True
            else:
                self._note("could not deliver the interrupt to the main "
                           "thread")

    def _revoke_main(self) -> None:
        if not self._main_interrupted:
            return
        self._main_interrupted = False
        main = threading.main_thread()
        if main.ident is not None and main.is_alive():
            _async_revoke(main.ident)

    def _interrupt_workers(self) -> None:
        for t in list(_interruptible):
            if t.ident is not None and t.is_alive():
                _async_raise(t.ident)


