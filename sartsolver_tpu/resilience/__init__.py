"""Resilience layer: fault injection, retry/backoff, failure taxonomy.

The reference solver is a batch MPI job that dies on any fault; a
production service reconstructing hundreds of frames must treat a torn
HDF5 read, a preempted host or one NaN-poisoned frame as an expected
event, not a process-fatal exception (docs/RESILIENCE.md). This package
supplies the three host-side building blocks, each threaded through the
stack by the module that owns the hazard:

- :mod:`~sartsolver_tpu.resilience.faults` — a deterministic
  fault-injection registry (``SART_FAULT=site:kind:prob[:count]`` env +
  programmatic API) with named sites in HDF5 ingest, prefetch, device
  staging, solve dispatch, output flush and multihost init, so every
  recovery path is testable without real hardware faults.
- :mod:`~sartsolver_tpu.resilience.retry` — bounded retry with
  exponential backoff + deterministic jitter and a per-site deadline,
  wrapped around HDF5 frame reads, RTM stripe ingest and
  ``jax.distributed.initialize``.
- :mod:`~sartsolver_tpu.resilience.failures` — the failure taxonomy:
  frame-level statuses (``DIVERGED``/``FRAME_FAILED``), the exception
  classes the CLI's per-frame isolation may absorb, process exit codes,
  and the end-of-run :class:`~sartsolver_tpu.resilience.failures.RunSummary`.

The *availability* layer (PR 3) adds the three pressures that dominate
fleet operation on a shared accelerator pool:

- :mod:`~sartsolver_tpu.resilience.shutdown` — graceful preemption:
  SIGTERM/SIGINT sets a stop flag the frame loop honors at group
  boundaries (drain, flush, ``EXIT_INTERRUPTED = 4``, resumable file);
  a second signal aborts immediately.
- :mod:`~sartsolver_tpu.resilience.watchdog` — hang watchdog: per-phase
  progress beacons feed a monitor thread that, after
  ``SART_WATCHDOG_TIMEOUT`` seconds of silence, dumps all thread stacks
  and escalates the stuck frame into the FRAME_FAILED /
  EXIT_INFRASTRUCTURE taxonomy (never a deadlocked process); optional
  ``SART_HEARTBEAT_FILE`` touched per frame for external supervisors.
- :mod:`~sartsolver_tpu.resilience.degrade` — adaptive OOM degradation:
  a ``RESOURCE_EXHAUSTED`` dispatch failure halves the frame-group size
  and re-solves the same frames (sticking for the rest of the run)
  before falling back to per-frame isolation.

All three are host-side only: with the layer disabled the traced
programs are byte-identical (the ``guarded_dispatch`` compile-audit
golden pins this). The in-solve divergence guard (rollback to the last
good iterate + relaxation halving,
``SolverOptions.divergence_recovery``) lives in ``models/sart.py`` — it
runs inside the jitted while_loop, not on the host.
"""

from sartsolver_tpu.resilience.degrade import (  # noqa: F401
    GroupSizeLadder,
    is_resource_exhausted,
)
from sartsolver_tpu.resilience.failures import (  # noqa: F401
    EXIT_INFRASTRUCTURE,
    EXIT_INPUT_ERROR,
    EXIT_INTERRUPTED,
    EXIT_OK,
    EXIT_PARTIAL,
    FRAME_FAILED,
    RECOVERABLE_FRAME_ERRORS,
    FrameFailure,
    OutputWriteError,
    RunSummary,
    WatchdogTimeout,
)
from sartsolver_tpu.resilience.faults import (  # noqa: F401
    FAULT_SITES,
    InjectedFault,
    InjectedIOError,
    InjectedOOM,
    clear_faults,
    corrupt,
    fire,
    inject,
)
from sartsolver_tpu.resilience.retry import (  # noqa: F401
    RetriesExhausted,
    RetryPolicy,
    retry_call,
    retry_stats,
)
