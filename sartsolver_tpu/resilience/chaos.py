"""Chaos campaign harness: prove the self-healing serve heals.

``sartsolve chaos`` (docs/SERVING.md §9, docs/RESILIENCE.md §10) runs
seeded randomized fault schedules against a REAL supervised engine
while a workload generator submits requests, then asserts the global
invariants the whole resilience stack promises:

1. **Exactly one outcome** — every accepted request ends with exactly
   one ``completed`` journal marker and a ``done`` response, across any
   number of kills and restarts (no request lost, none double-solved).
2. **Byte-identical outputs** — every solution file matches an
   undisturbed reference run dataset-for-dataset.
3. **Bounded unavailability** — supervised restarts never exceed the
   schedule's kill count (each SIGKILL buys at most one restart) and
   the crash-loop breaker never opens under the drill's budget.
4. **State continuity** — the final engine checkpoint's cumulative
   counters account every request exactly once across all process
   incarnations (``engine_requests_total``; with ``--slo_ms``, the SLO
   ok+breach pair) — a counter reset or a double solve both break it.

A schedule is drawn deterministically from the campaign seed: transient
fault arming (site × kind × count from the *retryable* subset of the
``SART_FAULT`` registry — faults the stack recovers from without
changing outcomes) plus process-level SIGKILLs timed inside the
deterministic crash windows the engine announces on stderr —
``SART_JOURNAL_POINT`` (each journal marker), ``SART_CKPT_POINT``
(mid-checkpoint), ``SART_RESPONSE_POINT`` (mid-response-write). The
same seed replays the same campaign.

Usage::

    sartsolve chaos --engine_dir /tmp/chaos --seeds 0,1 \
        -- --use_cpu -m 40 -c 1e-12 rtm_*.h5 img_*.h5

Everything after ``--`` is the serve worker's own flag set (solver
flags + input files). Exit codes: 0 all invariants hold on every seed;
1 flag/usage error; 2 an invariant was violated (the report names it).

``--fleet M`` swaps in the fleet campaign (docs/SERVING.md §10), and
``--pod N`` the pod fault-tolerance campaign (docs/RESILIENCE.md §11):
N lockstep fake-pod solver workers with in-solve checkpointing, one
host SIGKILLed inside a seeded stride-barrier or mid-checkpoint
window, survivors asserted to abort via the coordinated barrier
deadline (exit 3, crash bundle naming the dead host), then a pod-wide
``--resume`` judged on byte-identity against the undisturbed pass and
on stride-progress monotonicity — a resumed pod never repeats a
checkpointed stride.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

# Transient fault pool: sites the stack retries/recovers WITHOUT
# changing any request's outcome (journal appends and checkpoint writes
# retry in place; frame/RTM reads retry inside ingest). Sites that fail
# requests by design (session.attach, solve.dispatch) belong in the
# targeted drills of tests/test_engine.py, not here — this harness pins
# byte-identity against an undisturbed run.
FAULT_POOL: Tuple[Tuple[str, str], ...] = (
    ("journal.append", "io"),
    ("state.checkpoint", "io"),
    ("hdf5.frame_read", "io"),
    ("hdf5.rtm_ingest", "io"),
)

# Kill windows and the stderr marker lines that announce them.
KILL_WINDOWS = ("accepted", "dispatched", "pre-flush", "ckpt", "response")

_SPAWN_RE = re.compile(r"worker-spawn pid=(\d+)")
_JOURNAL_RE = re.compile(r"SART_JOURNAL_POINT (\S+)")
_CKPT_RE = re.compile(r"SART_CKPT_POINT")
# only COMPLETION responses: a kill there dies with the completed
# marker durable but the response unwritten — the window that drills
# the replay-republish and pre-respond-checkpoint contracts. Acceptance
# responses would shadow it (they are written first) and their kill is
# equivalent to the 'accepted' journal window.
_RESPONSE_RE = re.compile(r"SART_RESPONSE_POINT \S+ state=done")


def line_window(line: str) -> Optional[str]:
    """The kill window a combined-output line announces, or None."""
    m = _JOURNAL_RE.search(line)
    if m:
        return m.group(1)
    if _CKPT_RE.search(line):
        return "ckpt"
    if _RESPONSE_RE.search(line):
        return "response"
    return None


class FaultSchedule:
    """One seed's deterministic campaign: armed faults + kill plan."""

    def __init__(self, seed: int, *, max_kills: int = 2,
                 max_faults: int = 2):
        self.seed = int(seed)
        rng = np.random.default_rng([0x5A47, self.seed])
        n_faults = int(rng.integers(1, max_faults + 1))
        picks = rng.choice(len(FAULT_POOL), size=n_faults, replace=False)
        self.faults = [
            (FAULT_POOL[int(i)][0], FAULT_POOL[int(i)][1],
             int(rng.integers(1, 3)))
            for i in picks
        ]
        n_kills = int(rng.integers(1, max_kills + 1))
        self.kills: List[Tuple[str, int]] = [
            (KILL_WINDOWS[int(rng.integers(0, len(KILL_WINDOWS)))],
             int(rng.integers(1, 4)))
            for _ in range(n_kills)
        ]

    def fault_spec(self) -> str:
        return ",".join(f"{site}:{kind}:1:{count}"
                        for site, kind, count in self.faults)

    def window_env(self) -> Dict[str, str]:
        """Only the crash windows the kill plan targets are slowed."""
        env = {}
        windows = {w for w, _ in self.kills}
        if windows & {"accepted", "dispatched", "pre-flush"}:
            env["SART_TEST_JOURNAL_DELAY"] = "0.4"
        if "ckpt" in windows:
            env["SART_TEST_CKPT_DELAY"] = "0.3"
        if "response" in windows:
            env["SART_TEST_RESPONSE_DELAY"] = "0.3"
        return env

    def describe(self) -> dict:
        return {"seed": self.seed,
                "faults": [f"{s}:{k}:1:{c}" for s, k, c in self.faults],
                "kills": [f"{w}#{occ}" for w, occ in self.kills]}


def _solution_datasets(path: str) -> Dict[str, "np.ndarray"]:
    import h5py

    with h5py.File(path, "r") as f:
        return {key: f[f"solution/{key}"][:] for key in f["solution"]}


def _stage_requests(engine_dir: str, requests: List[dict]) -> None:
    ingest = os.path.join(engine_dir, "ingest")
    os.makedirs(ingest, exist_ok=True)
    from sartsolver_tpu.utils import atomicio

    for i, payload in enumerate(requests):
        path = os.path.join(ingest, f"{i:03d}-{payload['id']}.json")
        atomicio.write_json_atomic(path, payload, fsync=False)


class CampaignError(Exception):
    """An invariant violation (exit 2)."""


class ChaosCampaign:
    """Run the reference pass + one supervised seed pass and judge."""

    def __init__(self, *, root: str, serve_args: List[str],
                 requests: List[dict], slo_ms: Optional[float],
                 timeout: float, verbose=print):
        self.root = root
        self.serve_args = list(serve_args)
        self.requests = requests
        self.slo_ms = slo_ms
        self.timeout = float(timeout)
        self.say = verbose
        self.reference: Optional[Dict[str, dict]] = None

    # ---- process plumbing ------------------------------------------------

    def _env(self, extra: Optional[dict] = None) -> dict:
        env = dict(os.environ)
        for key in ("SART_FAULT", "SART_TEST_JOURNAL_DELAY",
                    "SART_TEST_CKPT_DELAY", "SART_TEST_RESPONSE_DELAY",
                    "SART_TEST_SERVE_CRASH"):
            env.pop(key, None)
        env["PYTHONUNBUFFERED"] = "1"  # the kill plan watches live lines
        env.update(extra or {})
        return env

    def _serve_cmd(self, engine_dir: str, *extra: str) -> List[str]:
        cmd = [sys.executable, "-m", "sartsolver_tpu.cli", "serve",
               "--engine_dir", engine_dir, "--poll_interval", "0.05",
               "--idle_exit", "1.5",
               # keep the full journal history: the exactly-once audit
               # counts completed markers across the whole campaign
               "--journal_rotate_bytes", "0",
               *extra]
        if self.slo_ms is not None:
            cmd += ["--slo_ms", str(self.slo_ms)]
        return cmd + self.serve_args

    # ---- reference pass --------------------------------------------------

    def run_reference(self) -> None:
        ref_dir = os.path.join(self.root, "reference")
        os.makedirs(ref_dir, exist_ok=True)
        _stage_requests(ref_dir, self.requests)
        self.say(f"chaos: reference pass in {ref_dir}")
        res = subprocess.run(
            self._serve_cmd(ref_dir), env=self._env(),
            capture_output=True, text=True, timeout=self.timeout,
        )
        if res.returncode != 0:
            raise CampaignError(
                f"reference serve exited {res.returncode}:\n"
                f"{res.stdout[-4000:]}\n{res.stderr[-4000:]}"
            )
        self.reference = {}
        for payload in self.requests:
            rid = payload["id"]
            out = os.path.join(ref_dir, "outputs", f"{rid}.h5")
            resp = self._response(ref_dir, rid)
            if not resp or resp.get("state") != "done":
                raise CampaignError(
                    f"reference run left no done response for {rid!r}"
                )
            self.reference[rid] = {
                "datasets": _solution_datasets(out),
                "status": (resp.get("outcome") or {}).get("status"),
            }

    @staticmethod
    def _response(engine_dir: str, rid: str) -> Optional[dict]:
        try:
            with open(os.path.join(engine_dir, "responses",
                                   f"{rid}.json")) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    # ---- seed pass -------------------------------------------------------

    def run_seed(self, schedule: FaultSchedule) -> dict:
        seed_dir = os.path.join(self.root, f"seed{schedule.seed}")
        os.makedirs(seed_dir, exist_ok=True)
        _stage_requests(seed_dir, self.requests)
        env = self._env(schedule.window_env())
        if schedule.faults:
            env["SART_FAULT"] = schedule.fault_spec()
            env["SART_FAULT_SEED"] = str(schedule.seed)
            env["SART_RETRY_BASE_DELAY"] = "0.02"
        self.say(f"chaos: seed {schedule.seed} "
                 f"faults=[{schedule.fault_spec()}] "
                 f"kills={schedule.describe()['kills']}")
        cmd = self._serve_cmd(
            seed_dir, "--supervised",
            "--restart_backoff", "0.05", "--restart_backoff_max", "0.5",
            # breaker budget far above the kill plan: the drill asserts
            # the breaker does NOT open under scheduled faults (the
            # storm drill in tests/test_selfheal.py proves it opens)
            "--crash_loop_window", "30",
            "--crash_loop_threshold", str(len(schedule.kills) + 10),
        )
        proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)
        guard = threading.Timer(self.timeout, proc.kill)
        guard.start()
        kills_fired = 0
        lines: List[str] = []
        try:
            pending = list(schedule.kills)
            worker_pid: Optional[int] = None
            count = 0
            for line in proc.stdout:
                lines.append(line)
                m = _SPAWN_RE.search(line)
                if m:
                    worker_pid = int(m.group(1))
                    continue
                if not pending:
                    continue
                window = line_window(line)
                if window != pending[0][0]:
                    continue
                count += 1
                if count < pending[0][1]:
                    continue
                # the worker is sleeping inside the announced window:
                # this SIGKILL lands deterministically mid-commit
                if worker_pid is not None:
                    try:
                        os.kill(worker_pid, signal.SIGKILL)
                        kills_fired += 1
                        self.say(f"chaos: seed {schedule.seed} SIGKILL "
                                 f"pid={worker_pid} in window "
                                 f"{pending[0][0]}#{pending[0][1]}")
                    except OSError:
                        pass
                pending.pop(0)
                count = 0
            rc = proc.wait(timeout=self.timeout)
        finally:
            guard.cancel()
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
        text = "".join(lines)
        if rc != 0:
            raise CampaignError(
                f"seed {schedule.seed}: supervised serve exited {rc} "
                f"(expected 0)\n{text[-6000:]}"
            )
        verdict = self._judge(seed_dir, schedule, kills_fired, text)
        verdict["exit"] = rc
        return verdict

    # ---- invariants ------------------------------------------------------

    def _judge(self, seed_dir: str, schedule: FaultSchedule,
               kills_fired: int, text: str) -> dict:
        from sartsolver_tpu.engine.journal import RequestJournal
        from sartsolver_tpu.engine.state import StateStore

        ids = [r["id"] for r in self.requests]
        # 1a. journal: every request completed, none pending
        journal = RequestJournal(os.path.join(seed_dir, "journal.jsonl"))
        completed, pending_reqs = journal.replay()
        if set(completed) != set(ids) or pending_reqs:
            raise CampaignError(
                f"seed {schedule.seed}: journal shows completed="
                f"{sorted(completed)} pending="
                f"{[r.id for r in pending_reqs]}, expected all of {ids}"
            )
        # 1b. exactly once: one completed marker per id over the WHOLE
        # campaign (rotation disabled above, so history is complete)
        marks: Dict[str, int] = {}
        with open(journal.path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("marker") == "completed":
                    marks[rec["id"]] = marks.get(rec["id"], 0) + 1
        doubled = {rid: n for rid, n in marks.items() if n != 1}
        if doubled:
            raise CampaignError(
                f"seed {schedule.seed}: completed-marker counts != 1: "
                f"{doubled} (a request was lost or double-solved)"
            )
        # 1c. every request has a done response with the reference status
        for rid in ids:
            resp = self._response(seed_dir, rid)
            if not resp or resp.get("state") != "done":
                raise CampaignError(
                    f"seed {schedule.seed}: no done response for {rid!r}"
                )
            status = (resp.get("outcome") or {}).get("status")
            want = self.reference[rid]["status"]
            if status != want:
                raise CampaignError(
                    f"seed {schedule.seed}: {rid!r} ended {status!r}, "
                    f"reference says {want!r}"
                )
        # 2. byte-identical outputs vs the undisturbed run
        for rid in ids:
            got = _solution_datasets(
                os.path.join(seed_dir, "outputs", f"{rid}.h5")
            )
            ref = self.reference[rid]["datasets"]
            if sorted(got) != sorted(ref):
                raise CampaignError(
                    f"seed {schedule.seed}: {rid!r} dataset set differs"
                )
            for key in sorted(ref):
                if not np.array_equal(got[key], ref[key]):
                    raise CampaignError(
                        f"seed {schedule.seed}: {rid!r} solution/{key} "
                        "not byte-identical to the undisturbed run"
                    )
        # 3. bounded unavailability: each kill buys at most one restart,
        # and the breaker stayed closed under the drill budget
        restarts = text.count("supervisor: worker-crash code=")
        if restarts > kills_fired:
            raise CampaignError(
                f"seed {schedule.seed}: {restarts} restart(s) for "
                f"{kills_fired} scheduled kill(s) — the worker is "
                "crashing on its own"
            )
        if "lame-duck-enter" in text:
            raise CampaignError(
                f"seed {schedule.seed}: crash-loop breaker opened "
                "under the drill's restart budget"
            )
        # 4. counter continuity across incarnations (engine/state.py):
        # cumulative totals account each request exactly once
        payload = StateStore(os.path.join(seed_dir, "state.jsonl")).load()
        if payload is None:
            raise CampaignError(
                f"seed {schedule.seed}: no consistent state checkpoint"
            )
        totals: Dict[str, float] = {}
        slo_total = 0.0
        for snap in payload.get("metrics") or []:
            if snap.get("kind") != "counter":
                continue
            name = snap.get("name")
            if name == "engine_requests_total":
                outcome = (snap.get("labels") or {}).get("outcome", "?")
                totals[outcome] = totals.get(outcome, 0) \
                    + float(snap.get("value", 0))
            elif name in ("engine_slo_ok_total",
                          "engine_slo_breach_total"):
                slo_total += float(snap.get("value", 0))
        if sum(totals.values()) != len(ids):
            raise CampaignError(
                f"seed {schedule.seed}: cumulative "
                f"engine_requests_total={totals} does not account "
                f"{len(ids)} request(s) exactly once — counter "
                "continuity broke across a restart"
            )
        if self.slo_ms is not None and slo_total != len(ids):
            raise CampaignError(
                f"seed {schedule.seed}: SLO ok+breach={slo_total:g} for "
                f"{len(ids)} request(s) — SLO burn not continuous "
                "across restarts"
            )
        return {
            **schedule.describe(),
            "kills_fired": kills_fired,
            "restarts": restarts,
            "requests": len(ids),
            "requests_total": totals,
            "verdict": "ok",
        }


# ---------------------------------------------------------------------------
# fleet campaign (docs/SERVING.md §10)
# ---------------------------------------------------------------------------

# fleet workers tag their window announcements (journal.py) so the kill
# plan can target the SPECIFIC worker sleeping inside the window
_WINDOW_WORKER_RE = re.compile(r"SART_JOURNAL_POINT (\S+) worker=w(\d+)")
_SPAWN_WORKER_RE = re.compile(
    r"worker-spawn pid=(\d+) spawn=\d+ worker=(\d+)")


class FleetSchedule:
    """One seed's fleet campaign: SIGKILL one of M workers inside a
    journal commit window, optionally SIGKILL the whole node (controller
    + workers) while the controller sleeps inside its handoff-marker
    append, with forced session evictions armed throughout."""

    WINDOWS = ("accepted", "dispatched", "pre-flush")

    def __init__(self, seed: int, *, size: int = 3):
        self.seed = int(seed)
        self.size = max(2, int(size))
        rng = np.random.default_rng([0x5A48, self.seed])
        self.window = self.WINDOWS[int(rng.integers(0,
                                                    len(self.WINDOWS)))]
        self.occurrence = int(rng.integers(1, 3))
        self.kill_controller_in_handoff = bool(rng.integers(0, 2))
        # fixed, not drawn: with >= 2*size requests some worker
        # incarnation must lease twice (pigeonhole), so every-2nd-lease
        # eviction provably fires — the campaign's eviction-under-load
        # leg can assert it happened instead of hoping
        self.evict_every = 2

    def describe(self) -> dict:
        return {"seed": self.seed,
                "window": f"{self.window}#{self.occurrence}",
                "controller_kill": self.kill_controller_in_handoff,
                "evict_every": self.evict_every}


class FleetCampaign(ChaosCampaign):
    """Reference pass (undisturbed single serve) + fleet passes:
    ``sartsolve fleet`` with M workers under seeded worker/controller
    SIGKILLs and forced session evictions, judged on the same
    invariants — fleet-wide: exactly one completed marker per id across
    ALL worker journals, done responses in the SHARED responses dir,
    byte-identical outputs, counter continuity summed across every
    worker's state checkpoint."""

    def __init__(self, *, size: int = 3, **kwargs):
        super().__init__(**kwargs)
        self.size = max(2, int(size))

    def _fleet_cmd(self, fleet_dir: str) -> List[str]:
        worker = ["--poll_interval", "0.05", "--idle_exit", "3.0",
                  "--journal_rotate_bytes", "0"]
        if self.slo_ms is not None:
            worker += ["--slo_ms", str(self.slo_ms)]
        return [sys.executable, "-m", "sartsolver_tpu.cli", "fleet",
                "--fleet_dir", fleet_dir, "--size", str(self.size),
                "--restart_backoff", "0.05",
                "--restart_backoff_max", "0.5",
                "--poll_interval", "0.05",
                "--"] + worker + self.serve_args

    def run_fleet_seed(self, schedule: FleetSchedule) -> dict:
        fleet_dir = os.path.join(self.root, f"fleet{schedule.seed}")
        os.makedirs(os.path.join(fleet_dir, "ingest"), exist_ok=True)
        # requests go through the controller intake: tenant-affinity
        # routing distributes them across the worker shards
        _stage_requests(fleet_dir, self.requests)
        env = self._env({
            "SART_TEST_JOURNAL_DELAY": "0.25",
            "SART_TEST_EVICT_EVERY": str(schedule.evict_every),
        })
        self.say(f"chaos: fleet seed {schedule.seed} "
                 f"{schedule.describe()}")
        cmd = self._fleet_cmd(fleet_dir)
        pids: Dict[int, int] = {}
        lines: List[str] = []
        kills_fired = 0
        controller_kills = 0
        relaunches = 0
        worker_kill_pending = True
        count = 0
        launch = 0
        while True:
            launch += 1
            proc = subprocess.Popen(cmd, env=env,
                                    stdout=subprocess.PIPE,
                                    stderr=subprocess.STDOUT, text=True)
            guard = threading.Timer(self.timeout, proc.kill)
            guard.start()
            try:
                for line in proc.stdout:
                    lines.append(line)
                    m = _SPAWN_WORKER_RE.search(line)
                    if m:
                        pids[int(m.group(2))] = int(m.group(1))
                        continue
                    if (schedule.kill_controller_in_handoff
                            and controller_kills == 0
                            and line_window(line) == "handoff"):
                        # only the controller appends handoff markers:
                        # it is sleeping inside the append — the marker
                        # is durable, the re-staged payload is NOT. The
                        # node-crash model takes out the controller AND
                        # every worker; recovery on relaunch must
                        # re-stage via the needs_restage gate.
                        for pid in [proc.pid] + list(pids.values()):
                            try:
                                os.kill(pid, signal.SIGKILL)
                            except OSError:
                                pass
                        controller_kills += 1
                        self.say(f"chaos: fleet seed {schedule.seed} "
                                 "SIGKILL controller (+workers) in "
                                 "handoff window")
                        continue
                    if not worker_kill_pending:
                        continue
                    m = _WINDOW_WORKER_RE.search(line)
                    if not m or m.group(1) != schedule.window:
                        continue
                    count += 1
                    if count < schedule.occurrence:
                        continue
                    victim = int(m.group(2))
                    pid = pids.get(victim)
                    if pid is not None:
                        try:
                            os.kill(pid, signal.SIGKILL)
                            kills_fired += 1
                            self.say(f"chaos: fleet seed "
                                     f"{schedule.seed} SIGKILL worker "
                                     f"{victim} pid={pid} in window "
                                     f"{schedule.window}"
                                     f"#{schedule.occurrence}")
                        except OSError:
                            pass
                    worker_kill_pending = False
                rc = proc.wait(timeout=self.timeout)
            finally:
                guard.cancel()
                if proc.poll() is None:
                    proc.kill()
                    proc.wait(timeout=30)
            if controller_kills and relaunches == 0:
                relaunches += 1
                self.say(f"chaos: fleet seed {schedule.seed} "
                         "relaunching after controller kill")
                continue
            break
        text = "".join(lines)
        if rc != 0:
            for pid in pids.values():  # no stray workers past a failure
                try:
                    os.kill(pid, signal.SIGKILL)
                except OSError:
                    pass
            raise CampaignError(
                f"fleet seed {schedule.seed}: controller exited {rc} "
                f"(expected 0)\n{text[-6000:]}"
            )
        verdict = self._judge_fleet(fleet_dir, schedule, kills_fired,
                                    controller_kills, text)
        verdict["exit"] = rc
        return verdict

    # ---- fleet-wide invariants -------------------------------------------

    def _judge_fleet(self, fleet_dir: str, schedule: FleetSchedule,
                     kills_fired: int, controller_kills: int,
                     text: str) -> dict:
        from sartsolver_tpu.engine.journal import RequestJournal
        from sartsolver_tpu.engine.state import StateStore

        ids = [r["id"] for r in self.requests]
        marks: Dict[str, int] = {}
        completed_union: Dict[str, dict] = {}
        pending_ids: List[str] = []
        evictions = 0
        for k in range(self.size):
            jpath = os.path.join(fleet_dir, "workers", f"w{k}",
                                 "journal.jsonl")
            completed, pending, _handed = \
                RequestJournal(jpath).replay_full()
            completed_union.update(completed)
            pending_ids += [req.id for req in pending]
            try:
                f = open(jpath)
            except OSError:
                continue
            with f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("marker") == "completed":
                        marks[rec["id"]] = marks.get(rec["id"], 0) + 1
                    elif (rec.get("marker") == "session"
                          and rec.get("event") == "session-evict"
                          and rec.get("reason") == "test-forced"):
                        evictions += 1
        # 1a. fleet-wide completion: every request completed SOMEWHERE,
        # none left pending on any shard
        if set(completed_union) != set(ids) or pending_ids:
            raise CampaignError(
                f"fleet seed {schedule.seed}: completed="
                f"{sorted(completed_union)} pending={pending_ids}, "
                f"expected all of {ids}"
            )
        # 1b. exactly once fleet-wide: ONE completed marker per id
        # across every worker journal (a handoff that double-drove a
        # request shows up as two markers on two shards)
        doubled = {rid: n for rid, n in marks.items() if n != 1}
        if doubled:
            raise CampaignError(
                f"fleet seed {schedule.seed}: completed-marker counts "
                f"!= 1 across the fleet: {doubled} (a request was lost "
                "or double-solved)"
            )
        # 1c. done response per id in the SHARED responses dir, status
        # matching the undisturbed reference
        for rid in ids:
            resp = self._response(fleet_dir, rid)
            if not resp or resp.get("state") != "done":
                raise CampaignError(
                    f"fleet seed {schedule.seed}: no done response "
                    f"for {rid!r}"
                )
            status = (resp.get("outcome") or {}).get("status")
            want = self.reference[rid]["status"]
            if status != want:
                raise CampaignError(
                    f"fleet seed {schedule.seed}: {rid!r} ended "
                    f"{status!r}, reference says {want!r}"
                )
        # 2. byte-identical outputs (shared outputs dir) — this is also
        # the eviction-correctness gate: a rebuilt session that solved
        # differently, or a handoff re-drive that lost frames, breaks it
        for rid in ids:
            got = _solution_datasets(
                os.path.join(fleet_dir, "outputs", f"{rid}.h5")
            )
            ref = self.reference[rid]["datasets"]
            if sorted(got) != sorted(ref):
                raise CampaignError(
                    f"fleet seed {schedule.seed}: {rid!r} dataset set "
                    "differs"
                )
            for key in sorted(ref):
                if not np.array_equal(got[key], ref[key]):
                    raise CampaignError(
                        f"fleet seed {schedule.seed}: {rid!r} "
                        f"solution/{key} not byte-identical to the "
                        "undisturbed run"
                    )
        # 3. bounded unavailability: each worker SIGKILL costs at most
        # one controller-observed crash (node kills die WITH the
        # controller and are respawns, not crashes)
        restarts = text.count("fleet: worker-crash code=")
        if restarts > kills_fired:
            raise CampaignError(
                f"fleet seed {schedule.seed}: {restarts} worker "
                f"crash(es) for {kills_fired} scheduled kill(s) — "
                "workers are crashing on their own"
            )
        # 4. counter continuity fleet-wide: summed across every
        # worker's state checkpoint, the cumulative totals account
        # each request exactly once — across kills, handoffs and a
        # controller relaunch
        totals: Dict[str, float] = {}
        slo_total = 0.0
        for k in range(self.size):
            payload = StateStore(os.path.join(
                fleet_dir, "workers", f"w{k}", "state.jsonl")).load()
            for snap in (payload or {}).get("metrics") or []:
                if snap.get("kind") != "counter":
                    continue
                name = snap.get("name")
                if name == "engine_requests_total":
                    outcome = (snap.get("labels") or {}).get(
                        "outcome", "?")
                    totals[outcome] = totals.get(outcome, 0) \
                        + float(snap.get("value", 0))
                elif name in ("engine_slo_ok_total",
                              "engine_slo_breach_total"):
                    slo_total += float(snap.get("value", 0))
        if sum(totals.values()) != len(ids):
            raise CampaignError(
                f"fleet seed {schedule.seed}: fleet-summed "
                f"engine_requests_total={totals} does not account "
                f"{len(ids)} request(s) exactly once"
            )
        if self.slo_ms is not None and slo_total != len(ids):
            raise CampaignError(
                f"fleet seed {schedule.seed}: fleet-summed SLO "
                f"ok+breach={slo_total:g} for {len(ids)} request(s)"
            )
        # 5. the eviction leg is not vacuous: forced evictions fired
        # (byte-identity above proves they were harmless)
        if evictions == 0:
            raise CampaignError(
                f"fleet seed {schedule.seed}: SART_TEST_EVICT_EVERY="
                f"{schedule.evict_every} armed but no forced eviction "
                "fired — the eviction-under-load leg ran vacuously"
            )
        return {
            **schedule.describe(),
            "kills_fired": kills_fired,
            "controller_kills": controller_kills,
            "restarts": restarts,
            "evictions": evictions,
            "requests": len(ids),
            "requests_total": totals,
            "verdict": "ok",
        }


# ---------------------------------------------------------------------------
# pod campaign (docs/RESILIENCE.md §11)
# ---------------------------------------------------------------------------

# fake-pod kill windows, announced on the VICTIM's stderr: "stride" is
# the pod rendezvous (SART_TEST_POD_MARKERS, printed before the barrier
# arrival lands — a kill there leaves the peers waiting forever) and
# "ckpt" is the held-open pre-durability window inside a solve
# checkpoint append (SART_TEST_SOLVE_CKPT_DELAY — a kill there dies
# with the record NOT durable, so the pod must fall back one stride).
_POD_STRIDE_RE = re.compile(r"SART_POD_POINT stride serial=(\d+)")
_POD_CKPT_RE = re.compile(r"SART_SOLVE_CKPT_POINT pre-append serial=(\d+)")
_POD_RESUME_RE = re.compile(r"SART_POD_POINT resume serial=(\d+)")

POD_CKPT_STRIDE = 2


class PodSchedule:
    """One seed's pod campaign: which host dies, in which window."""

    WINDOWS = ("stride", "ckpt")

    def __init__(self, seed: int, *, size: int = 2):
        self.seed = int(seed)
        self.size = max(2, int(size))
        rng = np.random.default_rng([0x5A4A, self.seed])
        self.victim = int(rng.integers(0, self.size))
        self.window = self.WINDOWS[int(rng.integers(0,
                                                    len(self.WINDOWS)))]
        # occurrence counts WINDOW announcements on the victim: stride
        # markers land every stride, ckpt markers every POD_CKPT_STRIDE
        # strides — both draws stay well inside even a short run
        if self.window == "stride":
            self.occurrence = int(rng.integers(2, 5))
        else:
            self.occurrence = int(rng.integers(1, 3))

    def describe(self) -> dict:
        return {"seed": self.seed, "victim": f"h{self.victim}",
                "window": f"{self.window}#{self.occurrence}"}


class PodCampaign:
    """Reference pass (N undisturbed lockstep fake-pod workers) + seed
    passes: SIGKILL one host inside a seeded commit window, assert the
    survivors abort via the coordinated barrier deadline with a crash
    bundle naming the dead host, then ``--resume`` the whole pod and
    judge byte-identity + stride-progress monotonicity (a resumed pod
    never repeats a checkpointed stride) + checkpoint-counter truth.

    The fake pod is N single-process solver CLIs in lockstep over the
    same frame stream: ``SART_POD_PROCESS=k/n`` identity, file barriers
    under a fresh ``SART_POD_BARRIER_DIR`` per pass (stale arrival
    files from a previous incarnation would satisfy a rendezvous
    instantly — pods MUST start on an empty barrier dir), and one
    shared ``SART_SOLVE_CKPT_FILE`` base so the pod-wide consistency
    intersection sees every host's records."""

    def __init__(self, *, root: str, solve_args: List[str], size: int,
                 timeout: float, verbose=print):
        self.root = root
        self.solve_args = list(solve_args)
        self.size = max(2, int(size))
        self.timeout = float(timeout)
        self.say = verbose
        self.reference: Optional[Dict[str, "np.ndarray"]] = None

    # ---- process plumbing ------------------------------------------------

    def _pod_env(self, index: int, barrier_dir: str,
                 extra: Optional[dict] = None) -> dict:
        env = dict(os.environ)
        for key in ("SART_FAULT", "SART_TEST_POD_MARKERS",
                    "SART_TEST_SOLVE_CKPT_DELAY", "SART_SOLVE_CKPT_FILE"):
            env.pop(key, None)
        env["PYTHONUNBUFFERED"] = "1"  # the kill plan watches live lines
        env["SART_POD_PROCESS"] = f"{index}/{self.size}"
        env["SART_POD_BARRIER_DIR"] = barrier_dir
        # short deadline: the drill asserts the barrier (not the hang
        # watchdog) detects the dead peer, and CI should not idle long
        env.setdefault("SART_POD_BARRIER_TIMEOUT", "30")
        env.update(extra or {})
        return env

    def _solve_cmd(self, outfile: str, *extra: str) -> List[str]:
        return [sys.executable, "-m", "sartsolver_tpu.cli",
                "-o", outfile, *self.solve_args, *extra]

    def _outputs(self, pass_dir: str) -> List[str]:
        return [os.path.join(pass_dir, f"out_h{k}.h5")
                for k in range(self.size)]

    @staticmethod
    def _barrier_dir(pass_dir: str, name: str) -> str:
        path = os.path.join(pass_dir, name)
        os.makedirs(path, exist_ok=True)
        if os.listdir(path):  # pragma: no cover - reused campaign root
            raise CampaignError(
                f"pod barrier dir {path} is not empty — stale arrival "
                "files would satisfy rendezvous instantly"
            )
        return path

    # ---- reference pass --------------------------------------------------

    def run_reference(self) -> None:
        ref_dir = os.path.join(self.root, "podref")
        os.makedirs(ref_dir, exist_ok=True)
        bdir = self._barrier_dir(ref_dir, "barriers")
        outs = self._outputs(ref_dir)
        self.say(f"chaos: pod reference pass ({self.size} hosts) in "
                 f"{ref_dir}")
        procs = [
            subprocess.Popen(self._solve_cmd(outs[k]),
                             env=self._pod_env(k, bdir),
                             stdout=subprocess.DEVNULL,
                             stderr=subprocess.PIPE, text=True)
            for k in range(self.size)
        ]
        errs = self._drain(procs)
        for k, proc in enumerate(procs):
            if proc.returncode != 0:
                raise CampaignError(
                    f"pod reference host h{k} exited {proc.returncode}:"
                    f"\n{errs[k][-4000:]}"
                )
        datasets = [_solution_datasets(out) for out in outs]
        # lockstep sanity: every host solved the identical stream —
        # the per-host outputs must already agree with each other
        for k in range(1, self.size):
            for key in sorted(datasets[0]):
                if not np.array_equal(datasets[0][key], datasets[k][key]):
                    raise CampaignError(
                        f"pod reference hosts h0/h{k} disagree on "
                        f"solution/{key} — lockstep is broken before "
                        "any fault was injected"
                    )
        self.reference = datasets[0]

    def _drain(self, procs: List[subprocess.Popen]) -> List[str]:
        """communicate() every worker under one wall-clock guard."""
        guards = [threading.Timer(self.timeout, p.kill) for p in procs]
        for g in guards:
            g.start()
        try:
            return [p.communicate()[1] or "" for p in procs]
        finally:
            for g in guards:
                g.cancel()
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait(timeout=30)

    # ---- seed pass -------------------------------------------------------

    def run_pod_seed(self, schedule: PodSchedule) -> dict:
        pass_dir = os.path.join(self.root, f"pod{schedule.seed}")
        os.makedirs(pass_dir, exist_ok=True)
        outs = self._outputs(pass_dir)
        ckpt_base = os.path.join(pass_dir, "pod.solveckpt")
        chaos_env = {
            "SART_TEST_POD_MARKERS": "1",
            "SART_TEST_SOLVE_CKPT_DELAY": "0.4",
            "SART_SOLVE_CKPT_FILE": ckpt_base,
        }
        self.say(f"chaos: pod seed {schedule.seed} "
                 f"{schedule.describe()}")

        # -- kill pass: one host dies inside the seeded window ------------
        bdir = self._barrier_dir(pass_dir, "barriers_kill")
        procs = [
            subprocess.Popen(
                self._solve_cmd(outs[k], "--solve_ckpt_stride",
                                str(POD_CKPT_STRIDE)),
                env=self._pod_env(k, bdir, chaos_env),
                stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE, text=True)
            for k in range(self.size)
        ]
        victim = procs[schedule.victim]
        want_re = (_POD_STRIDE_RE if schedule.window == "stride"
                   else _POD_CKPT_RE)
        victim_lines: List[str] = []
        killed_serial: List[int] = []

        def watch_victim() -> None:
            seen = 0
            for line in victim.stderr:
                victim_lines.append(line)
                m = want_re.search(line)
                if not m:
                    continue
                seen += 1
                if seen < schedule.occurrence:
                    continue
                killed_serial.append(int(m.group(1)))
                victim.kill()
                break
            try:  # drain so the dying child never blocks on the pipe
                victim.stderr.read()
            except (OSError, ValueError):
                pass

        watcher = threading.Thread(target=watch_victim, daemon=True)
        watcher.start()
        survivors = [p for k, p in enumerate(procs)
                     if k != schedule.victim]
        errs = self._drain(survivors)
        watcher.join(timeout=60)
        victim.wait(timeout=60)
        if victim.returncode != -signal.SIGKILL:
            raise CampaignError(
                f"pod seed {schedule.seed}: victim h{schedule.victim} "
                f"exited {victim.returncode} before the kill landed in "
                f"window {schedule.window}#{schedule.occurrence} — a "
                "clean exit 0 here usually means the workload is too "
                "short to reach this seed's window; give the campaign "
                "more frames:\n"
                f"{''.join(victim_lines)[-4000:]}"
            )
        self.say(f"chaos: pod seed {schedule.seed} SIGKILL "
                 f"h{schedule.victim} in window {schedule.window}"
                 f"#{schedule.occurrence} (serial "
                 f"{killed_serial[0] if killed_serial else '?'})")
        # every survivor must abort via the coordinated barrier deadline
        # — exit 3, stderr naming the barrier and the dead host — and
        # leave a crash bundle whose reason names the missing host
        for k, (proc, err) in enumerate(zip(survivors, errs)):
            host = k if k < schedule.victim else k + 1
            if proc.returncode != EXIT_INFRASTRUCTURE_POD:
                raise CampaignError(
                    f"pod seed {schedule.seed}: survivor h{host} exited "
                    f"{proc.returncode}, expected "
                    f"{EXIT_INFRASTRUCTURE_POD} (the barrier-deadline "
                    f"abort):\n{err[-4000:]}"
                )
            if "pod barrier" not in err \
                    or f"h{schedule.victim}" not in err:
                raise CampaignError(
                    f"pod seed {schedule.seed}: survivor h{host} abort "
                    f"does not name the pod barrier and the dead host "
                    f"h{schedule.victim}:\n{err[-4000:]}"
                )
            bundle_path = f"{outs[host]}.crash.json"
            try:
                with open(bundle_path) as f:
                    bundle = json.load(f)
            except (OSError, ValueError) as exc:
                raise CampaignError(
                    f"pod seed {schedule.seed}: survivor h{host} left "
                    f"no readable crash bundle at {bundle_path}: {exc}"
                )
            if f"h{schedule.victim}" not in str(bundle.get("reason")):
                raise CampaignError(
                    f"pod seed {schedule.seed}: crash bundle reason "
                    f"{bundle.get('reason')!r} does not name the dead "
                    f"host h{schedule.victim}"
                )
            if bundle.get("status", {}).get("host") != \
                    f"{host}/{self.size}":
                raise CampaignError(
                    f"pod seed {schedule.seed}: crash bundle host tag "
                    f"{bundle.get('status', {}).get('host')!r} is not "
                    f"{host}/{self.size}"
                )

        # -- resume pass: the whole pod relaunches with --resume ----------
        bdir = self._barrier_dir(pass_dir, "barriers_resume")
        arts = [os.path.join(pass_dir, f"resume_h{k}.jsonl")
                for k in range(self.size)]
        procs = [
            subprocess.Popen(
                self._solve_cmd(outs[k], "--solve_ckpt_stride",
                                str(POD_CKPT_STRIDE), "--resume",
                                "--metrics_out", arts[k]),
                env=self._pod_env(k, bdir, chaos_env),
                stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE, text=True)
            for k in range(self.size)
        ]
        errs = self._drain(procs)
        for k, proc in enumerate(procs):
            if proc.returncode != 0:
                raise CampaignError(
                    f"pod seed {schedule.seed}: resume host h{k} exited "
                    f"{proc.returncode}:\n{errs[k][-4000:]}"
                )
        verdict = self._judge_pod(schedule, outs, arts, errs)
        verdict["killed_serial"] = (killed_serial[0] if killed_serial
                                    else None)
        return verdict

    # ---- pod invariants --------------------------------------------------

    def _judge_pod(self, schedule: PodSchedule, outs: List[str],
                   arts: List[str], errs: List[str]) -> dict:
        # 1. byte-identical outputs: every host's resumed file equals
        # the undisturbed reference
        for k, out in enumerate(outs):
            got = _solution_datasets(out)
            if sorted(got) != sorted(self.reference):
                raise CampaignError(
                    f"pod seed {schedule.seed}: h{k} dataset set differs"
                )
            for key in sorted(self.reference):
                if not np.array_equal(got[key], self.reference[key]):
                    raise CampaignError(
                        f"pod seed {schedule.seed}: h{k} solution/{key} "
                        "not byte-identical to the undisturbed run"
                    )
        # 2. elastic resume really resumed: every host restored the SAME
        # checkpoint serial (divergent picks would have desynced the
        # stride barriers), and for a mid-checkpoint kill that serial is
        # strictly OLDER than the torn append (the one-stride fallback)
        resumed: List[int] = []
        for k, err in enumerate(errs):
            m = _POD_RESUME_RE.search(err)
            if not m:
                raise CampaignError(
                    f"pod seed {schedule.seed}: h{k} did not resume "
                    f"from a solve checkpoint:\n{err[-4000:]}"
                )
            resumed.append(int(m.group(1)))
        if len(set(resumed)) != 1:
            raise CampaignError(
                f"pod seed {schedule.seed}: hosts resumed from "
                f"divergent serials {resumed}"
            )
        # 3. progress monotonicity: a resumed pod never repeats a
        # checkpointed stride — every post-resume stride serial is
        # strictly newer than the restored one, strictly increasing
        post_serials: List[List[int]] = []
        for k, err in enumerate(errs):
            serials = [int(m.group(1))
                       for m in _POD_STRIDE_RE.finditer(err)]
            post_serials.append(serials)
            if not serials:
                raise CampaignError(
                    f"pod seed {schedule.seed}: h{k} resumed without "
                    "completing a single stride"
                )
            if serials[0] <= resumed[0] \
                    or serials != sorted(set(serials)):
                raise CampaignError(
                    f"pod seed {schedule.seed}: h{k} stride serials "
                    f"{serials} repeat or precede the restored "
                    f"checkpoint {resumed[0]} — a completed stride "
                    "was re-run"
                )
        # 4. counter truth: each host's metrics artifact validates and
        # accounts exactly one checkpoint resume plus the checkpoints
        # the resumed leg itself wrote
        from sartsolver_tpu.obs.cli import metrics_main

        for k, art in enumerate(arts):
            if metrics_main(["--check", art]) != 0:
                raise CampaignError(
                    f"pod seed {schedule.seed}: h{k} metrics artifact "
                    f"{art} fails sartsolve metrics --check"
                )
            counters: Dict[str, float] = {}
            with open(art) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("type") == "metric" \
                            and rec.get("kind") == "counter":
                        counters[rec["name"]] = float(rec.get("value", 0))
            if counters.get("solve_ckpt_resumed_total") != 1:
                raise CampaignError(
                    f"pod seed {schedule.seed}: h{k} "
                    f"solve_ckpt_resumed_total="
                    f"{counters.get('solve_ckpt_resumed_total')}, "
                    "expected exactly 1"
                )
            # a checkpoint is owed only when the resumed leg completed
            # a checkpoint-aligned stride — a short tail can finish
            # before the next multiple of the stride, legitimately
            # writing none
            aligned = [s for s in post_serials[k]
                       if s % POD_CKPT_STRIDE == 0]
            if aligned and counters.get(
                    "solve_ckpt_written_total", 0) < 1:
                raise CampaignError(
                    f"pod seed {schedule.seed}: h{k} completed "
                    f"checkpoint-aligned stride(s) {aligned} but wrote "
                    "no solve checkpoints"
                )
        return {
            **schedule.describe(),
            "resumed_serial": resumed[0],
            "hosts": self.size,
            "verdict": "ok",
        }


# the solver CLI's documented infrastructure-abort code (cli.py); named
# here so the drill reads as intent, not magic
EXIT_INFRASTRUCTURE_POD = 3


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="sartsolve chaos",
        description="Chaos campaign against a real supervised serve: "
                    "seeded fault schedules + SIGKILLs inside commit "
                    "windows, judged on exactly-once / byte-identity / "
                    "restart-budget / state-continuity invariants "
                    "(docs/SERVING.md §9). Everything after -- is the "
                    "serve worker's own flag set.",
    )
    p.add_argument("--engine_dir", required=True,
                   help="Campaign root: reference/ and seed<K>/ engine "
                        "dirs are created under it.")
    p.add_argument("--seeds", default="0,1",
                   help="Comma-separated campaign seeds (each runs one "
                        "supervised pass). Default 0,1.")
    p.add_argument("--requests", type=int, default=4,
                   help="Workload size per pass. Default 4.")
    p.add_argument("--max_kills", type=int, default=2,
                   help="Max SIGKILLs a seed's schedule may draw. "
                        "Default 2.")
    p.add_argument("--fleet", type=int, default=0, metavar="M",
                   help="Run fleet campaigns instead: each seed passes "
                        "through `sartsolve fleet` with M workers under "
                        "a seeded worker SIGKILL inside a commit "
                        "window, an optional controller kill mid-"
                        "handoff, and forced session evictions — "
                        "judged fleet-wide (docs/SERVING.md §10). "
                        "0 = single supervised engine (default).")
    p.add_argument("--pod", type=int, default=0, metavar="N",
                   help="Run pod campaigns instead: each seed runs N "
                        "lockstep fake-pod solver workers with in-solve "
                        "checkpointing, SIGKILLs one host inside a "
                        "seeded stride-barrier or mid-checkpoint "
                        "window, asserts the survivors abort via the "
                        "coordinated barrier deadline naming the dead "
                        "host, then --resume's the pod and judges "
                        "byte-identity + stride-progress monotonicity "
                        "(docs/RESILIENCE.md §11). Everything after -- "
                        "is the solver's own flag set (needs "
                        "--batch_frames > 1). 0 = serve campaign "
                        "(default).")
    p.add_argument("--slo_ms", type=float, default=None,
                   help="Arm the engine SLO pair and assert its burn "
                        "accounting is continuous across restarts.")
    p.add_argument("--timeout", type=float, default=300.0,
                   help="Per-pass wall-clock guard in seconds. "
                        "Default 300.")
    p.add_argument("--report", default=None, metavar="FILE",
                   help="Write the campaign report JSON here too.")
    p.add_argument("serve_args", nargs=argparse.REMAINDER,
                   help="-- followed by serve solver flags + input "
                        "files.")
    return p


def chaos_main(argv: Optional[List[str]] = None) -> int:
    parser = build_parser()
    try:
        args = parser.parse_args(argv)
    except SystemExit as err:
        raise SystemExit(1 if err.code else 0) from None
    serve_args = list(args.serve_args)
    if serve_args[:1] == ["--"]:
        serve_args = serve_args[1:]
    if not serve_args:
        print("sartsolve chaos: no serve flags/input files after -- .",
              file=sys.stderr)
        return 1
    try:
        seeds = [int(s) for s in args.seeds.split(",") if s.strip()]
    except ValueError:
        print(f"sartsolve chaos: malformed --seeds {args.seeds!r}.",
              file=sys.stderr)
        return 1
    if not seeds or args.requests < 1 or args.max_kills < 1:
        print("sartsolve chaos: need >=1 seed, >=1 request, >=1 kill.",
              file=sys.stderr)
        return 1
    if args.fleet < 0 or args.fleet == 1:
        print("sartsolve chaos: --fleet needs >= 2 workers (or 0 for "
              "the single-engine campaign).", file=sys.stderr)
        return 1
    if args.pod < 0 or args.pod == 1:
        print("sartsolve chaos: --pod needs >= 2 hosts (or 0 for the "
              "serve campaigns).", file=sys.stderr)
        return 1
    if args.pod and args.fleet:
        print("sartsolve chaos: --pod and --fleet are separate "
              "campaigns; pick one.", file=sys.stderr)
        return 1
    if args.pod:
        campaign = PodCampaign(
            root=args.engine_dir, solve_args=serve_args,
            size=args.pod, timeout=args.timeout,
        )
        report = {"seeds": seeds, "pod": args.pod, "passes": []}
        try:
            campaign.run_reference()
            for seed in seeds:
                verdict = campaign.run_pod_seed(
                    PodSchedule(seed, size=args.pod)
                )
                report["passes"].append(verdict)
                print(f"chaos: pod seed {seed} OK — killed "
                      f"{verdict['victim']} in {verdict['window']}, "
                      f"survivors exited {EXIT_INFRASTRUCTURE_POD} at "
                      "the barrier deadline, pod resumed from serial "
                      f"{verdict['resumed_serial']} without repeating "
                      "a stride, outputs byte-identical")
        except CampaignError as err:
            report["verdict"] = "FAILED"
            report["error"] = str(err)
            print(f"chaos: INVARIANT VIOLATED — {err}", file=sys.stderr)
            if args.report:
                with open(args.report, "w") as f:
                    json.dump(report, f, indent=2)
            return 2
        except subprocess.TimeoutExpired:
            print(f"chaos: campaign pass exceeded --timeout "
                  f"{args.timeout:g}s.", file=sys.stderr)
            return 2
        report["verdict"] = "ok"
        print(json.dumps({"chaos": report}))
        if args.report:
            with open(args.report, "w") as f:
                json.dump(report, f, indent=2)
        return 0
    if args.fleet:
        # >= 2*size requests with DISTINCT tenants: affinity spreads
        # them across shards, and pigeonhole guarantees some worker
        # incarnation leases twice so the forced-eviction leg fires
        n_requests = max(args.requests, 2 * args.fleet + 2)
        requests = [
            {"id": f"chaos-{i}", "tenant": f"t{i}"}
            for i in range(n_requests)
        ]
        campaign = FleetCampaign(
            size=args.fleet, root=args.engine_dir,
            serve_args=serve_args, requests=requests,
            slo_ms=args.slo_ms, timeout=args.timeout,
        )
    else:
        requests = [
            {"id": f"chaos-{i}", "tenant": f"t{i % 2}"}
            for i in range(args.requests)
        ]
        campaign = ChaosCampaign(
            root=args.engine_dir, serve_args=serve_args,
            requests=requests, slo_ms=args.slo_ms, timeout=args.timeout,
        )
    report = {"seeds": seeds, "requests": len(requests),
              "fleet": args.fleet, "passes": []}
    try:
        campaign.run_reference()
        for seed in seeds:
            if args.fleet:
                verdict = campaign.run_fleet_seed(
                    FleetSchedule(seed, size=args.fleet)
                )
                report["passes"].append(verdict)
                print(f"chaos: fleet seed {seed} OK — "
                      f"{verdict['kills_fired']} worker kill(s), "
                      f"{verdict['controller_kills']} controller "
                      f"kill(s), {verdict['evictions']} forced "
                      f"eviction(s), {verdict['requests']} request(s) "
                      "exactly once fleet-wide, outputs byte-identical")
                continue
            schedule = FaultSchedule(seed, max_kills=args.max_kills)
            verdict = campaign.run_seed(schedule)
            report["passes"].append(verdict)
            print(f"chaos: seed {seed} OK — "
                  f"{verdict['kills_fired']} kill(s), "
                  f"{verdict['restarts']} restart(s), "
                  f"{verdict['requests']} request(s) exactly once, "
                  "outputs byte-identical")
    except CampaignError as err:
        report["verdict"] = "FAILED"
        report["error"] = str(err)
        print(f"chaos: INVARIANT VIOLATED — {err}", file=sys.stderr)
        if args.report:
            with open(args.report, "w") as f:
                json.dump(report, f, indent=2)
        return 2
    except subprocess.TimeoutExpired:
        print(f"chaos: campaign pass exceeded --timeout "
              f"{args.timeout:g}s.", file=sys.stderr)
        return 2
    report["verdict"] = "ok"
    print(json.dumps({"chaos": report}))
    if args.report:
        with open(args.report, "w") as f:
            json.dump(report, f, indent=2)
    return 0


__all__ = ["ChaosCampaign", "FleetCampaign", "PodCampaign",
           "CampaignError", "FaultSchedule", "FleetSchedule",
           "PodSchedule", "chaos_main", "line_window", "FAULT_POOL",
           "KILL_WINDOWS", "POD_CKPT_STRIDE"]
