"""Failure taxonomy: statuses, exceptions, exit codes, run summary.

The reference has exactly two per-frame statuses (0 converged, -1
iteration cap) and one process outcome (alive or dead). A resilient
service needs a richer, *stable* vocabulary — every value here is part of
the output-file and exit-code contract (docs/RESILIENCE.md,
docs/FORMATS.md):

Per-frame statuses (``solution/status``; extends config.py's codes):

- ``0``  SUCCESS — converged.
- ``-1`` MAX_ITERATIONS_EXCEEDED — iteration cap (reference parity; not
  a failure).
- ``-2`` DIVERGED — the in-solve divergence guard exhausted its
  rollback/relaxation-halving ladder; the row holds the last *finite*
  iterate (models/sart.py).
- ``-3`` FRAME_FAILED — the frame never produced a solution (ingest
  retries exhausted, staging/solve dispatch fault); the row holds zeros
  and ``iterations = -1``.
- ``-4`` SDC_DETECTED — the in-solve ABFT integrity check
  (``--integrity``, docs/RESILIENCE.md §8) caught a silent-data-
  corruption signature; the row holds the last *consistent* iterate. The
  CLI's escalation normally recomputes the frame once and converts a
  repeat into FRAME_FAILED, so -4 reaches the file only from library
  callers that skip the escalation.
- ``-5`` DEADLINE_EXCEEDED — the serving engine (docs/SERVING.md) shed
  the frame at a scheduler stride boundary because its request's
  deadline passed mid-solve; the row holds the last iterate reached.
  Deliberately distinct from DIVERGED/FRAME_FAILED: a deadline miss is
  a *policy* outcome (the pool was busy), not a numerical or
  infrastructure fault, and must not count toward tenant quarantine or
  the partial exit code. Never produced by the one-shot CLI (its frames
  carry no deadline).

Process exit codes (the CLI contract):

- ``0`` EXIT_OK — every frame SUCCESS or MAX_ITERATIONS_EXCEEDED.
- ``1`` EXIT_INPUT_ERROR — user input/flag problem (reference parity).
- ``2`` EXIT_PARTIAL — the run COMPLETED but at least one frame is
  DIVERGED/FRAME_FAILED; the output file holds every frame's row.
- ``3`` EXIT_INFRASTRUCTURE — the run ABORTED on an unrecoverable
  infrastructure failure after retries (RTM ingest, output flush,
  multihost init) or a watchdog hard abort; the output file is resumable.
- ``4`` EXIT_INTERRUPTED — the run STOPPED GRACEFULLY on SIGTERM/SIGINT
  (resilience/shutdown.py): the in-flight frame group was drained, the
  async writer flushed, and the output file is resumable; frames not yet
  dispatched were not solved. A second signal aborts immediately (death
  by the signal, conventional 128+N status).
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional

import numpy as np

from sartsolver_tpu.config import (
    DIVERGED,
    MAX_ITERATIONS_EXCEEDED,
    SDC_DETECTED,
    SUCCESS,
)
from sartsolver_tpu.resilience.faults import InjectedFault, InjectedIOError
from sartsolver_tpu.resilience.retry import RetriesExhausted, retry_stats

FRAME_FAILED = -3
# Serving-engine deadline shed (docs/SERVING.md): the scheduler retired
# the lane at a stride boundary because the request's deadline passed.
DEADLINE_EXCEEDED = -5

EXIT_OK = 0
EXIT_INPUT_ERROR = 1
EXIT_PARTIAL = 2
EXIT_INFRASTRUCTURE = 3
EXIT_INTERRUPTED = 4


class OutputWriteError(RuntimeError):
    """A solution-file flush failed mid-run. Distinct from ``OSError`` so
    the CLI maps it to EXIT_INFRASTRUCTURE (the file is resumable), not
    the polite input-error exit."""


class WatchdogTimeout(RuntimeError):
    """Raised *into* a stuck thread by the hang watchdog
    (resilience/watchdog.py) after the progress beacons stalled past
    ``SART_WATCHDOG_TIMEOUT``. Defined here (not in watchdog.py) so the
    taxonomy module owns every member of RECOVERABLE_FRAME_ERRORS without
    an import cycle: a frame whose staging/dispatch was interrupted is
    escalated into the same FRAME_FAILED path as the injected
    ``device.put``/``solve.dispatch`` faults it stands in for."""


class FrameFailure(NamedTuple):
    """A frame the prefetcher could not deliver (read retries exhausted).

    Shaped like the ``(frame, time, camera_times)`` stream items —
    ``frame`` is None and ``[1]`` is still the composite time — so it
    flows through the CLI's resume filter unchanged; the frame loop
    pattern-matches on the type and records a FRAME_FAILED row instead of
    solving.
    """

    frame: None
    time: float
    camera_times: List[float]
    error: BaseException


# What the CLI's per-frame isolation may absorb into a FRAME_FAILED row.
# Deliberately narrow: an unexpected ValueError/TypeError is an internal
# bug and must traceback (tests/test_cli.py::test_internal_error_propagates),
# not be laundered into a "failed frame". JaxRuntimeError is the REAL
# counterpart of the injected device.put/solve.dispatch faults — device
# OOM, a preempted/halted runtime — raised at execute time, never for
# trace-time bugs (those surface as TypeError/ValueError before any
# frame-specific work). Guarded import: jax is always loaded by the time
# a solve can fail, but this module must stay importable without it.
try:
    from jax.errors import JaxRuntimeError as _JaxRuntimeError

    _DEVICE_ERRORS = (_JaxRuntimeError,)
except ImportError:  # pragma: no cover - jax is a hard dep in practice
    _DEVICE_ERRORS = ()

RECOVERABLE_FRAME_ERRORS = (
    OSError,  # includes InjectedIOError and real I/O errors
    InjectedFault,  # includes InjectedOOM (the injected RESOURCE_EXHAUSTED)
    RetriesExhausted,
    WatchdogTimeout,  # a hung frame interrupted by the watchdog
) + _DEVICE_ERRORS


def status_name(status: int) -> str:
    return {
        SUCCESS: "converged",
        MAX_ITERATIONS_EXCEEDED: "max-iterations",
        DIVERGED: "diverged",
        FRAME_FAILED: "failed",
        SDC_DETECTED: "sdc",
        DEADLINE_EXCEEDED: "deadline",
    }.get(int(status), f"unknown({int(status)})")


class RunSummary:
    """End-of-run accounting of per-frame outcomes and retry activity."""

    def __init__(self) -> None:
        self.counts = {SUCCESS: 0, MAX_ITERATIONS_EXCEEDED: 0,
                       DIVERGED: 0, FRAME_FAILED: 0, SDC_DETECTED: 0}
        self.failed_times: List[float] = []
        # availability events (watchdog fires, OOM degradations, stop
        # requests): free-form one-liners appended by their owners and
        # echoed verbatim in format() — anything that degraded or
        # recovered must be visible in the end-of-run accounting
        self.events: List[str] = []

    def record_status(self, status: int, time: Optional[float] = None) -> None:
        status = int(status)
        self.counts[status] = self.counts.get(status, 0) + 1
        if (status in (DIVERGED, FRAME_FAILED, SDC_DETECTED)
                and time is not None):
            self.failed_times.append(float(time))

    def record_event(self, event: str) -> None:
        """Note an availability event (thread-safe under the GIL: the
        watchdog monitor thread appends concurrently with the frame
        loop)."""
        self.events.append(str(event))

    @property
    def n_frames(self) -> int:
        return sum(self.counts.values())

    @property
    def n_failed(self) -> int:
        return (self.counts[DIVERGED] + self.counts[FRAME_FAILED]
                + self.counts[SDC_DETECTED])

    def had_retries(self) -> bool:
        return any(
            v["recoveries"] or v["exhausted"]
            for v in retry_stats().values()
        )

    def exit_code(self) -> int:
        return EXIT_PARTIAL if self.n_failed else EXIT_OK

    def format(self) -> str:
        parts = [
            f"{n} {status_name(s)}"
            for s, n in sorted(self.counts.items(), reverse=True) if n
        ]
        lines = [
            f"resilience summary: {self.n_frames} frame(s): "
            + ", ".join(parts or ["none"])
        ]
        if self.failed_times:
            shown = ", ".join(f"{t:g}" for t in self.failed_times[:8])
            more = len(self.failed_times) - 8
            lines.append(
                "  failed frame time(s): " + shown
                + (f" (+{more} more)" if more > 0 else "")
            )
        for site, v in sorted(retry_stats().items()):
            if v["recoveries"] or v["exhausted"]:
                lines.append(
                    f"  retries at {site}: {v['attempts']} attempt(s), "
                    f"{v['recoveries']} recovered, {v['exhausted']} exhausted"
                )
        for event in self.events:
            lines.append(f"  {event}")
        return "\n".join(lines)


def failed_row(nvoxel: int) -> np.ndarray:
    """The solution row written for a FRAME_FAILED frame (all zeros, the
    dataset fill value — indistinguishable from never-written except by
    its status, which is the point: the status column is authoritative)."""
    return np.zeros(nvoxel, np.float64)
