"""Supervised serve: keep the resident engine alive across crashes.

``sartsolve serve --supervised`` (docs/SERVING.md §9) turns the serve
process from a single point of failure into a self-healing pair: this
module is the *supervisor* — a small, jax-free parent process that
spawns the real serve worker and keeps it alive across every abnormal
exit (watchdog ``os._exit(3)``, SDC quarantine, OOM kill, segfault,
``kill -9``). The escalation ladder sits one level above the watchdog's
(docs/RESILIENCE.md §10):

- **Restart with bounded exponential backoff** — each consecutive crash
  doubles the respawn delay (``--restart_backoff`` base, capped at
  ``--restart_backoff_max``); a worker that stayed alive longer than
  the crash-loop window resets the streak. Durable engine state
  (engine/state.py) + journal replay make the restart cheap and
  exactly-once.
- **Crash-loop circuit breaker** — ``--crash_loop_threshold`` crashes
  inside a sliding ``--crash_loop_window`` open the breaker:
  **lame-duck mode**. The supervisor stops burning restarts, serves
  ``/healthz`` = 503 (``crash-loop``) on the worker's ``--http_port``,
  and *journals-but-refuses* admissions: every request landing in the
  ingest dir gets a machine-readable ``crash-loop`` rejection response
  (with a ``retry_after_s`` hint — the remaining breaker window) and a
  record in ``supervisor.jsonl``, until the window clears and the
  breaker half-opens into one more restart.
- **Deliberate exits are final** — worker exit 0 (idle), 4 (drained
  after SIGTERM) and 1 (flag/config error: restarting would loop
  pointlessly) end the supervisor with the same code.

SIGTERM/SIGINT at the supervisor forwards ONE SIGTERM to the worker for
a graceful drain (journal + state checkpoint land; exit 4); a second
signal SIGKILLs the worker and dies by the signal.

Observability: every restart increments
``engine_restarts_total{reason=...}`` and lame-duck flips the
``engine_crash_loop`` gauge — both live in the supervisor's registry,
exposed as a Prometheus textfile at ``<engine_dir>/supervisor.prom``
and on the lame-duck ``/metrics`` endpoint. Restart events and mirrored
worker crash-bundle reasons land in the flight ring and the durable
``<engine_dir>/supervisor.jsonl``; entering lame duck writes a
supervisor crash bundle (``supervisor.crash.json``).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from collections import deque
from typing import List, Optional

from sartsolver_tpu.engine import routing as fleet_routing
from sartsolver_tpu.obs import flight as obs_flight
from sartsolver_tpu.obs import metrics as obs_metrics
from sartsolver_tpu.utils import atomicio

# supervisor.jsonl / fleet.jsonl size-based rotation knob: past this
# many bytes the log is compacted to its newest half-limit tail. A
# supervisor that survives weeks of crash-loops must bound its own
# disk, same reasoning as the engine's journal rotation. 0 disables.
DEFAULT_ROTATE_BYTES = 256 * 1024


def _rotate_limit() -> int:
    try:
        return int(os.environ.get("SART_SUPERVISOR_ROTATE_BYTES")
                   or DEFAULT_ROTATE_BYTES)
    except ValueError:
        return DEFAULT_ROTATE_BYTES


def rotate_events(path: str, limit: int) -> int:
    """Size-based event-log rotation: once ``path`` passes ``limit``
    bytes, atomically rewrite it down to its newest ~``limit/2`` tail
    of whole lines (oldest records are the ones already mirrored to
    every other surface). Returns bytes reclaimed, 0 when nothing
    happened. Rotation failure is silent by design — the log keeps
    growing rather than the supervisor dying over housekeeping."""
    if not limit or limit <= 0:
        return 0
    try:
        size = os.path.getsize(path)
    except OSError:
        return 0
    if size <= limit:
        return 0
    try:
        with open(path, errors="replace") as f:
            lines = f.readlines()
    except OSError:
        return 0
    keep: List[str] = []
    budget = limit // 2
    kept = 0
    for line in reversed(lines):
        if kept + len(line) > budget and keep:
            break
        keep.append(line)
        kept += len(line)
    keep.reverse()
    try:
        # durable: rotated event log (atomic rename — every reader sees
        # a complete file; fsync'd so the kept tail survives a crash)
        atomicio.write_atomic(path, "".join(keep), fsync=True)
    except OSError:
        return 0
    return max(0, size - kept)


def classify_exit(returncode: int) -> str:
    """A worker exit's machine-readable restart reason (the
    ``engine_restarts_total`` label and event vocabulary): ``signal:
    SIGKILL``-style for signal deaths, ``infrastructure`` for the
    documented exit 3, ``exit:N`` otherwise."""
    if returncode < 0:
        try:
            return f"signal:{signal.Signals(-returncode).name}"
        except ValueError:
            return f"signal:{-returncode}"
    if returncode == 3:
        return "infrastructure"
    return f"exit:{returncode}"


class CrashLoopBreaker:
    """Sliding-window crash counter: ``threshold`` crashes inside
    ``window_s`` seconds opens the breaker (lame duck) until the oldest
    crash ages out of the window."""

    def __init__(self, threshold: int, window_s: float):
        self.threshold = max(1, int(threshold))
        self.window_s = float(window_s)
        self.crashes: deque = deque()

    def record(self, now: float) -> None:
        self.crashes.append(float(now))
        self._expire(now)

    def _expire(self, now: float) -> None:
        while self.crashes and now - self.crashes[0] > self.window_s:
            self.crashes.popleft()

    def open(self, now: float) -> bool:
        self._expire(now)
        return len(self.crashes) >= self.threshold

    def remaining_s(self, now: float) -> float:
        """Seconds until the breaker would close (0 when closed)."""
        self._expire(now)
        if len(self.crashes) < self.threshold:
            return 0.0
        # closes when the crash that keeps the count at threshold ages out
        oldest_needed = self.crashes[len(self.crashes) - self.threshold]
        return max(0.0, oldest_needed + self.window_s - now)


def restart_backoff(streak: int, base: float, cap: float) -> float:
    """Respawn delay before consecutive-crash number ``streak`` (1-based):
    exponential from ``base``, capped at ``cap``."""
    if streak <= 0:
        return 0.0
    return min(float(base) * (2.0 ** (streak - 1)), float(cap))


class Supervisor:
    """One supervised serve worker's parent process."""

    def __init__(
        self,
        worker_argv: List[str],
        *,
        engine_dir: str,
        backoff_base: float = 1.0,
        backoff_max: float = 30.0,
        crash_loop_window: float = 60.0,
        crash_loop_threshold: int = 5,
        max_restarts: int = 0,
        http_port: Optional[int] = None,
        poll_interval: float = 0.2,
    ):
        self.worker_argv = list(worker_argv)
        self.engine_dir = engine_dir
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.max_restarts = max(0, int(max_restarts))
        self.http_port = http_port
        self.poll_interval = float(poll_interval)
        self.breaker = CrashLoopBreaker(crash_loop_threshold,
                                        crash_loop_window)
        self.restarts = 0
        self.streak = 0  # consecutive fast crashes (backoff exponent)
        self.lame_ducks = 0
        self.lame_rejected = 0
        self._proc: Optional[subprocess.Popen] = None
        self._stop = False
        self._signame: Optional[str] = None
        self._forwarded = False
        # the intake/verdict dirs exist from the first instant: a client
        # must be able to submit (and read a rejection) even while the
        # worker is still coming up — or crash-looping before it ever
        # managed to create them
        for sub in ("", "ingest", "responses"):
            os.makedirs(os.path.join(engine_dir, sub), exist_ok=True)
        self.events_path = os.path.join(engine_dir, "supervisor.jsonl")  # durable: supervisor events
        self.rotate_bytes = _rotate_limit()
        self.prom_path = os.path.join(engine_dir, "supervisor.prom")
        self.bundle_path = os.path.join(engine_dir,
                                        "supervisor.crash.json")
        registry = obs_metrics.get_registry()
        self._crash_loop_gauge = registry.gauge("engine_crash_loop")
        self._crash_loop_gauge.set(0.0)

    # ---- events / metrics ------------------------------------------------

    def _event(self, kind: str, **data) -> None:
        """One supervisor event, fanned out to every surface: stderr
        (the operator's live view), the flight ring (crash-bundle
        tail), the durable supervisor.jsonl, and the Prometheus
        textfile (best-effort — a full disk must not kill the
        supervisor, it is the thing that survives)."""
        rec = {"unix": round(time.time(), 3), "kind": str(kind)}
        rec.update(data)
        detail = " ".join(f"{k}={v}" for k, v in data.items())
        print(f"sartsolve supervisor: {kind}"
              + (f" {detail}" if detail else ""), file=sys.stderr,
              flush=True)
        obs_flight.record_event(f"supervisor.{kind}", **data)
        # getattr: drills construct bare instances via __new__ with only
        # the paths set — rotation simply stays off there
        rotate_events(self.events_path, getattr(self, "rotate_bytes", 0))
        try:
            # flush+fsync like the journal/state appends: the
            # supervisor is the component that survives the crash, so
            # its record of the crash must survive it too
            atomicio.append_line(self.events_path,
                                 json.dumps(rec) + "\n")
        except OSError:
            pass
        self._write_prom()

    def _write_prom(self) -> None:
        from sartsolver_tpu.obs.sinks import PromSink

        try:
            PromSink(self.prom_path).write(
                obs_metrics.get_registry().snapshot(blocking=False)
            )
        except OSError:
            pass

    def _restart_ctr(self, reason: str):
        return obs_metrics.get_registry().counter(
            "engine_restarts_total", reason=reason
        )

    # ---- signals ---------------------------------------------------------

    def _handler(self, signum, _frame) -> None:
        name = signal.Signals(signum).name
        if self._stop:
            # second signal: SIGKILL the worker, die by the signal
            proc = self._proc
            if proc is not None and proc.poll() is None:
                try:
                    proc.kill()
                except OSError:
                    pass
            signal.signal(signum, signal.SIG_DFL)
            signal.raise_signal(signum)
            return
        self._stop = True
        self._signame = name
        sys.stderr.write(
            f"sartsolve supervisor: received {name} — forwarding "
            "SIGTERM to the worker for one graceful drain. Send again "
            "to abort immediately.\n"
        )
        sys.stderr.flush()

    def _install_signals(self) -> None:
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, self._handler)

    # ---- worker lifecycle ------------------------------------------------

    def _spawn(self) -> subprocess.Popen:
        cmd = [sys.executable, "-m", "sartsolver_tpu.cli", "serve",
               *self.worker_argv]
        proc = subprocess.Popen(cmd)  # stdout/stderr inherited
        self._proc = proc
        self._forwarded = False
        self._event("worker-spawn", pid=proc.pid,
                    spawn=self.restarts + 1)
        return proc

    def _wait(self, proc: subprocess.Popen) -> int:
        while True:
            rc = proc.poll()
            if rc is not None:
                return rc
            if self._stop and not self._forwarded:
                self._forwarded = True
                try:
                    proc.send_signal(signal.SIGTERM)
                    self._event("sigterm-forwarded", pid=proc.pid,
                                signal=self._signame)
                except OSError:
                    pass
            time.sleep(self.poll_interval)

    def _sleep(self, seconds: float) -> None:
        """Interruptible backoff sleep (a stop request cuts it short)."""
        deadline = time.monotonic() + seconds
        while not self._stop and time.monotonic() < deadline:
            time.sleep(min(self.poll_interval,
                           max(deadline - time.monotonic(), 0.0)))

    def _mirror_crash_bundle(self, spawned_unix: float) -> None:
        """Fold the dead worker's crash bundle (when it managed to write
        one) into the supervisor's event stream, so triage starts from
        supervisor.jsonl whatever killed the worker."""
        path = os.path.join(self.engine_dir, "engine.crash.json")
        try:
            if os.path.getmtime(path) < spawned_unix - 1.0:
                return  # a previous incarnation's bundle
            with open(path) as f:
                bundle = json.load(f)
        except (OSError, ValueError):
            return
        self._event("worker-crash-bundle",
                    reason=str(bundle.get("reason", "?")), path=path)

    # ---- lame duck -------------------------------------------------------

    def _lame_duck_status(self) -> dict:
        now = time.monotonic()
        return obs_flight.status_snapshot(
            blocking=False,
            supervisor={
                "lame_duck": True,
                "restarts": self.restarts,
                "breaker_remaining_s": round(
                    self.breaker.remaining_s(now), 1),
                "rejected": self.lame_rejected,
            },
        )

    def _reject_ingest(self, remaining_s: float) -> int:
        """The journal-but-refuse half of lame duck: every request file
        is answered with a byte-stable ``crash-loop`` rejection (plus
        the retry hint) and recorded — never silently dropped, never
        queued into a pool that cannot serve it."""
        from sartsolver_tpu.engine.request import REASON_CRASH_LOOP

        ingest = os.path.join(self.engine_dir, "ingest")
        responses = os.path.join(self.engine_dir, "responses")
        try:
            names = sorted(os.listdir(ingest))
        except OSError:
            return 0
        n = 0
        for name in names:
            if not name.endswith(".json"):
                continue
            path = os.path.join(ingest, name)
            rid = os.path.splitext(name)[0]
            try:
                with open(path) as f:
                    payload = json.load(f)
                if isinstance(payload, dict) and payload.get("id"):
                    rid = str(payload["id"])
            except (OSError, ValueError):
                pass  # reject under the file stem; id unknowable
            # never clobber a completed id's recorded outcome: a
            # resubmission during lame duck is a duplicate, and the
            # engine's contract is that the original response survives —
            # the submitter resolves from it, no rejection needed
            try:
                with open(os.path.join(responses, f"{rid}.json")) as f:
                    prev = json.load(f)
            except (OSError, ValueError):
                prev = None
            if prev and prev.get("state") == "done":
                try:
                    os.unlink(path)
                except OSError:
                    pass
                self._event("lame-duck-duplicate", id=rid)
                continue
            rec = {"unix": round(time.time(), 3), "id": rid,
                   "verdict": "rejected", "reason": REASON_CRASH_LOOP,
                   "retry_after_s": round(max(remaining_s, 1.0), 1)}
            try:
                os.makedirs(responses, exist_ok=True)
                atomicio.write_json_atomic(
                    os.path.join(responses, f"{rid}.json"), rec,
                    fsync=True,
                )
            except OSError:
                continue  # leave the request file for the next pass
            try:
                os.unlink(path)
            except OSError:
                pass
            self.lame_rejected += 1
            obs_metrics.get_registry().counter(
                "engine_shed_total", reason=REASON_CRASH_LOOP
            ).inc()
            self._event("lame-duck-reject", id=rid,
                        retry_after_s=rec["retry_after_s"])
            n += 1
        return n

    def _lame_duck(self, last_reason: str) -> None:
        """Breaker open: hold restarts, answer 503s and crash-loop
        rejections until the sliding window clears."""
        self.lame_ducks += 1
        self._crash_loop_gauge.set(1.0)
        now = time.monotonic()
        remaining = self.breaker.remaining_s(now)
        self._event("lame-duck-enter",
                    crashes=len(self.breaker.crashes),
                    window_s=self.breaker.window_s,
                    remaining_s=round(remaining, 1),
                    last_reason=last_reason)
        obs_flight.write_crash_bundle(
            self.bundle_path,
            f"crash-loop: {len(self.breaker.crashes)} crashes in "
            f"{self.breaker.window_s:g}s (last: {last_reason}); "
            f"lame duck for {remaining:.1f}s",
        )
        http = None
        if self.http_port is not None:
            from sartsolver_tpu.engine.httpd import EngineHTTPServer
            from sartsolver_tpu.engine.request import REASON_CRASH_LOOP

            registry = obs_metrics.get_registry()

            def detail() -> str:
                left = self.breaker.remaining_s(time.monotonic())
                return (f"crash-loop breaker open; retry in "
                        f"{left:.1f}s")

            try:
                http = EngineHTTPServer(
                    self.http_port,
                    metrics_snapshot=lambda: registry.snapshot(
                        blocking=False),
                    health=lambda: (REASON_CRASH_LOOP, detail()),
                    ready=lambda: (REASON_CRASH_LOOP, detail()),
                    status=self._lame_duck_status,
                )
                http.start()
                self._event("lame-duck-endpoint", port=http.port)
            except OSError as err:
                # the dead worker's socket may linger in TIME_WAIT;
                # lame duck still rejects via the responses dir
                self._event("lame-duck-endpoint-failed", error=str(err))
                http = None
        try:
            while not self._stop:
                now = time.monotonic()
                remaining = self.breaker.remaining_s(now)
                if remaining <= 0:
                    break
                self._reject_ingest(remaining)
                time.sleep(min(self.poll_interval, remaining))
        finally:
            if http is not None:
                http.stop()
        self._crash_loop_gauge.set(0.0)
        self.breaker.crashes.clear()
        self.streak = 0
        self._event("lame-duck-exit", rejected=self.lame_rejected)

    # ---- main loop -------------------------------------------------------

    def run(self) -> int:
        self._install_signals()
        obs_flight.install()
        self._event("start",
                    backoff=self.backoff_base,
                    backoff_max=self.backoff_max,
                    window_s=self.breaker.window_s,
                    threshold=self.breaker.threshold,
                    max_restarts=self.max_restarts or "unlimited")
        try:
            while True:
                spawned_unix = time.time()
                t_spawn = time.monotonic()
                proc = self._spawn()
                rc = self._wait(proc)
                lifetime = time.monotonic() - t_spawn
                reason = classify_exit(rc)
                if rc in (0, 4):
                    # clean idle exit / graceful drain (ours or an
                    # operator's direct SIGTERM at the worker): done
                    self._event("worker-done", code=rc,
                                lifetime_s=round(lifetime, 1))
                    return rc
                if rc == 1:
                    # flag/config error: a restart would re-fail
                    # identically forever — surface it instead
                    self._event("worker-config-error", code=rc)
                    return 1
                if self._stop:
                    # we asked for a drain and the worker died anyway
                    # (second signal, or it crashed mid-drain): stop
                    self._event("worker-died-draining", code=rc,
                                reason=reason)
                    return 4 if rc < 0 else rc
                self._mirror_crash_bundle(spawned_unix)
                self.restarts += 1
                self._restart_ctr(reason).inc()
                now = time.monotonic()
                self.breaker.record(now)
                # a worker that survived the whole window was healthy:
                # the next crash starts a fresh backoff ladder
                self.streak = (1 if lifetime > self.breaker.window_s
                               else self.streak + 1)
                self._event("worker-crash", code=rc, reason=reason,
                            lifetime_s=round(lifetime, 1),
                            restarts=self.restarts,
                            window_crashes=len(self.breaker.crashes))
                if self.max_restarts and self.restarts >= self.max_restarts:
                    self._event("restart-budget-exhausted",
                                restarts=self.restarts)
                    return 3
                if self.breaker.open(now):
                    self._lame_duck(reason)
                    if self._stop:
                        return 4
                    continue  # half-open: one fresh spawn
                delay = restart_backoff(self.streak, self.backoff_base,
                                        self.backoff_max)
                if delay > 0:
                    self._event("backoff", delay_s=round(delay, 2),
                                streak=self.streak)
                    self._sleep(delay)
                if self._stop:
                    return 4
        finally:
            obs_flight.uninstall()
            self._write_prom()


class FleetController:
    """M supervised serve workers + tenant-affinity routing +
    journal-backed failover (docs/SERVING.md §10).

    Layout under ``fleet_dir``::

        routing.json        atomically-published routing table
        fleet.jsonl         controller events (rotated like supervisor.jsonl)
        ingest/             controller intake (client fallback routing)
        responses/          SHARED verdict/outcome files (all workers)
        outputs/            SHARED solution files (all workers)
        workers/w<k>/       each worker's own engine dir (journal, state)

    Each worker is a normal ``sartsolve serve`` process pinned to its
    shard: ``--worker_index k --fleet_size M`` arms the admission
    affinity check (``wrong-worker`` sheds misrouted tenants), and the
    shared responses/outputs dirs mean a client polls ONE place no
    matter which worker — or which worker's *survivor* — solved its
    request.

    Failover is journal-backed: when a worker dies abnormally the
    controller replays its journal shard, appends a ``handoff`` marker
    per accepted-but-uncompleted request to the DEAD worker's journal
    (marker first: once durable, a restart of that worker will never
    re-drive the id), then re-stages each payload — ``handoff`` flag
    set so affinity admits it — into a surviving worker's ingest dir.
    The dedup watermark + shared responses dir carry the exactly-once
    story across the handoff; the crash-point model checker
    (analysis/protocol.py) enumerates a crash at every effect boundary
    of this dance, with :func:`~sartsolver_tpu.engine.protocol.
    needs_restage` as the shared recovery gate.
    """

    def __init__(
        self,
        worker_argv: List[str],
        *,
        fleet_dir: str,
        size: int = 3,
        base_port: Optional[int] = None,
        backoff_base: float = 0.5,
        backoff_max: float = 10.0,
        max_restarts: int = 0,
        poll_interval: float = 0.1,
    ):
        self.worker_argv = list(worker_argv)
        self.fleet_dir = fleet_dir
        self.size = max(1, int(size))
        self.base_port = None if base_port is None else int(base_port)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.max_restarts = max(0, int(max_restarts))
        self.poll_interval = float(poll_interval)
        self.restarts = 0
        self._stop = False
        self._signame: Optional[str] = None
        self._forwarded = False
        self.ingest_dir = os.path.join(fleet_dir, "ingest")
        self.responses_dir = os.path.join(fleet_dir, "responses")
        self.outputs_dir = os.path.join(fleet_dir, "outputs")
        self.events_path = os.path.join(fleet_dir, "fleet.jsonl")  # durable: fleet events
        self.rotate_bytes = _rotate_limit()
        for d in (fleet_dir, self.ingest_dir, self.responses_dir,
                  self.outputs_dir):
            os.makedirs(d, exist_ok=True)
        self.workers: List[dict] = []
        for k in range(self.size):
            wdir = os.path.join(fleet_dir, "workers", f"w{k}")
            os.makedirs(os.path.join(wdir, "ingest"), exist_ok=True)
            self.workers.append({
                "index": k, "dir": wdir, "proc": None, "state": "down",
                "spawns": 0, "streak": 0, "next_spawn": 0.0,
                "t_spawn": 0.0, "done": False,
            })

    # ---- events / plumbing -----------------------------------------------

    def _event(self, kind: str, **data) -> None:
        rec = {"unix": round(time.time(), 3), "kind": str(kind)}
        rec.update(data)
        detail = " ".join(f"{k}={v}" for k, v in data.items())
        print(f"sartsolve fleet: {kind}"
              + (f" {detail}" if detail else ""), file=sys.stderr,
              flush=True)
        obs_flight.record_event(f"fleet.{kind}", **data)
        rotate_events(self.events_path, getattr(self, "rotate_bytes", 0))
        try:
            atomicio.append_line(self.events_path,
                                 json.dumps(rec) + "\n")
        except OSError:
            pass

    def _journal(self, k: int):
        from sartsolver_tpu.engine.journal import RequestJournal

        return RequestJournal(
            os.path.join(self.workers[k]["dir"], "journal.jsonl")
        )

    def _worker_port(self, k: int) -> Optional[int]:
        return None if self.base_port is None else self.base_port + k

    def _alive(self, k: int) -> bool:
        proc = self.workers[k]["proc"]
        return (self.workers[k]["state"] == "up" and proc is not None
                and proc.poll() is None)

    def _ready(self, k: int) -> bool:
        """Best-effort ``/readyz`` poll (portless fleets count every
        alive worker as ready — the ingest backlog still load-balances)."""
        port = self._worker_port(k)
        if port is None:
            return True
        import urllib.request

        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/readyz", timeout=0.5) as r:
                return r.status == 200
        except Exception:  # noqa: BLE001 - a dead endpoint is "not ready"
            return False

    def _publish_routing(self) -> None:
        rows = [
            {"index": w["index"],
             "ingest_dir": os.path.join(w["dir"], "ingest"),
             "http_port": self._worker_port(w["index"]),
             "state": w["state"]}
            for w in self.workers
        ]
        fleet_routing.publish_routing(
            self.fleet_dir, rows, responses_dir=self.responses_dir,
            ingest_dir=self.ingest_dir,
        )

    # ---- worker lifecycle ------------------------------------------------

    def _spawn(self, k: int) -> None:
        w = self.workers[k]
        cmd = [sys.executable, "-m", "sartsolver_tpu.cli", "serve",
               "--engine_dir", w["dir"],
               "--responses_dir", self.responses_dir,
               "--outputs_dir", self.outputs_dir,
               "--worker_index", str(k),
               "--fleet_size", str(self.size)]
        port = self._worker_port(k)
        if port is not None:
            cmd += ["--http_port", str(port)]
        cmd += self.worker_argv
        env = dict(os.environ)
        # per-worker metric identity: every engine series the worker
        # registers carries worker=w<k> (obs/metrics.py default labels)
        env["SART_WORKER_ID"] = f"w{k}"
        proc = subprocess.Popen(cmd, env=env)  # stdout/stderr inherited
        w["proc"] = proc
        w["state"] = "up"
        w["spawns"] += 1
        w["t_spawn"] = time.monotonic()
        self._event("worker-spawn", pid=proc.pid, spawn=w["spawns"],
                    worker=k)

    def _pick_survivor(self, exclude: int) -> Optional[int]:
        """The failover/fallback target: an alive worker, ready ones
        first, least ingest backlog breaking ties."""
        alive = [w["index"] for w in self.workers
                 if w["index"] != exclude and self._alive(w["index"])]
        if not alive:
            return None
        ready = [k for k in alive if self._ready(k)]
        pool = ready or alive

        def backlog(k: int) -> int:
            try:
                return len(os.listdir(
                    os.path.join(self.workers[k]["dir"], "ingest")))
            except OSError:
                return 0

        return min(pool, key=lambda k: (backlog(k), k))

    def _failover(self, k: int) -> None:
        """Re-drive a dead worker's accepted-but-uncompleted journal
        entries on a survivor (handoff marker FIRST — see the class
        docstring for the crash-ordering argument)."""
        w = self.workers[k]
        w["state"] = "down"
        self._publish_routing()
        journal = self._journal(k)
        _completed, pending, _handed = journal.replay_full()
        if not pending:
            return
        target = self._pick_survivor(exclude=k)
        if target is None:
            # nobody to hand off to: the respawned worker replays its
            # own journal — same exactly-once story, just slower
            self._event("handoff-skipped", worker=k,
                        pending=len(pending))
            return
        target_ingest = os.path.join(self.workers[target]["dir"],
                                     "ingest")
        for req in pending:
            journal.handoff(req.id, target, trace_id=req.trace)
            payload = req.to_dict()
            payload["handoff"] = True
            # a partial solution from the dead worker's interrupted
            # attempt is removed so the survivor writes it fresh
            # (byte-identical re-drive; same contract as single-worker
            # journal replay) — safe because pending means no completed
            # marker exists anywhere for this id
            try:
                os.unlink(os.path.join(self.outputs_dir,
                                       f"{req.id}.h5"))
            except OSError:
                pass
            # durable: failover re-stage (fsync'd atomic publish)
            atomicio.write_json_atomic(
                os.path.join(target_ingest, f"{req.id}.json"),
                payload, fsync=True,
            )
            self._event("handoff", id=req.id, source=k, target=target)

    def _recover(self) -> None:
        """Controller-restart recovery: finish any handoff a previous
        incarnation's crash interrupted. The crash may have landed
        between the handoff marker and the re-stage publish — the
        shared gate :func:`~sartsolver_tpu.engine.protocol.
        needs_restage` re-stages exactly when no other copy of the
        story exists anywhere in the fleet."""
        from sartsolver_tpu.engine.protocol import needs_restage

        replays = [self._journal(w["index"]).replay_full()
                   for w in self.workers]
        completed_anywhere: set = set()
        for completed, _pending, _handed in replays:
            completed_anywhere.update(completed)
        restaged = 0
        for w in self.workers:
            _completed, _pending, handed = replays[w["index"]]
            for rid, story in handed.items():
                target = story.get("target")
                if target is None or not 0 <= int(target) < self.size:
                    continue
                target = int(target)
                t_ingest = os.path.join(self.workers[target]["dir"],
                                        "ingest")
                staged = os.path.exists(
                    os.path.join(t_ingest, f"{rid}.json"))
                pending_ids = {req.id for req in replays[target][1]}
                if not needs_restage(
                        completed_anywhere=rid in completed_anywhere,
                        pending_on_target=rid in pending_ids,
                        staged_on_target=staged):
                    continue
                req = story.get("request")
                payload = req.to_dict() if req is not None else {"id": rid}
                payload["handoff"] = True
                try:
                    os.unlink(os.path.join(self.outputs_dir,
                                           f"{rid}.h5"))
                except OSError:
                    pass
                # durable: failover re-stage (recovery pass)
                atomicio.write_json_atomic(
                    os.path.join(t_ingest, f"{rid}.json"), payload,
                    fsync=True,
                )
                restaged += 1
                self._event("handoff-restage", id=rid,
                            source=w["index"], target=target)
        if restaged:
            self._event("recovery", restaged=restaged)

    # ---- controller intake -----------------------------------------------

    def _pump_intake(self) -> int:
        """Route requests dropped in the fleet-level ingest dir (the
        routing table's client fallback) to their tenant-affinity
        worker — or, when that worker is down, to a survivor with the
        handoff flag set so admission accepts them there."""
        try:
            names = sorted(os.listdir(self.ingest_dir))
        except OSError:
            return 0
        n = 0
        for name in names:
            if not name.endswith(".json"):
                continue
            path = os.path.join(self.ingest_dir, name)
            try:
                with open(path) as f:
                    payload = json.load(f)
            except (OSError, ValueError):
                continue  # torn mid-write; picked up next pass
            tenant = "default"
            if isinstance(payload, dict):
                tenant = str(payload.get("tenant") or "default")
            k = fleet_routing.tenant_worker(tenant, self.size)
            if not self._alive(k):
                target = self._pick_survivor(exclude=k)
                if target is None:
                    return n  # nobody up; keep the file, retry next loop
                if isinstance(payload, dict):
                    payload = {**payload, "handoff": True}
                k = target
            dst = os.path.join(self.workers[k]["dir"], "ingest", name)
            try:
                # durable: routed intake (fsync'd atomic publish into
                # the worker's ingest, then the fleet copy is dropped)
                atomicio.write_json_atomic(dst, payload, fsync=True)
                os.unlink(path)
            except OSError:
                continue
            n += 1
        if n:
            self._event("intake-routed", requests=n)
        return n

    # ---- signals / main loop ---------------------------------------------

    def _handler(self, signum, _frame) -> None:
        name = signal.Signals(signum).name
        if self._stop:
            for w in self.workers:
                proc = w["proc"]
                if proc is not None and proc.poll() is None:
                    try:
                        proc.kill()
                    except OSError:
                        pass
            signal.signal(signum, signal.SIG_DFL)
            signal.raise_signal(signum)
            return
        self._stop = True
        self._signame = name
        sys.stderr.write(
            f"sartsolve fleet: received {name} — forwarding SIGTERM "
            "to every worker for one graceful drain. Send again to "
            "abort immediately.\n"
        )
        sys.stderr.flush()

    def run(self) -> int:
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, self._handler)
        obs_flight.install()
        self._event("start", size=self.size, fleet_dir=self.fleet_dir)
        exit_code = 0
        try:
            self._recover()
            for w in self.workers:
                self._spawn(w["index"])
            self._publish_routing()
            while True:
                if self._stop and not self._forwarded:
                    self._forwarded = True
                    for w in self.workers:
                        proc = w["proc"]
                        if proc is not None and proc.poll() is None:
                            try:
                                proc.send_signal(signal.SIGTERM)
                            except OSError:
                                pass
                    self._event("sigterm-forwarded",
                                signal=self._signame)
                self._pump_intake()
                now = time.monotonic()
                for w in self.workers:
                    if w["done"]:
                        continue
                    proc = w["proc"]
                    if proc is None:
                        if not self._stop and now >= w["next_spawn"]:
                            self._spawn(w["index"])
                            self._publish_routing()
                        continue
                    rc = proc.poll()
                    if rc is None:
                        continue
                    lifetime = now - w["t_spawn"]
                    reason = classify_exit(rc)
                    w["proc"] = None
                    if rc in (0, 4) or (self._stop and rc != 1):
                        # clean idle exit / graceful drain — final
                        w["done"] = True
                        w["state"] = "down"
                        self._event("worker-done", worker=w["index"],
                                    code=rc)
                        self._publish_routing()
                        continue
                    if rc == 1:
                        self._event("worker-config-error",
                                    worker=w["index"], code=rc)
                        self._stop = True
                        exit_code = 1
                        continue
                    self.restarts += 1
                    self._event("worker-crash", code=rc, reason=reason,
                                worker=w["index"],
                                lifetime_s=round(lifetime, 1),
                                restarts=self.restarts)
                    self._failover(w["index"])
                    if (self.max_restarts
                            and self.restarts >= self.max_restarts):
                        self._event("restart-budget-exhausted",
                                    restarts=self.restarts)
                        self._stop = True
                        exit_code = 3
                        continue
                    w["streak"] = (1 if lifetime > 30.0
                                   else w["streak"] + 1)
                    w["next_spawn"] = now + restart_backoff(
                        w["streak"], self.backoff_base, self.backoff_max
                    )
                running = any(
                    w["proc"] is not None and w["proc"].poll() is None
                    for w in self.workers
                )
                if all(w["done"] for w in self.workers):
                    break
                if self._stop and not running and all(
                        w["done"] or w["proc"] is None
                        for w in self.workers):
                    break
                time.sleep(self.poll_interval)
        finally:
            obs_flight.uninstall()
        if exit_code:
            return exit_code
        if self._signame is not None:
            return 4
        self._event("fleet-done", restarts=self.restarts)
        return 0


def supervisor_main(args, worker_argv: List[str]) -> int:
    """`sartsolve serve --supervised` entry (engine/cli.py): ``args`` is
    the parsed serve namespace (supervision knobs), ``worker_argv`` the
    original argv with ``--supervised`` stripped — the exact command the
    worker runs under."""
    sup = Supervisor(
        worker_argv,
        engine_dir=args.engine_dir,
        backoff_base=args.restart_backoff,
        backoff_max=args.restart_backoff_max,
        crash_loop_window=args.crash_loop_window,
        crash_loop_threshold=args.crash_loop_threshold,
        max_restarts=args.max_restarts,
        http_port=args.http_port,
    )
    return sup.run()


__all__ = ["Supervisor", "FleetController", "CrashLoopBreaker",
           "classify_exit", "restart_backoff", "supervisor_main",
           "rotate_events", "DEFAULT_ROTATE_BYTES"]
