"""Graceful preemption shutdown: SIGTERM/SIGINT → stop flag → exit 4.

On a shared accelerator pool the dominant "failure" is not a fault at all
but *preemption*: the scheduler sends SIGTERM and expects the process
gone within a deadline (SIGKILL follows). The reference binary dies
mid-write and leaves whatever the incremental flush happened to commit;
this module turns the same signal into a clean, resumable stop:

- The first SIGTERM/SIGINT sets a **stop-request flag** (and nothing
  else — the handler is async-signal-lean: one assignment plus a stderr
  note). The CLI frame loop polls :func:`stop_requested` at frame-group
  boundaries, drains the in-flight group and the async writer, flushes
  the solution file, prints the resilience summary, and exits with the
  documented ``EXIT_INTERRUPTED = 4`` — the output file is resumable
  with ``--resume``.
- A **second** signal aborts immediately: the handler restores the
  default disposition and re-raises the signal at the process, so it
  dies with the conventional ``128 + N`` status and no further draining
  (the solution file stays crash-consistent — the killdrill model).

Multihost runs poll the flag through
:func:`sartsolver_tpu.parallel.multihost.agree_stop`, a one-int host
allgather, so every process stops at the *same* group boundary even when
the scheduler's signals land at slightly different times — a lone
stopper would desynchronize the collective frame loop.

Handlers are installed by the CLI (``install``/``uninstall``; no-ops off
the main thread, where Python forbids ``signal.signal``). Library users
embedding the solver keep full control: nothing here runs at import.
"""

from __future__ import annotations

import signal
import sys
import threading
from typing import Dict, Optional

_HANDLED = (signal.SIGTERM, signal.SIGINT)

_state = {
    "stop": False,
    "signame": None,  # name of the first signal received
    "installed": False,
}
_previous: Dict[int, object] = {}


def stop_requested() -> bool:
    """True once a stop signal arrived (cheap enough to poll per frame)."""
    return _state["stop"]


def stop_signal() -> Optional[str]:
    """Name of the first stop signal received (``'SIGTERM'``), or None."""
    return _state["signame"]


def reset() -> None:
    """Clear the stop flag (a fresh run in the same process)."""
    _state["stop"] = False
    _state["signame"] = None


def _handler(signum, frame) -> None:
    name = signal.Signals(signum).name
    if _state["stop"]:
        # second signal: immediate abort — die by the signal so the
        # parent sees the conventional status, with no draining (the
        # incremental flush keeps the file crash-consistent)
        sys.stderr.write(
            f"sartsolve: second {name} — aborting immediately\n"
        )
        sys.stderr.flush()
        signal.signal(signum, signal.SIG_DFL)
        signal.raise_signal(signum)
        return
    _state["stop"] = True
    _state["signame"] = name
    sys.stderr.write(
        f"sartsolve: received {name} — stopping at the next frame-group "
        "boundary (drain, flush, exit 4; file resumable with --resume). "
        "Send again to abort immediately.\n"
    )
    sys.stderr.flush()


def install() -> bool:
    """Install the graceful handlers; returns True when installed.

    Resets the stop flag (repeated in-process runs — tests — start
    clean). A no-op returning False off the main thread or when already
    installed."""
    reset()
    if _state["installed"]:
        return True
    if threading.current_thread() is not threading.main_thread():
        return False
    for sig in _HANDLED:
        _previous[sig] = signal.signal(sig, _handler)
    _state["installed"] = True
    return True


def uninstall() -> None:
    """Restore the previous handlers (idempotent)."""
    if not _state["installed"]:
        return
    for sig, prev in _previous.items():
        try:
            signal.signal(sig, prev)
        except (ValueError, TypeError):  # pragma: no cover - teardown race
            pass
    _previous.clear()
    _state["installed"] = False


class installed:
    """Context manager pairing :func:`install`/:func:`uninstall`."""

    def __enter__(self) -> "installed":
        install()
        return self

    def __exit__(self, *exc) -> None:
        uninstall()
