"""Deterministic fault-injection registry.

Every recovery path in the stack (docs/RESILIENCE.md) is guarded by a
*named site*: a point in real production code that consults this registry
and — only when a fault is armed for it — raises or corrupts data. With
nothing armed a site costs one dict lookup on a cold I/O path; the hot
jitted solver code contains no sites (its resilience is the in-solve
divergence guard, ``models/sart.py``).

Arming faults:

- Environment: ``SART_FAULT=site:kind:prob[:count][,site:kind:prob...]``
  parsed once on first use (``reset()`` re-reads). ``prob`` is the
  per-encounter trip probability drawn from a per-site RNG seeded by
  ``SART_FAULT_SEED`` (default 0) — a given spec therefore trips on the
  exact same encounters every run. ``count`` caps the number of trips
  (default unlimited); ``prob=1`` with a count gives fully deterministic
  "fail the first N encounters" faults, which is what the test matrix
  uses. A site may carry a pod qualifier — ``site@i:kind:prob`` — and
  then arms only on the pod process whose index is ``i``
  (``SART_POD_PROCESS``, exported by parallel/multihost.py; a
  single-process run is index 0). One spec, distributed across an
  entire pod, therefore kills/hangs exactly one chosen host — the
  pod-aware drill the coordinated failure barriers need.
- Programmatic: :func:`inject` / :func:`clear_faults`, or the
  :func:`injected` context manager.

Kinds:

- ``io`` — the site raises :class:`InjectedIOError` (an ``OSError``),
  modeling a torn read / NFS blip / torn write.
- ``error`` — the site raises :class:`InjectedFault` (a ``RuntimeError``),
  modeling a non-I/O infrastructure failure (e.g. a device runtime error).
- ``nan`` — sites that pass data through :func:`corrupt` get the array
  NaN-poisoned, modeling bad sensor frames / bit flips; exception sites
  ignore this kind.
- ``corrupt`` — deterministic *finite* value perturbation, modeling a
  silent bit flip that no NaN check can see: data sites
  (:func:`corrupt`) get element 0 scaled by 256 and offset by 1 (dtype
  preserved — a torn stripe read looks like a stripe read); device-buffer
  sites probe :func:`take_corrupt` and perturb their resident array
  themselves (``parallel/sharded.py``). Only the integrity layer
  (``resilience/integrity.py``, ``SolverOptions.integrity``) detects this
  kind — it drills ABFT checks, ingest digests and the SDC escalation
  policy end-to-end, exactly like ``oom``/``hang`` drill theirs.
- ``oom`` — the site raises :class:`InjectedOOM` (an
  :class:`InjectedFault` whose message carries the runtime's
  ``RESOURCE_EXHAUSTED`` marker), modeling a device out-of-memory on
  dispatch; drives the adaptive batch-halving ladder
  (``resilience/degrade.py``).
- ``hang`` — the site blocks in a cooperative sleep loop (modeling a
  wedged device runtime / stalled filesystem) until the hang watchdog
  (``resilience/watchdog.py``) interrupts it with an async
  ``WatchdogTimeout``, or until ``SART_HANG_RELEASE`` seconds (default
  300) elapse — the release valve keeps an unwatched test from
  deadlocking; it then raises :class:`InjectedFault`.
"""

from __future__ import annotations

import dataclasses
import os
import time
import zlib
from typing import Dict, Optional

import numpy as np

from sartsolver_tpu.utils.locking import named_lock


def site_seed(site: str) -> int:
    """Stable per-site seed component. ``hash(str)`` is salted per process
    (PYTHONHASHSEED), which would make a prob < 1 fault trip on different
    encounters every run; CRC32 is stable across processes and Python
    versions, so a given SART_FAULT spec reproduces exactly."""
    return zlib.crc32(site.encode())

# Named injection sites. Free-form strings are rejected at arm time so a
# typo in SART_FAULT fails loudly instead of silently never firing.
SITE_FRAME_READ = "hdf5.frame_read"  # io/image.py: composite frame ingest
SITE_RTM_INGEST = "hdf5.rtm_ingest"  # parallel/multihost.py: RTM stripe read
SITE_PREFETCH = "prefetch.next"      # utils/prefetch.py: worker loop
SITE_DEVICE_PUT = "device.put"       # parallel/sharded.py: host->device staging
SITE_SOLVE = "solve.dispatch"        # parallel/sharded.py: solve entry
SITE_FLUSH = "io.flush"              # io/solution.py: output flush
SITE_MULTIHOST_INIT = "multihost.init"  # parallel/multihost.py: runtime init
SITE_DEVICE_BUFFER = "device.buffer"    # parallel/sharded.py: resident RTM rot
# Serving-engine seams (docs/SERVING.md): request-file/socket payload
# parsing, the request-journal append (the engine's durability backbone),
# and attaching a request's frame stream to the resident session.
SITE_REQUEST_PARSE = "request.parse"    # engine/request.py: payload parse
SITE_JOURNAL_APPEND = "journal.append"  # engine/journal.py: record append
SITE_SESSION_ATTACH = "session.attach"  # engine/session.py: frame-stream attach
SITE_STATE_CHECKPOINT = "state.checkpoint"  # engine/state.py: soft-state save
# Pod fault-tolerance seams (docs/RESILIENCE.md §11): the in-solve pod
# checkpoint append and the deadline-bounded pod rendezvous barrier.
SITE_SOLVE_CHECKPOINT = "solve.checkpoint"  # resilience/podckpt.py: ckpt append
SITE_POD_BARRIER = "pod.barrier"        # parallel/multihost.py: pod rendezvous

FAULT_SITES = frozenset({
    SITE_FRAME_READ, SITE_RTM_INGEST, SITE_PREFETCH, SITE_DEVICE_PUT,
    SITE_SOLVE, SITE_FLUSH, SITE_MULTIHOST_INIT, SITE_DEVICE_BUFFER,
    SITE_REQUEST_PARSE, SITE_JOURNAL_APPEND, SITE_SESSION_ATTACH,
    SITE_STATE_CHECKPOINT, SITE_SOLVE_CHECKPOINT, SITE_POD_BARRIER,
})

FAULT_KINDS = ("io", "error", "nan", "hang", "oom", "corrupt")


class InjectedIOError(OSError):
    """An injected I/O fault (kind ``io``)."""


class InjectedFault(RuntimeError):
    """An injected non-I/O fault (kind ``error``)."""


class InjectedOOM(InjectedFault):
    """An injected device out-of-memory (kind ``oom``). Subclasses
    :class:`InjectedFault` so per-frame isolation absorbs it once the
    degradation ladder is exhausted; the message carries the runtime's
    ``RESOURCE_EXHAUSTED`` marker so
    :func:`sartsolver_tpu.resilience.degrade.is_resource_exhausted`
    matches it and a real XLA OOM identically."""


@dataclasses.dataclass
class _Fault:
    site: str
    kind: str
    prob: float
    count: Optional[int]  # max trips; None = unlimited
    trips: int = 0
    encounters: int = 0
    rng: np.random.Generator = dataclasses.field(
        default_factory=lambda: np.random.default_rng(0)
    )

    def should_trip(self) -> bool:
        self.encounters += 1
        if self.count is not None and self.trips >= self.count:
            return False
        # the draw happens on every encounter (tripped or capped alike) so
        # the trip pattern of one site never depends on another's cap
        hit = self.prob >= 1.0 or self.rng.random() < self.prob
        if hit:
            self.trips += 1
        return hit


# site -> armed fault; None means "not yet initialized from the env".
_faults: Optional[Dict[str, _Fault]] = None
_lock = named_lock("resilience.faults")


def pod_index() -> int:
    """This process's pod index (0 on a single-process run).

    Reads ``SART_POD_PROCESS`` (``k/n`` or bare ``k``) — exported by
    ``parallel/multihost.py`` after runtime init and by the fake-pod
    chaos harness — so this module stays jax-free. Malformed values read
    as 0 (a drill env typo must not crash production arming)."""
    raw = os.environ.get("SART_POD_PROCESS", "")
    if not raw:
        return 0
    try:
        return int(raw.split("/", 1)[0])
    except ValueError:
        return 0


def parse_fault_spec(spec: str) -> Dict[str, _Fault]:
    """Parse a ``SART_FAULT`` spec string into armed faults.

    Grammar: comma-separated ``site[@i]:kind:prob[:count]`` entries.
    Raises ``ValueError`` on unknown sites/kinds or malformed numbers —
    an armed fault that never fires because of a typo would make the
    whole matrix vacuous. A ``@i`` pod qualifier restricts the entry to
    pod process ``i`` (:func:`pod_index`): entries for other hosts are
    validated (typos still fail loudly on every host) but not armed,
    and the armed fault is keyed by the bare site name.
    """
    seed = int(os.environ.get("SART_FAULT_SEED", "0"))
    here = pod_index()
    out: Dict[str, _Fault] = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) not in (3, 4):
            raise ValueError(
                f"Malformed SART_FAULT entry {entry!r}; expected "
                "site[@i]:kind:prob[:count]."
            )
        site, kind, prob_s = parts[0], parts[1], parts[2]
        target: Optional[int] = None
        if "@" in site:
            site, _at, idx_s = site.partition("@")
            try:
                target = int(idx_s)
            except ValueError:
                raise ValueError(
                    f"Malformed pod qualifier in SART_FAULT entry "
                    f"{entry!r}; expected site@<process_index>."
                ) from None
            if target < 0:
                raise ValueError(
                    f"Pod qualifier must be >= 0, got {target}."
                )
        if site not in FAULT_SITES:
            raise ValueError(
                f"Unknown fault site {site!r}; valid: "
                f"{', '.join(sorted(FAULT_SITES))}."
            )
        if kind not in FAULT_KINDS:
            raise ValueError(
                f"Unknown fault kind {kind!r}; valid: "
                f"{', '.join(FAULT_KINDS)}."
            )
        prob = float(prob_s)
        if not (0.0 < prob <= 1.0):
            raise ValueError(f"Fault probability must be in (0, 1], got {prob}.")
        count = int(parts[3]) if len(parts) == 4 else None
        if count is not None and count < 1:
            raise ValueError(f"Fault count must be >= 1, got {count}.")
        if target is not None and target != here:
            continue  # validated, but armed only on the qualified host
        if site in out:
            # one fault per site: a drill spec listing a site twice would
            # silently lose the first entry — loud beats last-wins
            raise ValueError(
                f"Fault site {site!r} armed twice in one spec; a site "
                "holds one fault (arm different sites to combine drills)."
            )
        out[site] = _Fault(
            site, kind, prob, count,
            rng=np.random.default_rng([seed, site_seed(site)]),
        )
    return out


def _active() -> Dict[str, _Fault]:
    global _faults
    if _faults is None:
        with _lock:
            if _faults is None:
                _faults = parse_fault_spec(os.environ.get("SART_FAULT", ""))
    return _faults


def inject(site: str, kind: str = "io", prob: float = 1.0,
           count: Optional[int] = None) -> None:
    """Arm a fault programmatically (same semantics as the env spec)."""
    armed = parse_fault_spec(
        f"{site}:{kind}:{prob}" + (f":{count}" if count is not None else "")
    )
    _active().update(armed)


def clear_faults() -> None:
    """Disarm every fault (env- and programmatically-armed alike)."""
    global _faults
    with _lock:
        _faults = {}


def reset() -> None:
    """Forget all state; the next use re-reads ``SART_FAULT``."""
    global _faults
    with _lock:
        _faults = None


class injected:
    """Context manager arming a fault for its scope (tests)."""

    def __init__(self, site: str, kind: str = "io", prob: float = 1.0,
                 count: Optional[int] = None):
        self._args = (site, kind, prob, count)

    def __enter__(self):
        inject(*self._args)
        return self

    def __exit__(self, *exc):
        _active().pop(self._args[0], None)


def _hang(site: str, trip: int) -> None:
    """Block cooperatively: small sleeps so the watchdog's async
    ``WatchdogTimeout`` (PyThreadState_SetAsyncExc delivers between
    bytecodes, i.e. each time a sleep returns) interrupts promptly.
    ``SART_HANG_RELEASE`` bounds the hang so a drill whose watchdog is
    misconfigured fails loudly instead of deadlocking the test run."""
    release = float(os.environ.get("SART_HANG_RELEASE", "300"))
    deadline = time.monotonic() + release
    while time.monotonic() < deadline:
        time.sleep(0.05)
    raise InjectedFault(
        f"injected hang at {site} (trip {trip}) released after {release}s "
        "(SART_HANG_RELEASE) without a watchdog interrupt"
    )


def fire(site: str) -> None:
    """Raise the armed exception fault for ``site``, if it trips.

    The zero-fault path is one dict lookup; ``nan``/``corrupt`` faults
    never raise (they act through :func:`corrupt` / :func:`take_corrupt`).
    """
    fault = _active().get(site)
    if fault is None or fault.kind in ("nan", "corrupt"):
        return
    if fault.should_trip():
        if fault.kind == "io":
            raise InjectedIOError(
                f"injected I/O fault at {site} (trip {fault.trips})"
            )
        if fault.kind == "oom":
            raise InjectedOOM(
                f"injected RESOURCE_EXHAUSTED at {site} "
                f"(trip {fault.trips}): out of memory while trying to "
                "allocate the dispatch buffers"
            )
        if fault.kind == "hang":
            _hang(site, fault.trips)
        raise InjectedFault(
            f"injected fault at {site} (trip {fault.trips})"
        )


def corrupt(site: str, array: np.ndarray) -> np.ndarray:
    """Corrupt ``array`` if a data-kind fault trips at ``site``.

    Returns the input unchanged (no copy) on the zero-fault path. A
    tripped ``nan`` fault returns a poisoned fp64 copy (first element set
    to NaN — enough to poison any reduction over the data). A tripped
    ``corrupt`` fault returns a *finite* perturbation with the dtype
    preserved — element 0 scaled by 256 and offset by 1 — modeling a
    silent bit flip that no NaN check can see; only the integrity layer
    (resilience/integrity.py) detects it.
    """
    fault = _active().get(site)
    if fault is None or fault.kind not in ("nan", "corrupt"):
        return array
    if not fault.should_trip():
        return array
    if fault.kind == "nan":
        poisoned = np.array(array, dtype=np.float64, copy=True)
        poisoned.reshape(-1)[0] = np.nan
        return poisoned
    perturbed = np.array(array, copy=True)  # dtype preserved
    flat = perturbed.reshape(-1)
    flat[0] = flat[0] * 256 + 1
    return perturbed


def take_corrupt(site: str) -> bool:
    """True iff a ``corrupt`` fault trips at ``site`` — for sites whose
    data is a *device-resident* buffer they must perturb themselves
    (``parallel/sharded.py``'s resident-RTM rot drill) rather than pass
    a host array through :func:`corrupt`."""
    fault = _active().get(site)
    if fault is None or fault.kind != "corrupt":
        return False
    return fault.should_trip()


def fault_trips() -> Dict[str, int]:
    """Trip counts per armed site (observability / test assertions)."""
    return {site: f.trips for site, f in _active().items()}
